"""Shared measured-probe runner — the autopilot's measurement half.

One timing discipline for every short measured probe in the tuning
package (and bench.py's scenario matrix): warm the compiled program, then
time a dispatch loop ended by a device->host scalar fetch
(utils.tracing.fence_tree — ``block_until_ready`` does not wait on
tunneled backends, the bench ladder's founding finding), best-of-N
against shared-host contention. Every completed row is ALSO written to a
JSON artifact atomically as it lands (:class:`ProbeLadder`), so a killed
or timed-out tune leaves parseable partial evidence — the same
tmp+rename contract the bench ladder's partial artifact carries.

Probes are TRAJECTORY-NEUTRAL by construction: they run on synthetic
batches drawn from their own PRNG keys and on states initialized from
their own seeds, never touching the training data iterator's shuffle RNG
or the run's model-init seed — which is what lets ``--auto tune`` hand
the chosen config to the normal train path bit-identically to launching
that config statically (the PR-7 acceptance contract).
"""

from __future__ import annotations

import math
import time
from typing import Optional

from atomo_tpu.utils.tracing import write_json_atomic


class ProbeLadder:
    """Rows-as-they-complete artifact recorder (atomic partial JSON).

    ``artifact_path=None`` disables writing (rows still accumulate for
    the caller). The document shape mirrors bench.py's partial artifact:
    ``{"kind": ..., "meta": {...}, "rows": [...], "complete": bool}``.
    Write failures warn and never crash the run being tuned — evidence is
    best-effort, training is not.
    """

    def __init__(
        self, artifact_path: Optional[str] = None, kind: str = "probe",
        meta: Optional[dict] = None, log_fn=print,
    ):
        self.artifact_path = artifact_path
        self.doc = {
            "kind": kind,
            "meta": dict(meta or {}),
            "rows": [],
            "complete": False,
        }
        self.log_fn = log_fn

    @property
    def rows(self) -> list[dict]:
        return self.doc["rows"]

    def _write(self) -> None:
        if not self.artifact_path:
            return
        try:
            write_json_atomic(self.artifact_path, self.doc)
        except OSError as exc:
            self.log_fn(f"probe artifact write failed: {exc}")

    def record(self, row: dict) -> dict:
        self.doc["rows"].append(row)
        self._write()
        return row

    def finish(self, **extra) -> dict:
        self.doc.update(extra)
        self.doc["complete"] = True
        self._write()
        return self.doc


def model_init_fn(model, sample):
    """The deterministic param-init closure every byte-budget consumer
    shares (the CLI's ``--aggregate auto`` resolution, the autopilot, the
    bench scenario matrix, the README table generator): fixed PRNGKey(0)
    for params/dropout over a zeros ``sample``, params extracted. ONE
    definition so the byte budgets those surfaces compute can never
    silently diverge. Meant for jax.eval_shape — never materializes."""
    import jax

    def init():
        return model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(0)},
            sample, train=False,
        )["params"]

    return init


def leaf_byte_budgets(codec, init_fn) -> list:
    """Per-leaf ``(dense_bytes, payload_bytes)`` pairs in canonical
    flatten order, at zero cost via jax.eval_shape — the per-leaf form of
    the byte budget (PR-12): :func:`byte_budget` is now its sum through
    ``comm_model.leaf_budget_totals``, so the whole-tree scalars and any
    per-leaf consumer (the hybrid planner's pricing, the +sp autopilot
    candidates) read the SAME accounting. ``codec=None`` (dense
    training) reports payload 0 per leaf."""
    import jax

    from atomo_tpu.codecs import encode_tree, payload_nbytes, tree_nbytes

    if codec is None:
        leaves = jax.tree_util.tree_leaves(jax.eval_shape(init_fn))
        return [(tree_nbytes([l]), 0) for l in leaves]

    def shapes():
        params = init_fn()
        payload, _ = encode_tree(codec, jax.random.PRNGKey(0), params)
        return params, payload

    grads_s, payload_s = jax.eval_shape(shapes)
    g_leaves, treedef = jax.tree_util.tree_flatten(grads_s)
    p_leaves = treedef.flatten_up_to(payload_s)
    return [
        (tree_nbytes([g]), payload_nbytes(p))
        for g, p in zip(g_leaves, p_leaves)
    ]


def byte_budget(codec, init_fn) -> tuple[int, int]:
    """(dense_bytes, payload_bytes) of one gradient exchange — the sum of
    :func:`leaf_byte_budgets` through the one honest accounting function
    (``comm_model.leaf_budget_totals``). Report shape unchanged: the one
    implementation behind the CLI's ``--aggregate auto`` resolution and
    the autopilot's prediction context; build ``init_fn`` with
    :func:`model_init_fn`."""
    from atomo_tpu.utils.comm_model import leaf_budget_totals

    d, p = leaf_budget_totals(leaf_byte_budgets(codec, init_fn))
    return int(d), int(p)


def fenced_seconds_per_call(
    call, *, reps: int, warmup: int = 2, best_of: int = 1
) -> tuple[float, bool]:
    """Best-of-``best_of`` mean seconds per ``call()`` over ``reps``-call
    dispatch loops, each fenced by a scalar fetch of the last call's
    output. Returns ``(seconds, sync_ok)`` — ``sync_ok`` False when the
    fence scalar came back non-finite (the measurement is then invalid,
    reported, never silently trusted)."""
    from atomo_tpu.utils.tracing import fence_tree

    out = None
    for _ in range(max(warmup, 1)):
        out = call()
    sync = fence_tree(out)  # drain warmup + compile
    best = float("inf")
    for _ in range(max(best_of, 1)):
        t0 = time.perf_counter()
        for _ in range(max(reps, 1)):
            out = call()
        sync = fence_tree(out)
        best = min(best, (time.perf_counter() - t0) / max(reps, 1))
    return best, bool(math.isfinite(sync))


def synthetic_batch(key, batch: int, sample_shape, num_classes: int):
    """A probe batch from the probe's OWN key — never the training
    stream (trajectory neutrality, module docstring)."""
    import jax
    import jax.numpy as jnp

    ki, kl = jax.random.split(key)
    images = jax.random.uniform(
        ki, (batch,) + tuple(sample_shape), jnp.float32
    )
    labels = jax.random.randint(kl, (batch,), 0, num_classes)
    return images, labels


def probe_candidate(
    cand: dict,
    *,
    model,
    optimizer,
    codec,
    n_dev: int,
    sample_shape,
    num_classes: int,
    batch: int,
    seed: int = 0,
    steps: int = 3,
    reps: int = 2,
    warmup: int = 2,
    num_aggregate: int = 0,
    zero1: bool = False,
    grad_accum: int = 1,
    compute_dtype=None,
    ring_bucket_size: int = 65536,
    dcn_ways: int = 0,
    hybrid=None,
    error_feedback: bool = False,
) -> dict:
    """Measure one candidate knob vector: build the REAL step program the
    train path would run (same builders, same knobs — zero1 / grad_accum
    / compute_dtype / num_aggregate ride along because they change the
    program's speed; guard/chaos/remedy stay off, they are correctness
    machinery, not a performance knob) and time it with the fence
    discipline. Returns the probe row (measured ms/step per OPTIMIZER
    step — a superstep-K program's one dispatch covers K of them).

    Hierarchical candidates (``aggregate='hierarchical'`` + a ``plan``
    knob) probe on the two-tier mesh ``(dp=dcn_ways, ici=n_dev/dcn_ways)``
    through the same builder the train path uses (inner_axis='ici',
    topology plan attached) — the probes `--auto tune` was missing on
    ``--dcn-ways`` meshes.

    ``hybrid`` (sparse.hybrid.HybridPlan) is attached to the built step
    only for ``+sp`` candidates (``cand["sparse_rows"] == "on"``) — the
    probe then times the REAL per-layer hybrid exchange the train path
    would dispatch. The probe batch stays the synthetic float batch;
    row-id workloads read it as low row ids, which under-exercises the
    power-law tail but prices the program structure honestly (the
    lossless budget is static, so the timing is shape-faithful).

    ``error_feedback=True`` probes the residual-carry step (EF state
    wrapped via ``init_ef_state`` after replication) — the ISSUE-17
    satellite. The caller (``tune(error_feedback=True)``) is responsible
    for narrowing the candidate space to the flat blocking programs EF
    composes with; this function just builds what it is asked to and
    lets the step builder's conflict matrix reject the rest loudly."""
    import jax
    import jax.numpy as jnp

    k = max(int(cand.get("superstep", 1)), 1)
    key = jax.random.PRNGKey(seed + 7)
    images, labels = synthetic_batch(
        jax.random.PRNGKey(seed + 11), batch, sample_shape, num_classes
    )

    if n_dev <= 1:
        if error_feedback:
            raise ValueError(
                "error-feedback probes need a multi-device mesh — EF "
                "corrects the lossy EXCHANGE, and a single device has "
                "no exchange to correct"
            )
        from atomo_tpu.training import create_state, make_train_step

        state = create_state(
            model, optimizer, jax.random.PRNGKey(seed), images
        )
        step = make_train_step(
            model, optimizer, codec=codec, compute_dtype=compute_dtype,
            superstep=k,
        )
        if k > 1:
            im = jnp.broadcast_to(images, (k,) + images.shape)
            lb = jnp.broadcast_to(labels, (k,) + labels.shape)
        else:
            im, lb = images, labels
        box = {"st": state}

        def call():
            box["st"], m = step(box["st"], key, im, lb)
            box["m"] = m
            return m["loss"]

    else:
        from atomo_tpu.parallel import (
            init_delayed_state,
            make_distributed_train_step,
            make_mesh,
            replicate_state,
            shard_batch,
        )
        from atomo_tpu.parallel.replicated import shard_superbatch
        from atomo_tpu.training import create_state

        agg = cand.get("aggregate", "gather")
        overlap = cand.get("overlap", "off")
        plan = None
        inner_axis = None
        batch_axes = "dp"
        if agg == "hierarchical":
            from atomo_tpu.topology.schedule import plan_from_name

            kw = int(dcn_ways)
            if not (1 < kw <= n_dev) or n_dev % kw:
                raise ValueError(
                    f"hierarchical candidate needs dcn_ways dividing "
                    f"n_dev; got dcn_ways={kw}, n_dev={n_dev}"
                )
            mesh = make_mesh(
                n_dev, axes=(("dp", kw), ("ici", n_dev // kw))
            )
            plan = plan_from_name(cand.get("plan", "legacy"))
            inner_axis = "ici"
            batch_axes = ("dp", "ici")
        else:
            mesh = make_mesh(n_dev)
        state = create_state(
            model, optimizer, jax.random.PRNGKey(seed), images
        )
        zero1_specs = None
        if zero1:
            from atomo_tpu.parallel.replicated import zero1_state

            state, zero1_specs = zero1_state(
                mesh, state, optimizer, axis=batch_axes
            )
        else:
            state = replicate_state(mesh, state)
        step = make_distributed_train_step(
            model, optimizer, mesh, codec, aggregate=agg,
            num_aggregate=num_aggregate if agg in ("gather", "ring") else 0,
            compute_dtype=compute_dtype, zero1_specs=zero1_specs,
            grad_accum=grad_accum, superstep=k, overlap=overlap,
            ring_bucket_size=cand.get("ring_bucket_size", ring_bucket_size),
            stream_encode=cand.get("stream_encode") == "on",
            stream_bucket_bytes=int(
                cand.get("stream_bucket_bytes", 4 << 20)
            ),
            inner_axis=inner_axis, plan=plan,
            hybrid=hybrid if cand.get("sparse_rows") == "on" else None,
            error_feedback=error_feedback,
        )
        if error_feedback:
            from atomo_tpu.parallel.replicated import init_ef_state

            state = init_ef_state(mesh, state)
        if overlap == "delayed":
            state = init_delayed_state(mesh, state, codec)
        if k > 1:
            im_k = jnp.broadcast_to(images, (k,) + images.shape)
            lb_k = jnp.broadcast_to(labels, (k,) + labels.shape)
            im, lb = shard_superbatch(mesh, im_k, lb_k, axis=batch_axes)
        else:
            im, lb = shard_batch(mesh, images, labels, axis=batch_axes)
        box = {"st": state}

        def call():
            box["st"], m = step(box["st"], key, im, lb)
            box["m"] = m
            return m["loss"]

    t0 = time.perf_counter()
    per_call, sync_ok = fenced_seconds_per_call(
        call, reps=steps, warmup=warmup, best_of=max(reps, 1)
    )
    row = {
        **{kk: v for kk, v in cand.items()},
        "measured_ms_per_step": round(per_call / k * 1e3, 4),
        "probe_wall_s": round(time.perf_counter() - t0, 3),
        "sync_ok": sync_ok,
        "probed": True,
    }
    m = box.get("m")
    if m is not None and "msg_bytes" in m:
        # the executed program's OWN byte accounting (per-chip message on
        # the scarcest fabric + dense gradient size) — what bench config
        # 11 compares the comm model's predictions against
        import numpy as np

        row["measured_msg_bytes"] = int(
            np.ravel(jax.device_get(m["msg_bytes"]))[-1]
        )
        row["measured_dense_bytes"] = int(
            np.ravel(jax.device_get(m["dense_bytes"]))[-1]
        )
    return row


def probe_batch_size(batch: int, n_dev: int) -> int:
    """The probe's batch: the run's batch rounded down to a mesh multiple
    (floored at one sample per device) so shard_batch always accepts it."""
    if n_dev <= 1:
        return max(int(batch), 1)
    return max((int(batch) // n_dev) * n_dev, n_dev)
