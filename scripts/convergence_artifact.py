"""Produce the ResNet-18 convergence-parity artifact (VERDICT r2 #6, r3 #6).

Round-3's version saturated: easy synthetic data drove both curves to a
~zero loss floor where ratio ≈ 1 is unfalsifiable — a biased codec could
pass. Round-4 hardening (VERDICT r3 next-round #6):

  * **label noise** (default 20%) keeps the loss floor well above zero and
    the accuracy ceiling well below 100%, so codec-induced degradation has
    somewhere to show;
  * **accuracy-vs-step curves** are recorded alongside loss, with a stated
    accuracy target standing in for BASELINE.md's unmeasurable 93%
    (no CIFAR-10 in this env): dense prec@1 must reach ``--acc-target``
    PERCENT (default 60 — accuracy metrics are on the 0-100 scale — at 500
    steps under 20% noise) and svd must land within ``--acc-gap``
    (default 5) percentage points of dense;
  * a **broken-codec ablation** runs the same gate: the pure-sketch
    no-residual-probes codec (its estimator discards the spectral tail —
    biased, the exact failure class the probes exist to fix) must FAIL
    the gate the production codec passes. A gate both pass would prove
    nothing; ``gate_discriminates`` in the JSON records this.

Runs the reference's canonical recipe (src/run_pytorch.sh:1-20: ResNet-18 /
CIFAR-10, batch 128, lr 0.01, momentum 0, svd-rank 3) three ways — dense,
default SVD codec, no-probes ablation — on whatever accelerator jax
resolves (the TPU chip under axon; JAX_PLATFORMS=cpu reproduces on CPU).

Data: real CIFAR-10 from ./data when present; otherwise the deterministic
synthetic fallback (documented in the artifact's "dataset" field) — class
structure is synthetic, but the gradient spectra exercising the codec are
real ResNet-18 gradients either way, and the label noise applies to both.

Usage: python scripts/convergence_artifact.py [--steps 500] [--out artifacts]
       [--network resnet18] [--label-noise 0.2] [--acc-target 60]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=500)
    ap.add_argument("--tail", type=int, default=50, help="final-window size")
    ap.add_argument("--out", type=str, default="artifacts")
    ap.add_argument("--network", type=str, default="resnet18")
    ap.add_argument("--label-noise", type=float, default=0.2,
                    help="fraction of train labels randomized (keeps the "
                         "loss floor off zero so the gate can discriminate)")
    ap.add_argument("--acc-target", type=float, default=60.0,
                    help="dense prec@1 (percent) the recipe must reach (the "
                         "stand-in for BASELINE.md's 93% — no real CIFAR-10 "
                         "here)")
    ap.add_argument("--acc-gap", type=float, default=5.0,
                    help="max dense-svd prec@1 gap (percentage points)")
    ap.add_argument("--ratio-tol", type=float, default=1.25,
                    help="max svd/dense final-loss ratio to pass")
    args = ap.parse_args()

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax
    import jax.numpy as jnp
    import numpy as np

    from atomo_tpu.codecs import SvdCodec
    from atomo_tpu.data import SPECS, BatchIterator, synthetic_dataset
    from atomo_tpu.models import get_model
    from atomo_tpu.training import create_state, make_optimizer, make_train_step

    dataset = "cifar10"
    try:
        from atomo_tpu.data import load_dataset

        ds = load_dataset("cifar10", "./data", train=True, synthetic_fallback=False)
        dataset_kind = "real"
    except Exception:
        ds = synthetic_dataset(SPECS["cifar10"], True, size=2048)
        dataset_kind = "synthetic-fallback"

    if args.label_noise > 0:
        # deterministic symmetric label noise: the same corrupted label set
        # for every run, so the comparison stays paired
        rng_np = np.random.RandomState(7)
        labels = ds.labels.copy()
        flip = rng_np.rand(labels.shape[0]) < args.label_noise
        labels[flip] = rng_np.randint(
            0, ds.spec.num_classes, size=int(flip.sum())
        ).astype(labels.dtype)
        ds = dataclasses.replace(ds, labels=labels)

    model = get_model(args.network, 10)
    dev = jax.devices()[0]

    def run(codec):
        opt = make_optimizer("sgd", lr=0.01, momentum=0.0)
        it = BatchIterator(ds, 128, seed=0)
        images, _ = next(iter(it.epoch()))
        state = create_state(model, opt, jax.random.PRNGKey(0), jnp.asarray(images))
        step = make_train_step(model, opt, codec=codec)
        key = jax.random.PRNGKey(1)
        stream = it.forever()
        losses, accs = [], []
        t0 = time.perf_counter()
        for _ in range(args.steps):
            im, lb = next(stream)
            state, m = step(state, key, jnp.asarray(im), jnp.asarray(lb))
            losses.append(float(m["loss"]))  # device->host sync every step
            accs.append(float(m["prec1"]))
        return losses, accs, time.perf_counter() - t0, int(m["msg_bytes"])

    codec = SvdCodec(rank=3)
    broken = SvdCodec(rank=3, residual_probes=0)  # pure sketch: biased
    runs = {}
    for tag, c in (("dense", None), ("svd3", codec), ("svd3_noprobes", broken)):
        print(f"running {tag} ...", flush=True)
        losses, accs, wall, msg = run(c)
        runs[tag] = dict(losses=losses, accs=accs, wall_s=round(wall, 1),
                         msg_bytes=msg)

    tail = args.tail

    def final(tag, key):
        return float(np.mean(runs[tag][key][-tail:]))

    def gate(tag):
        """The pass/fail contract, applied identically to the production
        codec and the ablation."""
        ratio = final(tag, "losses") / max(final("dense", "losses"), 1e-8)
        gap = final("dense", "accs") - final(tag, "accs")
        return {
            "final_loss": final(tag, "losses"),
            "final_prec1": final(tag, "accs"),
            "loss_ratio_vs_dense": round(ratio, 4),
            "prec1_gap_vs_dense": round(gap, 4),
            "ratio_ok": bool(ratio < args.ratio_tol),
            "acc_ok": bool(gap <= args.acc_gap),
            "passed": bool(ratio < args.ratio_tol and gap <= args.acc_gap),
        }

    dense_reached_target = bool(final("dense", "accs") >= args.acc_target)
    g_svd = gate("svd3")
    g_broken = gate("svd3_noprobes")
    # the gate only carries evidence if the production codec passes it AND
    # the deliberately-biased ablation fails it
    discriminates = bool(g_svd["passed"] and not g_broken["passed"])
    passed = bool(dense_reached_target and g_svd["passed"])

    os.makedirs(args.out, exist_ok=True)
    record = {
        "recipe": f"{args.network}/cifar10 batch=128 lr=0.01 momentum=0 "
                  f"svd_rank=3 label_noise={args.label_noise}",
        "reference": "src/run_pytorch.sh:1-20; oracle methodology src/nn_ops.py:123-169",
        "dataset": dataset,
        "dataset_kind": dataset_kind,
        "platform": dev.platform,
        "device": dev.device_kind,
        "steps": args.steps,
        "codec": {
            "name": "svd", "rank": codec.rank, "sample": codec.sample,
            "algorithm": codec.algorithm,
            "residual_probes": codec.residual_probes,
            "power_iters": codec.power_iters,
            "wire_dtype": codec.wire_dtype,
        },
        "acc_target_dense": args.acc_target,
        "acc_gap_tol": args.acc_gap,
        "ratio_tol": args.ratio_tol,
        "dense": {"final_loss": final("dense", "losses"),
                  "final_prec1": final("dense", "accs"),
                  "reached_acc_target": dense_reached_target},
        "svd3": g_svd,
        "svd3_noprobes_ablation": g_broken,
        "gate_discriminates": discriminates,
        "assertion_passed": passed,
        "wall_s": {t: runs[t]["wall_s"] for t in runs},
        "msg_bytes_per_step": runs["svd3"]["msg_bytes"],
        "curves": {
            t: {"losses": [round(x, 5) for x in runs[t]["losses"]],
                "prec1": [round(x, 5) for x in runs[t]["accs"]]}
            for t in runs
        },
    }
    jpath = os.path.join(args.out, "CONVERGENCE.json")
    with open(jpath, "w") as f:
        json.dump(record, f, indent=1)

    def sparkline(xs, buckets=40, log=True):
        # log10 scale for losses (exponential decay); linear for accuracy
        blocks = " .:-=+*#%@"
        chunk = max(1, len(xs) // buckets)
        means = []
        for i in range(0, len(xs), chunk):
            v = float(np.mean(xs[i : i + chunk]))
            means.append(float(np.log10(max(v, 1e-8))) if log else v)
        lo, hi = min(means), max(means)
        span = max(hi - lo, 1e-9)
        return "".join(blocks[int((x - lo) / span * (len(blocks) - 1))] for x in means)

    with open(os.path.join(args.out, "CONVERGENCE.md"), "w") as f:
        rows = "\n".join(
            "| {} | {:.4f} | {:.4f} | {} |".format(
                t, final(t, "losses"), final(t, "accs"), runs[t]["wall_s"]
            )
            for t in runs
        )
        f.write(
            f"""# {args.network} convergence parity — hardened gate ({dataset_kind} {dataset}, {dev.device_kind})

Canonical recipe (reference `src/run_pytorch.sh:1-20`) + **{args.label_noise:.0%}
label noise** so neither loss nor accuracy saturates (VERDICT r3 weak #5:
the round-3 artifact's zero-floor ratio was nearly unfalsifiable). Gate:
dense prec@1 >= {args.acc_target} (the stand-in for BASELINE's 93% — no real
CIFAR-10 in this env), svd within {args.acc_gap:.0f} points and loss ratio
< {args.ratio_tol}. The **no-probes ablation** (pure sketch, biased — it
discards the spectral tail) must FAIL the same gate.

| run | final loss (last {tail}) | final prec@1 | wall s ({args.steps} steps) |
|---|---|---|---|
{rows}

* svd3 gate: ratio {g_svd['loss_ratio_vs_dense']}, acc gap {g_svd['prec1_gap_vs_dense']:.3f}
  -> **{"PASSED" if g_svd['passed'] else "FAILED"}**
* no-probes ablation: ratio {g_broken['loss_ratio_vs_dense']}, acc gap {g_broken['prec1_gap_vs_dense']:.3f}
  -> **{"PASSED (gate too weak!)" if g_broken['passed'] else "FAILED (as it must)"}**
* gate discriminates: **{discriminates}** · overall: **{"PASSED" if passed else "FAILED"}**

Loss curves (log scale, high→low):

    dense    {sparkline(runs['dense']['losses'])}
    svd3     {sparkline(runs['svd3']['losses'])}
    noprobes {sparkline(runs['svd3_noprobes']['losses'])}

prec@1 curves (linear, low→high):

    dense    {sparkline(runs['dense']['accs'], log=False)}
    svd3     {sparkline(runs['svd3']['accs'], log=False)}
    noprobes {sparkline(runs['svd3_noprobes']['accs'], log=False)}

Full curves in `CONVERGENCE.json`.
"""
        )
    print(json.dumps({k: v for k, v in record.items() if k != "curves"}, indent=1))
    return 0 if passed else 1


if __name__ == "__main__":
    raise SystemExit(main())
