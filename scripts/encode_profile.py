"""Attribute the SVD encode tax, phase by phase (VERDICT r4 next-round #2).

Round 3 measured config 2 (ResNet-18 / CIFAR-10 / svd rank 3) at +2.5 ms
over dense on a v5e chip; the round-4 gram/CholeskyQR2 stack claims most of
that back but was never measured. This script produces the breakdown that
decides what (if anything) is left to optimize:

  encode_full       encode_tree on the real ResNet-18 gradient pytree (the
                    production path: bucketed vmap, auto algorithm)
  encode_<algo>     the same with the decomposition forced to gram /
                    randomized (and optionally exact, the known-slow oracle)
  resize_only       reshape-to-near-square cost alone (memory movement)
  decode_mean_8     fused decode-mean of 8 gathered payloads (the decode
                    half of the gather exchange at the canonical 8 ways)
  bucket table      per-shape-bucket encode cost (count x shape -> ms), the
                    data a further batching optimization would need

Timing discipline: identical to bench.py — each phase runs STEPS times
under one lax.scan dispatch with every payload leaf kept live, fenced by a
device->host scalar fetch, best-of-3 (the axon tunnel's ~3 ms dispatch and
shared-chip contention both demand it; see bench.py's docstring).

Writes <out>/ENCODE_PROFILE.json + .md. Reference hot spot being
attributed: the per-layer numpy SVD at src/codings/svd.py:95.

Usage: python scripts/encode_profile.py [--out artifacts/onchip_r5]
       [--steps 30] [--network resnet18] [--include-exact]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", type=str, default="artifacts/onchip_r5")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--network", type=str, default="resnet18")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--rank", type=int, default=3)
    ap.add_argument("--include-exact", action="store_true", default=False,
                    help="also time algorithm='exact' (QDWH — ~120 ms/step "
                         "on v5e, round-2 measurement; off by default so "
                         "the profile itself stays fast)")
    args = ap.parse_args()

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax
    import jax.numpy as jnp

    from atomo_tpu.codecs import SvdCodec, encode_tree
    from atomo_tpu.codecs.svd import resize_to_2d
    from atomo_tpu.models import get_model
    from atomo_tpu.training import create_state, make_optimizer

    dev = jax.devices()[0]
    steps = args.steps

    # real gradient pytree, per the canonical recipe
    model = get_model(args.network, 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.9)
    rng = jax.random.PRNGKey(0)
    images = jax.random.uniform(rng, (args.batch, 32, 32, 3), jnp.float32)
    labels = jax.random.randint(rng, (args.batch,), 0, 10)
    state = create_state(model, opt, rng, images)

    def _loss(p):
        variables = {"params": p}
        if jax.tree_util.tree_leaves(state.batch_stats):
            variables["batch_stats"] = state.batch_stats
        out = model.apply(variables, images, train=False)
        return jnp.mean((out - jax.nn.one_hot(labels, out.shape[-1])) ** 2)

    grads = jax.jit(jax.grad(_loss))(state.params)
    key = jax.random.PRNGKey(1)

    def _consume(tree):
        """Keep EVERY leaf live (uint leaves would otherwise be DCE'd)."""
        tot = jnp.float32(0)
        for l in jax.tree_util.tree_leaves(tree):
            if jnp.issubdtype(l.dtype, jnp.floating):
                tot = tot + jnp.vdot(l, l) * 1e-20
            else:
                tot = tot + jnp.sum(l.astype(jnp.float32)) * 1e-30
        return tot

    def timed(fn, *fn_args) -> float:
        """ms per call: scan-fenced best-of-3 (bench.py discipline)."""

        @jax.jit
        def many(k, a):
            def body(acc, i):
                out = fn(jax.random.fold_in(k, i), a, acc)
                return _consume(out), None

            acc, _ = jax.lax.scan(body, jnp.float32(0), jnp.arange(steps))
            return acc

        sync = float(many(key, fn_args))  # compile + warm
        if not math.isfinite(sync):
            raise RuntimeError(f"sync scalar not finite: {sync}")
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            sync = float(many(key, fn_args))
            best = min(best, (time.perf_counter() - t0) / steps)
            if not math.isfinite(sync):
                raise RuntimeError(f"sync scalar not finite: {sync}")
        return best * 1e3

    results: dict = {}

    def jitter(tree, acc):
        # serialize scan iterations without changing magnitudes
        return jax.tree_util.tree_map(lambda a: a + acc * 1e-30, tree)

    # phase: resize only
    def resize_phase(k, a, acc):
        (g,) = a
        return [resize_to_2d(leaf)[0] for leaf in jax.tree_util.tree_leaves(jitter(g, acc))]

    results["resize_only_ms"] = timed(resize_phase, grads)

    # phase: full encode per algorithm
    algos = ["auto", "gram", "randomized"] + (
        ["exact"] if args.include_exact else []
    )
    for algo in algos:
        codec = SvdCodec(rank=args.rank, algorithm=algo)

        def enc_phase(k, a, acc, c=codec):
            (g,) = a
            payload, _ = encode_tree(c, k, jitter(g, acc))
            return payload

        tag = "encode_full_ms" if algo == "auto" else f"encode_{algo}_ms"
        try:
            results[tag] = timed(enc_phase, grads)
        except Exception as exc:  # noqa: BLE001 - report, don't die
            results[tag] = None
            results[tag + "_error"] = str(exc)[:200]

    # phase: fused decode-mean of 8 gathered payloads
    from atomo_tpu.codecs import decode_mean_tree

    codec = SvdCodec(rank=args.rank)
    payloads = jax.jit(lambda k, g: encode_tree(codec, k, g)[0])(key, grads)
    gathered = jax.tree_util.tree_map(
        lambda a: jnp.stack([a] * 8), payloads
    )

    def dec_phase(k, a, acc):
        (gath, g) = a
        gath = jitter(gath, acc)
        return decode_mean_tree(codec, gath, g, 8)

    results["decode_mean_8_ms"] = timed(dec_phase, gathered, grads)

    # per-bucket encode table: where inside encode_full the time goes
    leaves = jax.tree_util.tree_leaves(grads)
    buckets: dict = {}
    for leaf in leaves:
        buckets.setdefault((tuple(leaf.shape), str(leaf.dtype)), []).append(leaf)
    table = []
    for (shape, dtype), group in sorted(
        buckets.items(), key=lambda kv: -kv[1][0].size * len(kv[1])
    ):
        stacked = jnp.stack(group)
        n = len(group)

        def bucket_phase(k, a, acc, n=n):
            (st,) = a
            keys = jax.vmap(lambda i: jax.random.fold_in(k, i))(jnp.arange(n))
            return jax.vmap(codec.encode)(keys, jitter(st, acc))

        try:
            ms = timed(bucket_phase, stacked)
        except Exception as exc:  # noqa: BLE001
            ms = None
        table.append(
            dict(shape=list(shape), count=n, dtype=dtype,
                 ms_per_step=None if ms is None else round(ms, 4))
        )
    results["buckets"] = table

    results.update(
        platform=dev.platform, device=dev.device_kind, steps=steps,
        network=args.network, rank=args.rank,
        codec_defaults=repr(codec), timing="scan-fenced best-of-3",
    )
    for k in list(results):
        if isinstance(results[k], float):
            results[k] = round(results[k], 4)

    os.makedirs(args.out, exist_ok=True)
    with open(os.path.join(args.out, "ENCODE_PROFILE.json"), "w") as f:
        json.dump(results, f, indent=1)
    lines = [
        "# SVD encode-tax breakdown",
        "",
        f"{args.network} rank-{args.rank} gradients on {dev.device_kind} "
        f"({dev.platform}); {steps}-step scan-fenced best-of-3 "
        "(bench.py discipline). Reference hot spot: per-layer numpy SVD, "
        "src/codings/svd.py:95.",
        "",
        "| phase | ms/step |",
        "|---|---|",
    ]
    for tag in (
        "resize_only_ms", "encode_full_ms", "encode_gram_ms",
        "encode_randomized_ms", "encode_exact_ms", "decode_mean_8_ms",
    ):
        if tag in results:
            lines.append(f"| {tag} | {results[tag]} |")
    lines += ["", "## Per-bucket encode cost", "",
              "| shape | count | ms/step |", "|---|---|---|"]
    for row in table:
        lines.append(
            f"| {tuple(row['shape'])} | {row['count']} | {row['ms_per_step']} |"
        )
    with open(os.path.join(args.out, "ENCODE_PROFILE.md"), "w") as f:
        f.write("\n".join(lines) + "\n")
    print(json.dumps({k: v for k, v in results.items() if k != "buckets"}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
