"""Produce the ResNet-18 convergence-parity artifact (VERDICT r2 #6).

Runs the reference's canonical recipe (src/run_pytorch.sh:1-20: ResNet-18 /
CIFAR-10, batch 128, lr 0.01, momentum 0, svd-rank 3) twice — dense and
with the default SVD codec ("auto" sketch + residual probes) — on whatever
accelerator jax resolves (the TPU chip under axon; set JAX_PLATFORMS=cpu to
reproduce on CPU), and writes artifacts/CONVERGENCE.json + .md with the
full loss curves and the final-loss ratio, asserting the slow test's
contract (ratio < 1.35, the quantitative version of the reference's oracle
methodology, src/nn_ops.py:123-169).

Data: real CIFAR-10 from ./data when present; otherwise the deterministic
synthetic fallback (documented in the artifact's "dataset" field) — class
structure is synthetic, but the gradient spectra exercising the codec are
real ResNet-18 gradients either way.

Usage: python scripts/convergence_artifact.py [--steps 500] [--out artifacts]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=500)
    ap.add_argument("--tail", type=int, default=50, help="final-loss window")
    ap.add_argument("--out", type=str, default="artifacts")
    args = ap.parse_args()

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax
    import jax.numpy as jnp
    import numpy as np

    from atomo_tpu.codecs import SvdCodec
    from atomo_tpu.data import SPECS, BatchIterator, synthetic_dataset
    from atomo_tpu.models import get_model
    from atomo_tpu.training import create_state, make_optimizer, make_train_step

    dataset = "cifar10"
    try:
        from atomo_tpu.data import load_dataset

        ds = load_dataset("cifar10", "./data", train=True, synthetic_fallback=False)
        dataset_kind = "real"
    except Exception:
        ds = synthetic_dataset(SPECS["cifar10"], True, size=2048)
        dataset_kind = "synthetic-fallback"

    model = get_model("resnet18", 10)
    dev = jax.devices()[0]

    def run(codec):
        opt = make_optimizer("sgd", lr=0.01, momentum=0.0)
        it = BatchIterator(ds, 128, seed=0)
        images, _ = next(iter(it.epoch()))
        state = create_state(model, opt, jax.random.PRNGKey(0), jnp.asarray(images))
        step = make_train_step(model, opt, codec=codec)
        key = jax.random.PRNGKey(1)
        stream = it.forever()
        losses = []
        t0 = time.perf_counter()
        for _ in range(args.steps):
            im, lb = next(stream)
            state, m = step(state, key, jnp.asarray(im), jnp.asarray(lb))
            losses.append(float(m["loss"]))  # device->host sync every step
        return losses, time.perf_counter() - t0, int(m["msg_bytes"])

    print("running dense oracle ...", flush=True)
    dense, dense_s, _ = run(None)
    print("running svd-rank-3 (default codec) ...", flush=True)
    codec = SvdCodec(rank=3)
    svd, svd_s, msg_bytes = run(codec)

    tail = args.tail
    d_final = float(np.mean(dense[-tail:]))
    s_final = float(np.mean(svd[-tail:]))
    ratio = s_final / max(d_final, 1e-8)
    passed = bool(ratio < 1.35 and d_final < dense[0] * 0.5 and s_final < svd[0] * 0.5)

    os.makedirs(args.out, exist_ok=True)
    record = {
        "recipe": "resnet18/cifar10 batch=128 lr=0.01 momentum=0 svd_rank=3",
        "reference": "src/run_pytorch.sh:1-20; oracle methodology src/nn_ops.py:123-169",
        "dataset": dataset,
        "dataset_kind": dataset_kind,
        "platform": dev.platform,
        "device": dev.device_kind,
        "steps": args.steps,
        "codec": {
            "name": "svd",
            "rank": codec.rank,
            "sample": codec.sample,
            "algorithm": codec.algorithm,
            "residual_probes": codec.residual_probes,
            "power_iters": codec.power_iters,
        },
        "dense_final_loss": d_final,
        "svd_final_loss": s_final,
        "final_loss_ratio": ratio,
        "tolerance": 1.35,
        "assertion_passed": passed,
        "dense_wall_s": round(dense_s, 1),
        "svd_wall_s": round(svd_s, 1),
        "msg_bytes_per_step": msg_bytes,
        "dense_losses": [round(x, 5) for x in dense],
        "svd_losses": [round(x, 5) for x in svd],
    }
    jpath = os.path.join(args.out, "CONVERGENCE.json")
    with open(jpath, "w") as f:
        json.dump(record, f, indent=1)

    def sparkline(xs, buckets=40):
        # log10 scale: training loss decays exponentially, so a linear
        # bucketing collapses everything after the first steps to one glyph
        blocks = " .:-=+*#%@"
        chunk = max(1, len(xs) // buckets)
        means = [
            float(np.log10(max(np.mean(xs[i : i + chunk]), 1e-8)))
            for i in range(0, len(xs), chunk)
        ]
        lo, hi = min(means), max(means)
        span = max(hi - lo, 1e-9)
        return "".join(blocks[int((x - lo) / span * (len(blocks) - 1))] for x in means)

    with open(os.path.join(args.out, "CONVERGENCE.md"), "w") as f:
        f.write(
            f"""# ResNet-18 convergence parity ({dataset_kind} {dataset}, {dev.device_kind})

Canonical recipe (reference `src/run_pytorch.sh:1-20`): batch 128, lr 0.01,
momentum 0, svd-rank 3. Default codec config: `{codec.sample}` sampling,
`{codec.algorithm}` SVD (sketch + {codec.residual_probes} residual probes).

| run | final loss (mean last {tail}) | wall s ({args.steps} steps) |
|---|---|---|
| dense | {d_final:.4f} | {dense_s:.1f} |
| svd-3 | {s_final:.4f} | {svd_s:.1f} |

final-loss ratio **{ratio:.3f}** (tolerance < 1.35) — assertion
**{"PASSED" if passed else "FAILED"}**.

Loss curves (high→low, {args.steps} steps):

    dense {sparkline(dense)}
    svd-3 {sparkline(svd)}

Full curves in `CONVERGENCE.json`.
"""
        )
    print(json.dumps({k: v for k, v in record.items() if "losses" not in k}, indent=1))
    return 0 if passed else 1


if __name__ == "__main__":
    raise SystemExit(main())
