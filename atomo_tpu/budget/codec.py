"""PerLeafCodec — an allocation's per-layer knobs (SVD ranks or QSGD
bit widths) as a codec wrapper.

The codecs.base tree walkers (``encode_tree`` / ``encode_leaf_subset`` /
``encode_tree_streamed`` / ``decode_tree`` / ``decode_mean_tree``)
resolve the codec PER LEAF through ``codecs.base.leaf_codec``; this
wrapper is the thing they resolve. Design constraints it satisfies:

  * STATIC per-leaf knobs: ``codec_for(i)`` returns a frozen dataclass
    whose rank is a Python int, so every payload shape is a trace-time
    constant — jit, the superstep ``lax.scan``, and the streamed
    per-bucket encode all see fixed shapes (tested under all three).
  * Key discipline untouched: the per-leaf fold_in keys are a function
    of (key, global leaf index) alone, exactly as before — the wrapper
    only swaps which static codec consumes them. With uniform ranks the
    resolved codecs compare EQUAL to the base codec, the vmap group
    keys coincide, and payloads are bit-identical to the unwrapped path
    (the degenerate-point identity, tested byte-for-byte).
  * Subset re-indexing: consumers that walk a partial leaf list with
    local indices (the layered ring's per-bucket decode) re-index via
    ``subset`` (see ``codecs.base.codec_subset``).

The wrapper intentionally has NO whole-tensor ``encode``/``decode`` of
its own: a per-leaf codec without a leaf index is a bug, and surfacing
it as an AttributeError at the call site beats silently encoding every
leaf at some default rank.
"""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass(frozen=True)
class PerLeafCodec:
    """A base codec + one resolved (frozen) codec per canonical leaf."""

    base: Any
    codecs: tuple  # per-leaf frozen codec instances, canonical order
    name: str = "svd+ab"

    @property
    def n_leaves(self) -> int:
        return len(self.codecs)

    @property
    def ks(self) -> tuple:
        return tuple(
            int(getattr(c, "rank", None) or c.bits) for c in self.codecs
        )

    def codec_for(self, i: int):
        """The codec for GLOBAL leaf index ``i`` (codecs.base.leaf_codec
        dispatch point)."""
        if not 0 <= int(i) < len(self.codecs):
            raise IndexError(
                f"PerLeafCodec covers {len(self.codecs)} leaves but leaf "
                f"{i} was requested — the allocation and the gradient "
                "tree must come from the same model"
            )
        return self.codecs[int(i)]

    def subset(self, idxs: tuple) -> "PerLeafCodec":
        """Re-indexed wrapper for a sub-list of leaves (local position j
        resolves to global leaf idxs[j] — codecs.base.codec_subset)."""
        return PerLeafCodec(
            base=self.base,
            codecs=tuple(self.codecs[int(i)] for i in idxs),
            name=self.name,
        )


def budgeted_codec(base, ks) -> PerLeafCodec:
    """Wrap ``base`` with an allocation's per-leaf knob values (canonical
    flatten order) — SVD ranks or QSGD bit widths, dispatched on which
    field the base codec carries (``budget.allocator.knob_name``). Knob
    values must be static Python ints — they size the wire payloads at
    trace time."""
    from atomo_tpu.budget.allocator import knob_name

    knob = knob_name(base)
    return PerLeafCodec(
        base=base,
        codecs=tuple(
            dataclasses.replace(base, **{knob: int(k)}) for k in ks
        ),
        name=f"{getattr(base, 'name', 'codec')}+ab",
    )
