"""Measured fabric — per-tier bandwidth/latency probed on the real mesh.

Every prediction in the system (autopilot candidate ranking, the topology
planner's per-tier reason lines, the sparse hybrid crossover, the flight
recorder's calibration column) is priced from NAMED fabric presets
(``utils/comm_model.FABRICS``), i.e. from what the operator asserts the
wire is, not what it measures as. ROADMAP open item 2 says it out loud:
"*measure* the fabric instead of naming it". This module is that probe:

  * :func:`probe_fabric` runs fenced ``ppermute`` / ``all_gather``
    ladders over a size sweep on the real mesh (the bench fence
    discipline — warm, dispatch loop, device->host scalar fence,
    best-of-reps via ``tuning.probe.fenced_seconds_per_call``), one
    ladder per tier: the flat mesh's single fabric, or — when
    ``dcn_ways > 1`` — the ici and dcn axes probed SEPARATELY on the
    same ``(dp=K, ici=n/K)`` mesh the hierarchical schedules execute on.
    Per tier it fits per-chip effective ring bandwidth from the ppermute
    size slope and per-hop latency from the small-size intercept, with
    the all_gather ladder recorded as a cross-check.
  * The result is written ATOMICALLY to ``train_dir/fabric_probe.json``
    (``write_json_atomic`` — the one artifact discipline), so a killed
    run leaves parseable evidence and a ``--resume`` reuses the
    measurement instead of re-probing.
  * ``--fabric measured`` resolves from the artifact: the ONE fabric
    parsers (``comm_model.resolve_fabric`` and
    ``topology.fabric.resolve_two_tier``) accept the probe document via
    their ``measured=`` parameter, so ``predict_step_s``,
    ``choose_plan``, the hybrid crossover, and ``enumerate_candidates``
    all price from measurement through the same grammar every other
    fabric value uses.

SEMANTICS CONTRACT (the PR-6 probe-isolation precedent): the fabric
value is a PRICING input, never a semantics input. The probe runs on
deterministic ``jnp``-built buffers — it never touches the training data
iterator's shuffle RNG or the run's init seed — so ``--fabric measured``
trains bit-identical to the same resolved knobs under a pinned scalar
fabric (drilled by bench config 14's in-row parity gate).

The probe also arms DRIFT BLAME (tuning.autopilot.OnlineRetuner): when a
step-time drift alarm fires, the retuner re-runs the cheap
:func:`quick_probe` and the ``perf_drift`` incident records whether the
FABRIC moved (per-tier baseline-vs-measured GB/s quoted; the artifact is
re-written so later pricing reads the new numbers) or the PROGRAM did
(the candidate re-probe decides), with both numbers quoted either way.

On the forced multi-device CPU mesh the "fabric" is host memcpy
bandwidth — recorded honestly (``meta.backend``), exactly like every
other CPU-mesh evidence row; the probe's value there is that the whole
measure->resolve->price loop is exercised end to end.
"""

from __future__ import annotations

import os
import time
from typing import Optional

FABRIC_PROBE_NAME = "fabric_probe.json"

# probe size sweep (bytes per chip per hop): small sizes expose the
# per-hop latency floor, large ones the bandwidth asymptote
DEFAULT_SIZES = (1 << 12, 1 << 16, 1 << 20, 1 << 23)
# the drift-blame re-probe: two points are enough for the slope, and the
# alarm path must stay cheap (it runs inside a checkpoint boundary)
QUICK_SIZES = (1 << 12, 1 << 20)
# per-tier bandwidth ratio past which drift blame says the FABRIC moved
FABRIC_MOVED_RATIO = 1.5


def probe_path(train_dir: str) -> str:
    return os.path.join(train_dir, FABRIC_PROBE_NAME)


def read_fabric_probe(train_dir: str) -> Optional[dict]:
    """The recorded probe document, or None when absent/unparseable
    (a torn or missing artifact is "no measurement", never a crash)."""
    import json

    try:
        with open(probe_path(train_dir)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def measured_bandwidths(doc: dict) -> dict:
    """``{tier label: per-chip bandwidth bytes/s}`` from a probe doc —
    the shape the ONE fabric parsers consume via ``measured=``."""
    out = {}
    for tier in (doc or {}).get("tiers", []):
        bw = tier.get("bandwidth_gbps")
        if isinstance(bw, (int, float)) and bw > 0:
            out[str(tier.get("label"))] = float(bw) * 1e9
    return out


def measured_outer_bw(doc: dict) -> float:
    """The SLOWEST measured tier's bandwidth (bytes/s) — the historical
    single-scalar meaning of a fabric value (the slowest link on the
    gradient path). Raises ValueError on an artifact with no usable
    tier, with the re-probe instruction in the message."""
    bws = measured_bandwidths(doc)
    if not bws:
        raise ValueError(
            "fabric_probe.json carries no usable tier measurement — "
            "delete it and re-run with --fabric measured to re-probe"
        )
    return min(bws.values())


def measured_two_tier(doc: dict, *, dcn_ways: int, n_dev: int):
    """A :class:`~atomo_tpu.topology.fabric.TwoTierFabric` built from
    the probe artifact — measured bandwidths AND measured per-hop
    latencies per tier (the preset anchors replaced by numbers from this
    mesh). Needs a probe that measured both tiers (``--dcn-ways`` was
    set when it ran)."""
    from atomo_tpu.topology.fabric import TwoTierFabric

    k = int(dcn_ways)
    tiers = {str(t.get("label")): t for t in (doc or {}).get("tiers", [])}
    if "ici" not in tiers and int(n_dev) // k == 1 and "dcn" in tiers:
        # dcn_ways == n_dev: every inner group is one chip — the inner
        # tier has no hops to probe (probe_fabric skips a 1-wide axis)
        # and its bandwidth prices zero bytes, so the dcn measurement
        # stands in rather than rejecting a shape resolve_two_tier's own
        # grammar accepts
        tiers = dict(tiers, ici=tiers["dcn"])
    if "ici" not in tiers or "dcn" not in tiers:
        raise ValueError(
            "--fabric measured on a two-tier mesh needs a probe artifact "
            "with both ici and dcn tiers (found: "
            f"{sorted(tiers) or 'none'}); delete fabric_probe.json and "
            "re-run with --dcn-ways set so both axes are probed"
        )

    def _bw(t):
        return float(t["bandwidth_gbps"]) * 1e9

    def _lat(t, default):
        v = t.get("latency_us")
        return float(v) / 1e6 if isinstance(v, (int, float)) else default

    from atomo_tpu.topology.fabric import (
        DCN_HOP_LATENCY_S,
        ICI_HOP_LATENCY_S,
    )

    return TwoTierFabric(
        inner_bw=_bw(tiers["ici"]),
        outer_bw=_bw(tiers["dcn"]),
        inner_ways=int(n_dev) // k,
        outer_ways=k,
        inner_latency_s=_lat(tiers["ici"], ICI_HOP_LATENCY_S),
        outer_latency_s=_lat(tiers["dcn"], DCN_HOP_LATENCY_S),
        inner_label="measured_ici",
        outer_label="measured_dcn",
    )


# ------------------------------------------------------------------ probe


def _ladder(mesh, axis: str, sizes, *, reps: int, warmup: int,
            best_of: int) -> list[dict]:
    """One tier's measured rows: fenced seconds for a single ppermute
    ring hop and a full all_gather of an S-byte per-chip buffer, per
    size. The buffers are deterministic ``jnp`` constants — no PRNG, no
    data-iterator contact (the probe-isolation contract)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from atomo_tpu.tuning.probe import fenced_seconds_per_call

    names = tuple(mesh.axis_names)
    ways = int(mesh.shape[axis])
    total = 1
    for n in names:
        total *= int(mesh.shape[n])
    perm = [(i, (i + 1) % ways) for i in range(ways)]
    rows = []
    for size in sizes:
        n_elem = max(int(size) // 4, 1)  # f32 elements per chip

        def hop(x):
            y = jax.lax.ppermute(x, axis, perm)
            # per-device scalar keeps the collective live under DCE and
            # the fence fetch O(1)
            return jnp.sum(y).reshape(1, 1)

        def gather(x):
            g = jax.lax.all_gather(x, axis)
            return jnp.sum(g).reshape(1, 1)

        buf = jnp.ones((total, n_elem), jnp.float32)

        def timed(fn):
            sm = jax.jit(jax.shard_map(
                fn, mesh=mesh, in_specs=P(names), out_specs=P(names),
                check_vma=False,
            ))
            secs, sync_ok = fenced_seconds_per_call(
                lambda: sm(buf), reps=reps, warmup=warmup, best_of=best_of
            )
            return secs, sync_ok

        t_pp, ok_pp = timed(hop)
        t_ag, ok_ag = timed(gather)
        rows.append({
            "bytes": int(size),
            "ppermute_ms": round(t_pp * 1e3, 6),
            "allgather_ms": round(t_ag * 1e3, 6),
            "sync_ok": bool(ok_pp and ok_ag),
        })
    return rows


def _fit_tier(rows: list[dict], ways: int) -> dict:
    """Bandwidth from the ppermute size slope, per-hop latency from the
    small-size intercept (t(S) = lat + S/bw — a stated two-point fit,
    not a regression), all_gather bandwidth as the recorded cross-check.
    Rows whose fence came back non-finite are excluded from the fit."""
    ok = [r for r in rows if r.get("sync_ok", True)]
    out = {"bandwidth_gbps": None, "latency_us": None,
           "allgather_gbps": None}
    if not ok:
        return out
    lo, hi = min(ok, key=lambda r: r["bytes"]), max(
        ok, key=lambda r: r["bytes"]
    )
    t_lo, t_hi = lo["ppermute_ms"] / 1e3, hi["ppermute_ms"] / 1e3
    if hi["bytes"] > lo["bytes"] and t_hi > t_lo:
        bw = (hi["bytes"] - lo["bytes"]) / (t_hi - t_lo)
    elif t_hi > 0:
        bw = hi["bytes"] / t_hi  # degenerate sweep: asymptote only
    else:
        return out
    out["bandwidth_gbps"] = round(bw / 1e9, 4)
    out["latency_us"] = round(max(t_lo - lo["bytes"] / bw, 0.0) * 1e6, 3)
    t_ag = hi["allgather_ms"] / 1e3
    if t_ag > 0 and ways > 1:
        out["allgather_gbps"] = round(
            hi["bytes"] * (ways - 1) / t_ag / 1e9, 4
        )
    return out


def probe_fabric(
    *,
    n_dev: int,
    dcn_ways: int = 0,
    sizes=DEFAULT_SIZES,
    reps: int = 3,
    warmup: int = 1,
    best_of: int = 2,
    log_fn=print,
) -> dict:
    """Measure the mesh's fabric per tier (module docstring). Flat mesh:
    one tier labeled ``ici`` (the convention for "the fabric connecting
    this mesh's chips"). ``dcn_ways > 1``: the ``(dp=K, ici=n/K)``
    two-tier mesh with the ici and dcn axes probed separately. Returns
    the probe document; writing it is the caller's move
    (:func:`ensure_fabric_probe` pairs it with the artifact path)."""
    import jax

    from atomo_tpu.parallel import make_mesh

    t0 = time.perf_counter()
    n = int(n_dev)
    if n < 2:
        raise ValueError(
            "--fabric measured needs a multi-device mesh: a single "
            "device has no inter-chip fabric to measure"
        )
    k = int(dcn_ways)
    two_tier = k > 1 and n % k == 0 and k <= n
    tiers = []
    if two_tier:
        mesh = make_mesh(n, axes=(("dp", k), ("ici", n // k)))
        for label, axis in (("ici", "ici"), ("dcn", "dp")):
            ways = int(mesh.shape[axis])
            if ways < 2:
                continue  # a 1-wide axis has no hops to time
            rows = _ladder(mesh, axis, sizes, reps=reps, warmup=warmup,
                           best_of=best_of)
            tiers.append({
                "label": label, "axis": axis, "ways": ways,
                **_fit_tier(rows, ways), "rows": rows,
            })
    else:
        mesh = make_mesh(n)
        rows = _ladder(mesh, "dp", sizes, reps=reps, warmup=warmup,
                       best_of=best_of)
        tiers.append({
            "label": "ici", "axis": "dp", "ways": n,
            **_fit_tier(rows, n), "rows": rows,
        })
    doc = {
        "kind": "fabric_probe",
        "meta": {
            "backend": jax.default_backend(),
            "n_devices": n,
            "dcn_ways": k if two_tier else 0,
            "sizes_bytes": [int(s) for s in sizes],
            "reps": int(reps),
            "best_of": int(best_of),
            "probe_wall_s": round(time.perf_counter() - t0, 3),
        },
        "tiers": tiers,
        "complete": all(
            t.get("bandwidth_gbps") for t in tiers
        ) and bool(tiers),
    }
    for t in tiers:
        log_fn(
            f"Fabric probe: {t['label']} ({t['ways']} ways) measured "
            f"{t['bandwidth_gbps']} GB/s/chip, {t['latency_us']} us/hop "
            f"(all_gather cross-check {t['allgather_gbps']} GB/s)"
        )
    return doc


def write_fabric_probe(train_dir: str, doc: dict) -> str:
    """Atomic artifact write (the one discipline — write_json_atomic)."""
    from atomo_tpu.utils.tracing import write_json_atomic

    path = probe_path(train_dir)
    write_json_atomic(path, doc)
    return path


def ensure_fabric_probe(
    train_dir: str,
    *,
    n_dev: int,
    dcn_ways: int = 0,
    reuse: bool = False,
    log_fn=print,
) -> dict:
    """The CLI's ``--fabric measured`` startup hook: reuse a complete
    recorded probe when ``reuse`` (a ``--resume`` must not re-measure —
    the resumed pricing should match the original run's), else probe the
    mesh and write ``train_dir/fabric_probe.json``. A recorded probe for
    a DIFFERENT mesh shape is never reused — the measurement describes a
    topology that no longer exists (the decision_reusable precedent)."""
    # normalize the requested shape the same way probe_fabric will
    # record it (a non-dividing or degenerate dcn_ways probes flat with
    # meta.dcn_ways=0) — otherwise a --resume of such a run would
    # re-probe forever on a mismatch that is not one
    k = int(dcn_ways)
    k_norm = k if (1 < k <= int(n_dev) and int(n_dev) % k == 0) else 0
    if reuse:
        doc = read_fabric_probe(train_dir)
        if doc and doc.get("complete"):
            meta = doc.get("meta") or {}
            if (
                meta.get("n_devices") == int(n_dev)
                and int(meta.get("dcn_ways") or 0) == k_norm
            ):
                log_fn(
                    f"Fabric probe: reusing {probe_path(train_dir)} "
                    "(delete the file to re-measure)"
                )
                return doc
            log_fn(
                "Fabric probe: NOT reusing the recorded artifact (it "
                f"measured n_devices={meta.get('n_devices')}, "
                f"dcn_ways={meta.get('dcn_ways')} — this run has "
                f"{n_dev}/{dcn_ways}); re-probing"
            )
    doc = probe_fabric(n_dev=n_dev, dcn_ways=dcn_ways, log_fn=log_fn)
    path = write_fabric_probe(train_dir, doc)
    log_fn(f"Fabric probe: artifact -> {path}")
    return doc


def quick_probe(*, n_dev: int, dcn_ways: int = 0, log_fn=print) -> dict:
    """The drift-blame re-probe: the same ladder at two sizes, one rep —
    cheap enough for a checkpoint boundary, accurate enough to answer
    "did the fabric move by >1.5x", which is the only question blame
    asks of it."""
    return probe_fabric(
        n_dev=n_dev, dcn_ways=dcn_ways, sizes=QUICK_SIZES, reps=1,
        warmup=1, best_of=1, log_fn=log_fn,
    )


# ------------------------------------------------- per-tier prediction


def predicted_tier_ms(
    *,
    aggregate: str,
    dense_bytes: float,
    payload_bytes: float,
    ways: int,
    fabric_bw: Optional[float] = None,
    fabric_label: str = "fabric",
    fabric2=None,
    plan_name: Optional[str] = None,
) -> dict:
    """``{tier label: predicted comm ms}`` — the per-tier decomposition
    of the winner's predicted step time that the flight recorder's
    per-tier calibration column tracks against. Flat aggregates cross
    one fabric end to end (one tier, the wire formula per mode);
    hierarchical plans decompose over both tiers via
    ``topology.schedule.plan_wire_bytes``. Returns {} when the context
    cannot be priced (no bandwidth) — an absent column, never a made-up
    one."""
    from atomo_tpu.utils.comm_model import (
        ring_allgather_wire_bytes,
        ring_allreduce_wire_bytes,
        ring_stream_wire_bytes,
    )

    ways = int(ways)
    if ways <= 1:
        return {}
    if aggregate == "hierarchical" and fabric2 is not None:
        from atomo_tpu.topology.schedule import (
            plan_from_name,
            plan_wire_bytes,
        )

        wires = plan_wire_bytes(
            plan_from_name(plan_name or "legacy"),
            dense_bytes=dense_bytes,
            payload_bytes=payload_bytes,
            fabric=fabric2,
        )
        return {
            fabric2.inner_label: round(fabric2.tier_time_s(
                wires["inner_bytes"], "inner", wires["inner_hops"]
            ) * 1e3, 4),
            fabric2.outer_label: round(fabric2.tier_time_s(
                wires["outer_bytes"], "outer", wires["outer_hops"]
            ) * 1e3, 4),
        }
    if not fabric_bw or fabric_bw <= 0:
        return {}
    if aggregate == "psum" or not payload_bytes:
        wire = ring_allreduce_wire_bytes(dense_bytes, ways)
    elif aggregate == "ring":
        wire = ring_stream_wire_bytes(payload_bytes, dense_bytes, ways)
    else:
        wire = ring_allgather_wire_bytes(payload_bytes, ways)
    return {fabric_label: round(wire / float(fabric_bw) * 1e3, 4)}
