"""Kill→restart→resume integration drill (the fault-tolerance tentpole's
acceptance test): a trainer killed mid-run by the chaos harness resumes
from the last valid checkpoint and recovers the uninterrupted run's exact
loss trajectory; a step with an injected non-finite gradient is skipped
without NaN-ing the params. Also proves the simulated-process-death path
of the real 2-process worker (tests/_mp_worker.py)."""

import os
import re
import subprocess
import sys

import pytest

from atomo_tpu.utils.chaos import CHAOS_EXIT_CODE

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_HERE)
_FT_WORKER = os.path.join(_HERE, "_ft_worker.py")
_MP_WORKER = os.path.join(_HERE, "_mp_worker.py")
_STEP_RE = re.compile(r"Worker: 0, Step: (\d+),.*?Loss: ([0-9.+-naif]+)")


def _run_ft(train_dir, chaos="", resume=False, timeout=240, extra_env=None):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "ATOMO_FT_DIR": str(train_dir),
        "ATOMO_FT_RESUME": "1" if resume else "0",
        "ATOMO_CHAOS": chaos,
        "PYTHONPATH": _REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    if extra_env:
        env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, _FT_WORKER],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    losses = {
        int(m.group(1)): m.group(2)
        for m in map(_STEP_RE.search, proc.stdout.splitlines())
        if m
    }
    final = None
    for line in proc.stdout.splitlines():
        if line.startswith("FTFINAL "):
            final = line.split()[1]
    return proc, losses, final


def test_kill_restart_resume_recovers_oracle_trajectory(tmp_path):
    """The acceptance drill. Three runs of tests/_ft_worker.py:

    oracle:  nan@3 (guard skips it), 8 steps, uninterrupted
    crash:   same plan + kill@6 — chaos hard-kills the process before
             step 6; the newest checkpoint is step 4 (save_freq=2)
    resume:  restarts with --resume semantics, replays the data stream,
             and must reproduce the oracle's steps 5..8 and final params
    """
    from atomo_tpu.training.checkpoint import latest_valid_step

    oracle_dir = tmp_path / "oracle"
    crash_dir = tmp_path / "crash"

    p_oracle, l_oracle, final_oracle = _run_ft(oracle_dir, chaos="nan@3")
    assert p_oracle.returncode == 0, p_oracle.stderr[-3000:]
    assert final_oracle is not None
    assert sorted(l_oracle) == list(range(1, 9))
    # the injected non-finite gradient was skipped, not trained through:
    # every logged loss is finite and the guard announced the skip
    assert all("nan" not in v and "inf" not in v for v in l_oracle.values())
    assert any(
        line.startswith("Guard: Step: 3") for line in p_oracle.stdout.splitlines()
    ), p_oracle.stdout

    p_crash, l_crash, final_crash = _run_ft(crash_dir, chaos="nan@3,kill@6")
    assert p_crash.returncode == CHAOS_EXIT_CODE, (
        p_crash.returncode, p_crash.stderr[-3000:]
    )
    assert final_crash is None  # it really died mid-run
    assert sorted(l_crash) == list(range(1, 6))
    assert latest_valid_step(str(crash_dir)) == 4
    # pre-crash trajectory already matches the oracle (same seed/plan)
    assert {s: l_crash[s] for s in l_crash} == {s: l_oracle[s] for s in l_crash}

    p_res, l_res, final_res = _run_ft(crash_dir, chaos="nan@3", resume=True)
    assert p_res.returncode == 0, p_res.stderr[-3000:]
    assert any(
        "Resumed from" in line and "step 4" in line
        for line in p_res.stdout.splitlines()
    ), p_res.stdout
    assert sorted(l_res) == [5, 6, 7, 8]  # restarted after the checkpoint
    # the recovered trajectory IS the oracle's trajectory...
    assert {s: l_res[s] for s in l_res} == {s: l_oracle[s] for s in l_res}
    # ...down to bit-identical final parameters (full opt-state restore +
    # data replay; one backend, one executable)
    assert final_res == final_oracle


def test_mp_worker_chaos_death_is_detected(tmp_path):
    """Simulated process death on the REAL 2-process jax.distributed worker
    path: with ATOMO_CHAOS=kill@1 both workers hard-exit with the chaos
    exit code before the collective forms — the parent sees dead processes
    (the reference's master would instead hang in waitany forever,
    SURVEY.md §5.3)."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "JAX_COORDINATOR_ADDRESS": "127.0.0.1:0",  # never dialed: death first
        "JAX_NUM_PROCESSES": "2",
        "ATOMO_CHAOS": "kill@1",
        "PYTHONPATH": _REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    procs = [
        subprocess.Popen(
            [sys.executable, _MP_WORKER],
            env={**env, "JAX_PROCESS_ID": str(i)},
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(2)
    ]
    for p in procs:
        out, err = p.communicate(timeout=120)
        assert p.returncode == CHAOS_EXIT_CODE, (p.returncode, err[-2000:])
        assert "CHAOS: killing process" in err
        assert "RESULT" not in out  # died before doing any work


# ---------------- PR 5: divergence doctor drills ----------------


def _cli_train(train_dir, *extra, timeout=180):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    cmd = [
        sys.executable, "-m", "atomo_tpu.cli", "train",
        "--synthetic", "--dataset", "mnist", "--network", "lenet",
        "--batch-size", "8", "--max-steps", "3", "--eval-freq", "2",
        "--log-interval", "1", "--n-devices", "1",
        "--train-dir", str(train_dir), *extra,
    ]
    return subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=timeout,
        cwd=_REPO_ROOT,
    )


def _read_incidents(train_dir):
    from atomo_tpu.utils.tracing import IncidentLog

    return IncidentLog.read(os.path.join(str(train_dir), "incidents.jsonl"))


@pytest.mark.slow
@pytest.mark.parametrize("superstep", [1, 2])
def test_spike_divergence_rollback_is_bit_exact_with_clean_run(
    tmp_path, superstep
):
    """The PR-5 acceptance drill: a spike-injected run (finite,
    norm-screen-passing amplification — invisible to grad_ok) must be
    caught by the windowed detector, roll back to the last HEALTHY
    checkpoint, replay the data stream, and end bit-identical to a
    never-diverged run under the same (skip) remedy. Runs at K=1 and K=2
    — the detector consumes the same per-step series either way."""
    doctor_env = {
        "ATOMO_FT_DIVERGE": "skip",
        "ATOMO_FT_STEPS": "14",
        "ATOMO_FT_SUPERSTEP": str(superstep),
        "ATOMO_CHAOS_SPIKE_SCALE": "30.0",
    }
    clean_dir, spike_dir = tmp_path / "clean", tmp_path / "spike"

    p_clean, l_clean, final_clean = _run_ft(
        clean_dir, extra_env=doctor_env
    )
    assert p_clean.returncode == 0, p_clean.stderr[-3000:]
    assert final_clean is not None
    assert not any(
        line.startswith("Doctor:") for line in p_clean.stdout.splitlines()
    ), p_clean.stdout  # the detector must not false-alarm on a sane run

    p_spike, l_spike, final_spike = _run_ft(
        spike_dir, chaos="spike@7:3", extra_env=doctor_env
    )
    assert p_spike.returncode == 0, p_spike.stderr[-3000:]
    doctor_lines = [
        line for line in p_spike.stdout.splitlines()
        if line.startswith("Doctor:")
    ]
    assert len(doctor_lines) == 1, p_spike.stdout  # exactly one rollback
    assert "rolling back" in doctor_lines[0]
    # ...and the post-recovery trajectory IS the clean trajectory, down to
    # bit-identical final parameters (healthy-checkpoint restore + stream
    # replay + generation-disarmed chaos)
    assert final_spike == final_clean
    # the recovered tail steps match the clean run's logged losses exactly
    tail = {s: l_spike[s] for s in l_spike if s in l_clean and s >= 10}
    assert tail == {s: l_clean[s] for s in tail}
    # machine-readable post-mortem: one divergence record with a rollback
    recs = _read_incidents(spike_dir)
    div = [r for r in recs if r["cause"] == "divergence"]
    assert len(div) == 1
    assert div[0]["action"] == "rollback+skip"
    assert div[0]["target"] < 7  # rolled back to a pre-spike checkpoint
    assert "step" in div[0] and "ts" in div[0]


@pytest.mark.slow
def test_rollback_budget_exhaustion_exits_rollback_code(tmp_path):
    """A run that keeps diverging past max_rollbacks must give up with
    DivergenceError; the _ft_worker surfaces it as a traceback (library
    path) — the CLI path maps it to ROLLBACK_EXIT_CODE, covered by the
    supervisor drills."""
    doctor_env = {
        "ATOMO_FT_DIVERGE": "skip",
        "ATOMO_FT_STEPS": "14",
        "ATOMO_FT_MAX_ROLLBACKS": "0",  # zero budget: first alarm gives up
        "ATOMO_CHAOS_SPIKE_SCALE": "30.0",
    }
    p, _, final = _run_ft(tmp_path / "d", chaos="spike@7:3", extra_env=doctor_env)
    assert p.returncode != 0
    assert "DivergenceError" in p.stderr
    assert final is None
    recs = _read_incidents(tmp_path / "d")
    assert any(
        r["cause"] == "divergence" and r["action"] == "give_up" for r in recs
    )


@pytest.mark.slow
def test_supervised_crashloop_recovers_within_budget(tmp_path):
    """crashloop@2 under --max-restarts 2: attempts 0 and 1 die at loop
    start, attempt 2 trains to completion — exit 0 and a complete
    incident log (2 crash records + the clean exit)."""
    d = tmp_path / "sup"
    p = _cli_train(
        d, "--chaos", "crashloop@2", "--max-restarts", "2",
        "--restart-backoff", "0.05",
    )
    assert p.returncode == 0, (p.stdout[-2000:], p.stderr[-2000:])
    assert "Supervisor: clean exit (attempt 2)" in p.stdout
    recs = _read_incidents(d)
    assert [r["cause"] for r in recs] == ["crash", "crash", "clean_exit"]
    assert [r["attempt"] for r in recs] == [0, 1, 2]
    assert recs[-1]["action"] == "done"
    # decorrelated backoff: recorded and positive
    assert all(r["backoff_s"] > 0 for r in recs[:2])


@pytest.mark.slow
def test_supervised_budget_exhaustion_exits_nonzero(tmp_path):
    """crashloop@5 under --max-restarts 1: the budget is exhausted while
    the fault persists — nonzero exit (the child's last code) and a final
    summarizing incident record."""
    d = tmp_path / "sup"
    p = _cli_train(
        d, "--chaos", "crashloop@5", "--max-restarts", "1",
        "--restart-backoff", "0.05",
    )
    assert p.returncode == CHAOS_EXIT_CODE, (p.returncode, p.stderr[-2000:])
    recs = _read_incidents(d)
    assert recs, "incident log missing"
    last = recs[-1]
    assert last["cause"] == "budget_exhausted"
    assert last["action"] == "give_up"
    assert last["rc"] == CHAOS_EXIT_CODE
    assert last["max_restarts"] == 1


@pytest.mark.slow
def test_overlap_delayed_payload_survives_rollback(tmp_path):
    """--overlap delayed + --aggregate ring: a spike-diverged run's
    rollback restores the in-flight encoded payload with the params (the
    DelayedState checkpoint), so the recovered trajectory is bit-exact
    with a clean delayed run's."""
    import hashlib
    import shutil

    import jax
    import numpy as np

    from atomo_tpu.codecs import QsgdCodec
    from atomo_tpu.data import SPECS, BatchIterator, synthetic_dataset
    from atomo_tpu.models import get_model
    from atomo_tpu.parallel import distributed_train_loop, make_mesh
    from atomo_tpu.training import (
        DetectorConfig,
        DivergeConfig,
        GuardConfig,
        make_optimizer,
    )
    from atomo_tpu.utils.chaos import ChaosConfig, ChaosInjector

    def run(train_dir, chaos_spec=None):
        shutil.rmtree(train_dir, ignore_errors=True)
        mesh = make_mesh(4)
        model = get_model("lenet", 10)
        opt = make_optimizer("sgd", lr=0.01, momentum=0.9)
        it = BatchIterator(
            synthetic_dataset(SPECS["mnist"], True, size=128), 16, seed=0
        )
        chaos = (
            ChaosInjector(
                ChaosConfig.from_spec(chaos_spec, spike_scale=30.0)
            )
            if chaos_spec
            else None
        )
        logs = []
        st = distributed_train_loop(
            model, opt, mesh, it, codec=QsgdCodec(bits=8, bucket_size=512),
            aggregate="ring", overlap="delayed", max_steps=12,
            train_dir=str(train_dir), save_freq=2, log_every=1, seed=0,
            guard=GuardConfig(), chaos=chaos,
            diverge=DivergeConfig(
                remedy="skip",
                detector=DetectorConfig(
                    window=4, zmax=4.0, patience=2, min_history=4
                ),
                max_rollbacks=2,
            ),
            log_fn=logs.append,
        )
        h = hashlib.sha256()
        for leaf in jax.tree_util.tree_leaves(jax.device_get(st.params)):
            h.update(np.asarray(leaf).tobytes())
        return h.hexdigest(), logs

    h_clean, logs_clean = run(tmp_path / "clean")
    assert not any(l.startswith("Doctor:") for l in logs_clean)
    h_spike, logs_spike = run(tmp_path / "spike", chaos_spec="spike@6:3")
    assert any("rolling back" in l for l in logs_spike), logs_spike
    assert h_spike == h_clean  # carry restored: same program family, same bits


@pytest.mark.slow
def test_host_faults_disarmed_on_rollback_replay(tmp_path):
    """kill@12 in the same plan as the spike: the alarm fires before step
    12, the rollback replays PAST step 12 — the loop's own (host-side)
    injector must have advanced its generation with the step program, or
    the replayed kill re-fires and the 'recovered' run dies."""
    doctor_env = {
        "ATOMO_FT_DIVERGE": "skip",
        "ATOMO_FT_STEPS": "14",
        "ATOMO_CHAOS_SPIKE_SCALE": "30.0",
    }
    p, losses, final = _run_ft(
        tmp_path / "d", chaos="spike@7:3,kill@12", extra_env=doctor_env
    )
    assert p.returncode == 0, (p.returncode, p.stderr[-3000:])
    assert final is not None
    assert any("rolling back" in line for line in p.stdout.splitlines())
    assert max(losses) == 14  # the replay ran through step 12 alive
