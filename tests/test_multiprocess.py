"""Real 2-process jax.distributed smoke (VERDICT r2 next-round #5).

Previously the multi-host path was tested only by monkeypatching
jax.distributed.initialize; shard_batch's
make_array_from_process_local_data branch had never executed. This test
spawns TWO actual processes with a localhost coordinator and runs one
compressed SPMD step through the whole stack (see tests/_mp_worker.py).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_mp_worker.py")
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TIMEOUT_S = 420


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_two_process(mode: str, extra_env: dict | None = None):
    port = _free_port()
    env_base = {
        **os.environ,
        **(extra_env or {}),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": "2",
        "ATOMO_MP_MODE": mode,
        # the workers import atomo_tpu from the repo root (pytest normally
        # injects it via rootdir conftest; a bare subprocess does not)
        "PYTHONPATH": _REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER],
            env={**env_base, "JAX_PROCESS_ID": str(i)},
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(2)
    ]
    results = {}
    try:
        # drain both children CONCURRENTLY: the workers block on each other
        # inside collectives, so sequential communicate() could deadlock on
        # a full stderr pipe of the not-yet-drained process
        import concurrent.futures

        with concurrent.futures.ThreadPoolExecutor(2) as pool:
            outs = list(
                pool.map(lambda p: p.communicate(timeout=_TIMEOUT_S), procs)
            )
        for p, (out, err) in zip(procs, outs):
            if p.returncode != 0 and (
                "Multiprocess computations aren't implemented" in err
            ):
                # installed jaxlib's CPU backend has no cross-process
                # collectives (API drift); the test is only meaningful on
                # runtimes that support them (real pods, newer jaxlib)
                pytest.skip(
                    "CPU backend lacks multiprocess collectives in this "
                    "jaxlib; 2-process smoke needs a capable runtime"
                )
            assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
            for line in out.splitlines():
                if line.startswith("RESULT "):
                    r = json.loads(line[len("RESULT "):])
                    results[r["pid"]] = r
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert sorted(results) == [0, 1], f"missing RESULT lines: {results}"
    r0, r1 = results[0], results[1]
    # replicated-PS equivalence across REAL process boundaries: both
    # controllers must hold bit-identical post-step state and metrics
    assert r0["loss"] == pytest.approx(r1["loss"], abs=0.0), (r0, r1)
    assert r0["params_sha256"] == r1["params_sha256"], (r0, r1)
    # the codec actually ran: factor bytes, not dense bytes, on the wire
    assert 0 < r0["msg_bytes"] == r1["msg_bytes"]
    return r0


def test_two_process_compressed_step_matches_single_process(tmp_path):
    """VERDICT r4 missing #3 / next-round #7: the compressed gather
    aggregation crosses a REAL process boundary AND lands on the params a
    single-process 4-device run computes. This is the wire-level deployment
    claim the single-chip hardware cannot exercise: what the reference's PS
    computes from networked worker messages
    (src/sync_replicas_master_nn.py:281-296) equals the local oracle.

    Tolerance note (measured): bit-for-bit holds WITHIN a topology — the
    two processes agree exactly (asserted in _run_two_process) and repeat
    runs are deterministic — but the 2-host and 1-host lowerings are
    different XLA executables whose backward reductions associate
    differently, giving ULP-scale param deltas (max |d| 1.1e-7, rel ~1e-6
    on this model; the pre-update LOSS is still bit-identical, pinning
    data/init/PRNG equality). So: loss exact, params allclose at 1e-6."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from atomo_tpu.codecs import SvdCodec
    from atomo_tpu.models import get_model
    from atomo_tpu.parallel.mesh import make_mesh
    from atomo_tpu.parallel.replicated import (
        make_distributed_train_step,
        replicate_state,
        shard_batch,
    )
    from atomo_tpu.training import create_state, make_optimizer

    r_mp = _run_two_process(
        "cv", extra_env={"ATOMO_MP_DUMP": str(tmp_path / "mp_params.npz")}
    )

    # single-process oracle: same global mesh shape, same deterministic
    # per-"process" data halves (RandomState(pid) — _mp_worker.main), same
    # init and step key
    mesh = make_mesh(4)
    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.0)
    sample = jnp.zeros((4, 28, 28, 1), jnp.float32)
    state = replicate_state(
        mesh, create_state(model, opt, jax.random.PRNGKey(0), sample)
    )
    step = make_distributed_train_step(
        model, opt, mesh, codec=SvdCodec(rank=2), aggregate="gather"
    )
    im = np.concatenate(
        [np.random.RandomState(p).rand(4, 28, 28, 1).astype(np.float32)
         for p in (0, 1)]
    )
    lb = np.concatenate(
        [np.random.RandomState(100 + p).randint(0, 10, (4,)).astype(np.int32)
         for p in (0, 1)]
    )
    gi, gl = shard_batch(mesh, im, lb)
    state, metrics = step(state, jax.random.PRNGKey(1), gi, gl)
    # the forward ran on identical data/init/keys: loss is bit-equal
    assert float(metrics["loss"]) == r_mp["loss"]
    assert int(metrics["msg_bytes"]) == r_mp["msg_bytes"]
    # post-update params: leaf-wise against the worker's dumped tree (a
    # summary scalar would absorb compensating divergences)
    dumped = np.load(r_mp["dump_path"])
    leaves = [
        np.asarray(jax.device_get(l))
        for l in jax.tree_util.tree_leaves(state.params)
    ]
    assert len(dumped.files) == len(leaves)
    for key, mine in zip(dumped.files, leaves):
        np.testing.assert_allclose(mine, dumped[key], atol=2e-6, rtol=2e-6)


@pytest.mark.slow
def test_two_process_lm_sequence_parallel_step():
    """dp x sp over TWO real processes, sequence axis ACROSS the process
    boundary: every ring-attention K/V rotation and the boundary-target
    fetch is a cross-process ppermute — the multi-host long-context claim,
    actually executed (see _mp_worker.main_lm)."""
    _run_two_process("lm")
