"""Fabric observatory (PR 13): the measured fabric probe, ``--fabric
measured`` resolution through the ONE parsers, the per-tier calibration
column, drift blame, the trace-based ``report timeline`` verb, and the
named_phase scope anchors it keys on. Runs on the forced 4-device CPU
mesh (conftest)."""

import json
import math
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from atomo_tpu.obs.fabric import (
    FABRIC_MOVED_RATIO,
    QUICK_SIZES,
    ensure_fabric_probe,
    measured_bandwidths,
    measured_outer_bw,
    measured_two_tier,
    predicted_tier_ms,
    probe_fabric,
    probe_path,
    read_fabric_probe,
    write_fabric_probe,
)

N_DEV = 4


def _quick_doc(**kw):
    kw.setdefault("n_dev", N_DEV)
    kw.setdefault("sizes", QUICK_SIZES)
    kw.setdefault("reps", 1)
    kw.setdefault("best_of", 1)
    kw.setdefault("log_fn", lambda *a, **k: None)
    return probe_fabric(**kw)


def _fake_doc(tiers):
    """A synthetic probe document: {label: (gbps, lat_us)}."""
    return {
        "kind": "fabric_probe",
        "meta": {"backend": "cpu", "n_devices": N_DEV, "dcn_ways": 0,
                 "reps": 1},
        "tiers": [
            {"label": lbl, "axis": "dp", "ways": N_DEV,
             "bandwidth_gbps": g, "latency_us": lat,
             "allgather_gbps": g, "rows": []}
            for lbl, (g, lat) in tiers.items()
        ],
        "complete": True,
    }


# ------------------------------------------------------------- the probe


def test_probe_flat_mesh_measures_one_tier():
    doc = _quick_doc()
    assert doc["complete"] is True
    assert [t["label"] for t in doc["tiers"]] == ["ici"]
    t = doc["tiers"][0]
    assert t["ways"] == N_DEV and t["bandwidth_gbps"] > 0
    assert t["latency_us"] >= 0 and t["allgather_gbps"] > 0
    # every ladder row is recorded with its fence verdict
    assert all(
        r["bytes"] > 0 and r["ppermute_ms"] > 0 and r["sync_ok"]
        for r in t["rows"]
    )
    assert doc["meta"]["n_devices"] == N_DEV
    assert doc["meta"]["dcn_ways"] == 0


def test_probe_two_tier_measures_both_axes():
    doc = _quick_doc(dcn_ways=2)
    labels = {t["label"]: t for t in doc["tiers"]}
    assert set(labels) == {"ici", "dcn"}
    assert labels["ici"]["axis"] == "ici" and labels["ici"]["ways"] == 2
    assert labels["dcn"]["axis"] == "dp" and labels["dcn"]["ways"] == 2
    assert all(t["bandwidth_gbps"] > 0 for t in doc["tiers"])
    bws = measured_bandwidths(doc)
    assert measured_outer_bw(doc) == min(bws.values())


def test_probe_rejects_single_device():
    with pytest.raises(ValueError, match="multi-device"):
        probe_fabric(n_dev=1)


def test_ensure_probe_writes_and_reuses(tmp_path, monkeypatch):
    calls = []
    import atomo_tpu.obs.fabric as fab

    real = fab.probe_fabric

    def counting(**kw):
        calls.append(kw)
        return real(**{**kw, "sizes": QUICK_SIZES, "reps": 1,
                       "best_of": 1})

    monkeypatch.setattr(fab, "probe_fabric", counting)
    d = str(tmp_path)
    doc = ensure_fabric_probe(d, n_dev=N_DEV, log_fn=lambda *a: None)
    assert os.path.exists(probe_path(d)) and len(calls) == 1
    assert read_fabric_probe(d)["complete"] is True
    # a resume reuses the recorded measurement for the SAME mesh shape
    doc2 = ensure_fabric_probe(
        d, n_dev=N_DEV, reuse=True, log_fn=lambda *a: None
    )
    assert len(calls) == 1 and doc2["meta"] == doc["meta"]
    # ... but never a measurement of a topology that no longer exists
    ensure_fabric_probe(d, n_dev=2, reuse=True, log_fn=lambda *a: None)
    assert len(calls) == 2
    assert read_fabric_probe(d)["meta"]["n_devices"] == 2


# ---------------------------------------------- the ONE-parser resolution


def test_resolve_fabric_measured_and_reject_messages():
    from atomo_tpu.utils.comm_model import resolve_fabric

    doc = _fake_doc({"ici": (40.0, 2.0), "dcn": (5.0, 20.0)})
    # measured = the SLOWEST tier (the historical scalar convention)
    assert resolve_fabric("measured", measured=doc) == 5.0e9
    with pytest.raises(ValueError, match="fabric_probe.json"):
        resolve_fabric("measured")
    # the reject usage line quotes every accepted form (PR-13 doc fix):
    # measured AND the two-tier grammar pointer
    with pytest.raises(ValueError, match="measured") as e1:
        resolve_fabric("nonsense")
    assert "inner" in str(e1.value) and "outer" in str(e1.value)
    with pytest.raises(ValueError, match="resolve_two_tier"):
        resolve_fabric("ici:dcn")


def test_resolve_two_tier_measured_uses_measured_latencies():
    from atomo_tpu.topology.fabric import resolve_two_tier

    doc = _fake_doc({"ici": (40.0, 2.0), "dcn": (5.0, 20.0)})
    f2 = resolve_two_tier("measured", dcn_ways=2, n_dev=4, measured=doc)
    assert f2.inner_bw == 40.0e9 and f2.outer_bw == 5.0e9
    assert f2.inner_latency_s == pytest.approx(2.0e-6)
    assert f2.outer_latency_s == pytest.approx(20.0e-6)
    assert f2.inner_label == "measured_ici"
    assert f2.outer_label == "measured_dcn"
    with pytest.raises(ValueError, match="fabric_probe.json"):
        resolve_two_tier("measured", dcn_ways=2, n_dev=4)
    # a flat probe (no dcn tier) cannot serve a two-tier mesh
    with pytest.raises(ValueError, match="both ici and dcn"):
        resolve_two_tier(
            "measured", dcn_ways=2, n_dev=4,
            measured=_fake_doc({"ici": (40.0, 2.0)}),
        )
    # a measured TOKEN inside <inner>:<outer> resolves per tier too
    f3 = resolve_two_tier("45:measured", dcn_ways=2, n_dev=4, measured=doc)
    assert f3.inner_bw == 45e9 and f3.outer_bw == 5.0e9


def test_tune_records_measured_tiers_in_meta(tmp_path):
    """A measured-priced tune decision carries the per-tier GB/s in its
    meta — the cross-artifact check's join key."""
    from atomo_tpu.models import get_model
    from atomo_tpu.training import make_optimizer
    from atomo_tpu.tuning.autopilot import tune
    from atomo_tpu.tuning.probe import model_init_fn

    doc = _fake_doc({"ici": (40.0, 2.0), "dcn": (5.0, 20.0)})
    model = get_model("lenet", 10)
    out = tune(
        model=model,
        optimizer=make_optimizer("sgd", lr=0.01, momentum=0.9),
        codec=None,
        model_init_fn=model_init_fn(
            model, jnp.zeros((1, 28, 28, 1), jnp.float32)
        ),
        n_dev=1,
        sample_shape=(28, 28, 1),
        num_classes=10,
        batch=4,
        fabric="measured",
        fabric_probe=doc,
        probe_top=1,
        probe_steps=1,
        probe_reps=1,
        log_fn=lambda *a: None,
    )
    meta = out["meta"]
    assert meta["fabric"] == "measured"
    assert meta["fabric_tiers"] == {"ici": 40.0, "dcn": 5.0}
    assert meta["fabric_gbps_per_chip"] == 5.0


# ------------------------------------------ per-tier calibration column


def test_predicted_tier_ms_flat_and_hierarchical():
    from atomo_tpu.topology.fabric import resolve_two_tier
    from atomo_tpu.utils.comm_model import ring_allgather_wire_bytes

    t = predicted_tier_ms(
        aggregate="gather", dense_bytes=1e6, payload_bytes=1e5,
        ways=4, fabric_bw=1e9, fabric_label="ici",
    )
    want = ring_allgather_wire_bytes(1e5, 4) / 1e9 * 1e3
    assert t == {"ici": pytest.approx(want, rel=1e-3)}
    f2 = resolve_two_tier("auto", dcn_ways=2, n_dev=4)
    t2 = predicted_tier_ms(
        aggregate="hierarchical", dense_bytes=1e6, payload_bytes=1e5,
        ways=4, fabric2=f2, plan_name="legacy",
    )
    assert set(t2) == {f2.inner_label, f2.outer_label}
    assert all(v > 0 for v in t2.values())
    # no bandwidth -> no column, never a made-up one
    assert predicted_tier_ms(
        aggregate="gather", dense_bytes=1e6, payload_bytes=1e5, ways=4,
    ) == {}


def test_recorder_emits_calib_tiers(tmp_path):
    from atomo_tpu.obs.recorder import FlightRecorder

    rec = FlightRecorder(
        str(tmp_path / "metrics.jsonl"),
        predicted_ms=10.0,
        predicted_tier_ms={"ici": 4.0},
    )
    # measured == predicted: both columns sit at 1.0
    rows = rec.record_block(1, {"loss": np.float32(1.0)}, wall_s=0.010)
    assert rows[0]["calib"] == pytest.approx(1.0, abs=1e-3)
    assert rows[0]["calib_tiers"]["ici"] == pytest.approx(1.0, abs=1e-3)
    # a +3 ms residual attributed entirely to the 4 ms tier -> 7/4
    rec2 = FlightRecorder(
        str(tmp_path / "m2.jsonl"),
        predicted_ms=10.0,
        predicted_tier_ms={"ici": 4.0},
    )
    rows = rec2.record_block(1, {"loss": np.float32(1.0)}, wall_s=0.013)
    assert rows[0]["calib_tiers"]["ici"] == pytest.approx(7.0 / 4.0,
                                                         abs=1e-3)
    # no tier decomposition -> no column (the disarmed shape unchanged)
    rec3 = FlightRecorder(str(tmp_path / "m3.jsonl"), predicted_ms=10.0)
    rows = rec3.record_block(1, {"loss": np.float32(1.0)}, wall_s=0.010)
    assert "calib_tiers" not in rows[0]


# ------------------------------------------------------------ drift blame


def _fire_alarm(tuner):
    """Feed the drift detector a clean baseline then a sustained 3x
    excursion until the alarm arms the pending re-probe."""
    tuner.observe([0.010] * tuner.cfg.min_history)
    for _ in range(tuner.cfg.patience + 2):
        tuner.observe(0.030)
        if tuner.pending:
            return
    raise AssertionError("drift alarm never fired")


def test_blame_program_when_fabric_steady(tmp_path):
    from atomo_tpu.tuning.autopilot import OnlineRetuner
    from atomo_tpu.utils.tracing import IncidentLog

    log = IncidentLog(str(tmp_path / "incidents.jsonl"))
    steady = _fake_doc({"ici": (10.0, 2.0)})
    tuner = OnlineRetuner(
        probe_fn=lambda mode: 10.0,
        incidents=log,
        fabric_probe_fn=lambda: steady,
        fabric_baseline=measured_bandwidths(steady),
        log_fn=lambda *a: None,
    )
    _fire_alarm(tuner)
    tuner.maybe_retune(40, "gather")
    recs = IncidentLog.read(log.path)
    r = [x for x in recs if x["cause"] == "perf_drift"][-1]
    assert r["action"].startswith("retune")
    blame = r["blame"]
    assert blame["verdict"] == "program"
    assert blame["step_ms"]["baseline"] > 0
    assert blame["step_ms"]["observed"] > blame["step_ms"]["baseline"]
    assert blame["fabric"]["ici"]["ratio"] == pytest.approx(1.0)


def test_blame_fabric_when_bandwidth_moved(tmp_path):
    from atomo_tpu.tuning.autopilot import OnlineRetuner
    from atomo_tpu.utils.tracing import IncidentLog

    log = IncidentLog(str(tmp_path / "incidents.jsonl"))
    base = _fake_doc({"ici": (10.0, 2.0)})
    slowed = _fake_doc({"ici": (10.0 / (FABRIC_MOVED_RATIO + 0.5), 2.0)})
    repriced = []
    tuner = OnlineRetuner(
        probe_fn=lambda mode: 10.0,
        incidents=log,
        fabric_probe_fn=lambda: slowed,
        fabric_baseline=measured_bandwidths(base),
        on_fabric_moved=repriced.append,
        log_fn=lambda *a: None,
    )
    _fire_alarm(tuner)
    tuner.maybe_retune(40, "gather")
    r = [x for x in IncidentLog.read(log.path)
         if x["cause"] == "perf_drift"][-1]
    blame = r["blame"]
    assert blame["verdict"] == "fabric"
    tier = blame["fabric"]["ici"]
    assert tier["baseline_gbps"] == 10.0
    assert tier["measured_gbps"] < 10.0 / FABRIC_MOVED_RATIO
    # the re-price hook fired with the fresh probe, and the NEXT alarm
    # compares against the new baseline (no permanent blame loop)
    assert repriced == [slowed]
    assert tuner.fabric_baseline == measured_bandwidths(slowed)


def test_blame_without_probe_states_basis(tmp_path):
    from atomo_tpu.tuning.autopilot import OnlineRetuner
    from atomo_tpu.utils.tracing import IncidentLog

    log = IncidentLog(str(tmp_path / "incidents.jsonl"))
    tuner = OnlineRetuner(
        probe_fn=lambda mode: 10.0, incidents=log, log_fn=lambda *a: None
    )
    _fire_alarm(tuner)
    tuner.maybe_retune(40, "gather")
    r = [x for x in IncidentLog.read(log.path)
         if x["cause"] == "perf_drift"][-1]
    assert r["blame"]["verdict"] == "program"
    assert "no fabric baseline" in r["blame"]["basis"]


# ------------------------------------------------- report cross-artifact


def test_report_fabric_probe_check(tmp_path):
    from atomo_tpu.obs.report import _check_fabric_probe

    doc = _fake_doc({"ici": (40.0, 2.0)})
    tune = {"meta": {"fabric": "measured", "fabric_tiers": {"ici": 40.0}}}
    assert _check_fabric_probe(tune, doc)["ok"]
    # a preset-priced decision has nothing to cross-check
    assert _check_fabric_probe({"meta": {"fabric": "ici"}}, doc)["skipped"]
    # measured-priced but the artifact vanished / disagrees / incomplete
    assert not _check_fabric_probe(tune, None)["ok"]
    bad = _fake_doc({"ici": (99.0, 2.0)})
    c = _check_fabric_probe(tune, bad)
    assert not c["ok"] and "rewritten" in c["detail"]
    incomplete = dict(doc, complete=False)
    assert not _check_fabric_probe(tune, incomplete)["ok"]
    c2 = _check_fabric_probe(
        {"meta": {"fabric": "measured",
                  "fabric_tiers": {"dcn": 5.0}}}, doc,
    )
    assert not c2["ok"] and "probe artifact measured" in c2["detail"]


def test_report_drift_blame_check():
    from atomo_tpu.obs.report import _check_drift_blame

    assert _check_drift_blame([])["skipped"]
    good = [{
        "cause": "perf_drift", "action": "retune_keep", "step": 40,
        "blame": {"verdict": "program",
                  "step_ms": {"baseline": 10.0, "observed": 31.2}},
    }]
    assert _check_drift_blame(good)["ok"]
    naked = [{"cause": "perf_drift", "action": "retune->ring", "step": 4}]
    c = _check_drift_blame(naked)
    assert not c["ok"] and "no blame verdict" in c["detail"]
    unquantified = [{
        "cause": "perf_drift", "action": "retune->ring", "step": 4,
        "blame": {"verdict": "fabric",
                  "step_ms": {"baseline": 10.0, "observed": 30.0},
                  "fabric": {"ici": {"measured_gbps": 1.0}}},
    }]
    c2 = _check_drift_blame(unquantified)
    assert not c2["ok"] and "per-tier" in c2["detail"]
    # drift observations that never triggered a retune are exempt
    assert _check_drift_blame(
        [{"cause": "perf_drift", "action": "observed"}]
    )["skipped"]


def test_report_verb_checks_include_fabric(tmp_path):
    """The new checks ride build_report: a dir with a measured-priced
    decision and a matching probe is consistent; deleting the probe
    flips fabric_probe_consistent and --strict exits 3."""
    from atomo_tpu.obs.report import build_report
    from atomo_tpu.utils.tracing import write_json_atomic

    d = str(tmp_path)
    write_fabric_probe(d, _fake_doc({"ici": (40.0, 2.0)}))
    write_json_atomic(
        os.path.join(d, "tune_decision.json"),
        {"complete": True,
         "meta": {"fabric": "measured", "fabric_tiers": {"ici": 40.0}},
         "winner": {"name": "k1", "knobs": {"superstep": 1}},
         "rows": []},
    )
    doc = build_report(d)
    names = {c["name"]: c for c in doc["checks"]}
    assert names["fabric_probe_consistent"]["ok"]
    assert not names["fabric_probe_consistent"]["skipped"]
    assert names["drift_blame_present"]["skipped"]
    assert doc["sources"]["fabric_probe_json"] is True
    os.remove(probe_path(d))
    doc2 = build_report(d)
    assert doc2["consistent"] is False
    from atomo_tpu.cli import main

    with pytest.raises(SystemExit):
        main(["report", "--train-dir", d + "/nope"])
    assert main(["report", "--train-dir", d]) == 0
    assert main(["report", "--train-dir", d, "--strict"]) == 3


# ------------------------------------------------------- named_phase HLO


QSGD = None


def _qsgd():
    global QSGD
    if QSGD is None:
        from atomo_tpu.codecs import QsgdCodec

        QSGD = QsgdCodec(bits=8, bucket_size=512)
    return QSGD


@pytest.mark.parametrize(
    "mode",
    ["gather", "ring", "stream", "sharded_gather", "sharded_ring"],
)
def test_named_phase_scopes_survive_into_compiled_hlo(mode):
    """The timeline verb keys on the named_phase scopes inside the fused
    distributed step; a refactor that drops them would silently blind it.
    Assert the anchors appear in the compiled HLO's op metadata for the
    gather, ring, and stream-encode programs — AND for the pjit-compiled
    sharded-update programs (the mesh-subsystem compile path must not
    silently drop the timeline's anchors; it additionally plants its own
    materialize_params / sharded_update scopes)."""
    from atomo_tpu.mesh import sharded_update_state
    from atomo_tpu.models import get_model
    from atomo_tpu.parallel import (
        make_distributed_train_step,
        make_mesh,
        replicate_state,
        shard_batch,
    )
    from atomo_tpu.training import create_state, make_optimizer

    mesh = make_mesh(N_DEV)
    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.9)
    images = jnp.zeros((8, 28, 28, 1), jnp.float32)
    labels = jnp.zeros((8,), jnp.int32)
    host = create_state(model, opt, jax.random.PRNGKey(0), images)
    sharded = mode.startswith("sharded_")
    if sharded:
        state, su = sharded_update_state(mesh, jax.device_get(host), opt)
    else:
        state, su = replicate_state(mesh, host), None
    step = make_distributed_train_step(
        model, opt, mesh, _qsgd(),
        aggregate="ring" if mode.endswith("ring") else "gather",
        stream_encode=mode == "stream",
        stream_bucket_bytes=1 << 16,
        sharded_update=su,
    )
    si, sl = shard_batch(mesh, images, labels)
    txt = step.lower(
        state, jax.random.PRNGKey(1), si, sl
    ).compile().as_text()
    assert "encode" in txt, mode
    if mode.endswith("ring"):
        assert "ring_exchange_decode" in txt
    else:
        assert "exchange" in txt and "decode_mean" in txt
    if sharded:
        assert "materialize_params" in txt, mode
        assert "sharded_update" in txt, mode


# --------------------------------------------------------- the timeline


def _traced_step(tmp_path, n_loops=6):
    """Capture a real xplane trace of a small jitted fn carrying the
    named_phase scopes (big enough that its device wall is measurable)."""
    from atomo_tpu.utils.tracing import named_phase, profile

    def f(x):
        with named_phase("encode"):
            y = x @ x
            for _ in range(n_loops):
                y = y @ x
        with named_phase("exchange"):
            z = jnp.sum(y, axis=0)
        with named_phase("decode_mean"):
            w = z / x.shape[0]
        return jnp.sum(w)

    jf = jax.jit(f)
    x = jnp.ones((512, 512), jnp.float32)
    float(jf(x))  # compile outside the trace
    prof = str(tmp_path / "trace")
    with profile(prof):
        for _ in range(2):
            float(jf(x))
    return prof


def test_timeline_parses_phases_from_a_real_trace(tmp_path):
    from atomo_tpu.obs.timeline import build_timeline

    prof = _traced_step(tmp_path)
    doc = build_timeline(prof)
    assert doc["trace"] and doc["module"]
    names = {c["name"]: c for c in doc["checks"]}
    assert names["timeline_phases_present"]["ok"]
    assert names["timeline_joins_metrics"]["skipped"]  # no train_dir
    assert doc["spans"], doc
    busy = {p: sum(s["phases"][p]["busy_ms"] for s in doc["spans"])
            for p in ("encode", "exchange", "decode")}
    assert busy["encode"] > 0  # the matmul chain dominates
    for s in doc["spans"]:
        for p in ("encode", "exchange", "decode"):
            ph = s["phases"][p]
            assert ph["exposed_ms"] >= 0 and ph["hidden_ms"] >= 0
            assert ph["busy_ms"] >= ph["exposed_ms"] + ph["hidden_ms"] - 1e-6


def test_timeline_join_passes_and_fails_on_fixture(tmp_path):
    """The join check must PASS against an honest metrics stream and
    FAIL on a violated fixture (missing steps; a host wall too small to
    contain the device span)."""
    from atomo_tpu.obs.recorder import metrics_path
    from atomo_tpu.obs.timeline import build_timeline

    prof = _traced_step(tmp_path)
    base = build_timeline(prof)
    max_wall = max(s["wall_ms"] for s in base["spans"])

    def write_metrics(d, steps, step_ms):
        os.makedirs(d, exist_ok=True)
        with open(metrics_path(d), "w") as f:
            f.write(json.dumps({
                "kind": "meta", "what": "profile_window",
                "first_step": 1, "last_step": 2, "profile_dir": prof,
            }) + "\n")
            for s in steps:
                f.write(json.dumps({
                    "kind": "step", "step": s, "ts": 0.0,
                    "loss": 1.0, "step_ms": step_ms,
                }) + "\n")

    # honest: the window's host wall generously contains the device span
    good = str(tmp_path / "good")
    write_metrics(good, [1, 2], step_ms=max_wall * 2)
    doc = build_timeline(prof, good)
    names = {c["name"]: c for c in doc["checks"]}
    assert names["timeline_joins_metrics"]["ok"], names
    assert doc["joined_steps"] == [1, 2]

    # violated fixture A: a recorded window step was never recorded
    holey = str(tmp_path / "holey")
    write_metrics(holey, [1], step_ms=max_wall * 2)
    doc_a = build_timeline(prof, holey)
    c = {x["name"]: x for x in doc_a["checks"]}["timeline_joins_metrics"]
    assert not c["ok"] and "missing" in c["detail"]
    assert doc_a["consistent"] is False

    # violated fixture B: the metrics claim steps far faster than the
    # device span the trace shows — they describe a different run
    fast = str(tmp_path / "fast")
    write_metrics(fast, [1, 2], step_ms=1e-4)
    doc_b = build_timeline(prof, fast)
    c = {x["name"]: x for x in doc_b["checks"]}["timeline_joins_metrics"]
    if max_wall > 1.5 * 2e-4 + 1.0:  # the guard band, stated in the check
        assert not c["ok"] and "EXCEEDS" in c["detail"]


def test_timeline_missing_trace_and_scopeless_trace(tmp_path):
    from atomo_tpu.obs.timeline import build_timeline
    from atomo_tpu.utils.tracing import profile

    doc = build_timeline(str(tmp_path / "nothing"))
    assert doc["consistent"] is False
    assert doc["checks"][0]["name"] == "timeline_trace_found"
    # a trace with no named_phase anchors is called out, not mis-read
    prof = str(tmp_path / "plain")
    jf = jax.jit(lambda x: jnp.sum(x * x))
    float(jf(jnp.ones(64)))
    with profile(prof):
        float(jf(jnp.ones(64)))
    doc2 = build_timeline(prof)
    assert doc2["consistent"] is False
    bad = [c for c in doc2["checks"] if not c["ok"]]
    assert bad and bad[0]["name"] == "timeline_phases_present"


def test_segmentation_anchors_on_one_device_line():
    """A multi-device trace carries every instruction once per DEVICE
    LINE per dispatch; segmentation must anchor on one reference line,
    not over-split each dispatch into per-device fragments (review
    finding)."""
    from atomo_tpu.obs.timeline import _segment_executions

    events = []
    for d in range(2):  # two dispatches
        base = d * 100.0
        for line in ("dev0", "dev1"):
            off = 0.1 if line == "dev1" else 0.0
            for i, op in enumerate(("a", "b", "c")):
                t = base + i * 1.0 + off
                events.append({
                    "name": op, "line": ("p", line),
                    "start_us": t, "end_us": t + 0.5,
                })
    events.sort(key=lambda e: e["start_us"])
    execs = _segment_executions(events)
    assert len(execs) == 2
    # each dispatch holds BOTH devices' events (6 = 3 ops x 2 lines)
    assert [len(ex) for ex in execs] == [6, 6]


def test_fabric_check_tolerates_recorded_reprice():
    """The drift-blame flow legitimately rewrites fabric_probe.json when
    the fabric moved; the cross-artifact check must accept a number
    mismatch that a fabric-verdict incident explains — and still fail an
    unexplained one (review finding)."""
    from atomo_tpu.obs.report import _check_fabric_probe

    tune = {"meta": {"fabric": "measured", "fabric_tiers": {"ici": 40.0}}}
    rewritten = _fake_doc({"ici": (20.0, 2.0)})
    moved = [{
        "cause": "perf_drift", "action": "retune_keep",
        "blame": {"verdict": "fabric",
                  "step_ms": {"baseline": 10.0, "observed": 30.0},
                  "fabric": {"ici": {"baseline_gbps": 40.0,
                                     "measured_gbps": 20.0,
                                     "ratio": 0.5}}},
    }]
    ok = _check_fabric_probe(tune, rewritten, moved)
    assert ok["ok"] and "re-price" in ok["detail"]
    assert not _check_fabric_probe(tune, rewritten, [])["ok"]


def test_measured_two_tier_degenerate_inner():
    """dcn_ways == n_dev: every inner group is one chip, so the probe
    records only the dcn tier — the resolution must accept the shape
    its own grammar accepts instead of dead-ending (review finding)."""
    doc = _fake_doc({"dcn": (5.0, 20.0)})
    f2 = measured_two_tier(doc, dcn_ways=4, n_dev=4)
    assert f2.inner_ways == 1 and f2.outer_ways == 4
    assert f2.outer_bw == 5.0e9


def test_ensure_probe_reuse_normalizes_nondividing_dcn(tmp_path,
                                                      monkeypatch):
    """A non-dividing --dcn-ways probes flat (meta.dcn_ways=0); a resume
    with the same flags must reuse that artifact, not re-probe forever
    on a mismatch that is not one (review finding)."""
    calls = []
    import atomo_tpu.obs.fabric as fab

    real = fab.probe_fabric

    def counting(**kw):
        calls.append(kw)
        return real(**{**kw, "sizes": QUICK_SIZES, "reps": 1,
                       "best_of": 1})

    monkeypatch.setattr(fab, "probe_fabric", counting)
    d = str(tmp_path)
    ensure_fabric_probe(d, n_dev=N_DEV, dcn_ways=3,
                        log_fn=lambda *a: None)
    assert read_fabric_probe(d)["meta"]["dcn_ways"] == 0
    ensure_fabric_probe(d, n_dev=N_DEV, dcn_ways=3, reuse=True,
                        log_fn=lambda *a: None)
    assert len(calls) == 1


def test_phase_of_classification():
    from atomo_tpu.obs.timeline import phase_of

    assert phase_of("jit(f)/jit(main)/encode/mul") == "encode"
    assert phase_of("jit(f)/transpose/decode_mean/dot") == "decode"
    assert phase_of("jit(f)/ring_exchange_decode/ppermute") == "exchange"
    assert phase_of("jit(f)/delayed_exchange/all_gather") == "exchange"
    assert phase_of("jit(f)/hybrid_exchange/all_gather") == "exchange"
    assert phase_of("jit(f)/dense/add") == "compute"
    assert phase_of(None) == "compute"


# --------------------------------------------- CLI wiring + deprecation


def test_preflight_rejects_measured_without_train_dir():
    from atomo_tpu.cli import main

    with pytest.raises(SystemExit, match="fabric_probe.json"):
        main(["train", "--fabric", "measured", "--train-dir", "",
              "--synthetic", "--n-devices", "4"])
    with pytest.raises(SystemExit, match="multi-device"):
        main(["train", "--fabric", "measured", "--train-dir", "x",
              "--synthetic", "--n-devices", "1"])


def test_phase_metrics_rejects_point_at_report_timeline():
    """Satellite: the conflict rejects all carry the replacement
    pointer, and the shared constant keeps the surfaces from drifting."""
    from atomo_tpu.cli import main
    from atomo_tpu.training.resilience import diverge_conflict
    from atomo_tpu.utils.tracing import PHASE_METRICS_HINT

    assert "report timeline" in PHASE_METRICS_HINT
    for argv in (
        ["train", "--auto", "tune", "--train-dir", "x",
         "--phase-metrics"],
        ["train", "--overlap", "delayed", "--code", "qsgd",
         "--n-devices", "4", "--phase-metrics"],
        ["train", "--stream-encode", "on", "--code", "qsgd",
         "--n-devices", "4", "--phase-metrics"],
        ["train", "--sparse-rows", "on", "--n-devices", "4",
         "--phase-metrics"],
        ["train", "--obs-quality", "--code", "qsgd", "--phase-metrics"],
        ["train", "--elastic", "--train-dir", "x", "--grad-guard",
         "--save-freq", "2", "--n-devices", "4", "--phase-metrics"],
    ):
        with pytest.raises(SystemExit, match="report timeline"):
            main(argv)
    reason = diverge_conflict(
        "skip", train_dir="x", phase_metrics=True, save_freq=2,
    )
    assert reason and "report timeline" in reason


def test_report_timeline_verb_requires_a_trace(tmp_path):
    from atomo_tpu.cli import main

    with pytest.raises(SystemExit, match="profile dir"):
        main(["report", "timeline", "--train-dir", str(tmp_path)])


# ----------------------------------------------- scenario table + lint


def test_scenario_table_from_probe(tmp_path):
    import subprocess
    import sys

    doc = _fake_doc({"ici": (40.0, 2.0), "dcn": (5.0, 20.0)})
    path = tmp_path / "fabric_probe.json"
    path.write_text(json.dumps(doc))
    p = subprocess.run(
        [sys.executable, "scripts/scenario_table.py", "--ways", "8",
         "--from-probe", str(path)],
        capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert p.returncode == 0, p.stderr[-2000:]
    assert "measured_ici" in p.stdout and "measured_dcn" in p.stdout
    assert "measured 2-tier" in p.stdout
    assert "measured fabric" in p.stdout  # the source caveat line


def test_artifact_lint_covers_the_probe_writer(tmp_path):
    """scripts/check_artifact_discipline.py scans the whole package, so
    the new artifact writer is covered BY CONSTRUCTION — prove it: the
    shipped module is in the target set and clean, and a json.dump
    smuggled into it would be flagged."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_artifact_discipline",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "check_artifact_discipline.py",
        ),
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    assert lint.collect_violations() == []
    rel = os.path.join("atomo_tpu", "obs", "fabric.py")
    bad = tmp_path / "fabric.py"
    bad.write_text(
        "import json\n"
        "def write_fabric_probe(train_dir, doc):\n"
        "    with open(train_dir + '/fabric_probe.json', 'w') as f:\n"
        "        json.dump(doc, f)\n"
    )
    out = lint.scan_file(str(bad), rel)
    assert out and "write_json_atomic" in out[0]
