"""Data layer: datasets (disk or synthetic) + TPU-first input pipeline."""

from atomo_tpu.data.datasets import (  # noqa: F401
    SPECS,
    ArrayDataset,
    DatasetSpec,
    canonical_name,
    load_dataset,
    synthetic_dataset,
)
from atomo_tpu.data.pipeline import (  # noqa: F401
    BatchIterator,
    augment_batch,
    normalize,
)
from atomo_tpu.data.zipf import (  # noqa: F401
    zipf_dataset,
    zipf_probs,
    zipf_spec,
)
