"""Tensor parallelism: Megatron-TP forward/step parity and codec composition.

The oracle is the stock single-device TransformerLM (models/transformer.py):
the TP-laid forward must reproduce it exactly, and a (dp=2, tp=4) sharded
train step with codec=None must land on the same loss and updated params as
plain full-batch AD + optax on one device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from atomo_tpu.codecs import SvdCodec
from atomo_tpu.models.transformer import TransformerLM
from atomo_tpu.parallel.mesh import make_mesh
from atomo_tpu.parallel.tp import (
    create_tp_lm_state,
    lm_params_to_tp,
    make_tp_lm_train_step,
    make_tp_state_specs,
    shard_tp_tokens,
    tp_lm_forward,
    tp_param_specs,
    tp_params_to_lm,
)

CFG = dict(vocab_size=16, max_len=12, width=16, depth=2, num_heads=4)


pytestmark = pytest.mark.slow  # heavy multi-device compile/parity runs; deselect with -m "not slow"


def _lm_and_params(key=0):
    lm = TransformerLM(**CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (4, 10), 0, CFG["vocab_size"])
    params = lm.init(jax.random.PRNGKey(key), tokens[:, :8])["params"]
    return lm, params, tokens


def test_tp_layout_roundtrip():
    _, params, _ = _lm_and_params()
    tp = lm_params_to_tp(params, CFG["num_heads"])
    back = tp_params_to_lm(tp, CFG["num_heads"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), params, back
    )


def test_tp_forward_matches_stock_model():
    lm, params, tokens = _lm_and_params()
    want = lm.apply({"params": params}, tokens)
    got = tp_lm_forward(lm_params_to_tp(params, CFG["num_heads"]), tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_tp_specs_shard_the_right_leaves():
    _, params, _ = _lm_and_params()
    tp = lm_params_to_tp(params, CFG["num_heads"])
    specs = tp_param_specs(tp)
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    by_name = {"/".join(str(p) for p in path): s for path, s in flat}
    sharded = [k for k, s in by_name.items() if any(a == "tp" for a in s if a)]
    # qkv+proj+up+down per block, + head
    assert len(sharded) == 4 * CFG["depth"] + 1
    assert all("emb" not in k and "ln" not in k for k in sharded)


@pytest.mark.parametrize("opt_name", ["sgd", "adam"])
def test_tp_step_matches_single_device(opt_name):
    if opt_name == "sgd":
        opt = optax.sgd(0.1, momentum=0.9)
    else:
        opt = optax.adam(1e-2)
    mesh = make_mesh(8, axes=(("dp", 2), ("tp", 4)))
    lm, params0, tokens = _lm_and_params()

    state, specs = create_tp_lm_state(mesh, CFG, opt, jax.random.PRNGKey(0))
    # overwrite the state's params with the oracle's for exact comparison
    tp0 = lm_params_to_tp(params0, CFG["num_heads"])
    from atomo_tpu.parallel.tp import shard_tp_state
    from atomo_tpu.training.trainer import TrainState

    state = shard_tp_state(
        mesh,
        TrainState(
            step=jnp.zeros((), jnp.int32),
            params=tp0,
            batch_stats={},
            opt_state=opt.init(tp0),
        ),
        specs,
    )
    # oracle FIRST: the tp step donates its state, whose leaves may alias
    # params0's buffers (layout conversion is a pure reshape)
    def loss_fn(p):
        logits = lm.apply({"params": p}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], tokens[:, 1:]
        ).mean()

    loss, grads = jax.value_and_grad(loss_fn)(params0)
    updates, _ = opt.update(grads, opt.init(params0), params0)
    want_params = jax.device_get(optax.apply_updates(params0, updates))

    step = make_tp_lm_train_step(CFG, opt, mesh, specs, codec=None)
    toks = shard_tp_tokens(mesh, tokens)
    state2, metrics = step(state, jax.random.PRNGKey(1), toks)

    np.testing.assert_allclose(float(metrics["loss"]), float(loss), atol=1e-5)
    got_params = tp_params_to_lm(
        jax.device_get(state2.params), CFG["num_heads"]
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5
        ),
        got_params,
        want_params,
    )
    assert int(state2.step) == 1


def test_tp_step_with_codec_runs_and_compresses():
    opt = optax.sgd(0.05, momentum=0.9)
    mesh = make_mesh(8, axes=(("dp", 2), ("tp", 4)))
    state, specs = create_tp_lm_state(mesh, CFG, opt, jax.random.PRNGKey(3))
    step = make_tp_lm_train_step(
        CFG, opt, mesh, specs, codec=SvdCodec(rank=2)
    )
    tokens = jax.random.randint(jax.random.PRNGKey(9), (4, 10), 0, CFG["vocab_size"])
    toks = shard_tp_tokens(mesh, tokens)
    st = state
    for i in range(2):
        st, metrics = step(st, jax.random.PRNGKey(10 + i), toks)
    assert np.isfinite(float(metrics["loss"]))
    assert int(metrics["msg_bytes"]) < int(metrics["dense_bytes"])
    assert int(st.step) == 2


def test_tp_rejects_indivisible_heads():
    mesh = make_mesh(8, axes=(("dp", 2), ("tp", 4)))
    bad = dict(CFG, num_heads=3, width=18)
    with pytest.raises(ValueError, match="num_heads"):
        create_tp_lm_state(mesh, bad, optax.sgd(0.1), jax.random.PRNGKey(0))


def test_tp_sp_3d_step_matches_single_device():
    """The flagship composition: one (dp=2, tp=2, sp=2) step — compressed-DP
    x Megatron-TP x ring-SP — lands on the same loss and params as plain
    single-device AD + SGD on the full batch."""
    from atomo_tpu.parallel.tp import make_tp_sp_lm_train_step

    cfg = dict(vocab_size=16, max_len=12, width=16, depth=2, num_heads=4)
    opt = optax.sgd(0.1, momentum=0.9)
    mesh = make_mesh(8, axes=(("dp", 2), ("tp", 2), ("sp", 2)))
    lm = TransformerLM(**cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (4, 8), 0, 16)
    params0 = lm.init(jax.random.PRNGKey(0), tokens)["params"]

    def loss_fn(p):
        logits = lm.apply({"params": p}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], tokens[:, 1:]
        ).mean()

    loss, grads = jax.value_and_grad(loss_fn)(params0)
    want = jax.device_get(
        optax.apply_updates(params0, opt.update(grads, opt.init(params0), params0)[0])
    )
    want_loss = float(loss)

    from atomo_tpu.parallel.tp import shard_tp_state
    from atomo_tpu.training.trainer import TrainState

    tp0 = lm_params_to_tp(params0, cfg["num_heads"])
    state_specs_source, specs = create_tp_lm_state(
        mesh, cfg, opt, jax.random.PRNGKey(0)
    )
    del state_specs_source
    state = shard_tp_state(
        mesh,
        TrainState(
            step=jnp.zeros((), jnp.int32), params=tp0, batch_stats={},
            opt_state=opt.init(tp0),
        ),
        specs,
    )
    step = make_tp_sp_lm_train_step(cfg, opt, mesh, specs, codec=None)
    toks = jax.device_put(
        tokens, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("dp", "sp")
        )
    )
    state2, metrics = step(state, jax.random.PRNGKey(1), toks)

    np.testing.assert_allclose(float(metrics["loss"]), want_loss, atol=1e-5)
    got = tp_params_to_lm(jax.device_get(state2.params), cfg["num_heads"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5
        ),
        got,
        want,
    )


def test_tp_sp_3d_step_with_codec_learns():
    from atomo_tpu.parallel.tp import make_tp_sp_lm_train_step

    cfg = dict(vocab_size=16, max_len=12, width=16, depth=2, num_heads=4)
    opt = optax.sgd(0.1, momentum=0.9)
    mesh = make_mesh(8, axes=(("dp", 2), ("tp", 2), ("sp", 2)))
    state, specs = create_tp_lm_state(mesh, cfg, opt, jax.random.PRNGKey(3))
    step = make_tp_sp_lm_train_step(cfg, opt, mesh, specs, codec=SvdCodec(rank=2))
    row = jnp.arange(8, dtype=jnp.int32) % 16
    tokens = jnp.tile(row[None], (4, 1))
    toks = jax.device_put(
        tokens, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("dp", "sp")
        )
    )
    st, losses = state, []
    for i in range(10):
        st, m = step(st, jax.random.PRNGKey(i), toks)
        losses.append(float(m["loss"]))
    assert int(m["msg_bytes"]) < int(m["dense_bytes"])
    assert losses[-1] < losses[0] * 0.8, losses
