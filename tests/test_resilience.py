"""Anomaly-guarded stepping + retry wrapper tests (training/resilience.py;
skip-and-rescale wiring in trainer.py / parallel/replicated.py).

The policy under test: drop an anomalous replica's contribution and
re-scale the surviving average by n/kept — valid because ATOMO's estimator
is unbiased (resilience.py docstring). The psum-mode test checks the
arithmetic EXACTLY against per-shard gradients computed outside the SPMD
step (LeNet is deterministic: no dropout, no BN)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from atomo_tpu.codecs import SvdCodec
from atomo_tpu.models import get_model
from atomo_tpu.parallel.mesh import make_mesh
from atomo_tpu.parallel.replicated import (
    make_distributed_train_step,
    replicate_state,
    shard_batch,
)
from atomo_tpu.training import GuardConfig, create_state, grad_ok, with_retries
from atomo_tpu.training.trainer import make_train_step
from atomo_tpu.utils.chaos import ChaosConfig, ChaosInjector


# ---------------- grad_ok ----------------


def test_grad_ok_screens_nonfinite_and_norm():
    good = {"a": jnp.ones((3,)), "b": jnp.zeros((2, 2))}
    assert bool(grad_ok(good))
    assert not bool(grad_ok({"a": jnp.array([1.0, jnp.nan])}))
    assert not bool(grad_ok({"a": jnp.array([jnp.inf])}))
    # norm screen: ||g|| = 2 over 4 unit entries
    g = {"a": jnp.ones((4,))}
    assert bool(grad_ok(g, max_grad_norm=3.0))
    assert not bool(grad_ok(g, max_grad_norm=1.0))
    # f32 overflow in the sum of squares reads as non-finite -> dropped
    assert not bool(grad_ok({"a": jnp.full((4,), 1e30)}, max_grad_norm=1e6))


# ---------------- with_retries ----------------


def test_with_retries_recovers_and_backs_off():
    calls, slept, notes = [], [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("disk on fire")
        return "ok"

    wrapped = with_retries(
        flaky,
        attempts=4,
        base_delay=0.1,
        on_retry=lambda i, exc: notes.append((i, str(exc))),
        sleep=slept.append,
    )
    assert wrapped() == "ok"
    assert len(calls) == 3
    assert slept == [0.1, 0.2]  # exponential
    assert [i for i, _ in notes] == [1, 2]


def test_with_retries_exhausts_and_raises():
    slept = []
    wrapped = with_retries(
        lambda: (_ for _ in ()).throw(OSError("nope")),
        attempts=3,
        sleep=slept.append,
    )
    with pytest.raises(OSError):
        wrapped()
    assert len(slept) == 2


def test_with_retries_unlisted_exception_propagates_immediately():
    calls = []

    def boom():
        calls.append(1)
        raise KeyError("bug, not flake")

    with pytest.raises(KeyError):
        with_retries(boom, attempts=5, sleep=lambda s: None)()
    assert len(calls) == 1


def test_with_retries_rejects_zero_attempts():
    with pytest.raises(ValueError):
        with_retries(lambda: None, attempts=0)


# ---------------- single-host guarded step ----------------


def _lenet_setup(lr=0.1):
    model = get_model("lenet", 10)
    opt = optax.sgd(lr)
    rng = np.random.RandomState(0)
    images = rng.rand(8, 28, 28, 1).astype(np.float32)
    labels = rng.randint(0, 10, (8,)).astype(np.int32)
    state = create_state(model, opt, jax.random.PRNGKey(0), jnp.asarray(images))
    return model, opt, state, jnp.asarray(images), jnp.asarray(labels)


def _leaves(tree):
    return [np.asarray(jax.device_get(l)) for l in jax.tree_util.tree_leaves(tree)]


def test_single_host_guard_skips_injected_nan_step():
    model, opt, state, images, labels = _lenet_setup()
    chaos = ChaosInjector(ChaosConfig.from_spec("nan@2"))
    step = make_train_step(model, opt, guard=GuardConfig(), chaos=chaos)
    key = jax.random.PRNGKey(1)

    state1, m1 = step(state, key, images, labels)
    assert float(m1["skipped"]) == 0.0
    state2, m2 = step(state1, key, images, labels)
    # the poisoned step is skipped: params/opt state held, counter advances
    assert float(m2["skipped"]) == 1.0
    assert int(state2.step) == 2
    for a, b in zip(_leaves(state2.params), _leaves(state1.params)):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(_leaves(state2.opt_state), _leaves(state1.opt_state)):
        np.testing.assert_array_equal(a, b)
    # and training continues afterwards with finite params
    state3, m3 = step(state2, key, images, labels)
    assert float(m3["skipped"]) == 0.0
    for leaf in _leaves(state3.params):
        assert np.isfinite(leaf).all()
    assert any(
        not np.array_equal(a, b)
        for a, b in zip(_leaves(state3.params), _leaves(state2.params))
    )


def test_single_host_norm_screen_drops_exploding_step():
    model, opt, state, images, labels = _lenet_setup()
    chaos = ChaosInjector(ChaosConfig.from_spec("explode@1"))
    step = make_train_step(
        model, opt, guard=GuardConfig(max_grad_norm=1e4), chaos=chaos
    )
    state1, m1 = step(state, jax.random.PRNGKey(1), images, labels)
    assert float(m1["skipped"]) == 1.0  # finite but enormous -> screened
    for a, b in zip(_leaves(state1.params), _leaves(state.params)):
        np.testing.assert_array_equal(a, b)


def test_single_host_unguarded_step_reports_not_skipped():
    model, opt, state, images, labels = _lenet_setup()
    step = make_train_step(model, opt)
    _, m = step(state, jax.random.PRNGKey(1), images, labels)
    assert float(m["skipped"]) == 0.0


# ---------------- distributed skip-and-rescale ----------------


def _per_shard_grads(model, params, images, labels, n_shards):
    """Oracle: each replica's raw gradient, computed outside the SPMD step."""
    from atomo_tpu.training.trainer import cross_entropy_loss

    def loss_fn(p, im, lb):
        return cross_entropy_loss(model.apply({"params": p}, im), lb)

    per = len(images) // n_shards
    return [
        jax.grad(loss_fn)(params, images[i * per:(i + 1) * per],
                          labels[i * per:(i + 1) * per])
        for i in range(n_shards)
    ]


def test_distributed_psum_skip_and_rescale_exact():
    """Replica 0's NaN contribution is dropped; the update must equal
    params - lr * mean(g1, g2, g3) exactly (surviving average re-scaled by
    n/kept = 4/3 of the masked sum/4... i.e. sum(g1..g3)/3)."""
    lr = 0.1
    model, opt, state0, images, labels = _lenet_setup(lr)
    # host snapshot first: the step donates its state input, and the
    # replicated copy may alias these buffers
    params_host = jax.device_get(state0.params)
    mesh = make_mesh(4)
    state = replicate_state(mesh, state0)
    chaos = ChaosInjector(ChaosConfig.from_spec("nan@1"))
    step = make_distributed_train_step(
        model, opt, mesh, codec=None, aggregate="psum",
        guard=GuardConfig(), chaos=chaos,
    )
    gi, gl = shard_batch(mesh, images, labels)
    state1, m = step(state, jax.random.PRNGKey(1), gi, gl)
    assert float(m["dropped"]) == 1.0
    assert float(m["skipped"]) == 0.0
    assert np.isfinite(float(m["loss"]))

    g = _per_shard_grads(model, params_host, images, labels, 4)
    mean_surv = jax.tree_util.tree_map(
        lambda a, b, c: (a + b + c) / 3.0, g[1], g[2], g[3]
    )
    expected = jax.tree_util.tree_map(
        lambda p, m_: p - lr * m_, params_host, mean_surv
    )
    for got, want in zip(_leaves(state1.params), _leaves(expected)):
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_distributed_gather_guard_rescales_and_stays_finite():
    model, opt, state0, images, labels = _lenet_setup()
    mesh = make_mesh(4)
    state_host = jax.device_get(state0)  # donation-proof template
    chaos = ChaosInjector(ChaosConfig.from_spec("inf@1"))

    def run():
        step = make_distributed_train_step(
            model, opt, mesh, codec=SvdCodec(rank=2), aggregate="gather",
            guard=GuardConfig(), chaos=chaos,
        )
        gi, gl = shard_batch(mesh, images, labels)
        return step(replicate_state(mesh, state_host), jax.random.PRNGKey(1), gi, gl)

    s1, m1 = run()
    assert float(m1["dropped"]) == 1.0 and float(m1["skipped"]) == 0.0
    for leaf in _leaves(s1.params):
        assert np.isfinite(leaf).all()
    # the surviving replicas DID move the params
    assert any(
        not np.array_equal(a, b)
        for a, b in zip(_leaves(s1.params), _leaves(state_host.params))
    )
    # deterministic: the chaos plan and codec keys are reproducible
    s2, m2 = run()
    for a, b in zip(_leaves(s1.params), _leaves(s2.params)):
        np.testing.assert_array_equal(a, b)


def test_distributed_all_replicas_bad_skips_step():
    model, opt, state0, images, labels = _lenet_setup()
    params_host = jax.device_get(state0.params)
    mesh = make_mesh(4)
    state = replicate_state(mesh, state0)
    chaos = ChaosInjector(ChaosConfig.from_spec("nan@1*"))  # every replica
    step = make_distributed_train_step(
        model, opt, mesh, codec=SvdCodec(rank=2), aggregate="gather",
        guard=GuardConfig(), chaos=chaos,
    )
    gi, gl = shard_batch(mesh, images, labels)
    s1, m = step(state, jax.random.PRNGKey(1), gi, gl)
    assert float(m["skipped"]) == 1.0
    assert float(m["dropped"]) == 4.0
    assert int(s1.step) == 1  # counter advances; weights do not
    for got, want in zip(
        _leaves(s1.params), [np.asarray(l) for l in jax.tree_util.tree_leaves(params_host)]
    ):
        np.testing.assert_array_equal(got, want)


def test_hierarchical_guard_drops_poisoned_inner_group():
    model, opt, state0, images, labels = _lenet_setup()
    mesh = make_mesh(4, axes=(("dp", 2), ("ici", 2)))
    state = replicate_state(mesh, state0)
    chaos = ChaosInjector(ChaosConfig.from_spec("nan@1"))  # chip 0 -> group 0
    step = make_distributed_train_step(
        model, opt, mesh, codec=SvdCodec(rank=2), aggregate="hierarchical",
        inner_axis="ici", guard=GuardConfig(), chaos=chaos,
    )
    gi, gl = shard_batch(mesh, images, labels, axis=("dp", "ici"))
    s1, m = step(state, jax.random.PRNGKey(1), gi, gl)
    # the unit of drop is the inner group (its dense pmean is poisoned)
    assert float(m["dropped"]) == 1.0
    assert float(m["skipped"]) == 0.0
    for leaf in _leaves(s1.params):
        assert np.isfinite(leaf).all()
