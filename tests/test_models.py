"""Model zoo tests: init + forward shapes + train/eval mode handling."""

import jax
import jax.numpy as jnp
import pytest

from atomo_tpu.models import get_model, model_names


def _init_and_apply(model, x, train=False):
    rngs = {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)}
    variables = model.init(rngs, x, train=False)
    if train:
        out, _ = model.apply(
            variables, x, train=True, rngs={"dropout": jax.random.PRNGKey(2)},
            mutable=["batch_stats"] if "batch_stats" in variables else [],
        )
    else:
        out = model.apply(variables, x, train=False)
    return out, variables


@pytest.mark.parametrize("name", ["lenet", "fc"])
def test_mnist_models(name):
    model = get_model(name, 10)
    x = jnp.ones((2, 28, 28, 1))
    out, _ = _init_and_apply(model, x)
    assert out.shape == (2, 10)


@pytest.mark.parametrize(
    "name",
    [
        pytest.param("resnet18", marks=pytest.mark.slow),
        "vgg11",
        # the deep ones compile for 10-70s each on 1 CPU core — full-suite
        # only; vgg11 keeps CIFAR-net coverage in the smoke set
        pytest.param("resnet50", marks=pytest.mark.slow),
        pytest.param("resnet110", marks=pytest.mark.slow),
        pytest.param("densenet100", marks=pytest.mark.slow),
    ],
)
def test_cifar_models(name):
    model = get_model(name, 10)
    x = jnp.ones((2, 32, 32, 3))
    out, variables = _init_and_apply(model, x)
    assert out.shape == (2, 10)
    assert "batch_stats" in variables  # all CIFAR nets here use BN
    out_t, _ = _init_and_apply(model, x, train=True)
    assert out_t.shape == (2, 10)


def test_cifar100_head():
    model = get_model("resnet18", 100)
    x = jnp.ones((2, 32, 32, 3))
    out, _ = _init_and_apply(model, x)
    assert out.shape == (2, 100)


@pytest.mark.slow
def test_alexnet_imagenet_geometry():
    model = get_model("alexnet", 1000)
    x = jnp.ones((1, 224, 224, 3))
    out, _ = _init_and_apply(model, x)
    assert out.shape == (1, 1000)


def test_resnet18_param_count():
    # kuangliu CIFAR ResNet18 has ~11.17M params; match within 1%
    model = get_model("resnet18", 10)
    variables = model.init(jax.random.PRNGKey(0), jnp.ones((1, 32, 32, 3)), train=False)
    n = sum(p.size for p in jax.tree_util.tree_leaves(variables["params"]))
    assert abs(n - 11_173_962) / 11_173_962 < 0.01, n


def test_registry_names():
    names = model_names()
    for ref_name in ["lenet", "fc", "resnet18", "resnet34", "densenet", "vgg11", "alexnet"]:
        assert ref_name in names
    with pytest.raises(ValueError):
        get_model("nope")
