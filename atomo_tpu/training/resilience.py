"""Anomaly-guarded stepping + bounded retries — the train loop's immune
system.

Why skip-and-rescale is *valid here*: ATOMO's whole construction is an
unbiased gradient estimator (PAPER.md — E[decode(encode(g))] = g). The mean
over any subset of replicas is therefore still an unbiased estimate of the
true gradient, just with more variance; dropping an anomalous contribution
and re-scaling the surviving average by n/kept is statistically equivalent
to one step at a smaller world size. The reference has no analogue: one
worker shipping a NaN gradient NaNs the PS momentum buffer permanently
(sync_replicas_master_nn.py:281-296 averages whatever arrives).

The escalation ladder (one level of autonomy per rung; each rung only sees
what the rung below it let through):

  1. In-graph screening (:func:`grad_ok`, used by trainer.make_train_step
     and parallel.replicated.make_distributed_train_step): finiteness plus
     an optional global-L2-norm ceiling, computed on the raw per-replica
     gradient BEFORE it is encoded/aggregated. Single host: an anomalous
     step is skipped outright (params, opt state, BN stats all held).
     Distributed: the anomalous replica's payload is masked out of the
     gather/psum and the surviving mean is re-scaled; only a step with zero
     survivors is skipped.

  2. Windowed divergence detection (:func:`detector_update` /
     :class:`DivergenceDoctor`): the per-step screen sees one gradient at a
     time — a run diverging with perfectly FINITE gradients (an
     over-aggressive svd rank or qsgd level, the variance blow-up the
     paper's Fig. 5 warns about) sails straight through ``grad_ok``. The
     detector watches the per-step loss series (the same ``(K,)`` block
     superstep execution already returns), a guard skip-rate EMA, and a
     gradient-norm trend counter; a robust z-score sustained past
     ``patience`` steps raises the alarm. The math is a pure sequential
     fold over the per-step series, so its decisions are IDENTICAL for any
     superstep block partition of the same run.

  3. Rollback-and-replay (:meth:`DivergenceDoctor.plan_rollback` + the
     train loops): checkpoints earn a ``healthy`` tag only after the
     detector window clears past them (training.checkpoint.mark_healthy);
     on alarm the loop reloads the newest healthy checkpoint (params, opt
     state, BN stats, AND the in-flight ``--overlap delayed`` payload),
     replays the data stream to the rollback step (the PR-1 resume-replay
     machinery), and applies the configured remedy (``--on-diverge``):
     ``skip`` re-runs the window unchanged (transient-fault model),
     ``rewarm`` ramps the effective LR from ``rewarm_floor`` back to 1
     over the detector window (:class:`RemedyConfig`), ``densify``
     temporarily de-escalates to dense (uncompressed) aggregation — valid
     because every codec is an unbiased estimator of the same mean.

  4. Supervised restarts (:func:`run_supervised`): a crash-looping host
     burns a bounded budget with decorrelated-jitter backoff instead of
     the job; exit codes distinguish clean-exit / rollback-requested
     (:data:`ROLLBACK_EXIT_CODE`, raised when the in-process rollback
     budget is exhausted) / crash, and every decision lands in the
     machine-readable incident log (utils.tracing.IncidentLog). Both
     prune surfaces — the doctor's in-process rollback and the
     supervisor's rc=23 cut — go through checkpoint.prune_after, which
     also cuts the flight recorder's metrics.jsonl timeline in lockstep
     (obs.recorder.prune_metrics_after), so no artifact ever describes
     a trajectory the checkpoints discarded.

  5. Host-side bounded retries (:func:`with_retries`): checkpoint IO, the
     data pipeline, and ``jax.distributed.initialize`` are fallible host
     ops whose transient failures (NFS blips, coordinator races) should
     cost a backoff, not the job. Backoff delays carry decorrelated
     jitter so a fleet-wide blip does not synchronize a retry storm.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import os
import random
import time
from typing import Callable, Optional, Sequence

# re-export: the supervisor protocol constant lives in utils.tracing so
# utils.chaos (crashloop's reader side) can share it without an import cycle
from atomo_tpu.utils.tracing import (  # noqa: F401
    ATTEMPT_ENV,
    PHASE_METRICS_HINT,
)

SUPERVISED_ENV = "ATOMO_SUPERVISED"  # set by run_supervised on children
# the trainer's "roll me back from a clean checkpoint" exit: distinct from
# crashes (1), the watchdog's 13, and chaos's 43 — the supervisor prunes
# the diverged timeline back to the last healthy checkpoint before the
# restart, so --resume cannot land on diverged weights
ROLLBACK_EXIT_CODE = 23
# deterministic config errors discovered only in-run (they need the
# resolved device count / built codec): rc=2 — argparse's own usage-error
# code — tells the supervisor the child will fail identically every time,
# so it gives up at once instead of burning the restart budget on
# jax-booting re-execs of the same reject
CONFIG_EXIT_CODE = 2
# the elastic membership boundary: the child recorded the NEXT epoch in
# train_dir/membership.json (a shrink to the surviving roster, or a
# re-grow back to the full one) and exits so the supervisor can re-exec
# it at the new world size. A PLANNED reshape, not a crash — it is never
# charged against the restart budget
MEMBERSHIP_EXIT_CODE = 29


@dataclasses.dataclass(frozen=True)
class GuardConfig:
    """Anomaly screen settings.

    max_grad_norm: reject a contribution whose global L2 norm exceeds this
        (0 = finiteness check only). This is a *screen*, not clipping — the
        gradient is dropped, not shrunk, so the estimator stays unbiased.
    """

    max_grad_norm: float = 0.0


# ---------------------------------------------------------------------------
# Windowed divergence detection (escalation rung 2)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DetectorConfig:
    """Divergence-detector knobs.

    window: EMA window (steps) for the loss baseline and skip-rate, the
        number of alarm-free steps a checkpoint must outlive to earn its
        healthy tag, AND the rewarm/densify remedy span — one time
        constant for the whole ladder keeps the knobs coherent.
    zmax: robust z-score threshold on the loss vs its EMA baseline.
    patience: consecutive above-threshold steps before the alarm fires (a
        single bad batch is noise; a sustained excursion is divergence).
    min_history: steps of warmup before z/skip/trend alarms arm.
    skip_max: alarm when the guard's skip-rate EMA exceeds this (a run
        whose screen constantly fires is wedged, not unlucky).
    grad_ratio: alarm when the gradient norm exceeds this multiple of its
        own EMA for ``patience`` consecutive steps (the finite-explosion
        trend ``grad_ok`` cannot see).
    """

    window: int = 16
    zmax: float = 6.0
    patience: int = 3
    min_history: int = 8
    skip_max: float = 0.5
    grad_ratio: float = 10.0

    def __post_init__(self):
        # window == 1 makes alpha = 1, the EMA variance identically zero,
        # and the z-score alarm silently unfireable; window <= 0 drives
        # the EMAs outside their domains
        if self.window < 2:
            raise ValueError(
                f"detector window must be >= 2, got {self.window} (a "
                "1-step window has zero variance — the z-score alarm "
                "could never fire)"
            )
        if self.patience < 1:
            raise ValueError(
                f"detector patience must be >= 1, got {self.patience}"
            )
        if self.min_history < 0:
            raise ValueError(
                f"detector min_history must be >= 0, got {self.min_history}"
            )
        if self.zmax <= 0:
            raise ValueError(f"detector zmax must be > 0, got {self.zmax}")


@dataclasses.dataclass(frozen=True)
class DetectorState:
    """The detector's carry — a handful of scalars folded once per step."""

    n: int = 0
    mean: float = 0.0  # loss EMA baseline
    var: float = 0.0  # loss EMA variance (frozen while hot — see update)
    hot: int = 0  # consecutive steps with z > zmax
    skip_ema: float = 0.0  # guard skip-rate EMA
    gn_ref: float = 0.0  # gradient-norm EMA baseline
    gn_hot: int = 0  # consecutive steps with norm > grad_ratio * gn_ref


def detector_update(
    cfg: DetectorConfig,
    st: DetectorState,
    loss: float,
    skipped: float = 0.0,
    grad_norm: Optional[float] = None,
) -> tuple[DetectorState, Optional[str]]:
    """One detector step: fold ``(loss, skipped[, grad_norm])`` into the
    carry, return ``(new_state, alarm_reason | None)``.

    A pure sequential fold — feeding a loss series step by step, or in
    ``(K,)`` superstep blocks of ANY partition, produces identical states
    and identical alarm decisions (tested). While the z-score is hot the
    loss baseline is FROZEN: absorbing diverging losses into the EMA would
    raise the mean until z drops back under ``zmax`` and the alarm never
    fires. Guard-skipped steps update only the skip-rate (their loss
    describes an update that was rejected, and their gradient norm is the
    rejected outlier's — folding either into a baseline would desensitize
    its alarm); a non-finite loss on an UN-skipped step alarms immediately
    — the guard should have caught it, so the trajectory itself is
    already poisoned.
    """
    loss = float(loss)
    alpha = 2.0 / (cfg.window + 1.0)
    armed = st.n >= cfg.min_history
    skip = 1.0 if skipped and float(skipped) > 0 else 0.0
    skip_ema = st.skip_ema + alpha * (skip - st.skip_ema)
    mean, var, hot = st.mean, st.var, st.hot
    gn_ref, gn_hot = st.gn_ref, st.gn_hot
    alarm = None

    if not math.isfinite(loss):
        if skip < 0.5:
            alarm = "nonfinite_loss"
    elif skip < 0.5:
        if st.n == 0 or (mean == 0.0 and var == 0.0 and st.hot == 0):
            mean, var, hot = loss, 0.0, 0
        else:
            diff = loss - mean
            sd = math.sqrt(var) if var > 0 else 0.0
            z = diff / sd if sd > 0 else 0.0
            if armed and sd > 0 and z > cfg.zmax:
                hot += 1  # baseline frozen while hot
            else:
                hot = 0
                mean += alpha * diff
                var = (1.0 - alpha) * (var + alpha * diff * diff)

    if alarm is None and hot >= cfg.patience:
        alarm = "loss_zscore"
    if alarm is None and armed and skip_ema > cfg.skip_max:
        alarm = "skip_rate"

    if grad_norm is not None:
        g = float(grad_norm)
        # skip-gated like the loss path: a guard-REJECTED gradient's norm
        # (e.g. a screened explosion) must not enter the gn_ref baseline,
        # or one rejected outlier desensitizes the trend alarm for good
        if math.isfinite(g) and g > 0 and skip < 0.5:
            if armed and gn_ref > 0 and g > cfg.grad_ratio * gn_ref:
                gn_hot += 1  # baseline frozen while trending
            else:
                gn_hot = 0
                gn_ref = g if gn_ref <= 0 else gn_ref + alpha * (g - gn_ref)
    if alarm is None and gn_hot >= cfg.patience:
        alarm = "grad_norm_trend"

    return (
        DetectorState(
            n=st.n + 1,
            mean=mean,
            var=var,
            hot=hot,
            skip_ema=skip_ema,
            gn_ref=gn_ref,
            gn_hot=gn_hot,
        ),
        alarm,
    )


def detector_scan(
    cfg: DetectorConfig,
    st: DetectorState,
    losses,
    skipped=None,
    grad_norms=None,
    first_step: int = 1,
) -> tuple[DetectorState, Optional[int], Optional[str]]:
    """Fold a per-step series (a superstep block's ``(K,)`` metrics, or a
    single step's scalars as length-1 sequences) through the detector.
    Stops at the FIRST alarm — the caller rolls back from there, so later
    entries of the block describe a timeline about to be discarded.
    Returns ``(state, alarm_step | None, reason | None)``."""
    losses = [float(x) for x in _as_seq(losses)]
    skips = (
        [0.0] * len(losses) if skipped is None
        else [float(x) for x in _as_seq(skipped)]
    )
    gns = (
        [None] * len(losses) if grad_norms is None
        else [float(x) for x in _as_seq(grad_norms)]
    )
    for i, (loss, sk, gn) in enumerate(zip(losses, skips, gns)):
        st, alarm = detector_update(cfg, st, loss, sk, gn)
        if alarm is not None:
            return st, first_step + i, alarm
    return st, None, None


def _as_seq(x):
    import numpy as np

    return np.asarray(x).reshape(-1)


# ---------------------------------------------------------------------------
# Step-time drift detection (escalation rung 0.5: performance, not health)
# ---------------------------------------------------------------------------
#
# The loss detector above watches the TRAJECTORY; this one watches the
# THROUGHPUT series beside it — per-step wall seconds. Sustained step-time
# drift (a contended host, a degraded link, a changed load profile) does
# not poison the math, so the response is the gentlest rung on the ladder:
# re-probe the performance config at the next checkpoint boundary
# (tuning.autopilot.OnlineRetuner) instead of rolling anything back. Same
# design rules as DetectorConfig: a pure sequential fold, an EMA baseline
# FROZEN while the signal is hot (absorbing a drifting series into its own
# baseline would chase the drift and never alarm), and a patience count so
# one slow step (a GC pause, an eval) is noise, not an incident.


@dataclasses.dataclass(frozen=True)
class DriftConfig:
    """Step-time drift knobs.

    window: EMA span (observations) for the step-time baseline.
    ratio: alarm threshold — an observation counts as drifting when it
        exceeds ``ratio`` x the frozen baseline.
    patience: consecutive drifting observations before the alarm fires.
    min_history: warmup observations before the alarm arms (the first
        steps after a (re)compile are not a baseline).
    """

    window: int = 32
    ratio: float = 1.5
    patience: int = 8
    min_history: int = 8

    def __post_init__(self):
        if self.window < 2:
            raise ValueError(
                f"drift window must be >= 2, got {self.window}"
            )
        if not self.ratio > 1.0:
            raise ValueError(
                f"drift ratio must be > 1, got {self.ratio} (a ratio <= 1 "
                "would alarm on the baseline itself)"
            )
        if self.patience < 1:
            raise ValueError(
                f"drift patience must be >= 1, got {self.patience}"
            )
        if self.min_history < 0:
            raise ValueError(
                f"drift min_history must be >= 0, got {self.min_history}"
            )


@dataclasses.dataclass(frozen=True)
class DriftState:
    """The drift detector's carry — folded once per observation."""

    n: int = 0
    mean: float = 0.0  # step-time EMA baseline (frozen while hot)
    hot: int = 0  # consecutive observations above ratio * mean


# downward EMA coefficient: the baseline tracks the step-time FLOOR, so
# speedups are adopted fast (a compile-inflated first observation decays
# within ~10 normal steps instead of ~window*ln(inflation) of them —
# during that decay a genuine slowdown could not clear ratio*mean and
# real drift would be silently absorbed) while slowdowns stay on the
# slow window EMA + hot-counting path that defines drift
_DRIFT_DOWN_ALPHA = 0.5


def drift_update(
    cfg: DriftConfig, st: DriftState, dt: float
) -> tuple[DriftState, Optional[str]]:
    """Fold one per-step wall time into the carry; returns
    ``(new_state, "step_time_drift" | None)``. Non-finite or non-positive
    observations are ignored (the count still advances — a gap is not a
    baseline sample). The baseline is asymmetric by design: observations
    BELOW it adapt at :data:`_DRIFT_DOWN_ALPHA` (the floor follows
    speedups and sheds compile-inflated seeds quickly), observations
    above it move the slow window EMA or, past ``ratio`` x, freeze it
    and count toward the alarm. A pure fold: feeding the same series one
    value at a time or in blocks of any partition produces identical
    states and identical alarm decisions (the superstep block loops rely
    on this)."""
    dt = float(dt)
    alpha = 2.0 / (cfg.window + 1.0)
    armed = st.n >= cfg.min_history
    mean, hot = st.mean, st.hot
    alarm = None
    if math.isfinite(dt) and dt > 0:
        if mean <= 0.0:
            mean, hot = dt, 0
        elif armed and dt > cfg.ratio * mean:
            hot += 1  # baseline frozen while hot (see module note)
        else:
            hot = 0
            mean += (
                alpha if dt >= mean else _DRIFT_DOWN_ALPHA
            ) * (dt - mean)
        if hot >= cfg.patience:
            alarm = "step_time_drift"
            hot = 0  # one alarm per sustained excursion; the retuner
            # resets the whole state after acting on it
    return DriftState(n=st.n + 1, mean=mean, hot=hot), alarm


def drift_scan(
    cfg: DriftConfig, st: DriftState, dts
) -> tuple[DriftState, Optional[str]]:
    """Fold a block of per-step wall times (the superstep loops observe
    once per block: the block wall divided into K equal per-step shares).
    Unlike detector_scan there is nothing to roll back, so the fold always
    consumes the whole block; the FIRST alarm in it is returned."""
    alarm = None
    for dt in _as_seq(dts):
        st, a = drift_update(cfg, st, dt)
        if a is not None and alarm is None:
            alarm = a
    return st, alarm


class DivergenceError(RuntimeError):
    """The in-process rollback budget is exhausted: the run keeps
    diverging after ``max_rollbacks`` rollback+remedy attempts. Callers
    (the CLI) translate this into :data:`ROLLBACK_EXIT_CODE` so a
    supervisor can prune to the last healthy checkpoint and restart —
    or give up against ITS budget."""

    def __init__(self, step: int, reason: str, rollbacks: int):
        super().__init__(
            f"divergence at step {step} ({reason}) after {rollbacks} "
            "rollback(s); in-process budget exhausted"
        )
        self.step = step
        self.reason = reason
        self.rollbacks = rollbacks


@dataclasses.dataclass(frozen=True)
class RemedyConfig:
    """The ``rewarm`` remedy, baked into the rebuilt step program: the
    effective LR ramps from ``floor`` back to 1.0 over ``window`` steps
    after ``start_step`` (implemented as an in-graph gradient pre-scale —
    scaling an unbiased gradient estimate keeps it unbiased, and the ramp
    is a function of the carried step counter, so superstep block
    partitions see identical arithmetic)."""

    start_step: int
    window: int
    floor: float = 0.1


def remedy_scale(remedy: RemedyConfig, step):
    """Traced ramp factor in [floor, 1] for the step counter ``step``."""
    import jax.numpy as jnp

    t = jnp.clip(
        (jnp.asarray(step, jnp.float32) - jnp.float32(remedy.start_step))
        / jnp.float32(max(remedy.window, 1)),
        0.0,
        1.0,
    )
    floor = jnp.float32(remedy.floor)
    return floor + (jnp.float32(1.0) - floor) * t


def apply_remedy(remedy: RemedyConfig, step, grads):
    """Pre-scale the aggregated gradient tree by the rewarm ramp — ONE
    definition shared by the single-host, blocking-distributed, and
    delayed-overlap update paths, so which step counter drives the ramp is
    decided exactly once per call site and the arithmetic cannot drift."""
    import jax

    scale = remedy_scale(remedy, step)
    return jax.tree_util.tree_map(
        lambda g: g * scale.astype(g.dtype), grads
    )


def global_sq_norm(grads):
    """Traced f32 sum of squares over every leaf — the raw global-L2
    signal (pre-screen, pre-codec) the divergence detector's grad-norm
    trend counter folds. ONE definition for the single-host and
    distributed ``track_grad_norm`` metrics so the two series cannot
    disagree about the same gradient. (:func:`grad_ok` keeps its own
    interleaved finiteness+norm leaf pass — it predates this helper and
    its traced op ORDER is pinned by the frozen guarded-program
    contracts; the arithmetic is the same.)"""
    import jax
    import jax.numpy as jnp

    sq = jnp.float32(0.0)
    for leaf in jax.tree_util.tree_leaves(grads):
        lf = leaf.astype(jnp.float32)
        sq += jnp.sum(lf * lf)
    return sq


@dataclasses.dataclass(frozen=True)
class DivergeConfig:
    """``--on-diverge`` settings: which remedy, the detector, and the
    in-process rollback budget."""

    remedy: str = "skip"  # skip | rewarm | densify
    detector: DetectorConfig = dataclasses.field(
        default_factory=DetectorConfig
    )
    max_rollbacks: int = 2
    rewarm_floor: float = 0.1

    def __post_init__(self):
        if self.remedy not in ("skip", "rewarm", "densify"):
            raise ValueError(
                f"unknown --on-diverge remedy {self.remedy!r}; expected "
                "skip | rewarm | densify"
            )


def diverge_conflict(
    remedy,
    *,
    train_dir,
    codec=None,
    aggregate=None,
    overlap=None,
    zero1=False,
    phase_metrics=False,
    num_aggregate=None,
    keep_ckpts=None,
    save_freq=None,
    window=None,
):
    """The ``--on-diverge`` compatibility matrix, stated once.

    Returns the human-readable reason the combination cannot work, or
    None when it can. Every surface that arms the doctor (the CLI and
    both train loops) asks here and raises its own error type with the
    returned message; a surface passes only the features it actually
    has — omitted ones are treated as off.
    """
    if not train_dir:
        return (
            "diverge (--on-diverge) needs a train_dir: rollback "
            "restores from checkpoints"
        )
    if save_freq is not None and not save_freq:
        # save_freq None = the caller has no cadence concept (unit tests);
        # 0 = checkpointing explicitly disabled — no save can ever earn a
        # healthy tag, so every rollback would replay from step 0
        return (
            "--on-diverge needs a checkpoint cadence (--save-freq or "
            "--eval-freq > 0): with saves disabled no checkpoint can earn "
            "a healthy tag and every rollback would restart from scratch"
        )
    if keep_ckpts and save_freq and window and keep_ckpts * save_freq < window:
        # a checkpoint earns the healthy tag only once the detector window
        # clears past it (~window steps after the save), but keep-last-K
        # retention deletes it keep_ckpts*save_freq steps after the save:
        # with keep*freq < window NO checkpoint ever survives to be tagged,
        # so the first alarm would roll back to step 0 and prune everything
        return (
            f"--on-diverge with --keep-ckpts {keep_ckpts} and --save-freq "
            f"{save_freq} retains checkpoints for only "
            f"{keep_ckpts * save_freq} steps — shorter than the "
            f"--diverge-window of {window}, so none would live long enough "
            "to earn the healthy tag a rollback needs; raise --keep-ckpts "
            "(or drop it to keep all checkpoints)"
        )
    if zero1:
        return (
            "--on-diverge is not supported with --zero1 (the sharded "
            "optimizer template cannot be rebuilt mid-run); drop one"
        )
    if phase_metrics:
        return (
            "--on-diverge needs the fused step's metric series; "
            "--phase-metrics has no doctor wiring — drop one"
            + PHASE_METRICS_HINT
        )
    if remedy == "densify":
        if codec is None:
            return (
                "--on-diverge densify needs a compressing --code — "
                "dense training has nothing denser to de-escalate to"
            )
        if overlap == "delayed":
            return (
                "--on-diverge densify cannot compose with --overlap "
                "delayed (the dense fallback has no delayed form); "
                "use skip or rewarm"
            )
        if aggregate == "hierarchical":
            return (
                "--on-diverge densify cannot compose with --aggregate "
                "hierarchical (the dense fallback aggregates with a flat "
                "psum; every two-level topology plan — the legacy "
                "psum+gather schedule and the re-encoded plans alike — "
                "needs a codec to compress at least one tier); use skip "
                "or rewarm"
            )
        if num_aggregate:
            return (
                "--on-diverge densify cannot compose with "
                "--num-aggregate (a dense psum cannot subset "
                "replicas); use skip or rewarm"
            )
    return None


@dataclasses.dataclass(frozen=True)
class RollbackPlan:
    """What the loop must do about an alarm: reload ``target``, replay the
    data stream to it, and rebuild the step program at ``generation``
    (chaos disarmed) with the remedy applied."""

    target: int
    remedy: str
    window: int
    generation: int
    reason: str
    alarm_step: int


class DivergenceDoctor:
    """Host-side controller tying detection to recovery: folds the
    per-step metric series through the detector, grants healthy tags to
    checkpoints the window has cleared, and turns alarms into
    :class:`RollbackPlan`s against the in-process budget.

    The doctor is loop-agnostic — the four train loops (single-host and
    distributed, per-step and superstep) share one instance's policy and
    incident log; only the state reload/stream rebuild is loop-specific.
    """

    def __init__(
        self,
        cfg: DivergeConfig,
        train_dir: Optional[str],
        incidents=None,
        log_fn=print,
    ):
        self.cfg = cfg
        self.train_dir = train_dir
        self.incidents = incidents
        self.log_fn = log_fn
        self.state = DetectorState()
        self.pending: list[int] = []  # saved steps awaiting the healthy tag
        self.rollbacks = 0
        self.generation = 0

    # -- observation ----------------------------------------------------

    def note_save(self, step: int) -> None:
        """A checkpoint landed at ``step``; it earns the healthy tag only
        after the detector window clears past it without an alarm."""
        if step not in self.pending:
            self.pending.append(step)

    def observe_block(
        self, first_step: int, losses, skipped=None, grad_norms=None
    ) -> tuple[Optional[int], Optional[str]]:
        """Fold the per-step series for steps ``first_step..`` (a superstep
        block or a single step) into the detector; confirm pending healthy
        tags for checkpoints the window has cleared. Returns
        ``(alarm_step, reason)`` or ``(None, None)``."""
        losses = _as_seq(losses)
        self._confirm_through(first_step - 1)
        self.state, alarm_step, reason = detector_scan(
            self.cfg.detector, self.state, losses, skipped, grad_norms,
            first_step=first_step,
        )
        if reason is None:
            self._confirm_through(first_step + len(losses) - 1)
        else:
            # the steps BEFORE the alarm were observed alarm-free, and the
            # K=1 trajectory confirms them before its alarm call's scan —
            # confirm through alarm_step-1 so a save whose window cleared
            # pre-alarm stays a rollback target under ANY block partition
            self._confirm_through(alarm_step - 1)
        return alarm_step, reason

    def _confirm_through(self, step: int) -> None:
        """Grant healthy tags to pending saves whose window [save,
        save+window] finished strictly before or at ``step`` alarm-free.
        A pending save whose file retention already pruned is dropped
        untagged — marking it would leave an orphaned sidecar that a
        FUTURE checkpoint reusing the step number (a post-rollback
        timeline) would inherit without earning."""
        if not self.pending:
            return
        from atomo_tpu.training.checkpoint import (
            checkpoint_path,
            mark_healthy,
        )

        w = self.cfg.detector.window
        still = []
        for s in sorted(self.pending):
            if s + w <= step:
                if self.train_dir and os.path.exists(
                    checkpoint_path(self.train_dir, s)
                ):
                    mark_healthy(self.train_dir, s)
            else:
                still.append(s)
        self.pending = still

    # -- recovery -------------------------------------------------------

    def plan_rollback(self, alarm_step: int, reason: str) -> RollbackPlan:
        """Turn an alarm into a rollback plan (or raise
        :class:`DivergenceError` once the budget is spent). Prunes the
        diverged timeline above the target so no resume path can land on
        it, resets the detector, and bumps the chaos generation."""
        from atomo_tpu.training.checkpoint import (
            latest_healthy_step,
            prune_after,
        )

        if self.rollbacks >= self.cfg.max_rollbacks:
            pruned: list[int] = []
            if self.train_dir:
                # make the same cut a supervisor would on rc=23: without
                # it an unsupervised run's later --resume lands on the
                # diverged tail written during this final excursion
                pruned = prune_after(
                    self.train_dir, latest_healthy_step(self.train_dir) or 0
                )
            if self.incidents is not None:
                self.incidents.append(
                    "divergence",
                    action="give_up",
                    step=alarm_step,
                    reason=reason,
                    rollbacks=self.rollbacks,
                    pruned=pruned,
                )
            raise DivergenceError(alarm_step, reason, self.rollbacks)
        self.rollbacks += 1
        target = None
        removed: list[int] = []
        if self.train_dir:
            target = latest_healthy_step(self.train_dir)
            removed = prune_after(self.train_dir, target or 0)
        target = int(target) if target is not None else 0
        self.generation += 1
        self.state = DetectorState()
        self.pending = [s for s in self.pending if s <= target]
        plan = RollbackPlan(
            target=target,
            remedy=self.cfg.remedy,
            window=self.cfg.detector.window,
            generation=self.generation,
            reason=reason,
            alarm_step=alarm_step,
        )
        self.log_fn(
            f"Doctor: divergence at step {alarm_step} ({reason}); rolling "
            f"back to step {target} with remedy {plan.remedy!r} "
            f"(rollback {self.rollbacks}/{self.cfg.max_rollbacks}"
            + (f", pruned steps {removed}" if removed else "")
            + ")"
        )
        if self.incidents is not None:
            self.incidents.append(
                "divergence",
                action=f"rollback+{plan.remedy}",
                step=alarm_step,
                target=target,
                reason=reason,
                pruned=removed,
                rollbacks=self.rollbacks,
            )
        return plan


class RecoveryRig:
    """The loop-facing half of the rollback engine: binds a
    :class:`DivergenceDoctor` to one train loop's reload / replay /
    step-rebuild closures, so the four loops (single-host and distributed,
    per-step and superstep) share the recovery sequence verbatim.

    ``reload_state(target)`` must return the loop's state restored from
    the step-``target`` checkpoint (target 0 = fresh init — no healthy
    checkpoint survived); ``restream(target)`` must return a data stream
    replayed past ``target`` batches from the run-start RNG snapshot;
    ``build_step(generation, remedy_cfg, densify)`` must return the loop's
    step callable with chaos at ``generation``, the optional rewarm ramp,
    and (densify) the codec swapped out for dense aggregation.
    """

    def __init__(self, doctor, diverge, reload_state, restream, build_step):
        self.doctor = doctor
        self.diverge = diverge
        self._reload = reload_state
        self._restream = restream
        self._build = build_step
        self.densify_until: Optional[int] = None
        self.remedy_until: Optional[int] = None  # rewarm ramp end step

    def observe(self, first_step, metrics):
        """Feed a fetched metrics dict (per-step scalars or (K,) block
        series) to the detector; returns (alarm_step, reason).

        ``sample_skipped`` (delayed-overlap programs) wins over
        ``skipped``: in that mode "skipped" describes the CONSUMED
        step-(t-1) payload while the loss describes this step's forward,
        so gating on it would be off by one — folding a forward whose
        every chip the guard rejected (loss collapsed to 0.0) as a clean
        sample."""
        return self.doctor.observe_block(
            first_step,
            metrics["loss"],
            metrics.get("sample_skipped", metrics.get("skipped")),
            metrics.get("grad_norm"),
        )

    def note_save(self, step):
        self.doctor.note_save(step)

    def rollback(self, alarm_step, reason):
        """Execute the doctor's plan; returns (plan, state, stream,
        step_fn) for the loop to adopt. Raises DivergenceError when the
        in-process budget is spent."""
        plan = self.doctor.plan_rollback(alarm_step, reason)
        remedy_cfg = (
            RemedyConfig(
                start_step=plan.target,
                window=plan.window,
                floor=self.diverge.rewarm_floor,
            )
            if plan.remedy == "rewarm"
            else None
        )
        densify = plan.remedy == "densify"
        self.densify_until = (
            plan.target + plan.window if densify else None
        )
        self.remedy_until = (
            plan.target + plan.window if plan.remedy == "rewarm" else None
        )
        state = self._reload(plan.target)
        stream = self._restream(plan.target)
        step_fn = self._build(plan.generation, remedy_cfg, densify)
        return plan, state, stream, step_fn

    def recover(self, alarm_step, reason, chaos):
        """The whole recovery sequence the four loops share: execute the
        rollback, advance the loop's OWN chaos injector to the plan's
        generation (host-side faults — kill/slow/ckpt corruption — must
        disarm with the step program, or they re-fire on the replayed
        range), and fetch the restored step counter the loop's cadence
        counters clamp to. Feed/profiler teardown stays at the call site —
        it is the only part that differs per loop. Returns
        ``(state, stream, step_fn, chaos, step)``; raises DivergenceError
        when the in-process budget is spent."""
        import jax

        plan, state, stream, step_fn = self.rollback(alarm_step, reason)
        if chaos is not None:
            chaos = chaos.with_generation(plan.generation)
        step = int(jax.device_get(state.step))
        return state, stream, step_fn, chaos, step

    def maybe_end_densify(self, step):
        """After the densify window closes, rebuild the real-codec step
        (snapped to the first step/block boundary past the window);
        returns the new step_fn or None."""
        if self.densify_until is not None and step >= self.densify_until:
            self.densify_until = None
            return self._build(self.doctor.generation, None, False)
        return None

    def remedy_active(self, step) -> bool:
        """True while a rollback remedy still shapes the step program:
        the densify window is open, or the rewarm ramp has not yet
        saturated (past ``target + window`` the ramp computes exactly
        1.0, so a program rebuilt WITHOUT it is arithmetically
        identical). The online re-tuner defers its aggregate-switch
        rebuild past this window — a default ``build_step()`` rebuild
        mid-treatment would silently drop the doctor's remedy."""
        if self.densify_until is not None and step < self.densify_until:
            return True
        return self.remedy_until is not None and step < self.remedy_until


def grad_ok(grads, max_grad_norm: float = 0.0):
    """Traced bool scalar: True iff every leaf is finite (and the global L2
    norm is within ``max_grad_norm`` when > 0). An overflowing
    sum-of-squares is itself non-finite, so the norm screen also catches
    exploding gradients whose square overflows f32."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(grads)
    ok = jnp.bool_(True)
    sq = jnp.float32(0.0)
    for leaf in leaves:
        lf = leaf.astype(jnp.float32)
        ok &= jnp.all(jnp.isfinite(lf))
        sq += jnp.sum(lf * lf)
    if max_grad_norm and max_grad_norm > 0:
        ok &= sq <= jnp.float32(max_grad_norm) ** 2
    return ok


def select_state(ok, new_tree, old_tree):
    """Per-leaf ``where(ok, new, old)`` — the skip: holding params, opt
    state and BN stats at their pre-step values when ``ok`` is False."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), new_tree, old_tree
    )


def zero_if(bad, tree):
    """Zero every leaf when ``bad`` — keeps non-finite values out of the
    optimizer update (whose arithmetic would propagate NaN into the
    momentum buffers even if the result is later discarded)."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda g: jnp.where(bad, jnp.zeros((), g.dtype), g), tree
    )


def resolve_chaos(chaos):
    """Default the fault injector from the ATOMO_CHAOS env when the caller
    passed none — the flagless path subprocess drills use. One definition
    for both train loops."""
    from atomo_tpu.utils.chaos import ChaosInjector

    return ChaosInjector.from_env() if chaos is None else chaos


@contextlib.contextmanager
def heartbeat_watchdog(health_timeout: float, on_failure=None):
    """Arm the step-heartbeat watchdog around a train loop body (no-op at
    timeout 0). Yields the HealthMonitor to ``beat()`` — or None — and
    guarantees the watchdog thread stops on the way out. One definition
    for both train loops, so arming/stop semantics cannot drift."""
    from atomo_tpu.parallel.launch import HealthMonitor, HealthWatchdog

    monitor = watchdog = None
    if health_timeout > 0:
        monitor = HealthMonitor(timeout=health_timeout)
        watchdog = HealthWatchdog(
            monitor,
            interval=min(health_timeout / 4, 10.0),
            on_failure=on_failure,
        ).start()
    try:
        yield monitor
    finally:
        if watchdog is not None:
            watchdog.stop()


def retrying_saver(log_fn=print, incidents=None):
    """save_checkpoint wrapped in the standard bounded backoff — the one
    saver both train loops (single-host and distributed) use, so retry
    policy and logging cannot drift between them. With ``incidents`` (an
    IncidentLog), each retried save lands in the post-mortem record."""
    from atomo_tpu.training.checkpoint import save_checkpoint

    return with_retries(
        save_checkpoint,
        on_retry=lambda i, exc: log_fn(
            f"Checkpoint save failed (attempt {i}): {exc}; retrying"
        ),
        incidents=incidents,
        incident_cause="checkpoint_save",
    )


def masked_mean(tree, ok, kept, axis):
    """Skip-and-rescale, psum form: zero this replica's contribution when
    ``ok`` is False, sum over ``axis``, divide by the surviving count
    (floored at 1 so the zero-survivor step stays finite; the caller's
    select_state discards it anyway)."""
    import jax
    import jax.numpy as jnp

    summed = jax.lax.psum(zero_if(~ok, tree), axis)
    return jax.tree_util.tree_map(
        lambda s: s / jnp.maximum(kept, 1.0).astype(s.dtype), summed
    )


def rescale_by_survivors(tree, n_contrib, kept):
    """Skip-and-rescale, gather form: a mean taken over all ``n_contrib``
    slots (anomalous ones masked to zero) re-scaled by n/kept so it equals
    the mean over survivors alone."""
    import jax
    import jax.numpy as jnp

    scale = n_contrib / jnp.maximum(kept, 1.0)
    return jax.tree_util.tree_map(
        lambda g: g * scale.astype(g.dtype), tree
    )


def decorrelated_delay(
    prev: float, base: float, cap: float, rng: random.Random
) -> tuple[float, float]:
    """One decorrelated-jitter backoff step: ``delay = min(cap,
    uniform(base, 3*prev))``. Returns ``(delay, next_prev)`` — the floor
    at ``base`` keeps the envelope from collapsing. The ONE backoff
    formula for both the retry path (:func:`with_retries`) and the
    supervisor (:func:`run_supervised`); hosts tripping over the same
    fleet-wide blip must not re-synchronize into a retry storm."""
    delay = min(cap, rng.uniform(base, prev * 3))
    return delay, max(delay, base)


def with_retries(
    fn: Callable,
    *,
    attempts: int = 3,
    base_delay: float = 0.1,
    max_delay: float = 5.0,
    exceptions: Sequence[type] = (OSError,),
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    jitter: bool = True,
    rng: Optional[random.Random] = None,
    incidents=None,
    incident_cause: str = "retry",
) -> Callable:
    """Wrap a fallible host-side op with bounded, jittered backoff.

    Returns a callable with ``fn``'s signature that retries on the listed
    exception types and re-raises the last failure once ``attempts`` are
    exhausted. Anything not in ``exceptions`` propagates immediately —
    retrying a programming error just hides it.

    Backoff is DECORRELATED JITTER (delay_i = uniform(base, 3 * delay_{i-1})
    capped at ``max_delay``): the old deterministic base * 2**i schedule
    made every host that tripped over the same NFS blip retry at the same
    instant, turning one transient into a synchronized retry storm.
    ``jitter=False`` restores the deterministic schedule (tests); ``rng``
    injects a seeded random.Random. With ``incidents`` (an IncidentLog),
    each retry's cause is recorded under ``incident_cause``.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    exc_types = tuple(exceptions)
    rng = rng if rng is not None else random.Random()

    def wrapped(*args, **kwargs):
        prev = base_delay
        for i in range(attempts):
            try:
                return fn(*args, **kwargs)
            except exc_types as exc:
                if i + 1 >= attempts:
                    raise
                if on_retry is not None:
                    on_retry(i + 1, exc)
                if incidents is not None:
                    incidents.append(
                        incident_cause,
                        action="retry",
                        attempt=i + 1,
                        op=getattr(fn, "__name__", str(fn)),
                        error=f"{type(exc).__name__}: {exc}",
                    )
                if jitter:
                    delay, prev = decorrelated_delay(
                        prev, base_delay, max_delay, rng
                    )
                else:
                    delay = min(base_delay * (2 ** i), max_delay)
                sleep(delay)

    return wrapped


# ---------------------------------------------------------------------------
# Run-level supervision (escalation rung 4)
# ---------------------------------------------------------------------------


def run_supervised(
    cmd: Sequence[str],
    *,
    max_restarts: int = 2,
    backoff_base: float = 1.0,
    backoff_max: float = 30.0,
    train_dir: Optional[str] = None,
    resume_flag: Optional[str] = "--resume",
    log_fn=print,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
    env: Optional[dict] = None,
) -> int:
    """Supervise a train command with a crash-loop budget.

    Runs ``cmd`` as a child process (with :data:`SUPERVISED_ENV` set so the
    child never re-supervises itself, and :data:`ATTEMPT_ENV` carrying the
    0-based run attempt for attempt-keyed chaos). Exit codes are triaged:

      0                    clean exit — done.
      ROLLBACK_EXIT_CODE   rollback requested (the child's in-process
                           rollback budget is spent): the supervisor cuts
                           the checkpoint timeline back to the newest
                           HEALTHY step (prune_after) so the restart's
                           ``--resume`` cannot land on diverged weights,
                           then restarts against the budget.
      CONFIG_EXIT_CODE     deterministic config error (argparse usage
                           errors and the CLI's in-run rejects that need
                           the resolved mesh/codec): give up immediately —
                           every restart would die identically.
      MEMBERSHIP_EXIT_CODE elastic membership boundary: the child recorded
                           the next epoch in train_dir/membership.json; the
                           supervisor rewrites ``--n-devices`` to the new
                           world size (elastic.apply_world_to_argv), hands
                           the epoch id to children via
                           ATOMO_MEMBERSHIP_EPOCH, and re-execs WITHOUT
                           charging the restart budget — a planned reshape
                           is not a crash. A membership exit whose plan is
                           missing or not newer than the last adopted one
                           is triaged as a crash (the runaway-reshape
                           guard).
      anything else        crash — restart against the budget.

    Crash/rollback restarts append ``resume_flag`` to the command (once),
    wait a decorrelated-jittered backoff (base ``backoff_base`` s, capped
    at ``backoff_max`` s), and burn one unit of the ``max_restarts``
    budget; exhaustion returns the child's last exit code. Membership
    re-execs resume immediately, budget untouched. Every decision is one
    record in ``train_dir/incidents.jsonl``.
    """
    import subprocess

    from atomo_tpu.utils.tracing import MEMBERSHIP_EPOCH_ENV, IncidentLog

    incidents = (
        IncidentLog.for_train_dir(train_dir) if train_dir else None
    )
    rng = rng if rng is not None else random.Random()
    base_env = dict(os.environ if env is None else env)
    cmd = list(cmd)
    extra_env: dict = {}
    attempt = 0  # every child run, incl. membership re-execs (ATTEMPT_ENV)
    budget_used = 0  # crash/rollback restarts only — the actual budget
    last_epoch: Optional[int] = None
    prev = max(backoff_base, 1e-3)
    while True:
        run_cmd = list(cmd)
        if attempt > 0 and resume_flag and resume_flag not in run_cmd:
            run_cmd.append(resume_flag)
        child_env = {
            **base_env, **extra_env,
            SUPERVISED_ENV: "1", ATTEMPT_ENV: str(attempt),
        }
        t0 = time.time()
        rc = subprocess.call(run_cmd, env=child_env)
        wall = round(time.time() - t0, 3)
        if rc == 0:
            if incidents is not None:
                incidents.append(
                    "clean_exit", action="done", attempt=attempt, run_s=wall
                )
            log_fn(f"Supervisor: clean exit (attempt {attempt})")
            return 0
        if rc == MEMBERSHIP_EXIT_CODE and train_dir:
            plan = None
            try:
                from atomo_tpu.elastic.membership import MembershipLog

                plan = MembershipLog.load(train_dir).latest()
            except Exception:  # noqa: BLE001 — unreadable plan = crash triage
                plan = None
            if plan is not None and (
                last_epoch is None or plan.epoch > last_epoch
            ):
                from atomo_tpu.elastic.membership import apply_world_to_argv

                last_epoch = plan.epoch
                cmd = apply_world_to_argv(cmd, plan.world_size)
                extra_env[MEMBERSHIP_EPOCH_ENV] = str(plan.epoch)
                if incidents is not None:
                    incidents.append(
                        "membership_change",
                        action=f"reshape->{plan.world_size}",
                        attempt=attempt,
                        rc=rc,
                        epoch=plan.epoch,
                        world=plan.world_size,
                        reason=plan.reason,
                        run_s=wall,
                    )
                log_fn(
                    f"Supervisor: membership epoch {plan.epoch} "
                    f"({plan.reason}); re-exec with --n-devices "
                    f"{plan.world_size} (planned reshape — restart "
                    "budget untouched)"
                )
                attempt += 1
                continue
            log_fn(
                f"Supervisor: attempt {attempt} exited rc={rc} "
                "(membership-change) but membership.json holds no newer "
                "epoch; triaging as a crash"
            )
        if rc == CONFIG_EXIT_CODE:
            # deterministic: every restart would die on the same reject
            if incidents is not None:
                incidents.append(
                    "config_error",
                    action="give_up",
                    attempt=attempt,
                    rc=rc,
                    run_s=wall,
                )
            log_fn(
                f"Supervisor: attempt {attempt} exited rc={rc} (config "
                "error — deterministic); not restarting"
            )
            return rc
        cause = "rollback_requested" if rc == ROLLBACK_EXIT_CODE else "crash"
        target = None
        if rc == ROLLBACK_EXIT_CODE and train_dir:
            from atomo_tpu.training.checkpoint import (
                latest_healthy_step,
                prune_after,
            )

            target = latest_healthy_step(train_dir) or 0
            prune_after(train_dir, target)
        if budget_used >= max_restarts:
            if incidents is not None:
                incidents.append(
                    "budget_exhausted",
                    action="give_up",
                    attempt=attempt,
                    rc=rc,
                    run_s=wall,
                    max_restarts=max_restarts,
                )
            log_fn(
                f"Supervisor: budget exhausted after attempt {attempt} "
                f"(rc={rc}, {cause}); giving up"
            )
            return rc
        if train_dir:
            # a LIVE reshape (--elastic-reshard live) advances
            # membership.json WITHOUT an rc=29 exit, so a later crash
            # must not relaunch at the stale world: membership.json is
            # the source of truth for the next attempt's --n-devices
            # regardless of how the epoch advanced. Charged as a normal
            # crash — the reshape already happened in-process.
            try:
                from atomo_tpu.elastic.membership import MembershipLog

                plan = MembershipLog.load(train_dir).latest()
            except Exception:  # noqa: BLE001 — unreadable plan: keep argv
                plan = None
            if plan is not None and (
                last_epoch is None or plan.epoch > last_epoch
            ):
                from atomo_tpu.elastic.membership import (
                    apply_world_to_argv,
                )

                last_epoch = plan.epoch
                new_cmd = apply_world_to_argv(cmd, plan.world_size)
                extra_env[MEMBERSHIP_EPOCH_ENV] = str(plan.epoch)
                if new_cmd != cmd:
                    cmd = new_cmd
                    log_fn(
                        f"Supervisor: membership.json holds epoch "
                        f"{plan.epoch} (world {plan.world_size}, "
                        f"{plan.reason}) — reshaped before the crash; "
                        f"restarting with --n-devices {plan.world_size}"
                    )
        delay, prev = decorrelated_delay(prev, backoff_base, backoff_max, rng)
        delay = round(delay, 3)
        if incidents is not None:
            incidents.append(
                cause,
                action="restart",
                attempt=attempt,
                rc=rc,
                target=target,
                backoff_s=delay,
                run_s=wall,
            )
        log_fn(
            f"Supervisor: attempt {attempt} exited rc={rc} ({cause}); "
            f"restarting in {delay:.2f}s "
            f"({max_restarts - budget_used} restart(s) left)"
        )
        sleep(delay)
        attempt += 1
        budget_used += 1
