"""The controller's decision-space grammar.

One PRICED space over every performance knob the repo grew one decider
at a time: aggregate + overlap + superstep + ring bucket + stream
buckets (the autopilot's axes), the topology plan (two-tier meshes),
the per-leaf rank/bit allocation (the variance budget), the per-layer
sparse-row representation (the hybrid planner), and the quorum/
staleness pair. The GRAMMAR is ``comm_model.candidate_name``'s suffix
algebra — ``<agg>+<overlap>[+se][+sp][+ab][+qK]+k<K>[+b<N>]`` with
``hier[<plan>]`` replacing the flat aggregate on two-tier candidates —
and this module contributes two pure pieces:

  * :func:`joint_candidates` — the CROSS TERMS the single deciders
    never enumerate (``+sp+ab``, ``+ab+se``, ``+ab`` under delayed
    overlap, ``+ab`` under each hierarchical plan, ``+ab+qK``), each
    carrying its own per-leaf ``leaf_budgets`` pricing override where
    the shared ranking inputs cannot express it. They ride the SAME
    ``predict_step_s``-ranked ladder as the enumerated space — one
    ordering decides who gets probed, not four independent winners.
  * :func:`candidate_predicate` — subspace restriction: confining the
    search to one legacy decider's knob axes must reproduce that
    decider's winner bit-identically (the degeneracy tests), which is
    what makes the controller a superset of the old paths rather than
    a fifth opinion.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

DECIDERS = ("autopilot", "budget", "hybrid", "topology")


def normalize_deciders(deciders: Optional[Iterable[str]]) -> frozenset:
    """Validated decider set; ``None`` = the full joint space."""
    if deciders is None:
        return frozenset(DECIDERS)
    out = frozenset(str(d) for d in deciders)
    bad = out - frozenset(DECIDERS)
    if bad:
        raise ValueError(
            f"unknown decider(s) {sorted(bad)}; the decision space is "
            f"composed of {DECIDERS}"
        )
    if not out:
        raise ValueError("the decider set must name at least one axis")
    return out


def candidate_predicate(
    deciders: Iterable[str],
) -> Optional[Callable[[dict], bool]]:
    """The subspace restriction as a candidate predicate (``None`` for
    the full space — no filtering, zero overhead on the default path).

    Excluding a decider removes its knob axis: no ``topology`` drops
    hierarchical candidates, no ``hybrid`` drops ``+sp``, no ``budget``
    drops ``+ab``. Excluding ``autopilot`` freezes ITS axes at the
    degenerate point (blocking, superstep 1, no stream, no quorum,
    gather — or hierarchical-only when topology is the surviving
    decider), which is exactly what the budget-only / hybrid-only /
    topology-only degeneracy tests pin against the standalone solvers.
    """
    d = normalize_deciders(deciders)
    if d == frozenset(DECIDERS):
        return None

    def pred(cand: dict) -> bool:
        if "topology" not in d and cand.get("aggregate") == "hierarchical":
            return False
        if "hybrid" not in d and cand.get("sparse_rows") == "on":
            return False
        if "budget" not in d and cand.get("budget_alloc") == "variance":
            return False
        if "autopilot" not in d:
            if cand.get("overlap", "off") != "off":
                return False
            if int(cand.get("superstep", 1)) != 1:
                return False
            if cand.get("stream_encode") == "on" or cand.get("quorum"):
                return False
            if d == frozenset({"topology"}):
                return cand.get("aggregate") == "hierarchical"
            if cand.get("aggregate") not in ("gather", "hierarchical"):
                return False
        return True

    return pred


def joint_candidates(
    *,
    deciders: Iterable[str],
    allow_ring: bool = True,
    ring_bucket_size: int = 65536,
    have_budget: bool = False,
    have_sparse: bool = False,
    sparse_ab_leaf_budgets=None,
    allow_overlap: bool = True,
    allow_stream: bool = False,
    stream_bucket_bytes: int = 4 << 20,
    stream_buckets: int = 0,
    two_tier: bool = False,
    plan_names=None,
    allow_quorum: bool = False,
    quorum_q: int = 0,
    quorum_staleness_options=(1, 2),
) -> list[dict]:
    """The joint cross-term candidates (module docstring), named through
    the one grammar (``candidate_name``) so the decision artifact reads
    like the enumerated rows. Pure and deterministic — same inputs,
    same list, same order.

    ``sparse_ab_leaf_budgets`` (the hybrid plan RE-PLANNED under the
    budget-wrapped codec, ``HybridPlan.leaf_budgets()``) is required for
    the ``+sp+ab`` cross term: its wire is neither the base hybrid's nor
    the allocation's, so the candidate carries the override
    ``predict_step_s`` prices first. The other ``+ab`` cross terms price
    through the ranking call's ``budget_leaf_budgets`` — the same sums
    the wrapped codec's executed program reports."""
    from atomo_tpu.utils.comm_model import candidate_name

    d = normalize_deciders(deciders)
    have_budget = bool(have_budget and "budget" in d)
    have_sparse = bool(have_sparse and "hybrid" in d)
    out: list[dict] = []
    aggs = ["gather"] + (["ring"] if allow_ring else [])
    for agg in aggs:
        base = {"aggregate": agg, "overlap": "off", "superstep": 1}
        if agg == "ring":
            base["ring_bucket_size"] = int(ring_bucket_size)
        if have_budget and have_sparse and sparse_ab_leaf_budgets:
            out.append({
                **base,
                "sparse_rows": "on",
                "budget_alloc": "variance",
                "leaf_budgets": [
                    (int(a), int(b)) for a, b in sparse_ab_leaf_budgets
                ],
            })
        if have_budget and allow_stream:
            c = {
                **base,
                "stream_encode": "on",
                "stream_bucket_bytes": int(stream_bucket_bytes),
                "budget_alloc": "variance",
            }
            if stream_buckets > 0:
                c["stream_buckets"] = int(stream_buckets)
            out.append(c)
        if have_budget and allow_overlap:
            out.append(
                {**base, "overlap": "delayed", "budget_alloc": "variance"}
            )
        if (
            have_budget
            and allow_quorum
            and int(quorum_q) >= 1
            and "autopilot" in d
        ):
            for st in sorted(
                {max(int(s), 1) for s in quorum_staleness_options}
            ):
                out.append({
                    **base,
                    "quorum": int(quorum_q),
                    "staleness": st,
                    "budget_alloc": "variance",
                })
    if have_budget and two_tier and "topology" in d:
        from atomo_tpu.topology.schedule import PLAN_NAMES

        for pname in PLAN_NAMES if plan_names is None else tuple(plan_names):
            out.append({
                "aggregate": "hierarchical",
                "plan": pname,
                "overlap": "off",
                "superstep": 1,
                "budget_alloc": "variance",
            })
    for c in out:
        c["name"] = candidate_name(c)
    return out


# ---------------------------------------------------------------------------
# Model-axis LM candidates: the lm[...] corner of the joint space
# ---------------------------------------------------------------------------

#: Codec compositions PROVEN on the model-axis dp exchange (bit-parity /
#: bit-identical-payload tests, tests/test_model_axes.py) vs rejected
#: with honest reasons. A knob absent from both maps composes freely.
MODEL_AXIS_REJECTS = {
    "hierarchical": (
        "the model axes (tp/pp/ep/sp) own the second mesh dimension — "
        "there is no free inner data axis for a two-level schedule to "
        "reduce over"
    ),
    "sparse_rows": (
        "the hybrid sparse-row planner is unproven on the LM param "
        "trees (its row heuristics were fit to conv kernels); honest "
        "reject until a parity test lands"
    ),
    "quorum": (
        "the model-axis steps now carry the delayed rig (ok-flags, "
        "staleness carry — parallel.lm.make_delayed_model_axis_step), "
        "but the arrival-schedule rig (per-replica delay injection + "
        "quorum wait) is not threaded through build_model_axis_program; "
        "honest reject until it is"
    ),
}


def model_axis_conflicts(cand: dict) -> Optional[str]:
    """The honest-reject reason a knob vector cannot run on a model-axis
    LM layout, or None when the composition is PROVEN (gather/psum/ring,
    stream-encode, variance budget — the tested degenerate points).

    This is the ISSUE's "conflict rejects lifted one by one" surface:
    every lift deletes an entry from :data:`MODEL_AXIS_REJECTS` and adds
    a parity test; every remaining entry names why, so a reject is a
    statement, not a silent filter."""
    if cand.get("aggregate") == "hierarchical":
        return MODEL_AXIS_REJECTS["hierarchical"]
    if cand.get("sparse_rows") == "on":
        return MODEL_AXIS_REJECTS["sparse_rows"]
    if cand.get("quorum"):
        return MODEL_AXIS_REJECTS["quorum"]
    if cand.get("overlap", "off") == "delayed":
        # delayed itself is PROVEN (stale-by-one carry threaded through
        # every model-axis family, tests/test_model_axes.py) — but it
        # carries an ENCODED payload, so the dense psum exchange has
        # nothing to carry, and without a codec there is no payload
        if cand.get("aggregate") == "psum" or not cand.get("codec"):
            return (
                "delayed overlap carries an ENCODED payload between "
                "steps; a dense exchange (psum / no codec) has no "
                "payload to carry — use a codec with gather or ring"
            )
    return None


def lm_axis_candidates(
    *,
    model_axes: dict,
    codec_tag: str = "",
    allow_ring: bool = True,
    ring_bucket_size: int = 65536,
    allow_stream: bool = True,
    stream_bucket_bytes: int = 4 << 20,
    allow_overlap: bool = True,
    have_budget: bool = False,
    model_comm_s: float = 0.0,
    pipeline_bubble_s: float = 0.0,
) -> list[dict]:
    """Knob vectors for ONE model-axis LM layout — the ``lm[tp2]+qsgd8+se``
    rows the controller enumerates next to the replicated candidates.

    ``model_axes`` is the layout's model-axis shape dict (``{"tp": 2}``);
    ``model_comm_s`` / ``pipeline_bubble_s`` are the layout's PRE-PRICED
    axis-collective floor (``comm_model.tp_psum_wire_bytes`` /
    ``moe_all_to_all_wire_bytes`` / ``pipeline_bubble_s`` over the
    measured fabric) that ``predict_step_s`` adds to every prediction.
    Only PROVEN compositions are emitted (:func:`model_axis_conflicts`
    returns None for each, asserted); like quorum rows, these are priced,
    never probed — the probe harness builds replicated-family programs.
    ``allow_overlap`` adds ``+delayed`` variants (plain and ``+se``) for
    the payload-carrying aggregations when a codec is armed —
    ``predict_step_s`` prices them with the compute AND pipeline-bubble
    hiding budget. Pure and deterministic."""
    from atomo_tpu.utils.comm_model import candidate_name

    axes = {
        str(a): int(s)
        for a, s in dict(model_axes).items()
        if a not in ("dp", "ici")
    }
    if not axes:
        raise ValueError(
            "lm_axis_candidates needs at least one model axis; a pure "
            "data layout's candidates come from enumerate_candidates"
        )
    shared = {
        "model_axes": axes,
        "overlap": "off",
        "superstep": 1,
        "model_comm_s": float(model_comm_s),
        "pipeline_bubble_s": float(pipeline_bubble_s),
    }
    if codec_tag:
        shared["codec"] = str(codec_tag)
    out: list[dict] = []
    aggs = ["gather", "psum"] + (["ring"] if allow_ring else [])
    for agg in aggs:
        base = {**shared, "aggregate": agg}
        if agg == "ring":
            base["ring_bucket_size"] = int(ring_bucket_size)
        out.append(dict(base))
        variants = [dict(base)]
        if allow_stream and agg in ("gather", "ring"):
            se = {
                **base,
                "stream_encode": "on",
                "stream_bucket_bytes": int(stream_bucket_bytes),
            }
            out.append(dict(se))
            variants.append(se)
        if have_budget:
            out.append({**base, "budget_alloc": "variance"})
        if allow_overlap and codec_tag and agg in ("gather", "ring"):
            # the stale-by-one carry composes with stream-encode (it
            # restructures the PRODUCE side only); psum / codec-less
            # rows have no payload to carry — model_axis_conflicts
            # rejects them, so they are never emitted here
            for v in variants:
                out.append({**v, "overlap": "delayed"})
    for c in out:
        reason = model_axis_conflicts(c)
        assert reason is None, f"emitted a rejected composition: {reason}"
        c["name"] = candidate_name(c)
    return out
