"""Parallelism layer: meshes, replicated compressed-DP, distributed init."""

from atomo_tpu.parallel.mesh import (  # noqa: F401
    batch_sharded,
    make_mesh,
    replicated,
)
from atomo_tpu.parallel.compile import (  # noqa: F401
    compile_global,
    compile_step,
    shardings_from_specs,
)
from atomo_tpu.parallel.launch import (  # noqa: F401
    HealthMonitor,
    HealthWatchdog,
    global_mesh,
    initialize,
)
from atomo_tpu.parallel.replicated import (  # noqa: F401
    DelayedState,
    EfState,
    OverlapCarry,
    distributed_train_loop,
    init_delayed_state,
    init_ef_state,
    make_delayed_oracle_steps,
    make_distributed_eval_step,
    make_distributed_train_step,
    make_phase_train_steps,
    replicate_state,
    shard_batch,
    shard_superbatch,
)
from atomo_tpu.parallel.tp import (  # noqa: F401
    create_tp_lm_state,
    make_tp_lm_train_step,
    make_tp_sp_lm_train_step,
    shard_tp_tokens,
)
from atomo_tpu.parallel.moe import (  # noqa: F401
    create_moe_lm_state,
    make_moe_lm_train_step,
    shard_moe_tokens,
)
from atomo_tpu.parallel.pp import (  # noqa: F401
    create_pp_lm_state,
    make_pp_lm_train_step,
    shard_pp_tokens,
)
