"""Decoder-only transformer LM — the long-context model family.

The reference's zoo is CV-only (SURVEY.md §2 model row); this family extends
the framework to sequence models so the sequence/context-parallel machinery
(atomo_tpu.parallel.ring) has a first-class consumer. Design is TPU-first:
pre-LN blocks, bias-free linears feeding the MXU, GELU MLP at 4x width,
learned positional embeddings, all static shapes.

The attention callable is injectable: ``attention_fn(q, k, v)`` receives
(B, H, S, D). Default is the single-device exact softmax
(parallel.ring.full_attention); under a mesh with an 'sp' axis pass the
shard_map-wrapped ring attention (make_sequence_parallel_attention) and the
same module runs with the sequence dimension sharded.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from atomo_tpu.parallel.ring import full_attention

AttentionFn = Callable[[jax.Array, jax.Array, jax.Array], jax.Array]


class MultiHeadAttention(nn.Module):
    num_heads: int
    head_dim: int
    attention_fn: Optional[AttentionFn] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, s, _ = x.shape
        h, d = self.num_heads, self.head_dim
        qkv = nn.Dense(3 * h * d, use_bias=False, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):  # (B, S, H*D) -> (B, H, S, D)
            return t.reshape(b, s, h, d).transpose(0, 2, 1, 3)

        fn = self.attention_fn or (lambda q, k, v: full_attention(q, k, v, causal=True))
        out = fn(heads(q), heads(k), heads(v))  # (B, H, S, D)
        out = out.transpose(0, 2, 1, 3).reshape(b, s, h * d)
        return nn.Dense(x.shape[-1], use_bias=False, name="proj")(out)


class Block(nn.Module):
    num_heads: int
    head_dim: int
    mlp_ratio: int = 4
    dropout: float = 0.0
    attention_fn: Optional[AttentionFn] = None

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False) -> jax.Array:
        width = x.shape[-1]
        y = nn.LayerNorm(use_bias=False, name="ln1")(x)
        y = MultiHeadAttention(self.num_heads, self.head_dim, self.attention_fn)(y)
        if self.dropout:
            y = nn.Dropout(self.dropout, deterministic=not train)(y)
        x = x + y
        y = nn.LayerNorm(use_bias=False, name="ln2")(x)
        y = nn.Dense(self.mlp_ratio * width, use_bias=False, name="up")(y)
        y = nn.gelu(y)
        y = nn.Dense(width, use_bias=False, name="down")(y)
        if self.dropout:
            y = nn.Dropout(self.dropout, deterministic=not train)(y)
        return x + y


class TransformerLM(nn.Module):
    """Causal LM: int32 tokens (B, S) -> logits (B, S, vocab)."""

    vocab_size: int = 256
    max_len: int = 1024
    width: int = 256
    depth: int = 4
    num_heads: int = 4
    dropout: float = 0.0
    attention_fn: Optional[AttentionFn] = None

    @nn.compact
    def __call__(
        self, tokens: jax.Array, train: bool = False, pos_offset=0
    ) -> jax.Array:
        """``pos_offset`` is the global position of tokens[:, 0] — pass
        axis_index(sp) * S_local when the sequence dim is sharded, so every
        shard embeds its true positions (not local 0..S/n)."""
        b, s = tokens.shape
        head_dim = self.width // self.num_heads
        x = nn.Embed(self.vocab_size, self.width, name="tok_emb")(tokens)
        pos = nn.Embed(self.max_len, self.width, name="pos_emb")(
            pos_offset + jnp.arange(s)
        )
        x = x + pos[None, :, :]
        for i in range(self.depth):
            x = Block(
                self.num_heads,
                head_dim,
                dropout=self.dropout,
                attention_fn=self.attention_fn,
                name=f"block{i}",
            )(x, train=train)
        x = nn.LayerNorm(use_bias=False, name="ln_f")(x)
        return nn.Dense(self.vocab_size, use_bias=False, name="head")(x)


def lm_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Next-token cross-entropy: predict tokens[:, 1:] from logits[:, :-1]."""
    import optax

    return optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], tokens[:, 1:]
    ).mean()
