"""Shrink-and-continue — layer 2 of the elastic-world subsystem.

Two pieces, one per half of the "replica stopped contributing" story:

* :class:`AbsenceTracker` — the HOST side. The guarded step already
  reports which replicas passed the screen (``metrics["ok_bits"]``, a
  psum-ed bitmask added by ``make_distributed_train_step(track_ok_bits=
  True)``); the tracker is a pure fold over that per-step series that
  separates a transient anomaly (one masked step — rung 1's business)
  from a PERSISTENTLY absent replica (the same bit low for ``patience``
  consecutive steps — the membership layer's business). Same design rules
  as the divergence detector: a sequential fold, so a superstep block's
  ``(K,)`` series and a per-step series produce identical verdicts for
  any partition.

* :func:`survivor_decode_mean` — the DEVICE side. While a dead replica is
  being *carried* (between its death and the next checkpoint boundary),
  the guard masks its payload out of the aggregation. The pre-elastic
  rescale (``decode_mean_tree`` = sum/N, then ``rescale_by_survivors`` =
  ×N/kept) is mathematically the survivors' mean but ROUNDS TWICE — its
  last mantissa bits differ from any mean computed with one division.
  This operator is the bit-exact statement of "mean over the surviving
  roster": per-replica canonical decode, a SEQUENTIAL roster-order fold
  of the rows, ONE division by the surviving count. Pinning the fold
  order is what makes the bit-identity claim well-defined AND true: a
  masked slot decodes to exactly zero (the ``_mask_gathered`` invariant)
  and ``x + 0.0`` is exact in IEEE, so the N-row masked fold produces
  the SAME bits as the (N-1)-row fold over the survivors alone — whereas
  an ``jnp.sum``/``jnp.mean`` reduction changes its association tree
  with the row count ((a+0)+(c+d) vs (a+c)+d) and drifts in the last
  mantissa bit (measured; the reassociation class this repo documents
  for fused SVD decode and scan-vs-standalone). The ring's elastic
  segment reduction uses the same pinned fold, so the gather and ring
  carried-world operators and the survivors-only reference are all
  bit-identical BY CONSTRUCTION (tested per codec in
  tests/test_elastic.py); the unpinned ``decode_mean_tree(fused=False)``
  agrees to the documented last-bit drift class. The carried-world
  operator and the shrunken-world operator are then the SAME function of
  the surviving payloads — the shrink boundary changes the data shards,
  never the aggregation arithmetic.
"""

from __future__ import annotations

from typing import Optional


def ok_bits_mask(bits: float, world_size: int) -> int:
    """Decode a step's ``ok_bits`` metric (psum of ok * 2^replica, exact
    in float32 for the <= 24-replica meshes this targets) into an int
    bitmask of the replicas that passed the screen."""
    full = (1 << world_size) - 1
    return int(round(float(bits))) & full


class AbsenceTracker:
    """Pure fold over the per-step ``ok_bits`` series: replica ``i`` is
    declared ABSENT once its bit has been low for ``patience`` consecutive
    steps. One masked step is rung-1 noise (a transient screen hit); a
    sustained run of them is a dead member. Feeding the same series one
    step at a time or in blocks of any partition produces identical
    verdicts (the detector-fold contract)."""

    def __init__(self, world_size: int, patience: int):
        if world_size < 1:
            raise ValueError(f"world_size must be >= 1, got {world_size}")
        if patience < 1:
            raise ValueError(f"absence patience must be >= 1, got {patience}")
        self.world_size = world_size
        self.patience = patience
        self._misses = [0] * world_size

    def observe(self, bits) -> set[int]:
        """Fold one step's ok_bits; returns the replica slots that JUST
        crossed the patience threshold this step (empty most steps)."""
        mask = ok_bits_mask(bits, self.world_size)
        newly = set()
        for i in range(self.world_size):
            if mask & (1 << i):
                self._misses[i] = 0
            else:
                self._misses[i] += 1
                if self._misses[i] == self.patience:
                    newly.add(i)
        return newly

    def observe_series(self, series) -> list[tuple[int, int]]:
        """Fold a block's ``(K,)`` ok_bits series (or one scalar); returns
        ``[(in_block_index, slot), ...]`` for every slot that crossed the
        threshold, in fold order — the block entry point the coordinator
        consumes (the index lets it name the exact step in its log/record
        without re-implementing the fold)."""
        import numpy as np

        events: list[tuple[int, int]] = []
        for i, v in enumerate(np.asarray(series).reshape(-1)):
            for slot in sorted(self.observe(v)):
                events.append((i, slot))
        return events


def mask_absent(gathered, okg):
    """Zero the gathered payload slots of absent replicas (leading axis =
    replica) — the elastic name for parallel.replicated's
    ``_mask_gathered``, delegated so there is exactly ONE masking
    implementation: the survivor mean's "a masked payload decodes to
    exact zeros" invariant must be the SAME arithmetic the frozen guarded
    gather path applies (``where``, never multiply — NaN x 0 is still
    NaN), and two copies would let a fix to one silently break the
    other's bit-identity claim. Lazy import: replicated lazily imports
    this module inside its traced step, so the cycle never closes at
    module load."""
    from atomo_tpu.parallel.replicated import _mask_gathered

    return _mask_gathered(gathered, okg)


def roster_fold_sum(rows):
    """Sequential left-fold sum of a ``(N, ...)`` row stack in roster
    order — THE pinned reduction every elastic mean uses (module
    docstring: pinning the association tree is what makes "a zero row is
    an exact identity" compose into bit-identity across row counts).
    ``N`` is a trace-time constant, so the unrolled adds cost what one
    reduce costs; XLA does not reassociate fp adds."""
    acc = rows[0]
    for i in range(1, rows.shape[0]):
        acc = acc + rows[i]
    return acc


def survivor_decode_mean(codec, gathered, okg, grads_like, kept=None):
    """Decode-mean over the SURVIVING roster, computed from the full
    gathered slot array: mask absent slots, per-replica canonical decode
    (the ring/gather parity order — vmap of ``codec.decode``), a
    roster-order :func:`roster_fold_sum` over the replica axis, ONE
    division by the surviving count.

    Bit-identity contract (the elastic acceptance test): for any absent
    subset this equals the same pinned fold over the SURVIVORS' rows
    alone — the mean a shrunken world computes over those payloads — bit
    for bit, for every codec; the unpinned ``decode_mean_tree(codec, ...,
    fused=False)`` agrees to the documented last-mantissa-bit
    reassociation drift. The fused SVD decode_mean is deliberately NOT
    used here: it reassociates over the flattened (replica, atom) axis,
    and the elastic contract is exactness, not MXU throughput, for the
    handful of steps a dead replica is carried.

    ``kept`` defaults to ``sum(okg)``; pass it when the caller already
    computed the surviving count (one fewer reduction in the traced step).
    """
    import jax
    import jax.numpy as jnp

    masked = mask_absent(gathered, okg)
    if kept is None:
        kept = jnp.sum(okg)
    denom = jnp.maximum(kept, 1.0)
    leaves, treedef = jax.tree_util.tree_flatten(grads_like)
    p_leaves = treedef.flatten_up_to(masked)
    out = []
    for p, g in zip(p_leaves, leaves):
        dec = jax.vmap(
            lambda q, s=tuple(g.shape), d=g.dtype: codec.decode(q, s, d)
        )(p)
        s = roster_fold_sum(dec)
        out.append(s / denom.astype(s.dtype))
    return jax.tree_util.tree_unflatten(treedef, out)
