"""Fleet launcher — real multi-process formation + the lease drill loop.

Two layers, deliberately separable:

  * **Collective formation** (:func:`form_fleet` / :func:`reform_fleet`)
    wires :func:`atomo_tpu.parallel.launch.initialize` — the retrying
    jax.distributed handshake — so a real 2-process run FORMS, and
    re-forms at a new world after a membership transition. The re-form
    coordinator address is DERIVED (base port + membership epoch), so
    every surviving member computes the same rendezvous without any
    side channel: the epoch record in ``membership.json`` *is* the
    agreement.
  * **The lease loop** (:func:`run_fleet_member`) drives one host's
    :class:`~atomo_tpu.fleet.control.FleetController` round by round —
    heartbeat, observe, reconcile, maybe_transition — with the chaos
    hooks applied at the layer they model: ``hostdie@`` exits the
    process, ``slowlink@`` delays the lease renewal, ``partition@``
    cuts this host off the store entirely (no writes, no reads — the
    colocation fence, see control.py).

    The lease loop needs NO cross-process collectives, so it runs —
    and is drilled 2-process — on runtimes whose CPU backend cannot
    execute a multiprocess psum (where the collective smoke in
    tests/test_multiprocess.py must skip). Collective formation is
    attempted when a coordinator address is given and every failure is
    RECORDED (``fleet_form``/``fleet_reform`` incidents), never fatal
    to the control plane: losing the collective runtime is exactly the
    situation the control plane exists to survive.

``python -m atomo_tpu.fleet.launcher`` runs one member and prints one
``RESULT {json}`` line (the tests/_mp_worker.py convention).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional

from atomo_tpu.fleet.control import (
    FleetConfig,
    FleetController,
    roster_hash,
)
from atomo_tpu.utils.chaos import ChaosInjector


def _reform_address(base: str, epoch: int) -> str:
    """Deterministic per-epoch rendezvous: base ``host:port`` with the
    membership epoch added to the port — every member of the new roster
    derives the same address from the epoch record alone."""
    host, _, port = base.rpartition(":")
    return f"{host}:{int(port) + int(epoch)}"


def _collective_up() -> bool:
    """Is a jax.distributed client currently formed in this process?"""
    try:
        from jax._src.distributed import global_state as _gs

        return getattr(_gs, "client", None) is not None
    except ImportError:
        return False


def _shutdown_bounded(timeout: float) -> bool:
    """``jax.distributed.shutdown()`` with a watchdog: the shutdown is a
    CLUSTER-WIDE BARRIER on this runtime — every member of the old
    collective must call it, and a one-sided call blocks until the peers
    arrive (or the service declares the barrier failed and the error
    poller hard-kills the process). Run it in a thread and give it
    ``timeout`` seconds; returns True when the barrier completed. On
    False the old client is left abandoned — the caller must NOT
    re-initialize in this process (the stale barrier state aborts it)
    and records the re-form as deferred to the next process generation
    instead."""
    import threading

    import jax

    done = threading.Event()

    def _sd():
        try:
            jax.distributed.shutdown()
        except Exception:  # noqa: BLE001 — judged by the event, not the raise
            pass
        done.set()

    th = threading.Thread(target=_sd, daemon=True)
    th.start()
    th.join(max(0.1, float(timeout)))
    return done.is_set()


def stand_down_collective(ctrl: FleetController, timeout: float) -> bool:
    """The EXCLUDED host's half of a re-form: join the old collective's
    shutdown barrier so the survivors' shutdown completes. A store
    partition fences the lease store, not TCP — the excluded host can
    still reach the coordination service, and doing so is what lets the
    surviving roster re-form without tearing the process down. Recorded
    either way (``fleet_stand_down``); a barrier that never completes
    (the peer really died) is abandoned after ``timeout`` and said so."""
    completed = _shutdown_bounded(timeout)
    ctrl.incidents.append(
        "fleet_stand_down",
        action="collective_released" if completed else "release_timeout",
        host=ctrl.host_id,
        epoch=ctrl.epoch.epoch if ctrl.epoch else None,
    )
    ctrl.log_fn(
        f"Fleet: host {ctrl.host_id} "
        + ("released the old collective (stood down)"
           if completed else
           "could not release the old collective within "
           f"{timeout:.0f}s; abandoned")
    )
    return completed


def form_fleet(
    ctrl: FleetController,
    coordinator: str,
    num_processes: int,
    process_id: int,
    *,
    attempts: int = 3,
    backoff: float = 0.5,
    init_timeout: float = 15.0,
) -> bool:
    """Initial collective formation via the retrying handshake
    (:func:`parallel.launch.initialize` — restart-race tolerant). A
    failure is an incident, not an exception: the lease loop runs
    either way."""
    try:
        from atomo_tpu.parallel import launch

        launch.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
            attempts=attempts,
            backoff=backoff,
            init_timeout=init_timeout,
        )
    except Exception as exc:  # noqa: BLE001 — recorded, never fatal here
        ctrl.incidents.append(
            "fleet_form",
            action="form_failed",
            host=ctrl.host_id,
            world=num_processes,
            error=str(exc)[:300],
        )
        ctrl.log_fn(f"Fleet: collective formation failed ({exc}); "
                    "continuing lease-only")
        return False
    ctrl.incidents.append(
        "fleet_form",
        action="formed",
        host=ctrl.host_id,
        world=num_processes,
        coordinator=coordinator,
    )
    return True


def reform_fleet(
    ctrl: FleetController,
    base_coordinator: str,
    *,
    init_timeout: float = 15.0,
) -> bool:
    """Re-form the collective runtime on the CURRENT epoch's roster:
    release the old handshake (the shutdown BARRIER — every old member,
    including the host the new roster excludes, joins it via
    :func:`stand_down_collective`) and re-initialize at the
    epoch-derived address with ranks = roster order. Called by every
    member that adopts (or appends) a roster-changing epoch; the
    blocking initialize is the rendezvous barrier — the leader waits
    there for a healed host that is still reconciling.

    When the old collective cannot be released within ``init_timeout``
    (the excluded peer really died, so the barrier never completes),
    the re-form is DEFERRED: recorded as a ``fleet_reform`` incident
    with ``action="deferred"`` and left for the next process generation
    — re-initializing over an abandoned shutdown barrier hard-aborts
    the process on this runtime, which would take the control plane
    down with it."""
    rec = ctrl.epoch
    if rec is None or ctrl.host_id not in rec.roster:
        return False
    addr = _reform_address(base_coordinator, rec.epoch)
    rank = list(rec.roster).index(ctrl.host_id)
    if _collective_up() and not _shutdown_bounded(init_timeout):
        ctrl.incidents.append(
            "fleet_reform",
            action="deferred",
            host=ctrl.host_id,
            epoch=rec.epoch,
            world=rec.world_size,
            reason=(
                "old collective's shutdown barrier did not complete "
                f"within {init_timeout:.0f}s (a dead peer never joins "
                "it); collective re-form deferred to the next process "
                "generation — the lease control plane continues"
            ),
        )
        ctrl.log_fn(
            f"Fleet: re-form at epoch {rec.epoch} deferred (old "
            "collective not released); continuing lease-only"
        )
        return False
    try:
        from atomo_tpu.parallel import launch

        launch.initialize(
            coordinator_address=addr,
            num_processes=rec.world_size,
            process_id=rank,
            attempts=3,
            backoff=0.5,
            init_timeout=init_timeout,
        )
    except Exception as exc:  # noqa: BLE001 — recorded, never fatal
        ctrl.incidents.append(
            "fleet_reform",
            action="reform_failed",
            host=ctrl.host_id,
            epoch=rec.epoch,
            world=rec.world_size,
            error=str(exc)[:300],
        )
        ctrl.log_fn(
            f"Fleet: re-form at epoch {rec.epoch} failed ({exc}); "
            "continuing lease-only"
        )
        return False
    ctrl.incidents.append(
        "fleet_reform",
        action="reformed",
        host=ctrl.host_id,
        epoch=rec.epoch,
        world=rec.world_size,
        rank=rank,
        coordinator=addr,
    )
    ctrl.log_fn(
        f"Fleet: re-formed at epoch {rec.epoch} "
        f"(world {rec.world_size}, rank {rank})"
    )
    return True


def run_fleet_member(
    train_dir: str,
    host_id: int,
    n_hosts: int,
    *,
    cfg: Optional[FleetConfig] = None,
    rounds: int = 40,
    chaos: Optional[ChaosInjector] = None,
    coordinator: Optional[str] = None,
    stop_epoch: int = 0,
    max_seconds: float = 45.0,
    log_fn=print,
) -> dict:
    """Drive one host through ``rounds`` heartbeat rounds. Returns a
    JSON-able summary. ``stop_epoch`` > 0 ends the drill early once
    this host is a member of an epoch >= it (the drills know their
    target epoch; production would loop forever). ``max_seconds`` is a
    wall guard so a wedged drill fails visibly instead of hanging its
    parent."""
    cfg = cfg or FleetConfig()
    ctrl = FleetController(cfg, train_dir, host_id, n_hosts, log_fn=log_fn)
    formed = False
    reforms = 0
    if coordinator:
        formed = form_fleet(
            ctrl, coordinator, n_hosts, host_id,
            init_timeout=cfg.init_timeout_s,
        )
    ctrl.adopt()
    if chaos is not None and ctrl.epoch is not None:
        chaos.membership_epoch = ctrl.epoch.epoch
    t0 = time.monotonic()
    rounds_run = 0
    cut_rounds = 0
    was_cut = False
    for r in range(1, int(rounds) + 1):
        if time.monotonic() - t0 > max_seconds:
            ctrl.log_fn(
                f"Fleet: host {host_id} drill wall guard hit after "
                f"{r - 1} rounds"
            )
            break
        if chaos is not None:
            chaos.maybe_hostdie(r, host_id)
            if chaos.store_partitioned(r, host_id):
                # cut off the store: no lease renewal, no reads, no
                # evidence rows — the other side sees exactly what a
                # real partition shows it (a lease that stopped)
                cut_rounds += 1
                was_cut = True
                time.sleep(cfg.period_s)
                continue
            if was_cut:
                # back on the store: say so in my own stream (the
                # observer side already recorded lease_stale; this is
                # the healed side's half of the story)
                was_cut = False
                ctrl.incidents.append(
                    "fleet_partition",
                    action="healed",
                    host=ctrl.host_id,
                    round=r,
                    cut_rounds=cut_rounds,
                )
                ctrl.log_fn(
                    f"Fleet: host {host_id} back on the store after "
                    f"{cut_rounds} cut round(s)"
                )
            lag = chaos.slowlink_delay(r, host_id)
            if lag:
                time.sleep(lag)
        before = ctrl.epoch.epoch if ctrl.epoch else -1
        ctrl.heartbeat(step=r)
        ctrl.observe()
        status = ctrl.reconcile()
        if status == "excluded" and coordinator and _collective_up():
            # the excluded host's duty to the survivors: join the old
            # collective's shutdown barrier so THEIR re-form completes
            stand_down_collective(ctrl, cfg.init_timeout_s)
        rec = ctrl.maybe_transition(step=r)
        ctrl.record_metrics(step=r, status=status)
        rounds_run = r
        if ctrl.epoch is not None and ctrl.epoch.epoch != before:
            if chaos is not None:
                # epoch-keyed faults disarm once this host has moved on
                # (the die@ rule at host granularity)
                chaos.membership_epoch = ctrl.epoch.epoch
            if coordinator and ctrl.host_id in ctrl.epoch.roster:
                reforms += int(reform_fleet(
                    ctrl, coordinator,
                    init_timeout=cfg.init_timeout_s,
                ))
        if (
            stop_epoch
            and ctrl.epoch is not None
            and ctrl.epoch.epoch >= stop_epoch
            and ctrl.host_id in ctrl.epoch.roster
        ):
            ctrl.record_metrics(step=r, status="done")
            break
        time.sleep(cfg.period_s)
        _ = rec
    final = ctrl.epoch
    return {
        "host": int(host_id),
        "rounds_run": int(rounds_run),
        "cut_rounds": int(cut_rounds),
        "formed": bool(formed),
        "reforms": int(reforms),
        "epoch": int(final.epoch) if final else None,
        "world": int(final.world_size) if final else None,
        "roster": list(final.roster) if final else [],
        "roster_hash": roster_hash(final.roster) if final else None,
        "member": bool(final and host_id in final.roster),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m atomo_tpu.fleet.launcher",
        description="Run one fleet member's lease loop (drill driver).",
    )
    p.add_argument("--train-dir", required=True)
    p.add_argument("--host-id", type=int, required=True)
    p.add_argument("--n-hosts", type=int, required=True)
    p.add_argument("--rounds", type=int, default=40)
    p.add_argument("--period", type=float, default=0.05)
    p.add_argument("--patience", type=int, default=3)
    p.add_argument("--max-regrows", type=int, default=1)
    p.add_argument("--stop-epoch", type=int, default=0)
    p.add_argument("--max-seconds", type=float, default=45.0)
    p.add_argument("--init-timeout", type=float, default=15.0,
                   help="seconds to bound each collective handshake and "
                        "the re-form shutdown barrier")
    p.add_argument("--coordinator", default="",
                   help="host:port — attempt real jax.distributed "
                        "formation/re-formation (lease-only when empty)")
    p.add_argument("--chaos", default="",
                   help="chaos spec (hostdie@/slowlink@/partition@ ...)")
    args = p.parse_args(argv)
    cfg = FleetConfig(
        patience=args.patience,
        period_s=args.period,
        max_regrows=args.max_regrows,
        init_timeout_s=args.init_timeout,
    )
    chaos = None
    if args.chaos:
        from atomo_tpu.utils.chaos import ChaosConfig

        chaos = ChaosInjector(ChaosConfig.from_spec(args.chaos))
    summary = run_fleet_member(
        args.train_dir,
        args.host_id,
        args.n_hosts,
        cfg=cfg,
        rounds=args.rounds,
        chaos=chaos,
        coordinator=args.coordinator or None,
        stop_epoch=args.stop_epoch,
        max_seconds=args.max_seconds,
    )
    print("RESULT " + json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
