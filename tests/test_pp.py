"""Pipeline parallelism: GPipe schedule parity against the unpipelined oracle.

The oracle is pp_lm_forward_reference — the exact function the pipeline
distributes — so a (dp=2, pp=4, M=2) dense step must land on the same loss
and updated params as single-device AD + optax on the full batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from atomo_tpu.codecs import SvdCodec
from atomo_tpu.parallel.mesh import make_mesh
from atomo_tpu.parallel.pp import (
    create_pp_lm_state,
    init_pp_lm_params,
    make_pp_state_specs,
    make_pp_lm_train_step,
    pp_lm_forward_reference,
    pp_param_specs,
    shard_pp_state,
    shard_pp_tokens,
)
from atomo_tpu.training.trainer import TrainState

CFG = dict(vocab_size=16, max_len=12, width=16, depth=4, num_heads=4)


pytestmark = pytest.mark.slow  # heavy multi-device compile/parity runs; deselect with -m "not slow"


def test_pp_reference_forward_shapes():
    params = init_pp_lm_params(jax.random.PRNGKey(0), CFG)
    tokens = jnp.zeros((2, 10), jnp.int32)
    logits = pp_lm_forward_reference(params, tokens, CFG)
    assert logits.shape == (2, 10, CFG["vocab_size"])
    assert np.all(np.isfinite(np.asarray(logits)))


@pytest.mark.parametrize("microbatches", [2, 4])
def test_pp_step_matches_single_device(microbatches):
    opt = optax.sgd(0.1, momentum=0.9)
    mesh = make_mesh(8, axes=(("dp", 2), ("pp", 4)))
    tokens = jax.random.randint(jax.random.PRNGKey(7), (8, 10), 0, CFG["vocab_size"])
    params0 = init_pp_lm_params(jax.random.PRNGKey(0), CFG)

    def oracle_loss(p):
        reps = tokens.reshape(2, 4, -1)
        tot = 0.0
        for r in range(2):
            logits = pp_lm_forward_reference(p, reps[r], CFG)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], reps[r][:, 1:]
            )
            tot = tot + ce.mean()
        return tot / 2.0

    grads = jax.grad(oracle_loss)(params0)
    want = jax.device_get(
        optax.apply_updates(params0, opt.update(grads, opt.init(params0), params0)[0])
    )
    want_loss = float(oracle_loss(params0))

    state = TrainState(
        step=jnp.zeros((), jnp.int32), params=params0, batch_stats={},
        opt_state=opt.init(params0),
    )
    specs = make_pp_state_specs(state, pp_param_specs(params0))
    state = shard_pp_state(mesh, state, specs)
    step = make_pp_lm_train_step(
        CFG, opt, mesh, specs, codec=None, num_microbatches=microbatches
    )
    state2, metrics = step(state, jax.random.PRNGKey(1), shard_pp_tokens(mesh, tokens))

    np.testing.assert_allclose(float(metrics["loss"]), want_loss, atol=1e-5)
    got = jax.device_get(state2.params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5
        ),
        got,
        want,
    )
    assert int(state2.step) == 1


def test_pp_step_with_codec_runs_and_learns():
    opt = optax.sgd(0.1, momentum=0.9)
    mesh = make_mesh(8, axes=(("dp", 2), ("pp", 4)))
    state, specs = create_pp_lm_state(mesh, CFG, opt, jax.random.PRNGKey(3))
    step = make_pp_lm_train_step(CFG, opt, mesh, specs, codec=SvdCodec(rank=2))
    row = jnp.arange(10, dtype=jnp.int32) % CFG["vocab_size"]
    tokens = jnp.tile(row[None], (8, 1))
    toks = shard_pp_tokens(mesh, tokens)
    st, losses = state, []
    for i in range(12):
        st, m = step(st, jax.random.PRNGKey(i), toks)
        losses.append(float(m["loss"]))
    assert int(m["msg_bytes"]) < int(m["dense_bytes"])
    assert losses[-1] < losses[0] * 0.8, losses


def test_pp_rejects_indivisible_depth():
    mesh = make_mesh(8, axes=(("dp", 2), ("pp", 4)))
    bad = dict(CFG, depth=6)
    with pytest.raises(ValueError, match="depth"):
        create_pp_lm_state(mesh, bad, optax.sgd(0.1), jax.random.PRNGKey(0))


def test_pp_bf16_step_runs_and_keeps_f32_state():
    opt = optax.sgd(0.05, momentum=0.9)
    mesh = make_mesh(8, axes=(("dp", 2), ("pp", 4)))
    state, specs = create_pp_lm_state(mesh, CFG, opt, jax.random.PRNGKey(3))
    step = make_pp_lm_train_step(
        CFG, opt, mesh, specs, codec=SvdCodec(rank=2),
        compute_dtype=jnp.bfloat16,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(9), (8, 10), 0, 16)
    state, m = step(state, jax.random.PRNGKey(1), shard_pp_tokens(mesh, tokens))
    assert np.isfinite(float(m["loss"]))
    for leaf in jax.tree_util.tree_leaves(state.params):
        assert leaf.dtype == jnp.float32


def test_pp_step_multiblock_stage_matches_single_device():
    """depth=8 over pp=4 (TWO blocks per stage): the per-stage local
    lax.scan over multiple blocks must still match the unpipelined oracle."""
    cfg = dict(CFG, depth=8)
    opt = optax.sgd(0.1, momentum=0.9)
    mesh = make_mesh(8, axes=(("dp", 2), ("pp", 4)))
    tokens = jax.random.randint(jax.random.PRNGKey(7), (8, 10), 0, CFG["vocab_size"])
    params0 = init_pp_lm_params(jax.random.PRNGKey(0), cfg)

    def oracle_loss(p):
        reps = tokens.reshape(2, 4, -1)
        tot = 0.0
        for r in range(2):
            logits = pp_lm_forward_reference(p, reps[r], cfg)
            tot = tot + optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], reps[r][:, 1:]
            ).mean()
        return tot / 2.0

    grads = jax.grad(oracle_loss)(params0)
    want = jax.device_get(
        optax.apply_updates(params0, opt.update(grads, opt.init(params0), params0)[0])
    )
    state = TrainState(
        step=jnp.zeros((), jnp.int32), params=params0, batch_stats={},
        opt_state=opt.init(params0),
    )
    specs = make_pp_state_specs(state, pp_param_specs(params0))
    state = shard_pp_state(mesh, state, specs)
    step = make_pp_lm_train_step(cfg, opt, mesh, specs, codec=None)
    state2, _ = step(state, jax.random.PRNGKey(1), shard_pp_tokens(mesh, tokens))
    got = jax.device_get(state2.params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5
        ),
        got,
        want,
    )
