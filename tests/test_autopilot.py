"""Performance autopilot (PR 7): predictor ranking sanity, decision
determinism, preflight pinned-knob rejection, calibration honesty, the
step-time drift detector, the online re-tuner's protocol, the LR grid's
artifact, and the acceptance drill — a ``--auto tune`` run on the forced
4-device CPU mesh whose trajectory is bit-identical to launching the
chosen config statically (subprocess, slow-marked)."""

import json
import math
import os
import subprocess
import sys

import numpy as np
import pytest

from atomo_tpu.training.resilience import (
    DriftConfig,
    DriftState,
    drift_scan,
    drift_update,
)
from atomo_tpu.tuning.autopilot import OnlineRetuner, choose_winner, winner_knobs
from atomo_tpu.utils.comm_model import (
    calibration_warning,
    candidate_name,
    choose_aggregate,
    enumerate_candidates,
    predict_step_s,
    rank_candidates,
    recommend_for_scenario,
    resolve_fabric,
)

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_HERE)


# ---------------------------------------------------------------- predictor


def test_enumerate_candidates_respects_conflict_matrix():
    # single device: only the superstep knob exists
    one = enumerate_candidates(has_codec=True, ways=1)
    assert all("aggregate" not in c for c in one)
    assert {c["superstep"] for c in one} == {1, 8}
    # dense code: psum only, never delayed
    dense = enumerate_candidates(has_codec=False, ways=4)
    assert {c["aggregate"] for c in dense} == {"psum"}
    assert all(c["overlap"] == "off" for c in dense)
    # compressed multi-device: delayed exists only for gather/ring
    full = enumerate_candidates(has_codec=True, ways=4)
    assert all(
        c["aggregate"] in ("gather", "ring")
        for c in full if c["overlap"] == "delayed"
    )
    # the allow_* narrowing used for densify/zero1/num-aggregate configs
    no_delayed = enumerate_candidates(
        has_codec=True, ways=4, allow_overlap=False, allow_psum=False
    )
    assert all(c["overlap"] == "off" for c in no_delayed)
    assert all(c["aggregate"] != "psum" for c in no_delayed)
    # names are unique (they are the artifact's candidate identity)
    names = [c["name"] for c in full]
    assert len(names) == len(set(names))


def test_predictor_ranking_agrees_with_choose_aggregate():
    """The blocking candidates' predicted order must agree with the
    established ``choose_aggregate`` wire-byte logic in both regimes: the
    gather-wins region (N < 2x byte reduction) and the psum-wins region
    (N past it)."""
    dense_b, ways = 44.7e6, 4
    for payload_b, expect in ((1.0e6, "gather"), (30.0e6, "psum")):
        mode, _ = choose_aggregate(
            has_codec=True, dense_bytes=dense_b, payload_bytes=payload_b,
            ways=ways, fabric_bw=1.25e9, tax_s=2.5e-3,
        )
        assert mode.split("+")[0] in (expect, "ring"), mode
        cands = [
            c for c in enumerate_candidates(has_codec=True, ways=ways)
            if c["overlap"] == "off" and c["superstep"] == 1
            and c["aggregate"] in ("gather", "psum")
        ]
        ranked = rank_candidates(
            cands, dense_bytes=dense_b, payload_bytes=payload_b,
            ways=ways, fabric_bw=1.25e9, tax_s=2.5e-3, compute_s=5e-3,
        )
        assert ranked[0]["aggregate"] == expect, (payload_b, ranked)


def test_predictor_overlap_hides_chain_and_superstep_amortizes():
    ctx = dict(
        dense_bytes=44.7e6, payload_bytes=1e6, ways=4, fabric_bw=1.25e9,
        compute_s=10e-3, tax_s=2e-3,
    )
    blocking = predict_step_s(
        {"aggregate": "gather", "overlap": "off", "superstep": 1}, **ctx
    )
    delayed = predict_step_s(
        {"aggregate": "gather", "overlap": "delayed", "superstep": 1}, **ctx
    )
    # the chain fits under 10 ms of compute: delayed = compute + encode
    assert delayed < blocking
    assert delayed == pytest.approx(10e-3 + 1e-3)
    k1 = predict_step_s(
        {"aggregate": "gather", "overlap": "off", "superstep": 1},
        dispatch_s=3e-3, **ctx,
    )
    k8 = predict_step_s(
        {"aggregate": "gather", "overlap": "off", "superstep": 8},
        dispatch_s=3e-3, **ctx,
    )
    assert k1 - k8 == pytest.approx(3e-3 * 7 / 8)


def test_resolve_fabric_contract():
    assert resolve_fabric("ici") == 45e9
    assert resolve_fabric("auto", n_proc=1) == 45e9
    assert resolve_fabric("auto", n_proc=2) == 6.25e9
    assert resolve_fabric("2.5") == pytest.approx(2.5e9)
    for bad in ("nope", "-1", "inf", "nan", ""):
        with pytest.raises(ValueError):
            resolve_fabric(bad)


def test_calibration_warning_is_two_sided_and_bounded():
    assert calibration_warning(10e-3, 15e-3) is None  # 1.5x: fine
    up = calibration_warning(10e-3, 25e-3, "slow")
    down = calibration_warning(25e-3, 10e-3, "fast")
    assert up and "25.00 ms/step" in up and "10.00 ms/step" in up
    assert down and "2.5x" in down
    assert calibration_warning(0.0, 10e-3) is None  # nothing to compare
    assert calibration_warning(10e-3, float("nan")) is None


def test_recommend_for_scenario_is_pure_and_uses_measured_tax():
    budgets = {"dense": (44.7e6, 0), "qsgd8": (44.7e6, 15.1e6),
               "svd3": (44.7e6, 0.95e6)}
    measured = {"dense": 6.5, "qsgd8": 9.0, "svd3": 9.0}
    a = recommend_for_scenario(
        codec_budgets=budgets, measured_ms=measured, ways=8,
        fabric_bw=1.25e9,
    )
    b = recommend_for_scenario(
        codec_budgets=dict(reversed(list(budgets.items()))),
        measured_ms=measured, ways=8, fabric_bw=1.25e9,
    )
    assert a == b  # pure + order-independent
    # measured tax = measured codec step - measured dense step
    svd = next(r for r in a["ranked"] if r["code"] == "svd3")
    assert svd["codec_tax_ms"] == pytest.approx(2.5)
    with pytest.raises(ValueError, match="dense"):
        recommend_for_scenario(
            codec_budgets=budgets, measured_ms={"qsgd8": 9.0}, ways=8,
            fabric_bw=1.25e9,
        )


# ----------------------------------------------------------- decision layer


def _rows():
    return [
        {"name": "gather+off+k1", "aggregate": "gather", "overlap": "off",
         "superstep": 1, "probed": True, "sync_ok": True,
         "predicted_ms_per_step": 11.0, "measured_ms_per_step": 14.0},
        {"name": "ring+off+k1+b65536", "aggregate": "ring",
         "overlap": "off", "superstep": 1, "ring_bucket_size": 65536,
         "probed": True, "sync_ok": True,
         "predicted_ms_per_step": 12.0, "measured_ms_per_step": 13.0},
        {"name": "psum+off+k8", "aggregate": "psum", "overlap": "off",
         "superstep": 8, "probed": False,
         "predicted_ms_per_step": 9.0},
    ]


def test_choose_winner_is_deterministic_and_order_independent():
    rows = _rows()
    w1 = choose_winner(rows)
    w2 = choose_winner(list(reversed(rows)))
    assert w1["name"] == w2["name"] == "ring+off+k1+b65536"
    # same artifact re-read from JSON round-trip => same winner
    again = choose_winner(json.loads(json.dumps(rows)))
    assert again["name"] == w1["name"]
    assert winner_knobs(w1) == {
        "aggregate": "ring", "overlap": "off", "superstep": 1,
        "ring_bucket_size": 65536,
    }


def test_choose_winner_measured_beats_predicted_and_falls_back():
    rows = _rows()
    # an unprobed 9.0-predicted row must NOT beat a measured 13.0 row
    assert choose_winner(rows)["name"] == "ring+off+k1+b65536"
    # no valid measurement anywhere -> prediction decides
    for r in rows:
        r.pop("measured_ms_per_step", None)
        r["probed"] = False
    assert choose_winner(rows)["name"] == "psum+off+k8"
    # a non-finite measurement is not a measurement
    rows = _rows()
    rows[1]["measured_ms_per_step"] = float("nan")
    assert choose_winner(rows)["name"] == "gather+off+k1"
    # sync_ok=False rows are excluded from the measured pool
    rows = _rows()
    rows[1]["sync_ok"] = False
    assert choose_winner(rows)["name"] == "gather+off+k1"
    # ...and when EVERY probe is sync-invalid, the prediction decides —
    # an invalid measurement must not sneak back in via the fallback
    rows = _rows()
    for r in rows:
        r["sync_ok"] = False
    assert choose_winner(rows)["name"] == "psum+off+k8"
    assert choose_winner([]) is None


def test_tune_survives_a_failing_candidate_probe(monkeypatch, tmp_path):
    """One candidate OOMing/failing to compile must not abort the tune:
    the failure is recorded as a row and the ladder continues to a
    winner (review finding)."""
    import atomo_tpu.tuning.autopilot as ap

    calls = {"n": 0}

    def fake_probe(cand, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("XlaRuntimeError: out of memory")
        return {
            **cand, "probed": True, "sync_ok": True,
            "measured_ms_per_step": 10.0 + calls["n"],
            "probe_wall_s": 0.1,
        }

    monkeypatch.setattr("atomo_tpu.tuning.probe.probe_candidate",
                        fake_probe)
    import jax.numpy as jnp

    from atomo_tpu.codecs import QsgdCodec
    from atomo_tpu.models import get_model
    from atomo_tpu.training import make_optimizer
    from atomo_tpu.tuning.probe import model_init_fn

    model = get_model("lenet", 10)
    doc = ap.tune(
        model=model,
        optimizer=make_optimizer("sgd", lr=0.01, momentum=0.9),
        codec=QsgdCodec(bits=8, bucket_size=512),
        model_init_fn=model_init_fn(
            model, jnp.zeros((1, 28, 28, 1), jnp.float32)
        ),
        n_dev=4, sample_shape=(28, 28, 1), num_classes=10, batch=8,
        artifact_path=str(tmp_path / "td.json"),
        probe_top=3, probe_steps=1, probe_reps=1,
        log_fn=lambda *_: None,
    )
    failed = [r for r in doc["rows"] if r.get("probe_error")]
    assert len(failed) == 1 and "out of memory" in failed[0]["probe_error"]
    assert doc["complete"] is True
    assert doc["winner"]["name"] not in {failed[0]["name"]}
    assert doc["winner"]["measured_ms_per_step"] is not None


def test_candidate_name_round_trip():
    c = {"aggregate": "ring", "overlap": "delayed", "superstep": 8,
         "ring_bucket_size": 1024}
    assert candidate_name(c) == "ring+delayed+k8+b1024"
    assert candidate_name({"superstep": 1}) == "k1"


# ------------------------------------------------------------ drift detector


def test_drift_detector_alarms_on_sustained_drift_only():
    cfg = DriftConfig(window=8, ratio=1.5, patience=3, min_history=4)
    st = DriftState()
    for _ in range(10):
        st, a = drift_update(cfg, st, 0.010)
        assert a is None
    # a single spike is noise
    st, a = drift_update(cfg, st, 0.030)
    assert a is None
    st, a = drift_update(cfg, st, 0.010)
    assert a is None and st.hot == 0
    # sustained 2x drift fires after `patience` consecutive observations
    alarms = []
    for _ in range(3):
        st, a = drift_update(cfg, st, 0.022)
        alarms.append(a)
    assert alarms == [None, None, "step_time_drift"]


def test_drift_baseline_frozen_while_hot():
    cfg = DriftConfig(window=8, ratio=1.5, patience=50, min_history=2)
    st = DriftState()
    for _ in range(5):
        st, _ = drift_update(cfg, st, 0.010)
    base = st.mean
    for _ in range(20):
        st, _ = drift_update(cfg, st, 0.050)
    # the drifting series must NOT be absorbed into its own baseline
    assert st.mean == base
    assert st.hot == 20


def test_drift_baseline_sheds_compile_inflated_seed_fast():
    """The first observation of a cold run is compile-dominated (can be
    1000x a steady step). The floor-tracking baseline must shed it within
    ~a dozen steps so genuine drift early in training still alarms
    (review finding: a symmetric window-32 EMA needed ~130 steps, during
    which real 2x drift was silently absorbed)."""
    cfg = DriftConfig(window=32, ratio=1.5, patience=3, min_history=8)
    st = DriftState()
    st, _ = drift_update(cfg, st, 20.0)  # the compile step
    for _ in range(14):
        st, _ = drift_update(cfg, st, 0.010)
    assert st.mean < 0.015  # baseline recovered to ~the steady floor
    alarms = []
    for _ in range(3):
        st, a = drift_update(cfg, st, 0.025)  # genuine sustained 2.5x
        alarms.append(a)
    assert alarms[-1] == "step_time_drift"


def test_drift_scan_matches_sequential_fold_and_skips_garbage():
    cfg = DriftConfig(window=8, ratio=1.5, patience=3, min_history=2)
    series = [0.01] * 6 + [float("nan"), -1.0] + [0.03] * 3
    st_seq = DriftState()
    last = None
    for x in series:
        st_seq, a = drift_update(cfg, st_seq, x)
        last = a or last
    st_blk, a_blk = drift_scan(cfg, DriftState(), series)
    assert st_blk == st_seq
    assert a_blk == last == "step_time_drift"


def test_drift_config_validation():
    with pytest.raises(ValueError):
        DriftConfig(window=1)
    with pytest.raises(ValueError):
        DriftConfig(ratio=1.0)
    with pytest.raises(ValueError):
        DriftConfig(patience=0)


# ------------------------------------------------------------ online retuner


class _Log:
    def __init__(self):
        self.records = []

    def append(self, cause, **kw):
        self.records.append({"cause": cause, **kw})


def _drifted(tuner):
    """Feed a clean baseline then a sustained excursion."""
    for _ in range(10):
        tuner.observe(0.010)
    for _ in range(tuner.cfg.patience):
        tuner.observe(0.030)


def test_retuner_switches_at_boundary_and_logs_incident():
    log = _Log()
    probes = {"gather": 20.0, "ring": 12.0}
    tuner = OnlineRetuner(
        probe_fn=probes.__getitem__,
        drift=DriftConfig(window=8, ratio=1.5, patience=3, min_history=4),
        incidents=log, log_fn=lambda *_: None,
    )
    assert tuner.maybe_retune(5, "gather") is None  # nothing pending
    _drifted(tuner)
    assert tuner.pending == "step_time_drift"
    new = tuner.maybe_retune(10, "gather")
    assert new == "ring"
    assert tuner.pending is None
    rec = log.records[-1]
    assert rec["cause"] == "perf_drift" and rec["action"] == "retune->ring"
    assert rec["step"] == 10 and rec["mode"] == "gather"
    assert set(rec["measured_ms"]) == {"gather", "ring"}
    # the drift baseline restarts after a decision
    assert tuner.state == DriftState()


def test_retuner_keeps_config_within_margin_and_observe_only_mode():
    log = _Log()
    # 3% apart: inside the 5% switch margin -> keep
    tuner = OnlineRetuner(
        probe_fn={"gather": 10.0, "ring": 9.7}.__getitem__,
        drift=DriftConfig(window=8, ratio=1.5, patience=3, min_history=4),
        incidents=log, log_fn=lambda *_: None,
    )
    _drifted(tuner)
    assert tuner.maybe_retune(10, "gather") is None
    assert log.records[-1]["action"] == "retune_keep"
    # observe-only (no probe_fn): drift recorded, config kept
    log2 = _Log()
    t2 = OnlineRetuner(
        probe_fn=None,
        drift=DriftConfig(window=8, ratio=1.5, patience=3, min_history=4),
        incidents=log2, log_fn=lambda *_: None,
    )
    _drifted(t2)
    assert t2.maybe_retune(8, "local") is None
    assert log2.records[-1]["action"] == "observed"
    # a mode outside the bit-identical pair is never switched
    log3 = _Log()
    t3 = OnlineRetuner(
        probe_fn=lambda m: 1.0,
        drift=DriftConfig(window=8, ratio=1.5, patience=3, min_history=4),
        incidents=log3, log_fn=lambda *_: None,
    )
    _drifted(t3)
    assert t3.maybe_retune(8, "psum") is None
    assert log3.records[-1]["action"] == "observed"


def test_retune_defers_while_rollback_remedy_active():
    """The rig reports an open remedy window so the loop's re-probe can
    defer: a default rebuild mid-rewarm/densify would silently drop the
    doctor's remedy from the program (review finding)."""
    from atomo_tpu.training.resilience import (
        DetectorConfig,
        DivergeConfig,
        DivergenceDoctor,
        RecoveryRig,
    )

    def _rig(remedy):
        cfg = DivergeConfig(
            remedy=remedy, detector=DetectorConfig(window=4),
            max_rollbacks=2,
        )
        return RecoveryRig(
            DivergenceDoctor(cfg, train_dir=None, log_fn=lambda *_: None),
            cfg,
            reload_state=lambda t: "state",
            restream=lambda t: iter(()),
            build_step=lambda *a, **k: "step_fn",
        )

    rig = _rig("rewarm")
    assert not rig.remedy_active(3)  # nothing rolled back yet
    rig.rollback(5, "loss_zscore")  # target 0 (no train_dir), window 4
    assert rig.remedy_active(0) and rig.remedy_active(3)
    assert not rig.remedy_active(4)  # ramp saturated: rebuild is identity

    rig = _rig("densify")
    rig.rollback(5, "loss_zscore")
    assert rig.remedy_active(3) and rig.densify_until == 4
    assert rig.maybe_end_densify(4) == "step_fn"
    assert not rig.remedy_active(3)  # window closed, densify cleared

    rig = _rig("skip")
    rig.rollback(5, "loss_zscore")
    assert not rig.remedy_active(1)  # skip changes nothing in the program


def test_retuner_survives_probe_failure():
    log = _Log()

    def bad_probe(mode):
        raise RuntimeError("mesh on fire")

    tuner = OnlineRetuner(
        probe_fn=bad_probe,
        drift=DriftConfig(window=8, ratio=1.5, patience=3, min_history=4),
        incidents=log, log_fn=lambda *_: None,
    )
    _drifted(tuner)
    assert tuner.maybe_retune(10, "gather") is None  # keep, don't crash
    assert log.records[-1]["action"] == "retune_keep"


# ----------------------------------------------------------- CLI preflight


def _preflight(argv):
    from atomo_tpu.cli import _argv_preflight, build_parser

    parser = build_parser()
    sub = next(
        a for a in parser._actions if hasattr(a, "choices") and a.choices
    )
    return _argv_preflight(sub.choices["train"].parse_args(argv))


@pytest.mark.parametrize(
    "pinned",
    [
        ["--aggregate", "ring"],
        ["--overlap", "delayed", "--code", "svd", "--n-devices", "4"],
        ["--superstep", "4"],
    ],
)
def test_preflight_rejects_auto_tune_with_pinned_knobs(pinned):
    with pytest.raises(SystemExit, match="pin"):
        _preflight(["--auto", "tune", "--train-dir", "d"] + pinned)


def test_preflight_auto_tune_other_conflicts_and_acceptance():
    with pytest.raises(SystemExit, match="phase-metrics"):
        _preflight(["--auto", "tune", "--train-dir", "d",
                    "--phase-metrics"])
    with pytest.raises(SystemExit, match="train-dir"):
        _preflight(["--auto", "tune", "--train-dir", ""])
    # the clean form passes preflight (superstep 0 = auto is not a pin)
    assert _preflight(["--auto", "tune", "--train-dir", "d"]) is None
    assert _preflight(
        ["--auto", "tune", "--train-dir", "d", "--code", "qsgd",
         "--n-devices", "4", "--zero1"]
    ) is None
    # ring bucket size is a bit-identical LAYOUT knob: pinning it composes
    # with --auto tune (the ring candidates probe the pinned packing)
    assert _preflight(
        ["--auto", "tune", "--train-dir", "d",
         "--ring-bucket-size", "1024"]
    ) is None
    pinned_buckets = enumerate_candidates(
        has_codec=True, ways=4, bucket_options=(1024,)
    )
    assert {
        c["ring_bucket_size"]
        for c in pinned_buckets if c["aggregate"] == "ring"
    } == {1024}


# ------------------------------------------------------- grid-search artifact


def test_grid_search_writes_partial_json_artifact(tmp_path, capsys):
    from atomo_tpu.cli import main

    art = tmp_path / "grid.json"
    rc = main([
        "tune", "--synthetic", "--dataset", "mnist", "--network", "LeNet",
        "--batch-size", "8", "--tuning-steps", "2", "--window", "2",
        "--grid", "0.1,0.01", "--train-dir", str(tmp_path),
        "--artifact", str(art), "--eval-freq", "0",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "best lr:" in out  # the regex-parsed log contract is intact
    doc = json.loads(art.read_text())
    assert doc["kind"] == "lr_grid" and doc["complete"] is True
    assert [r["lr"] for r in doc["rows"]] == [0.1, 0.01]
    for r in doc["rows"]:
        assert r["mean_loss"] is None or math.isfinite(r["mean_loss"])
        assert r["wall_s"] > 0
    assert doc["best"]["lr"] in (0.1, 0.01)
    # printed scores and artifact rows agree (one contract, two surfaces)
    for r in doc["rows"]:
        if r["mean_loss"] is not None:
            assert f"lr {r['lr']:g}: mean loss {r['mean_loss']:.4f}" in out


# ----------------------------------------------- acceptance drill (slow)


def _run_cli(argv, timeout=420):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "PYTHONPATH": _REPO_ROOT + os.pathsep + os.environ.get(
            "PYTHONPATH", ""
        ),
    }
    return subprocess.run(
        [sys.executable, "-m", "atomo_tpu.cli"] + argv,
        env=env, capture_output=True, text=True, timeout=timeout,
    )


@pytest.mark.slow
def test_auto_tune_trajectory_bit_identical_to_static(tmp_path):
    """The PR-7 acceptance drill: on the forced 4-dev CPU mesh,
    ``--auto tune`` probes, writes a complete tune_decision.json with
    predicted-vs-measured ms/step for every candidate, and the
    subsequent trajectory is bit-identical to launching the chosen
    config statically."""
    import jax
    import jax.numpy as jnp

    tuned = tmp_path / "tuned"
    static = tmp_path / "static"
    common = [
        "train", "--synthetic", "--dataset", "mnist", "--network",
        "LeNet", "--batch-size", "8", "--max-steps", "4", "--eval-freq",
        "0", "--save-freq", "2", "--log-interval", "1", "--n-devices",
        "4", "--code", "qsgd", "--quantization-level", "8", "--seed", "3",
    ]
    p = _run_cli(common + [
        "--train-dir", str(tuned), "--auto", "tune", "--tune-steps", "2",
        "--tune-reps", "1", "--tune-top", "2",
    ])
    assert p.returncode == 0, p.stderr[-3000:]
    doc = json.loads((tuned / "tune_decision.json").read_text())
    assert doc["complete"] is True
    win = doc["winner"]
    assert win and win["name"] and win["knobs"], doc
    # every candidate row carries a prediction; probed ones a measurement
    for r in doc["rows"]:
        assert isinstance(r.get("predicted_ms_per_step"), (int, float)), r
        if r.get("probed"):
            assert isinstance(r.get("measured_ms_per_step"), (int, float)), r
    # determinism: the artifact's rows re-decide to the same winner
    from atomo_tpu.tuning.autopilot import choose_winner as cw

    assert cw(doc["rows"])["name"] == win["name"]

    # the static equivalent: the winner's knobs as explicit flags
    knobs = win["knobs"]
    static_args = common + ["--train-dir", str(static)]
    if "aggregate" in knobs:
        static_args += ["--aggregate", knobs["aggregate"]]
    if knobs.get("overlap", "off") != "off":
        static_args += ["--overlap", knobs["overlap"]]
    static_args += ["--superstep", str(knobs.get("superstep", 1))]
    if "ring_bucket_size" in knobs:
        static_args += ["--ring-bucket-size",
                        str(knobs["ring_bucket_size"])]
    p2 = _run_cli(static_args)
    assert p2.returncode == 0, p2.stderr[-3000:]

    # final checkpoints must match BIT FOR BIT (params, opt state, BN
    # stats, and — when the winner is delayed — the in-flight payload)
    from atomo_tpu.codecs import QsgdCodec
    from atomo_tpu.models import get_model
    from atomo_tpu.training import create_state, make_optimizer
    from atomo_tpu.training.checkpoint import load_checkpoint

    model = get_model("lenet", 10)
    opt = make_optimizer(
        "sgd", lr=0.01, lr_shrinkage=0.95, shrinkage_freq=50, momentum=0.5
    )
    tpl = jax.device_get(create_state(
        model, opt, jax.random.PRNGKey(3), jnp.zeros((8, 28, 28, 1))
    ))
    if knobs.get("overlap") == "delayed":
        from atomo_tpu.parallel.replicated import (
            DelayedState,
            _zero_carry_host,
        )

        tpl = DelayedState(
            train=tpl,
            carry=_zero_carry_host(
                QsgdCodec(bits=8, bucket_size=512), tpl.params, 4
            ),
        )
    a = load_checkpoint(str(tuned), tpl, step=4)
    b = load_checkpoint(str(static), tpl, step=4)
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    assert all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    ), "tuned trajectory is not bit-identical to the static equivalent"

    # a resumed tuned run (the supervised-restart path) must reuse the
    # recorded decision instead of re-probing: probe timings vary, and a
    # different winner could not resume this program family's checkpoints
    p3 = _run_cli(common + [
        "--train-dir", str(tuned), "--auto", "tune", "--tune-steps", "2",
        "--tune-reps", "1", "--tune-top", "2", "--max-steps", "6",
        "--resume",
    ])
    assert p3.returncode == 0, p3.stderr[-3000:]
    assert "resuming with the recorded decision" in p3.stdout
    assert "Autopilot probe [" not in p3.stdout  # no re-probe happened
    assert f"--auto tune -> {win['name']}" in p3.stdout


@pytest.mark.slow
def test_distributed_loop_retunes_on_injected_drift(tmp_path):
    """Loop wiring: a tuner whose drift detector is primed to fire sees
    the re-probe executed at the next checkpoint boundary, the incident
    logged, and the step program rebuilt onto the probed-better mode."""
    import jax

    from atomo_tpu.codecs import QsgdCodec
    from atomo_tpu.data import BatchIterator, SPECS, synthetic_dataset
    from atomo_tpu.models import get_model
    from atomo_tpu.parallel import distributed_train_loop, make_mesh
    from atomo_tpu.training import make_optimizer
    from atomo_tpu.utils.tracing import IncidentLog

    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.9)
    ds = synthetic_dataset(SPECS["mnist"], True, size=64)
    it = BatchIterator(ds, 8, seed=0)
    mesh = make_mesh(4)
    # a probe that always says ring is faster, and a PRE-ARMED pending
    # alarm (real wall-times FALL after the compile head, so a genuine
    # drift cannot be staged in a 6-step run — the detector math itself
    # is covered by the pure-fold tests above): the loop must execute
    # the re-probe at the first save boundary and flip gather -> ring
    tuner = OnlineRetuner(
        probe_fn={"gather": 50.0, "ring": 1.0}.__getitem__,
    )
    tuner.pending = "step_time_drift"
    distributed_train_loop(
        model, opt, mesh, it,
        codec=QsgdCodec(bits=8, bucket_size=512), aggregate="gather",
        max_steps=6, eval_freq=0, save_freq=2, seed=0,
        train_dir=str(tmp_path), log_fn=lambda *_: None, tuner=tuner,
    )
    recs = IncidentLog.read(str(tmp_path / "incidents.jsonl"))
    drift = [r for r in recs if r["cause"] == "perf_drift"]
    assert drift, recs
    assert drift[0]["action"] == "retune->ring"
    assert drift[0]["step"] % 2 == 0  # snapped to the save cadence
    assert tuner.switches == 1


def test_tune_error_feedback_probes_narrowed_space(monkeypatch, tmp_path):
    """EF x autopilot (ISSUE-17 satellite): --error-feedback runs ARE
    tunable — the ladder narrows to the flat blocking programs EF
    composes with, every probe builds the EF step, and the bias
    contract is recorded (rows + meta carry error_feedback="on"; probed
    rows carry the wall-clock-only probe_note)."""
    import atomo_tpu.tuning.autopilot as ap

    seen_ef = []

    def fake_probe(cand, **kw):
        seen_ef.append(kw.get("error_feedback"))
        return {
            **cand, "probed": True, "sync_ok": True,
            "measured_ms_per_step": 10.0 + len(cand["name"]),
            "probe_wall_s": 0.1,
        }

    monkeypatch.setattr("atomo_tpu.tuning.probe.probe_candidate",
                        fake_probe)
    import jax.numpy as jnp

    from atomo_tpu.codecs import QsgdCodec
    from atomo_tpu.models import get_model
    from atomo_tpu.training import make_optimizer
    from atomo_tpu.tuning.probe import model_init_fn

    model = get_model("lenet", 10)
    narrowed = []
    common = dict(
        model=model,
        optimizer=make_optimizer("sgd", lr=0.01, momentum=0.9),
        codec=QsgdCodec(bits=8, bucket_size=512),
        model_init_fn=model_init_fn(
            model, jnp.zeros((1, 28, 28, 1), jnp.float32)
        ),
        n_dev=4, sample_shape=(28, 28, 1), num_classes=10, batch=8,
        probe_top=3, probe_steps=1, probe_reps=1,
    )
    doc = ap.tune(
        artifact_path=str(tmp_path / "td.json"),
        error_feedback=True,
        # ask for everything EF conflicts with: the tuner must narrow
        # out loud, not build programs the step builder would refuse
        allow_overlap=True, allow_stream=True,
        allow_quorum=True, quorum_q=3,
        log_fn=narrowed.append,
        **common,
    )
    assert any("narrows the candidate space" in str(m) for m in narrowed)
    assert doc["complete"] is True
    assert doc["meta"]["error_feedback"] == "on"
    assert seen_ef and all(v is True for v in seen_ef)
    for r in doc["rows"]:
        assert r["error_feedback"] == "on"
        assert r["overlap"] == "off"
        # stream encode composes with the residual carry and stays in;
        # the conflict-matrix axes are out
        assert "+q" not in r["name"] and "+sp" not in r["name"]
        assert "hier[" not in r["name"]
        if r.get("probed"):
            assert "wall-clock only" in r["probe_note"]
    assert doc["winner"]["knobs"]["error_feedback"] == "on"
    # zero1's sharded optimizer state conflicts with the residual carry
    with pytest.raises(ValueError, match="zero1"):
        ap.tune(artifact_path=str(tmp_path / "td2.json"),
                error_feedback=True, zero1=True,
                log_fn=lambda *_: None, **common)
