"""Trace-based phase timeline — ``report timeline``.

The legacy ``--phase-metrics`` mode times the four phases as SEPARATE
blocking programs, which is why its conflict matrix rejects superstep,
stream-encode, sparse-rows, tune, delayed, elastic, and hierarchical —
it cannot observe any program we actually ship. The honest phase surface
for the FUSED step has existed since PR 3: the ``named_phase``
(``jax.named_scope``) regions — ``encode`` / ``exchange`` /
``decode_mean`` / ``ring_exchange_decode`` / ``delayed_*`` /
``hybrid_exchange`` — survive into the compiled program as HLO op-name
metadata, and a ``--profile-dir`` trace records every op execution with
its timing. This module turns that trace into the per-step phase
timeline ``--phase-metrics`` never could produce:

  1. PARSE: ``jax.profiler`` writes ``plugins/profile/<run>/*.xplane.pb``
     (a TSL XSpace protobuf). :func:`parse_xplane` is a minimal
     stdlib-only wire-format walker for exactly the fields we need — no
     tensorflow/tensorboard dependency is baked into the container, so
     the reader hand-walks varints instead of importing protos (the
     "stub or gate missing deps" rule).
  2. MAP: the ``/host:metadata`` plane carries each program's serialized
     HloProto; instruction name -> ``metadata.op_name`` gives every op
     its full scope path (``jit(step)/.../encode/...``) — the anchor the
     ``named_phase`` scopes planted (tested: a refactor that drops them
     fails tests/test_fabric_obs.py's scope-presence asserts).
  3. ATTRIBUTE: op events of the training-step module are segmented into
     dispatches (executions) by the modal-occurrence boundary op, then
     every op lands in a phase by its scope path. Per dispatch and per
     phase the timeline reports ``busy`` (summed op time), ``exposed``
     (the phase's interval union MINUS the compute union — time the
     phase held the device alone) and ``hidden`` (overlapped by
     compute) — live exposed-vs-hidden attribution for fused, superstep,
     stream-encode, and hybrid programs. Ring's fused
     ``ring_exchange_decode`` scope is attributed to ``exchange`` (its
     decode overlaps the transfer BY CONSTRUCTION — the fusion is the
     feature, and no trace can split it).
  4. JOIN: with a ``train_dir``, the spans are joined against
     ``metrics.jsonl`` by absolute time (the trace's
     ``profile_start_time`` is unix ns) and cross-checked: the recorded
     steps in the profiled window must partition evenly over the trace's
     dispatches (superstep blocks cover K steps each), and the device
     wall per step share must not exceed the recorded host step wall
     (device work cannot take longer than the host wall that contains
     it) — a violated fixture fails the check (tested).

A trace is an OBSERVATION artifact: this module never touches devices,
never imports jax — safe on a box that cannot reach the accelerator
(the ``report`` verb contract).
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, Optional

TIMELINE_REPORT_NAME = "timeline_report.json"

# scope token -> reported phase. ring_exchange_decode is exchange-with-
# decode-overlapped by construction (module docstring); the delayed_*
# scopes are the same phases consumed one step late.
PHASE_OF_SCOPE = {
    "encode": "encode",
    "exchange": "exchange",
    "hybrid_exchange": "exchange",
    "delayed_exchange": "exchange",
    "ring_exchange_decode": "exchange",
    "decode_mean": "decode",
    "delayed_decode_mean": "decode",
}
PHASES = ("encode", "exchange", "decode")


# ------------------------------------------------ minimal protobuf walk


def _walk(data: bytes) -> Iterator[tuple[int, int, object]]:
    """Yield ``(field_no, wire_type, value)`` over one message's fields.
    Varint (0), 64-bit (1), length-delimited (2) and 32-bit (5) cover
    every field XSpace/HloProto use; anything else is a parse error the
    caller treats as "no trace"."""
    i, n = 0, len(data)
    while i < n:
        tag = 0
        shift = 0
        while True:
            b = data[i]
            i += 1
            tag |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        fno, wt = tag >> 3, tag & 7
        if wt == 0:
            v = 0
            shift = 0
            while True:
                b = data[i]
                i += 1
                v |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
        elif wt == 2:
            ln = 0
            shift = 0
            while True:
                b = data[i]
                i += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            v = data[i:i + ln]
            i += ln
        elif wt == 5:
            v = data[i:i + 4]
            i += 4
        elif wt == 1:
            v = data[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield fno, wt, v


def _map_entry(data: bytes) -> tuple[Optional[int], bytes]:
    """A proto3 map<int64, Message> entry: key = 1, value = 2."""
    k, v = None, b""
    for fno, _wt, val in _walk(data):
        if fno == 1:
            k = val
        elif fno == 2:
            v = val
    return k, v


def _stat(data: bytes) -> tuple[Optional[int], object]:
    """An XStat: metadata_id = 1; value oneof double(2)/uint(3)/int(4)/
    str(5)/bytes(6)/ref(7)."""
    mid, val = None, None
    for fno, _wt, v in _walk(data):
        if fno == 1:
            mid = v
        elif fno == 2:
            val = struct.unpack("<d", v)[0]
        elif fno in (3, 4, 7):
            val = v
        elif fno == 5:
            val = v.decode("utf-8", "replace")
        elif fno == 6:
            val = v  # bytes (the Hlo Proto stat)
    return mid, val


def parse_xplane(path: str) -> dict:
    """The XSpace fields the timeline needs: per plane its name, stat /
    event metadata name tables, plane-level stats, and per line its
    name, ``timestamp_ns`` and events (metadata id, offset_ps,
    duration_ps, stats resolved to ``{stat name: value}``)."""
    with open(path, "rb") as f:
        data = f.read()
    planes = []
    for fno, _wt, pv in _walk(data):
        if fno != 1:  # XSpace.planes
            continue
        plane = {"name": "", "lines": [], "event_meta": {},
                 "stat_meta": {}, "stats": []}
        for f2, _w2, v2 in _walk(pv):
            if f2 == 2:
                plane["name"] = v2.decode("utf-8", "replace")
            elif f2 == 3:
                plane["lines"].append(v2)
            elif f2 == 4:
                k, ev = _map_entry(v2)
                em = {"name": None, "stats": []}
                for f3, _w3, v3 in _walk(ev):
                    if f3 == 2:
                        em["name"] = v3.decode("utf-8", "replace")
                    elif f3 == 5:
                        em["stats"].append(v3)
                plane["event_meta"][k] = em
            elif f2 == 5:
                k, sv = _map_entry(v2)
                for f3, _w3, v3 in _walk(sv):
                    if f3 == 2:
                        plane["stat_meta"][k] = v3.decode(
                            "utf-8", "replace"
                        )
            elif f2 == 6:
                plane["stats"].append(v2)
        # resolve lines/events against the name tables
        lines = []
        for lv in plane["lines"]:
            line = {"name": "", "timestamp_ns": 0, "events": []}
            for f3, _w3, v3 in _walk(lv):
                if f3 in (2, 11) and not line["name"]:
                    line["name"] = v3.decode("utf-8", "replace")
                elif f3 == 3:
                    line["timestamp_ns"] = int(v3)
                elif f3 == 4:
                    ev = {"metadata_id": None, "offset_ps": 0,
                          "duration_ps": 0, "stats": {}}
                    for f4, _w4, v4 in _walk(v3):
                        if f4 == 1:
                            ev["metadata_id"] = v4
                        elif f4 == 2:
                            ev["offset_ps"] = int(v4)
                        elif f4 == 3:
                            ev["duration_ps"] = int(v4)
                        elif f4 == 4:
                            mid, val = _stat(v4)
                            name = plane["stat_meta"].get(mid, mid)
                            ev["stats"][name] = val
                    em = plane["event_meta"].get(ev["metadata_id"]) or {}
                    ev["name"] = em.get("name")
                    line["events"].append(ev)
            lines.append(line)
        plane["lines"] = lines
        plane["stats"] = dict(
            (plane["stat_meta"].get(mid, mid), val)
            for mid, val in (_stat(s) for s in plane["stats"])
        )
        planes.append(plane)
    return {"path": path, "planes": planes}


def _hlo_scope_map(hlo_proto: bytes) -> dict:
    """``{instruction name: metadata.op_name}`` from a serialized
    HloProto (HloProto.hlo_module=1 -> computations=3 -> instructions=2;
    HloInstructionProto.name=1, metadata=7; OpMetadata.op_name=2)."""
    out = {}
    for f1, _w1, module in _walk(hlo_proto):
        if f1 != 1:
            continue
        for f2, _w2, comp in _walk(module):
            if f2 != 3:
                continue
            for f3, _w3, instr in _walk(comp):
                if f3 != 2:
                    continue
                name, op_name = None, None
                for f4, _w4, v4 in _walk(instr):
                    if f4 == 1:
                        name = v4.decode("utf-8", "replace")
                    elif f4 == 7:
                        for f5, _w5, v5 in _walk(v4):
                            if f5 == 2:
                                op_name = v5.decode("utf-8", "replace")
                if name and op_name:
                    out[name] = op_name
    return out


def scope_maps(space: dict) -> dict:
    """``{program_id: {"module": name, "scopes": {instr: op_name}}}``
    from the ``/host:metadata`` plane's Hlo Proto stats — the join key
    the device events' ``program_id`` stat points at."""
    out = {}
    for plane in space["planes"]:
        if plane["name"] != "/host:metadata":
            continue
        for pid, em in plane["event_meta"].items():
            scopes = {}
            for st in em.get("stats", []):
                _mid, val = _stat(st)
                if isinstance(val, bytes):
                    try:
                        scopes.update(_hlo_scope_map(val))
                    except (ValueError, IndexError):
                        continue  # a truncated proto is "no scopes"
            if scopes:
                out[pid] = {"module": em.get("name"), "scopes": scopes}
    return out


def phase_of(op_name: Optional[str]) -> str:
    """Classify one op's scope path into encode/exchange/decode/compute
    by its ``named_phase`` path components."""
    if op_name:
        for part in op_name.split("/"):
            ph = PHASE_OF_SCOPE.get(part)
            if ph:
                return ph
    return "compute"


def latest_trace(profile_dir: str) -> Optional[str]:
    """Newest ``*.xplane.pb`` under ``profile_dir`` (jax.profiler writes
    one per capture under plugins/profile/<timestamp>/)."""
    newest, newest_m = None, -1.0
    for base, _dirs, files in os.walk(profile_dir):
        for f in files:
            if f.endswith(".xplane.pb"):
                p = os.path.join(base, f)
                m = os.path.getmtime(p)
                if m > newest_m:
                    newest, newest_m = p, m
    return newest


# ---------------------------------------------------------- attribution


def _union_len_us(intervals: list[tuple[float, float]]) -> float:
    if not intervals:
        return 0.0
    ivs = sorted(intervals)
    total = 0.0
    cur_s, cur_e = ivs[0]
    for s, e in ivs[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    return total + (cur_e - cur_s)


def _intersect_len_us(a: list, b: list) -> float:
    """Length of the intersection of two interval UNIONS (both merged
    first so overlapping ops are not double counted)."""
    def merged(ivs):
        out = []
        for s, e in sorted(ivs):
            if out and s <= out[-1][1]:
                out[-1][1] = max(out[-1][1], e)
            else:
                out.append([s, e])
        return out

    ma, mb = merged(a), merged(b)
    i = j = 0
    total = 0.0
    while i < len(ma) and j < len(mb):
        s = max(ma[i][0], mb[j][0])
        e = min(ma[i][1], mb[j][1])
        if e > s:
            total += e - s
        if ma[i][1] < mb[j][1]:
            i += 1
        else:
            j += 1
    return total


def _segment_executions(events: list[dict]) -> list[list[dict]]:
    """Split one module's op events (time-sorted) into dispatches.

    A trace of a multi-device program carries every instruction once per
    DEVICE LINE per dispatch, and the devices run concurrently — pooling
    all lines and counting occurrences would over-split each dispatch
    into per-device fragments. So: segment on ONE reference line (the
    line with the most recorded busy time — a full participant of every
    dispatch), where an instruction OUTSIDE any scan loop executes
    exactly once per dispatch while scan-body ops (a superstep program's
    step body) run K times — the MINIMUM per-instruction occurrence
    count on that line is the dispatch count, and the earliest-starting
    minimum-count instruction is the boundary anchor. Every line's
    events are then assigned to dispatches by TIME against the anchor
    windows (a concurrent device may start an op fractionally before the
    reference anchor and land one dispatch early — tolerable noise for
    wall and busy sums, stated here rather than hidden)."""
    if not events:
        return []
    busy_by_line: dict = {}
    for ev in events:
        busy_by_line[ev.get("line")] = busy_by_line.get(
            ev.get("line"), 0.0
        ) + (ev["end_us"] - ev["start_us"])
    ref = max(busy_by_line, key=lambda ln: busy_by_line[ln])
    ref_events = [ev for ev in events if ev.get("line") == ref]
    counts: dict = {}
    for ev in ref_events:
        counts[ev["name"]] = counts.get(ev["name"], 0) + 1
    n_min = min(counts.values())
    boundary = next(
        ev["name"] for ev in ref_events if counts[ev["name"]] == n_min
    )
    anchors = [
        ev["start_us"] for ev in ref_events if ev["name"] == boundary
    ]
    import bisect

    execs: list[list[dict]] = [[] for _ in anchors]
    for ev in events:
        # window i covers [anchors[i], anchors[i+1]); pre-anchor events
        # (another device's head start) join the first window
        i = max(bisect.bisect_right(anchors, ev["start_us"]) - 1, 0)
        execs[i].append(ev)
    return [ex for ex in execs if ex]


def build_timeline(
    profile_dir: str, train_dir: Optional[str] = None
) -> dict:
    """The timeline document (module docstring): per-dispatch phase
    spans from the newest trace under ``profile_dir``, joined against
    ``train_dir/metrics.jsonl`` when given. Pure host-side file reads."""
    checks = []

    def check(name, ok, detail, skipped=False):
        checks.append({"name": name, "ok": bool(ok), "skipped": skipped,
                       "detail": detail})

    doc = {
        "kind": "timeline_report",
        "profile_dir": os.path.abspath(profile_dir),
        "trace": None,
        "module": None,
        "spans": [],
        "checks": checks,
        "consistent": True,
    }
    trace = latest_trace(profile_dir) if os.path.isdir(profile_dir) else None
    if trace is None:
        check("timeline_trace_found", False,
              f"no *.xplane.pb under {profile_dir!r} — run with "
              "--profile-dir to capture one")
        doc["consistent"] = False
        return doc
    doc["trace"] = trace
    try:
        space = parse_xplane(trace)
    except (ValueError, IndexError, OSError) as exc:
        check("timeline_trace_found", False,
              f"unparseable trace {trace!r}: {exc}")
        doc["consistent"] = False
        return doc
    maps = scope_maps(space)
    # the training-step module: the program whose scope map carries the
    # named_phase anchors; ties broken by total device time (an eval or
    # iota program must not shadow the step)
    phased = {
        pid: m for pid, m in maps.items()
        if any(phase_of(op) != "compute" for op in m["scopes"].values())
    }
    if not phased:
        check(
            "timeline_phases_present", False,
            "no named_phase scopes (encode/exchange/decode) in any traced "
            "program — the trace predates the fused step, or the "
            "anchors were dropped (tests/test_fabric_obs.py guards them)",
        )
        doc["consistent"] = False
        return doc

    # collect op events per program id across every line of every plane
    events_by_pid: dict = {}
    for plane in space["planes"]:
        for line in plane["lines"]:
            base_us = line["timestamp_ns"] / 1e3
            for ev in line["events"]:
                pid = ev["stats"].get("program_id")
                if pid is None or "hlo_op" not in ev["stats"]:
                    continue
                start = base_us + ev["offset_ps"] / 1e6
                events_by_pid.setdefault(pid, []).append({
                    "name": ev["name"],
                    # the (plane, line) identity: _segment_executions
                    # anchors on ONE device line so concurrent devices
                    # do not over-split dispatches
                    "line": (plane["name"], line["name"]),
                    "start_us": start,
                    "end_us": start + ev["duration_ps"] / 1e6,
                })
    # Task Environment anchors trace time to unix time
    start_ns = None
    for plane in space["planes"]:
        v = plane["stats"].get("profile_start_time")
        if isinstance(v, int):
            start_ns = v
    doc["profile_start_unix_s"] = (
        start_ns / 1e9 if start_ns is not None else None
    )

    def pid_key(pid):
        evs = events_by_pid.get(pid, [])
        return sum(e["end_us"] - e["start_us"] for e in evs)

    candidates = [p for p in phased if events_by_pid.get(p)]
    if not candidates:
        check(
            "timeline_phases_present", False,
            "named_phase scopes exist in the HLO metadata but no device "
            "op events were recorded for those programs — the profiled "
            "window may not have executed the fused step",
        )
        doc["consistent"] = False
        return doc
    pid = max(candidates, key=pid_key)
    doc["module"] = phased[pid]["module"]
    scopes = phased[pid]["scopes"]
    events = sorted(events_by_pid[pid], key=lambda e: e["start_us"])
    for ev in events:
        ev["phase"] = phase_of(scopes.get(ev["name"]))
    check(
        "timeline_phases_present", True,
        f"module {doc['module']} carries "
        f"{sum(1 for e in events if e['phase'] != 'compute')} phase-scoped "
        f"op executions across {len(events)} events",
    )

    spans = []
    for i, ex in enumerate(_segment_executions(events)):
        ivs: dict = {p: [] for p in PHASES}
        ivs["compute"] = []
        busy: dict = {p: 0.0 for p in PHASES}
        busy["compute"] = 0.0
        for ev in ex:
            ivs[ev["phase"]].append((ev["start_us"], ev["end_us"]))
            busy[ev["phase"]] += ev["end_us"] - ev["start_us"]
        t_start = min(e["start_us"] for e in ex)
        t_end = max(e["end_us"] for e in ex)
        span = {
            "dispatch": i,
            "t_start_us": round(t_start, 3),
            "wall_ms": round((t_end - t_start) / 1e3, 4),
            "compute_ms": round(busy["compute"] / 1e3, 4),
            "phases": {},
        }
        if doc["profile_start_unix_s"] is not None:
            span["t_start_unix_s"] = round(
                doc["profile_start_unix_s"] + t_start / 1e6, 3
            )
        for p in PHASES:
            union = _union_len_us(ivs[p])
            hidden = _intersect_len_us(ivs[p], ivs["compute"])
            span["phases"][p] = {
                "busy_ms": round(busy[p] / 1e3, 4),
                "exposed_ms": round((union - hidden) / 1e3, 4),
                "hidden_ms": round(hidden / 1e3, 4),
            }
        spans.append(span)
    doc["spans"] = spans
    doc["n_dispatches"] = len(spans)

    # ---- join against metrics.jsonl ---------------------------------
    if train_dir:
        from atomo_tpu.obs.recorder import FlightRecorder, metrics_path

        recs = FlightRecorder.read(metrics_path(train_dir))
        steps = [r for r in recs if r.get("kind") == "step"]
        window = next(
            (r for r in recs
             if r.get("kind") == "meta"
             and r.get("what") == "profile_window"),
            None,
        )
        if not steps:
            check(
                "timeline_joins_metrics", True,
                "no metrics.jsonl step records to join against "
                "(run with --obs-record to arm the recorder)",
                skipped=True,
            )
        else:
            if window is not None:
                # the exact artifact-side key the loops record when the
                # trace starts: which steps the profiled window covers
                lo = int(window["first_step"])
                hi = int(window["last_step"])
                joined = [
                    r for r in steps if lo <= int(r["step"]) <= hi
                ]
                basis = f"recorded profile_window steps {lo}..{hi}"
            else:
                # fallback for pre-meta artifacts: wall-clock overlap
                # (trace times are unix-anchored via profile_start_time)
                t_lo = min(
                    (s.get("t_start_unix_s") for s in spans
                     if s.get("t_start_unix_s") is not None),
                    default=None,
                )
                t_hi = max(
                    (s.get("t_start_unix_s", 0) + s["wall_ms"] / 1e3
                     for s in spans if s.get("t_start_unix_s") is not None),
                    default=None,
                )
                joined = [
                    r for r in steps
                    if t_lo is not None and t_hi is not None
                    and t_lo - 2.0 <= float(r.get("ts", 0)) <= t_hi + 30.0
                ]
                basis = "wall-clock overlap (no profile_window meta)"
            doc["joined_steps"] = [int(r["step"]) for r in joined]
            if joined and spans and len(joined) % len(spans) == 0:
                # informational only (a trailing async dispatch can leak
                # into the trace, so a non-dividing count is not an
                # error — the wall check below is the contract)
                doc["steps_per_dispatch"] = len(joined) // len(spans)
            if not joined:
                check(
                    "timeline_joins_metrics", False,
                    f"no metrics.jsonl step records join the trace "
                    f"({basis}) — the trace and the metrics stream "
                    "describe different runs",
                )
            else:
                missing = []
                if window is not None:
                    have = {int(r["step"]) for r in joined}
                    missing = [
                        s for s in range(lo, hi + 1) if s not in have
                    ]
                window_ms = sum(
                    float(r["step_ms"]) for r in joined
                    if r.get("step_ms")
                )
                max_wall = max(s["wall_ms"] for s in spans)
                # the quantitative cross-check: the LARGEST device
                # dispatch must fit inside the profiled window's
                # recorded host wall (device work cannot outlast the
                # host wall that dispatched and fetched it; 1.5x guard
                # band for fetch jitter). A metrics stream describing a
                # different — or doctored — run fails here (tested on a
                # violated fixture).
                ok_wall = (
                    window_ms <= 0
                    or max_wall <= window_ms * 1.5 + 1.0
                )
                ok = not missing and ok_wall
                check(
                    "timeline_joins_metrics", ok,
                    f"{len(joined)} recorded step(s) joined ({basis}); "
                    f"largest dispatch {max_wall:.3f} ms vs window host "
                    f"wall {window_ms:.3f} ms"
                    + (
                        f"; steps {missing} missing from metrics.jsonl "
                        "(pruned or never recorded)" if missing else ""
                    )
                    + (
                        "" if ok_wall else
                        " — the device span EXCEEDS the host wall that "
                        "dispatched it; the metrics stream does not "
                        "describe this trace"
                    ),
                )
    else:
        check("timeline_joins_metrics", True,
              "no --train-dir given; trace-only timeline", skipped=True)

    doc["consistent"] = all(c["ok"] for c in checks)
    return doc


def summarize_timeline(doc: dict) -> str:
    """The human rendering: one line per dispatch with the phase
    exposed/hidden split, then the check verdicts."""
    lines = [
        f"phase timeline: {doc.get('trace') or doc.get('profile_dir')}",
    ]
    if doc.get("module"):
        lines.append(
            f"  module {doc['module']}: {doc.get('n_dispatches')} "
            "dispatch(es)"
            + (
                f", {doc['steps_per_dispatch']} step(s)/dispatch"
                if doc.get("steps_per_dispatch") else ""
            )
        )
    for s in doc.get("spans", []):
        ph = s["phases"]
        bits = [
            f"{p} {ph[p]['busy_ms']}ms"
            f" (exposed {ph[p]['exposed_ms']}, hidden {ph[p]['hidden_ms']})"
            for p in PHASES
            if ph[p]["busy_ms"] > 0
        ]
        lines.append(
            f"  [dispatch {s['dispatch']}] wall {s['wall_ms']} ms, "
            f"compute {s['compute_ms']} ms"
            + (": " + "; ".join(bits) if bits else " (no phase ops)")
        )
    bad = [c["name"] for c in doc.get("checks", []) if not c["ok"]]
    ran = [c for c in doc.get("checks", []) if not c.get("skipped")]
    if doc.get("consistent"):
        lines.append(
            f"  consistency: OK ({len(ran)} check(s) ran, "
            f"{len(doc.get('checks', [])) - len(ran)} skipped)"
        )
    else:
        lines.append(f"  consistency: FAILED ({', '.join(bad)})")
        for c in doc.get("checks", []):
            if not c["ok"]:
                lines.append(f"    {c['name']}: {c['detail']}")
    return "\n".join(lines)
