"""ONE front-end over the LM model-axis program families.

Every LM parallelism layout — pure dp, dp x sp (ring/Ulysses), dp x tp
(Megatron), dp x ep (switch-MoE), dp x pp (GPipe), and the 3-D
dp x tp x sp composition — used to be wired up ad hoc at each call site
(``cli.cmd_lm``'s per-layout elif ladder, each test's private setup).
This module is the single resolution of a :class:`~atomo_tpu.mesh.spec.
MeshSpec` model-axis layout to a runnable program:

  * the mesh comes from ``spec.build()`` (the same axes tuples the legacy
    call sites handed ``make_mesh`` — same mesh, same compiled program);
  * the step comes from the layout's builder, compiled through
    :func:`atomo_tpu.parallel.compile.compile_step` (the one compile
    path), with the dp gradient exchange routed through the compressed
    stack when the caller hands a
    :class:`~atomo_tpu.parallel.lm.DpExchange`;
  * state/specs/token-sharding come bundled, so a driver (CLI, bench,
    test) asks for a layout by name instead of re-deriving the recipe.

The legacy builders stay importable and bit-identical — this is a
front-end, not a fork: ``build_model_axis_program("dp-tp", ...)`` returns
exactly ``make_tp_lm_train_step``'s program.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax

from atomo_tpu.mesh.spec import LAYOUT_MODEL_AXES, MeshSpec
from atomo_tpu.parallel.lm import DpExchange
from atomo_tpu.training.trainer import TrainState

__all__ = [
    "LAYOUT_MODEL_AXES",
    "ModelAxisProgram",
    "build_model_axis_program",
]


class ModelAxisProgram(NamedTuple):
    """A runnable model-axis LM program: everything a driver needs."""

    spec: MeshSpec
    mesh: Any
    state: TrainState
    state_specs: Optional[TrainState]  # None for the replicated layouts
    step: Callable  # jitted (state, key, tokens) -> (state, metrics)
    shard_tokens: Callable  # host (B, S) array -> device-sharded tokens


def build_model_axis_program(
    spec: MeshSpec,
    lm_config: dict,
    optimizer,
    rng,
    codec=None,
    *,
    attn_impl: str = "ring",
    num_microbatches: int = 2,
    capacity_factor: float = 1.25,
    aux_weight: float = 0.01,
    compute_dtype=None,
    aggregate: str = "gather",
    exchange: Optional[DpExchange] = None,
    devices=None,
    oracle_parts: bool = False,
) -> ModelAxisProgram:
    """Resolve a model-axis layout to its (mesh, state, specs, step,
    shard) bundle.

    ``spec`` comes from :meth:`MeshSpec.from_layout`; the dispatch key is
    ``spec.layout_name()`` (raises for shapes outside the LM grammar).
    ``exchange=None`` keeps each family's legacy dp tail byte-for-byte;
    a :class:`DpExchange` routes it through the full compressed stack
    (ring aggregation, stream-encode, per-leaf budget codecs).

    ``exchange.overlap == "delayed"`` threads the stale-by-one carry:
    ``state`` comes back as a :class:`~atomo_tpu.parallel.replicated.
    DelayedState` (``.params``/``.step`` read through, so driver loops
    are unchanged) and ``step`` consumes/returns it; ``state_specs``
    still describes the TRAIN half (checkpoint placement, reshard).
    ``oracle_parts=True`` (delayed only) swaps ``step`` for the
    ``{"produce", "apply"}`` two-program oracle the parity tests drive.
    Sizing errors (head/vocab/depth/expert divisibility) surface as the
    builders' ValueErrors, untranslated.
    """
    layout = spec.layout_name()
    mesh = spec.build(devices)
    delayed = exchange is not None and exchange.overlap == "delayed"
    kw = dict(
        compute_dtype=compute_dtype, aggregate=aggregate, exchange=exchange
    )
    if delayed:
        kw["oracle_parts"] = oracle_parts

    def finish(state, specs, step, shard_fn) -> ModelAxisProgram:
        if delayed:
            from atomo_tpu.parallel.lm import init_model_axis_delayed_state

            state = init_model_axis_delayed_state(mesh, state, codec)
        return ModelAxisProgram(spec, mesh, state, specs, step, shard_fn)

    if layout in ("dp", "dp-sp"):
        from atomo_tpu.models.transformer import TransformerLM
        from atomo_tpu.parallel.lm import make_lm_train_step, shard_tokens
        from atomo_tpu.parallel.replicated import replicate_state
        from atomo_tpu.training import create_state

        sample = jax.numpy.zeros((1, lm_config["max_len"]), jax.numpy.int32)
        state = create_state(TransformerLM(**lm_config), optimizer, rng, sample)
        state = replicate_state(mesh, state)
        step = make_lm_train_step(
            lm_config, optimizer, mesh, codec, attn_impl=attn_impl, **kw
        )
        return finish(state, None, step, lambda t: shard_tokens(mesh, t))

    if layout == "dp-tp":
        from atomo_tpu.parallel.tp import (
            create_tp_lm_state, make_tp_lm_train_step, shard_tp_tokens,
        )

        state, specs = create_tp_lm_state(mesh, lm_config, optimizer, rng)
        step = make_tp_lm_train_step(
            lm_config, optimizer, mesh, specs, codec, **kw
        )
        return finish(state, specs, step, lambda t: shard_tp_tokens(mesh, t))

    if layout == "dp-tp-sp":
        from atomo_tpu.parallel.tp import (
            create_tp_lm_state, make_tp_sp_lm_train_step,
        )
        from atomo_tpu.parallel.common import shard_tokens_with_spec
        from jax.sharding import PartitionSpec as P

        state, specs = create_tp_lm_state(mesh, lm_config, optimizer, rng)
        step = make_tp_sp_lm_train_step(
            lm_config, optimizer, mesh, specs, codec,
            attn_impl=attn_impl, **kw
        )
        return finish(
            state, specs, step,
            lambda t: shard_tokens_with_spec(mesh, t, P("dp", "sp")),
        )

    if layout == "dp-ep":
        from atomo_tpu.parallel.moe import (
            create_moe_lm_state, make_moe_lm_train_step, shard_moe_tokens,
        )

        state, specs = create_moe_lm_state(mesh, lm_config, optimizer, rng)
        step = make_moe_lm_train_step(
            lm_config, optimizer, mesh, specs, codec,
            capacity_factor=capacity_factor, aux_weight=aux_weight, **kw
        )
        return finish(state, specs, step, lambda t: shard_moe_tokens(mesh, t))

    if layout == "dp-pp":
        from atomo_tpu.parallel.pp import (
            create_pp_lm_state, make_pp_lm_train_step, shard_pp_tokens,
        )

        state, specs = create_pp_lm_state(mesh, lm_config, optimizer, rng)
        step = make_pp_lm_train_step(
            lm_config, optimizer, mesh, specs, codec,
            num_microbatches=num_microbatches, **kw
        )
        return finish(state, specs, step, lambda t: shard_pp_tokens(mesh, t))

    raise ValueError(  # pragma: no cover - layout_name() guards this
        f"unhandled layout {layout!r}"
    )
