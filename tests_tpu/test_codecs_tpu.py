"""Real-TPU compile + correctness coverage for the SVD codec hot path and
the distributed step program.

The CPU suite proves semantics; these prove the SAME programs lower through
XLA:TPU — the class of gap round 2 exposed for QSGD (code that only runs on
hardware had zero hardware coverage). Everything here auto-skips off-TPU
(tests_tpu/conftest.py).
"""

import jax
import jax.numpy as jnp
import numpy as np

from atomo_tpu.codecs import SvdCodec, encode_tree, decode_tree
from atomo_tpu.models import get_model
from atomo_tpu.training import create_state, make_optimizer, make_train_step


def test_default_svd_codec_roundtrip_on_chip():
    """The default codec config (auto sketch + residual probes) on a
    conv-sized gradient: encode → decode on the chip, sane output."""
    codec = SvdCodec(rank=3)
    g = jax.random.normal(jax.random.PRNGKey(0), (512, 512), jnp.float32)
    rt = jax.jit(
        lambda k, x: codec.decode(codec.encode(k, x), (512, 512))
    )
    out = np.asarray(rt(jax.random.PRNGKey(1), g))
    assert np.isfinite(out).all()
    # rank-3+2probes of a noise matrix: reconstruction is sparse in energy
    # but must correlate positively in expectation over keys
    acc = np.zeros_like(out)
    for i in range(16):
        acc += np.asarray(rt(jax.random.PRNGKey(10 + i), g))
    corr = np.corrcoef(acc.ravel(), np.asarray(g).ravel())[0, 1]
    assert corr > 0.1, f"mean decode uncorrelated with input: {corr}"


def test_resnet18_compressed_train_step_on_chip():
    """One full compressed train step (fwd/bwd + encode_tree + decode_tree +
    update) compiles and runs on the chip with finite loss."""
    model = get_model("resnet18", 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.9)
    rng = jax.random.PRNGKey(0)
    images = jax.random.uniform(rng, (16, 32, 32, 3), jnp.float32)
    labels = jax.random.randint(rng, (16,), 0, 10)
    state = create_state(model, opt, rng, images)
    step = make_train_step(model, opt, codec=SvdCodec(rank=3))
    state, m = step(state, jax.random.PRNGKey(1), images, labels)
    assert np.isfinite(float(m["loss"]))
    assert int(m["msg_bytes"]) > 0


def test_bf16_train_step_on_chip():
    """The --bf16 step (bf16 MXU compute, f32 master state) on hardware."""
    model = get_model("resnet18", 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.9)
    rng = jax.random.PRNGKey(0)
    images = jax.random.uniform(rng, (16, 32, 32, 3), jnp.float32)
    labels = jax.random.randint(rng, (16,), 0, 10)
    state = create_state(model, opt, rng, images)
    step = make_train_step(
        model, opt, codec=SvdCodec(rank=3), compute_dtype=jnp.bfloat16
    )
    state, m = step(state, jax.random.PRNGKey(1), images, labels)
    assert np.isfinite(float(m["loss"]))
    for leaf in jax.tree_util.tree_leaves(state.params):
        assert leaf.dtype == jnp.float32


def test_encode_tree_bucketed_on_chip():
    """The production bucketed/vmapped encode over a small pytree."""
    rng = jax.random.PRNGKey(5)
    params = {
        "a": jax.random.normal(rng, (64, 64)),
        "b": jax.random.normal(jax.random.fold_in(rng, 1), (64, 64)),
        "c": jax.random.normal(jax.random.fold_in(rng, 2), (40,)),
    }
    codec = SvdCodec(rank=2)
    payloads, stats = encode_tree(codec, rng, params)
    decoded = decode_tree(codec, payloads, params)
    for leaf in jax.tree_util.tree_leaves(decoded):
        assert np.isfinite(np.asarray(leaf)).all()
    assert stats.payload_bytes < stats.dense_bytes


# ----------------------------------------------------- round-4 codec paths


def test_gram_svd_on_chip():
    """The gram factorization (eigh of the small-side Gram — the round-4
    replacement for iterative SVD on small matrices and the Bernoulli
    modes) compiles and reconstructs on hardware, both orientations."""
    for shape in [(32, 54), (54, 32)]:
        mat = jax.random.normal(jax.random.PRNGKey(2), shape) * 0.3
        u, s, vt = jax.jit(SvdCodec._gram_svd)(mat)
        rec = np.asarray((u * s[None, :]) @ vt)
        np.testing.assert_allclose(rec, np.asarray(mat), atol=5e-4)


def test_cholesky_qr_zero_block_on_chip():
    """TPU flushes subnormals to zero: the CholeskyQR jitter must survive
    that (code-review r4 finding — 10*eps*tiny would flush and revive the
    cholesky(0) NaN). A zero matrix through the full randomized encode
    must produce a finite all-zero decode ON HARDWARE."""
    q = jax.jit(SvdCodec._orthonormalize)(jnp.zeros((128, 8)))
    assert np.isfinite(np.asarray(q)).all()
    codec = SvdCodec(rank=3, algorithm="randomized")
    rt = jax.jit(lambda k, x: codec.decode(codec.encode(k, x), (128, 128)))
    out = np.asarray(rt(jax.random.PRNGKey(0), jnp.zeros((128, 128))))
    np.testing.assert_allclose(out, 0.0, atol=1e-6)


def test_bf16_wire_on_chip():
    """wire_dtype=bfloat16: the stochastic-round bitcast chain
    (bitcast_convert_type + random.bits uint16 + mask) must lower through
    Mosaic/XLA:TPU, halve the payload, and decode finite."""
    from atomo_tpu.codecs import payload_nbytes

    codec32 = SvdCodec(rank=3)
    codec16 = SvdCodec(rank=3, wire_dtype="bfloat16")
    g = jax.random.normal(jax.random.PRNGKey(3), (256, 256), jnp.float32)
    p32 = jax.jit(codec32.encode)(jax.random.PRNGKey(4), g)
    p16 = jax.jit(codec16.encode)(jax.random.PRNGKey(4), g)
    assert p16.u.dtype == jnp.bfloat16
    assert payload_nbytes(p16) < 0.6 * payload_nbytes(p32)
    out = np.asarray(
        jax.jit(lambda p: codec16.decode(p, (256, 256)))(p16)
    )
    assert np.isfinite(out).all() and (out != 0).any()


def test_stochastic_round_unbiased_on_chip():
    """E[stochastic_round(x)] == x must hold for the HARDWARE rounding
    path (bit arithmetic on the chip), not just the CPU interpreter."""
    from atomo_tpu.codecs.svd import stochastic_round

    x = jax.random.normal(jax.random.PRNGKey(5), (2048,)) * 2.3
    keys = jax.random.split(jax.random.PRNGKey(6), 512)
    rounded = jax.jit(
        jax.vmap(lambda k: stochastic_round(k, x).astype(jnp.float32))
    )(keys)
    mean = np.asarray(jnp.mean(rounded, axis=0))
    np.testing.assert_allclose(mean, np.asarray(x), rtol=2e-3, atol=1e-5)


def test_bernoulli_budget_gram_on_chip():
    """Config 5's sampler (bernoulli_budget, now on the gram path) on a
    resnet110-sized conv matricization: static payload, finite decode."""
    codec = SvdCodec(rank=3, sample="bernoulli_budget")
    g = jax.random.normal(jax.random.PRNGKey(7), (3, 3, 64, 64))
    p = jax.jit(codec.encode)(jax.random.PRNGKey(8), g)
    assert p.coeff.shape == (7,)
    out = np.asarray(
        jax.jit(lambda q: codec.decode(q, (3, 3, 64, 64)))(p)
    )
    assert np.isfinite(out).all()
