"""The host-side quorum rig: schedule, wait, record, replay.

The compiled quorum step is schedule-agnostic — it consumes a per-step
(n_dev,) staleness-assignment vector as a traced input. This rig is the
single producer of that vector:

  * LIVE: derive it from the chaos ``slow@S:R:SEC`` table (a pure
    function of step — quorum.schedule), sleep the exposed wait the
    quorum floor implies (the rig OWNS the wait; the chaos blocking
    sleep ``maybe_sleep_replica`` stands down when a rig is armed),
    append the record to ``arrival_schedule.jsonl``;
  * REPLAY (``--replay-arrivals``): read the vectors back from a
    recorded schedule — wait-free, because the trajectory depends only
    on the vectors — and re-record them verbatim into this run's own
    artifact, so a replayed run's train_dir is as complete as the
    original's.

Every DROPPED entry lands one ``staleness_exceeded`` incident (action
'drop', the offending replica as target) — the 'never a silent stale
apply' half of the staleness contract, auditable by ``report``'s
``quorum_schedule_consistent`` check.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

from atomo_tpu.quorum.artifact import (
    append_record,
    prune_schedule_after,
    read_schedule,
    schedule_path,
)
from atomo_tpu.quorum.schedule import DROPPED, staleness_vector


class QuorumRig:
    def __init__(
        self,
        config,
        *,
        n_dev: int,
        train_dir: Optional[str] = None,
        chaos=None,
        incidents=None,
        replay_path: Optional[str] = None,
        log_fn=print,
    ):
        if config.quorum > n_dev:
            raise ValueError(
                f"--quorum {config.quorum} exceeds the {n_dev}-replica "
                "mesh: a step can never collect more arrivals than there "
                "are replicas"
            )
        self.config = config
        self.n_dev = n_dev
        self.train_dir = train_dir
        self.incidents = incidents
        self.log_fn = log_fn
        self.faults = ()
        if chaos is not None and not chaos.membership_epoch:
            # die@'s epoch keying: a shrunken/re-grown world starts clean
            self.faults = chaos.config.slow_replica_faults
        self._replay: Optional[dict[int, dict]] = None
        if replay_path:
            meta, arrivals = read_schedule(replay_path)
            if not arrivals:
                raise ValueError(
                    f"--replay-arrivals {replay_path!r}: no arrival "
                    "records found (not a recorded quorum schedule?)"
                )
            self._check_meta(meta, replay_path)
            self._replay = arrivals
        self._own_path = None
        if train_dir:
            self._own_path = schedule_path(train_dir)
            rp = os.path.abspath(replay_path) if replay_path else None
            if rp == os.path.abspath(self._own_path):
                # replaying a dir's own schedule in place: reading and
                # re-appending the same file would duplicate every line
                self._own_path = None
            else:
                meta, _ = read_schedule(self._own_path)
                self._check_meta(meta, self._own_path)
                if meta is None:
                    append_record(self._own_path, self._meta_record())

    def _meta_record(self) -> dict:
        return {
            "kind": "meta",
            "what": "quorum_config",
            "quorum": self.config.quorum,
            "staleness": self.config.staleness,
            "n_replicas": self.n_dev,
            "period_s": self.config.period_s,
        }

    def _check_meta(self, meta: Optional[dict], path: str) -> None:
        """Refuse knobs that disagree with a recorded schedule: vectors
        derived under one (Q, K, N, period) silently mean something else
        under another — the decision_reusable discipline, applied to the
        arrival artifact itself."""
        if meta is None:
            return
        want = self._meta_record()
        for k in ("quorum", "staleness", "n_replicas", "period_s"):
            if meta.get(k) != want[k]:
                raise ValueError(
                    f"quorum schedule {path!r} was recorded with "
                    f"{k}={meta.get(k)!r} but this run sets {want[k]!r}; "
                    "match the recorded knobs or remove the artifact — "
                    "refusing to mix schedules"
                )

    def prune_past(self, step: int) -> None:
        """Resume discipline (the flight recorder's): cut the killed
        attempt's recorded tail past the restart checkpoint so the
        replayed steps re-record their lines instead of duplicating."""
        if self.train_dir and self._own_path is not None:
            prune_schedule_after(self.train_dir, step)

    def begin_step(self, step: int) -> np.ndarray:
        """Produce step ``step``'s staleness-assignment vector: sleep the
        exposed wait (live mode), record, incident every drop. Returns
        the (n_dev,) int32 vector the compiled step consumes."""
        if self._replay is not None:
            rec = self._replay.get(step)
            if rec is None:
                raise ValueError(
                    f"--replay-arrivals: recorded schedule has no step "
                    f"{step} — the replay ran past (or resumed before) "
                    "the recorded run's range"
                )
            sigma = [int(x) for x in rec["staleness"]]
            if len(sigma) != self.n_dev:
                raise ValueError(
                    f"--replay-arrivals: step {step} records "
                    f"{len(sigma)} replicas, this run has {self.n_dev}"
                )
            drops = [(r, None) for r, s in enumerate(sigma) if s == DROPPED]
        else:
            sigma, exposed, drops = staleness_vector(
                step,
                n_dev=self.n_dev,
                quorum=self.config.quorum,
                staleness=self.config.staleness,
                faults=self.faults,
                period_s=self.config.period_s,
            )
            if exposed > 0:
                # the rig owns the straggler wait: Q-th-arrival exposure,
                # not the blocking max — this sleep IS the measured cost
                # bench config 17 compares against the blocking baseline
                time.sleep(exposed)
            rec = {
                "kind": "arrival",
                "step": step,
                "staleness": list(sigma),
                "kept": sum(1 for s in sigma if s >= 0),
                "dropped": sum(1 for s in sigma if s == DROPPED),
                "exposed_wait_ms": round(exposed * 1e3, 3),
            }
        if self._own_path is not None:
            append_record(self._own_path, rec)
        if self.incidents is not None:
            for rep, avail in drops:
                detail = {"bound": self.config.staleness}
                if avail is not None:
                    detail["available_staleness"] = avail
                self.incidents.append(
                    "staleness_exceeded",
                    action="drop",
                    step=step,
                    target=rep,
                    **detail,
                )
        return np.asarray(sigma, np.int32)
