"""Analytic comm-cost model invariants (atomo_tpu/utils/comm_model.py).

The measured side lives in scripts/comm_crossover.py (8-device exchange
timings); these tests pin the model algebra the bench rows embed.
"""

import math

from atomo_tpu.utils.comm_model import (
    crossover_bandwidth,
    crossover_report,
    gather_buffer_bytes,
    max_beneficial_ways,
    ring_allgather_wire_bytes,
    ring_allreduce_wire_bytes,
    ring_stream_wire_bytes,
)

D = 44.7e6  # dense ResNet-18 gradient bytes
P = 0.62e6  # rank-3 payload bytes


def test_wire_byte_formulas():
    # all-reduce saturates at 2D as N grows; all-gather grows ~linearly
    assert ring_allreduce_wire_bytes(D, 2) == D
    assert abs(ring_allreduce_wire_bytes(D, 1 << 20) - 2 * D) < 1e-3 * D
    assert ring_allgather_wire_bytes(P, 8) == P * 7


def test_ring_stream_wire_and_buffer_accounting():
    """PR-3 Msg(MB) honesty: ring mode's wire = the N-1 ppermute payload
    hops (exactly the ring all_gather's hop traffic) PLUS the dense/N
    segment all_gather it pays for exact cross-chip determinism; the win
    it buys is the O(N·payload) gathered buffer never existing."""
    n = 8
    assert ring_stream_wire_bytes(P, D, n) == (
        ring_allgather_wire_bytes(P, n) + D * (n - 1) / n
    )
    # ring ALWAYS moves more wire than gather — the accounting must never
    # pretend otherwise (the model's stated reason ring is a memory/
    # overlap tool, not a bytes tool)
    for ways in (2, 8, 64, 256):
        assert ring_stream_wire_bytes(P, D, ways) > ring_allgather_wire_bytes(
            P, ways
        )
    # the buffer ring deletes grows linearly with N; dense-gradient-sized
    # at exactly N = byte reduction
    assert gather_buffer_bytes(P, 8) == 8 * P
    n_eq = D / P
    assert abs(gather_buffer_bytes(P, n_eq) - D) < 1e-6 * D


def test_max_beneficial_ways_is_twice_reduction():
    red = D / P
    assert abs(max_beneficial_ways(D, P) - 2 * red) < 1e-9
    # beyond that N, the gather moves MORE bytes than the all-reduce
    n_star = int(max_beneficial_ways(D, P))
    assert ring_allgather_wire_bytes(P, n_star + 5) > ring_allreduce_wire_bytes(
        D, n_star + 5
    )
    assert ring_allgather_wire_bytes(P, n_star - 5) < ring_allreduce_wire_bytes(
        D, n_star - 5
    )


def test_crossover_bandwidth_semantics():
    tax = 2.5e-3
    bw = crossover_bandwidth(D, P, 8, tax)
    # below the crossover bandwidth compression must win, above it lose
    for frac, wins in ((0.5, True), (2.0, False)):
        b = bw * frac
        t_dense = ring_allreduce_wire_bytes(D, 8) / b
        t_svd = tax + ring_allgather_wire_bytes(P, 8) / b
        assert (t_svd < t_dense) == wins
    # zero tax -> compression wins at any bandwidth
    assert crossover_bandwidth(D, P, 8, 0.0) == float("inf")
    # negative byte saving (payload too big for this N) -> never wins
    assert crossover_bandwidth(D, D, 8, tax) is None


def test_crossover_report_shape_and_consistency():
    rep = crossover_report(D, P, dense_step_s=6.5e-3, svd_step_s=9.0e-3)
    assert rep["codec_tax_ms"] == 2.5
    assert [r["ways"] for r in rep["ways"]] == [8, 16, 32, 64]
    for row in rep["ways"]:
        for label, cell in row["implied"].items():
            # speedup must equal the ratio of the implied step times
            assert math.isclose(
                cell["speedup"], cell["dense_ms"] / cell["compressed_ms"], rel_tol=5e-3
            )
        # the slowest fabric must favor compression the most
        sp = [row["implied"][k]["speedup"] for k in
              ("ici_45GBps", "dcn_6.25GBps", "eth10G_1.25GBps")]
        assert sp[0] < sp[1] < sp[2]
    # compression must lose on ICI at single-chip tax, win on 10GbE (the
    # printed story of artifacts/COMM_CROSSOVER.md)
    w8 = rep["ways"][0]["implied"]
    assert w8["ici_45GBps"]["speedup"] < 1.0 < w8["eth10G_1.25GBps"]["speedup"]


def test_overlap_hidden_exposed_algebra():
    """PR-4: overlap hides min(comm, compute) and exposes the excess —
    the two must always sum back to the full comm chain, and clamp at 0."""
    from atomo_tpu.utils.comm_model import (
        overlap_exposed_comm_s,
        overlap_hidden_comm_s,
    )

    for comm, comp in ((0.004, 0.010), (0.010, 0.004), (0.0, 0.01),
                       (0.01, 0.0)):
        hidden = overlap_hidden_comm_s(comm, comp)
        exposed = overlap_exposed_comm_s(comm, comp)
        assert hidden == min(comm, comp)
        assert abs(hidden + exposed - comm) < 1e-12
        assert hidden >= 0 and exposed >= 0


def test_overlap_report_models_both_modes():
    """The delayed step is compute + exposed, the blocking step is
    compute + chain; hidden + exposed == chain; ring mode charges ring's
    honest wire. All JSON-safe."""
    import json

    from atomo_tpu.utils.comm_model import (
        overlap_report,
        ring_allgather_wire_bytes,
        ring_stream_wire_bytes,
    )

    rep = overlap_report(
        dense_bytes=D, payload_bytes=P, ways=8, fabric_bw=1.25e9,
        compute_s=6.5e-3, decode_s=1.0e-3,
    )
    assert rep["wire_mb_per_chip"] == round(
        ring_allgather_wire_bytes(P, 8) / 1e6, 3
    )
    assert abs(
        rep["hidden_ms"] + rep["exposed_ms"] - rep["comm_chain_ms"]
    ) < 1e-6
    assert abs(
        rep["blocking_step_ms"]
        - (rep["compute_ms"] + rep["comm_chain_ms"])
    ) < 1e-6
    assert abs(
        rep["delayed_step_ms"] - (rep["compute_ms"] + rep["exposed_ms"])
    ) < 1e-6
    # a comm chain that fits under compute leaves ZERO exposed: the
    # delayed step time equals the compute-only step
    small = overlap_report(
        dense_bytes=D, payload_bytes=P, ways=8, fabric_bw=45e9,
        compute_s=6.5e-3,
    )
    assert small["exposed_ms"] == 0.0
    assert small["delayed_step_ms"] == small["compute_ms"]
    ring = overlap_report(
        dense_bytes=D, payload_bytes=P, ways=8, fabric_bw=1.25e9,
        compute_s=6.5e-3, aggregate="ring",
    )
    assert ring["wire_mb_per_chip"] == round(
        ring_stream_wire_bytes(P, D, 8) / 1e6, 3
    )
    json.dumps(rep, allow_nan=False)


def test_codec_leaf_payload_bytes_prices_clamped_actual():
    """The fixed-budget honesty regression (ISSUE-15 satellite): analytic
    per-leaf pricing must equal jax.eval_shape over the REAL encode for
    every sampler/algorithm/wire-dtype — including the layers whose full
    rank CLAMPS the configured budget (r_full < rank, and r_full <
    rank + budget_slack for the Bernoulli-budget sampler) and the
    dense-fallback layers. A nominal rank+slack slot count would
    overprice exactly those layers."""
    import jax
    import jax.numpy as jnp

    from atomo_tpu.codecs import SvdCodec, payload_nbytes
    from atomo_tpu.utils.comm_model import codec_leaf_payload_bytes

    # shapes chosen to hit every branch: tiny (dense fallback), small
    # (clamped full rank below rank+slack), mid (gram), large
    # (randomized sketch + probe atoms)
    shapes = [(10,), (4, 3), (50,), (5, 5, 10, 20), (320, 50), (800, 500)]
    codecs = [
        SvdCodec(rank=3),
        SvdCodec(rank=3, algorithm="exact"),
        SvdCodec(rank=3, algorithm="randomized"),
        SvdCodec(rank=3, sample="bernoulli_budget", budget_slack=4),
        SvdCodec(rank=3, sample="bernoulli"),
        SvdCodec(rank=3, sample="topk"),
        SvdCodec(rank=3, wire_dtype="bfloat16"),
        SvdCodec(rank=12, sample="bernoulli_budget", budget_slack=6),
    ]
    for codec in codecs:
        for shape in shapes:
            analytic = codec_leaf_payload_bytes(codec, shape)
            ev = payload_nbytes(jax.eval_shape(
                lambda c=codec, s=shape: c.encode(
                    jax.random.PRNGKey(0), jnp.zeros(s, jnp.float32)
                )
            ))
            assert analytic == ev, (codec.sample, codec.algorithm,
                                    codec.wire_dtype, shape, analytic, ev)
    # the clamp is REAL for the bernoulli budget on a small matrix:
    # (50,) resizes to (8, 7) — full rank 7, far below 12 + 6 = 18
    # nominal slots. Under the near-square matricization a payload
    # clamped to full rank always REACHES the dense fallback
    # (r_full*(m+n+1) >= m*n whenever min(m,n) <= r_full), so the
    # clamped actual IS the exact 200-byte DensePayload — a nominal
    # 18-slot pricing would charge ~6x that
    bb = SvdCodec(rank=12, sample="bernoulli_budget", budget_slack=6)
    m, n, k_nom = 8, 7, 12 + 6
    nominal = (m * k_nom + k_nom * n) * 4 + k_nom * 4
    actual = codec_leaf_payload_bytes(bb, (50,))
    assert actual == 50 * 4  # the dense fallback: the clamped actual
    assert actual < nominal
    # eval_shape fallback path for codecs without analytic pricing
    from atomo_tpu.codecs import QsgdCodec

    q = QsgdCodec(bits=4, bucket_size=128)
    ev = payload_nbytes(jax.eval_shape(
        lambda: q.encode(
            jax.random.PRNGKey(0), jnp.zeros((320, 50), jnp.float32)
        )
    ))
    assert codec_leaf_payload_bytes(q, (320, 50)) == ev


def test_budget_candidates_emitted_and_priced():
    """The +ab candidate family: emitted only for plain blocking
    gather/ring points, named with the ab suffix, priced from the
    allocation's per-leaf pairs through the one honest accounting
    function."""
    from atomo_tpu.utils.comm_model import (
        enumerate_candidates,
        leaf_budget_totals,
        predict_step_s,
        rank_candidates,
    )

    lb = [(1000.0, 100.0), (2000.0, 150.0)]
    cands = enumerate_candidates(
        has_codec=True, ways=4, allow_budget=True,
        budget_leaf_budgets=lb, allow_stream=True,
    )
    ab = [c for c in cands if c.get("budget_alloc") == "variance"]
    assert ab and all("+ab" in c["name"] for c in ab)
    # only plain blocking gather/ring variants gain +ab
    for c in ab:
        assert c["aggregate"] in ("gather", "ring")
        assert c.get("overlap", "off") == "off"
        assert c.get("stream_encode") != "on"
    # pricing: the +ab candidate's wire comes from the allocation pairs
    d, p = leaf_budget_totals(lb)
    plain = dict(ab[0])
    plain.pop("budget_alloc")
    t_ab = predict_step_s(
        ab[0], dense_bytes=d, payload_bytes=9e9, ways=4, fabric_bw=1e9,
        compute_s=1e-3, tax_s=0.0, budget_leaf_budgets=lb,
    )
    t_plain = predict_step_s(
        plain, dense_bytes=d, payload_bytes=p, ways=4, fabric_bw=1e9,
        compute_s=1e-3, tax_s=0.0,
    )
    assert t_ab == t_plain  # same bytes -> same prediction; the bogus
    # whole-tree payload_bytes=9e9 was ignored for the +ab candidate
    rows = rank_candidates(
        cands, dense_bytes=d, payload_bytes=p, ways=4, fabric_bw=1e9,
        compute_s=1e-3, tax_s=0.0, budget_leaf_budgets=lb,
    )
    assert all("predicted_ms_per_step" in r for r in rows)
    # no budgets supplied -> no +ab variants (the flag alone is not
    # enough, the sparse precedent)
    none = enumerate_candidates(has_codec=True, ways=4, allow_budget=True)
    assert not [c for c in none if c.get("budget_alloc") == "variance"]


def test_winner_knobs_carries_budget_alloc():
    from atomo_tpu.tuning.autopilot import winner_knobs

    row = {"aggregate": "gather", "overlap": "off", "superstep": 1,
           "budget_alloc": "variance", "name": "gather+off+ab+k1"}
    assert winner_knobs(row)["budget_alloc"] == "variance"
