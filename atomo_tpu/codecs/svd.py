"""ATOMO's SVD codec: atomic gradient sparsification on the singular-value basis.

Reference behavior (src/codings/svd.py): reshape the gradient to 2-D
(`_resize_to_2d`, svd.py:12-28), take a thin SVD (svd.py:95), Bernoulli-sample
singular triplets with probabilities proportional to their singular values
(`_sample_svd`, svd.py:49-67: p_i = min(1, rank * s_i / sum(s)), recurse if
nothing kept), rescale kept values by 1/p_i for unbiasedness, ship the kept
(U, s, Vt) columns; decode = U @ diag(s) @ Vt reshaped back (svd.py:160-178).

TPU-first redesign — three sampling modes, all unbiased:

* ``fixed_k`` (default wire format): sample exactly ``rank`` atoms *with
  replacement*, atom i drawn with probability q_i = s_i / sum(s); estimator
  sum_j s_{i_j} / (rank * q_{i_j}) * u_{i_j} v_{i_j}^T. Unbiased
  (E = sum_i q_i * s_i/q_i u_i v_i^T / rank * rank = X) with a *static*
  payload shape — k_tot*(m + n + 1) floats where k_tot = rank, plus
  ``residual_probes`` extra probe atoms (default 2) whenever the matrix
  resolves to the randomized sketch (see SvdCodec) — which is what an XLA
  all_gather needs. The reference's variable-length Bernoulli keep-set
  cannot be expressed with static shapes without either padding to the
  full width or biased truncation.
* ``bernoulli_budget``: the reference's Bernoulli keep-without-replacement
  semantics (p_i = min(1, rank * s_i / sum(s)), kept atoms rescaled by
  1/p_i) packed into a *static* budget of k_max = rank + budget_slack
  atoms: sample the keep-mask, scatter the kept atoms into k_max padded
  slots (zero coefficients mark empty slots), and redraw (bounded) only in
  the Chernoff-rare event more than k_max atoms are kept. An empty keep is
  shipped as a zero payload — unlike the reference's recursion-on-empty
  (svd.py:61-63), which biases its estimator up by 1/(1-P(empty)). Real
  bytes win (k_max*(m+n+1) on the wire) with the reference's exact
  per-atom inclusion law.
* ``bernoulli`` (reference-faithful, full width): the same probabilities,
  keep-mask applied to the *full-width* factors. Payload is full-size (no
  bytes win) — used for in-process compression studies and as the oracle
  in unbiasedness tests, mirroring how the reference master uses
  deterministic top-k (random_sample=False, svd.py:109-113).

Deviation notes (SURVEY.md §7 'reference bug compatibility'): the reference's
encode-path name shadowing of the nuclear indicator (svd.py:97-101), the dead
code after return (svd.py:180-197) and the CUDA branch are not reproduced.

Round-4 TPU decomposition stack (VERDICT r3 next-round #3/#5 — the encode
tax): no code path chosen by "auto" runs an iterative LAPACK-style SVD
program anymore. Large matrices take the Halko sketch with CholeskyQR2
orthonormalization (Gram matmul + tiny Cholesky instead of serialized
Householder panels) and an eigh of the (k+p, k+p) sliver Gram; small
matrices and both Bernoulli modes take "gram" — the full spectrum via one
Gram matmul + eigh of the small side. Optional ``wire_dtype="bfloat16"``
ships u/vt stochastically rounded (E[wire] == factor) for a further ~2x
byte cut. Unbiasedness is preserved through all of it: the samplers need
only u@diag(s)@vt == mat (exact to fp for gram and, with residual probes,
for the sketch — see the invariant notes on _orthonormalize/_gram_svd),
never per-singular-value accuracy.
"""

from __future__ import annotations

import dataclasses
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp

from atomo_tpu.codecs.base import PRNGKey
from atomo_tpu.codecs.dense import DensePayload


class SvdPayload(NamedTuple):
    """Fixed-shape wire format: ``k`` sampled (and rescaled) atoms.

    Shape metadata (original tensor shape, padding) is static and travels
    out-of-band via the codec's decoder closure, never on the wire.
    """

    u: jax.Array  # (m, k) sampled left singular vectors
    coeff: jax.Array  # (k,) importance-sampling coefficients
    vt: jax.Array  # (k, n) sampled right singular vectors


class SvdMaskedPayload(NamedTuple):
    """Full-width masked factors (reference-faithful Bernoulli mode)."""

    u: jax.Array  # (m, r)
    s: jax.Array  # (r,) masked + 1/p rescaled singular values
    vt: jax.Array  # (r, n)


def _square_dims(total: int, cap: int) -> tuple[int, int]:
    """Near-square power-of-two matricization, capped at ``cap``.

    Picks m from the two powers of two bracketing sqrt(total) — whichever
    minimizes the rank-k payload factor m + ceil(total/m) (floor alone can
    land up to 2x under sqrt and cost ~25% extra wire bytes)."""
    if total <= 1:
        return 1, 1
    lo = 1 << int(math.floor(math.log2(math.sqrt(total))))
    candidates = [min(lo, cap), min(lo * 2, cap)]
    m = min(candidates, key=lambda c: c + -(-total // c))
    return m, -(-total // m)


def resize_to_2d(
    x: jax.Array, policy: str = "reference", max_min_dim: int = 512
) -> tuple[jax.Array, tuple[int, ...], int]:
    """Reshape an arbitrary-rank gradient to 2-D for SVD.

    ``policy="reference"`` follows `_resize_to_2d` (src/codings/svd.py:12-28):
      * scalars/0-d -> (1, 1)
      * 1-D (n,)    -> (n/2, 2) when n is even (reference assumes even); odd
                       sizes are zero-padded by one element first (deviation:
                       the reference would crash on odd n).
      * 2-D         -> unchanged
      * >=3-D (a, b, *c) -> (a*b/2, 2*prod(c)) when a*b even, else (a*b, prod(c))

    ``policy="square"`` (the TPU-first default on SvdCodec) flattens and
    zero-pads to a near-square (m, ceil(total/m)) with m a power of two
    capped at ``max_min_dim``. Rationale: a rank-k payload costs k*(m+n)
    floats, minimized at m == n == sqrt(total) — the reference's layouts
    (e.g. (9, cin*cout) for a flax conv kernel, (cout*cin/2, 2*kh*kw) for a
    torch one) cap the achievable byte reduction at small multiples, while
    near-square matricization reaches k*2*sqrt(total)/total. The power-of-two
    m keeps XLA tilings MXU-friendly; the cap bounds SVD cost (O(m^2 * n)).

    Returns (matrix, original_shape, pad) where ``pad`` is the number of
    zero elements appended to the flattened tensor before reshaping.
    """
    shape = tuple(x.shape)
    if policy == "square":
        total = int(x.size)
        m, n = _square_dims(total, max_min_dim)
        pad = m * n - total
        flat = x.reshape(-1)
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), x.dtype)])
        return flat.reshape(m, n), shape, pad
    if policy != "reference":
        raise ValueError(f"unknown resize policy {policy!r}")
    if x.ndim == 0:
        return x.reshape(1, 1), shape, 0
    if x.ndim == 1:
        n = shape[0]
        pad = n % 2
        if pad:
            x = jnp.concatenate([x, jnp.zeros((1,), x.dtype)])
        return x.reshape((n + pad) // 2, 2), shape, pad
    if x.ndim == 2:
        return x, shape, 0
    a, b = shape[0], shape[1]
    rest = 1
    for d in shape[2:]:
        rest *= d
    m = a * b
    if m % 2 == 0:
        return x.reshape(m // 2, 2 * rest), shape, 0
    return x.reshape(m, rest), shape, 0


def undo_resize(mat: jax.Array, orig_shape: tuple[int, ...], pad: int) -> jax.Array:
    """Inverse of :func:`resize_to_2d`."""
    flat = mat.reshape(-1)
    if pad:
        flat = flat[:-pad]
    return flat.reshape(orig_shape)


def stochastic_round(key: PRNGKey, x: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
    """Round f32 -> bf16 with E[result] == x (unbiased wire narrowing).

    Bit trick: add 16 uniform random low bits to the f32 pattern, then
    truncate to the bf16 prefix. Within a binade the mantissa grid is
    uniform, so P(round up) equals the fractional position between the two
    representable neighbours — exactly stochastic rounding; a carry out of
    the mantissa lands on the next binade's first value, which is the
    correct upper neighbour. Deterministic rounding would inject a
    *systematic* ~2^-9 relative bias into every shipped factor (the codec
    contract is unbiasedness); stochastic rounding converts it to zero-mean
    noise the same class as the sampling noise SGD already averages out.
    """
    if dtype != jnp.bfloat16:
        raise ValueError("stochastic_round supports bfloat16 wire narrowing")
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    r = jax.random.bits(key, x.shape, jnp.uint16).astype(jnp.uint32)
    out = (bits + r) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(out, jnp.float32).astype(jnp.bfloat16)


def _s_floor(s: jax.Array) -> jax.Array:
    """Divisor floor for factor rows recovered as (basis^T @ mat) / s.

    A plain ``tiny`` floor is unsafe on rank-deficient matrices: the true
    row norm equals s_i exactly, but a numerically-zero s_i divides f32
    noise (~eps*s_max) into ~1e32 rows whose products overflow downstream.
    Flooring at eps*s_max caps those rows near unit norm; the induced
    contribution error is bounded by eps*s_max per atom (the row's true
    mass), far below sampling noise. s must be sorted descending (s[0] =
    s_max; zero matrices degrade to the tiny floor and yield zero rows).
    """
    eps = jnp.finfo(s.dtype).eps
    return jnp.maximum(s, eps * s[0] + jnp.finfo(s.dtype).tiny)


def _safe_probs(s: jax.Array) -> jax.Array:
    """q_i = s_i / sum(s), falling back to uniform for an all-zero spectrum."""
    total = jnp.sum(s)
    r = s.shape[0]
    uniform = jnp.full_like(s, 1.0 / r)
    return jnp.where(total > 0, s / jnp.where(total > 0, total, 1.0), uniform)


def bernoulli_probs(s: jax.Array, rank: int) -> jax.Array:
    """Reference keep-probabilities (src/codings/svd.py:49-60).

    rank == 0: p_i = s_i / s_0 (relative to the largest singular value);
    rank >= 1: p_i = clip(rank * s_i / sum(s), 0, 1).
    """
    if rank == 0:
        p = s / jnp.maximum(s[0], jnp.finfo(s.dtype).tiny)
    else:
        p = rank * s / jnp.maximum(jnp.sum(s), jnp.finfo(s.dtype).tiny)
    return jnp.clip(p, 0.0, 1.0)


@dataclasses.dataclass(frozen=True)
class SvdCodec:
    """Atomic sparsification with a fixed atom budget (static wire shape).

    ``reshape``/``max_min_dim`` select the matricization (see resize_to_2d);
    tensors too small for SVD to beat dense (k*(m+n+1) >= total, e.g. BN
    scales and biases) are shipped as exact DensePayloads — the decision is
    static (shape-only) so both encode and decode agree at trace time.

    Default-sampler deviation note (VERDICT r2 weak #7): the reference's
    default inclusion law is Bernoulli (src/codings/svd.py:49-67); ours is
    ``fixed_k`` with-replacement importance sampling because its payload
    shape is static at ``rank`` atoms (+ ``residual_probes`` probe atoms
    when the sketch runs — 5 total at the rank-3 defaults), while the
    Bernoulli law needs k_max = rank + budget_slack padded slots
    (``bernoulli_budget``, 7 at the defaults, ~1.4x the fixed_k wire
    bytes) for the same expected atom count.
    Measured on the ResNet-18 convergence oracle (tests/test_convergence.py)
    both samplers track the uncompressed loss curve within the same
    tolerance; ``bernoulli_budget`` remains one flag away
    (--svd-sample bernoulli_budget) for reference-exact semantics.
    """

    rank: int = 3
    sample: str = "fixed_k"  # "fixed_k" | "bernoulli_budget" | "bernoulli" | "topk"
    reshape: str = "square"  # "square" | "reference"
    max_min_dim: int = 512
    algorithm: str = "auto"  # "auto" | "exact" | "randomized"
    oversample: int = 8  # sketch slack for the randomized algorithm
    power_iters: int = 1  # Halko power iterations (two extra matmuls + QR
    # each; tighten the sketch's top-subspace capture)
    residual_probes: int = 2  # Rademacher probe atoms restoring exact
    # unbiasedness of the sketched fixed_k estimator (see encode): without
    # them the sketch DISCARDS the spectral tail — on late-training
    # noise-like gradients that is most of the expected mass, and the LeNet
    # convergence oracle plateaus at ~8x the dense final loss (measured;
    # power iterations alone only got it to ~7x). Keep >= 2: a single
    # probe's variance sat just past the stability edge on the LeNet
    # recipe at lr 0.01 (diverged); 2 probes converged at 0.52x dense.
    auto_min_dim: int = 64  # "auto": randomized when min(m, n) >= this
    budget_slack: int = 4  # extra atom slots for bernoulli_budget (k_max = rank + slack)
    max_redraws: int = 4  # bounded resampling when the keep-set overflows k_max
    wire_dtype: str = "float32"  # "float32" | "bfloat16": factor dtype ON THE
    # WIRE. bfloat16 halves u/vt bytes (the payload is almost entirely
    # factors) via *stochastic* rounding so E[wire] == factor and the codec
    # stays unbiased (see stochastic_round); coeffs stay f32 — they carry
    # the 1/p importance weights whose relative error multiplies everything.
    name: str = "svd"

    def _resize(self, x: jax.Array):
        return resize_to_2d(x, policy=self.reshape, max_min_dim=self.max_min_dim)

    def _algorithm_for(self, m: int, n: int) -> str:
        """Resolve "auto" per matrix (static, shape-only decision).

        Default policy (VERDICT r2 next-round #3 + r3 next-round #3/#5):
        LAPACK-style ``exact`` SVD lowers to an iterative QDWH/Jacobi
        program on TPU and cost ~120 ms/step of pure encode overhead on
        batch-128 ResNet-18/v5e (130.4 ms vs 9.9 ms dense). So "auto"
        never picks it: matrices whose small side reaches ``auto_min_dim``
        take the Halko sketch ("randomized"); smaller ones take "gram" —
        the FULL spectrum via one Gram matmul + an eigh of the small side,
        the MXU-native way to get every singular triplet (see _gram_svd:
        reconstruction is exact to fp even where the tiny singular values
        are squared away, which is all the samplers need). The Bernoulli
        modes advertise the reference's inclusion law p_i = min(1,
        rank*s_i/sum(s)) over the full spectrum (src/codings/svd.py:49-67),
        so they use "gram" at every size rather than a sketch that would
        renormalize the law over rank+oversample triplets and bias 1/p_i.
        """
        if self.algorithm != "auto":
            return self.algorithm
        if self.sample in ("bernoulli", "bernoulli_budget"):
            return "gram"
        return "randomized" if min(m, n) >= self.auto_min_dim else "gram"

    @staticmethod
    def _orthonormalize(y: jax.Array, passes: int = 2) -> jax.Array:
        """CholeskyQR orthonormalization of a tall-skinny block (m, k).

        TPU-first replacement for Householder ``jnp.linalg.qr`` (round-3
        encode profile: 3 QRs per power iteration dominated the sketch):
        per pass, ONE (k, k) Gram matmul + a tiny Cholesky + a triangular
        solve — all MXU/VPU-native, no serialized panel reflectors. Two
        passes (CholeskyQR2) reach fp-precision orthonormality for block
        condition up to ~1/sqrt(eps); an eps*trace jitter keeps the
        Cholesky PD for degenerate/zero blocks (a zero gradient then
        yields q = 0, which downstream sampling handles as the all-zero
        spectrum).

        Invariant the codec rests on (tested): the sketch estimator is
        unbiased for ANY q, orthonormal or not. Algebra: the sampled atoms
        estimate u@diag(s)@vt = q@ub@ub^T@(q^T mat) = q q^T mat (ub from
        eigh is complete orthonormal), and the probe atoms estimate
        mat - u u^T mat = mat - q q^T mat — the sum telescopes to mat
        exactly. An ill-conditioned block therefore costs sketch QUALITY
        (variance), never bias; CholeskyQR2's occasional imperfection is
        benign where Householder QR's serialized cost never was.
        """
        hi = jax.lax.Precision.HIGHEST
        k = y.shape[1]
        for _ in range(passes):
            g = jnp.matmul(y.T, y, precision=hi)
            # the jitter must dominate the Gram's negative ROUNDING
            # eigenvalues (~eps * lambda_max * sqrt(k), observed up to
            # ~6*eps*lambda_max on rank-deficient sketches) or Cholesky
            # emits NaNs; 10*eps*trace clears that with margin since
            # trace >= lambda_max, at the cost of not orthonormalizing
            # directions below ~10*eps*trace — variance, never bias.
            # tiny is ADDED OUTSIDE the product (not to the trace): for a
            # zero block, 10*eps*tiny would be subnormal and TPU flushes
            # subnormals to zero, reviving the cholesky(0) NaN this
            # jitter exists to prevent; a bare tiny (smallest NORMAL)
            # survives the flush and yields q = 0 as documented
            jitter = (
                10.0 * jnp.finfo(y.dtype).eps * jnp.trace(g)
                + jnp.finfo(y.dtype).tiny
            )
            el = jnp.linalg.cholesky(g + jitter * jnp.eye(k, dtype=y.dtype))
            y = jax.lax.linalg.triangular_solve(
                el, y, left_side=False, lower=True, transpose_a=True
            )
        return y

    @staticmethod
    def _gram_svd(mat: jax.Array):
        """Full-spectrum factorization via eigh of the smaller Gram matrix.

        ``jnp.linalg.svd`` on TPU is an iterative QDWH program (polar
        iterations + eigh); forming min(m,n)^2 Gram once on the MXU and
        eigh-ing only that skips the polar iterations entirely. The cost:
        singular values below ~sqrt(eps)*s_max lose relative accuracy
        (they are squared away). That is harmless here — the samplers are
        unbiased for ANY factorization with u@diag(s)@vt == mat
        (importance sampling with matching coeff/probabilities; inclusion
        probabilities shift by O(sqrt(eps)) at worst), and reconstruction
        IS exact to fp: for m <= n every atom contributes
        s_i*u_i*(u_i^T mat / s_i) = u_i u_i^T mat and the u_i are a
        complete orthonormal basis from eigh, so the full sum telescopes
        to mat (mirror argument for m > n).
        """
        hi = jax.lax.Precision.HIGHEST
        m, n = mat.shape
        if m <= n:
            g = jnp.matmul(mat, mat.T, precision=hi)
            w, u = jnp.linalg.eigh(g)  # ascending
            w, u = w[::-1], u[:, ::-1]
            s = jnp.sqrt(jnp.clip(w, 0.0, None))
            vt = jnp.matmul(u.T, mat, precision=hi) / _s_floor(s)[:, None]
            return u, s, vt
        g = jnp.matmul(mat.T, mat, precision=hi)
        w, v = jnp.linalg.eigh(g)
        w, v = w[::-1], v[:, ::-1]
        s = jnp.sqrt(jnp.clip(w, 0.0, None))
        u = jnp.matmul(mat, v, precision=hi) / _s_floor(s)[None, :]
        return u, s, v.T

    def _svd(self, key: PRNGKey, mat: jax.Array):
        """Thin SVD: "exact" (LAPACK-style QDWH — the oracle, never chosen
        by "auto" on TPU-cost grounds), "gram" (full spectrum via eigh of
        the small-side Gram matrix), or "randomized" (Halko-Martinsson-
        Tropp sketch, MXU-native: tall matmuls + CholeskyQR2 + an eigh of
        the (k+p, k+p) sliver Gram).

        The randomized path returns only the top (rank + oversample)
        triplets; downstream sampling then draws atoms from the sketched
        subspace. With fast-decaying gradient spectra the missed tail mass
        is negligible, but the estimator is unbiased only within the
        sketched subspace (bias bound measured in
        tests/test_codecs.py::test_randomized_bias_bounded_on_full_spectrum;
        the residual probes restore exact unbiasedness — see encode).
        """
        algorithm = self._algorithm_for(*mat.shape)
        if algorithm == "exact":
            return jnp.linalg.svd(mat, full_matrices=False)
        if algorithm == "gram":
            return self._gram_svd(mat)
        if algorithm != "randomized":
            raise ValueError(f"unknown svd algorithm {self.algorithm!r}")
        m, n = mat.shape
        hi = jax.lax.Precision.HIGHEST
        sketch = min(self.rank + self.oversample, min(m, n))
        g = jax.random.normal(key, (n, sketch), mat.dtype)
        y = jnp.matmul(mat, g, precision=hi)
        q = self._orthonormalize(y)  # (m, sketch)
        # power iterations: two extra MXU-friendly matmuls + CholeskyQR
        # re-orthonormalization each, shrinking the missed-subspace error
        # by (s_tail/s_k)^2 per round
        for _ in range(self.power_iters):
            z = jnp.matmul(mat.T, q, precision=hi)
            z = self._orthonormalize(z, passes=1)  # scale guard only
            y = jnp.matmul(mat, z, precision=hi)
            q = self._orthonormalize(y)
        b = jnp.matmul(q.T, mat, precision=hi)  # (sketch, n)
        # SVD of the sliver via its tiny (sketch, sketch) Gram: on TPU an
        # iterative svd of (11, n) costs far more than eigh of (11, 11)
        gb = jnp.matmul(b, b.T, precision=hi)
        w, ub = jnp.linalg.eigh(gb)
        w, ub = w[::-1], ub[:, ::-1]
        s = jnp.sqrt(jnp.clip(w, 0.0, None))
        vt = jnp.matmul(ub.T, b, precision=hi) / _s_floor(s)[:, None]
        u = jnp.matmul(q, ub, precision=hi)
        return u, s, vt

    def _dense_fallback(self, grad_shape: tuple[int, ...]) -> bool:
        if self.sample == "bernoulli":
            return False  # full-width payload by design
        total = 1
        for d in grad_shape:
            total *= d
        probe_m, probe_n = (
            _square_dims(total, self.max_min_dim)
            if self.reshape == "square"
            else resize_to_2d(jnp.zeros(grad_shape), self.reshape)[0].shape
        )
        k = self._payload_k(min(probe_m, probe_n)) + self._n_probes(probe_m, probe_n)
        return k * (probe_m + probe_n + 1) >= total

    def _payload_k(self, r_full: int) -> int:
        """Static atom-slot count of the wire payload for this sampler."""
        if self.rank <= 0:
            return r_full
        if self.sample == "bernoulli_budget":
            return min(self.rank + self.budget_slack, r_full)
        return min(self.rank, r_full)

    def _n_probes(self, m: int, n: int) -> int:
        """Residual-probe atoms appended to a sketched fixed_k payload
        (0 whenever the matrix resolves to exact SVD — the exact estimator
        is already unbiased)."""
        if self.sample != "fixed_k" or self.residual_probes <= 0:
            return 0
        if self._algorithm_for(m, n) != "randomized":
            return 0
        return self.residual_probes

    def _narrow_payload(self, key: PRNGKey, payload):
        """Apply the wire dtype: stochastically round factors to bf16
        (independent keys for u and vt, so E[u_r @ diag(c) @ vt_r] =
        u @ diag(c) @ vt — unbiasedness survives the narrowing)."""
        if self.wire_dtype == "float32":
            return payload
        if self.wire_dtype != "bfloat16":
            raise ValueError(f"unknown wire_dtype {self.wire_dtype!r}")
        ku, kv = jax.random.split(key)
        if isinstance(payload, SvdMaskedPayload):
            return SvdMaskedPayload(
                u=stochastic_round(ku, payload.u),
                s=payload.s,
                vt=stochastic_round(kv, payload.vt),
            )
        return SvdPayload(
            u=stochastic_round(ku, payload.u),
            coeff=payload.coeff,
            vt=stochastic_round(kv, payload.vt),
        )

    def leaf_payload_bytes(self, grad_shape: tuple[int, ...]) -> int:
        """Static wire bytes of ``encode``'s payload for one gradient leaf
        — the CLAMPED actual, priced without tracing.

        This is the analytic twin of ``jax.eval_shape`` over ``encode``
        (pinned equal per sampler/algorithm/wire-dtype in
        tests/test_comm_model.py): every slot count is the one the encode
        path really ships — ``_payload_k`` clamps ``rank`` (and
        ``rank + budget_slack`` for the Bernoulli budget) to the matrix's
        full rank, the sketch's probe atoms appear exactly when the
        randomized algorithm resolves, and the dense fallback prices the
        exact DensePayload. The adaptive budget allocator
        (atomo_tpu.budget) prices every candidate rank through this, so
        a predicted allocation total and the executed program's
        ``msg_bytes`` agree to the byte."""
        shape = tuple(int(d) for d in grad_shape)
        total = 1
        for d in shape:
            total *= d
        if self._dense_fallback(shape):
            return total * 4  # exact DensePayload, f32 values
        m, n = (
            _square_dims(total, self.max_min_dim)
            if self.reshape == "square"
            else resize_to_2d(jnp.zeros(shape), self.reshape)[0].shape
        )
        m, n = int(m), int(n)
        wire = 2 if self.wire_dtype == "bfloat16" else 4
        if self.sample == "bernoulli":
            # full-width masked factors: u (m, r) + s (r,) f32 + vt (r, n)
            r = min(m, n)
            return (m * r + r * n) * wire + r * 4
        k = self._payload_k(min(m, n)) + self._n_probes(m, n)
        # u (m, k) + coeff (k,) f32 + vt (k, n)
        return (m * k + k * n) * wire + k * 4

    # -- encode ------------------------------------------------------------
    def encode(self, key: PRNGKey, grad: jax.Array):
        if self._dense_fallback(tuple(grad.shape)):
            return DensePayload(values=grad.astype(jnp.float32))
        mat, orig_shape, pad = self._resize(grad.astype(jnp.float32))
        m, n = mat.shape
        key, k_sketch, k_wire = jax.random.split(key, 3)
        u, s, vt = self._svd(k_sketch, mat)
        r_full = s.shape[0]  # randomized: only the sketched triplets exist

        if self.sample == "bernoulli":
            p = bernoulli_probs(s, self.rank)
            keep = jax.random.bernoulli(key, p).astype(s.dtype)
            s_hat = jnp.where(p > 0, s * keep / jnp.maximum(p, jnp.finfo(s.dtype).tiny), 0.0)
            return self._narrow_payload(
                k_wire, SvdMaskedPayload(u=u, s=s_hat, vt=vt)
            )

        if self.sample == "bernoulli_budget":
            # Reference inclusion law (src/codings/svd.py:49-67): atom i kept
            # with p_i = min(1, rank*s_i/sum(s)), kept values rescaled 1/p_i.
            # Packed into k_max static slots; empty slots carry coeff 0.
            # Deviations from the reference, both toward exactness:
            #  * an empty keep-set is SHIPPED as a zero payload (a valid
            #    unbiased outcome) — the reference recurses on empty
            #    (svd.py:61-63), which conditions the distribution and
            #    biases E[decode] up by 1/(1-P(empty));
            #  * a keep-set overflowing k_max is redrawn (bounded); with
            #    slack >= 4 the overflow probability is Chernoff-small, so
            #    the conditioning bias is negligible (statistically tested).
            #    The last resort after max_redraws truncates to top-s kept.
            k_max = self._payload_k(r_full)
            p = bernoulli_probs(s, self.rank)
            tiny = jnp.finfo(s.dtype).tiny

            def draw(carry):
                key_c, _, tries = carry
                key_n, sub = jax.random.split(key_c)
                return key_n, jax.random.bernoulli(sub, p), tries + 1

            def need_redraw(carry):
                _, keep, tries = carry
                return (jnp.sum(keep) > k_max) & (tries < self.max_redraws)

            carry = draw((key, jnp.zeros_like(s, bool), jnp.zeros((), jnp.int32)))
            _, keep, _ = jax.lax.while_loop(need_redraw, draw, carry)
            # kept atoms first (descending s — s is already SVD-sorted),
            # then pad slots pointing at unkept atoms with coeff 0
            order = jnp.argsort(jnp.where(keep, 0, 1), stable=True)
            idx = order[:k_max]
            valid = keep[idx]
            coeff = jnp.where(valid, s[idx] / jnp.maximum(p[idx], tiny), 0.0)
            return self._narrow_payload(
                k_wire, SvdPayload(u=u[:, idx], coeff=coeff, vt=vt[idx, :])
            )

        k = min(self.rank, r_full) if self.rank > 0 else r_full
        if self.sample == "topk":
            # Deterministic top-k — the reference master's random_sample=False
            # path (svd.py:109-113). Biased; used for decode-side parity.
            coeff = s[:k]
            return self._narrow_payload(
                k_wire, SvdPayload(u=u[:, :k], coeff=coeff, vt=vt[:k, :])
            )

        # fixed_k importance sampling with replacement
        key_idx, key_probe = jax.random.split(key)
        q = _safe_probs(s)
        idx = jax.random.categorical(
            key_idx, jnp.log(jnp.maximum(q, jnp.finfo(q.dtype).tiny)), shape=(k,)
        )
        coeff = s[idx] / (k * jnp.maximum(q[idx], jnp.finfo(q.dtype).tiny))
        # all-zero gradient: s[idx] == 0 -> coeff 0, decode gives exact zeros
        u_k, c_k, vt_k = u[:, idx], coeff, vt[idx, :]
        n_probes = self._n_probes(m, n)
        if n_probes:
            # Residual probes: the sketch estimator above is unbiased only
            # for P@mat (P = u u^T, the sketched subspace); the discarded
            # residual R = mat - P@mat is restored in expectation by probe
            # atoms ((1/p) * R w_j, w_j) with Rademacher w_j — E[R w w^T]
            # = R, so the TOTAL payload estimator is unbiased for mat, the
            # full ATOMO contract (the reference achieves this by paying
            # for an exact SVD, src/codings/svd.py:95). Variance ~(n/p)
            # ||R||_F^2 is zero-mean sampling noise, the same class (and
            # scale, ~r/k) the exact fixed_k sampler already injects on
            # flat spectra — and SGD demonstrably tolerates it
            # (tests/test_convergence.py), while bias floors convergence.
            hi = jax.lax.Precision.HIGHEST
            w = jax.random.rademacher(key_probe, (n, n_probes), mat.dtype)
            xw = jnp.matmul(mat, w, precision=hi)  # (m, p)
            rw = xw - jnp.matmul(u, jnp.matmul(u.T, xw, precision=hi), precision=hi)
            u_k = jnp.concatenate([u_k, rw], axis=1)
            c_k = jnp.concatenate(
                [c_k, jnp.full((n_probes,), 1.0 / n_probes, coeff.dtype)]
            )
            vt_k = jnp.concatenate([vt_k, w.T.astype(vt.dtype)], axis=0)
        return self._narrow_payload(
            k_wire, SvdPayload(u=u_k, coeff=c_k, vt=vt_k)
        )

    # -- decode ------------------------------------------------------------
    def decode_matrix(self, payload) -> jax.Array:
        """Reconstruct the 2-D matrix: U @ diag(c) @ Vt (svd.py:171-175).

        HIGHEST matmul precision: on TPU the MXU's default bf16 passes would
        corrupt the reconstructed gradient; full-f32 accumulation keeps the
        decode bit-stable across replicas (replicated-PS equivalence).
        """
        if isinstance(payload, SvdMaskedPayload):
            scaled = payload.u.astype(jnp.float32) * payload.s[None, :]
        else:
            scaled = payload.u.astype(jnp.float32) * payload.coeff[None, :]
        # bf16-wire factors cast up before the contraction (f32 accumulate)
        vt = payload.vt.astype(jnp.float32)
        return jnp.matmul(scaled, vt, precision=jax.lax.Precision.HIGHEST)

    def decode(self, payload, grad_shape: tuple[int, ...], dtype=jnp.float32) -> jax.Array:
        """Reconstruct the gradient from a payload + static shape metadata."""
        return self.make_decoder(grad_shape, dtype)(payload)

    def decode_mean(
        self, gathered, grad_shape: tuple[int, ...], dtype, n_replicas: int
    ):
        """Fused mean-of-decodes for all_gather-ed payloads (leading axis N).

        Concatenates the N rank-k factor blocks and reconstructs the mean
        with ONE (m, N*k) @ (N*k, n) matmul — an MXU-sized contraction
        instead of N thin slivers, and no N dense (m, n) intermediates.
        The reference decodes each worker's message separately then sums
        (src/sync_replicas_master_nn.py:292-296, src/codings/svd.py:160-178).
        Returns None for payload types without a fused path (the caller
        falls back to vmap-decode + mean).
        """
        if self._dense_fallback(tuple(grad_shape)):
            return jnp.mean(gathered.values, axis=0).reshape(grad_shape).astype(dtype)
        if isinstance(gathered, SvdMaskedPayload):
            u, c, vt = gathered.u, gathered.s, gathered.vt
        elif isinstance(gathered, SvdPayload):
            u, c, vt = gathered.u, gathered.coeff, gathered.vt
        else:
            return None
        u = u.astype(jnp.float32)
        vt = vt.astype(jnp.float32)
        n_rep, m, k = u.shape
        n = vt.shape[2]
        u_cat = jnp.transpose(u, (1, 0, 2)).reshape(m, n_rep * k)
        scaled = u_cat * (c.reshape(n_rep * k) / n_rep)[None, :]
        mat = jnp.matmul(
            scaled, vt.reshape(n_rep * k, n), precision=jax.lax.Precision.HIGHEST
        )
        probe = jnp.zeros(grad_shape, dtype)
        _, orig_shape, pad = self._resize(probe)
        return undo_resize(mat, orig_shape, pad).astype(dtype)

    def make_decoder(self, grad_shape: tuple[int, ...], dtype=jnp.float32):
        """Return decode(payload) -> grad for a known gradient shape.

        Shape metadata travels out-of-band (it is static), not on the wire —
        unlike the reference which pickles `orig_size`/`reshaped` flags into
        every message (svd.py:103-117).
        """
        if self._dense_fallback(tuple(grad_shape)):
            def decode_dense(payload):
                return payload.values.reshape(grad_shape).astype(dtype)

            return decode_dense
        probe = jnp.zeros(grad_shape, dtype)
        _, orig_shape, pad = self._resize(probe)

        def decode(payload):
            return undo_resize(self.decode_matrix(payload), orig_shape, pad).astype(dtype)

        return decode


def encode_decode(codec: SvdCodec, key: PRNGKey, grad: jax.Array) -> jax.Array:
    """Round-trip helper: compress-then-decompress one gradient in-process.

    This is the single-host 'compression on, comm off' mode (SURVEY.md §7
    build-order step 4 / the reference's single_machine study path).
    """
    payload = codec.encode(key, grad)
    return codec.make_decoder(tuple(grad.shape), grad.dtype)(payload)
