"""Pipeline parallelism: GPipe-style staged transformer over a 'pp' axis.

The reference's nearest relative is the *split-backward* models — per-layer
manual backward interleaved with per-layer sends INSIDE one process
(SURVEY.md §2.1 "Pipeline parallelism: No"; resnet_split.py:259-361) — i.e.
comm/compute overlap, never multi-device pipelining. This module is the real
thing, TPU-native: transformer blocks are stacked on a leading depth axis
and sharded over 'pp' (depth/n blocks per chip = one stage); microbatches
march through stages with one ``ppermute`` hop per tick on the ICI torus,
and the classic GPipe schedule (M + n_pp - 1 ticks for M microbatches) runs
as a single ``lax.scan`` — static shapes, no Python-level pipeline engine.

SPMD uniformity: every chip executes the same tick program; stage identity
enters only through ``where(stage == 0, embedded_microbatch, received)`` at
the pipe head and a masked loss at the pipe tail. The backward schedule
falls out of AD: the transpose of ppermute is the inverse rotation, so
cotangents flow tail -> head with the same overlap, no hand-scheduling.

Gradient discipline (cf. parallel.tp/moe derivations): the loss path
crosses NO psum — only ppermute, whose transpose is exact. Stage-sharded
block grads arrive exact via the rotation transpose chain; pp-replicated
leaves (embeddings on the head stage, final-LN/head on the tail stage) hold
nonzero grads only on the stage that used them, so one psum over pp
completes them with no n-scaling. Compressed gradient exchange rides dp via
parallel.lm.compressed_dp_update, composing with the stage sharding.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from atomo_tpu.mesh.collectives import ppermute_pipeline
from atomo_tpu.parallel.common import (
    attention_sublayer,
    dense_init as _dense_init,
    layernorm,
    complete_model_axis_grads,
    make_state_specs,
    shard_state,
    shard_tokens_with_spec,
)
from atomo_tpu.parallel.compile import compile_step
from atomo_tpu.parallel.lm import DpExchange, dp_exchange_tail
from atomo_tpu.training.trainer import TrainState, cast_params

# ---------------------------------------------------------------------------
# params: blocks stacked on a leading depth axis (shardable over pp)
# ---------------------------------------------------------------------------


def init_pp_lm_params(key, cfg: dict) -> Any:
    """Param tree with all transformer blocks STACKED on a leading depth
    axis. ``cfg``: vocab_size, max_len, width, depth, num_heads,
    mlp_ratio (default 4)."""
    w = cfg["width"]
    dep = cfg["depth"]
    f = cfg.get("mlp_ratio", 4) * w
    h, d = cfg["num_heads"], w // cfg["num_heads"]
    ks = jax.random.split(key, 7)

    def stacked(k, shape, in_axis):
        return jax.vmap(
            lambda kk: _dense_init(kk, shape, in_axis=in_axis)
        )(jax.random.split(k, dep))

    return {
        "tok_emb": {"embedding": jax.random.normal(ks[0], (cfg["vocab_size"], w)) / math.sqrt(w)},
        "pos_emb": {"embedding": jax.random.normal(ks[1], (cfg["max_len"], w)) / math.sqrt(w)},
        "blocks": {
            "ln1": {"scale": jnp.ones((dep, w), jnp.float32)},
            "qkv": {"kernel": stacked(ks[2], (w, 3 * h * d), 0)},
            "proj": {"kernel": stacked(ks[3], (h * d, w), 0)},
            "ln2": {"scale": jnp.ones((dep, w), jnp.float32)},
            "up": {"kernel": stacked(ks[4], (w, f), 0)},
            "down": {"kernel": stacked(ks[5], (f, w), 0)},
        },
        "ln_f": {"scale": jnp.ones((w,), jnp.float32)},
        "head": {"kernel": _dense_init(ks[6], (w, cfg["vocab_size"]))},
    }


def pp_param_specs(params: Any, pp_axis: str = "pp") -> Any:
    """Stacked block leaves sharded on their leading depth axis; embeddings,
    final LN and head replicated (used only on the head/tail stages but
    co-located everywhere for SPMD uniformity)."""

    def spec(path, leaf) -> P:
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        if "blocks" in names:
            return P(pp_axis, *([None] * (leaf.ndim - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


make_pp_state_specs = make_state_specs
shard_pp_state = shard_state


def create_pp_lm_state(
    mesh: Mesh, cfg: dict, optimizer, rng, *, pp_axis: str = "pp"
) -> tuple[TrainState, TrainState]:
    n_pp = mesh.shape[pp_axis]
    if cfg["depth"] % n_pp:
        raise ValueError(f"depth {cfg['depth']} not divisible by pp={n_pp}")
    params = init_pp_lm_params(rng, cfg)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats={},
        opt_state=optimizer.init(params),
    )
    specs = make_pp_state_specs(state, pp_param_specs(params, pp_axis))
    return shard_pp_state(mesh, state, specs), specs


# ---------------------------------------------------------------------------
# block stack + single-device reference
# ---------------------------------------------------------------------------


def _one_block(bp: Any, x: jax.Array, num_heads: int) -> jax.Array:
    """One pre-LN block on UNSTACKED block params (leaves without the depth
    axis). Same math as parallel.tp's blocks / models.transformer.Block."""
    x = attention_sublayer(bp, x, num_heads)
    y = layernorm(x, bp["ln2"]["scale"])
    return x + jax.nn.gelu(y @ bp["up"]["kernel"]) @ bp["down"]["kernel"]


def _block_stack(stacked: Any, x: jax.Array, num_heads: int) -> jax.Array:
    """Apply a (local) stack of blocks via lax.scan over the depth axis."""

    def body(xc, bp):
        return _one_block(bp, xc, num_heads), None

    out, _ = jax.lax.scan(body, x, stacked)
    return out


def _embed(params: Any, tokens: jax.Array) -> jax.Array:
    s = tokens.shape[1]
    return (
        params["tok_emb"]["embedding"][tokens]
        + params["pos_emb"]["embedding"][jnp.arange(s)][None]
    )


def _head(params: Any, x: jax.Array) -> jax.Array:
    return layernorm(x, params["ln_f"]["scale"]) @ params["head"]["kernel"]


def pp_lm_forward_reference(params: Any, tokens: jax.Array, cfg: dict) -> jax.Array:
    """Single-device oracle: the exact function the pipeline distributes."""
    x = _embed(params, tokens)
    x = _block_stack(params["blocks"], x, cfg["num_heads"])
    return _head(params, x)


# ---------------------------------------------------------------------------
# the dp x pp train step
# ---------------------------------------------------------------------------


def make_pp_lm_train_step(
    cfg: dict,
    optimizer,
    mesh: Mesh,
    state_specs: TrainState,
    codec=None,
    *,
    dp_axis: str = "dp",
    pp_axis: str = "pp",
    num_microbatches: int = 2,
    compute_dtype=None,
    aggregate: str = "gather",
    exchange: DpExchange | None = None,
    oracle_parts: bool = False,
):
    """Jitted (state, key, tokens) -> (state, metrics): GPipe pipeline over
    pp with ATOMO-compressed gradient exchange over dp.

    tokens (B, S) are sharded over dp only (each dp replica's full
    minibatch is cut into ``num_microbatches`` microbatches that flow
    through the pp stages)."""
    n_dp = mesh.shape[dp_axis]
    n_pp = mesh.shape[pp_axis]
    m = num_microbatches
    param_specs = state_specs.params

    def grads_fn(state: TrainState, key, tokens):
        b_local, s = tokens.shape
        if b_local % m:
            raise ValueError(
                f"per-replica batch {b_local} not divisible by "
                f"num_microbatches={m}"
            )
        mb = b_local // m
        stage = jax.lax.axis_index(pp_axis)
        is_head = stage == 0
        is_tail = stage == n_pp - 1
        my_dp = jax.lax.axis_index(dp_axis)
        k_codec = jax.random.fold_in(jax.random.fold_in(key, state.step), my_dp)

        def loss_fn(params):
            if compute_dtype is not None:
                # bf16 MXU compute, f32 master state; the scan carry
                # (activations) rides the compute dtype
                params = cast_params(params, compute_dtype)
            act_dtype = compute_dtype or jnp.float32
            local_blocks = params["blocks"]  # (depth/n_pp, ...) slices

            def tick(carry, t):
                acts = carry
                # pipe head: microbatch t enters (other stages use received)
                in_idx = jnp.clip(t, 0, m - 1) * mb
                toks_in = jax.lax.dynamic_slice_in_dim(tokens, in_idx, mb, 0)
                x_in = jnp.where(is_head, _embed(params, toks_in), acts)
                y = _block_stack(local_blocks, x_in, cfg["num_heads"])
                # one pipeline tick (mesh.collectives.pipeline_perm): the
                # hop utils.comm_model's bubble pricing counts per stage
                return ppermute_pipeline(y, pp_axis, n_pp), y

            acts0 = jnp.zeros((mb, s, cfg["width"]), act_dtype)
            _, ys = jax.lax.scan(
                tick, acts0, jnp.arange(m + n_pp - 1)
            )
            # head + CE ONCE, post-scan, on the m live tail ticks only
            # (microbatch i exits the tail at tick n_pp-1+i) — the drained
            # ticks' outputs are dropped instead of pushed through a masked
            # vocab matmul every tick
            y_live = ys[n_pp - 1 :].reshape(b_local, s, cfg["width"])
            logits = _head(params, y_live).astype(jnp.float32)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], tokens[:, 1:]
            )
            # sum / replica token count: nonzero only on the tail stage
            # (other stages' y_live is pipeline garbage, masked out here);
            # see module docstring for why no psum belongs inside the loss
            return jnp.where(is_tail, jnp.sum(ce), 0.0) / (b_local * (s - 1))

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        # pp-replicated leaves carry nonzero grads only on the stage that
        # used them (embeddings: head; ln_f/head: tail) — psum completes
        # them; stage-sharded block slices are exact as-is (no psum in the
        # loss path, so no divide_by)
        grads = complete_model_axis_grads(grads, param_specs, pp_axis)
        replica_loss = jax.lax.psum(loss, pp_axis)
        return k_codec, grads, replica_loss

    def spmd_step(state: TrainState, key, tokens):
        k_codec, grads, replica_loss = grads_fn(state, key, tokens)
        return dp_exchange_tail(
            optimizer, codec, state, k_codec, grads, replica_loss,
            dp_axis=dp_axis, n_dp=n_dp, aggregate=aggregate,
            exchange=exchange,
        )

    if exchange is not None and exchange.overlap == "delayed":
        # the consume chain reads only step-start values, so the scheduler
        # can run the dp exchange underneath the pipeline's drain ticks —
        # the bubble becomes overlap headroom (comm_model.overlap_report's
        # bubble_hidden_ms term prices exactly this)
        from atomo_tpu.parallel.lm import make_delayed_model_axis_step

        return make_delayed_model_axis_step(
            grads_fn, optimizer, codec, mesh,
            dp_axis=dp_axis, n_dp=n_dp, exchange=exchange,
            state_specs=state_specs, token_spec=P(dp_axis, None),
            oracle_parts=oracle_parts,
        )

    return compile_step(
        spmd_step,
        mesh,
        in_specs=(state_specs, P(), P(dp_axis, None)),
        out_specs=(state_specs, P()),
        donate_argnums=(0,),
    )


def shard_pp_tokens(mesh: Mesh, tokens, dp_axis: str = "dp"):
    return shard_tokens_with_spec(mesh, tokens, P(dp_axis, None))
