"""Pallas TPU kernel for fused (flash) attention — the LM forward hot path.

The jnp attention paths (parallel.ring.full_attention / blockwise_attention)
leave the softmax chain to XLA: scores, max, exp, sum and the PV matmul are
separate HBM-visible ops unless XLA fuses them. This kernel is the classic
flash-attention schedule as ONE VMEM-resident program per query block: K/V
stream through the MXU in blocks under an online-softmax accumulator, the
S×S score matrix never exists, and HBM traffic is O(S·D) reads + O(S·D)
writes per head regardless of S. For causal masks the K loop stops at the
diagonal block, halving the work.

Scope discipline (round-2 lesson: TPU-only code paths must stay testable):
  * forward = Pallas kernel, bit-compared against full_attention in the
    TPU-semantics interpreter on CPU and on the real chip (tests_tpu);
  * backward = jax.vjp of the jnp blockwise oracle (identical math), so
    training through ``flash_attention`` is exact and needs no hand-written
    transpose kernel; the fused win applies to the forward pass.
  * shapes that don't tile (S % block) fall back to blockwise_attention —
    no silent padding semantics.

No reference analogue: the reference has no attention at all (SURVEY.md
§5.7); this is TPU-first capability the framework adds on top of parity.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from atomo_tpu.ops.qsgd_kernels import _interpret_mode, is_tpu

NEG_INF = float("-inf")


def _fa_kernel(
    q_ref, k_ref, v_ref, o_ref, *, scale: float, causal: bool,
    block_k: int, s_total: int
):
    """One (batch, head, q-block) program: stream K/V blocks through an
    online-softmax accumulator. Block shapes: q/o (1, 1, Bq, D);
    k/v (1, 1, S, D) resident in VMEM."""
    q = q_ref[0, 0].astype(jnp.float32)  # (Bq, D)
    bq, d = q.shape
    iq = pl.program_id(2)
    q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)

    n_k = pl.cdiv(s_total, block_k)
    if causal:
        # blocks strictly above the diagonal contribute nothing
        n_k = jnp.minimum(n_k, pl.cdiv((iq + 1) * bq, block_k))

    def body(jk, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (Bq, Bk)
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )
        valid = k_pos < s_total
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe), 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, n_k, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, jnp.finfo(jnp.float32).tiny)
    o_ref[0, 0] = out.astype(o_ref.dtype)


def _flash_forward(
    q, k, v, *, causal: bool, scale: float, block_q: int, block_k: int,
    interpret: bool,
):
    b, h, s, d = q.shape
    grid = (b, h, s // block_q)
    kernel = partial(
        _fa_kernel, scale=scale, causal=causal, block_k=block_k, s_total=s
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bb, hh, i: (bb, hh, i, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bb, hh, i: (bb, hh, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bb, hh, i: (bb, hh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda bb, hh, i: (bb, hh, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpret_mode(interpret),
    )(q, k, v)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret,
    )


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_forward(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret,
    )
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, do):
    # exact gradients via the jnp blockwise oracle (same online-softmax
    # math, same O(S·block) memory); the fused kernel accelerates forward
    from atomo_tpu.parallel.ring import blockwise_attention

    q, k, v = res
    _, vjp = jax.vjp(
        lambda qq, kk, vv: blockwise_attention(
            qq, kk, vv, causal=causal, scale=scale, block_size=block_k
        ),
        q, k, v,
    )
    return vjp(do)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused exact attention (B, H, S, D) -> (B, H, S, D).

    Forward runs the Pallas flash kernel (interpreter on CPU, Mosaic on
    TPU); backward is the jnp blockwise oracle's VJP. Falls back to
    blockwise_attention when S doesn't tile by the blocks — identical
    results either way (tested)."""
    from atomo_tpu.parallel.ring import blockwise_attention

    b, h, s, d = q.shape
    if scale is None:
        scale = 1.0 / (d**0.5)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        return blockwise_attention(
            q, k, v, causal=causal, scale=scale, block_size=block_k
        )
    if interpret is None:
        interpret = not is_tpu()
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)
