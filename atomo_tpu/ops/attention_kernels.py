"""Pallas TPU kernel for fused (flash) attention — the LM forward hot path.

The jnp attention paths (parallel.ring.full_attention / blockwise_attention)
leave the softmax chain to XLA: scores, max, exp, sum and the PV matmul are
separate HBM-visible ops unless XLA fuses them. This kernel is the classic
flash-attention schedule: the grid walks (batch, head, q-block, k-block)
with the k-block axis innermost, K/V arrive one (block_k, D) tile at a time
(Pallas double-buffers the HBM→VMEM DMA), and an online-softmax accumulator
lives in VMEM scratch across the k sweep. The S×S score matrix never
exists, VMEM residency is O(block·D) — independent of S, so sequence
length is NOT bounded by VMEM (ADVICE r3 #1: the round-3 kernel kept the
full (S, D) K/V resident per program, capping S at ~16k for D=64 f32 on a
16 MB-VMEM core). For causal masks, k-blocks strictly above the diagonal
skip their FLOPs via `pl.when` (the static grid still walks — and
prefetches — those blocks, so causal saves compute but not bandwidth).

Scope discipline (round-2 lesson: TPU-only code paths must stay testable):
  * forward = Pallas kernel, bit-compared against full_attention in the
    TPU-semantics interpreter on CPU and on the real chip (tests_tpu);
  * backward = jax.vjp of the jnp blockwise oracle (identical math), so
    training through ``flash_attention`` is exact and needs no hand-written
    transpose kernel; the fused win applies to the forward pass.
  * shapes that don't tile (S % block) fall back to blockwise_attention —
    no silent padding semantics.

No reference analogue: the reference has no attention at all (SURVEY.md
§5.7); this is TPU-first capability the framework adds on top of parity.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from atomo_tpu.ops.qsgd_kernels import _interpret_mode, is_tpu

NEG_INF = float("-inf")


def _fa_kernel(
    q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
    scale: float, causal: bool,
):
    """One (batch, head, q-block, k-block) grid step. Blocks: q/o
    (1, 1, Bq, D) pinned across the k sweep; k/v (1, 1, Bk, D) — one tile
    per step, streamed from HBM. The online-softmax state (m, l, acc)
    lives in VMEM scratch, initialized at k-block 0 and folded into o_ref
    at the last k-block."""
    iq = pl.program_id(2)
    jk = pl.program_id(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]

    @pl.when(jk == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, NEG_INF, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    # causal: a k-block whose first position is past this q-block's last
    # position is fully masked — skip its FLOPs (the DMA still happened;
    # see module docstring)
    live = (jk * bk <= (iq + 1) * bq - 1) if causal else (jk >= 0)

    @pl.when(live)
    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32)  # (Bq, D)
        k_blk = k_ref[0, 0].astype(jnp.float32)  # (Bk, D)
        v_blk = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (Bq, Bk)
        if causal:
            q_pos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
            k_pos = jk * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m, l, acc = m_ref[...], l_ref[...], acc_ref[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_safe), 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        m_ref[...] = m_new
        l_ref[...] = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jk == pl.num_programs(3) - 1)
    def _finalize():
        out = acc_ref[...] / jnp.maximum(l_ref[...], jnp.finfo(jnp.float32).tiny)
        o_ref[0, 0] = out.astype(o_ref.dtype)


def _flash_forward(
    q, k, v, *, causal: bool, scale: float, block_q: int, block_k: int,
    interpret: bool,
):
    b, h, s, d = q.shape
    grid = (b, h, s // block_q, s // block_k)
    kernel = partial(_fa_kernel, scale=scale, causal=causal)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bb, hh, i, j: (bb, hh, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bb, hh, i, j: (bb, hh, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bb, hh, i, j: (bb, hh, j, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bb, hh, i, j: (bb, hh, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),  # running max m
            pltpu.VMEM((block_q, 1), jnp.float32),  # running denom l
            pltpu.VMEM((block_q, d), jnp.float32),  # unnormalized acc
        ],
        interpret=_interpret_mode(interpret),
    )(q, k, v)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret,
    )


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_forward(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret,
    )
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, do):
    # exact gradients via the jnp blockwise oracle (same online-softmax
    # math, same O(S·block) memory); the fused kernel accelerates forward
    from atomo_tpu.parallel.ring import blockwise_attention

    q, k, v = res
    _, vjp = jax.vjp(
        lambda qq, kk, vv: blockwise_attention(
            qq, kk, vv, causal=causal, scale=scale, block_size=block_k
        ),
        q, k, v,
    )
    return vjp(do)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused exact attention (B, H, S, D) -> (B, H, S, D).

    Forward runs the Pallas flash kernel (interpreter on CPU, Mosaic on
    TPU); backward is the jnp blockwise oracle's VJP. Falls back to
    blockwise_attention when S doesn't tile by the blocks — identical
    results either way (tested)."""
    from atomo_tpu.parallel.ring import blockwise_attention

    b, h, s, d = q.shape
    if scale is None:
        scale = 1.0 / (d**0.5)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        return blockwise_attention(
            q, k, v, causal=causal, scale=scale, block_size=block_k
        )
    if interpret is None:
        interpret = not is_tpu()
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)
