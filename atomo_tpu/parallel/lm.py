"""Long-context LM training: dp×sp SPMD with compressed gradient exchange.

The capability composition the reference cannot express (DP-only, CV-only —
SURVEY.md §2.1): a 2-D mesh where

  dp — batch replicas exchanging ATOMO-compressed gradients (all_gather of
       codec payloads, identical decode+mean on every chip — exactly the
       replicated-PS semantics of parallel.replicated)
  sp — the sequence dimension of each replica's batch, attended over with
       exact ring attention (parallel.ring), gradients dense-psum'd: the sp
       reduction *forms* one replica's gradient, so it is intra-replica and
       not part of the compressed inter-replica exchange.

Loss is the exact global next-token cross-entropy: shard-boundary targets
are fetched from the ring neighbor with ppermute, and the final position of
the last shard is masked, so sharded and unsharded training compute the same
scalar.
"""

from __future__ import annotations

import dataclasses
from functools import partial


import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from atomo_tpu.codecs import (
    decode_mean_tree,
    decode_tree,
    encode_tree,
    encode_tree_streamed,
    tree_nbytes,
)
from atomo_tpu.mesh.collectives import ppermute_ring
from atomo_tpu.parallel.common import plan_layer_buckets
from atomo_tpu.parallel.compile import compile_step
from atomo_tpu.parallel.ring import ATTENTION_IMPLS
from atomo_tpu.training.trainer import TrainState, cast_params
from atomo_tpu.utils.tracing import named_phase


def sp_boundary_targets_and_mask(tokens, sp_axis: str, n_sp: int):
    """Boundary-exact next-token targets for a sequence-sharded batch:
    each shard's last target is the FIRST token of the next shard
    (ppermute), and the global final position (last shard's last column)
    is masked out. Returns (targets, valid) of shape (B, S_local) — the
    contract shared by the dp x sp and dp x tp x sp loss functions, so
    sharded and unsharded training compute the same scalar CE."""
    # one ring hop (mesh.collectives.ring_perm — the SAME rotation every
    # ring schedule uses): shard i's first column arrives at shard i-1
    nxt = ppermute_ring(tokens[:, :1], sp_axis, n_sp)
    targets = jnp.concatenate([tokens[:, 1:], nxt], axis=1)
    valid = jnp.ones(targets.shape, jnp.float32)
    is_last = (jax.lax.axis_index(sp_axis) == n_sp - 1).astype(jnp.float32)
    valid = valid.at[:, -1].set(1.0 - is_last)
    return targets, valid


def compressed_dp_update(
    optimizer,
    codec,
    state: TrainState,
    k_codec,
    grads,
    loss,
    *,
    dp_axis: str,
    n_dp: int,
    aggregate: str = "gather",
):
    """The shared per-shard tail of every compressed-DP train step: encode
    this replica's (already-completed) gradient, all_gather payloads over
    dp, decode+mean identically everywhere, apply the optimizer — or dense
    pmean when ``codec`` is None. Returns (new_state, metrics). Used by the
    dp x sp (make_lm_train_step) and dp x tp (parallel.tp) steps; gradients
    may be model-sharded on other mesh axes — each shard exchanges its own
    slice over dp, so compression composes with model sharding.

    ``aggregate="psum"`` with a codec keeps the encode->decode round trip
    (the quantization-noise semantics) but exchanges DENSE gradients with a
    pmean — the mode ``--aggregate auto`` picks on fast ICI, where the
    factor gather's codec tax loses to the wire saving
    (utils/comm_model.choose_aggregate)."""
    dense_bytes = tree_nbytes(grads)
    if codec is None:
        mean_grads = jax.lax.pmean(grads, dp_axis)
        msg_bytes = dense_bytes
    elif aggregate == "psum":
        payloads, _ = encode_tree(codec, k_codec, grads)
        decoded = decode_tree(codec, payloads, grads)
        mean_grads = jax.lax.pmean(decoded, dp_axis)
        msg_bytes = dense_bytes  # the wire truly carries dense bytes here
    elif aggregate == "gather":
        payloads, stats = encode_tree(codec, k_codec, grads)
        msg_bytes = stats.payload_bytes
        gathered = jax.lax.all_gather(payloads, dp_axis)
        # fused decode_mean where the codec provides it (SVD: one
        # (m, N·k)@(N·k, n) matmul), vmap-decode + mean otherwise
        mean_grads = decode_mean_tree(codec, gathered, grads, n_dp)
    else:
        raise ValueError(f"unknown aggregate mode {aggregate!r}")

    updates, new_opt = optimizer.update(mean_grads, state.opt_state, state.params)
    new_params = optax.apply_updates(state.params, updates)
    metrics = {
        "loss": jax.lax.pmean(loss, dp_axis),
        # float32, not int32: byte counts are static Python ints at trace
        # time and a >=2 GiB per-shard gradient (the large-model regime tp
        # exists for) would overflow int32 at jit time
        "msg_bytes": jnp.asarray(msg_bytes, jnp.float32),
        "dense_bytes": jnp.asarray(dense_bytes, jnp.float32),
    }
    new_state = TrainState(
        step=state.step + 1,
        params=new_params,
        batch_stats=state.batch_stats,
        opt_state=new_opt,
    )
    return new_state, metrics


@dataclasses.dataclass(frozen=True)
class DpExchange:
    """The data-parallel gradient-exchange recipe of a model-axis step —
    the knob vector of the compressed stack, carried as ONE static value.

    Passing ``exchange=`` to a model-axis step builder routes its dp tail
    through :func:`compressed_dp_exchange` (the scoped, full-stack tail:
    ring aggregation, stream-encode buckets, per-leaf budget codecs all
    compose); ``exchange=None`` keeps the legacy
    :func:`compressed_dp_update` tail byte-for-byte. The fields mirror the
    replicated family's knob names (``utils.comm_model.candidate_name``
    algebra), so a controller candidate maps onto this dataclass
    field-for-field.

    ``overlap="delayed"`` threads the replicated loop's consume-next-step
    carry through the step (:func:`delayed_dp_exchange`): the dp exchange
    consumes the PREVIOUS step's encoded payload while this step's
    backward (and, on dp-pp, the pipeline's drain ticks) runs, so the
    exposed exchange time drops to ``max(0, exchange - compute_tail)``.
    ``overlap="off"`` (the default) is byte-identical HLO to a DpExchange
    that predates the field (tested).
    """

    aggregate: str = "gather"  # gather | psum | ring
    ring_bucket_size: int = 0
    stream_encode: bool = False
    stream_bucket_bytes: int = 4 << 20
    overlap: str = "off"  # off | delayed

    def __post_init__(self):
        if self.aggregate not in ("gather", "psum", "ring"):
            raise ValueError(
                f"unknown aggregate mode {self.aggregate!r}; the model-axis "
                "dp exchange ships gather | psum | ring"
            )
        if self.overlap not in ("off", "delayed"):
            raise ValueError(
                f"unknown overlap mode {self.overlap!r}; the model-axis dp "
                "exchange ships off | delayed"
            )
        if self.overlap == "delayed" and self.aggregate == "psum":
            raise ValueError(
                "overlap='delayed' carries an ENCODED payload between "
                "steps; the dense psum exchange has no payload to carry — "
                "use aggregate='gather' or 'ring'"
            )


def compressed_dp_exchange(
    optimizer,
    codec,
    state: TrainState,
    k_codec,
    grads,
    loss,
    *,
    dp_axis: str,
    n_dp: int,
    exchange: DpExchange,
):
    """The full-stack dp tail of the model-axis steps: the same contract as
    :func:`compressed_dp_update` (encode this shard's completed gradient,
    exchange over dp, decode+mean identically everywhere, apply the
    optimizer) with the rest of the compressed stack composed in —

      * ``named_phase`` scopes (``encode`` / ``exchange`` / ``decode_mean``
        / ``ring_exchange_decode``) label the traced regions, so ``report
        timeline`` finds the same anchors in every model-axis program
        family that it finds in the replicated family;
      * ``aggregate="ring"`` streams payload chunks around the dp ring
        (:func:`atomo_tpu.parallel.replicated._ring_stream_mean` — the
        same canonical staged mean, so replicas stay bit-equal);
      * ``stream_encode`` encodes per layer bucket
        (:func:`atomo_tpu.parallel.common.plan_layer_buckets` — payloads
        bit-identical to the monolithic encode, dataflow overlappable);
      * per-leaf budget codecs (``--budget-alloc variance``'s PerLeafCodec)
        flow through ``encode_tree``'s per-leaf resolution untouched.

    Gradients may be model-sharded on other mesh axes: each shard
    exchanges its own completed slice over dp, exactly as the legacy tail.
    """
    dense_bytes = tree_nbytes(grads)
    agg = exchange.aggregate
    if codec is None:
        if agg == "ring":
            raise ValueError(
                "aggregate='ring' needs a codec: the ring streams encoded "
                "payload chunks; a dense ring would just be a slower pmean"
            )
        with named_phase("exchange"):
            mean_grads = jax.lax.pmean(grads, dp_axis)
        msg_bytes = dense_bytes
    elif agg == "psum":
        with named_phase("encode"):
            payloads, _ = encode_tree(codec, k_codec, grads)
            decoded = decode_tree(codec, payloads, grads)
        with named_phase("exchange"):
            mean_grads = jax.lax.pmean(decoded, dp_axis)
        msg_bytes = dense_bytes  # the wire truly carries dense bytes here
    else:
        # stream_encode: per-layer-bucket encode (reverse-topological
        # plan, global-leaf-index keys) — bit-identical payloads whose
        # dataflow lets each bucket's encode run under backprop of the
        # layers feeding the next bucket; off keeps the monolithic call
        # byte-for-byte (the replicated family's exact idiom)
        lplan = (
            plan_layer_buckets(grads, exchange.stream_bucket_bytes)
            if exchange.stream_encode
            else None
        )
        with named_phase("encode"):
            if exchange.stream_encode:
                payloads, stats = encode_tree_streamed(
                    codec, k_codec, grads, lplan
                )
            else:
                payloads, stats = encode_tree(codec, k_codec, grads)
        msg_bytes = stats.payload_bytes
        if agg == "gather":
            with named_phase("exchange"):
                gathered = jax.lax.all_gather(payloads, dp_axis)
            with named_phase("decode_mean"):
                mean_grads = decode_mean_tree(codec, gathered, grads, n_dp)
        else:  # ring
            # lazy: replicated.py does not import this module, but a
            # module-level import here would cycle the other way around
            # through parallel/__init__
            from atomo_tpu.parallel.replicated import (
                _ring_stream_mean,
                _ring_stream_mean_layered,
            )

            my = jax.lax.axis_index(dp_axis)
            with named_phase("ring_exchange_decode"):
                if exchange.stream_encode:
                    mean_grads, _ = _ring_stream_mean_layered(
                        codec, payloads, grads, lplan,
                        axis=dp_axis, n_dev=n_dp, my=my, n_contrib=n_dp,
                        bucket_size=exchange.ring_bucket_size,
                    )
                else:
                    mean_grads, _ = _ring_stream_mean(
                        codec, payloads, grads,
                        axis=dp_axis, n_dev=n_dp, my=my, n_contrib=n_dp,
                        bucket_size=exchange.ring_bucket_size,
                    )

    updates, new_opt = optimizer.update(mean_grads, state.opt_state, state.params)
    new_params = optax.apply_updates(state.params, updates)
    metrics = {
        "loss": jax.lax.pmean(loss, dp_axis),
        # float32, not int32 — same overflow rationale as the legacy tail
        "msg_bytes": jnp.asarray(msg_bytes, jnp.float32),
        "dense_bytes": jnp.asarray(dense_bytes, jnp.float32),
    }
    new_state = TrainState(
        step=state.step + 1,
        params=new_params,
        batch_stats=state.batch_stats,
        opt_state=new_opt,
    )
    return new_state, metrics


def dp_exchange_tail(
    optimizer, codec, state, k_codec, grads, loss, *,
    dp_axis: str, n_dp: int, aggregate: str, exchange=None,
):
    """Dispatch one model-axis step's dp tail: the legacy
    :func:`compressed_dp_update` when ``exchange`` is None (byte-for-byte
    the pre-refactor program), :func:`compressed_dp_exchange` when the
    caller hands a :class:`DpExchange` (``exchange.aggregate`` wins over
    the legacy ``aggregate`` string — one source of truth per path)."""
    if exchange is None:
        return compressed_dp_update(
            optimizer, codec, state, k_codec, grads, loss,
            dp_axis=dp_axis, n_dp=n_dp, aggregate=aggregate,
        )
    return compressed_dp_exchange(
        optimizer, codec, state, k_codec, grads, loss,
        dp_axis=dp_axis, n_dp=n_dp, exchange=exchange,
    )


# ---------------------------------------------------------------------------
# delayed overlap for the model-axis steps: the replicated loop's
# consume-next-step carry (parallel.replicated.OverlapCarry/DelayedState)
# generalized to every dp x {sp,tp,ep,pp} layout
# ---------------------------------------------------------------------------


def _delayed_produce_payload(codec, k_codec, grads, exchange: DpExchange):
    """PRODUCE half of the delayed exchange: encode THIS step's completed
    gradient under the same ``encode`` anchor (and the same stream-encode
    restructure) as the blocking tail — the payload at step t is
    bit-identical to what blocking mode would have put on the wire at
    step t (same ``k_codec`` fold, same plan). Returns the carry-shaped
    payload (leading per-device axis of length 1) and the byte stats."""
    with named_phase("encode"):
        if exchange.stream_encode:
            payloads, stats = encode_tree_streamed(
                codec, k_codec, grads,
                plan_layer_buckets(grads, exchange.stream_bucket_bytes),
            )
        else:
            payloads, stats = encode_tree(codec, k_codec, grads)
    payload_x = jax.tree_util.tree_map(lambda a: a[None], payloads)
    return payload_x, stats


def _delayed_consume(
    optimizer, codec, train, prev_payload, valid, *,
    dp_axis: str, n_dp: int, exchange: DpExchange,
):
    """CONSUME half: exchange -> decode-mean -> optimizer update on the
    PREVIOUS step's payload, computed from STEP-START values only. The
    ``optimization_barrier`` pins that boundary (the replicated loop's
    exact idiom): the chain is dataflow-independent of this step's
    forward/backward — which is the overlap — and the separately-jitted
    oracle's apply program compiles to the same arithmetic (bit-for-bit,
    tested). Stream-encode restructures the PRODUCE side only; payloads
    are bit-identical to the monolithic encode, so the consume side
    stays monolithic (the replicated family's documented choice).

    Step 0 consumes nothing (``valid=0``): params/opt state hold and
    ``metrics["skipped"]`` is 1 — the stale-by-one schedule's defined
    start."""
    from atomo_tpu.training.resilience import select_state

    params, opt_state, prev_payload, valid = jax.lax.optimization_barrier(
        (train.params, train.opt_state, prev_payload, valid)
    )
    if exchange.aggregate == "gather":
        with named_phase("exchange"):
            gathered = jax.lax.all_gather(prev_payload, dp_axis)
        with named_phase("decode_mean"):
            mean_grads = decode_mean_tree(codec, gathered, params, n_dp)
    else:  # ring — the same canonical staged mean as the blocking tail
        from atomo_tpu.parallel.replicated import _ring_stream_mean

        my = jax.lax.axis_index(dp_axis)
        with named_phase("ring_exchange_decode"):
            mean_grads, _ = _ring_stream_mean(
                codec, prev_payload, params,
                axis=dp_axis, n_dev=n_dp, my=my, n_contrib=n_dp,
                bucket_size=exchange.ring_bucket_size,
            )
    updates, new_opt = optimizer.update(mean_grads, opt_state, params)
    new_params = optax.apply_updates(params, updates)
    consume_ok = valid > 0  # step 0: nothing in flight -> full skip
    new_params = select_state(consume_ok, new_params, params)
    new_opt = select_state(consume_ok, new_opt, opt_state)
    new_train = TrainState(
        step=train.step + 1,
        params=new_params,
        batch_stats=train.batch_stats,
        opt_state=new_opt,
    )
    return new_train, {"skipped": 1.0 - consume_ok.astype(jnp.float32)}


def delayed_dp_exchange(
    optimizer, codec, train, carry, k_codec, grads, loss, *,
    dp_axis: str, n_dp: int, exchange: DpExchange,
):
    """The fused delayed dp tail of a model-axis step: produce this
    step's payload (:func:`_delayed_produce_payload`), consume the
    carried one (:func:`_delayed_consume`), return
    ``(new_train, new_carry, metrics)``. The carry holds the ENCODED
    payload on purpose (the :class:`~atomo_tpu.parallel.replicated.
    OverlapCarry` contract): the consume chain reads only step-start
    values, so the scheduler can run the exchange+decode underneath this
    step's forward/backward — and, on dp-pp, underneath the pipeline's
    drain ticks."""
    from atomo_tpu.parallel.replicated import OverlapCarry

    payload_x, stats = _delayed_produce_payload(codec, k_codec, grads, exchange)
    prev_payload = jax.tree_util.tree_map(
        lambda a: jnp.squeeze(a, 0), carry.payload
    )
    new_train, am = _delayed_consume(
        optimizer, codec, train, prev_payload, carry.valid,
        dp_axis=dp_axis, n_dp=n_dp, exchange=exchange,
    )
    metrics = {
        "loss": jax.lax.pmean(loss, dp_axis),
        "msg_bytes": jnp.asarray(stats.payload_bytes, jnp.float32),
        "dense_bytes": jnp.asarray(tree_nbytes(grads), jnp.float32),
        **am,
    }
    new_carry = OverlapCarry(
        payload=payload_x, ok=carry.ok, valid=jnp.float32(1.0)
    )
    return new_train, new_carry, metrics


def model_axis_carry_specs(mesh: Mesh):
    """The carry's PartitionSpec tree on a model-axis mesh: the leading
    per-device axis sharded over ALL mesh axes (every device owns the one
    row holding its own encoded slice — uniform across layouts because
    each shard encodes its model-sharded gradient locally), the scalar
    ``valid`` replicated."""
    from atomo_tpu.parallel.replicated import OverlapCarry

    axes = tuple(mesh.axis_names)
    return OverlapCarry(payload=P(axes), ok=P(axes), valid=P())


def place_model_axis_carry(mesh: Mesh, carry):
    """Place a host-side carry onto the mesh (fresh init, ``--resume``
    and the reshard drain all MUST place identically, or a restored
    trajectory drifts from an uninterrupted one — the replicated
    ``_place_carry`` contract on the model-axis sharding)."""
    from atomo_tpu.parallel.replicated import OverlapCarry

    sh = NamedSharding(mesh, P(tuple(mesh.axis_names)))
    return OverlapCarry(
        payload=jax.tree_util.tree_map(
            lambda a: jax.device_put(jnp.asarray(a), sh), carry.payload
        ),
        ok=jax.device_put(jnp.asarray(carry.ok), sh),
        valid=jax.device_put(
            jnp.asarray(carry.valid), NamedSharding(mesh, P())
        ),
    )


def init_model_axis_delayed_state(mesh: Mesh, state, codec):
    """Wrap a (possibly model-sharded) LM train state into the fresh
    :class:`~atomo_tpu.parallel.replicated.DelayedState` a delayed
    model-axis step consumes: zero payload rows shaped by eval_shape of
    the codec's encode over each device's LOCAL param-shard shapes (the
    gradient the device will encode), all-healthy flags, ``valid=0``."""
    from atomo_tpu.parallel.replicated import DelayedState, OverlapCarry

    n_total = 1
    for a in mesh.axis_names:
        n_total *= mesh.shape[a]

    def local_sds(leaf):
        return jax.ShapeDtypeStruct(
            tuple(leaf.sharding.shard_shape(leaf.shape)), leaf.dtype
        )

    local = jax.tree_util.tree_map(local_sds, state.params)
    shapes = jax.eval_shape(
        lambda p: encode_tree(codec, jax.random.PRNGKey(0), p)[0], local
    )
    payload = jax.tree_util.tree_map(
        lambda s: jnp.zeros((n_total,) + tuple(s.shape), s.dtype), shapes
    )
    carry = OverlapCarry(
        payload=payload,
        ok=jnp.ones((n_total,), jnp.float32),
        valid=jnp.float32(0.0),
    )
    return DelayedState(
        train=state, carry=place_model_axis_carry(mesh, carry)
    )


def make_delayed_model_axis_step(
    grads_fn, optimizer, codec, mesh: Mesh, *,
    dp_axis: str, n_dp: int, exchange: DpExchange,
    state_specs, token_spec, oracle_parts: bool = False,
):
    """Compile the delayed variant of a model-axis family: ``grads_fn``
    is the family's forward/backward closure — ``(train, key, tokens) ->
    (k_codec, grads, loss)`` with grads COMPLETED over the model axes —
    and this wrapper threads the stale-by-one carry around its dp tail.
    The jitted step is ``(DelayedState, key, tokens) -> (DelayedState,
    metrics)`` with the carry sharded per :func:`model_axis_carry_specs`.

    ``oracle_parts=True`` returns ``{"produce", "apply"}`` instead: the
    SAME closures, separately jitted — the two-program eager oracle
    tests/bench drive host-side to prove the fused program's stale-by-one
    schedule bit-exact (the replicated family's ``_oracle_parts``
    precedent)."""
    if codec is None:
        raise ValueError(
            "overlap='delayed' needs a codec: the carry holds encoded "
            "payloads (a dense delayed exchange has nothing to carry)"
        )
    from atomo_tpu.parallel.replicated import DelayedState

    sspec = state_specs if state_specs is not None else P()
    carry_spec = model_axis_carry_specs(mesh)
    axes_p = carry_spec.payload

    if oracle_parts:

        def produce_prog(train, key, tokens):
            k_codec, grads, loss = grads_fn(train, key, tokens)
            payload_x, stats = _delayed_produce_payload(
                codec, k_codec, grads, exchange
            )
            pm = {
                "loss": jax.lax.pmean(loss, dp_axis),
                "msg_bytes": jnp.asarray(stats.payload_bytes, jnp.float32),
                "dense_bytes": jnp.asarray(tree_nbytes(grads), jnp.float32),
            }
            return payload_x, pm

        def apply_prog(train, payload_x, valid):
            prev = jax.tree_util.tree_map(
                lambda a: jnp.squeeze(a, 0), payload_x
            )
            return _delayed_consume(
                optimizer, codec, train, prev, valid,
                dp_axis=dp_axis, n_dp=n_dp, exchange=exchange,
            )

        produce_j = compile_step(
            produce_prog, mesh,
            in_specs=(sspec, P(), token_spec),
            out_specs=(axes_p, P()),
            check_vma=False,
        )
        apply_j = compile_step(
            apply_prog, mesh,
            in_specs=(sspec, axes_p, P()),
            out_specs=(sspec, P()),
            check_vma=False,
        )
        return {"produce": produce_j, "apply": apply_j}

    def spmd_delayed(d, key, tokens):
        k_codec, grads, loss = grads_fn(d.train, key, tokens)
        new_train, new_carry, metrics = delayed_dp_exchange(
            optimizer, codec, d.train, d.carry, k_codec, grads, loss,
            dp_axis=dp_axis, n_dp=n_dp, exchange=exchange,
        )
        return DelayedState(train=new_train, carry=new_carry), metrics

    d_spec = DelayedState(train=sspec, carry=carry_spec)
    return compile_step(
        spmd_delayed, mesh,
        in_specs=(d_spec, P(), token_spec),
        out_specs=(d_spec, P()),
        donate_argnums=(0,),
        check_vma=False,
    )


def make_lm_train_step(
    lm_config: dict,
    optimizer,
    mesh: Mesh,
    codec=None,
    *,
    dp_axis: str = "dp",
    sp_axis: str = "sp",
    attn_impl: str = "ring",
    compute_dtype=None,
    aggregate: str = "gather",
    exchange: DpExchange | None = None,
    oracle_parts: bool = False,
):
    """Jitted (state, key, tokens) -> (state, metrics) with tokens (B, S)
    sharded batch-over-dp and sequence-over-sp. ``lm_config`` are
    TransformerLM kwargs (attention_fn is injected here). ``attn_impl``
    selects the sequence-parallel strategy: "ring" (ppermute K/V rotation,
    O(S/n) memory) or "ulysses" (two all_to_all collectives, blockwise
    local attention on H/n heads — see parallel.ring.ulysses_attention)."""
    if attn_impl not in ATTENTION_IMPLS:
        raise ValueError(
            f"unknown attn_impl {attn_impl!r}; expected one of "
            f"{sorted(ATTENTION_IMPLS)}"
        )
    # lazy: models.transformer imports parallel.ring, so a module-level
    # import here would cycle through parallel/__init__ (which exports tp,
    # which imports this module)
    from atomo_tpu.models.transformer import TransformerLM

    n_sp = mesh.shape[sp_axis]
    n_dp = mesh.shape[dp_axis]

    def grads_fn(state: TrainState, key, tokens):
        model = TransformerLM(
            **lm_config,
            attention_fn=partial(
                ATTENTION_IMPLS[attn_impl], axis_name=sp_axis,
                axis_size=n_sp, causal=True,
            ),
        )
        my_dp = jax.lax.axis_index(dp_axis)
        k_codec = jax.random.fold_in(
            jax.random.fold_in(key, state.step), my_dp
        )

        def loss_fn(params):
            if compute_dtype is not None:
                # bf16 MXU compute, f32 master state; token ids are integer
                # inputs, so only the params need the cast
                params = cast_params(params, compute_dtype)
            s_local = tokens.shape[1]
            logits = model.apply(
                {"params": params},
                tokens,
                train=True,
                pos_offset=jax.lax.axis_index(sp_axis) * s_local,
            )
            if compute_dtype is not None:
                logits = logits.astype(jnp.float32)
            targets, valid = sp_boundary_targets_and_mask(tokens, sp_axis, n_sp)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
            total = jax.lax.psum(jnp.sum(valid), sp_axis)
            return jax.lax.psum(jnp.sum(ce * valid), sp_axis) / total

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        # sp-PMEAN completes THIS replica's gradient (intra-replica, dense).
        # Mean, not sum: under shard_map the transpose of the loss psum is
        # itself a psum, so each shard's per-shard grads already carry an
        # n_sp factor (the replicated seed is summed across shards); summing
        # them again would scale the gradient by n_sp — a silent effective-LR
        # inflation verified empirically (tests/test_ring.py oracle parity).
        grads = jax.lax.pmean(grads, sp_axis)
        return k_codec, grads, loss

    def spmd_step(state: TrainState, key, tokens):
        k_codec, grads, loss = grads_fn(state, key, tokens)
        return dp_exchange_tail(
            optimizer, codec, state, k_codec, grads, loss,
            dp_axis=dp_axis, n_dp=n_dp, aggregate=aggregate,
            exchange=exchange,
        )

    if exchange is not None and exchange.overlap == "delayed":
        return make_delayed_model_axis_step(
            grads_fn, optimizer, codec, mesh,
            dp_axis=dp_axis, n_dp=n_dp, exchange=exchange,
            state_specs=None, token_spec=P(dp_axis, sp_axis),
            oracle_parts=oracle_parts,
        )

    # the ONE compile path (parallel.compile): construction byte-identical
    # to the hand-rolled jax.jit(jax.shard_map(...)) stack this builder
    # used to assemble inline (tested per program family)
    return compile_step(
        spmd_step,
        mesh,
        in_specs=(P(), P(), P(dp_axis, sp_axis)),
        out_specs=(P(), P()),
        donate_argnums=(0,),
    )


def shard_tokens(mesh: Mesh, tokens, dp_axis: str = "dp", sp_axis: str = "sp"):
    return jax.device_put(
        jnp.asarray(tokens), NamedSharding(mesh, P(dp_axis, sp_axis))
    )
