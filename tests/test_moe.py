"""Expert parallelism: switch-MoE routing, a2a sharding parity, step oracle.

Oracles: (1) with capacity >= T no token is dropped, so the MoE layer must
equal dense per-token chosen-expert compute; (2) the ep-sharded layer must
equal the single-device layer applied per token group (same per-group
capacity semantics); (3) a full (dp=2, ep=4) dense step must land on the
same params as single-device AD over the group-partitioned objective.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from atomo_tpu.codecs import SvdCodec
from atomo_tpu.parallel.mesh import make_mesh
from atomo_tpu.parallel.moe import (
    create_moe_lm_state,
    init_moe_lm_params,
    make_moe_lm_train_step,
    moe_lm_forward,
    moe_mlp,
    moe_param_specs,
    shard_moe_tokens,
)

CFG = dict(
    vocab_size=16, max_len=12, width=16, depth=2, num_heads=4, num_experts=4
)


pytestmark = pytest.mark.slow  # heavy multi-device compile/parity runs; deselect with -m "not slow"


def _moe_block_params(key, width=16, n_experts=4, f=32):
    kr, ku, kd = jax.random.split(key, 3)
    return {
        "router": {"kernel": jax.random.normal(kr, (width, n_experts)) * 0.5},
        "up": {"kernel": jax.random.normal(ku, (n_experts, width, f)) * 0.1},
        "down": {"kernel": jax.random.normal(kd, (n_experts, f, width)) * 0.1},
    }


def test_moe_no_drop_equals_dense_expert_choice():
    p = _moe_block_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (24, 16))
    out, _ = moe_mlp(p, x, capacity=24)  # capacity >= T: nothing dropped

    logits = x @ p["router"]["kernel"]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
    # dense: run every expert on every token, select
    h = jax.nn.gelu(jnp.einsum("tw,ewf->etf", x, p["up"]["kernel"]))
    y = jnp.einsum("etf,efw->etw", h, p["down"]["kernel"])
    want = y[expert, jnp.arange(24)] * gate[:, None]
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)


def test_moe_capacity_drops_tokens():
    p = _moe_block_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (24, 16))
    full, _ = moe_mlp(p, x, capacity=24)
    tight, _ = moe_mlp(p, x, capacity=1)
    # with 24 tokens over 4 experts and capacity 1 most tokens are dropped
    kept_full = np.count_nonzero(np.abs(np.asarray(full)).sum(-1) > 1e-7)
    kept_tight = np.count_nonzero(np.abs(np.asarray(tight)).sum(-1) > 1e-7)
    assert kept_full == 24
    assert kept_tight <= 4


def test_moe_sharded_layer_matches_grouped_oracle():
    """ep=4-sharded moe_mlp == vmapped single-device layer per token group."""
    n_ep, t_local, w = 4, 8, 16
    p = _moe_block_params(jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (n_ep * t_local, w))
    cap = 3

    # oracle: independent routing per group, all experts local
    want = jax.vmap(
        lambda xg: moe_mlp(p, xg, capacity=cap)[0]
    )(x.reshape(n_ep, t_local, w)).reshape(n_ep * t_local, w)

    mesh = make_mesh(4, axes=(("ep", 4),))
    sharded = jax.jit(
        jax.shard_map(
            lambda pp, xx: moe_mlp(pp, xx, capacity=cap, ep_axis="ep")[0],
            mesh=mesh,
            in_specs=(moe_param_specs(p), P("ep", None)),
            out_specs=P("ep", None),
            check_vma=False,
        )
    )
    p_sharded = jax.device_put(
        p, jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), moe_param_specs(p))
    )
    got = sharded(p_sharded, jax.device_put(x, NamedSharding(mesh, P("ep", None))))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_moe_step_matches_single_device():
    """One dense (dp=2, ep=4) update == single-device AD over the same
    group-partitioned objective (capacity semantics included)."""
    opt = optax.sgd(0.1, momentum=0.9)
    mesh = make_mesh(8, axes=(("dp", 2), ("ep", 4)))
    aux_w, cf = 0.01, 1.25
    tokens = jax.random.randint(jax.random.PRNGKey(7), (8, 10), 0, CFG["vocab_size"])

    params0 = init_moe_lm_params(jax.random.PRNGKey(0), CFG)

    n_dp, n_ep = 2, 4
    b_local = tokens.shape[0] // (n_dp * n_ep)
    t_local = b_local * tokens.shape[1]
    cap = max(1, math.ceil(cf * t_local / CFG["num_experts"]))

    def replica_loss(p, replica_tokens):
        groups = replica_tokens.reshape(n_ep, b_local, -1)
        total = 0.0
        for g in range(n_ep):
            logits, aux = moe_lm_forward(p, groups[g], CFG, capacity=cap)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], groups[g][:, 1:]
            )
            total = total + (jnp.sum(ce) + aux_w * aux * ce.size) / (
                n_ep * ce.size
            )
        return total

    def oracle_loss(p):
        reps = tokens.reshape(n_dp, tokens.shape[0] // n_dp, -1)
        return (replica_loss(p, reps[0]) + replica_loss(p, reps[1])) / 2.0

    grads = jax.grad(oracle_loss)(params0)
    want = jax.device_get(
        optax.apply_updates(params0, opt.update(grads, opt.init(params0), params0)[0])
    )

    from atomo_tpu.parallel.moe import make_moe_state_specs, shard_moe_state
    from atomo_tpu.training.trainer import TrainState

    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params0,
        batch_stats={},
        opt_state=opt.init(params0),
    )
    specs = make_moe_state_specs(state, moe_param_specs(params0))
    state = shard_moe_state(mesh, state, specs)
    step = make_moe_lm_train_step(
        CFG, opt, mesh, specs, codec=None,
        capacity_factor=cf, aux_weight=aux_w,
    )
    state2, metrics = step(
        state, jax.random.PRNGKey(1), shard_moe_tokens(mesh, tokens)
    )
    got = jax.device_get(state2.params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5
        ),
        got,
        want,
    )
    assert int(state2.step) == 1


def test_moe_step_with_codec_runs_and_learns():
    opt = optax.sgd(0.1, momentum=0.9)
    mesh = make_mesh(8, axes=(("dp", 2), ("ep", 4)))
    state, specs = create_moe_lm_state(mesh, CFG, opt, jax.random.PRNGKey(3))
    step = make_moe_lm_train_step(CFG, opt, mesh, specs, codec=SvdCodec(rank=2))
    # repeating pattern the LM can memorize
    row = jnp.arange(10, dtype=jnp.int32) % CFG["vocab_size"]
    tokens = jnp.tile(row[None], (8, 1))
    toks = shard_moe_tokens(mesh, tokens)
    losses = []
    st = state
    for i in range(12):
        st, m = step(st, jax.random.PRNGKey(i), toks)
        losses.append(float(m["loss"]))
    assert int(m["msg_bytes"]) < int(m["dense_bytes"])
    assert losses[-1] < losses[0] * 0.8, losses


def test_moe_rejects_indivisible_experts():
    mesh = make_mesh(8, axes=(("dp", 2), ("ep", 4)))
    bad = dict(CFG, num_experts=6)
    with pytest.raises(ValueError, match="num_experts"):
        create_moe_lm_state(mesh, bad, optax.sgd(0.1), jax.random.PRNGKey(0))


def test_moe_bf16_step_runs_and_keeps_f32_state():
    opt = optax.sgd(0.05, momentum=0.9)
    mesh = make_mesh(8, axes=(("dp", 2), ("ep", 4)))
    state, specs = create_moe_lm_state(mesh, CFG, opt, jax.random.PRNGKey(3))
    step = make_moe_lm_train_step(
        CFG, opt, mesh, specs, codec=SvdCodec(rank=2),
        compute_dtype=jnp.bfloat16,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(9), (8, 10), 0, 16)
    state, m = step(state, jax.random.PRNGKey(1), shard_moe_tokens(mesh, tokens))
    assert np.isfinite(float(m["loss"]))
    for leaf in jax.tree_util.tree_leaves(state.params):
        assert leaf.dtype == jnp.float32
