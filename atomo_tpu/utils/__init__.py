"""Shared utilities: metrics, logging, tracing."""

from atomo_tpu.utils.metrics import (  # noqa: F401
    StepMetrics,
    Timer,
    accuracy,
    master_line,
)
