#!/bin/bash
# Round-5 on-chip queue, second attempt — reordered after the first TPU
# window (03:48-~04:05) was spent on tests_tpu and died mid-bench when the
# relay wedged. Lessons applied:
#   - bench FIRST: the round's make-or-break (VERDICT r4 #1) and its ladder
#     already emits the config-2 headline before the long tail.
#   - convergence artifact NOT here: it runs on CPU in parallel (the gate is
#     a statistics artifact, not a hardware one).
#   - tests_tpu LAST with per-file timeouts so one wedged dial cannot eat
#     the window.
set -u
cd "$(dirname "$0")/.."
OUT=artifacts/onchip_r5
mkdir -p "$OUT"
TS() { date +%H:%M:%S; }

echo "$(TS) queue-b start" | tee -a "$OUT/queue.log"

echo "$(TS) [1/5] bench --all" | tee -a "$OUT/queue.log"
timeout 7200 python bench.py --all > "$OUT/bench_all.jsonl" 2> "$OUT/bench_all.err"
rc=$?; echo "$(TS) bench rc=$rc" | tee -a "$OUT/queue.log"

echo "$(TS) [2/5] encode_profile" | tee -a "$OUT/queue.log"
timeout 2400 python scripts/encode_profile.py --out "$OUT" \
  > "$OUT/encode_profile.log" 2>&1
rc=$?; echo "$(TS) encode_profile rc=$rc" | tee -a "$OUT/queue.log"

echo "$(TS) [3/5] bf16_probe" | tee -a "$OUT/queue.log"
timeout 2400 python scripts/bf16_probe.py > "$OUT/bf16_probe.log" 2>&1
rc=$?; echo "$(TS) bf16_probe rc=$rc" | tee -a "$OUT/queue.log"

echo "$(TS) [4/5] convergence artifact (resnet18 hardened; minutes on chip," \
     "hopeless on the 1-core CPU host)" | tee -a "$OUT/queue.log"
timeout 3600 python scripts/convergence_artifact.py --out "$OUT" \
  > "$OUT/convergence.log" 2>&1
rc=$?; echo "$(TS) convergence rc=$rc" | tee -a "$OUT/queue.log"

echo "$(TS) [5/5] tests_tpu (per-file budgets)" | tee -a "$OUT/queue.log"
for f in tests_tpu/test_codecs_tpu.py tests_tpu/test_attention_tpu.py \
         tests_tpu/test_qsgd_tpu.py; do
  timeout 1200 python -m pytest "$f" -q --tb=line -p no:cacheprovider \
    >> "$OUT/tests_tpu_b.log" 2>&1
  rc=$?; echo "$(TS) $f rc=$rc" | tee -a "$OUT/queue.log"
done

echo "$(TS) queue-b done" | tee -a "$OUT/queue.log"
