"""Fleet control plane (host-level leases -> membership epochs).

Contracts pinned here:

  * Lease expiry is MONOTONIC-BEAT based, never wall-clock: forged /
    absurd ``ts`` values cannot change a staleness verdict (satellite:
    two hosts with skewed clocks must not mutually evict each other).
  * ``fold_leases`` is the pure transition function: shrink records the
    dead, grow respects the re-admission budget and refuses OUT LOUD
    when it is spent, a fleet of one is still viable.
  * ``roster_hash`` is order-insensitive; ``current_roster_hash``
    prefers the newest host-granularity membership epoch, falls back to
    the lease files, and returns None on a pre-fleet train_dir.
  * ``decision_reusable`` refuses a resume onto a DIFFERENT host roster
    at the same device count, and states the pre-fleet fallback when
    the artifact predates the roster record.
  * The host-level chaos verbs (hostdie@ / slowlink@ / partition@)
    parse, inject at the lease layer, and stay epoch-keyed.
  * Two FleetControllers over one shared train_dir drill the full
    story in process: form -> partition -> lease_stale -> shrink ->
    heal -> stand_down -> re-admit -> budget-refusal, and the fleet
    report's two checks hold over the artifacts they left.
  * The REAL 2-process drill (subprocess launcher + jax.distributed
    formation): form at world 2, shrink to 1, re-form, re-admit,
    re-form again — gated on ``report --fleet --strict`` rc=0.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

from atomo_tpu.elastic.membership import MembershipEpoch, MembershipLog
from atomo_tpu.fleet.control import (
    FleetConfig,
    FleetController,
    HostLease,
    LeaseTracker,
    current_roster_hash,
    fold_leases,
    host_metrics_path,
    hosts_dir,
    read_leases,
    roster_hash,
    write_lease,
)
from atomo_tpu.utils.chaos import ChaosConfig, ChaosInjector
from atomo_tpu.utils.tracing import IncidentLog

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- leases


def test_roster_hash_order_insensitive():
    assert roster_hash([2, 0, 1]) == roster_hash((0, 1, 2))
    assert roster_hash([0, 1]) != roster_hash([0, 2])


def test_lease_roundtrip_and_torn_file_skipped(tmp_path):
    d = str(tmp_path)
    write_lease(d, HostLease(host_id=0, beat=3, epoch=1, step=7, ts=1.5))
    write_lease(d, HostLease(host_id=2, beat=9))
    with open(os.path.join(hosts_dir(d), "1.json"), "w") as f:
        f.write('{"host_id": 1, "beat":')  # torn
    leases = read_leases(d)
    assert sorted(leases) == [0, 2]  # the torn lease reads as absent
    assert leases[0].beat == 3 and leases[0].epoch == 1
    assert leases[2].beat == 9


def test_lease_staleness_is_beat_based_never_wallclock():
    """Satellite witness: the tracker's verdict is a pure function of
    the beat counters and the observer's own rounds — leases carrying
    FORGED timestamps (ancient, far-future, jumping backwards) produce
    exactly the same staleness verdicts."""
    def drive(ts_fn):
        t = LeaseTracker(patience=3)
        verdicts = []
        # rounds 1..4: host 1's beat advances (ancient/forged ts)
        for r in range(1, 5):
            t.observe({
                0: HostLease(host_id=0, beat=r, ts=ts_fn(0, r)),
                1: HostLease(host_id=1, beat=r, ts=ts_fn(1, r)),
            })
            verdicts.append(frozenset(t.stale()))
        # rounds 5..8: host 1's beat FREEZES while its ts stays fresh
        for r in range(5, 9):
            t.observe({
                0: HostLease(host_id=0, beat=r, ts=ts_fn(0, r)),
                1: HostLease(host_id=1, beat=4, ts=ts_fn(1, r)),
            })
            verdicts.append(frozenset(t.stale()))
        return verdicts

    honest = drive(lambda h, r: 1000.0 + r)
    forged = drive(
        lambda h, r: [-1.0, 1e12, 0.0, 3.5e9][(h + r) % 4]  # garbage
    )
    assert honest == forged  # ts never reaches the verdict
    assert honest[3] == frozenset()          # beating -> never stale
    assert honest[-1] == frozenset({1})      # frozen beat -> stale
    assert honest[5] == frozenset()          # ...but only past patience


def test_lease_tracker_missing_file_and_formation_grace():
    t = LeaseTracker(patience=2)
    t.observe({0: HostLease(host_id=0, beat=1)}, expected=(0, 1))
    assert t.stale() == set()        # host 1 never formed: grace round 1
    t.observe({0: HostLease(host_id=0, beat=2)}, expected=(0, 1))
    assert t.stale() == {1}          # grace spent at patience
    # a lease file that disappears counts as a non-advancing beat
    t2 = LeaseTracker(patience=2)
    t2.observe({0: HostLease(host_id=0, beat=1),
                1: HostLease(host_id=1, beat=1)})
    t2.observe({0: HostLease(host_id=0, beat=2)})
    t2.observe({0: HostLease(host_id=0, beat=3)})
    assert t2.stale() == {1} and t2.alive() == {0}


# ----------------------------------------------------- fold_leases


def _epoch(epoch=0, roster=(0, 1, 2), reason="init", step=0):
    return MembershipEpoch(
        epoch=epoch, world_size=len(roster), roster=tuple(roster),
        start_step=step, reason=reason,
        detail={"granularity": "host"},
    )


def test_fold_leases_shrink_records_dead():
    rec, why = fold_leases(
        _epoch(), {0, 2}, step=9, full_roster=(0, 1, 2),
        grows=0, max_regrows=1,
    )
    assert why is None
    assert rec.epoch == 1 and rec.roster == (0, 2) and rec.dead == (1,)
    assert rec.reason == "shrink" and rec.start_step == 9
    # a fleet of ONE host is still viable (it holds a full local mesh)
    rec2, _ = fold_leases(
        rec, {0}, step=11, full_roster=(0, 1, 2), grows=0, max_regrows=1,
    )
    assert rec2.roster == (0,)
    # ...but zero survivors is a refusal, not an epoch
    rec3, why3 = fold_leases(
        rec2, set(), step=12, full_roster=(0, 1, 2), grows=0,
        max_regrows=1,
    )
    assert rec3 is None and "no surviving hosts" in why3


def test_fold_leases_grow_and_budget_refusal():
    cur = _epoch(epoch=1, roster=(0, 2), reason="shrink")
    rec, why = fold_leases(
        cur, {0, 1, 2}, step=20, full_roster=(0, 1, 2),
        grows=0, max_regrows=1,
    )
    assert why is None and rec.reason == "grow" and rec.roster == (0, 1, 2)
    # spent budget: refusal carries the human reason
    rec2, why2 = fold_leases(
        cur, {0, 1, 2}, step=20, full_roster=(0, 1, 2),
        grows=1, max_regrows=1,
    )
    assert rec2 is None and "re-admission budget is spent" in why2
    # steady state: nothing to do, no reason either
    rec3, why3 = fold_leases(
        _epoch(), {0, 1, 2}, step=5, full_roster=(0, 1, 2),
        grows=0, max_regrows=1,
    )
    assert rec3 is None and why3 is None


# ------------------------------------------------- current_roster_hash


def test_current_roster_hash_sources(tmp_path):
    d = str(tmp_path)
    assert current_roster_hash(None) is None
    assert current_roster_hash(d) is None  # pre-fleet: no evidence
    # leases alone imply a roster
    write_lease(d, HostLease(host_id=0, beat=1))
    write_lease(d, HostLease(host_id=1, beat=1))
    assert current_roster_hash(d) == roster_hash((0, 1))
    # a host-granularity membership epoch WINS over the lease set
    log = MembershipLog.load(d)
    log.append(_epoch(epoch=0, roster=(0, 1, 2)))
    assert current_roster_hash(d) == roster_hash((0, 1, 2))
    # a replica-granularity epoch is NOT fleet evidence
    d2 = str(tmp_path / "replica")
    os.makedirs(d2)
    log2 = MembershipLog.load(d2)
    log2.append(MembershipEpoch(
        epoch=0, world_size=4, roster=(0, 1, 2, 3), start_step=0,
        reason="init",
    ))
    assert current_roster_hash(d2) is None


def test_decision_reusable_fleet_roster_gate():
    """Same device count, different hosts -> refuse out loud; an
    artifact that PREDATES the roster record falls back to the device
    count alone and SAYS so."""
    from atomo_tpu.tuning.autopilot import decision_reusable

    h = roster_hash((0, 1))
    doc = {
        "complete": True,
        "winner": {"knobs": {"aggregate": "gather"}},
        "meta": {"n_devices": 4, "fleet_roster_hash": h},
    }
    ok, why = decision_reusable(doc, n_dev=4, fleet_roster=h)
    assert ok, why
    other = roster_hash((0, 2))
    ok, why = decision_reusable(doc, n_dev=4, fleet_roster=other)
    assert not ok and h in why and other in why
    assert "roster" in why
    legacy = {
        "complete": True,
        "winner": {"knobs": {"aggregate": "gather"}},
        "meta": {"n_devices": 4},
    }
    ok, why = decision_reusable(legacy, n_dev=4, fleet_roster=other)
    assert ok
    assert "predates the fleet roster record" in why


# ------------------------------------------------------- chaos verbs


def test_chaos_host_verbs_parse_and_reject():
    cfg = ChaosConfig.from_spec(
        "hostdie@3:1,slowlink@2:0:0.5,partition@4:0-1:2.0"
    )
    assert cfg.host_die_faults == ((3, 1),)
    assert cfg.slowlink_faults == ((2, 0, 0.5),)
    assert cfg.partition_faults == ((4, 0, 1, 2.0),)
    with pytest.raises(ValueError, match="distinct"):
        ChaosConfig.from_spec("partition@4:1-1:2.0")
    with pytest.raises(ValueError, match="slowlink needs both"):
        ChaosConfig.from_spec("slowlink@2:0")
    with pytest.raises(ValueError, match="delay must be > 0"):
        ChaosConfig.from_spec("slowlink@2:0:0")


def test_chaos_partition_window_and_epoch_keying():
    inj = ChaosInjector(
        ChaosConfig.from_spec("partition@3:0-1:2.0"), membership_epoch=0
    )
    clock = iter([10.0, 10.5, 11.9, 12.5]).__next__
    assert not inj.store_partitioned(2, 1, now=clock)  # before round 3
    # conftest note: the first active round stamps t0 = 10.0
    assert inj.store_partitioned(3, 1, now=lambda: 10.0)
    assert inj.store_partitioned(4, 1, now=lambda: 11.9)   # inside 2 s
    assert not inj.store_partitioned(5, 1, now=lambda: 12.5)  # healed
    # the LOWER id of the pair keeps the store (colocation fence)
    assert not inj.store_partitioned(3, 0, now=lambda: 10.0)
    # epoch-keyed: a re-admitted host comes back healthy
    inj2 = ChaosInjector(
        ChaosConfig.from_spec("partition@3:0-1:2.0"), membership_epoch=1
    )
    assert not inj2.store_partitioned(3, 1, now=lambda: 10.0)
    # slowlink: pure lag table, epoch-keyed the same way
    s = ChaosInjector(
        ChaosConfig.from_spec("slowlink@2:1:0.25"), membership_epoch=0
    )
    assert s.slowlink_delay(1, 1) == 0.0
    assert s.slowlink_delay(2, 1) == 0.25
    assert s.slowlink_delay(2, 0) == 0.0
    s.membership_epoch = 1
    assert s.slowlink_delay(2, 1) == 0.0


# ------------------------------- two controllers, one store, in process


def _drive(ctrl, r):
    ctrl.heartbeat(step=r)
    ctrl.observe()
    status = ctrl.reconcile()
    ctrl.maybe_transition(step=r)
    ctrl.record_metrics(step=r, status=status)


def test_two_controllers_full_story_and_fleet_report(tmp_path):
    """Form -> host 1 silent -> lease_stale -> shrink -> heal ->
    stand_down -> re-admit -> second death -> budget refusal; then the
    fleet report's two checks hold over the artifacts this left."""
    d = str(tmp_path)
    cfg = FleetConfig(patience=2, period_s=0.01, max_regrows=1)
    logs = []
    c0 = FleetController(cfg, d, 0, 2, log_fn=logs.append)
    c1 = FleetController(cfg, d, 1, 2, log_fn=logs.append)
    c0.adopt()
    c1.adopt()
    for r in range(1, 4):          # both healthy
        _drive(c0, r)
        _drive(c1, r)
    for r in range(4, 8):          # host 1 silent (partitioned)
        _drive(c0, r)
    log = MembershipLog.load(d)
    assert [(e.epoch, tuple(e.roster)) for e in log.epochs] == [
        (0, (0, 1)), (1, (0,))
    ]
    assert log.epochs[1].dead == (1,)
    for r in range(8, 12):         # host 1 heals: stand down, re-admit
        _drive(c1, r)
        _drive(c0, r)
    log = MembershipLog.load(d)
    assert [(e.epoch, e.reason) for e in log.epochs] == [
        (0, "init"), (1, "shrink"), (2, "grow")
    ]
    inc0 = IncidentLog.read(
        os.path.join(hosts_dir(d), "0.incidents.jsonl")
    )
    assert any(r["cause"] == "lease_stale" and r["host"] == 1
               for r in inc0)
    inc1 = IncidentLog.read(
        os.path.join(hosts_dir(d), "1.incidents.jsonl")
    )
    assert any(r.get("action") == "stand_down" for r in inc1)
    # second death: shrink again, but the re-grow budget is spent
    for r in range(12, 16):
        _drive(c0, r)
    for r in range(16, 19):
        _drive(c1, r)
        _drive(c0, r)
    log = MembershipLog.load(d)
    assert [e.reason for e in log.epochs] == [
        "init", "shrink", "grow", "shrink"
    ]
    inc0 = IncidentLog.read(
        os.path.join(hosts_dir(d), "0.incidents.jsonl")
    )
    refused = [r for r in inc0 if r.get("action") == "transition_refused"]
    assert refused and "budget is spent" in refused[-1]["reason"]
    # the leader is positional: host 1 never wrote membership.json
    assert not any(
        r.get("action") in ("shrink", "grow") for r in inc1
    )

    from atomo_tpu.obs.report import build_fleet_report

    doc = build_fleet_report(d)
    checks = {c["name"]: c for c in doc["checks"]}
    for name in ("fleet_membership_consistent", "fleet_lease_gap_explained"):
        assert not checks[name]["skipped"], checks[name]
        assert checks[name]["ok"], checks[name]
    assert doc["summary"]["final_roster"] == [0]
    assert doc["summary"]["final_roster_hash"] == roster_hash((0,))


def test_fleet_report_fails_on_unexplained_gap(tmp_path):
    """A forged evidence stream with a hole and NO recorded explanation
    must FAIL the gap check — silent evidence loss is the failure the
    control plane exists to rule out."""
    d = str(tmp_path)
    log = MembershipLog.load(d)
    log.append(_epoch(epoch=0, roster=(0,)))
    os.makedirs(hosts_dir(d), exist_ok=True)
    with open(host_metrics_path(d, 0), "a") as f:
        for step in (1, 2, 9, 10):  # rounds 3..8 vanished, nobody said so
            f.write(json.dumps({
                "ts": 0.0, "host": 0, "round": step, "beat": step,
                "step": step, "epoch": 0,
            }) + "\n")

    from atomo_tpu.obs.report import build_fleet_report

    doc = build_fleet_report(d)
    checks = {c["name"]: c for c in doc["checks"]}
    gap = checks["fleet_lease_gap_explained"]
    assert not gap["ok"]
    assert "no lease_stale/stand_down/shrink record" in gap["detail"]
    assert not doc["consistent"]


# ------------------------------------- the real 2-process drill


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch_member(train_dir, host_id, port, extra=()):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _REPO_ROOT + os.pathsep
        + os.environ.get("PYTHONPATH", ""),
    }
    cmd = [
        sys.executable, "-m", "atomo_tpu.fleet.launcher",
        "--train-dir", str(train_dir), "--host-id", str(host_id),
        "--n-hosts", "2", "--rounds", "400", "--period", "0.05",
        "--patience", "4", "--stop-epoch", "2", "--max-seconds", "60",
        "--init-timeout", "20",
        "--chaos", "partition@3:0-1:0.8", *extra,
    ]
    if port is not None:
        cmd += ["--coordinator", f"127.0.0.1:{port}"]
    return subprocess.Popen(
        cmd, env=env, cwd=_REPO_ROOT,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def _result_line(stdout):
    for line in stdout.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    return None


def test_two_process_formation_drill_forms_shrinks_reforms(tmp_path):
    """The tentpole drill with REAL jax.distributed formation: world 2
    forms, a store partition shrinks it to 1 (the survivor re-forms
    alone after the excluded host joins the shutdown barrier), the
    healed host is re-admitted and BOTH re-form at world 2 — then
    ``report --fleet --strict`` holds (rc=0)."""
    d = tmp_path / "fleet"
    port = _free_port()
    procs = [
        _launch_member(d, 0, port),
        _launch_member(d, 1, port),
    ]
    outs = [p.communicate(timeout=120) for p in procs]
    results = {}
    for (out, err), p in zip(outs, procs):
        assert p.returncode == 0, (out[-2000:], err[-2000:])
        r = _result_line(out)
        assert r is not None, out[-2000:]
        results[r["host"]] = r
    assert sorted(results) == [0, 1]
    for r in results.values():
        assert r["formed"] and r["member"]
        assert r["epoch"] == 2 and r["world"] == 2
    assert results[0]["roster_hash"] == results[1]["roster_hash"]
    assert results[0]["reforms"] == 2  # world 1 at epoch 1, 2 at epoch 2
    assert results[1]["reforms"] == 1  # rejoined at epoch 2
    assert results[1]["cut_rounds"] > 0

    # the excluded host recorded its half of the barrier story
    inc1 = IncidentLog.read(
        os.path.join(hosts_dir(str(d)), "1.incidents.jsonl")
    )
    assert any(
        r.get("action") == "collective_released" for r in inc1
    ), inc1

    rc = subprocess.run(
        [sys.executable, "-m", "atomo_tpu.cli", "report", "--train-dir",
         str(d), "--fleet", "--strict"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120, cwd=_REPO_ROOT,
    )
    assert rc.returncode == 0, (rc.stdout[-2000:], rc.stderr[-2000:])
    assert "consistency: OK" in rc.stdout
