"""atomo_tpu — TPU-native framework for communication-efficient distributed SGD
via atomic gradient sparsification.

A ground-up JAX/XLA/Pallas re-design of the capabilities of hwang595/ATOMO
(NeurIPS 2018): unbiased gradient compression (SVD atomic sparsification,
QSGD/TernGrad quantization, lossless packing) embedded in synchronous
data-parallel training — expressed as SPMD programs over a `jax.sharding.Mesh`
instead of an MPI parameter server.

Layer map (TPU-native analogue of reference SURVEY.md §1):
  codecs/    jit-compiled gradient compression kernels   (ref: src/codings/)
  models/    Flax model zoo                              (ref: src/model_ops/)
  training/  single-host + replicated trainers, optim    (ref: src/nn_ops.py,
             src/distributed_worker.py, src/sync_replicas_master_nn.py)
  parallel/  mesh, shard_map step functions, collectives (ref: mpi4py calls)
  data/      datasets + input pipeline                   (ref: src/datasets.py)
  utils/     metrics, logging, byte accounting           (ref: scattered)
  native/    C++ host-side runtime (lossless codec)      (ref: python-blosc)
"""

__version__ = "0.1.0"

from atomo_tpu import compat as _compat

_compat.install()  # jax API drift (shard_map location/kwargs) — see compat.py

from atomo_tpu.codecs import get_codec  # noqa: E402,F401
