"""CIFAR VGG 11/13/16/19 (+BN variants), as Flax modules.

Architecture parity with src/model_ops/vgg.py:15-108: feature configs
A/B/D/E (3x3 convs, 'M' = 2x2 maxpool), classifier
Dropout -> 512 -> ReLU -> Dropout -> 512 -> ReLU -> num_classes.
The reference CLI's VGG11 is the batch-norm variant (vgg11_bn,
src/distributed_worker.py:153-154).

Deviations: NHWC; He-normal conv init matches the reference's manual
normal_(0, sqrt(2/n)) fan-out init (vgg.py:32-36).
"""

from __future__ import annotations

from typing import Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

CFGS: dict[str, list] = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Module):
    cfg: Sequence[Union[int, str]]
    batch_norm: bool = False
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(v), (3, 3), padding=1, kernel_init=kernel_init)(x)
                if self.batch_norm:
                    x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(512)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(512)(x))
        return nn.Dense(self.num_classes)(x)


def _vgg(cfg: str, bn: bool, num_classes: int) -> VGG:
    return VGG(cfg=tuple(CFGS[cfg]), batch_norm=bn, num_classes=num_classes)


def vgg11(num_classes: int = 10) -> VGG:
    return _vgg("A", False, num_classes)


def vgg11_bn(num_classes: int = 10) -> VGG:
    return _vgg("A", True, num_classes)


def vgg13(num_classes: int = 10) -> VGG:
    return _vgg("B", False, num_classes)


def vgg13_bn(num_classes: int = 10) -> VGG:
    return _vgg("B", True, num_classes)


def vgg16(num_classes: int = 10) -> VGG:
    return _vgg("D", False, num_classes)


def vgg16_bn(num_classes: int = 10) -> VGG:
    return _vgg("D", True, num_classes)


def vgg19(num_classes: int = 10) -> VGG:
    return _vgg("E", False, num_classes)


def vgg19_bn(num_classes: int = 10) -> VGG:
    return _vgg("E", True, num_classes)
