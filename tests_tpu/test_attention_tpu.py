"""Flash-attention Pallas kernel compiled by Mosaic on the real chip.

The CPU suite (tests/test_attention_kernels.py) runs the same comparisons
under the TPU-semantics interpreter; this file is the hardware half of the
round-2 discipline: Mosaic-only lowering (dot_general shapes, iota layouts,
the dynamic-bound fori_loop) has no CPU path, so only an on-chip compile
can catch its regressions.
"""

import jax
import jax.numpy as jnp
import numpy as np


def _qkv(key, b=2, h=4, s=256, d=64):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(key), 3)
    return (
        jax.random.normal(kq, (b, h, s, d), jnp.float32),
        jax.random.normal(kk, (b, h, s, d), jnp.float32),
        jax.random.normal(kv, (b, h, s, d), jnp.float32),
    )


def test_flash_compiles_and_matches_on_tpu():
    from atomo_tpu.ops.attention_kernels import flash_attention
    from atomo_tpu.parallel.ring import full_attention

    q, k, v = _qkv(0)
    got = jax.jit(
        lambda q, k, v: flash_attention(q, k, v, causal=True)
    )(q, k, v)
    want = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-2, rtol=2e-2
    )


def test_flash_grad_compiles_on_tpu():
    from atomo_tpu.ops.attention_kernels import flash_attention

    q, k, v = _qkv(1, s=128)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    grads = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for g in grads:
        assert np.all(np.isfinite(np.asarray(g)))
