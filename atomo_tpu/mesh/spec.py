"""Mesh description layer — ONE grammar for every device layout.

Every program family in the repo runs over a ``jax.sharding.Mesh`` whose
shape used to be re-derived ad hoc at each call site (``make_mesh(n)``
here, ``make_mesh(n, axes=(("dp", k), ("ici", n // k)))`` there, a bare
``n_devices`` int in the tune decision). :class:`MeshSpec` is the single
description those sites now share:

  * ``dp`` is always the first (outer, slow-fabric) data axis;
  * ``--dcn-ways K`` declares a SECOND data axis ``ici`` (the fast
    fabric): the mesh is ``(dp=K, ici=n/K)`` and the data-parallel world
    is the product;
  * the degenerate shapes are first-class, not special cases: a 1-device
    mesh is ``dp1`` and a flat data-parallel mesh is ``dpN`` — the same
    spec grammar, the same compile path
    (:func:`atomo_tpu.parallel.compile.compile_step`), the same artifact
    record.

``shape_dict()`` is the artifact form (``{"dp": 2, "ici": 2}``) — the
tune decision's ``meta.mesh_axes`` and the elastic membership record both
carry it, and :func:`atomo_tpu.tuning.autopilot.decision_reusable`
compares it on resume (an ``n_devices``-only check cannot tell ``dp4``
from ``dp2 x ici2``, which are different program families).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax

#: Model axes the layout grammar understands, in the order they appear in
#: a layout name. These shard the MODEL (or the sequence), not the batch
#: replicas: gradients are completed ACROSS them (psum / pmean) before the
#: data-parallel exchange, so the compressed dp wire never sees them.
MODEL_AXES = ("tp", "pp", "ep", "sp")

#: The LM layout grammar (cli ``lm --layout``): layout name -> the model
#: axes it adds after ``dp``. ``dp-tp-sp`` is the 3-D Megatron x ring
#: composition; everything else is 2-D.
LAYOUT_MODEL_AXES = {
    "dp": (),
    "dp-sp": ("sp",),
    "dp-tp": ("tp",),
    "dp-ep": ("ep",),
    "dp-pp": ("pp",),
    "dp-tp-sp": ("tp", "sp"),
}


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """An ordered tuple of named mesh axes, e.g. ``(("dp", 2), ("ici", 2))``.

    Immutable and hashable so it can ride static closures and dict keys;
    build the runtime ``jax.sharding.Mesh`` with :meth:`build`.
    """

    axes: tuple[tuple[str, int], ...]

    def __post_init__(self):
        if not self.axes:
            raise ValueError("MeshSpec needs at least one axis")
        names = [a for a, _ in self.axes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate mesh axis names: {names}")
        for name, size in self.axes:
            if size < 1:
                raise ValueError(f"mesh axis {name!r} has size {size}")

    # ----------------------------------------------------------- builders
    @classmethod
    def from_world(cls, n_devices: int, dcn_ways: int = 0) -> "MeshSpec":
        """The ONE resolution of (--n-devices, --dcn-ways) to a mesh shape.

        ``dcn_ways`` <= 1 is the flat (or degenerate 1-device) data-parallel
        mesh ``dpN``; ``dcn_ways`` > 1 is the two-tier ``dpK x ici(N/K)``
        mesh the hierarchical schedules run on. The divisibility contract
        matches the CLI preflight: K must divide N.
        """
        n = int(n_devices)
        k = int(dcn_ways)
        if n < 1:
            raise ValueError(f"n_devices must be >= 1, got {n}")
        if k > 1:
            if n % k or not 1 < k <= n:
                raise ValueError(
                    f"dcn_ways {k} must divide n_devices {n} "
                    "(outer slow-fabric groups x inner fast-fabric chips)"
                )
            return cls((("dp", k), ("ici", n // k)))
        return cls((("dp", n),))

    @classmethod
    def from_layout(
        cls, layout: str, n_devices: int, ways=1
    ) -> "MeshSpec":
        """The ONE resolution of (``--layout``, ``--ways``) to a mesh shape
        — the LM model-axis counterpart of :meth:`from_world`.

        Reproduces exactly the axes tuples ``cli.cmd_lm`` used to hand
        ``make_mesh`` (same axes -> same mesh -> same compiled program):
        ``dp`` is ``(dp=N, sp=1)`` (the dp x sp step with a degenerate
        sequence axis — same program text, degenerate shape), the 2-D
        layouts are ``(dp=N/ways, <axis>=ways)``, and ``dp-tp-sp`` takes
        ``ways`` as a ``(tp, sp)`` pair. Divisibility mirrors the CLI
        preflight: the model ways must divide the device count.
        """
        if layout not in LAYOUT_MODEL_AXES:
            raise ValueError(
                f"unknown layout {layout!r}; expected one of "
                f"{sorted(LAYOUT_MODEL_AXES)}"
            )
        n = int(n_devices)
        if n < 1:
            raise ValueError(f"n_devices must be >= 1, got {n}")
        model = LAYOUT_MODEL_AXES[layout]
        if layout == "dp-tp-sp":
            try:
                tp_ways, sp_ways = (int(w) for w in ways)
            except TypeError:
                raise ValueError(
                    "layout 'dp-tp-sp' takes ways as a (tp, sp) pair"
                ) from None
            sizes = (tp_ways, sp_ways)
        else:
            sizes = (int(ways),) * len(model)
        m = 1
        for s in sizes:
            if s < 1:
                raise ValueError(f"model ways must be >= 1, got {s}")
            m *= s
        if n % m:
            raise ValueError(
                f"model ways {m} (layout {layout!r}) does not divide "
                f"{n} devices"
            )
        if layout == "dp":
            # cmd_lm's dp layout runs the dp x sp program with sp=1 —
            # keep the axes tuple identical so the program family is too
            return cls((("dp", n), ("sp", 1)))
        return cls(
            (("dp", n // m),) + tuple(zip(model, sizes))
        )

    @classmethod
    def from_shape_dict(cls, d) -> Optional["MeshSpec"]:
        """Inverse of :meth:`shape_dict` for artifact round-trips.

        Axis order in the artifact dict is meaningful (dp is outer);
        returns None for a missing/empty/garbage document rather than
        raising — resume code treats that as "old artifact, shape
        unrecorded" and falls back to the n_devices check.
        """
        if not isinstance(d, dict) or not d:
            return None
        try:
            axes = tuple((str(k), int(v)) for k, v in d.items())
            return cls(axes)
        except (TypeError, ValueError):
            return None

    # ---------------------------------------------------------- properties
    @property
    def names(self) -> tuple[str, ...]:
        return tuple(a for a, _ in self.axes)

    @property
    def n_devices(self) -> int:
        n = 1
        for _, s in self.axes:
            n *= s
        return n

    @property
    def data_axes(self) -> tuple[str, ...]:
        """The axes the batch (and the sharded update) spans: ``("dp",)``
        flat, ``("dp", "ici")`` two-tier."""
        return tuple(n for n in self.names if n in ("dp", "ici"))

    @property
    def model_axes(self) -> tuple[tuple[str, int], ...]:
        """The non-data (model/sequence) axes with their sizes, in mesh
        order — empty for the pure data-parallel shapes. Degenerate
        size-1 model axes are included (they are part of the program
        family: ``dp4 x sp1`` and ``dp4`` lower differently)."""
        return tuple(
            (n, s) for n, s in self.axes if n not in ("dp", "ici")
        )

    @property
    def inner_axis(self) -> Optional[str]:
        return "ici" if "ici" in self.names else None

    @property
    def is_two_tier(self) -> bool:
        return self.inner_axis is not None

    @property
    def is_degenerate(self) -> bool:
        """One device: every collective is the identity and the sharded
        update's slice is the whole vector — same program text, degenerate
        shape."""
        return self.n_devices == 1

    @property
    def is_flat(self) -> bool:
        return not self.is_two_tier

    # ----------------------------------------------------------- renderers
    def shape_dict(self) -> dict:
        """Artifact form: insertion-ordered ``{"dp": K, "ici": M}``."""
        return {name: size for name, size in self.axes}

    def describe(self) -> str:
        """Human grammar: ``dp4``, ``dp2xici2`` — the string log lines and
        bench rows print."""
        return "x".join(f"{n}{s}" for n, s in self.axes)

    def layout_name(self) -> str:
        """The ``--layout`` string this shape answers to: the inverse of
        :meth:`from_layout` up to degenerate model axes (``dp4 x sp1``
        renders as ``dp`` — that IS the layout the CLI built it from).
        Raises for shapes outside the LM layout grammar (an ``ici``
        two-tier mesh is a data layout, not a model layout)."""
        live = tuple(n for n, s in self.model_axes if s > 1)
        name = "-".join(("dp",) + live)
        if "ici" in self.names or name not in LAYOUT_MODEL_AXES:
            raise ValueError(
                f"mesh shape {self.describe()} is not an LM model-axis "
                f"layout (grammar: {sorted(LAYOUT_MODEL_AXES)})"
            )
        return name

    def build(self, devices: Optional[Sequence["jax.Device"]] = None):
        """Materialize the ``jax.sharding.Mesh`` (first ``n_devices`` of
        the roster by default)."""
        from atomo_tpu.parallel.mesh import make_mesh

        return make_mesh(self.n_devices, axes=self.axes, devices=devices)


def spec_of_mesh(mesh) -> MeshSpec:
    """Recover the spec of an existing ``jax.sharding.Mesh`` (axis order
    preserved) — the bridge for call sites that still hand a raw Mesh
    around."""
    return MeshSpec(
        tuple((str(n), int(mesh.shape[n])) for n in mesh.axis_names)
    )
