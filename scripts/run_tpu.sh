#!/usr/bin/env bash
# Canonical distributed recipe — the reference's src/run_pytorch.sh:1-20
# (ResNet-18 / CIFAR-10, batch 128, lr 0.01 shrinking 0.95 per 50 steps,
# momentum 0, SVD rank 3, sync replicas), re-expressed for an SPMD mesh.
# No mpirun, no hostfile: every chip runs this same program; on a multi-host
# pod the TPU runtime starts one process per host automatically.
set -euo pipefail

python -m atomo_tpu train \
  --network ResNet18 \
  --dataset Cifar10 \
  --batch-size 128 \
  --test-batch-size 1000 \
  --max-steps 10000 \
  --lr 0.01 \
  --momentum 0.0 \
  --lr-shrinkage 0.95 \
  --code svd \
  --svd-rank 3 \
  --eval-freq 50 \
  --train-dir "${TRAIN_DIR:-output/models/}" \
  "$@"
