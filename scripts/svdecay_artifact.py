"""Reproduce the reference's motivating observation: gradient singular
values decay fast, so spectral atoms are an efficient basis.

The reference ships this as its only figure (images/SVdecay.jpg, embedded
at README.md:9) plus research helpers that print nuclear/L1 indicators
during training (src/nn_ops.py:17-23,66-82, src/codings/utils.py). This
script is the reproducible version: train LeNet for a few hundred steps,
capture the gradient spectrum of the largest layers at checkpoints, and
write artifacts/SVDECAY.{json,md} with

  * normalized singular-value decay curves (early vs late training),
  * the energy fraction captured by the top-k atoms (the rank-3 story),
  * the nuclear-vs-L1 indicator decision per layer
    (codecs/indicators.spectral_atoms_preferred).

Runs anywhere (CPU fine): python scripts/svdecay_artifact.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--capture-at", type=str, default="1,50,300")
    ap.add_argument("--top-k", type=int, default=3)
    ap.add_argument("--out", type=str, default="artifacts")
    args = ap.parse_args()

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax
    import jax.numpy as jnp
    import numpy as np

    from atomo_tpu.codecs.indicators import (
        l1_indicator,
        nuclear_indicator,
        spectral_atoms_preferred,
    )
    from atomo_tpu.codecs.svd import resize_to_2d
    from atomo_tpu.data import SPECS, BatchIterator, synthetic_dataset
    from atomo_tpu.models import get_model
    from atomo_tpu.training import create_state, make_optimizer
    from atomo_tpu.training.trainer import make_train_step

    capture_at = sorted(int(s) for s in args.capture_at.split(","))
    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.0)
    ds = synthetic_dataset(SPECS["mnist"], True, size=512)
    it = BatchIterator(ds, 32, seed=0)
    images, labels = next(iter(it.epoch()))
    state = create_state(model, opt, jax.random.PRNGKey(0), jnp.asarray(images))

    # a gradient-only step: reuse the train step but also recompute grads
    # for capture at the requested steps
    step = make_train_step(model, opt, codec=None)

    def grads_of(state, images, labels):
        from atomo_tpu.training.trainer import cross_entropy_loss

        def loss_fn(p):
            logits = model.apply({"params": p}, jnp.asarray(images), train=False)
            return cross_entropy_loss(logits, jnp.asarray(labels))

        return jax.grad(loss_fn)(state.params)

    key = jax.random.PRNGKey(1)
    stream = it.forever()
    captures = {}
    for s in range(1, args.steps + 1):
        images, labels = next(stream)
        if s in capture_at:
            grads = grads_of(state, images, labels)
            flat = {
                "/".join(map(str, path)): leaf
                for path, leaf in jax.tree_util.tree_flatten_with_path(grads)[0][:]
            }
            # the two largest 2-D-able layers carry the spectral story
            big = sorted(flat.items(), key=lambda kv: -kv[1].size)[:2]
            captures[s] = {}
            for name, g in big:
                mat, _, _ = resize_to_2d(g.astype(jnp.float32), policy="square")
                sv = np.asarray(jnp.linalg.svd(mat, compute_uv=False))
                sv_n = sv / max(sv[0], 1e-12)
                energy = float((sv[: args.top_k] ** 2).sum() / max((sv**2).sum(), 1e-30))
                captures[s][name] = {
                    "shape": list(g.shape),
                    "matricized": list(mat.shape),
                    "normalized_sv": [round(float(x), 5) for x in sv_n[:32]],
                    f"top{args.top_k}_energy": round(energy, 4),
                    "nuclear_indicator": round(float(nuclear_indicator(mat)), 3),
                    "l1_indicator": round(float(l1_indicator(mat)), 3),
                    "spectral_preferred": bool(spectral_atoms_preferred(mat)),
                }
        state, _ = step(state, key, jnp.asarray(images), jnp.asarray(labels))

    os.makedirs(args.out, exist_ok=True)
    record = {
        "recipe": "lenet/mnist(synthetic) batch=32 lr=0.01 momentum=0",
        "reference": "images/SVdecay.jpg (README.md:9); indicators "
                     "src/nn_ops.py:66-82, src/codings/utils.py",
        "top_k": args.top_k,
        "captures": captures,
    }
    with open(os.path.join(args.out, "SVDECAY.json"), "w") as f:
        json.dump(record, f, indent=1)

    def bars(vals, width=32):
        blocks = " ▁▂▃▄▅▆▇█"
        return "".join(
            blocks[min(int(v * (len(blocks) - 1) + 0.999), len(blocks) - 1)]
            for v in vals[:width]
        )

    lines = [
        "# Gradient singular-value decay (the ATOMO premise, reproduced)",
        "",
        "Reference artifact: `images/SVdecay.jpg` — shipped as a static jpg;",
        "here the capture is a reproducible script. Bars = normalized",
        "singular values s_i/s_0 of the matricized gradient (first 32).",
        "",
        "Design note: the measured tail mass is exactly why the sketched-SVD",
        "default carries Rademacher residual probes (codecs/svd.py) — a pure",
        "rank-(k+p) sketch would discard most of the expected gradient on",
        "spectra like these and bias training (measured ~8x worse final",
        "loss); the probes return that tail in expectation.",
        "",
    ]
    for s, layers in captures.items():
        lines.append(f"## step {s}")
        lines.append("")
        for name, d in layers.items():
            lines.append(
                f"- `{name}` {tuple(d['shape'])} → {tuple(d['matricized'])}: "
                f"top-{args.top_k} energy **{d[f'top{args.top_k}_energy']:.1%}**, "
                f"spectral atoms preferred: {d['spectral_preferred']}"
            )
            lines.append(f"  `{bars(d['normalized_sv'])}`")
        lines.append("")
    with open(os.path.join(args.out, "SVDECAY.md"), "w") as f:
        f.write("\n".join(lines))
    print(json.dumps({s: {k: v[f"top{args.top_k}_energy"] for k, v in d.items()}
                      for s, d in captures.items()}, indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
