"""The mesh subsystem (PR-14 tentpole): explicit sharding, one compile
path, cross-replica sharded weight update (Xu et al. 2004.13336).

Contracts pinned here:

  * MeshSpec is the ONE mesh grammar: degenerate 1-device, flat dp and
    two-tier dp x ici shapes round-trip through the artifact form.
  * compile_step's map-style half is byte-identical to the hand-rolled
    ``jax.jit(jax.shard_map(...))`` stack it replaced (lowered-text
    equality on degenerate and multi-device meshes) — the replicated
    program family kept its frozen HLO through the refactor BY
    CONSTRUCTION.
  * Sharded-update trajectories are bit-identical to replicated ones
    per codec in the canonical decode order (qsgd gather/ring, svd ring
    and unfused gather, dense psum; superstep and two-tier compose);
    the fused-SVD gather tracks replicated to the documented ~1e-8
    cross-program fusion-drift class.
  * Per-chip persistent state actually shrinks: master+opt bytes on
    chip 0 are ~1/n of the replicated run's (measured from the real
    device buffers).
  * ``--overlap delayed`` composes: the in-flight payload is a sharded
    carry leaf, kill->restart->resume through the loop is bit-exact —
    the historical zero1 x delayed x supervision dead end, dissolved
    (satellite 1).
  * decision_reusable refuses a resume whose MESH SHAPE changed even at
    equal device count (satellite 2).
  * Live re-shard (elastic's in-process reshape path) equals a fresh
    build from the gathered host state, momentum carried exactly.
"""

import os
import sys
import subprocess

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from atomo_tpu.codecs import DenseCodec, QsgdCodec, SvdCodec
from atomo_tpu.data import BatchIterator, SPECS, synthetic_dataset
from atomo_tpu.mesh import (
    MeshSpec,
    reshard_sharded_update,
    sharded_update_state,
    spec_of_mesh,
)
from atomo_tpu.models import get_model
from atomo_tpu.parallel import (
    compile_step,
    init_delayed_state,
    make_distributed_train_step,
    make_mesh,
    replicate_state,
    shard_batch,
    shard_superbatch,
)
from atomo_tpu.training import (
    GuardConfig,
    create_state,
    make_optimizer,
    snapshot_state,
)

QSGD = QsgdCodec(bits=4, bucket_size=128)


def _eq(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        )
    )


def _setup(n_dev=4, batch=8):
    mesh = make_mesh(n_dev)
    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.9)
    r = np.random.default_rng(0)
    images = r.standard_normal((batch, 28, 28, 1)).astype(np.float32)
    labels = r.integers(0, 10, batch).astype(np.int32)
    host = snapshot_state(
        create_state(model, opt, jax.random.PRNGKey(0), jnp.asarray(images))
    )
    return mesh, model, opt, host, jnp.asarray(images), jnp.asarray(labels)


# ------------------------------------------------------------ MeshSpec


def test_meshspec_grammar_and_roundtrip():
    flat = MeshSpec.from_world(4)
    assert flat.axes == (("dp", 4),) and flat.is_flat
    assert not flat.is_degenerate and flat.describe() == "dp4"
    one = MeshSpec.from_world(1)
    assert one.is_degenerate and one.is_flat and one.shape_dict() == {"dp": 1}
    two = MeshSpec.from_world(4, dcn_ways=2)
    assert two.axes == (("dp", 2), ("ici", 2))
    assert two.is_two_tier and two.inner_axis == "ici"
    assert two.data_axes == ("dp", "ici")
    assert two.describe() == "dp2xici2"
    # artifact round-trip preserves order and sizes
    assert MeshSpec.from_shape_dict(two.shape_dict()) == two
    assert MeshSpec.from_shape_dict(flat.shape_dict()) == flat
    # garbage documents resolve to None, not an exception
    assert MeshSpec.from_shape_dict(None) is None
    assert MeshSpec.from_shape_dict({}) is None
    assert MeshSpec.from_shape_dict({"dp": "x"}) is None


def test_meshspec_validation_and_of_mesh():
    with pytest.raises(ValueError):
        MeshSpec.from_world(4, dcn_ways=3)  # does not divide
    with pytest.raises(ValueError):
        MeshSpec.from_world(0)
    with pytest.raises(ValueError):
        MeshSpec((("dp", 2), ("dp", 2)))  # duplicate axis
    mesh = make_mesh(4, axes=(("dp", 2), ("ici", 2)))
    assert spec_of_mesh(mesh) == MeshSpec.from_world(4, dcn_ways=2)
    assert MeshSpec.from_world(4).build().shape["dp"] == 4


# ------------------------------------------- one compile path, frozen HLO


@pytest.mark.parametrize("n_dev", [1, 4])
def test_compile_step_map_style_is_byte_identical_to_hand_rolled(n_dev):
    """The replicated family's byte-identity through the refactor, by
    construction: compile_step without explicit shardings must lower to
    the EXACT text of the jit(shard_map) stack it replaced — on the
    degenerate 1-device mesh and a real multi-device one alike."""
    mesh = make_mesh(n_dev)

    def body(x, y):
        g = jax.lax.pmean(x * y, "dp")
        return g + jax.lax.axis_index("dp").astype(jnp.float32) * 0.0

    x = jnp.arange(4 * n_dev, dtype=jnp.float32).reshape(n_dev * 2, 2)
    helper = compile_step(
        body, mesh, in_specs=(P("dp"), P("dp")), out_specs=P("dp"),
        donate_argnums=(0,), check_vma=False,
    )
    hand = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=(P("dp"), P("dp")),
            out_specs=P("dp"), check_vma=False,
        ),
        donate_argnums=(0,),
    )
    a = helper.lower(x, x).as_text()
    b = hand.lower(x, x).as_text()
    assert a == b


def test_compile_step_explicit_shardings_constrains_boundary():
    """The pjit half: explicit shardings appear at the jit boundary (the
    compiled program's input layout is the annotated one, so sharded
    state stays sharded by contract, not convention)."""
    mesh = make_mesh(4)

    def body(x):
        return x * 2.0

    step = compile_step(
        body, mesh, in_specs=(P("dp"),), out_specs=P("dp"),
        explicit_shardings=True,
    )
    x = jnp.arange(8, dtype=jnp.float32)
    out = step(x)
    assert out.sharding.spec == P("dp")
    np.testing.assert_array_equal(np.asarray(out), np.arange(8) * 2.0)


# ------------------------------- sharded update vs replicated, per codec


def _run_traj(mesh, model, opt, host, images, labels, codec, *, su_mode,
              n_steps=3, **kw):
    si, sl = shard_batch(mesh, images, labels)
    if su_mode:
        st, su = sharded_update_state(mesh, host, opt)
        step = make_distributed_train_step(
            model, opt, mesh, codec, sharded_update=su, **kw
        )
    else:
        st, su = replicate_state(mesh, host), None
        step = make_distributed_train_step(model, opt, mesh, codec, **kw)
    m = None
    for _ in range(n_steps):
        st, m = step(st, jax.random.PRNGKey(1), si, sl)
    params = (
        su.materialize_host(st.master) if su_mode
        else jax.device_get(st.params)
    )
    return params, m


@pytest.mark.parametrize(
    "codec,kw",
    [
        (QSGD, dict(aggregate="gather")),
        # the ring and svd variants re-prove the same sharded-update
        # identity over pricier exchanges/encoders (~37 s combined on 1
        # core) — full-suite only; qsgd-gather + dense-psum keep the
        # identity witnessed across codec'd and dense wires in the smoke
        # set (the unfused-decode flag is an svd-only decode detail)
        pytest.param(QSGD, dict(aggregate="ring"), marks=pytest.mark.slow),
        (None, dict(aggregate="psum")),
        pytest.param(
            SvdCodec(rank=2), dict(aggregate="ring"), marks=pytest.mark.slow
        ),
        pytest.param(
            SvdCodec(rank=2), dict(aggregate="gather", unfused_decode=True),
            marks=pytest.mark.slow,
        ),
    ],
    ids=["qsgd-gather", "qsgd-ring", "dense-psum", "svd-ring",
         "svd-gather-unfused"],
)
def test_sharded_update_bit_identical_to_replicated(codec, kw):
    """The house acceptance bar: sharded-update trajectories ==
    replicated trajectories, bit for bit, per codec in the canonical
    decode order."""
    mesh, model, opt, host, images, labels = _setup()
    pr, mr = _run_traj(mesh, model, opt, host, images, labels, codec,
                       su_mode=False, **kw)
    ps, ms = _run_traj(mesh, model, opt, host, images, labels, codec,
                       su_mode=True, **kw)
    assert _eq(pr, ps)
    assert float(mr["loss"]) == float(ms["loss"])


@pytest.mark.slow  # ~14 s on 1 core — full-suite only; the unfused
# svd-gather bit-identity stays in the smoke set above
def test_sharded_update_fused_svd_gather_within_drift_class():
    """The fused-SVD gather program restructures around the transient
    materialize and XLA fuses the decode matmul differently: the
    documented cross-program fusion-drift class (~1e-8 allclose), NOT
    bit-identity — stated and pinned, never silent."""
    mesh, model, opt, host, images, labels = _setup()
    codec = SvdCodec(rank=2)
    pr, _ = _run_traj(mesh, model, opt, host, images, labels, codec,
                      su_mode=False, aggregate="gather")
    ps, _ = _run_traj(mesh, model, opt, host, images, labels, codec,
                      su_mode=True, aggregate="gather")
    for a, b in zip(jax.tree_util.tree_leaves(pr),
                    jax.tree_util.tree_leaves(ps)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6
        )


@pytest.mark.slow
def test_sharded_update_superstep_and_guard_compose():
    mesh, model, opt, host, images, labels = _setup()
    # superstep scan carries the sharded state — bit-identical to rep
    K = 2
    im2, lb2 = jnp.stack([images] * K), jnp.stack([labels] * K)
    si2, sl2 = shard_superbatch(mesh, im2, lb2)
    st_r = replicate_state(mesh, host)
    step_r = make_distributed_train_step(
        model, opt, mesh, QSGD, aggregate="gather", superstep=K
    )
    st_r, _ = step_r(st_r, jax.random.PRNGKey(1), si2, sl2)
    st_s, su = sharded_update_state(mesh, host, opt)
    step_s = make_distributed_train_step(
        model, opt, mesh, QSGD, aggregate="gather", superstep=K,
        sharded_update=su,
    )
    st_s, _ = step_s(st_s, jax.random.PRNGKey(1), si2, sl2)
    assert _eq(jax.device_get(st_r.params), su.materialize_host(st_s.master))
    # guarded compositions restructure the select/rescale tail and land
    # in the documented cross-program fusion-drift class — pinned as
    # allclose, not bit-identity (the make_distributed_train_step
    # docstring states this)
    pr, _ = _run_traj(mesh, model, opt, host, images, labels, QSGD,
                      su_mode=False, aggregate="ring", guard=GuardConfig())
    ps, _ = _run_traj(mesh, model, opt, host, images, labels, QSGD,
                      su_mode=True, aggregate="ring", guard=GuardConfig())
    for a, b in zip(jax.tree_util.tree_leaves(pr),
                    jax.tree_util.tree_leaves(ps)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6
        )


@pytest.mark.slow
def test_sharded_update_two_tier_hierarchical():
    """The one compile path serves the two-tier program: master sharded
    over BOTH data axes, hierarchical aggregation unchanged, bit-identical
    to the replicated two-tier run."""
    mesh = make_mesh(4, axes=(("dp", 2), ("ici", 2)))
    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.9)
    r = np.random.default_rng(0)
    images = jnp.asarray(
        r.standard_normal((8, 28, 28, 1)).astype(np.float32)
    )
    labels = jnp.asarray(r.integers(0, 10, 8).astype(np.int32))
    host = snapshot_state(
        create_state(model, opt, jax.random.PRNGKey(0), images)
    )
    si, sl = shard_batch(mesh, images, labels, axis=("dp", "ici"))
    st_r = replicate_state(mesh, host)
    step_r = make_distributed_train_step(
        model, opt, mesh, QSGD, aggregate="hierarchical", inner_axis="ici"
    )
    st_s, su = sharded_update_state(mesh, host, opt, axis=("dp", "ici"))
    step_s = make_distributed_train_step(
        model, opt, mesh, QSGD, aggregate="hierarchical", inner_axis="ici",
        sharded_update=su,
    )
    for _ in range(3):
        st_r, _ = step_r(st_r, jax.random.PRNGKey(1), si, sl)
        st_s, _ = step_s(st_s, jax.random.PRNGKey(1), si, sl)
    assert _eq(jax.device_get(st_r.params), su.materialize_host(st_s.master))


def test_degenerate_one_device_mesh_is_first_class():
    """dp1 runs the same sharded-update program text with identity
    collectives: the chunk is the whole padded vector and the trajectory
    equals the replicated one exactly."""
    mesh, model, opt, host, images, labels = _setup(n_dev=1)
    pr, _ = _run_traj(mesh, model, opt, host, images, labels, QSGD,
                      su_mode=False, aggregate="gather")
    ps, _ = _run_traj(mesh, model, opt, host, images, labels, QSGD,
                      su_mode=True, aggregate="gather")
    assert _eq(pr, ps)


# --------------------------------------------------- per-chip memory


def _chip0_bytes(tree):
    dev0 = jax.devices()[0]
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        for s in leaf.addressable_shards:
            if s.device == dev0:
                total += int(np.prod(s.data.shape)) * s.data.dtype.itemsize
    return total


def test_per_chip_persistent_state_shrinks_by_world_size():
    """The 2004.13336 memory claim, measured from device buffers: chip
    0's persistent (master + optimizer) bytes under sharded-update are
    ~1/n of the replicated run's (exact up to flat padding)."""
    mesh, model, opt, host, images, labels = _setup()
    st_r = replicate_state(mesh, host)
    rep = _chip0_bytes((st_r.params, st_r.opt_state))
    st_s, su = sharded_update_state(mesh, host, opt)
    shd = _chip0_bytes((st_s.master, st_s.opt_state))
    n = mesh.shape["dp"]
    assert shd < rep / (n - 0.5)  # 1/n up to padding + scalar counts
    # and the master really is distributed: every chip holds one chunk
    assert len(st_s.master.addressable_shards) == n
    assert st_s.master.addressable_shards[0].data.shape == (su.chunk,)


# ------------------------------------------- delayed overlap, resume drill


def test_sharded_delayed_matches_replicated_delayed_ring():
    """The in-flight payload as a sharded carry leaf: the su delayed-ring
    trajectory is bit-identical to the replicated delayed-ring one."""
    mesh, model, opt, host, images, labels = _setup()
    si, sl = shard_batch(mesh, images, labels)

    def run(su_mode):
        if su_mode:
            st, su = sharded_update_state(mesh, host, opt)
            step = make_distributed_train_step(
                model, opt, mesh, QSGD, aggregate="ring",
                overlap="delayed", sharded_update=su,
            )
            st = init_delayed_state(
                mesh, st, QSGD,
                params_host=su.materialize_host(st.master),
            )
        else:
            st, su = replicate_state(mesh, host), None
            step = make_distributed_train_step(
                model, opt, mesh, QSGD, aggregate="ring", overlap="delayed"
            )
            st = init_delayed_state(mesh, st, QSGD)
        for _ in range(4):
            st, m = step(st, jax.random.PRNGKey(1), si, sl)
        tr = st.train
        return (
            su.materialize_host(tr.master) if su_mode
            else jax.device_get(tr.params)
        ), m

    pr, mr = run(False)
    ps, ms = run(True)
    assert _eq(pr, ps)
    assert float(mr["skipped"]) == float(ms["skipped"]) == 0.0


@pytest.mark.slow
def test_sharded_delayed_kill_restart_resume_bit_exact(tmp_path):
    """Satellite 1's drill: the zero1 x delayed x supervision dead end is
    LIFTED on the sharded path — a sharded-update + delayed run killed at
    a checkpoint resumes (in-flight payload restored from the sharded
    carry leaf) and finishes bit-identical to the uninterrupted run."""
    from atomo_tpu.parallel import distributed_train_loop

    mesh, model, opt, _host, _im, _lb = _setup(n_dev=2, batch=8)

    def make_iter():
        return BatchIterator(
            synthetic_dataset(SPECS["mnist"], True, size=64), 16, seed=0
        )

    common = dict(
        codec=QSGD, aggregate="gather", overlap="delayed",
        sharded_update=True, log_every=0, eval_freq=0, seed=0,
    )
    oracle = distributed_train_loop(
        model, opt, mesh, make_iter(), max_steps=6, **common
    )
    distributed_train_loop(
        model, opt, mesh, make_iter(), max_steps=4,
        train_dir=str(tmp_path), save_freq=2, **common
    )
    logs = []
    resumed = distributed_train_loop(
        model, opt, mesh, make_iter(), max_steps=6,
        train_dir=str(tmp_path), resume=True, log_fn=logs.append,
        **common
    )
    assert any("Resumed" in l and "step 4" in l for l in logs), logs
    # both are DelayedState-over-ShardedUpdateState: flat master compare
    assert _eq(
        jax.device_get(resumed.train.master),
        jax.device_get(oracle.train.master),
    )
    assert int(jax.device_get(resumed.step)) == 6


@pytest.mark.slow
def test_sharded_blocking_loop_resume_and_replicated_fallback(tmp_path):
    """Blocking-mode loop resume restores the sharded layout; resuming a
    REPLICATED checkpoint into a sharded-update run falls back to
    params-only out loud (the ZeRO-1 fallback, inherited)."""
    from atomo_tpu.parallel import distributed_train_loop

    mesh, model, opt, _host, _im, _lb = _setup(n_dev=2, batch=8)

    def make_iter():
        return BatchIterator(
            synthetic_dataset(SPECS["mnist"], True, size=64), 16, seed=0
        )

    common = dict(codec=QSGD, aggregate="gather", log_every=0,
                  eval_freq=0, seed=0)
    oracle = distributed_train_loop(
        model, opt, mesh, make_iter(), max_steps=6, sharded_update=True,
        **common
    )
    distributed_train_loop(
        model, opt, mesh, make_iter(), max_steps=3, sharded_update=True,
        train_dir=str(tmp_path), save_freq=3, **common
    )
    logs = []
    resumed = distributed_train_loop(
        model, opt, mesh, make_iter(), max_steps=6, sharded_update=True,
        train_dir=str(tmp_path), resume=True, log_fn=logs.append, **common
    )
    assert any("Resumed" in l and "step 3" in l for l in logs), logs
    assert _eq(
        jax.device_get(resumed.master), jax.device_get(oracle.master)
    )
    # replicated checkpoint -> sharded run: params-only fallback, warned
    rep_dir = tmp_path / "rep"
    distributed_train_loop(
        model, opt, mesh, make_iter(), max_steps=2,
        train_dir=str(rep_dir), save_freq=2, **common
    )
    with pytest.warns(UserWarning, match="sharded-update resume"):
        st = distributed_train_loop(
            model, opt, mesh, make_iter(), max_steps=3,
            sharded_update=True, train_dir=str(rep_dir), resume=True,
            **common
        )
    assert int(jax.device_get(st.step)) == 3


@pytest.mark.slow
def test_sharded_resume_across_overlap_layouts(tmp_path, recwarn):
    """Cross-layout resume fallbacks (code-review hardening): a
    sharded-update DELAYED checkpoint resumed by a BLOCKING sharded run
    restores the sharded train state (payload discarded, warned), and a
    REPLICATED delayed checkpoint resumed by a sharded run falls back to
    params-only — neither path may crash on flax's key mismatch."""
    from atomo_tpu.parallel import distributed_train_loop

    mesh, model, opt, _host, _im, _lb = _setup(n_dev=2, batch=8)

    def make_iter():
        return BatchIterator(
            synthetic_dataset(SPECS["mnist"], True, size=64), 16, seed=0
        )

    common = dict(codec=QSGD, aggregate="gather", log_every=0,
                  eval_freq=0, seed=0)
    # (a) sharded delayed checkpoint -> blocking sharded resume
    d_a = str(tmp_path / "a")
    distributed_train_loop(
        model, opt, mesh, make_iter(), max_steps=2, sharded_update=True,
        overlap="delayed", train_dir=d_a, save_freq=2, **common
    )
    st = distributed_train_loop(
        model, opt, mesh, make_iter(), max_steps=3, sharded_update=True,
        train_dir=d_a, resume=True, **common
    )
    assert int(jax.device_get(st.step)) == 3
    assert any(
        "overlap-carry layout" in str(w.message) for w in recwarn.list
    ), [str(w.message) for w in recwarn.list]
    # (b) replicated delayed checkpoint -> sharded resume (params-only)
    d_b = str(tmp_path / "b")
    distributed_train_loop(
        model, opt, mesh, make_iter(), max_steps=2, overlap="delayed",
        train_dir=d_b, save_freq=2, **common
    )
    st = distributed_train_loop(
        model, opt, mesh, make_iter(), max_steps=3, sharded_update=True,
        train_dir=d_b, resume=True, **common
    )
    assert int(jax.device_get(st.step)) == 3
    assert any(
        "restoring params only" in str(w.message)
        or "params only" in str(w.message)
        for w in recwarn.list
    ), [str(w.message) for w in recwarn.list]


# --------------------------------------------------- live re-shard


def test_reshard_live_state_equals_fresh_build():
    """Elastic's in-process reshape path: re-sharding a LIVE sharded
    state onto a smaller mesh carries params AND momentum exactly — the
    resharded run continues the same optimizer trajectory a fresh build
    from the gathered host state would."""
    mesh, model, opt, host, images, labels = _setup(n_dev=4)
    si, sl = shard_batch(mesh, images, labels)
    st, su = sharded_update_state(mesh, host, opt)
    step = make_distributed_train_step(
        model, opt, mesh, QSGD, aggregate="gather", sharded_update=su
    )
    for _ in range(2):
        st, _ = step(st, jax.random.PRNGKey(1), si, sl)
    mesh2 = make_mesh(2)
    st2, su2 = reshard_sharded_update(st, su, mesh2, opt)
    # params carried bit-exact
    assert _eq(su.materialize_host(st.master), su2.materialize_host(st2.master))
    # momentum carried bit-exact (vector buffers re-sliced, not re-init)
    old_mom = np.asarray(jax.device_get(
        [l for l in jax.tree_util.tree_leaves(st.opt_state) if l.ndim][0]
    ))[: su.d_flat]
    new_mom = np.asarray(jax.device_get(
        [l for l in jax.tree_util.tree_leaves(st2.opt_state) if l.ndim][0]
    ))[: su2.d_flat]
    np.testing.assert_array_equal(old_mom, new_mom)
    # and the resharded state steps on the new mesh
    step2 = make_distributed_train_step(
        model, opt, mesh2, QSGD, aggregate="gather", sharded_update=su2
    )
    si2, sl2 = shard_batch(mesh2, images, labels)
    st2, m2 = step2(st2, jax.random.PRNGKey(1), si2, sl2)
    assert np.isfinite(float(m2["loss"]))


# ------------------------------------- live re-shard, replicated layout


def test_reshard_replicated_trainstate_is_fresh_build_bit_exact():
    """The elastic live path's determinism argument, at the primitive:
    reshard_replicated(state, mesh') == replicate_state(mesh',
    device_get(state)) leaf-wise bit-exact — the resharded trajectory IS
    the fresh-build-and-continue trajectory by construction."""
    from atomo_tpu.mesh import reshard_replicated

    mesh, model, opt, host, images, labels = _setup(n_dev=4)
    state = replicate_state(mesh, host)
    step = make_distributed_train_step(
        model, opt, mesh, QSGD, aggregate="gather"
    )
    si, sl = shard_batch(mesh, images, labels)
    for _ in range(2):
        state, _ = step(state, jax.random.PRNGKey(1), si, sl)
    mesh2 = make_mesh(3)
    moved = reshard_replicated(state, mesh2)
    fresh = replicate_state(mesh2, jax.device_get(state))
    assert _eq(jax.device_get(moved), jax.device_get(fresh))
    # and it steps on the new mesh
    step2 = make_distributed_train_step(
        model, opt, mesh2, QSGD, aggregate="gather"
    )
    b = images.shape[0] - images.shape[0] % 3
    si2, sl2 = shard_batch(mesh2, images[:b], labels[:b])
    moved, m2 = step2(moved, jax.random.PRNGKey(2), si2, sl2)
    assert np.isfinite(float(m2["loss"]))


def test_reshard_replicated_delayed_carry_moves_with_owners():
    """DelayedState: shrink re-slices the SURVIVORS' in-flight payload
    rows (valid rides along); grow resets to the fresh valid=0 carry
    (one in-flight update dropped, stated)."""
    from atomo_tpu.mesh import reshard_replicated
    from atomo_tpu.parallel.replicated import DelayedState, OverlapCarry

    mesh, model, opt, host, *_ = _setup(n_dev=4)
    ds = init_delayed_state(mesh, replicate_state(mesh, host), QSGD)
    # make every per-source row distinguishable: row i = i + 1
    stamp = jax.tree_util.tree_map(
        lambda a: np.asarray(jax.device_get(a))
        + np.arange(1, 5, dtype=np.float32).reshape(
            (4,) + (1,) * (a.ndim - 1)
        ).astype(np.asarray(a).dtype),
        jax.device_get(ds.carry.payload),
    )
    from atomo_tpu.parallel.replicated import _place_carry

    carry = _place_carry(
        mesh,
        OverlapCarry(
            payload=stamp,
            ok=np.asarray([1.0, 0.0, 1.0, 1.0], np.float32),
            valid=np.float32(1.0),
        ),
    )
    ds = DelayedState(train=ds.train, carry=carry)

    shrunk = reshard_replicated(
        ds, make_mesh(2), survivors=(0, 2), codec=QSGD
    )
    got = jax.device_get(shrunk.carry.payload)
    want = jax.tree_util.tree_map(lambda a: a[[0, 2]], stamp)
    assert _eq(got, want)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(shrunk.carry.ok)), [1.0, 1.0]
    )
    assert float(jax.device_get(shrunk.carry.valid)) == 1.0

    grown = reshard_replicated(ds, make_mesh(8), codec=QSGD)
    assert float(jax.device_get(grown.carry.valid)) == 0.0
    assert int(jax.device_get(grown.carry.ok).shape[0]) == 8


def test_reshard_replicated_refusals_are_loud():
    """Every unsafe reshape REFUSES with the reason the coordinator
    records in its reshard_fallback incident: wrapped layouts, a
    DelayedState without its codec, a codec whose encode does not match
    the in-flight payload, malformed survivor ranks."""
    from atomo_tpu.mesh import reshard_replicated

    mesh, model, opt, host, *_ = _setup(n_dev=4)
    st, _su = sharded_update_state(mesh, host, opt)
    with pytest.raises(ValueError, match="reshard_sharded_update"):
        reshard_replicated(st, make_mesh(2))

    ds = init_delayed_state(mesh, replicate_state(mesh, host), QSGD)
    with pytest.raises(ValueError, match="needs the run's codec"):
        reshard_replicated(ds, make_mesh(2), survivors=(0, 2))
    with pytest.raises(ValueError, match="carry/codec mismatch"):
        reshard_replicated(
            ds, make_mesh(2), survivors=(0, 2),
            codec=QsgdCodec(bits=8, bucket_size=32),
        )
    for bad in ((2, 0), (0,), (0, 5)):
        with pytest.raises(ValueError, match="survivor"):
            reshard_replicated(
                ds, make_mesh(2), survivors=bad, codec=QSGD
            )


# ------------------------------------------------ decision_reusable mesh


def test_decision_reusable_refuses_changed_mesh_shape():
    """Satellite 2: same n_devices, different axis shape -> refuse."""
    from atomo_tpu.tuning.autopilot import decision_reusable

    doc = {
        "complete": True,
        "winner": {"knobs": {"aggregate": "gather"}},
        "meta": {"n_devices": 4, "mesh_axes": {"dp": 2, "ici": 2}},
    }
    ok, why = decision_reusable(doc, n_dev=4, mesh_axes={"dp": 4})
    assert not ok and "different axis shape" in why
    ok, why = decision_reusable(
        doc, n_dev=4, mesh_axes={"dp": 2, "ici": 2}
    )
    assert ok, why
    # old artifact without the record: the shape is RECONSTRUCTED from
    # the recorded dcn_ways, so a legacy flat artifact matches a flat
    # mesh and a legacy two-tier one refuses a flat resume
    legacy_flat = {
        "complete": True,
        "winner": {"knobs": {"aggregate": "gather"}},
        "meta": {"n_devices": 4},
    }
    ok, why = decision_reusable(legacy_flat, n_dev=4, mesh_axes={"dp": 4})
    assert ok and "reconstructed" in why
    legacy_2t = {
        "complete": True,
        "winner": {"knobs": {"aggregate": "hier[legacy]"}},
        "meta": {"n_devices": 4, "dcn_ways": 2},
    }
    ok, why = decision_reusable(legacy_2t, n_dev=4, mesh_axes={"dp": 4})
    assert not ok and "reconstructed" in why
    # the n_devices mismatch still dominates
    ok, _ = decision_reusable(doc, n_dev=3, mesh_axes={"dp": 3})
    assert not ok


def test_tune_records_mesh_axes_and_partition(tmp_path):
    """The decision artifact carries the probed mesh's named-axis shape
    and the weight-update partition."""
    from atomo_tpu.tuning.autopilot import tune

    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.9)

    def init_fn():
        return create_state(
            model, opt, jax.random.PRNGKey(0),
            jnp.zeros((1, 28, 28, 1), jnp.float32),
        ).params

    doc = tune(
        model=model, optimizer=opt, codec=QSGD, model_init_fn=init_fn,
        n_dev=2, sample_shape=(28, 28, 1), num_classes=10, batch=8,
        fabric="ici", probe_top=1, probe_steps=1, probe_reps=1,
        superstep_options=(1,), bucket_options=(65536,),
        partition="sharded_update", log_fn=lambda *a, **k: None,
    )
    assert doc["meta"]["mesh_axes"] == {"dp": 2}
    assert doc["meta"]["partition"] == "sharded_update"


# ------------------------------------------------ CLI preflight (sat. 1)


def _base_args(**over):
    from atomo_tpu.cli import build_parser

    argv = over.pop("argv")
    args = build_parser().parse_args(argv)
    args._argv = argv
    return args


def test_preflight_zero1_delayed_supervised_still_rejected():
    """The legacy dead end keeps its reject (message now names the way
    out)."""
    from atomo_tpu.cli import _argv_preflight

    args = _base_args(argv=[
        "train", "--synthetic", "--code", "qsgd", "--n-devices", "2",
        "--overlap", "delayed", "--zero1", "--max-restarts", "2",
        "--train-dir", "/tmp/x",
    ])
    with pytest.raises(SystemExit, match="sharded-update"):
        _argv_preflight(args)


def test_preflight_sharded_update_delayed_supervised_allowed():
    """Satellite 1: the SAME flag triple passes preflight on the sharded
    path — the in-flight payload is a sharded carry leaf now."""
    from atomo_tpu.cli import _argv_preflight

    args = _base_args(argv=[
        "train", "--synthetic", "--code", "qsgd", "--n-devices", "2",
        "--overlap", "delayed", "--partition", "sharded-update",
        "--max-restarts", "2", "--train-dir", "/tmp/x",
    ])
    _argv_preflight(args)  # must not raise


def test_preflight_sharded_update_conflicts():
    from atomo_tpu.cli import _argv_preflight

    base = ["train", "--synthetic", "--code", "qsgd", "--n-devices", "2",
            "--partition", "sharded-update"]
    with pytest.raises(SystemExit, match="--zero1 conflicts"):
        _argv_preflight(_base_args(argv=base + ["--zero1"]))
    with pytest.raises(SystemExit, match="on-diverge|rollback"):
        _argv_preflight(_base_args(argv=base + [
            "--on-diverge", "skip", "--train-dir", "/tmp/x",
            "--save-freq", "2", "--keep-ckpts", "2",
        ]))


@pytest.mark.slow
def test_cli_sharded_update_trains_and_resumes(tmp_path):
    """End to end through the CLI: --partition sharded-update trains on
    the forced multi-device mesh, checkpoints, and a supervised-style
    resume continues from the saved sharded layout."""
    from atomo_tpu.cli import main

    d = str(tmp_path / "run")
    argv = ["train", "--synthetic", "--code", "qsgd",
            "--n-devices", "2", "--network", "lenet", "--dataset", "mnist",
            "--batch-size", "8", "--max-steps", "2", "--eval-freq", "0",
            "--partition", "sharded-update", "--overlap", "delayed",
            "--train-dir", d, "--save-freq", "2"]
    main(argv)
    assert os.path.exists(os.path.join(d, "model_step_2"))
    main(argv[:argv.index("--max-steps") + 1] + ["4"]
         + argv[argv.index("--max-steps") + 2:] + ["--resume"])
    assert os.path.exists(os.path.join(d, "model_step_4"))