"""Live re-sharding — elastic reshapes as data movement, not process death.

The elastic coordinator's historical reshape is exit-and-re-exec: write
``membership.json``, exit rc=29, let the supervisor relaunch at N-1 and
resume from the newest checkpoint. That stays the FALLBACK (it is the
only correct move when the dead replica took its host process with it).
But with explicit sharding the common case — a healthy process whose
mesh merely changes shape — is a data-movement problem: gather the live
sharded state once, re-slice it for the new mesh, place it. No exec, no
checkpoint round-trip, no re-reading the data directory.

Determinism contract (the elastic acceptance bar, inherited): the
re-sharded state is built from the SAME host bytes a checkpoint
save/restore cycle would move, through the same
:func:`~atomo_tpu.mesh.update.sharded_update_state` placement a fresh
N'-device run performs — so the resharded trajectory is the fresh-run
trajectory by construction (tested: reshard == gather + fresh build,
leaf-wise bit-exact).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from atomo_tpu.mesh.spec import MeshSpec
from atomo_tpu.mesh.update import (
    ShardedUpdateSpecs,
    ShardedUpdateState,
    sharded_update_state,
)


def reshard_sharded_update(
    state: ShardedUpdateState,
    specs: ShardedUpdateSpecs,
    new_mesh,
    optimizer,
    *,
    axis="dp",
) -> tuple[ShardedUpdateState, ShardedUpdateSpecs]:
    """Re-shard a LIVE sharded-update state onto ``new_mesh``.

    Master weights are gathered to the true (unpadded) flat vector and
    re-padded/re-sliced for the new shard count. The optimizer state is
    rebuilt the careful way: vector buffers whose flat layout matches the
    master's (the momentum/mu/nu family) are re-sliced exactly — the
    resharded run continues the SAME optimizer trajectory, not a
    fresh-momentum one; scalar leaves (counts) carry over replicated.
    """
    from atomo_tpu.training.trainer import TrainState

    params = specs.materialize_host(state.master)
    stats = jax.device_get(state.batch_stats)
    step = jax.device_get(state.step)
    host_tpl = TrainState(
        step=jnp.asarray(step, jnp.int32), params=params,
        batch_stats=stats, opt_state=None,
    )
    new_state, new_specs = sharded_update_state(
        new_mesh, host_tpl, optimizer, axis=axis
    )
    pad = new_specs.chunk * new_specs.n_shards - new_specs.d_flat

    def carry_opt(old_leaf, new_leaf, sp):
        old_leaf = jnp.asarray(jax.device_get(old_leaf))
        if old_leaf.ndim == 0:
            return jax.device_put(
                old_leaf, new_leaf.sharding
            )
        # flat vector buffer: strip the OLD padding, re-pad for the new
        # shard count, place with the new layout
        flat = old_leaf[: specs.d_flat]
        return jax.device_put(jnp.pad(flat, (0, pad)), new_leaf.sharding)

    new_opt = jax.tree_util.tree_map(
        carry_opt, state.opt_state, new_state.opt_state,
        new_specs.opt_specs,
    )
    return (
        ShardedUpdateState(
            step=new_state.step, master=new_state.master,
            batch_stats=new_state.batch_stats, opt_state=new_opt,
        ),
        new_specs,
    )


def reshard_replicated(state, new_mesh, *, survivors=None, codec=None,
                       axis="dp"):
    """Re-place a LIVE replicated train state onto ``new_mesh`` — the
    elastic coordinator's zero-downtime reshape for the replicated data
    layout (the layout the elastic loop runs: the builders refuse
    elastic + sharded-update/zero1/quorum, so this is the whole family).

    Replicated state is the easy half of the determinism contract: the
    host bytes are gathered once (``jax.device_get`` — the same bytes a
    checkpoint save would write) and replicated onto the new mesh via
    the same :func:`~atomo_tpu.parallel.replicated.replicate_state` a
    fresh N'-device build performs, so the resharded trajectory IS the
    fresh-build-and-continue trajectory by construction (tested
    leaf-wise bit-exact, tests/test_elastic.py).

    A ``DelayedState`` (``--overlap delayed``) carries the in-flight
    encoded gradients as a ``(world, ...)`` row-per-source payload, and
    those rows move with their owners:

    * **shrink** — the SURVIVOR rows are re-sliced (``survivors`` = the
      surviving old ranks, one per new-world slot, strictly increasing);
      ``valid`` rides along, so the boundary step applies the mean of
      the survivors' in-flight gradients — exactly what the shrunk
      world's aggregation computes.
    * **grow** — the new members have no in-flight rows, and zero rows
      under ``valid=1`` would bias the mean; the carry RESETS to the
      fresh ``valid=0`` value (one in-flight update dropped, the same
      honest cost :func:`reshard_model_axes` states).

    ``codec`` is required for a DelayedState: the payload row shapes are
    checked against THIS codec's encode over these params and a mismatch
    is REFUSED (a carry encoded by a different codec cannot be re-sliced
    into a decodable one) — the caller falls back to re-exec and records
    why.
    """
    # lazy: mesh.* must not import parallel.* at module level (cycle)
    from atomo_tpu.parallel.replicated import (
        DelayedState,
        OverlapCarry,
        _place_carry,
        _zero_carry_host,
        replicate_state,
    )
    from atomo_tpu.training.trainer import TrainState

    n_new = int(new_mesh.shape[axis])
    carry_in = None
    if isinstance(state, DelayedState):
        if codec is None:
            raise ValueError(
                "resharding a DelayedState needs the run's codec: the "
                "carry's payload rows are codec-encoded gradients and "
                "the reshard must prove they decode on the new world"
            )
        carry_in = state.carry
        state = state.train
    if not isinstance(state, TrainState):
        raise ValueError(
            "reshard_replicated moves the plain replicated TrainState "
            f"(or DelayedState) only; got {type(state).__name__} — "
            "wrapped layouts (zero1/sharded-update/quorum) are "
            "layout-owned and go through reshard_sharded_update or the "
            "checkpoint round-trip"
        )
    host = jax.device_get(state)
    new_train = replicate_state(new_mesh, host)
    if carry_in is None:
        return new_train
    payload = jax.device_get(carry_in.payload)
    ok = jax.device_get(carry_in.ok)
    valid = jnp.asarray(jax.device_get(carry_in.valid))
    n_old = int(ok.shape[0])
    zero = _zero_carry_host(codec, host.params, n_new)

    def _check(old_leaf, zero_leaf):
        if (
            tuple(old_leaf.shape[1:]) != tuple(zero_leaf.shape[1:])
            or old_leaf.dtype != zero_leaf.dtype
        ):
            raise ValueError(
                "carry/codec mismatch: payload rows "
                f"{tuple(old_leaf.shape[1:])}/{old_leaf.dtype} vs this "
                f"codec's encode {tuple(zero_leaf.shape[1:])}/"
                f"{zero_leaf.dtype} — the in-flight payload was encoded "
                "by a different codec; re-exec instead"
            )

    try:
        jax.tree_util.tree_map(_check, payload, zero.payload)
    except ValueError:
        raise
    except Exception as exc:  # tree-structure mismatch = codec mismatch
        raise ValueError(
            f"carry/codec mismatch: payload tree differs from this "
            f"codec's encode tree ({exc}); re-exec instead"
        ) from None
    if n_new > n_old:
        carry = zero
    elif n_new < n_old or survivors is not None:
        ranks = [int(s) for s in (survivors or ())]
        if len(ranks) != n_new or any(
            b <= a for a, b in zip(ranks, ranks[1:])
        ) or any(r < 0 or r >= n_old for r in ranks):
            raise ValueError(
                f"shrinking a DelayedState carry needs the survivor "
                f"ranks: {n_new} strictly-increasing old ranks in "
                f"[0, {n_old}); got {survivors!r}"
            )
        carry = OverlapCarry(
            payload=jax.tree_util.tree_map(
                lambda a: jnp.asarray(a)[jnp.asarray(ranks)], payload
            ),
            ok=jnp.asarray(ok)[jnp.asarray(ranks)],
            valid=valid,
        )
    else:
        carry = OverlapCarry(
            payload=jax.tree_util.tree_map(jnp.asarray, payload),
            ok=jnp.asarray(ok),
            valid=valid,
        )
    return DelayedState(
        train=new_train, carry=_place_carry(new_mesh, carry, axis=axis)
    )


def reshard_plan(
    old_spec: MeshSpec, n_devices: int, dcn_ways: int = 0
) -> Optional[MeshSpec]:
    """The coordinator's reshape decision record: the new
    :class:`MeshSpec` for a world of ``n_devices``, or None when the
    shape is unchanged (no reshape needed). Pure — the incident log
    captures both shapes either way."""
    new = MeshSpec.from_world(n_devices, dcn_ways)
    return None if new == old_spec else new


# ---------------------------------------------------------------------------
# Model-axis layout redistribution: lm <-> tp as a reshard, not a restart
# ---------------------------------------------------------------------------

#: Which param-tree LAYOUT each LM mesh layout stores: the replicated
#: layouts hold the plain TransformerLM tree, the tensor-parallel ones
#: hold the head-sliced re-layout (``parallel.tp.lm_params_to_tp``).
#: dp-ep / dp-pp are absent ON PURPOSE: their param trees are
#: layout-owned (expert- / stage-sharded shapes with no bijection to the
#: flat tree proven here) — redistribution for them goes through the
#: checkpoint round-trip, and :func:`reshard_model_axes` says so.
_LAYOUT_PARAM_FAMILY = {
    "dp": "lm",
    "dp-sp": "lm",
    "dp-tp": "tp",
    "dp-tp-sp": "tp",
}


def reshard_model_axes(
    state,
    old_spec: MeshSpec,
    new_spec: MeshSpec,
    lm_config: dict,
    *,
    devices=None,
    codec=None,
):
    """Redistribute a LIVE LM train state between model-axis layouts —
    e.g. a replicated ``dp`` run onto a ``dp-tp`` mesh (or back) without
    a checkpoint round-trip.

    The param re-layout is the same pure bijection the builders use
    (``lm_params_to_tp`` / ``tp_params_to_lm``), applied to the params
    AND to every optimizer-state subtree that mirrors the param tree
    (the momentum/mu/nu family) — so the resharded run continues the
    SAME optimizer trajectory, bit-for-bit, exactly as if the target
    layout had been built fresh from these host values (tested:
    reshard == fresh-build + continue, tests/test_model_axes.py).

    A delayed-overlap state (``parallel.replicated.DelayedState``) is
    accepted when ``codec`` is given: the TRAIN half rides the bijection
    above, but the carry's encoded payload shards are the OLD layout's
    local gradient slices — no bijection exists — so the carry RESETS to
    the fresh ``valid=0`` value on the new layout. That is exactly a
    fresh build's start (the determinism contract holds: reshard ==
    fresh-build from these host values), at the stated cost of the one
    in-flight update: the step after the reshard skips, like step 0.

    Returns ``(mesh, state, state_specs)`` with ``state_specs`` None for
    the replicated target layouts — the same triple
    ``build_model_axis_program`` hands a driver.
    """
    # lazy: mesh.* must not import parallel.* at module level (cycle)
    from atomo_tpu.parallel.replicated import DelayedState

    carry_in = None
    if isinstance(state, DelayedState):
        if codec is None:
            raise ValueError(
                "resharding a DelayedState needs the run's codec: the "
                "fresh carry's zero-payload shapes come from the codec's "
                "encode over the NEW layout's local shard shapes"
            )
        carry_in = state.carry
        state = state.train

    def _with_carry(mesh, new_state, new_specs):
        if carry_in is None:
            return mesh, new_state, new_specs
        from atomo_tpu.parallel.lm import init_model_axis_delayed_state

        return mesh, init_model_axis_delayed_state(
            mesh, new_state, codec
        ), new_specs

    old_layout = old_spec.layout_name()
    new_layout = new_spec.layout_name()
    fam_old = _LAYOUT_PARAM_FAMILY.get(old_layout)
    fam_new = _LAYOUT_PARAM_FAMILY.get(new_layout)
    if fam_old is None or fam_new is None:
        bad = old_layout if fam_old is None else new_layout
        raise ValueError(
            f"layout {bad!r} stores a layout-owned param tree (expert/"
            "stage sharded); live redistribution is proven only between "
            f"{sorted(_LAYOUT_PARAM_FAMILY)} — go through a checkpoint "
            "save/restore instead"
        )
    # lazy: mesh.* must not import parallel.* at module level (cycle)
    from atomo_tpu.parallel.tp import lm_params_to_tp, tp_params_to_lm
    from atomo_tpu.training.trainer import TrainState

    num_heads = int(lm_config["num_heads"])
    params = jax.device_get(state.params)
    opt = jax.device_get(state.opt_state)
    stats = jax.device_get(state.batch_stats)
    if not jax.tree_util.tree_leaves(stats):
        # the LM families carry no batch stats; normalize the empty
        # container (create_state's FrozenDict vs create_tp_lm_state's
        # dict) so the specs tree matches the target builder's exactly
        stats = {}
    if fam_old != fam_new:
        convert = lm_params_to_tp if fam_new == "tp" else tp_params_to_lm
        p_def = jax.tree_util.tree_structure(params)

        def params_like(node) -> bool:
            return jax.tree_util.tree_structure(node) == p_def

        params = convert(params, num_heads)
        # momentum carried EXACTLY: the same bijection on every
        # params-shaped optimizer buffer, scalars (counts) untouched
        opt = jax.tree_util.tree_map(
            lambda sub: convert(sub, num_heads) if params_like(sub) else sub,
            opt,
            is_leaf=params_like,
        )
    mesh = new_spec.build(devices)
    host = TrainState(
        step=jnp.asarray(jax.device_get(state.step), jnp.int32),
        params=params,
        batch_stats=stats,
        opt_state=opt,
    )
    if fam_new == "lm":
        from atomo_tpu.parallel.replicated import replicate_state

        return _with_carry(mesh, replicate_state(mesh, host), None)
    n_tp = dict(new_spec.axes)["tp"]
    if lm_config["num_heads"] % n_tp or lm_config["vocab_size"] % n_tp:
        raise ValueError(
            f"num_heads {lm_config['num_heads']} / vocab_size "
            f"{lm_config['vocab_size']} must divide by tp={n_tp}"
        )
    from atomo_tpu.parallel.tp import (
        make_tp_state_specs,
        shard_tp_state,
        tp_param_specs,
    )

    specs = make_tp_state_specs(host, tp_param_specs(params, "tp"))
    return _with_carry(mesh, shard_tp_state(mesh, host, specs), specs)
