"""Ring attention: exact attention over a sequence-sharded axis.

The reference is CV-only and has no sequence dimension (SURVEY.md §5.7), but
this framework treats long-context as first-class: a sequence of length S is
sharded over the mesh axis ``sp`` (S/n per chip), and attention runs exactly
— not approximately — by rotating key/value blocks around the ring with
``jax.lax.ppermute`` while accumulating a streaming (online-softmax) partial
result. Compute for block t overlaps the transfer of block t+1 on the ICI
torus, which is the TPU-native analogue of the reference's comm/compute
overlap idea (the split-backward models, resnet_split.py:259-361 — there,
per-layer Isend under manual backward; here, XLA pipelines the ppermute).

Memory per chip is O(S/n) for activations and O((S/n)^2) for one score block
— never the full S×S matrix; with n chips the max context grows n× at equal
per-chip HBM.

All shapes are static; the rotation loop is a ``lax.fori_loop`` (compiler-
friendly control flow, no Python unrolling at large n).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _online_softmax_block(q, k_blk, v_blk, bias, m_prev, l_prev, o_prev, scale):
    """One streaming-softmax update: fold a new K/V block into (m, l, o).

    q: (B, H, Sq, D); k_blk/v_blk: (B, H, Sk, D); bias: (Sq, Sk) additive
    mask (-inf for masked); m/l: (B, H, Sq); o: (B, H, Sq, D).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk, precision=jax.lax.Precision.HIGHEST)
    s = s * scale + bias[None, None, :, :]
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    # guard -inf (fully masked rows) against NaN in exp(m_prev - m_new)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1)
    o_new = o_prev * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v_blk, precision=jax.lax.Precision.HIGHEST
    )
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact multi-head attention with sequence sharded over ``axis_name``.

    Call inside shard_map with q/k/v of per-chip shape (B, H, S/n, D); the
    global sequence order is shard-major (chip r holds positions
    [r*S/n, (r+1)*S/n)). Returns the per-chip output block (B, H, S/n, D).
    """
    b, h, s_local, d = q.shape
    if scale is None:
        scale = 1.0 / (d**0.5)
    my = jax.lax.axis_index(axis_name)

    neg = jnp.float32(-jnp.inf)
    q_pos = my * s_local + jnp.arange(s_local)  # global query positions

    def body(t, carry):
        k_blk, v_blk, m, l, o = carry
        # block t came from chip (my + t) mod n  → its global offset
        src = (my + t) % axis_size
        k_pos = src * s_local + jnp.arange(s_local)
        if causal:
            bias = jnp.where(q_pos[:, None] >= k_pos[None, :], 0.0, neg)
        else:
            bias = jnp.zeros((s_local, s_local), jnp.float32)
        m, l, o = _online_softmax_block(q, k_blk, v_blk, bias, m, l, o, scale)
        # rotate K/V one step around the ring (chip r receives from r+1, so
        # after t rotations we hold the block that started at (my + t) mod n)
        perm = [(i, (i - 1) % axis_size) for i in range(axis_size)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, o

    m0 = jnp.full((b, h, s_local), neg, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    o0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    _, _, m, l, o = jax.lax.fori_loop(
        0, axis_size, body, (k.astype(jnp.float32), v.astype(jnp.float32), m0, l0, o0)
    )
    out = o / jnp.maximum(l, jnp.finfo(jnp.float32).tiny)[..., None]
    return out.astype(q.dtype)


def full_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Single-device exact attention (B, H, S, D) — the oracle ring_attention
    must match, and the path used when no 'sp' axis is in play."""
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d**0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, precision=jax.lax.Precision.HIGHEST) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    ).astype(q.dtype)


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_size: int = 512,
) -> jax.Array:
    """Single-device exact attention that never materializes the S×S score
    matrix: streams K/V blocks through the same online-softmax update the
    ring uses, O(Sq·block) score memory. Equals full_attention (tested)."""
    b, h, s, d = q.shape
    if scale is None:
        scale = 1.0 / (d**0.5)
    blk = min(block_size, s)
    n_blocks = -(-s // blk)
    pad = n_blocks * blk - s
    neg = jnp.float32(-jnp.inf)
    kf, vf = k.astype(jnp.float32), v.astype(jnp.float32)
    if pad:  # pad keys with fully-masked positions
        kf = jnp.pad(kf, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, 0), (0, pad), (0, 0)))
    q_pos = jnp.arange(s)

    def body(t, carry):
        m, l, o = carry
        k_blk = jax.lax.dynamic_slice_in_dim(kf, t * blk, blk, axis=2)
        v_blk = jax.lax.dynamic_slice_in_dim(vf, t * blk, blk, axis=2)
        k_pos = t * blk + jnp.arange(blk)
        valid = k_pos[None, :] < s
        if causal:
            valid = valid & (q_pos[:, None] >= k_pos[None, :])
        bias = jnp.where(valid, 0.0, neg)
        return _online_softmax_block(q, k_blk, v_blk, bias, m, l, o, scale)

    m0 = jnp.full((b, h, s), neg, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    o0 = jnp.zeros((b, h, s, d), jnp.float32)
    m, l, o = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, o0))
    out = o / jnp.maximum(l, jnp.finfo(jnp.float32).tiny)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool = False,
    scale: Optional[float] = None,
    block_size: int = 512,
    local_impl: str = "blockwise",
) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses-style) sequence parallelism: swap the
    sequence sharding for a *head* sharding with one ``all_to_all``, run
    blockwise exact attention on whole sequences for H/n local heads, and
    swap back. ``local_impl`` picks the per-chip attention after the swap:
    "blockwise" (jnp online-softmax scan) or "flash" (the fused Pallas
    kernel, ops.attention_kernels — Mosaic on TPU, interpreter on CPU).
    The second first-class long-context strategy next to
    :func:`ring_attention`:

      * ring — n ppermute hops of K/V around the ICI torus, O(S/n)
        sequence activations per chip; best when S is huge and H is small.
      * ulysses — TWO all_to_all collectives total (q/k/v ride one stacked
        collective in, the output one out — vs n hops), and the local
        attention is blockwise (no S×S matrix; O(S·block) score memory,
        O(S/n · H) activations after the swap); needs H divisible by n.

    Same contract as ring_attention: call inside shard_map with per-chip
    (B, H, S/n, D), shard-major global sequence order; returns the per-chip
    (B, H, S/n, D) output block. Exactness is tested against
    full_attention, and gradient parity against ring
    (tests/test_ring.py).
    """
    b, h, s_local, d = q.shape
    if local_impl not in ("blockwise", "flash"):
        raise ValueError(
            f"unknown local_impl {local_impl!r}; expected blockwise|flash"
        )
    if h % axis_size != 0:
        raise ValueError(
            f"ulysses needs heads ({h}) divisible by the {axis_name!r} "
            f"axis ({axis_size}); use ring_attention otherwise"
        )

    # ONE collective for all three operands: stack -> (3, B, H, S/n, D),
    # split heads (axis 2), concat sequence (axis 3)
    qkv = jnp.stack([q, k, v])
    qkv = jax.lax.all_to_all(qkv, axis_name, split_axis=2, concat_axis=3, tiled=True)
    q_g, k_g, v_g = qkv[0], qkv[1], qkv[2]  # (B, H/n, S, D)
    if local_impl == "flash":
        from atomo_tpu.ops.attention_kernels import flash_attention

        out = flash_attention(
            q_g, k_g, v_g, causal=causal, scale=scale,
            block_q=block_size, block_k=block_size,
        )
    else:
        out = blockwise_attention(
            q_g, k_g, v_g, causal=causal, scale=scale, block_size=block_size
        )
    # (B, H/n, S, D) -> (B, H, S/n, D): split the sequence, regather heads
    return jax.lax.all_to_all(
        out, axis_name, split_axis=2, concat_axis=1, tiled=True
    )


ATTENTION_IMPLS = {
    "ring": ring_attention,
    "ulysses": ulysses_attention,
    # Ulysses with the fused Pallas kernel as its local attention — the
    # flash forward IS reachable from training (make_lm_train_step /
    # `lm --attn-impl ulysses-flash`)
    "ulysses-flash": partial(ulysses_attention, local_impl="flash"),
}


def make_sequence_parallel_attention(
    mesh: Mesh, axis: str = "sp", causal: bool = True, impl: str = "ring"
):
    """shard_map-wrapped sequence-parallel attention: (B, H, S, D) arrays
    sharded over ``axis`` on the sequence dim; drop-in for full_attention
    at S too large for one chip. ``impl`` picks the strategy ("ring" |
    "ulysses" — see ulysses_attention for the tradeoff)."""
    if impl not in ATTENTION_IMPLS:
        raise ValueError(
            f"unknown attention impl {impl!r}; expected one of "
            f"{sorted(ATTENTION_IMPLS)}"
        )
    n = mesh.shape[axis]

    fn = partial(ATTENTION_IMPLS[impl], axis_name=axis, axis_size=n, causal=causal)
    return jax.jit(
        jax.shard_map(
            fn,
            mesh=mesh,
            in_specs=(P(None, None, axis, None),) * 3,
            out_specs=P(None, None, axis, None),
            check_vma=False,
        )
    )
