#!/usr/bin/env bash
# Multi-host pod launch — the TPU-native replacement for the reference's L0
# cluster layer (tools/pytorch_ec2.py: EC2 spot fleet + hostfile + pdsh +
# NFS; SURVEY.md §2 'Cluster tools'). On Cloud TPU there is no hostfile to
# build and no ssh fan-out to script: the pod runtime starts one worker per
# host, `jax.distributed` wires them (atomo_tpu.parallel.launch.initialize),
# and jax.devices() spans the slice.
#
# Usage:
#   TPU_NAME=my-v5e-16 ZONE=us-central2-b ./scripts/launch_pod.sh \
#       [extra `atomo_tpu train` flags]
#
# Requires: gcloud CLI authenticated against a project with TPU quota.
set -euo pipefail

TPU_NAME="${TPU_NAME:?set TPU_NAME to the TPU VM/pod name}"
ZONE="${ZONE:?set ZONE}"
WORKDIR="${WORKDIR:-/tmp/atomo_tpu}"

# push the framework to every host (the reference's NFS+pdsh step,
# tools/pytorch_ec2.py:880-905, collapses to one scp fan-out)
gcloud compute tpus tpu-vm scp --recurse --worker=all --zone="$ZONE" \
  "$(git rev-parse --show-toplevel)" "$TPU_NAME":"$WORKDIR"

# run the same SPMD program on every host. On Cloud TPU jax.distributed
# picks coordinator/process-id up from the TPU metadata automatically (one
# ssh fan-out, no env needed). For other fabrics (or to override), export
# JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES here: each worker then needs
# its OWN JAX_PROCESS_ID, so ranks are assigned by per-worker ssh — the
# replacement for the reference's `mpirun --hostfile` rank dispatch
# (src/run_pytorch.sh:1, src/distributed_nn.py:86-88).
if [[ -n "${JAX_COORDINATOR_ADDRESS:-}" ]]; then
  NUM="${JAX_NUM_PROCESSES:?export JAX_NUM_PROCESSES with JAX_COORDINATOR_ADDRESS}"
  for ((i = 0; i < NUM; i++)); do
    gcloud compute tpus tpu-vm ssh --worker="$i" --zone="$ZONE" "$TPU_NAME" \
      --command="cd $WORKDIR && env JAX_COORDINATOR_ADDRESS=$JAX_COORDINATOR_ADDRESS \
JAX_NUM_PROCESSES=$NUM JAX_PROCESS_ID=$i python -m atomo_tpu train $*" &
  done
  wait
else
  gcloud compute tpus tpu-vm ssh --worker=all --zone="$ZONE" "$TPU_NAME" \
    --command="cd $WORKDIR && python -m atomo_tpu train $*"
fi
