"""Tensor parallelism: Megatron-style sharded transformer + compressed DP.

The reference is DP-only (SURVEY.md §2.1 — "full model per process",
src/distributed_worker.py:139-164); a model too large for one worker simply
cannot run there. This module extends the framework with the second model-
sharding axis — a 2-D ('dp', 'tp') mesh (make_tp_lm_train_step) and the
full 3-D ('dp', 'tp', 'sp') composition with ring/Ulysses sequence
parallelism (make_tp_sp_lm_train_step) — where

  tp — attention heads, MLP hidden width, and the vocab projection are
       sharded over the axis; every block costs exactly two ``psum``s in
       forward (after the attention output projection and after the MLP
       down-projection), the classic Megatron cut riding ICI.
  dp — batch replicas exchanging ATOMO-compressed gradients, identical to
       parallel.replicated: each (dp, tp) shard encodes ITS slice of the
       gradient, all_gathers payloads over dp only, and decode+means — so
       gradient compression composes with model sharding instead of being
       an alternative to it.

Design choices (TPU-first):
  * The parameter tree is the stock ``TransformerLM`` tree re-laid-out so
    every sharded matmul is a clean slice: the packed qkv kernel
    (W, 3·H·D) becomes (W, 3, H, D) sharded on H, the output projection
    (H·D, W) becomes (H, D, W) sharded on H. ``lm_params_to_tp`` /
    ``tp_params_to_lm`` are pure reshapes, so checkpoints interchange with
    the single-device model.
  * The LM head is vocab-sharded and the full (B, S, V) logits are NEVER
    materialized: cross-entropy runs on local vocab slices via the
    psum-logsumexp identity (pmax for the max, psum for the partition
    function and the target logit).
  * Gradient completion: under shard_map the transpose of psum is psum, so
    per-shard grads come out uniformly n_tp-scaled — sharded leaves are
    divided by n_tp, tp-replicated leaves (embeddings, LayerNorm scales)
    take a pmean over tp (see the in-code derivation in
    make_tp_lm_train_step and the matching pmean fix in parallel.lm).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from atomo_tpu.mesh.collectives import psum as _axis_psum
from atomo_tpu.parallel.common import (
    layernorm as _layernorm,
    complete_model_axis_grads,
    make_state_specs,
    shard_state,
    shard_tokens_with_spec,
)
from atomo_tpu.parallel.compile import compile_step
from atomo_tpu.parallel.lm import (
    DpExchange,
    dp_exchange_tail,
    sp_boundary_targets_and_mask,
)
from atomo_tpu.parallel.ring import ATTENTION_IMPLS, full_attention
from atomo_tpu.training.trainer import TrainState, cast_params

# ---------------------------------------------------------------------------
# parameter layout: stock TransformerLM tree <-> TP tree (pure reshapes)
# ---------------------------------------------------------------------------


def _blocks(params) -> list[str]:
    return sorted(
        (k for k in params if k.startswith("block")),
        key=lambda k: int(k.removeprefix("block")),
    )


def lm_params_to_tp(params: Any, num_heads: int) -> Any:
    """Re-lay-out a TransformerLM param tree for head-sliced sharding.

    qkv kernel (W, 3·H·D) -> (W, 3, H, D); proj kernel (H·D, W) ->
    (H, D, W). Everything else unchanged. Inverse: :func:`tp_params_to_lm`.
    """
    out = jax.tree_util.tree_map(lambda x: x, params)  # shallow-ish copy
    for blk in _blocks(out):
        attn = out[blk]["MultiHeadAttention_0"]
        qkv = attn["qkv"]["kernel"]
        w = qkv.shape[0]
        d = qkv.shape[1] // (3 * num_heads)
        attn["qkv"]["kernel"] = qkv.reshape(w, 3, num_heads, d)
        proj = attn["proj"]["kernel"]
        attn["proj"]["kernel"] = proj.reshape(num_heads, d, proj.shape[1])
    return out


def tp_params_to_lm(params: Any, num_heads: int) -> Any:
    out = jax.tree_util.tree_map(lambda x: x, params)
    for blk in _blocks(out):
        attn = out[blk]["MultiHeadAttention_0"]
        qkv = attn["qkv"]["kernel"]
        w, _, h, d = qkv.shape
        attn["qkv"]["kernel"] = qkv.reshape(w, 3 * h * d)
        proj = attn["proj"]["kernel"]
        attn["proj"]["kernel"] = proj.reshape(h * d, proj.shape[2])
    return out


def tp_param_specs(tp_params: Any, tp_axis: str = "tp") -> Any:
    """PartitionSpec tree for a TP-laid-out param tree.

    Sharded: qkv on heads, proj on heads, MLP up on hidden, MLP down on
    hidden, head on vocab. Replicated: embeddings, LayerNorm scales.
    """

    def spec(path, leaf) -> P:
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        if "MultiHeadAttention_0" in names:
            if "qkv" in names:
                return P(None, None, tp_axis, None)
            if "proj" in names:
                return P(tp_axis, None, None)
        if "up" in names:
            return P(None, tp_axis)
        if "down" in names:
            return P(tp_axis, None)
        if "head" in names:
            return P(None, tp_axis)
        return P()

    return jax.tree_util.tree_map_with_path(spec, tp_params)


# state-spec construction and sharding live in parallel.common (shared with
# parallel.moe); these aliases are tp's public names for them
make_tp_state_specs = make_state_specs
shard_tp_state = shard_state


def create_tp_lm_state(
    mesh: Mesh, lm_config: dict, optimizer, rng, *, tp_axis: str = "tp"
) -> tuple[TrainState, TrainState]:
    """Init a TransformerLM, re-lay-out for TP, shard over ``mesh``.

    Returns (state, state_specs); pass both to make_tp_lm_train_step.
    """
    n_tp = mesh.shape[tp_axis]
    if lm_config["num_heads"] % n_tp:
        raise ValueError(
            f"num_heads {lm_config['num_heads']} not divisible by tp={n_tp}"
        )
    if lm_config["vocab_size"] % n_tp:
        raise ValueError(
            f"vocab_size {lm_config['vocab_size']} not divisible by tp={n_tp}"
        )
    if 4 * lm_config["width"] % n_tp:  # Block hardcodes mlp_ratio=4
        raise ValueError("MLP hidden width not divisible by tp")
    # lazy: models.transformer imports parallel.ring, so a module-level
    # import here would cycle through parallel/__init__
    from atomo_tpu.models.transformer import TransformerLM

    lm = TransformerLM(**lm_config)
    sample = jnp.zeros((1, min(8, lm_config["max_len"])), jnp.int32)
    params = lm.init(rng, sample)["params"]
    tp_params = lm_params_to_tp(params, lm_config["num_heads"])
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=tp_params,
        batch_stats={},
        opt_state=optimizer.init(tp_params),
    )
    specs = make_tp_state_specs(state, tp_param_specs(tp_params, tp_axis))
    return shard_tp_state(mesh, state, specs), specs


# ---------------------------------------------------------------------------
# TP forward: exact math parity with TransformerLM.apply on the re-laid tree
# ---------------------------------------------------------------------------


def tp_lm_forward(
    params: Any, tokens: jax.Array, *, pos_offset=0, tp_axis=None,
    attention_fn=None,
) -> jax.Array:
    """Per-shard TP forward on a TP-laid (and possibly head/hidden/vocab-
    SLICED) param tree. With ``tp_axis`` set (inside shard_map over sliced
    params) each block does the two Megatron psums — after the attention
    output projection and after the MLP down-projection — so the residual
    stream is the full sum over heads/hidden on every shard. With
    ``tp_axis=None`` and unsliced params this equals TransformerLM.apply on
    the equivalent stock tree (tested). ``attention_fn(q, k, v)`` overrides
    the causal full attention on the LOCAL heads — inject ring/Ulysses to
    compose tp with a sequence-sharded axis (make_tp_sp_lm_train_step).
    Returns LOCAL vocab-slice logits (B, S, V_local)."""
    b, s = tokens.shape
    attn = attention_fn or (
        lambda q, k, v: full_attention(q, k, v, causal=True)
    )

    def _g(t):  # parallel-region exit: all-reduce the partial sums
        # mesh.collectives.psum: the priced model-axis collective — two per
        # block (utils.comm_model.tp_psum_wire_bytes prices exactly these)
        return t if tp_axis is None else _axis_psum(t, tp_axis)

    x = params["tok_emb"]["embedding"][tokens]
    x = x + params["pos_emb"]["embedding"][pos_offset + jnp.arange(s)][None]
    for blk in _blocks(params):
        p = params[blk]
        y = _layernorm(x, p["ln1"]["scale"])
        qkv_k = p["MultiHeadAttention_0"]["qkv"]["kernel"]  # (W, 3, Hl, D)
        qkv = jnp.einsum("bsw,wthd->tbhsd", y, qkv_k)
        out = attn(qkv[0], qkv[1], qkv[2])
        proj_k = p["MultiHeadAttention_0"]["proj"]["kernel"]  # (Hl, D, W)
        x = x + _g(jnp.einsum("bhsd,hdw->bsw", out, proj_k))
        y = _layernorm(x, p["ln2"]["scale"])
        h = jax.nn.gelu(jnp.einsum("bsw,wf->bsf", y, p["up"]["kernel"]))
        x = x + _g(jnp.einsum("bsf,fw->bsw", h, p["down"]["kernel"]))
    x = _layernorm(x, params["ln_f"]["scale"])
    return jnp.einsum("bsw,wv->bsv", x, params["head"]["kernel"])


def tp_sharded_ce_terms(
    logits_local: jax.Array, targets: jax.Array, tp_axis: str, v_local: int
) -> jax.Array:
    """Per-position next-token CE (B, S) over a vocab-sharded logits slice
    (B, S, V_local) without materializing full logits: psum-logsumexp over
    the tp axis. ``targets`` are global token ids aligned with positions."""
    my = jax.lax.axis_index(tp_axis)
    m_local = jnp.max(logits_local, axis=-1)
    # stop_gradient BEFORE pmax: the max shift is AD-invariant and pmax has
    # no differentiation rule, so keep it out of the tangent graph entirely
    m = jax.lax.pmax(jax.lax.stop_gradient(m_local), tp_axis)
    z = jax.lax.psum(
        jnp.sum(jnp.exp(logits_local - m[..., None]), axis=-1), tp_axis
    )
    lse = jnp.log(z) + m
    t_local = targets - my * v_local
    in_range = (t_local >= 0) & (t_local < v_local)
    t_clip = jnp.clip(t_local, 0, v_local - 1)
    picked = jnp.take_along_axis(logits_local, t_clip[..., None], axis=-1)[..., 0]
    correct = jax.lax.psum(jnp.where(in_range, picked, 0.0), tp_axis)
    return lse - correct


def tp_sharded_ce(
    logits_local: jax.Array, targets: jax.Array, tp_axis: str, v_local: int
) -> jax.Array:
    """Mean of :func:`tp_sharded_ce_terms` — the dp x tp loss."""
    return jnp.mean(
        tp_sharded_ce_terms(logits_local, targets, tp_axis, v_local)
    )


# ---------------------------------------------------------------------------
# the dp x tp train step
# ---------------------------------------------------------------------------


def make_tp_lm_train_step(
    lm_config: dict,
    optimizer,
    mesh: Mesh,
    state_specs: TrainState,
    codec=None,
    *,
    dp_axis: str = "dp",
    tp_axis: str = "tp",
    compute_dtype=None,
    aggregate: str = "gather",
    exchange: DpExchange | None = None,
    oracle_parts: bool = False,
):
    """Jitted (state, key, tokens) -> (state, metrics): Megatron-TP forward/
    backward with ATOMO-compressed gradient exchange over dp.

    tokens are (B, S) sharded batch-over-dp, replicated over tp. ``state``
    and ``state_specs`` come from :func:`create_tp_lm_state`. ``exchange``
    (a :class:`~atomo_tpu.parallel.lm.DpExchange`) upgrades the dp tail to
    the full compressed stack; None keeps the legacy tail byte-for-byte.
    """
    n_dp = mesh.shape[dp_axis]
    n_tp = mesh.shape[tp_axis]
    v_local = lm_config["vocab_size"] // n_tp
    param_specs = state_specs.params

    def grads_fn(state: TrainState, key, tokens):
        my_dp = jax.lax.axis_index(dp_axis)
        k_codec = jax.random.fold_in(jax.random.fold_in(key, state.step), my_dp)

        def loss_fn(params):
            if compute_dtype is not None:
                params = cast_params(params, compute_dtype)
            logits_local = tp_lm_forward(params, tokens, tp_axis=tp_axis)
            if compute_dtype is not None:
                logits_local = logits_local.astype(jnp.float32)
            return tp_sharded_ce(
                logits_local[:, :-1], tokens[:, 1:], tp_axis, v_local
            )

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        # Per-shard grad completion (common.complete_model_axis_grads).
        # Under shard_map the transpose of psum is psum, and every
        # loss->leaf path crosses exactly one parallel-region psum (block
        # exits, or the loss logsumexp psums for the head), so per-shard
        # cotangents of replicated activations SUM over tp to n_tp x the
        # true cotangent (verified empirically; see the pmean fix in
        # parallel.lm for the sp-axis instance). divide_by=n_tp removes the
        # uniform n-scaling: sharded leaves become their exact slice grad,
        # replicated leaves get psum/n = pmean.
        grads = complete_model_axis_grads(grads, param_specs, tp_axis, n_tp)
        return k_codec, grads, loss

    def spmd_step(state: TrainState, key, tokens):
        k_codec, grads, loss = grads_fn(state, key, tokens)
        return dp_exchange_tail(
            optimizer, codec, state, k_codec, grads, loss,
            dp_axis=dp_axis, n_dp=n_dp, aggregate=aggregate,
            exchange=exchange,
        )

    if exchange is not None and exchange.overlap == "delayed":
        from atomo_tpu.parallel.lm import make_delayed_model_axis_step

        return make_delayed_model_axis_step(
            grads_fn, optimizer, codec, mesh,
            dp_axis=dp_axis, n_dp=n_dp, exchange=exchange,
            state_specs=state_specs, token_spec=P(dp_axis, None),
            oracle_parts=oracle_parts,
        )

    return compile_step(
        spmd_step,
        mesh,
        in_specs=(state_specs, P(), P(dp_axis, None)),
        out_specs=(state_specs, P()),
        donate_argnums=(0,),
    )


def shard_tp_tokens(mesh: Mesh, tokens, dp_axis: str = "dp"):
    return shard_tokens_with_spec(mesh, tokens, P(dp_axis, None))


# ---------------------------------------------------------------------------
# the dp x tp x sp train step: compression x Megatron x sequence parallelism
# ---------------------------------------------------------------------------


def make_tp_sp_lm_train_step(
    lm_config: dict,
    optimizer,
    mesh: Mesh,
    state_specs: TrainState,
    codec=None,
    *,
    dp_axis: str = "dp",
    tp_axis: str = "tp",
    sp_axis: str = "sp",
    attn_impl: str = "ring",
    compute_dtype=None,
    aggregate: str = "gather",
    exchange: DpExchange | None = None,
    oracle_parts: bool = False,
):
    """Jitted (state, key, tokens) -> (state, metrics) over a 3-D mesh:
    batch over dp, heads/hidden/vocab over tp, SEQUENCE over sp — the full
    composition: each (tp, sp) shard computes ring (or Ulysses) attention
    on its head slice of its sequence shard, the residual psums ride tp,
    K/V rotation rides sp, and the ATOMO-compressed gradient exchange rides
    dp with every chip encoding its own tp slice.

    tokens are (B, S) sharded P(dp, sp); ``state``/``state_specs`` come
    from create_tp_lm_state on the same mesh. Loss is the exact global
    next-token CE (lm.py's boundary-exact handling, vocab-sharded over tp).

    Gradient completion (see the dp x tp step + parallel.lm for the two
    1-axis derivations): every loss->leaf path crosses exactly one sp psum
    (the CE-sum) and one tp psum (block exits or the logsumexp), so
    per-shard grads are uniformly n_tp*n_sp-scaled AND partial over sp;
    completion = psum over sp always, psum over tp for tp-replicated
    leaves, then divide everything by n_tp*n_sp.
    """
    if attn_impl not in ATTENTION_IMPLS:
        raise ValueError(
            f"unknown attn_impl {attn_impl!r}; expected one of "
            f"{sorted(ATTENTION_IMPLS)}"
        )
    n_dp = mesh.shape[dp_axis]
    n_tp = mesh.shape[tp_axis]
    n_sp = mesh.shape[sp_axis]
    v_local = lm_config["vocab_size"] // n_tp
    param_specs = state_specs.params

    def grads_fn(state: TrainState, key, tokens):
        s_local = tokens.shape[1]
        my_dp = jax.lax.axis_index(dp_axis)
        k_codec = jax.random.fold_in(jax.random.fold_in(key, state.step), my_dp)
        attention_fn = partial(
            ATTENTION_IMPLS[attn_impl], axis_name=sp_axis, axis_size=n_sp,
            causal=True,
        )

        def loss_fn(params):
            if compute_dtype is not None:
                params = cast_params(params, compute_dtype)
            logits_local = tp_lm_forward(
                params, tokens, tp_axis=tp_axis,
                pos_offset=jax.lax.axis_index(sp_axis) * s_local,
                attention_fn=attention_fn,
            )
            if compute_dtype is not None:
                logits_local = logits_local.astype(jnp.float32)
            targets, valid = sp_boundary_targets_and_mask(
                tokens, sp_axis, n_sp
            )
            ce = tp_sharded_ce_terms(logits_local, targets, tp_axis, v_local)
            total = jax.lax.psum(jnp.sum(valid), sp_axis)
            return jax.lax.psum(jnp.sum(ce * valid), sp_axis) / total

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        # completion per the docstring: sp-psum everything (params are
        # sp-replicated), tp-psum the tp-replicated leaves, /(n_tp*n_sp)
        grads = jax.lax.psum(grads, sp_axis)
        grads = complete_model_axis_grads(
            grads, param_specs, tp_axis, n_tp * n_sp
        )
        return k_codec, grads, loss

    def spmd_step(state: TrainState, key, tokens):
        k_codec, grads, loss = grads_fn(state, key, tokens)
        return dp_exchange_tail(
            optimizer, codec, state, k_codec, grads, loss,
            dp_axis=dp_axis, n_dp=n_dp, aggregate=aggregate,
            exchange=exchange,
        )

    if exchange is not None and exchange.overlap == "delayed":
        from atomo_tpu.parallel.lm import make_delayed_model_axis_step

        return make_delayed_model_axis_step(
            grads_fn, optimizer, codec, mesh,
            dp_axis=dp_axis, n_dp=n_dp, exchange=exchange,
            state_specs=state_specs, token_spec=P(dp_axis, sp_axis),
            oracle_parts=oracle_parts,
        )

    return compile_step(
        spmd_step,
        mesh,
        in_specs=(state_specs, P(), P(dp_axis, sp_axis)),
        out_specs=(state_specs, P()),
        donate_argnums=(0,),
    )
