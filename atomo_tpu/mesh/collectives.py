"""Named-axis collective helpers — the single source for the collectives
the aggregation schedules place.

Before the mesh subsystem, every schedule hand-placed its own
``jax.lax.ppermute`` permutation lists and tiled ``all_gather`` calls;
a topology plan was a recipe of raw collectives. These helpers are the
sharding-annotated spelling: each one is a THIN, trace-identical wrapper
over the ``jax.lax`` primitive (same op, same arguments, byte-identical
HLO — the legacy-plan byte-identity tests pin this), so call sites
migrate without moving a single compiled instruction, and the mesh axis
name is the only vocabulary a schedule needs.
"""

from __future__ import annotations

import jax


def ring_perm(n: int) -> list[tuple[int, int]]:
    """The canonical ring rotation ``i -> i-1 (mod n)``: payload chunk t
    held by chip i moves so that after t hops chip i holds source
    ``(i + t) % n`` — the rotation every ring schedule in the repo uses
    (ONE definition; the staging index math in _ring_stream_mean assumes
    exactly this direction)."""
    return [(i, (i - 1) % n) for i in range(n)]


def ppermute_ring(x, axis: str, n: int):
    """One ring hop of ``x`` over the named ``axis``."""
    return jax.lax.ppermute(x, axis, ring_perm(n))


def all_gather(x, axis):
    """Stacking all_gather (leading source axis) over one or more named
    axes."""
    return jax.lax.all_gather(x, axis)


def all_gather_tiled(x, axis):
    """Tiled all_gather: per-chip slices concatenate along dim 0 — the
    republish step of every sharded-segment reduction (ring segment
    means, ZeRO-1 and sharded-update param reassembly)."""
    return jax.lax.all_gather(x, axis, tiled=True)


def psum_mean(x, axis):
    """Dense mean over named data axes (the psum exchange)."""
    return jax.lax.pmean(x, axis)


def psum(x, axis):
    """Dense sum over a named axis — the model-axis completion collective
    (Megatron TP partial-product reduction, pp/moe gradient completion:
    model shards hold PARTS of one replica's value, so sum, don't mean)."""
    return jax.lax.psum(x, axis)


def pipeline_perm(n: int) -> list[tuple[int, int]]:
    """The pipeline forward shift ``i -> i+1 (mod n)``: stage i's
    activations move to stage i+1 each tick (the GPipe microbatch chain).
    The opposite rotation from :func:`ring_perm` — activations flow DOWN
    the stage order while ring payload chunks flow up it."""
    return [(i, (i + 1) % n) for i in range(n)]


def ppermute_pipeline(x, axis: str, n: int):
    """One pipeline tick: shift ``x`` to the next stage over ``axis``."""
    return jax.lax.ppermute(x, axis, pipeline_perm(n))


def all_to_all_tiled(x, axis: str, *, split_axis: int, concat_axis: int):
    """Tiled all_to_all over a named axis — the MoE dispatch/return
    shuffle (split one array dim across the axis peers, concatenate what
    arrives along another)."""
    return jax.lax.all_to_all(
        x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )
