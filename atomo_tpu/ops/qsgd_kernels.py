"""Pallas TPU kernels for the QSGD quantize→bit-pack hot path.

Reference equivalent: the per-value uint64 shifting loops of
src/codings/qsgd.py:52-79 (pack) and :126-139 (unpack), run in numpy on the
host CPU. Here the whole encode — per-bucket scale (L2 for qsgd, max-norm
for terngrad), stochastic rounding (on-core PRNG, no key streams from HBM),
sign/magnitude coding, and uint32 word packing — is one fused VMEM-resident
kernel: the gradient is read from HBM exactly once and only the ~(1+b)/32-
sized words go back out, so encode bandwidth ≈ the payload size rather than
2x the dense gradient.

Wire format (round 3, *planar*): words have shape
(n_buckets, words_per_bucket) uint32. Within a bucket padded to
bucket_p = vpw * n_words values (vpw = floor(32/(1+b)) values per word),
the value at bucket position p = j*n_words + w sits in word w at bit
j*(1+b). This planar layout (vs round 2's interleaved p = w*vpw + j) is
what real-TPU Mosaic can express: packing is a Python loop of middle-axis
slices over a (block, vpw, n_words) tile — the interleaved layout needed a
lane-dim-splitting reshape, which Mosaic rejects ("infer-vector-layout:
unsupported shape cast", hardware-verified this round). ``QsgdCodec`` emits
and accepts this exact layout from both its jnp path and these kernels, so
the fused kernels ARE the production encode on TPU; the jnp path is the
test oracle.

Mosaic dtype discipline (all hardware-verified failures): no uint32
reductions, no u32<->f32 or bool->u32 casts — the kernels therefore compute
codes entirely in int32 (bit-identical for these small non-negative
fields) and bitcast to uint32 only at the output boundary.

RNG: passing ``u`` (external jax.random uniforms) makes the kernel
bit-identical to the jnp oracle; ``u=None`` draws from the on-core PRNG —
the zero-extra-bandwidth TPU hot path (per-block seeds: the block index is
folded into the seed so stochastic-rounding noise is independent across
blocks — round-1 ADVICE finding). Kernels run under the TPU-semantics
interpreter on CPU for tests (whose prng_random_bits is a zero stub, so
interpreter tests must pass explicit ``u``).

The grid tiles buckets; bucket_size is padded to the word boundary, so any
bucket_size works (the default 512 = reference --bucket-size).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def is_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Pack-kernel default decision record (the use_pallas precedent, codified)
# ---------------------------------------------------------------------------
#
# The round-4 rule for every hand kernel in this repo: NO kernel
# auto-selects without a measured hardware win on record (the fused
# use_pallas quantize kernel measured SLOWER than XLA's fusion on v5e —
# encode 2.68/2.79 ms pallas vs 2.52/2.59 ms jnp, 8.4M values — and its
# auto-selection was flipped OFF with those numbers quoted). This table
# makes the rule a MECHANISM instead of a docstring: ``pack_kernel=None``
# resolves default-ON exactly for the device kinds listed here with a
# measured win, and to the jnp oracle everywhere else — including every
# non-TPU backend, which stays the automatic fallback unconditionally.
# A future bench round that records a pack-kernel win on real hardware
# graduates the kernel by adding one entry with its evidence pointer; no
# code-path change, and the decision is auditable in-place.
PACK_KERNEL_MEASURED_WINS: dict = {
    # device-kind substring (lowercase) -> {"win": bool, "evidence": str}
    #
    # No entry yet: the bucketed pack/unpack kernels (PR 10) have no
    # real-TPU measurement on record — bench.py measures both paths each
    # round, and the first recorded win lands here with its artifact.
}


def pack_kernel_default(
    device_kind: Optional[str] = None, on_tpu: Optional[bool] = None
) -> bool:
    """Resolve ``QsgdCodec.pack_kernel=None``: True only on a real TPU
    whose device kind has a measured win recorded in
    :data:`PACK_KERNEL_MEASURED_WINS`; False (the jnp oracle) everywhere
    else — off-TPU backends fall back automatically by construction.

    ``device_kind``/``on_tpu`` default to the live backend; passing them
    explicitly is the graduation DRILL (tests and the controller's
    pack-kernel pricing): a synthetic win recorded for a device-kind
    substring must flip this default for that kind — and only that kind
    — without any code-path change. The measurement procedure that earns
    a real entry is documented in README "Graduating the pack kernel"."""
    if on_tpu is None:
        on_tpu = is_tpu()
    if not on_tpu:
        return False
    if device_kind is None:
        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:
            return False
    kind = str(device_kind).lower()
    for tag, rec in PACK_KERNEL_MEASURED_WINS.items():
        if tag in kind and rec.get("win"):
            return True
    return False


def _interpret_mode(interpret: bool):
    """True → the TPU-semantics interpreter (generic interpret mode has no
    CPU lowering for pltpu.prng_* primitives). On jax versions without
    ``pltpu.InterpretParams`` this falls back to plain ``interpret=True`` —
    fine for the external-uniform kernels the tests use; the on-core-PRNG
    path needs real hardware there."""
    from atomo_tpu.compat import pallas_tpu_interpret_mode

    return pallas_tpu_interpret_mode(interpret)


def _finish_quantize(x, u, words_ref, scales_ref, *, bits, levels, vpw, scheme):
    """x, u: (B_blk, vpw, n_words) planar bucket tiles → packed words.

    int32 throughout (Mosaic has no unsigned reductions / u32 casts); the
    field values are small and non-negative so the detour is exact.
    """
    # per-bucket scale: reduce the (vpw, n_words) tile in two supported
    # stages (middle axis, then lane axis with keepdims)
    if scheme == "terngrad":
        scale = jnp.max(jnp.max(jnp.abs(x), axis=1), axis=1, keepdims=True)
    else:
        scale = jnp.sqrt(jnp.sum(jnp.sum(x * x, axis=1), axis=1, keepdims=True))
    safe = jnp.maximum(scale, jnp.finfo(jnp.float32).tiny)  # (B_blk, 1)
    y = jnp.abs(x) / safe[:, :, None] * levels
    lo = jnp.floor(y)
    frac = y - lo
    level = jnp.clip(lo + (u < frac), 0, levels).astype(jnp.int32)
    sign = (x < 0).astype(jnp.int32)
    codes = (sign << bits) | level  # (B_blk, vpw, n_words) int32
    bpv = bits + 1
    acc = codes[:, 0, :]
    for j in range(1, vpw):
        acc = acc | (codes[:, j, :] << (j * bpv))
    words_ref[:] = jax.lax.bitcast_convert_type(acc, jnp.uint32)
    scales_ref[:] = scale


def _quantize_pack_kernel(
    x_ref, seed_ref, words_ref, scales_ref, *, bits, levels, vpw, scheme
):
    """One grid step: a block of buckets (B_blk, vpw, n_words) → packed
    words. Stochastic-rounding uniforms come from the on-core PRNG (no HBM
    key stream). The block index is folded into the seed so each block
    draws an independent stream (ADVICE r1: a shared scalar seed correlated
    the rounding noise across blocks)."""
    pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
    x = x_ref[:]  # (B_blk, vpw, n_words)
    rbits = pltpu.bitcast(pltpu.prng_random_bits(x.shape), jnp.uint32)
    # uniform in [0,1) from the top 24 bits (exact float32 representability).
    # Mosaic has no u32->f32 cast; the top-24-bit values fit in int32, so
    # route the cast through int32 (VERDICT r2 finding 1).
    u = (rbits >> 8).astype(jnp.int32).astype(jnp.float32) * (1.0 / (1 << 24))
    _finish_quantize(
        x, u, words_ref, scales_ref, bits=bits, levels=levels, vpw=vpw, scheme=scheme
    )


def _quantize_pack_kernel_ext(
    x_ref, u_ref, words_ref, scales_ref, *, bits, levels, vpw, scheme
):
    """External-uniform variant: u in [0,1) supplied as a second input —
    bit-identical to the jnp oracle when fed the same uniforms."""
    _finish_quantize(
        x_ref[:], u_ref[:], words_ref, scales_ref,
        bits=bits, levels=levels, vpw=vpw, scheme=scheme,
    )


def _unpack_dequantize_kernel(
    words_ref, scales_ref, out_ref, *, bits: int, levels: int, vpw: int
):
    bpv = bits + 1
    words = jax.lax.bitcast_convert_type(words_ref[:], jnp.int32)  # (B_blk, n_words)
    scales = scales_ref[:]  # (B_blk, 1)
    mask = (1 << bpv) - 1
    inv = 1.0 / levels
    for j in range(vpw):
        # arithmetic >> then & mask == logical shift for these fields
        codes = (words >> (j * bpv)) & mask
        level = (codes & levels).astype(jnp.float32)
        sign = 1.0 - 2.0 * ((codes >> bits) & 1).astype(jnp.float32)
        out_ref[:, j, :] = sign * level * inv * scales


def padded_bucket(bucket_size: int, bits: int) -> int:
    """Bucket size rounded up to a whole number of uint32 words."""
    vpw = 32 // (bits + 1)
    return -(-bucket_size // vpw) * vpw


def words_per_bucket(bucket_size: int, bits: int) -> int:
    vpw = 32 // (bits + 1)
    return padded_bucket(bucket_size, bits) // vpw


@partial(
    jax.jit,
    static_argnames=("bits", "bucket_size", "scheme", "interpret", "block"),
)
def pallas_quantize_pack(
    x: jax.Array,
    seed: jax.Array,
    u: Optional[jax.Array] = None,
    *,
    bits: int,
    bucket_size: int = 512,
    scheme: str = "qsgd",
    interpret: bool = False,
    block: int = 8,
):
    """Fused QSGD encode. x: flat float32; returns (words, scales) with
    words (n_buckets, words_per_bucket) uint32, scales (n_buckets,) f32 —
    the codec wire format (planar field layout, see module docstring).

    ``u=None`` draws stochastic-rounding uniforms from the on-core PRNG
    seeded per-block from ``seed`` (TPU hot path, zero extra bandwidth);
    passing ``u`` of shape (n_buckets, bucket_size) uses those uniforms
    (oracle-checkable; required under the interpreter, whose
    prng_random_bits is a zero stub)."""
    vpw = 32 // (bits + 1)
    n = x.shape[0]
    n_buckets = -(-n // bucket_size)
    blocks = -(-n_buckets // block)
    pad_buckets = blocks * block
    bucket_p = padded_bucket(bucket_size, bits)
    n_words = bucket_p // vpw

    def to_planar(flat, fill_rows):
        """(rows, bucket_size) values → (pad_buckets, vpw, n_words) planar."""
        g = jnp.zeros((pad_buckets, bucket_p), jnp.float32)
        g = g.at[:fill_rows, :bucket_size].set(flat)
        return g.reshape(pad_buckets, vpw, n_words)

    x_rows = jnp.zeros((n_buckets * bucket_size,), jnp.float32).at[:n].set(x)
    grid_x = to_planar(x_rows.reshape(n_buckets, bucket_size), n_buckets)

    out_shape = (
        jax.ShapeDtypeStruct((pad_buckets, n_words), jnp.uint32),
        jax.ShapeDtypeStruct((pad_buckets, 1), jnp.float32),
    )
    out_specs = (
        pl.BlockSpec((block, n_words), lambda i: (i, 0)),
        pl.BlockSpec((block, 1), lambda i: (i, 0)),
    )
    levels = (1 << bits) - 1
    if u is None:
        seeds = jnp.asarray(seed, jnp.int32).reshape(1)
        words, scales = pl.pallas_call(
            partial(
                _quantize_pack_kernel,
                bits=bits, levels=levels, vpw=vpw, scheme=scheme,
            ),
            out_shape=out_shape,
            grid=(blocks,),
            in_specs=[
                pl.BlockSpec((block, vpw, n_words), lambda i: (i, 0, 0)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=out_specs,
            interpret=_interpret_mode(interpret),
        )(grid_x, seeds)
    else:
        grid_u = to_planar(u, n_buckets)
        words, scales = pl.pallas_call(
            partial(
                _quantize_pack_kernel_ext,
                bits=bits, levels=levels, vpw=vpw, scheme=scheme,
            ),
            out_shape=out_shape,
            grid=(blocks,),
            in_specs=[
                pl.BlockSpec((block, vpw, n_words), lambda i: (i, 0, 0)),
                pl.BlockSpec((block, vpw, n_words), lambda i: (i, 0, 0)),
            ],
            out_specs=out_specs,
            interpret=_interpret_mode(interpret),
        )(grid_x, grid_u)
    return words[:n_buckets], scales[:n_buckets, 0]


def _pack_codes_kernel(codes_ref, words_ref, *, bits: int, vpw: int):
    """One grid step: a block of planar code tiles (B_blk, vpw, n_words)
    int32 -> packed uint32 words (B_blk, n_words). The bare bit-pack stage
    of _finish_quantize, split out so the BUCKETED pack/unpack behind
    ``--stream-encode``'s layer-bucket boundary can run fused without the
    quantizer (the codec's jnp ``pack_bucketed`` is the bit-parity
    oracle). Same Mosaic dtype discipline: int32 fields (small,
    non-negative — exact), bitcast to uint32 only at the output."""
    bpv = bits + 1
    codes = codes_ref[:]
    acc = codes[:, 0, :]
    for j in range(1, vpw):
        acc = acc | (codes[:, j, :] << (j * bpv))
    words_ref[:] = jax.lax.bitcast_convert_type(acc, jnp.uint32)


def _unpack_codes_kernel(words_ref, out_ref, *, bits: int, vpw: int):
    """Inverse of :func:`_pack_codes_kernel`: words -> planar int32 codes
    (arithmetic >> then & mask == logical shift for these fields)."""
    bpv = bits + 1
    words = jax.lax.bitcast_convert_type(words_ref[:], jnp.int32)
    mask = (1 << bpv) - 1
    for j in range(vpw):
        out_ref[:, j, :] = (words >> (j * bpv)) & mask


@partial(jax.jit, static_argnames=("bits", "interpret", "block"))
def pallas_pack_bucketed(
    codes: jax.Array, *, bits: int, interpret: bool = False, block: int = 8
):
    """Fused bucketed bit-pack: (n_buckets, bucket_p) codes ->
    (n_buckets, bucket_p/vpw) uint32 words, bit-identical to the jnp
    ``codecs.qsgd.pack_bucketed`` (the oracle; planar field layout —
    bucket position p = j*n_words + w sits in word w at bit j*(1+bits)).
    ``bucket_p`` must be a whole number of vals-per-word, exactly as the
    jnp path requires. One VMEM-resident pass: the codes are read from
    HBM once and only the ~1/vpw-sized words go back out."""
    vpw = 32 // (bits + 1)
    nb, bucket_p = codes.shape
    if bucket_p % vpw:
        raise ValueError(
            f"bucket_p {bucket_p} must be a multiple of vals-per-word "
            f"{vpw} (pad with zero codes first — the pack_bucketed "
            "contract)"
        )
    n_words = bucket_p // vpw
    blocks = -(-nb // block)
    pad_b = blocks * block
    # int32 in-kernel (Mosaic has no u32 ops); code fields are < 2^(1+bits)
    planar = (
        jnp.zeros((pad_b, bucket_p), jnp.int32)
        .at[:nb]
        .set(codes.astype(jnp.int32))
        .reshape(pad_b, vpw, n_words)
    )
    words = pl.pallas_call(
        partial(_pack_codes_kernel, bits=bits, vpw=vpw),
        out_shape=jax.ShapeDtypeStruct((pad_b, n_words), jnp.uint32),
        grid=(blocks,),
        in_specs=[pl.BlockSpec((block, vpw, n_words), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((block, n_words), lambda i: (i, 0)),
        interpret=_interpret_mode(interpret),
    )(planar)
    return words[:nb]


@partial(jax.jit, static_argnames=("bits", "interpret", "block"))
def pallas_unpack_bucketed(
    words: jax.Array, *, bits: int, interpret: bool = False, block: int = 8
):
    """Fused inverse of :func:`pallas_pack_bucketed`: (nb, wpb) uint32 ->
    (nb, wpb*vpw) uint32 codes, bit-identical to the jnp
    ``codecs.qsgd.unpack_bucketed`` oracle."""
    vpw = 32 // (bits + 1)
    nb, n_words = words.shape
    blocks = -(-nb // block)
    pad_b = blocks * block
    w = jnp.zeros((pad_b, n_words), jnp.uint32).at[:nb].set(words)
    codes = pl.pallas_call(
        partial(_unpack_codes_kernel, bits=bits, vpw=vpw),
        out_shape=jax.ShapeDtypeStruct((pad_b, vpw, n_words), jnp.int32),
        grid=(blocks,),
        in_specs=[pl.BlockSpec((block, n_words), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block, vpw, n_words), lambda i: (i, 0, 0)),
        interpret=_interpret_mode(interpret),
    )(w)
    # fields are < 2^(1+bits): the int32 detour is exact (module docstring)
    return codes.reshape(pad_b, vpw * n_words)[:nb].astype(jnp.uint32)


@partial(jax.jit, static_argnames=("bits", "bucket_size", "n", "interpret", "block"))
def pallas_unpack_dequantize(
    words: jax.Array,
    scales: jax.Array,
    *,
    bits: int,
    bucket_size: int = 512,
    n: int,
    interpret: bool = False,
    block: int = 8,
):
    """Fused QSGD decode: (words, scales) → flat float32 of length n."""
    vpw = 32 // (bits + 1)
    n_buckets = scales.shape[0]
    blocks = -(-n_buckets // block)
    pad_buckets = blocks * block
    bucket_p = padded_bucket(bucket_size, bits)
    n_words = bucket_p // vpw

    w = jnp.zeros((pad_buckets, n_words), jnp.uint32).at[:n_buckets].set(words)
    s = jnp.zeros((pad_buckets, 1), jnp.float32).at[:n_buckets, 0].set(scales)

    vals = pl.pallas_call(
        partial(
            _unpack_dequantize_kernel, bits=bits, levels=(1 << bits) - 1, vpw=vpw
        ),
        out_shape=jax.ShapeDtypeStruct((pad_buckets, vpw, n_words), jnp.float32),
        grid=(blocks,),
        in_specs=[
            pl.BlockSpec((block, n_words), lambda i: (i, 0)),
            pl.BlockSpec((block, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block, vpw, n_words), lambda i: (i, 0, 0)),
        interpret=_interpret_mode(interpret),
    )(w, s)
    vals = vals.reshape(pad_buckets, bucket_p)
    return vals[:n_buckets, :bucket_size].reshape(-1)[:n]
