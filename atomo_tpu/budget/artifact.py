"""budget_alloc.json — the allocation as a first-class run artifact.

The determinism contract (ISSUE 15, house style): a frozen allocation is
bit-identical across replicas and superstep partitions BECAUSE it is a
trace-time constant; re-allocation happens only at checkpoint
boundaries; and kill->restart->resume replays bit-exact because the
artifact records every allocation epoch with its start step — a resume
rebuilds the wrapped codec from the RECORDED epoch instead of
re-measuring spectra (the ``tune_decision.json`` reuse precedent,
including the refuse-on-mismatch half: a doc recorded for a different
codec or leaf count re-allocates instead of silently applying).

Written atomically (``utils.tracing.write_json_atomic`` — the artifact
discipline the lint enforces over this package by construction).

Document shape::

    {"kind": "budget_alloc", "complete": true,
     "codec": "svd", "sample": "fixed_k", "alloc": "variance",
     "budget_bytes": B, "n_leaves": L,
     "epochs": [{"epoch": 0, "start_step": 0, "mode": "variance",
                 "ks": [...], "payload_bytes": P,
                 "predicted_variance": V,
                 "layers": [{"name", "k", "payload_bytes"}, ...]}, ...]}
"""

from __future__ import annotations

import json
import os
from typing import Optional

from atomo_tpu.budget.allocator import Allocation, allocation_leaf_budgets

BUDGET_ALLOC_NAME = "budget_alloc.json"


def alloc_path(train_dir: str) -> str:
    return os.path.join(train_dir, BUDGET_ALLOC_NAME)


def _epoch_record(codec, spectra, alloc: Allocation, start_step: int) -> dict:
    pairs = allocation_leaf_budgets(codec, spectra, alloc.ks)
    return {
        "epoch": int(alloc.epoch),
        "start_step": int(start_step),
        "mode": alloc.mode,
        "ks": [int(k) for k in alloc.ks],
        "payload_bytes": int(alloc.payload_bytes),
        "budget_bytes": int(alloc.budget_bytes),
        "predicted_variance": float(alloc.predicted_variance),
        "layers": [
            {
                "name": l.name,
                "k": int(alloc.ks[l.index]),
                "adaptive": bool(l.adaptive),
                "dense_bytes": int(l.dense_bytes),
                "payload_bytes": int(pairs[l.index][1]),
            }
            for l in spectra
        ],
    }


def new_alloc_doc(codec, spectra, alloc: Allocation) -> dict:
    base = getattr(codec, "base", codec)
    return {
        "kind": "budget_alloc",
        "complete": True,
        "codec": getattr(base, "name", str(base)),
        "sample": getattr(base, "sample", None),
        "alloc": alloc.mode,
        "budget_bytes": int(alloc.budget_bytes),
        "n_leaves": len(spectra),
        "epochs": [_epoch_record(codec, spectra, alloc, 0)],
    }


def append_epoch(
    doc: dict, codec, spectra, alloc: Allocation, start_step: int
) -> dict:
    doc = dict(doc)
    doc["epochs"] = list(doc.get("epochs", [])) + [
        _epoch_record(codec, spectra, alloc, start_step)
    ]
    return doc


def write_alloc(train_dir: str, doc: dict) -> str:
    from atomo_tpu.utils.tracing import write_json_atomic

    path = alloc_path(train_dir)
    write_json_atomic(path, doc)
    return path


def read_alloc(train_dir: Optional[str]) -> Optional[dict]:
    """Parse budget_alloc.json; missing/unparseable -> None (the caller
    re-allocates from a fresh probe and says so)."""
    if not train_dir:
        return None
    try:
        with open(alloc_path(train_dir)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def latest_epoch(doc: Optional[dict]) -> Optional[dict]:
    if not doc:
        return None
    epochs = doc.get("epochs") or []
    return epochs[-1] if epochs else None


def alloc_reusable(
    doc: Optional[dict], *, codec_name: str, n_leaves: int
) -> tuple:
    """Can a ``--resume`` reuse this recorded allocation? PURE function
    of the document (the ``decision_reusable`` precedent): a doc for a
    different codec or a different leaf count would size payloads for a
    model that no longer exists — refuse out loud, re-allocate."""
    if not doc or not doc.get("complete"):
        return False, "budget_alloc.json is missing or incomplete"
    ep = latest_epoch(doc)
    if not ep or not ep.get("ks"):
        return False, "budget_alloc.json records no allocation epoch"
    if doc.get("codec") != codec_name:
        return False, (
            f"allocation was recorded for codec {doc.get('codec')!r} but "
            f"this run compresses with {codec_name!r} — re-allocating"
        )
    if int(doc.get("n_leaves", -1)) != int(n_leaves):
        return False, (
            f"allocation covers {doc.get('n_leaves')} leaves but this "
            f"model has {n_leaves} — re-allocating"
        )
    return True, (
        f"reusing recorded allocation epoch {ep.get('epoch')} "
        f"({ep.get('payload_bytes')} B predicted wire)"
    )


def allocation_meta(epoch_record: dict) -> dict:
    """The flight-recorder meta line for one allocation epoch: the
    per-layer budget columns metrics.jsonl carries (``what`` is
    epoch-qualified so the recorder's idempotent write_meta keeps one
    line PER epoch, and ``report``'s budget_alloc_consistent check can
    match each against the artifact)."""
    return {
        "what": f"budget_alloc_epoch{int(epoch_record['epoch'])}",
        "budget_epoch": int(epoch_record["epoch"]),
        "start_step": int(epoch_record["start_step"]),
        "mode": epoch_record.get("mode"),
        "payload_bytes": int(epoch_record["payload_bytes"]),
        "predicted_variance": epoch_record.get("predicted_variance"),
        "layers": [
            {
                "name": l["name"],
                "k": int(l["k"]),
                "payload_bytes": int(l["payload_bytes"]),
            }
            for l in epoch_record.get("layers", [])
        ],
    }
