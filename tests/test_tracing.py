"""utils.tracing coverage: the profiler trace capture (``profile``) and
the ``--profile-dir`` CLI flag — the trace-capture surface had zero tests
(PR-11 satellite). Runs on the forced CPU mesh (conftest)."""

import os

import jax
import jax.numpy as jnp
import pytest

from atomo_tpu.utils.tracing import (
    IncidentLog,
    format_incident,
    profile,
    read_jsonl,
    span,
)


def _files_under(root):
    return [
        os.path.join(b, f)
        for b, _, fs in os.walk(root)
        for f in fs
    ]


def test_profile_captures_a_device_trace(tmp_path):
    """profile(dir) must leave a loadable jax.profiler trace — the only
    honest way to see phase cost inside a fused program."""
    f = jax.jit(lambda x: jnp.sum(x * x))
    with profile(str(tmp_path)):
        float(f(jnp.arange(64.0)))
    files = _files_under(tmp_path)
    assert files, "no trace files written"
    assert any("xplane" in f or "trace" in f for f in files), files


def test_profile_stops_trace_on_error(tmp_path):
    """The trace must be closed even when the profiled block raises —
    a leaked open trace would crash the next capture."""
    with pytest.raises(RuntimeError, match="boom"):
        with profile(str(tmp_path)):
            raise RuntimeError("boom")
    # a second capture works: the previous one was stopped
    with profile(str(tmp_path)):
        float(jax.jit(jnp.sum)(jnp.ones(4)))
    assert _files_under(tmp_path)


@pytest.mark.slow  # full end-to-end CLI training under the profiler (~14 s
# on 1 core) — full-suite only; test_fabric_obs's timeline test keeps
# trace-production coverage in the smoke set
def test_cli_profile_dir_flag_produces_trace(tmp_path, capsys):
    """The --profile-dir trace flag end to end: a short distributed run
    announces the profiled window and leaves trace files."""
    from atomo_tpu.cli import main

    prof = tmp_path / "trace"
    rc = main([
        "train", "--synthetic", "--dataset", "mnist", "--network", "lenet",
        "--batch-size", "8", "--max-steps", "4", "--eval-freq", "0",
        "--log-interval", "0", "--n-devices", "2", "--code", "qsgd",
        "--quantization-level", "8", "--aggregate", "gather",
        "--train-dir", str(tmp_path / "run"), "--momentum", "0.0",
        "--profile-dir", str(prof),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Profiling steps" in out
    assert _files_under(prof), "no profiler trace written by --profile-dir"


def test_span_and_read_jsonl_and_format_incident(tmp_path):
    sink = {}
    with span("load", sink):
        pass
    assert sink["load"] >= 0.0
    log = IncidentLog(str(tmp_path / "i.jsonl"))
    log.append("membership", action="shrink", step=4, epoch=1, world=3)
    recs = read_jsonl(str(tmp_path / "i.jsonl"))
    assert len(recs) == 1
    line = format_incident(recs[0])
    # the PR-9 special cases live in the SHARED formatter now
    assert "epoch=1" in line and "world=3" in line and "-> shrink" in line
    assert IncidentLog.summarize(str(tmp_path / "i.jsonl")).count(line) == 1
