"""AlexNet, as a Flax module.

Architecture parity with src/model_ops/alexnet.py:13-47 (the torchvision
'one weird trick' variant): 5 conv features with 3 maxpools, classifier
Dropout -> 4096 -> ReLU -> Dropout -> 4096 -> ReLU -> num_classes.

Note: the reference wires AlexNet into its CIFAR CLI
(src/distributed_worker.py:154-155) although the 224x224 feature geometry
collapses 32x32 inputs to zero spatial size — i.e. the reference's AlexNet
path only works on ImageNet-sized inputs. We keep the faithful geometry and
flatten dynamically, so 224x224 inputs reproduce the 256*6*6 classifier
input; small inputs raise a clear shape error instead of a torch crash.
"""

from __future__ import annotations

import flax.linen as nn


class AlexNet(nn.Module):
    num_classes: int = 1000

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(64, (11, 11), strides=4, padding=2)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(192, (5, 5), padding=2)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(nn.Conv(384, (3, 3), padding=1)(x))
        x = nn.relu(nn.Conv(256, (3, 3), padding=1)(x))
        x = nn.relu(nn.Conv(256, (3, 3), padding=1)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        if x.shape[1] == 0 or x.shape[2] == 0:
            raise ValueError(
                f"AlexNet features collapsed to spatial size {x.shape[1:3]}; "
                "input must be >= 63x63 (224x224 canonical)."
            )
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096)(x))
        return nn.Dense(self.num_classes)(x)


def alexnet(num_classes: int = 1000) -> AlexNet:
    return AlexNet(num_classes=num_classes)
