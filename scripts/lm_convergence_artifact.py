"""Produce the LM convergence-parity artifact: compressed vs dense training
of the transformer LM on a dp mesh.

The CV artifact (scripts/convergence_artifact.py) proves the codec on
ResNet gradient spectra; this one proves it on TRANSFORMER gradients — the
matrices the tp/sp/pp/ep superset axes actually train. Three runs of the
dp-parallel LM step (parallel/lm.py with sp=1), identical data/seeds:
dense pmean, SVD rank-3 gather, and the deliberately-biased no-probes
ablation that must FAIL the gate (round-4 hardening, VERDICT r3 #6 —
plus token noise so the loss floor stays off zero and the gate can
discriminate). Writes artifacts/LM_CONVERGENCE.json + .md with the loss
curves, the final-window loss ratios, and the measured byte reduction.

Data: deterministic synthetic streams in the lm CLI's style (arithmetic
progressions with random starts/strides — learnable structure, reproducible
from this script's fixed seed; stride range differs from the CLI's).

Usage: python scripts/lm_convergence_artifact.py [--steps 300] [--out artifacts]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The recipe is calibrated at a 4-way dp mesh (batch 32); on a 1-device CPU
# the batch silently shrinks to 8 and the gate numbers mean nothing. Force
# the virtual device count BEFORE jax import unconditionally — the flag
# only affects the HOST platform, so it is inert on a real TPU run — and
# hard-fail after backend init if fewer than 4 devices resolved anyway.
_fl = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _fl:
    os.environ["XLA_FLAGS"] = (
        _fl + " --xla_force_host_platform_device_count=4"
    ).strip()


# the measured flooring rank: rank 3 floors a width-64 LM at 1.39x dense CE
# (sweep 2026-07-30) and lands out-of-bound (1.178) at width 128 — the
# configuration the width-scaled policy exists to prevent, and therefore the
# foil for policy-rank gate runs
FLOOR_RANK = 3


def resolve_ablation(choice: str, rank: int, default_rank: int) -> str:
    """Pick the gate's foil. The no-probes sketch converges toward the
    production codec as rank grows (measured: w128 rank-12 no-probes ratio
    1.141, under the 1.15 bound), so above-default ranks foil against the
    measured flooring rank instead. Raises on the degenerate
    rank<=FLOOR_RANK floor-rank combination (the foil IS that rank)."""
    if choice == "auto":
        choice = "floor-rank" if rank > default_rank else "noprobes"
    if choice == "floor-rank" and rank <= FLOOR_RANK:
        raise ValueError(
            f"--ablation floor-rank needs --rank > {FLOOR_RANK}: the foil "
            f"IS rank {FLOOR_RANK}, so the gate could never discriminate"
        )
    return choice


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=800)
    ap.add_argument("--out", type=str, default="artifacts")
    ap.add_argument("--ratio-bound", type=float, default=1.15,
                    help="bound sized to DISCRIMINATE at this recipe "
                         "(sweep 2026-07-30, lr 0.05, 800 steps: production "
                         "rank-6 ratio 1.07, no-probes ablation 1.20 — a "
                         "1.25 bound would pass both)")
    ap.add_argument("--rank", type=int, default=6,
                    help="codec rank. NOT the CV default 3: on this "
                         "width-64 LM, rank 3 measurably FLOORS the loss "
                         "(1.39x dense CE at 800 steps, sweep 2026-07-30) "
                         "— atom-sampling variance scales with the "
                         "spectrum kept vs matrix width, so small models "
                         "need proportionally higher rank; rank 6 restores "
                         "parity at ~5x byte reduction")
    ap.add_argument("--width", type=int, default=64,
                    help="transformer width. Non-default widths validate "
                         "the width-scaled rank policy (cli lm --svd-rank "
                         "0: rank = ceil(width*6/64)) at a second measured "
                         "point; outputs are then suffixed _w{width}")
    ap.add_argument("--token-noise", type=float, default=0.1,
                    help="fraction of stream tokens randomized: keeps the "
                         "loss floor off zero so the gate can discriminate "
                         "(VERDICT r3 weak #5)")
    ap.add_argument("--ablation", choices=["auto", "noprobes", "floor-rank"],
                    default="auto",
                    help="which deliberately-broken codec must FAIL the "
                         "gate. 'noprobes' (pure sketch) biases hard at "
                         "low rank but converges toward the production "
                         "codec as rank grows (measured: w128 rank 12 "
                         "no-probes ratio 1.141 — under a 1.15 bound), so "
                         "'auto' selects 'floor-rank' — the rank-3 "
                         "configuration the width policy exists to prevent "
                         "(measured 1.39x floor at w64) — once rank "
                         "exceeds the default, and 'noprobes' otherwise")
    args = ap.parse_args()
    default_rank = ap.get_default("rank")
    try:
        args.ablation = resolve_ablation(args.ablation, args.rank, default_rank)
    except ValueError as e:
        ap.error(str(e))

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax
    import jax.numpy as jnp
    import numpy as np

    from atomo_tpu.codecs import SvdCodec
    from atomo_tpu.models.transformer import TransformerLM
    from atomo_tpu.parallel.lm import make_lm_train_step, shard_tokens
    from atomo_tpu.parallel.mesh import make_mesh
    from atomo_tpu.parallel.replicated import replicate_state
    from atomo_tpu.training import create_state, make_optimizer

    n_dev = min(4, len(jax.devices()))
    if n_dev < 4:
        raise SystemExit(
            f"only {n_dev} device(s) resolved; the gate's bound/rank are "
            "calibrated at the 4-way batch-32 recipe — running at a smaller "
            "batch would score against the wrong calibration (set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=4 on CPU)"
        )
    cfg = dict(
        vocab_size=64, max_len=64, width=args.width, depth=2, num_heads=4
    )
    batch, seq = 8 * n_dev, 64
    mesh = make_mesh(n_dev, axes=(("dp", n_dev), ("sp", 1)))
    # lr 0.05: at lr 0.1+momentum this width-64 LM sits on the stability
    # edge and the codec's sampling noise tips it into late-training loss
    # creep (measured: rank-6 svd descends to 1.19 by step 400 then climbs
    # back to 1.49 by 800) — the gate would then measure noise-amplified
    # instability, not estimator parity. Dense converges fine either way.
    opt = make_optimizer("sgd", lr=0.05, momentum=0.9)

    rng = np.random.default_rng(0)

    def batch_tokens():
        starts = rng.integers(0, cfg["vocab_size"], size=(batch, 1))
        strides = rng.integers(1, 5, size=(batch, 1))
        toks = (starts + strides * np.arange(seq)) % cfg["vocab_size"]
        if args.token_noise > 0:
            # symmetric token noise: an irreducible CE floor, so parity is
            # judged mid-descent rather than at a saturated zero floor
            flip = rng.random(toks.shape) < args.token_noise
            toks = np.where(
                flip, rng.integers(0, cfg["vocab_size"], size=toks.shape), toks
            )
        return toks.astype(np.int32)

    batches = [batch_tokens() for _ in range(args.steps)]

    # deliberately-broken ablation: must FAIL the gate the production codec
    # passes, or the gate proves nothing (VERDICT r3 next-round #6)
    if args.ablation == "noprobes":
        ablation_codec = SvdCodec(rank=args.rank, residual_probes=0)
        ablation_label = f"rank-{args.rank} NO probes (pure sketch)"
    else:  # floor-rank: the configuration the width-scaled policy prevents
        ablation_codec = SvdCodec(rank=FLOOR_RANK)
        ablation_label = f"rank-{FLOOR_RANK} (measured flooring rank)"

    curves, bytes_info = {}, {}
    for tag, codec in (
        ("dense", None),
        ("svd", SvdCodec(rank=args.rank)),
        ("svd_ablation", ablation_codec),
    ):
        lm = TransformerLM(**cfg)
        state = create_state(
            lm, opt, jax.random.PRNGKey(0), jnp.zeros((1, seq), jnp.int32)
        )
        state = replicate_state(mesh, state)
        step = make_lm_train_step(cfg, opt, mesh, codec)
        losses = []
        t0 = time.time()
        for i, toks in enumerate(batches):
            state, m = step(
                state, jax.random.PRNGKey(1000 + i), shard_tokens(mesh, toks)
            )
            losses.append(float(m["loss"]))
        curves[tag] = losses
        bytes_info[tag] = dict(
            msg_bytes=float(m["msg_bytes"]), dense_bytes=float(m["dense_bytes"])
        )
        print(
            f"{tag}: final {losses[-1]:.4f} "
            f"({time.time() - t0:.1f}s, {len(losses)} steps)",
            flush=True,
        )

    w = max(args.steps // 10, 1)
    final_dense = float(np.mean(curves["dense"][-w:]))
    final_svd = float(np.mean(curves["svd"][-w:]))
    final_broken = float(np.mean(curves["svd_ablation"][-w:]))
    ratio = final_svd / max(final_dense, 1e-9)
    ratio_broken = final_broken / max(final_dense, 1e-9)
    reduction = bytes_info["svd"]["dense_bytes"] / max(
        bytes_info["svd"]["msg_bytes"], 1.0
    )
    # parity alone is not enough: both runs must have actually converged
    # (sibling artifact's guard — a broken step would give ratio ~1.0)
    converged = (
        final_dense < curves["dense"][0] * 0.5
        and final_svd < curves["svd"][0] * 0.5
    )
    discriminates = bool(
        ratio < args.ratio_bound and ratio_broken >= args.ratio_bound
    )
    # the verdict requires all three: parity, real convergence, AND a gate
    # that provably fails the biased ablation (ADVICE r4: a non-discriminating
    # gate must not report PASS)
    ok = ratio < args.ratio_bound and converged and discriminates

    os.makedirs(args.out, exist_ok=True)
    payload = dict(
        model="TransformerLM", config=cfg, batch=batch, seq_len=seq,
        n_devices=n_dev, steps=args.steps, optimizer="sgd lr=0.05 m=0.9",
        platform=jax.devices()[0].platform,
        device=jax.devices()[0].device_kind,
        final_window=w, final_loss_dense=final_dense,
        rank=args.rank, final_loss_svd=final_svd, ratio=ratio,
        ablation=args.ablation, ablation_label=ablation_label,
        final_loss_svd_ablation=final_broken, ratio_ablation=ratio_broken,
        gate_discriminates=discriminates, token_noise=args.token_noise,
        ratio_bound=args.ratio_bound, byte_reduction=reduction,
        bytes=bytes_info, converged=converged, passes=ok, curves=curves,
    )
    sfx = "" if args.width == 64 else f"_w{args.width}"
    if args.rank != default_rank:
        sfx += f"_r{args.rank}"
    if args.ablation != "noprobes":
        # distinct foils are distinct experiments; never overwrite one
        # ablation's artifact with another's
        sfx += "_floorabl"
    with open(os.path.join(args.out, f"LM_CONVERGENCE{sfx}.json"), "w") as f:
        json.dump(payload, f)
    with open(os.path.join(args.out, f"LM_CONVERGENCE{sfx}.md"), "w") as f:
        f.write(
            f"# LM convergence parity: SVD rank-{args.rank} vs dense\n\n"
            f"TransformerLM ({cfg['depth']}x{cfg['width']}, vocab "
            f"{cfg['vocab_size']}), batch {batch}, seq {seq}, {n_dev}-way dp "
            f"mesh on {payload['device']}; {args.steps} steps, synthetic "
            "arithmetic-progression streams (deterministic).\n\n"
            f"| run | final loss (last {w} mean) |\n|---|---|\n"
            f"| dense pmean | {final_dense:.4f} |\n"
            f"| svd rank-{args.rank} gather | {final_svd:.4f} |\n"
            f"| svd {ablation_label} (biased ablation) | {final_broken:.4f} |\n\n"
            f"ratio {ratio:.3f} (bound {args.ratio_bound}; ablation ratio "
            f"{ratio_broken:.3f} must be >= bound — gate discriminates: "
            f"{discriminates}), both runs "
            f"converged: {converged} — {'PASS' if ok else 'FAIL'}; byte "
            f"reduction {reduction:.1f}x per step per chip "
            f"(svd {bytes_info['svd']['msg_bytes']:.0f} B vs dense "
            f"{bytes_info['svd']['dense_bytes']:.0f} B).\n"
        )
    print(
        f"ratio={ratio:.3f} ablation_ratio={ratio_broken:.3f} "
        f"bound={args.ratio_bound} discriminates={discriminates} "
        f"byte_reduction={reduction:.1f}x -> {'PASS' if ok else 'FAIL'}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
