"""Live re-sharding — elastic reshapes as data movement, not process death.

The elastic coordinator's historical reshape is exit-and-re-exec: write
``membership.json``, exit rc=29, let the supervisor relaunch at N-1 and
resume from the newest checkpoint. That stays the FALLBACK (it is the
only correct move when the dead replica took its host process with it).
But with explicit sharding the common case — a healthy process whose
mesh merely changes shape — is a data-movement problem: gather the live
sharded state once, re-slice it for the new mesh, place it. No exec, no
checkpoint round-trip, no re-reading the data directory.

Determinism contract (the elastic acceptance bar, inherited): the
re-sharded state is built from the SAME host bytes a checkpoint
save/restore cycle would move, through the same
:func:`~atomo_tpu.mesh.update.sharded_update_state` placement a fresh
N'-device run performs — so the resharded trajectory is the fresh-run
trajectory by construction (tested: reshard == gather + fresh build,
leaf-wise bit-exact).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from atomo_tpu.mesh.spec import MeshSpec
from atomo_tpu.mesh.update import (
    ShardedUpdateSpecs,
    ShardedUpdateState,
    sharded_update_state,
)


def reshard_sharded_update(
    state: ShardedUpdateState,
    specs: ShardedUpdateSpecs,
    new_mesh,
    optimizer,
    *,
    axis="dp",
) -> tuple[ShardedUpdateState, ShardedUpdateSpecs]:
    """Re-shard a LIVE sharded-update state onto ``new_mesh``.

    Master weights are gathered to the true (unpadded) flat vector and
    re-padded/re-sliced for the new shard count. The optimizer state is
    rebuilt the careful way: vector buffers whose flat layout matches the
    master's (the momentum/mu/nu family) are re-sliced exactly — the
    resharded run continues the SAME optimizer trajectory, not a
    fresh-momentum one; scalar leaves (counts) carry over replicated.
    """
    from atomo_tpu.training.trainer import TrainState

    params = specs.materialize_host(state.master)
    stats = jax.device_get(state.batch_stats)
    step = jax.device_get(state.step)
    host_tpl = TrainState(
        step=jnp.asarray(step, jnp.int32), params=params,
        batch_stats=stats, opt_state=None,
    )
    new_state, new_specs = sharded_update_state(
        new_mesh, host_tpl, optimizer, axis=axis
    )
    pad = new_specs.chunk * new_specs.n_shards - new_specs.d_flat

    def carry_opt(old_leaf, new_leaf, sp):
        old_leaf = jnp.asarray(jax.device_get(old_leaf))
        if old_leaf.ndim == 0:
            return jax.device_put(
                old_leaf, new_leaf.sharding
            )
        # flat vector buffer: strip the OLD padding, re-pad for the new
        # shard count, place with the new layout
        flat = old_leaf[: specs.d_flat]
        return jax.device_put(jnp.pad(flat, (0, pad)), new_leaf.sharding)

    new_opt = jax.tree_util.tree_map(
        carry_opt, state.opt_state, new_state.opt_state,
        new_specs.opt_specs,
    )
    return (
        ShardedUpdateState(
            step=new_state.step, master=new_state.master,
            batch_stats=new_state.batch_stats, opt_state=new_opt,
        ),
        new_specs,
    )


def reshard_plan(
    old_spec: MeshSpec, n_devices: int, dcn_ways: int = 0
) -> Optional[MeshSpec]:
    """The coordinator's reshape decision record: the new
    :class:`MeshSpec` for a world of ``n_devices``, or None when the
    shape is unchanged (no reshape needed). Pure — the incident log
    captures both shapes either way."""
    new = MeshSpec.from_world(n_devices, dcn_ways)
    return None if new == old_spec else new
