"""Native C++ lossless codec tests (the blosc-capability replacement,
reference src/utils.py:3-16)."""

import os

import numpy as np
import pytest

from atomo_tpu.native import lossless

pytestmark = pytest.mark.skipif(
    not lossless.available(), reason="g++ toolchain unavailable"
)


@pytest.mark.parametrize("typesize", [1, 2, 4, 8])
@pytest.mark.parametrize(
    "data",
    [
        b"",
        b"x",
        b"abc" * 1000,
        np.arange(10000, dtype=np.float32).tobytes(),
        np.random.RandomState(0).randn(5000).astype(np.float64).tobytes(),
        os.urandom(4096),
    ],
    ids=["empty", "one", "repeat", "arange", "randn", "urandom"],
)
def test_roundtrip(data, typesize):
    blob = lossless.compress(data, typesize=typesize)
    assert lossless.decompress(blob) == data


def test_structured_floats_compress_well():
    data = np.arange(100000, dtype=np.float64).tobytes()
    blob = lossless.compress(data, typesize=8)
    assert len(blob) < len(data) / 10  # shuffle makes this highly regular


def test_incompressible_stored_near_raw():
    data = os.urandom(100000)
    blob = lossless.compress(data, typesize=1)
    assert len(blob) <= len(data) + 64  # stored fallback, tiny header only


def test_bad_magic_rejected():
    with pytest.raises(ValueError):
        lossless.decompress(b"NOPE" + b"\x00" * 32)


def test_truncated_rejected():
    data = np.arange(1000, dtype=np.float32).tobytes()
    blob = lossless.compress(data, typesize=4)
    with pytest.raises(ValueError):
        lossless.decompress(blob[: len(blob) // 2])
