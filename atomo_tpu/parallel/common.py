"""Shared helpers for the model-sharded train steps (tp, moe).

Kept free of model/codec imports so any parallel module can use them
without import cycles.
"""

from __future__ import annotations

import math
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from atomo_tpu.mesh.collectives import psum as _axis_psum
from atomo_tpu.training.trainer import TrainState


class PackSpec(NamedTuple):
    """Static layout of a bucket-packed pytree (see :func:`pack_tree_buckets`).

    ``leaves[i] = (group, offset, size, shape, dtype)`` locates flattened
    leaf ``i`` inside buffer ``group``; all fields are Python ints/tuples
    known at trace time, so unpacking is static slicing — a pure relayout
    with zero arithmetic, hence bit-exact by construction.
    """

    treedef: Any
    leaves: tuple  # ((group, offset, size, shape, dtype_name), ...)
    group_dtypes: tuple  # dtype name per buffer, sorted


def pack_tree_buckets(tree: Any, bucket_size: int = 0):
    """Pack a pytree of arrays into one flat (n_buckets, bucket_size) buffer
    per dtype — the rotation unit of ring-streamed aggregation.

    A deep model's encoded payload has dozens of small leaves; rotating
    them leaf-by-leaf would issue one ``ppermute`` per leaf per hop. Packing
    concatenates every same-dtype leaf into a single buffer (padded with
    zeros to a whole number of ``bucket_size``-element buckets, <= one
    bucket of overhead per dtype), so each ring hop is one collective per
    dtype (typically f32 + uint32 = two) regardless of model depth —
    "small layers amortize into one rotation slot". ``bucket_size <= 0``
    packs each dtype into a single unpadded bucket.

    Packing is concat/reshape/zero-pad only; :func:`unpack_tree_buckets`
    inverts it exactly (bit-level round trip for ANY bucket size — tested
    as a property in tests/test_ring_aggregate.py). Kept codec-free in
    this module per the ring design: the rotation layer never interprets
    payload semantics.

    Returns ``(buffers, spec)`` where ``buffers`` is a tuple (sorted by
    dtype name, stable across chips) and ``spec`` a :class:`PackSpec`.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups: dict[str, list[int]] = {}
    for i, leaf in enumerate(leaves):
        groups.setdefault(jnp.dtype(leaf.dtype).name, []).append(i)
    keys = sorted(groups)
    bufs = []
    where: dict[int, tuple[int, int]] = {}
    for gi, dname in enumerate(keys):
        idxs = groups[dname]
        off = 0
        flats = []
        for i in idxs:
            where[i] = (gi, off)
            off += int(leaves[i].size)
            flats.append(leaves[i].reshape(-1))
        cat = flats[0] if len(flats) == 1 else jnp.concatenate(flats)
        if bucket_size > 0:
            n_buckets = max(1, -(-off // bucket_size))
            padded = n_buckets * bucket_size
            if padded > off:
                cat = jnp.concatenate(
                    [cat, jnp.zeros((padded - off,), cat.dtype)]
                )
            bufs.append(cat.reshape(n_buckets, bucket_size))
        else:
            bufs.append(cat.reshape(1, -1))
    spec = PackSpec(
        treedef=treedef,
        leaves=tuple(
            (
                where[i][0],
                where[i][1],
                int(leaves[i].size),
                tuple(leaves[i].shape),
                jnp.dtype(leaves[i].dtype).name,
            )
            for i in range(len(leaves))
        ),
        group_dtypes=tuple(keys),
    )
    return tuple(bufs), spec


def unpack_tree_buckets(bufs, spec: PackSpec):
    """Exact inverse of :func:`pack_tree_buckets` (static slicing only)."""
    flat = [b.reshape(-1) for b in bufs]
    leaves = [
        flat[g][off : off + size].reshape(shape)
        for g, off, size, shape, _ in spec.leaves
    ]
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


class LayerBucketPlan(NamedTuple):
    """Ordered layer-axis partition of a gradient pytree — the unit of
    ``--stream-encode``'s backward-interleaved pipeline (see
    :func:`plan_layer_buckets`).

    ``buckets[b]`` is a tuple of GLOBAL leaf indices (into the tree's
    canonical flatten order); bucket 0 holds the LAST-flattened leaves —
    the last-computed layers, whose gradients backprop finishes first —
    so bucket order is the order payloads become ready. Every leaf
    appears in exactly one bucket. A pure trace-time object (Python ints
    only), so the plan is a LAYOUT knob: which leaves share one encode
    dispatch, never what any leaf's encode computes.
    """

    n_leaves: int
    buckets: tuple  # ((leaf_idx, ...), ...) reverse-topological

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)


def plan_layer_buckets(tree: Any, bucket_bytes: int = 0) -> LayerBucketPlan:
    """Partition a gradient pytree into size-bounded LAYER buckets,
    reverse-topological (DDP-style), for backward-interleaved encode.

    The existing :func:`pack_tree_buckets` buckets along the RING axis
    (dtype-grouped rotation buffers); this plans along the LAYER axis:
    leaves are walked in REVERSE canonical flatten order — flax flattens
    params in module definition order, so the last-flattened leaves
    belong to the last layers, whose gradients are the FIRST outputs
    backprop completes — and greedily packed into buckets of at most
    ``bucket_bytes`` dense bytes (every bucket holds >= 1 leaf, so an
    oversized leaf becomes its own bucket). ``bucket_bytes <= 0`` yields
    one bucket holding the whole tree (reverse order).

    Deterministic: a pure function of the tree's leaf shapes/dtypes (the
    same plan on every chip, every trace). The plan carries GLOBAL leaf
    indices so per-leaf codec keys fold from the leaf's canonical index
    regardless of the partition — which is what makes any
    ``bucket_bytes`` choice produce bit-identical payloads (the
    estimator never sees the layout knob; tested in
    tests/test_stream_encode.py).
    """
    leaves = jax.tree_util.tree_leaves(tree)
    buckets: list[tuple[int, ...]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i in reversed(range(len(leaves))):
        nbytes = int(leaves[i].size) * jnp.dtype(leaves[i].dtype).itemsize
        if bucket_bytes > 0 and cur and cur_bytes + nbytes > bucket_bytes:
            buckets.append(tuple(cur))
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(tuple(cur))
    return LayerBucketPlan(n_leaves=len(leaves), buckets=tuple(buckets))


def dense_init(key, shape, in_axis: int = 0):
    """Plain normal scaled by 1/sqrt(fan_in) of the contracted axis
    (lecun-style variance, untruncated — NOT bit-identical to flax's
    truncated lecun_normal)."""
    fan_in = shape[in_axis]
    return jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)


def layernorm(x, scale, eps: float = 1e-6):
    """flax.linen.LayerNorm(use_bias=False) semantics: mean2 - mean^2 var."""
    mean = jnp.mean(x, axis=-1, keepdims=True)
    mean2 = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
    return (x - mean) * jax.lax.rsqrt(var + eps) * scale


def shard_tokens_with_spec(mesh: Mesh, tokens, spec: P):
    """device_put an int token batch with the given PartitionSpec — the one
    shared sharding helper behind every *_tokens entry point (tp/moe/pp)."""
    return jax.device_put(jnp.asarray(tokens), NamedSharding(mesh, spec))


def attention_sublayer(bp, x, num_heads: int):
    """Pre-LN causal attention sublayer on stock-layout block params
    (keys ln1/qkv/proj, qkv kernel (W, 3·H·D)): returns x + proj(attn).
    Shared by the moe and pp forwards; tp has its own head-sliced variant."""
    from atomo_tpu.parallel.ring import full_attention

    b, s, w = x.shape
    h = num_heads
    d = w // h
    y = layernorm(x, bp["ln1"]["scale"])
    qkv = (y @ bp["qkv"]["kernel"]).reshape(b, s, 3, h, d)
    q, k, v = (qkv[:, :, j].transpose(0, 2, 1, 3) for j in range(3))
    att = full_attention(q, k, v, causal=True)
    att = att.transpose(0, 2, 1, 3).reshape(b, s, h * d)
    return x + att @ bp["proj"]["kernel"]


def opt_state_specs_like(opt_state: Any, params: Any, param_specs: Any) -> Any:
    """Specs for an optax state: subtrees structurally identical to the param
    tree (momentum / mu / nu mirrors) inherit the param specs; every other
    leaf (step counts, scalars) is replicated."""
    pdef = jax.tree_util.tree_structure(params)

    def params_like(sub) -> bool:
        try:
            return jax.tree_util.tree_structure(sub) == pdef
        except Exception:
            return False

    return jax.tree_util.tree_map(
        lambda sub: param_specs if params_like(sub) else P(),
        opt_state,
        is_leaf=lambda sub: params_like(sub)
        or not isinstance(sub, (tuple, list, dict)),
    )


def complete_model_axis_grads(grads, param_specs, axis: str, divide_by: int = 1):
    """Per-shard gradient completion for a model-sharding axis (tp/ep/pp):
    leaves whose spec mentions ``axis`` are already exact for their slice;
    replicated leaves hold shard-partials that one psum over the axis
    completes. ``divide_by`` removes a uniform n-scaling when the loss path
    crosses a psum (the tp case — see parallel.tp's derivation)."""

    def one(g, sp):
        sharded = any(a == axis for a in sp if a is not None)
        full = g if sharded else _axis_psum(g, axis)
        return full / divide_by if divide_by != 1 else full

    return jax.tree_util.tree_map(one, grads, param_specs)


def make_state_specs(state: TrainState, param_specs: Any) -> TrainState:
    """A TrainState of PartitionSpecs matching ``state`` leaf-for-leaf."""
    return TrainState(
        step=P(),
        params=param_specs,
        batch_stats=jax.tree_util.tree_map(lambda _: P(), state.batch_stats),
        opt_state=opt_state_specs_like(state.opt_state, state.params, param_specs),
    )


def shard_state(mesh: Mesh, state: TrainState, state_specs: TrainState) -> TrainState:
    """device_put every leaf of ``state`` with its NamedSharding."""
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), state_specs
    )
    return jax.device_put(state, shardings)
