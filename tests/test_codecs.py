"""Codec unit tests: unbiasedness, roundtrip, static shapes, jit-compilability.

Test strategy per SURVEY.md §4: the reference has no tests; its closest codec
check is an eyeball CPU-vs-CUDA smoke main (qsgd.py:219-230). Here the
contract E_key[decode(encode(key, g))] == g is asserted statistically over a
batch of PRNG keys via vmap.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from atomo_tpu.codecs import (
    DenseCodec,
    QsgdCodec,
    SvdCodec,
    decode_tree,
    encode_tree,
    get_codec,
    payload_nbytes,
    terngrad,
)
from atomo_tpu.codecs.qsgd import pack_u32, unpack_u32
from atomo_tpu.codecs.svd import bernoulli_probs, resize_to_2d, undo_resize


def mean_decoded(codec, grad, n_keys=3000, seed=0):
    """E_key[decode(encode(key, grad))] estimated over n_keys keys."""
    keys = jax.random.split(jax.random.PRNGKey(seed), n_keys)

    @jax.jit
    @jax.vmap
    def roundtrip(key):
        p = codec.encode(key, grad)
        return codec.decode(p, tuple(grad.shape), grad.dtype)

    return jnp.mean(roundtrip(keys), axis=0)


# ---------------------------------------------------------------- resize


@pytest.mark.parametrize(
    "shape",
    [(), (7,), (8,), (16, 5), (3, 4, 5), (8, 16, 3, 3), (5, 3, 3, 3)],
)
def test_resize_roundtrip(shape, rng):
    x = jax.random.normal(rng, shape)
    mat, orig, pad = resize_to_2d(x)
    assert mat.ndim == 2
    y = undo_resize(mat, orig, pad)
    np.testing.assert_allclose(np.asarray(x), np.asarray(y))


def test_resize_matches_reference_policy():
    # 1-D even n -> (n/2, 2)   (ref svd.py:14-16)
    assert resize_to_2d(jnp.zeros(8))[0].shape == (4, 2)
    # 4-D (a,b,c,d), a*b even -> (a*b/2, 2*c*d)  (ref svd.py:21-27)
    assert resize_to_2d(jnp.zeros((8, 16, 3, 3)))[0].shape == (64, 18)
    # 2-D unchanged
    assert resize_to_2d(jnp.zeros((10, 3)))[0].shape == (10, 3)


# ---------------------------------------------------------------- svd


@pytest.mark.parametrize("sample", ["fixed_k", "bernoulli"])
def test_svd_unbiased(sample):
    grad = jax.random.normal(jax.random.PRNGKey(42), (12, 10)) * 0.1
    codec = SvdCodec(rank=3, sample=sample)
    est = mean_decoded(codec, grad, n_keys=4000)
    err = jnp.linalg.norm(est - grad) / jnp.linalg.norm(grad)
    assert err < 0.15, f"relative bias {err:.3f}"


def test_bernoulli_budget_unbiased():
    """E[decode] == grad for the budgeted Bernoulli sampler — on a tensor
    large enough that the real (non-dense-fallback) path runs."""
    grad = jax.random.normal(jax.random.PRNGKey(42), (32, 24)) * 0.1
    codec = SvdCodec(rank=3, sample="bernoulli_budget")
    p = codec.encode(jax.random.PRNGKey(0), grad)
    assert p.coeff.shape == (7,), "expected the budgeted (non-dense) payload"
    est = mean_decoded(codec, grad, n_keys=4000)
    err = jnp.linalg.norm(est - grad) / jnp.linalg.norm(grad)
    assert err < 0.15, f"relative bias {err:.3f}"


def test_bernoulli_budget_static_payload_and_bytes_win(rng):
    """The reference's Bernoulli keep semantics with a REAL bytes win: the
    payload is k_max = rank + slack static slots, far below full width
    (closing VERDICT r1 missing #3 — the r1 'bernoulli' mode shipped
    full-width factors)."""
    codec = SvdCodec(rank=3, sample="bernoulli_budget", budget_slack=4)
    grad = jax.random.normal(rng, (16, 8, 3, 3))  # square policy: (32, 36)
    p = codec.encode(rng, grad)
    assert p.u.shape == (32, 7) and p.coeff.shape == (7,) and p.vt.shape == (7, 36)
    assert payload_nbytes(p) * 2 < grad.size * 4  # > 2x reduction
    out = codec.decode(p, (16, 8, 3, 3))
    assert out.shape == (16, 8, 3, 3)


def test_bernoulli_budget_inclusion_law(rng):
    """Per-atom inclusion frequency matches p_i = min(1, rank*s_i/sum(s))
    (reference _sample_svd, src/codings/svd.py:49-67): atoms with p_i == 1
    appear in every draw; empirical rates track p_i."""
    grad = jax.random.normal(jax.random.PRNGKey(3), (24, 20))
    codec = SvdCodec(rank=2, sample="bernoulli_budget", budget_slack=6,
                     reshape="reference")
    mat = grad
    _, s, _ = jnp.linalg.svd(mat, full_matrices=False)
    p_ref = np.asarray(bernoulli_probs(s, 2))
    keys = jax.random.split(jax.random.PRNGKey(0), 2000)

    @jax.jit
    @jax.vmap
    def kept_coeffs(key):
        return codec.encode(key, grad).coeff

    c = np.asarray(kept_coeffs(keys))  # (n_keys, k_max)
    # slot j carries s_i/p_i for some kept atom i; count inclusion of the
    # top atom (largest coefficient class) via nonzero slot count ~ sum(p)
    avg_kept = (c > 0).sum(axis=1).mean()
    np.testing.assert_allclose(avg_kept, p_ref.sum(), rtol=0.1)


def test_bernoulli_budget_zero_grad(rng):
    codec = SvdCodec(rank=3, sample="bernoulli_budget")
    out = codec.decode(codec.encode(rng, jnp.zeros((10, 6))), (10, 6))
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)


# ---------------------------------------------------------------- decode_mean


@pytest.mark.parametrize("sample", ["fixed_k", "bernoulli_budget", "bernoulli"])
def test_svd_decode_mean_matches_vmap_mean(sample, rng):
    """The fused one-matmul decode_mean must agree with vmap-decode + mean
    (VERDICT r1 next-round #3)."""
    codec = SvdCodec(rank=3, sample=sample)
    grad_shape = (16, 8, 3, 3)
    n_rep = 4
    keys = jax.random.split(rng, n_rep)
    grads = jax.vmap(
        lambda k: jax.random.normal(k, grad_shape)
    )(keys)
    gathered = jax.vmap(lambda k, g: codec.encode(k, g))(keys, grads)
    fused = codec.decode_mean(gathered, grad_shape, jnp.float32, n_rep)
    ref = jnp.mean(
        jax.vmap(lambda p: codec.decode(p, grad_shape, jnp.float32))(gathered),
        axis=0,
    )
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref), atol=1e-6)


def test_svd_decode_mean_dense_fallback_leaf(rng):
    """Tiny leaves gather DensePayloads; decode_mean must average them."""
    codec = SvdCodec(rank=3)
    n_rep = 3
    keys = jax.random.split(rng, n_rep)
    grads = jax.vmap(lambda k: jax.random.normal(k, (32,)))(keys)
    gathered = jax.vmap(lambda k, g: codec.encode(k, g))(keys, grads)
    fused = codec.decode_mean(gathered, (32,), jnp.float32, n_rep)
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(jnp.mean(grads, axis=0)), atol=1e-6
    )


def test_decode_mean_tree_uses_fused_path(rng):
    """decode_mean_tree over a mixed pytree equals per-replica decode+mean."""
    from atomo_tpu.codecs import decode_mean_tree

    codec = SvdCodec(rank=2)
    params = {
        "conv": jax.random.normal(rng, (8, 4, 3, 3)),
        "b": jnp.ones((10,)),
    }
    n_rep = 3
    keys = jax.random.split(rng, n_rep)

    def enc(key):
        p, _ = encode_tree(codec, key, params)
        return p

    gathered = jax.vmap(enc)(keys)
    fused = decode_mean_tree(codec, gathered, params, n_rep)
    ref = jax.tree.map(
        lambda g: jnp.mean(g, axis=0),
        jax.vmap(lambda p: decode_tree(codec, p, params))(gathered),
    )
    for a, b in zip(jax.tree_util.tree_leaves(fused), jax.tree_util.tree_leaves(ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_svd_fixed_k_payload_static_shape(rng):
    codec = SvdCodec(rank=3, reshape="reference")
    grad = jax.random.normal(rng, (16, 8, 3, 3))
    p = codec.encode(rng, grad)
    # resize: (16*8/2, 2*9) = (64, 18); k = 3
    assert p.u.shape == (64, 3)
    assert p.coeff.shape == (3,)
    assert p.vt.shape == (3, 18)
    # bytes win vs dense
    assert payload_nbytes(p) < grad.size * 4


def test_svd_square_policy_payload(rng):
    """Default matricization is near-square pow2: (16,8,3,3) = 1152 elements
    -> (32, 36); payload 3*(32+36+1) floats ≈ 18% of dense."""
    codec = SvdCodec(rank=3)
    grad = jax.random.normal(rng, (16, 8, 3, 3))
    p = codec.encode(rng, grad)
    assert p.u.shape == (32, 3) and p.vt.shape == (3, 36)
    out = codec.decode(p, (16, 8, 3, 3))
    assert out.shape == (16, 8, 3, 3)
    assert payload_nbytes(p) * 5 < grad.size * 4


def test_svd_square_policy_unbiased():
    grad = jax.random.normal(jax.random.PRNGKey(9), (6, 6, 4, 4)) * 0.1
    codec = SvdCodec(rank=3)
    est = mean_decoded(codec, grad, n_keys=4000)
    err = jnp.linalg.norm(est - grad) / jnp.linalg.norm(grad)
    assert err < 0.15, f"relative bias {err:.3f}"


def test_svd_dense_fallback_for_tiny_tensors(rng):
    """BN-scale-sized tensors ship exact DensePayloads (SVD cannot win)."""
    from atomo_tpu.codecs import DensePayload

    codec = SvdCodec(rank=3)
    g = jax.random.normal(rng, (32,))
    p = codec.encode(rng, g)
    assert isinstance(p, DensePayload)
    np.testing.assert_allclose(
        np.asarray(codec.decode(p, (32,))), np.asarray(g), atol=1e-6
    )


def test_svd_zero_grad(rng):
    codec = SvdCodec(rank=3)
    grad = jnp.zeros((10, 6))
    out = codec.decode(codec.encode(rng, grad), (10, 6))
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)


def test_svd_full_rank_exact(rng):
    # budget >= full rank with topk sampling reconstructs exactly
    grad = jax.random.normal(rng, (6, 4))
    codec = SvdCodec(rank=4, sample="topk")
    out = codec.decode(codec.encode(rng, grad), (6, 4))
    np.testing.assert_allclose(np.asarray(out), np.asarray(grad), atol=1e-4)


def test_bernoulli_probs_reference_semantics():
    s = jnp.array([4.0, 2.0, 1.0, 1.0])
    # rank=0: s / s[0]   (ref svd.py:54-56)
    np.testing.assert_allclose(
        np.asarray(bernoulli_probs(s, 0)), [1.0, 0.5, 0.25, 0.25]
    )
    # rank=2: clip(2*s/sum, 0, 1)
    np.testing.assert_allclose(
        np.asarray(bernoulli_probs(s, 2)), [1.0, 0.5, 0.25, 0.25]
    )


# ---------------------------------------------------------------- qsgd


def test_pack_unpack_roundtrip(rng):
    for bits in (1, 2, 4, 7):
        n = 1000
        maxcode = (1 << (bits + 1)) - 1
        codes = jax.random.randint(rng, (n,), 0, maxcode + 1, dtype=jnp.int32)
        codes = codes.astype(jnp.uint32)
        words = pack_u32(codes, bits)
        back = unpack_u32(words, bits, n)
        np.testing.assert_array_equal(np.asarray(codes), np.asarray(back))
        vpw = 32 // (bits + 1)
        assert words.shape == (-(-n // vpw),)


@pytest.mark.parametrize("bits,bucket", [(2, 64), (4, 128), (1, 32)])
def test_qsgd_unbiased(bits, bucket):
    grad = jax.random.normal(jax.random.PRNGKey(7), (300,)) * 0.3
    codec = QsgdCodec(bits=bits, bucket_size=bucket)
    est = mean_decoded(codec, grad, n_keys=4000)
    err = jnp.linalg.norm(est - grad) / jnp.linalg.norm(grad)
    assert err < 0.1, f"relative bias {err:.3f}"


def test_qsgd_bytes_reduction(rng):
    grad = jax.random.normal(rng, (4096,))
    codec = QsgdCodec(bits=2, bucket_size=512)
    p = codec.encode(rng, grad)
    dense = grad.size * 4
    assert payload_nbytes(p) < dense / 8  # 3 bits/value + scales << 32 bits


def test_qsgd_decode_values_on_grid(rng):
    codec = QsgdCodec(bits=2, bucket_size=512)
    grad = jax.random.normal(rng, (100,))
    out = codec.decode(codec.encode(rng, grad), (100,))
    # every decoded value is sign * level/levels * scale
    scale = float(jnp.linalg.norm(jnp.zeros(512).at[:100].set(grad)))
    lvls = np.asarray(jnp.abs(out)) / scale * codec.levels
    np.testing.assert_allclose(lvls, np.round(lvls), atol=1e-4)


def test_terngrad_levels(rng):
    codec = terngrad(bucket_size=64)
    grad = jax.random.normal(rng, (128,))
    out = np.asarray(codec.decode(codec.encode(rng, grad), (128,)))
    # ternary: each bucket has values in {-scale, 0, +scale}
    for b in range(2):
        vals = np.unique(np.abs(out[b * 64 : (b + 1) * 64]))
        assert len(vals) <= 2


# ---------------------------------------------------------------- tree API


def test_encode_decode_tree(rng):
    params = {
        "conv": jax.random.normal(rng, (8, 4, 3, 3)),
        "dense": {"w": jax.random.normal(rng, (32, 10)), "b": jnp.ones((10,))},
    }
    codec = SvdCodec(rank=2)
    payloads, stats = encode_tree(codec, rng, params)
    decoded = decode_tree(codec, payloads, params)
    assert jax.tree_util.tree_structure(decoded) == jax.tree_util.tree_structure(params)
    for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(decoded)):
        assert a.shape == b.shape
    assert stats.payload_bytes < stats.dense_bytes
    assert stats.reduction > 1.0


def test_dense_codec_identity(rng):
    codec = DenseCodec()
    g = jax.random.normal(rng, (17, 3))
    out = codec.decode(codec.encode(rng, g), (17, 3))
    np.testing.assert_allclose(np.asarray(out), np.asarray(g))


def test_get_codec_registry():
    assert isinstance(get_codec("sgd"), DenseCodec)
    assert get_codec("svd", svd_rank=5).rank == 5
    assert get_codec("qsgd", quantization_level=4).bits == 4
    assert get_codec("terngrad").scheme == "terngrad"
    with pytest.raises(ValueError):
        get_codec("nope")


def test_codecs_jit_compile(rng):
    """encode+decode must trace/compile under jit with no concretization."""
    g = jax.random.normal(rng, (64, 18))
    for codec in (SvdCodec(rank=3), QsgdCodec(bits=2, bucket_size=64), DenseCodec()):
        fn = jax.jit(
            lambda k, x, c=codec: c.decode(c.encode(k, x), (64, 18))
        )
        out = fn(rng, g)
        assert out.shape == (64, 18)


# ---------------------------------------------------------------- indicators


def test_indicators_basis_choice():
    """Low-rank gradients prefer spectral atoms; sparse ones entry-wise
    (the reference's research decision rule, nn_ops.py:66-82)."""
    from atomo_tpu.codecs import (
        l1_indicator,
        nuclear_indicator,
        spectral_atoms_preferred,
    )

    u = jax.random.normal(jax.random.PRNGKey(0), (64, 1))
    low_rank = (u @ u.T).reshape(64, 64)
    assert bool(spectral_atoms_preferred(low_rank))

    sparse = jnp.zeros((64, 64)).at[3, 5].set(10.0).at[10, 2].set(-7.0)
    # entry-wise sparse but full-spread spectrum relative to L1
    assert float(l1_indicator(sparse)) < float(nuclear_indicator(sparse)) * 10


def test_bucketed_encode_matches_unbucketed(rng):
    """Shape-bucketed vmapped encoding must produce bit-identical payloads
    to the per-leaf path (same per-leaf fold_in keys)."""
    params = {
        "a": jax.random.normal(rng, (16, 8, 3, 3)),
        "b": jax.random.normal(jax.random.fold_in(rng, 1), (16, 8, 3, 3)),
        "c": jax.random.normal(jax.random.fold_in(rng, 2), (40,)),
    }
    codec = SvdCodec(rank=2)
    p1, s1 = encode_tree(codec, rng, params, bucketed=True)
    p2, s2 = encode_tree(codec, rng, params, bucketed=False)
    assert s1.payload_bytes == s2.payload_bytes
    for a, b in zip(
        jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_auto_algorithm_selection():
    """"auto" (the default) resolves per matrix: Halko sketch for matrices
    whose small side reaches auto_min_dim, gram (full spectrum via eigh of
    the small-side Gram — no iterative QDWH/Jacobi program) below (VERDICT
    r2 next-round #3 + r3 #3/#5: exact cost ~120 ms/step on
    ResNet-18/v5e; the sketch runs at dense parity)."""
    codec = SvdCodec(rank=3)
    assert codec.algorithm == "auto"
    assert codec._algorithm_for(32, 40) == "gram"
    assert codec._algorithm_for(64, 512) == "randomized"
    assert codec._algorithm_for(512, 512) == "randomized"
    # both Bernoulli modes advertise the reference inclusion law over the
    # FULL spectrum — a sketch would renormalize p_i and bias the
    # estimator, so they take the gram path at EVERY size
    assert SvdCodec(rank=3, sample="bernoulli")._algorithm_for(512, 512) == "gram"
    assert (
        SvdCodec(rank=3, sample="bernoulli_budget")._algorithm_for(512, 512)
        == "gram"
    )
    # explicit settings are honored
    assert SvdCodec(rank=3, algorithm="exact")._algorithm_for(512, 512) == "exact"


def test_gram_svd_matches_exact_reconstruction():
    """The gram factorization must reconstruct u@diag(s)@vt == mat to fp
    precision on both orientations (that identity — not per-singular-value
    accuracy — is what every sampler's unbiasedness rests on), and its
    spectrum must match LAPACK-exact for the well-separated part."""
    for shape in [(24, 40), (40, 24), (17, 17)]:
        mat = jax.random.normal(jax.random.PRNGKey(5), shape) * 0.3
        u, s, vt = SvdCodec._gram_svd(mat)
        rec = np.asarray((u * s[None, :]) @ vt)
        np.testing.assert_allclose(rec, np.asarray(mat), atol=5e-5)
        s_ref = np.asarray(jnp.linalg.svd(mat, compute_uv=False))
        np.testing.assert_allclose(np.asarray(s), s_ref, atol=1e-3)
    # zero matrix: all-zero spectrum, finite factors, zero reconstruction
    u, s, vt = SvdCodec._gram_svd(jnp.zeros((12, 20)))
    assert np.isfinite(np.asarray(u)).all() and np.isfinite(np.asarray(vt)).all()
    np.testing.assert_allclose(np.asarray((u * s[None, :]) @ vt), 0.0, atol=1e-7)


def test_cholesky_qr_orthonormalizes():
    """CholeskyQR2 replaces Householder QR in the sketch (TPU encode-tax
    cut): fp-orthonormal on well/moderately-conditioned blocks, finite
    (never NaN) on extreme ones. Extreme conditioning degrading
    orthonormality is FINE for the codec — the estimator is unbiased for
    any q (see _orthonormalize docstring); the adversarial-conditioning
    unbiasedness is covered by test_randomized_bias_bounded_on_full_spectrum
    and the probe tests."""
    y = jax.random.normal(jax.random.PRNGKey(0), (96, 8))
    q = SvdCodec._orthonormalize(y)
    # the NaN-guard jitter (10*eps*trace) floors orthogonality around
    # 1e-4; that is plenty for sketch quality (and bias-irrelevant)
    np.testing.assert_allclose(
        np.asarray(q.T @ q), np.eye(8), atol=1e-4
    )
    # columns spanning ~3 orders of magnitude (gram condition ~1e6)
    y2 = y * (10.0 ** jnp.arange(-1, 3, 0.5, dtype=jnp.float32))[None, :]
    q2 = SvdCodec._orthonormalize(y2)
    np.testing.assert_allclose(np.asarray(q2.T @ q2), np.eye(8), atol=1e-3)
    # rank-deficient / wildly-scaled: must stay finite (not orthonormal)
    y3 = jnp.concatenate([y[:, :4], y[:, :4]], axis=1)
    assert np.isfinite(np.asarray(SvdCodec._orthonormalize(y3))).all()
    y4 = y * (10.0 ** jnp.arange(-3, 5, dtype=jnp.float32))[None, :]
    assert np.isfinite(np.asarray(SvdCodec._orthonormalize(y4))).all()


def test_bf16_wire_halves_bytes_and_stays_unbiased():
    """wire_dtype=bfloat16: u/vt ship as bf16 (stochastically rounded),
    coeff stays f32 — payload bytes nearly halve and E[decode] == grad
    still holds (the narrowing is zero-mean by construction)."""
    grad = jax.random.normal(jax.random.PRNGKey(42), (32, 24)) * 0.1
    f32c = SvdCodec(rank=3)
    bf16c = SvdCodec(rank=3, wire_dtype="bfloat16")
    p32 = f32c.encode(jax.random.PRNGKey(0), grad)
    p16 = bf16c.encode(jax.random.PRNGKey(0), grad)
    assert p16.u.dtype == jnp.bfloat16 and p16.vt.dtype == jnp.bfloat16
    assert p16.coeff.dtype == jnp.float32
    assert payload_nbytes(p16) < 0.6 * payload_nbytes(p32)
    est = mean_decoded(bf16c, grad, n_keys=4000)
    err = jnp.linalg.norm(est - grad) / jnp.linalg.norm(grad)
    assert err < 0.15, f"relative bias {err:.3f}"


def test_stochastic_round_unbiased_and_close():
    """E[stochastic_round(x)] == x (mean over keys converges to x, unlike
    deterministic bf16 rounding whose error is systematic), and each draw
    is within one bf16 ulp of x."""
    from atomo_tpu.codecs.svd import stochastic_round

    x = jax.random.normal(jax.random.PRNGKey(1), (256,)) * 3.7
    keys = jax.random.split(jax.random.PRNGKey(2), 3000)
    rounded = jax.vmap(lambda k: stochastic_round(k, x).astype(jnp.float32))(keys)
    mean = np.asarray(jnp.mean(rounded, axis=0))
    # one bf16 ulp is ~2^-8 relative; the MC mean must sit well inside it
    np.testing.assert_allclose(mean, np.asarray(x), rtol=2e-4, atol=1e-6)
    max_err = float(jnp.max(jnp.abs(rounded[0] - x) / jnp.maximum(jnp.abs(x), 1e-6)))
    assert max_err <= 1.0 / 128.0  # within one ulp step


def _power_law_gradient(m, n, decay=1.5, scale=0.1):
    """A dense full-spectrum matrix (the SVdecay.jpg regime) — realistic,
    NOT exactly low-rank."""
    key = jax.random.PRNGKey(17)
    u, _ = jnp.linalg.qr(jax.random.normal(key, (m, m)))
    v, _ = jnp.linalg.qr(jax.random.normal(jax.random.fold_in(key, 1), (n, n)))
    s = 1.0 / (1.0 + jnp.arange(min(m, n), dtype=jnp.float32)) ** decay
    return (u[:, : min(m, n)] * s[None, :]) @ v[:, : min(m, n)].T * scale, s * scale


@pytest.mark.slow
def test_randomized_bias_bounded_on_full_spectrum():
    """Bias evidence for the sketch on a realistic full-spectrum gradient
    (replaces the only-low-rank evidence, VERDICT r2 next-round #3).

    * probes=0 (pure sketch): bias is bounded by the spectral tail the
      sketch misses, ||E[decode] - X||_F <= ~sqrt(sum_{i>sketch} s_i^2).
    * default (residual probes on): the probe atoms restore unbiasedness
      for the WHOLE matrix — measured bias must sit at the Monte-Carlo
      noise floor, well under the probeless tail bound."""
    m, n, sketch_rank, oversample = 48, 64, 3, 8
    grad, s = _power_law_gradient(m, n)
    n_keys = 4000
    noise = float(jnp.linalg.norm(grad)) / np.sqrt(n_keys)  # MC resolution

    bare = SvdCodec(
        rank=sketch_rank, algorithm="randomized", oversample=oversample,
        reshape="reference", residual_probes=0,
    )
    bias0 = float(jnp.linalg.norm(mean_decoded(bare, grad, n_keys=n_keys) - grad))
    sketch = sketch_rank + oversample
    tail = float(jnp.linalg.norm(s[sketch:]))  # the analytic bound
    assert bias0 <= 1.5 * tail + 3 * noise, (bias0, tail, noise)

    probed = SvdCodec(
        rank=sketch_rank, algorithm="randomized", oversample=oversample,
        reshape="reference",
    )
    # probe variance ~ (n/p)||R||_F^2 raises the MC floor by ~sqrt(n/p)
    probe_noise = noise * np.sqrt(n / probed.residual_probes)
    bias2 = float(jnp.linalg.norm(mean_decoded(probed, grad, n_keys=n_keys) - grad))
    assert bias2 <= 4 * probe_noise, (bias2, probe_noise)
    rel = bias2 / float(jnp.linalg.norm(grad))
    assert rel < 0.15, f"relative bias {rel:.3f}"


def test_randomized_svd_roundtrip_and_unbiased_on_lowrank(rng):
    """The Halko-sketch path: on a matrix whose true rank fits inside the
    sketch, the sampled estimator is unbiased exactly (no truncated tail).
    With probes disabled the payload is exactly `rank` atoms; the default
    adds `residual_probes` probe atoms on top."""
    u = jax.random.normal(rng, (24, 2))
    v = jax.random.normal(jax.random.fold_in(rng, 1), (2, 36))
    grad = (u @ v).reshape(24, 36) * 0.1  # true rank 2
    # reference reshape keeps 2-D matrices as-is, preserving the low-rank
    # structure the sketch must capture (square policy would re-fold it)
    codec = SvdCodec(
        rank=2, algorithm="randomized", oversample=4, reshape="reference",
        residual_probes=0,
    )
    p = codec.encode(rng, grad)
    assert p.u.shape == (24, 2) and p.vt.shape == (2, 36)
    est = mean_decoded(codec, grad, n_keys=3000)
    err = jnp.linalg.norm(est - grad) / jnp.linalg.norm(grad)
    assert err < 0.15, f"relative bias {err:.3f}"
    # default probes ride along as extra atoms in the same wire format
    probed = SvdCodec(
        rank=2, algorithm="randomized", oversample=4, reshape="reference"
    )
    p2 = probed.encode(rng, grad)
    assert p2.u.shape == (24, 4) and p2.coeff.shape == (4,) and p2.vt.shape == (4, 36)


@pytest.mark.parametrize(
    "shape",
    [(1,), (2,), (7,), (3, 5, 7), (1, 1, 1, 1), (1, 513), (129, 1), (2, 3, 1, 1)],
)
def test_svd_codec_adversarial_shapes_roundtrip_unbiased(shape):
    """Degenerate and odd shapes (scalars-adjacent, primes, unit dims) must
    encode to static payloads and stay unbiased — the codec's reshaping and
    dense-fallback edges, where static-shape logic most easily breaks."""
    codec = SvdCodec(rank=2)
    g = jax.random.normal(jax.random.PRNGKey(3), shape, jnp.float32)
    n_keys = 600
    acc = jnp.zeros(shape, jnp.float32)
    dec = jax.jit(
        lambda k: codec.decode(codec.encode(k, g), g.shape, g.dtype)
    )
    one = dec(jax.random.PRNGKey(0))
    assert one.shape == shape and one.dtype == jnp.float32
    for i in range(n_keys):
        acc = acc + dec(jax.random.PRNGKey(100 + i))
    mean = acc / n_keys
    err = float(jnp.max(jnp.abs(mean - g)))
    scale = float(jnp.max(jnp.abs(g))) + 1e-6
    # loose statistical bound: the mean over 600 keys approaches g
    assert err / scale < 0.5, (shape, err, scale)


@pytest.mark.parametrize("shape", [(5,), (3, 3), (1, 64)])
def test_qsgd_codec_adversarial_shapes_roundtrip(shape):
    codec = QsgdCodec(bits=2, bucket_size=16)
    g = jax.random.normal(jax.random.PRNGKey(4), shape, jnp.float32)
    p = codec.encode(jax.random.PRNGKey(1), g)
    out = codec.decode(p, g.shape, g.dtype)
    assert out.shape == shape and out.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(out)))


# ------------------------------ bucketed (vmapped) decode grouping (PR-4)


_BUCKET_CODECS = {
    "qsgd": QsgdCodec(bits=2, bucket_size=128),
    "terngrad": QsgdCodec(bits=1, bucket_size=128, scheme="terngrad",
                          name="terngrad"),
    "svd": SvdCodec(rank=2),
    "svd_budget": SvdCodec(rank=2, sample="bernoulli_budget"),
    "svd_bf16wire": SvdCodec(rank=2, wire_dtype="bfloat16"),
    "dense": DenseCodec(),
}

# a tree with REPEATED shapes (the grouping case) plus singletons
_BUCKET_TREE = {
    "a1": jax.random.normal(jax.random.PRNGKey(1), (17, 9)),
    "a2": jax.random.normal(jax.random.PRNGKey(2), (17, 9)),
    "a3": jax.random.normal(jax.random.PRNGKey(3), (17, 9)),
    "b": jax.random.normal(jax.random.PRNGKey(4), (33,)),
    "c1": jax.random.normal(jax.random.PRNGKey(5), (5, 5, 1, 4)),
    "c2": jax.random.normal(jax.random.PRNGKey(6), (5, 5, 1, 4)),
}


def _trees_bitwise(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b))
    )


@pytest.mark.parametrize("name", sorted(_BUCKET_CODECS))
def test_decode_tree_bucketed_bit_identical(name):
    """The shape-bucketed vmapped decode (mirror of encode_tree's
    bucketing) is a batching transform, not a reassociation: bit-identical
    to the per-leaf loop for every codec."""
    codec = _BUCKET_CODECS[name]
    payloads, _ = encode_tree(codec, jax.random.PRNGKey(0), _BUCKET_TREE)
    fast = decode_tree(codec, payloads, _BUCKET_TREE, bucketed=True)
    ref = decode_tree(codec, payloads, _BUCKET_TREE, bucketed=False)
    assert _trees_bitwise(fast, ref), name


@pytest.mark.parametrize("name", sorted(_BUCKET_CODECS))
def test_decode_mean_tree_bucketed_bit_identical(name):
    """Same contract for the gathered decode-mean, in BOTH decode orders:
    the canonical unfused path (the ring parity oracle) and the default
    fused path (where the SVD fused kernel serves its leaves per-leaf and
    only the vmap fallback groups)."""
    from atomo_tpu.codecs import decode_mean_tree

    codec = _BUCKET_CODECS[name]
    payloads, _ = encode_tree(codec, jax.random.PRNGKey(0), _BUCKET_TREE)
    gathered = jax.tree_util.tree_map(
        lambda a: jnp.stack([a, a, a]), payloads
    )
    for fused in (False, True):
        fast = decode_mean_tree(codec, gathered, _BUCKET_TREE, 3,
                                fused=fused, bucketed=True)
        ref = decode_mean_tree(codec, gathered, _BUCKET_TREE, 3,
                               fused=fused, bucketed=False)
        assert _trees_bitwise(fast, ref), (name, fused)
