#!/bin/bash
# Round-5 on-chip evidence queue (VERDICT r4 next-round #1).
#
# Runs the full armed queue into artifacts/onchip_r5/ the moment the axon
# relay is healthy. Order matters: cheapest/highest-value first so a relay
# window that closes early still yields evidence.
#
#   1. tests_tpu/           — codec + flash-attention Mosaic compile on TPU
#   2. bench.py --all       — all ladder configs with the final gram/CholeskyQR2
#                             codec (config 5 expected <<58.4 ms)
#   3. bf16_probe.py        — localize the bf16-slower-than-f32 regression
#   4. convergence_artifact — ResNet-18 hardened (label-noise + ablation) gate
#
# Usage: bash scripts/onchip_queue_r5.sh   (assumes relay already healthy)
set -u
cd "$(dirname "$0")/.."
OUT=artifacts/onchip_r5
mkdir -p "$OUT"
TS() { date +%H:%M:%S; }

echo "$(TS) queue start" | tee -a "$OUT/queue.log"

echo "$(TS) [1/5] tests_tpu" | tee -a "$OUT/queue.log"
timeout 2400 python -m pytest tests_tpu/ -q --tb=short \
  > "$OUT/tests_tpu.log" 2>&1
rc=$?; echo "$(TS) tests_tpu rc=$rc" | tee -a "$OUT/queue.log"

echo "$(TS) [2/5] bench --all" | tee -a "$OUT/queue.log"
timeout 9000 python bench.py --all > "$OUT/bench_all.jsonl" 2> "$OUT/bench_all.err"
rc=$?; echo "$(TS) bench rc=$rc" | tee -a "$OUT/queue.log"

echo "$(TS) [3/5] encode_profile (VERDICT r4 #2 breakdown)" | tee -a "$OUT/queue.log"
timeout 2400 python scripts/encode_profile.py --out "$OUT" \
  > "$OUT/encode_profile.log" 2>&1
rc=$?; echo "$(TS) encode_profile rc=$rc" | tee -a "$OUT/queue.log"

echo "$(TS) [4/5] bf16_probe" | tee -a "$OUT/queue.log"
timeout 2400 python scripts/bf16_probe.py > "$OUT/bf16_probe.log" 2>&1
rc=$?; echo "$(TS) bf16_probe rc=$rc" | tee -a "$OUT/queue.log"

echo "$(TS) [5/5] convergence artifact (resnet18 hardened)" | tee -a "$OUT/queue.log"
timeout 7200 python scripts/convergence_artifact.py --out "$OUT" \
  > "$OUT/convergence.log" 2>&1
rc=$?; echo "$(TS) convergence rc=$rc" | tee -a "$OUT/queue.log"

echo "$(TS) queue done" | tee -a "$OUT/queue.log"
