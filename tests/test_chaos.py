"""Chaos harness unit tests: spec parsing, deterministic in-graph fault
injection, and file-corruption primitives (utils/chaos.py)."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from atomo_tpu.utils.chaos import (
    CHAOS_EXIT_CODE,
    ChaosConfig,
    ChaosInjector,
    corrupt_file,
)


def test_spec_parsing_all_kinds():
    cfg = ChaosConfig.from_spec(
        "nan@3,inf@5,explode@7,slow@2:0.5,kill@6,truncate@4,bitflip@8,badmagic@9"
    )
    assert cfg.grad_faults == (
        (3, "nan", False), (5, "inf", False), (7, "explode", False)
    )
    assert cfg.slow_steps == ((2, 0.5),)
    assert cfg.kill_steps == (6,)
    assert cfg.ckpt_faults == ((4, "truncate"), (8, "bitflip"), (9, "badmagic"))
    assert cfg.target_replica == 0
    assert cfg.exit_code == CHAOS_EXIT_CODE
    assert cfg.enabled()


def test_spec_star_is_per_fault():
    """@S* marks THAT fault all-replica; other faults in the same plan
    keep hitting only the target replica."""
    cfg = ChaosConfig.from_spec("nan@2,inf@5*")
    assert cfg.grad_faults == ((2, "nan", False), (5, "inf", True))
    assert cfg.target_replica == 0  # unchanged by the star


def test_spec_rejects_garbage():
    for bad in ("frobnicate@3", "nan", "nan@x", "kill@3:oops,"):
        with pytest.raises(ValueError):
            ChaosConfig.from_spec(bad)


def test_spec_rejects_duplicate_grad_fault_steps():
    """Two gradient faults on one step would sum their in-graph codes into
    a different fault kind (nan+inf == explode's code) — refused up front."""
    with pytest.raises(ValueError, match="same step"):
        ChaosConfig.from_spec("nan@4,inf@4")
    with pytest.raises(ValueError, match="same step"):
        ChaosConfig(grad_faults=((4, "nan", False), (4, "explode", False)))


def test_from_env():
    assert ChaosConfig.from_env({}) is None
    assert ChaosConfig.from_env({"ATOMO_CHAOS": "  "}) is None
    cfg = ChaosConfig.from_env({"ATOMO_CHAOS": "kill@4", "ATOMO_CHAOS_SEED": "7"})
    assert cfg.kill_steps == (4,) and cfg.seed == 7
    assert ChaosInjector.from_env({"ATOMO_CHAOS": "kill@4"}).should_die(4)
    assert ChaosInjector.from_env({}) is None


def test_inject_grads_deterministic_per_step():
    inj = ChaosInjector(ChaosConfig.from_spec("nan@2,inf@3,explode@4"))
    grads = {"w": jnp.ones((4,)), "b": jnp.full((2,), 2.0)}

    @jax.jit
    def poisoned(step):
        return inj.inject_grads(grads, step)

    g1 = poisoned(1)
    np.testing.assert_array_equal(np.asarray(g1["w"]), np.ones(4))
    assert np.isnan(np.asarray(poisoned(2)["w"])).all()
    assert np.isinf(np.asarray(poisoned(3)["b"])).all()
    g4 = np.asarray(poisoned(4)["w"])
    assert np.isfinite(g4).all() and (g4 > 1e11).all()
    # steps past the plan are untouched
    np.testing.assert_array_equal(np.asarray(poisoned(5)["b"]), np.full(2, 2.0))


def test_inject_grads_replica_targeting():
    inj = ChaosInjector(ChaosConfig.from_spec("nan@2"))
    grads = {"w": jnp.ones((4,))}
    hit = inj.inject_grads(grads, 2, replica=jnp.int32(0))
    miss = inj.inject_grads(grads, 2, replica=jnp.int32(1))
    assert np.isnan(np.asarray(hit["w"])).all()
    np.testing.assert_array_equal(np.asarray(miss["w"]), np.ones(4))
    # starred fault poisons every replica...
    inj_all = ChaosInjector(ChaosConfig.from_spec("nan@2*"))
    for r in (0, 3):
        assert np.isnan(
            np.asarray(inj_all.inject_grads(grads, 2, replica=jnp.int32(r))["w"])
        ).all()
    # ...without widening the other faults in the same plan
    inj_mix = ChaosInjector(ChaosConfig.from_spec("nan@2,inf@5*"))
    off_target = inj_mix.inject_grads(grads, 2, replica=jnp.int32(1))
    np.testing.assert_array_equal(np.asarray(off_target["w"]), np.ones(4))
    assert np.isinf(
        np.asarray(inj_mix.inject_grads(grads, 5, replica=jnp.int32(1))["w"])
    ).all()


def test_maybe_sleep_and_die_steps():
    inj = ChaosInjector(ChaosConfig.from_spec("slow@3:0.05,kill@9"))
    t0 = time.monotonic()
    assert inj.maybe_sleep(3) == 0.05
    assert time.monotonic() - t0 >= 0.05
    assert inj.maybe_sleep(4) == 0.0
    assert inj.should_die(9) and not inj.should_die(8)
    inj.maybe_die(8)  # must NOT exit on a non-kill step


def _write(path, data: bytes):
    with open(path, "wb") as f:
        f.write(data)


def test_corrupt_truncate(tmp_path):
    p = str(tmp_path / "f")
    _write(p, bytes(range(100)))
    corrupt_file(p, "truncate")
    assert 9 <= os.path.getsize(p) < 100


def test_corrupt_bitflip_deterministic(tmp_path):
    blob = bytes(100)
    p1, p2 = str(tmp_path / "a"), str(tmp_path / "b")
    _write(p1, blob)
    _write(p2, blob)
    corrupt_file(p1, "bitflip", seed=5)
    corrupt_file(p2, "bitflip", seed=5)
    with open(p1, "rb") as f:
        d1 = f.read()
    with open(p2, "rb") as f:
        d2 = f.read()
    assert d1 == d2 != blob  # same seed, same flip
    assert d1[:8] == blob[:8]  # header untouched: the CRC must catch it
    diff = [i for i in range(100) if d1[i] != blob[i]]
    assert len(diff) == 1
    assert bin(d1[diff[0]] ^ blob[diff[0]]).count("1") == 1


def test_corrupt_badmagic(tmp_path):
    p = str(tmp_path / "f")
    _write(p, b"ATR2" + bytes(60))
    corrupt_file(p, "badmagic")
    with open(p, "rb") as f:
        assert f.read(4) == b"XXXX"


def test_corrupt_unknown_kind(tmp_path):
    p = str(tmp_path / "f")
    _write(p, bytes(20))
    with pytest.raises(ValueError):
        corrupt_file(p, "gamma-ray")


# ---------------- spike + crashloop (PR 5) ----------------


def test_spec_parses_spike_and_crashloop():
    cfg = ChaosConfig.from_spec("spike@7:3,crashloop@2", spike_scale=30.0)
    assert cfg.spike_faults == ((7, 3),)
    assert cfg.spike_scale == 30.0
    assert cfg.crashloop == 2
    assert cfg.enabled()
    # window defaults to 3 when the :arg is omitted
    assert ChaosConfig.from_spec("spike@5").spike_faults == ((5, 3),)
    with pytest.raises(ValueError):
        ChaosConfig.from_spec("spike@5:0")  # window must be >= 1


def test_from_spec_reads_env_knobs_like_from_env():
    """--chaos and ATOMO_CHAOS must behave identically for the same spec:
    from_spec defaults seed/spike_scale to the env knobs."""
    env = {"ATOMO_CHAOS_SPIKE_SCALE": "50", "ATOMO_CHAOS_SEED": "7"}
    cfg = ChaosConfig.from_spec("spike@3", environ=env)
    assert cfg.spike_scale == 50.0
    assert cfg.seed == 7
    # explicit arguments still beat the env
    cfg = ChaosConfig.from_spec("spike@3", spike_scale=9.0, environ=env)
    assert cfg.spike_scale == 9.0
    # no env knobs -> the documented defaults
    cfg = ChaosConfig.from_spec("spike@3", environ={})
    assert cfg.spike_scale == 8.0 and cfg.seed == 0


def test_spike_amplifies_finite_window_only():
    import jax.numpy as jnp

    inj = ChaosInjector(ChaosConfig.from_spec("spike@3:2", spike_scale=8.0))
    g = {"w": jnp.ones((4,))}
    for step, want in [(2, 1.0), (3, 8.0), (4, 8.0), (5, 1.0)]:
        out = inj.inject_grads(g, step)
        np.testing.assert_allclose(np.asarray(out["w"]), want)
        # finite: the norm-screen-passing property that distinguishes
        # spike from explode
        assert np.isfinite(np.asarray(out["w"])).all()


def test_generation_disarms_step_faults_but_not_crashloop(tmp_path):
    import jax.numpy as jnp

    inj = ChaosInjector(
        ChaosConfig.from_spec("spike@3:2,nan@5,kill@7,slow@2:9,truncate@4")
    )
    g1 = inj.with_generation(1)
    g = {"w": jnp.ones((4,))}
    for step in (3, 4, 5):  # spike and nan steps: replay must be clean
        np.testing.assert_array_equal(
            np.asarray(g1.inject_grads(g, step)["w"]), 1.0
        )
    assert not g1.should_die(7)
    assert g1.maybe_sleep(2) == 0.0
    assert g1.ckpt_fault_for(4) is None
    # crashloop is attempt-keyed, not step-keyed: generations don't apply
    cfg = ChaosConfig.from_spec("crashloop@2")
    assert ChaosInjector(cfg, generation=1).config.crashloop == 2


def test_crashloop_dies_below_attempt_threshold():
    """The injector must hard-exit for attempts < M and return for
    attempts >= M. os._exit can't be intercepted in-process, so the doomed
    side runs in a child interpreter."""
    import subprocess
    import sys

    code = (
        "from atomo_tpu.utils.chaos import ChaosConfig, ChaosInjector\n"
        "inj = ChaosInjector(ChaosConfig.from_spec('crashloop@2'))\n"
        "inj.maybe_die_crashloop(attempt={a})\n"
        "print('SURVIVED')\n"
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    dead = subprocess.run(
        [sys.executable, "-c", code.format(a=1)], env=env,
        capture_output=True, text=True,
    )
    assert dead.returncode == CHAOS_EXIT_CODE
    assert "SURVIVED" not in dead.stdout
    alive = subprocess.run(
        [sys.executable, "-c", code.format(a=2)], env=env,
        capture_output=True, text=True,
    )
    assert alive.returncode == 0 and "SURVIVED" in alive.stdout


def test_spike_scale_env_plumbs_through():
    cfg = ChaosConfig.from_env(
        {"ATOMO_CHAOS": "spike@4:2", "ATOMO_CHAOS_SPIKE_SCALE": "12.5"}
    )
    assert cfg.spike_faults == ((4, 2),) and cfg.spike_scale == 12.5
