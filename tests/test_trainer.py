"""End-to-end single-host trainer tests: the 'minimum slice' milestone
(SURVEY.md §7 build-order step 4 / BASELINE config 1: LeNet + entry-wise
sparsification, single process)."""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from atomo_tpu.codecs import QsgdCodec, SvdCodec
from atomo_tpu.data import BatchIterator, load_dataset, synthetic_dataset, SPECS
from atomo_tpu.models import get_model
from atomo_tpu.training import evaluate, make_optimizer, train_loop
from atomo_tpu.training.optim import stepwise_shrink


def _iters(name="mnist", batch=32, train_n=512, test_n=128):
    train = synthetic_dataset(SPECS[name], True, size=train_n)
    test = synthetic_dataset(SPECS[name], False, size=test_n)
    return (
        BatchIterator(train, batch, seed=0),
        BatchIterator(test, batch, shuffle=False, seed=0),
    )


def test_lr_schedule_reference_semantics():
    # lr = base * 0.95^(step // 50)  (sync_replicas_master_nn.py:232-234)
    sched = stepwise_shrink(0.01, 0.95, 50)
    assert float(sched(0)) == 0.01
    assert float(sched(49)) == 0.01
    np.testing.assert_allclose(float(sched(50)), 0.0095)
    np.testing.assert_allclose(float(sched(100)), 0.01 * 0.95**2)


def test_lenet_learns_uncompressed():
    train_it, test_it = _iters()
    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.9)
    logs = []
    state = train_loop(
        model, opt, train_it, max_steps=60, log_fn=logs.append, log_every=10
    )
    ev = evaluate(model, state, test_it)
    assert ev["prec1"] > 30.0, ev  # well above 10% chance on blob data
    assert any(line.startswith("Worker: 0, Step:") for line in logs)


def test_lenet_learns_with_qsgd_codec():
    train_it, test_it = _iters()
    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.9)
    codec = QsgdCodec(bits=2, bucket_size=512)
    state = train_loop(
        model, opt, train_it, codec=codec, max_steps=60, log_every=0
    )
    ev = evaluate(model, state, test_it)
    assert ev["prec1"] > 30.0, ev


@pytest.mark.slow
def test_lenet_learns_with_svd_codec():
    train_it, test_it = _iters()
    model = get_model("lenet", 10)
    # momentum 0.0 mirrors the reference's canonical SVD recipe
    # (run_pytorch.sh:1-20); heavy momentum amplifies the rank-3
    # estimator's sampling noise ~1/(1-beta) and stalls short runs.
    opt = make_optimizer("sgd", lr=0.01, momentum=0.0)
    codec = SvdCodec(rank=3)
    state = train_loop(
        model, opt, train_it, codec=codec, max_steps=60, log_every=0
    )
    ev = evaluate(model, state, test_it)
    assert ev["prec1"] > 25.0, ev


def test_worker_log_line_matches_tuning_regex():
    """The tuning parser regex (tiny_tuning_parser.py:17-19) must match."""
    import re

    from atomo_tpu.utils.metrics import StepMetrics

    line = StepMetrics(
        rank=1, step=5, epoch=0, samples_seen=640, dataset_size=50000,
        loss=2.3021, time_cost=0.5, comp_dur=0.1, encode_dur=0.2,
        comm_dur=0.1, msg_bytes=1048576, prec1=12.5, prec5=50.0,
    ).worker_line()
    pat = (
        r"Worker: .*, Step: .*, Epoch: .* \[.* \(.*\)\], Loss: (.*), "
        r"Time Cost: .*, Comp: .*, Encode:  .*, Comm:  .*, Msg\(MB\):  .*"
    )
    m = re.search(pat, line)
    assert m, line
    assert float(m.group(1).split(",")[0]) == 2.3021


@pytest.mark.slow
def test_bf16_mixed_precision_learns_and_keeps_f32_state():
    """--bf16 mode: bf16 forward/backward, f32 master state. The model must
    still learn, params/opt-state/BN stats must stay f32, and the codec
    must see f32 gradients (wire format unchanged)."""
    train_it, _ = _iters()
    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.9)
    state = train_loop(
        model, opt, train_it, codec=SvdCodec(rank=3), max_steps=60,
        log_fn=lambda s: None, compute_dtype=jnp.bfloat16,
    )
    for leaf in jax.tree_util.tree_leaves(state.params):
        assert leaf.dtype == jnp.float32
    for leaf in jax.tree_util.tree_leaves(state.batch_stats):
        assert leaf.dtype == jnp.float32


def test_bf16_tracks_f32_loss():
    """bf16 compute must track the f32 run closely over a short horizon
    (same data order, same init)."""
    from atomo_tpu.training import create_state, make_train_step

    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.0)
    ds = synthetic_dataset(SPECS["mnist"], True, size=256)

    def run(dtype):
        it = BatchIterator(ds, 32, seed=0)
        images, _ = next(iter(it.epoch()))
        state = create_state(model, opt, jax.random.PRNGKey(0), jnp.asarray(images))
        step = make_train_step(model, opt, compute_dtype=dtype)
        key = jax.random.PRNGKey(1)
        losses = []
        for im, lb in list(it.epoch())[:30]:
            state, m = step(state, key, jnp.asarray(im), jnp.asarray(lb))
            losses.append(float(m["loss"]))
        return losses

    f32 = run(None)
    bf16 = run(jnp.bfloat16)
    # same trajectory within bf16 rounding accumulation
    np.testing.assert_allclose(bf16[-1], f32[-1], rtol=0.2)
    assert bf16[-1] < bf16[0]
