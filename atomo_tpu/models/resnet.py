"""CIFAR-style ResNets (18/34/50/101/152) + ResNet-110, as Flax modules.

Architecture parity with src/model_ops/resnet.py:14-127 (the kuangliu
CIFAR variant): 3x3 stem conv (64 ch, stride 1), 4 stages of BasicBlock /
Bottleneck with plane widths 64/128/256/512, stride-2 downsampling at stage
entry, 1x1-conv+BN shortcut when shape changes, 4x4 average pool, linear head.
Depths: 18=[2,2,2,2], 34=[3,4,6,3] basic; 50=[3,4,6,3], 101=[3,4,23,3],
152=[3,8,36,3] bottleneck (expansion 4).

ResNet-110 is the classic 6n+2 (n=18) three-stage CIFAR ResNet with plane
widths 16/32/64 (He et al. 2015, Table 6) — required by the BASELINE config
ladder (config 5), not present in the reference zoo.

Deviations: NHWC layout; flax BatchNorm momentum 0.9 == torch momentum 0.1;
the reference's `full_modules` bookkeeping lists (resnet.py:19-36) are
unnecessary — per-layer gradient access falls out of the params pytree.
The reference's ResNet34 NameError on `num_classes` (resnet.py:117-118,
SURVEY.md §7 bug list) is fixed, not reproduced.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    planes: int
    stride: int = 1
    expansion: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = lambda: nn.BatchNorm(use_running_average=not train, momentum=0.9)
        out = nn.Conv(self.planes, (3, 3), strides=self.stride, padding=1, use_bias=False)(x)
        out = nn.relu(norm()(out))
        out = nn.Conv(self.planes, (3, 3), padding=1, use_bias=False)(out)
        out = norm()(out)
        if self.stride != 1 or x.shape[-1] != self.planes * self.expansion:
            x = nn.Conv(
                self.planes * self.expansion, (1, 1), strides=self.stride, use_bias=False
            )(x)
            x = norm()(x)
        return nn.relu(out + x)


class Bottleneck(nn.Module):
    planes: int
    stride: int = 1
    expansion: int = 4

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = lambda: nn.BatchNorm(use_running_average=not train, momentum=0.9)
        out = nn.relu(norm()(nn.Conv(self.planes, (1, 1), use_bias=False)(x)))
        out = nn.Conv(self.planes, (3, 3), strides=self.stride, padding=1, use_bias=False)(out)
        out = nn.relu(norm()(out))
        out = nn.Conv(self.planes * self.expansion, (1, 1), use_bias=False)(out)
        out = norm()(out)
        if self.stride != 1 or x.shape[-1] != self.planes * self.expansion:
            x = nn.Conv(
                self.planes * self.expansion, (1, 1), strides=self.stride, use_bias=False
            )(x)
            x = norm()(x)
        return nn.relu(out + x)


class ResNet(nn.Module):
    """4-stage CIFAR ResNet (stem 64ch), ref resnet.py:75-112."""

    block: type
    num_blocks: Sequence[int]
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(64, (3, 3), padding=1, use_bias=False)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9)(x))
        for stage, (planes, n) in enumerate(zip((64, 128, 256, 512), self.num_blocks)):
            for i in range(n):
                stride = (2 if stage > 0 else 1) if i == 0 else 1
                x = self.block(planes=planes, stride=stride)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global avg == avg_pool2d(out, 4) on 4x4
        return nn.Dense(self.num_classes)(x)


class ResNetCifar3Stage(nn.Module):
    """6n+2 three-stage ResNet (16/32/64 planes) — ResNet-110 with n=18."""

    n: int = 18
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(16, (3, 3), padding=1, use_bias=False)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9)(x))
        for stage, planes in enumerate((16, 32, 64)):
            for i in range(self.n):
                stride = 2 if (stage > 0 and i == 0) else 1
                x = BasicBlock(planes=planes, stride=stride)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def ResNet18(num_classes: int = 10) -> ResNet:
    return ResNet(block=BasicBlock, num_blocks=(2, 2, 2, 2), num_classes=num_classes)


def ResNet34(num_classes: int = 10) -> ResNet:
    return ResNet(block=BasicBlock, num_blocks=(3, 4, 6, 3), num_classes=num_classes)


def ResNet50(num_classes: int = 10) -> ResNet:
    return ResNet(block=Bottleneck, num_blocks=(3, 4, 6, 3), num_classes=num_classes)


def ResNet101(num_classes: int = 10) -> ResNet:
    return ResNet(block=Bottleneck, num_blocks=(3, 4, 23, 3), num_classes=num_classes)


def ResNet152(num_classes: int = 10) -> ResNet:
    return ResNet(block=Bottleneck, num_blocks=(3, 8, 36, 3), num_classes=num_classes)


def ResNet110(num_classes: int = 10) -> ResNetCifar3Stage:
    return ResNetCifar3Stage(n=18, num_classes=num_classes)
