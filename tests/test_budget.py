"""Adaptive variance-budget codecs + error feedback (PR-15 tentpole).

Contracts pinned here (atomo_tpu/budget + parallel/replicated EfState):

  * The water-filling solver is PURE and deterministic: same spectra and
    budget -> same allocation, always.
  * Degenerate-point identities: the per-leaf wrapper at UNIFORM ranks
    is byte-for-byte today's fixed-budget codec (bit-identical payloads,
    identical wire bytes); an unbounded budget drives every layer into
    the codec's exact dense fallback — ``--on-diverge densify``'s remedy
    as the dial's spend-everything limit.
  * The allocator's predicted per-leaf byte sums equal the executed
    encode's to the byte (the bench config 16 wire-match gate), under
    jit, the superstep scan and the streamed per-bucket encode — the
    per-leaf ranks are STATIC trace-time values.
  * budget_alloc.json round-trips; reuse refuses codec/leaf mismatches;
    the checkpoint-boundary retuner re-allocates out loud (artifact
    epoch + budget_realloc incident quoting both predicted variances).
  * Error feedback (EfState): step 1 equals the plain program bitwise
    (zero residual); the single-step estimator is BIASED (the stated
    contract) while the telescoping identity applied + residual ==
    sum(gradients) holds; the residual carry survives
    kill->restart->resume bit-exactly; unproven compositions are
    rejected by the builder, the loop and the CLI preflight.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from atomo_tpu.budget import (
    Allocation,
    BudgetRetuner,
    PerLeafCodec,
    alloc_reusable,
    allocation_leaf_budgets,
    allocation_meta,
    budgeted_codec,
    latest_epoch,
    measure_spectra,
    new_alloc_doc,
    read_alloc,
    solve_allocation,
    spectra_from_qerr2,
    uniform_ks,
    write_alloc,
)
from atomo_tpu.codecs import (
    DensePayload,
    SvdCodec,
    decode_mean_tree,
    decode_tree,
    encode_tree,
    encode_tree_streamed,
    payload_nbytes,
)
from atomo_tpu.data import BatchIterator, SPECS, synthetic_dataset
from atomo_tpu.models import get_model
from atomo_tpu.parallel import (
    EfState,
    init_ef_state,
    make_distributed_train_step,
    make_mesh,
    replicate_state,
    shard_batch,
)
from atomo_tpu.parallel.common import plan_layer_buckets
from atomo_tpu.training import create_state, make_optimizer


def _eq(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        )
    )


def _grad_tree(key=0):
    k = jax.random.PRNGKey(key)
    return {
        "conv": jax.random.normal(k, (5, 5, 10, 20)),
        "fc": jax.random.normal(jax.random.fold_in(k, 1), (320, 50)) * 3.0,
        "bias": jax.random.normal(jax.random.fold_in(k, 2), (10,)),
        "fc2": jax.random.normal(jax.random.fold_in(k, 3), (50, 10)),
    }


CODEC = SvdCodec(rank=3)


# --------------------------------------------------------------- solver


def test_solver_pure_deterministic():
    spectra = measure_spectra(CODEC, _grad_tree())
    a1 = solve_allocation(CODEC, spectra, mode="variance")
    a2 = solve_allocation(CODEC, spectra, mode="variance")
    assert a1 == a2
    assert a1.payload_bytes <= a1.budget_bytes
    for l in spectra:
        assert 1 <= a1.ks[l.index] <= max(l.r_full, l.base_k)


def test_solver_respects_explicit_budget():
    spectra = measure_spectra(CODEC, _grad_tree())
    uni = solve_allocation(CODEC, spectra, mode="uniform")
    tight = solve_allocation(
        CODEC, spectra, budget_bytes=uni.payload_bytes * 3 // 4,
        mode="variance",
    )
    assert tight.payload_bytes <= uni.payload_bytes * 3 // 4
    rich = solve_allocation(
        CODEC, spectra, budget_bytes=uni.payload_bytes * 2,
        mode="variance",
    )
    # more budget never hurts the predicted variance
    assert rich.predicted_variance <= uni.predicted_variance + 1e-9


def test_uniform_degenerate_point_is_today_byte_for_byte():
    grads = _grad_tree()
    spectra = measure_spectra(CODEC, grads)
    wrapped = budgeted_codec(CODEC, uniform_ks(spectra))
    key = jax.random.PRNGKey(7)
    p0, s0 = encode_tree(CODEC, key, grads)
    p1, s1 = encode_tree(wrapped, key, grads)
    assert s0.payload_bytes == s1.payload_bytes
    assert _eq(p0, p1)
    # and decode agrees bitwise too
    assert _eq(decode_tree(CODEC, p0, grads), decode_tree(wrapped, p1, grads))


def test_spend_everything_point_is_densify():
    grads = _grad_tree()
    spectra = measure_spectra(CODEC, grads)
    big = solve_allocation(
        CODEC, spectra, budget_bytes=10**12, mode="variance"
    )
    wrapped = budgeted_codec(CODEC, big.ks)
    payloads, stats = encode_tree(wrapped, jax.random.PRNGKey(0), grads)
    # every leaf crossed into the codec's exact dense fallback: the
    # payload IS the gradient (the densify remedy, reached as the
    # budget dial's limit) and the wire equals dense
    assert stats.payload_bytes == stats.dense_bytes
    for p in jax.tree_util.tree_leaves(
        payloads, is_leaf=lambda x: isinstance(x, DensePayload)
    ):
        assert isinstance(p, DensePayload)
    decoded = decode_tree(wrapped, payloads, grads)
    for d, g in zip(
        jax.tree_util.tree_leaves(decoded),
        jax.tree_util.tree_leaves(grads),
    ):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(g))


def test_wire_match_predicted_equals_executed():
    grads = _grad_tree()
    spectra = measure_spectra(CODEC, grads)
    alloc = solve_allocation(CODEC, spectra, mode="variance")
    wrapped = budgeted_codec(CODEC, alloc.ks)
    _, stats = encode_tree(wrapped, jax.random.PRNGKey(0), grads)
    assert stats.payload_bytes == alloc.payload_bytes
    # and the per-leaf pairs sum to the same number (the +ab pricing)
    assert sum(p for _, p in allocation_leaf_budgets(
        CODEC, spectra, alloc.ks
    )) == alloc.payload_bytes


def test_per_leaf_static_shapes_jit_and_stream():
    """The allocation's ranks are static per-leaf values: the wrapped
    encode traces under jit, and the streamed per-bucket encode is
    bit-identical to the monolithic one for any bucket size (the
    global-leaf-index key + codec dispatch discipline)."""
    grads = _grad_tree()
    spectra = measure_spectra(CODEC, grads)
    alloc = solve_allocation(CODEC, spectra, mode="variance")
    wrapped = budgeted_codec(CODEC, alloc.ks)
    key = jax.random.PRNGKey(3)
    p_ref, _ = encode_tree(wrapped, key, grads)
    p_jit = jax.jit(
        lambda k, g: encode_tree(wrapped, k, g)[0]
    )(key, grads)
    assert _eq(p_ref, p_jit)
    for bucket_bytes in (1 << 12, 1 << 14, 0):
        plan = plan_layer_buckets(grads, bucket_bytes)
        p_s, _ = encode_tree_streamed(wrapped, key, grads, plan)
        assert _eq(p_ref, p_s)


def test_decode_mean_tree_per_leaf_dispatch():
    """Gathered per-replica payloads of a per-leaf wrapped codec decode
    to the same mean as the per-replica decode + mean oracle."""
    grads = _grad_tree()
    spectra = measure_spectra(CODEC, grads)
    alloc = solve_allocation(CODEC, spectra, mode="variance")
    wrapped = budgeted_codec(CODEC, alloc.ks)
    n = 4
    payloads = [
        encode_tree(wrapped, jax.random.PRNGKey(100 + r), grads)[0]
        for r in range(n)
    ]
    gathered = jax.tree_util.tree_map(
        lambda *a: jnp.stack(a), *payloads
    )
    fused = decode_mean_tree(wrapped, gathered, grads, n, fused=False)
    oracle = jax.tree_util.tree_map(
        lambda *a: jnp.mean(jnp.stack(a), axis=0),
        *[decode_tree(wrapped, p, grads) for p in payloads],
    )
    assert _eq(fused, oracle)


def test_subset_reindexes_for_partial_leaf_lists():
    grads = _grad_tree()
    spectra = measure_spectra(CODEC, grads)
    alloc = solve_allocation(CODEC, spectra, mode="variance")
    wrapped = budgeted_codec(CODEC, alloc.ks)
    sub = wrapped.subset((2, 0))
    assert isinstance(sub, PerLeafCodec)
    assert sub.codec_for(0) == wrapped.codec_for(2)
    assert sub.codec_for(1) == wrapped.codec_for(0)
    with pytest.raises(IndexError):
        wrapped.codec_for(99)


def test_spectra_fold_from_qerr2():
    spectra = measure_spectra(CODEC, _grad_tree())
    ks = uniform_ks(spectra)
    q = [2.0] * len(spectra)
    fresh = spectra_from_qerr2(spectra, q, ks)
    for old, new in zip(spectra, fresh):
        if old.adaptive:
            assert new.a == pytest.approx(2.0 * ks[old.index])
        else:
            assert new.a == old.a
    # a gap (None / non-finite) keeps the prior A — not a sample
    q2 = [None, float("nan")] + [1.0] * (len(spectra) - 2)
    fresh2 = spectra_from_qerr2(spectra, q2, ks)
    assert fresh2[0].a == spectra[0].a
    assert fresh2[1].a == spectra[1].a


def test_spectra_fold_keeps_prior_a_at_dense_fallback():
    """A leaf currently shipped via the exact dense fallback reads
    q_err2 == 0 because the wire is exact, not because its spectrum
    vanished: with the codec passed (the retuner's call), the fold must
    keep the prior A so a re-solve cannot strip the leaf 'for free'
    and oscillate at every boundary (code-review finding)."""
    spectra = measure_spectra(CODEC, _grad_tree())
    target = next(l for l in spectra if l.adaptive and l.a > 0)
    # rank the target into its dense fallback (full rank always crosses
    # it under the near-square matricization)
    ks = list(uniform_ks(spectra))
    ks[target.index] = target.r_full
    q = [0.0] * len(spectra)  # the exact wire's observed error
    folded = spectra_from_qerr2(spectra, q, ks, codec=CODEC)
    assert folded[target.index].a == target.a  # prior kept
    # without the codec (no fallback knowledge) the raw law applies
    raw = spectra_from_qerr2(spectra, q, ks)
    assert raw[target.index].a == 0.0


# ------------------------------------------------------------- artifact


def test_artifact_roundtrip_and_reuse(tmp_path):
    grads = _grad_tree()
    spectra = measure_spectra(CODEC, grads)
    alloc = solve_allocation(CODEC, spectra, mode="variance")
    doc = new_alloc_doc(CODEC, spectra, alloc)
    write_alloc(str(tmp_path), doc)
    back = read_alloc(str(tmp_path))
    assert back == json.loads(json.dumps(doc))
    ok, why = alloc_reusable(
        back, codec_name=CODEC.name, n_leaves=len(spectra)
    )
    assert ok, why
    ep = latest_epoch(back)
    assert tuple(ep["ks"]) == alloc.ks
    # refusals: wrong codec, wrong leaf count, missing doc
    ok, why = alloc_reusable(back, codec_name="qsgd", n_leaves=len(spectra))
    assert not ok and "codec" in why
    ok, why = alloc_reusable(back, codec_name=CODEC.name, n_leaves=99)
    assert not ok and "leaves" in why
    ok, _ = alloc_reusable(None, codec_name=CODEC.name, n_leaves=1)
    assert not ok
    # the recorder meta's per-layer sum equals the artifact's
    meta = allocation_meta(ep)
    assert sum(l["payload_bytes"] for l in meta["layers"]) == \
        ep["payload_bytes"]


def test_retuner_reallocates_on_drifted_spectra(tmp_path):
    """Feed the retuner a recorded q_err2 series whose per-layer means
    contradict the startup spectra: the boundary re-solve must move the
    allocation, append an artifact epoch, and land a budget_realloc
    incident quoting predicted variance both ways."""
    from atomo_tpu.utils.tracing import IncidentLog

    grads = _grad_tree()
    spectra = measure_spectra(CODEC, grads)
    alloc = solve_allocation(CODEC, spectra, mode="variance")
    doc = new_alloc_doc(CODEC, spectra, alloc)
    write_alloc(str(tmp_path), doc)
    # fabricate the recorded stream: the leaf the startup allocation
    # fed LEAST suddenly carries all the error mass — the re-solve must
    # move atoms toward it
    n = len(spectra)
    target = min(
        (
            l for l in spectra
            if l.adaptive and alloc.ks[l.index] < l.r_full
        ),
        key=lambda l: (alloc.ks[l.index], l.index),
    ).index
    qrow = [0.0] * n
    qrow[target] = 1e6
    with open(os.path.join(str(tmp_path), "metrics.jsonl"), "w") as f:
        for s in range(1, 11):
            f.write(json.dumps(
                {"kind": "step", "step": s, "q_err2": qrow}
            ) + "\n")
    incidents = IncidentLog.for_train_dir(str(tmp_path))
    logs = []
    rt = BudgetRetuner(
        train_dir=str(tmp_path), base_codec=CODEC, spectra=spectra,
        alloc=alloc, doc=doc, incidents=incidents, log_fn=logs.append,
    )
    new_codec = rt.maybe_realloc(10)
    assert new_codec is not None
    assert new_codec.ks[target] > alloc.ks[target]
    back = read_alloc(str(tmp_path))
    assert len(back["epochs"]) == 2
    assert back["epochs"][1]["start_step"] == 10
    recs = IncidentLog.read(
        os.path.join(str(tmp_path), "incidents.jsonl")
    )
    rec = [r for r in recs if r.get("cause") == "budget_realloc"][-1]
    assert rec["action"] == "realloc->epoch1"
    assert rec["predicted_variance_old"] > rec["predicted_variance_new"]
    assert rec["ks_old"] != rec["ks_new"]


def test_retuner_keeps_without_signal_or_gain(tmp_path):
    from atomo_tpu.utils.tracing import IncidentLog

    grads = _grad_tree()
    spectra = measure_spectra(CODEC, grads)
    alloc = solve_allocation(CODEC, spectra, mode="variance")
    doc = new_alloc_doc(CODEC, spectra, alloc)
    write_alloc(str(tmp_path), doc)
    incidents = IncidentLog.for_train_dir(str(tmp_path))
    rt = BudgetRetuner(
        train_dir=str(tmp_path), base_codec=CODEC, spectra=spectra,
        alloc=alloc, doc=doc, incidents=incidents, log_fn=lambda *_: None,
    )
    # no recorded q series at all: not even a decision (no incident)
    assert rt.maybe_realloc(10) is None
    assert not [
        r for r in IncidentLog.read(
            os.path.join(str(tmp_path), "incidents.jsonl")
        )
        if r.get("cause") == "budget_realloc"
    ]
    # a consistent series (q == A/k of the startup spectra): keep, with
    # the decision on the record
    n = len(spectra)
    qrow = [
        (l.a / alloc.ks[l.index]) if l.adaptive else 0.0
        for l in spectra
    ]
    assert len(qrow) == n
    with open(os.path.join(str(tmp_path), "metrics.jsonl"), "w") as f:
        for s in range(1, 11):
            f.write(json.dumps(
                {"kind": "step", "step": s, "q_err2": qrow}
            ) + "\n")
    assert rt.maybe_realloc(10) is None
    kept = [
        r for r in IncidentLog.read(
            os.path.join(str(tmp_path), "incidents.jsonl")
        )
        if r.get("cause") == "budget_realloc"
    ]
    assert kept and kept[-1]["action"] == "keep"


def test_budget_alloc_consistent_report_check(tmp_path):
    from atomo_tpu.obs.report import build_report

    grads = _grad_tree()
    spectra = measure_spectra(CODEC, grads)
    alloc = solve_allocation(CODEC, spectra, mode="variance")
    doc = new_alloc_doc(CODEC, spectra, alloc)
    write_alloc(str(tmp_path), doc)
    meta = allocation_meta(latest_epoch(doc))
    with open(os.path.join(str(tmp_path), "metrics.jsonl"), "w") as f:
        f.write(json.dumps({"kind": "meta", **meta}) + "\n")
        for s in range(1, 4):
            f.write(json.dumps(
                {"kind": "step", "step": s, "loss": 1.0,
                 "budget_epoch": 0}
            ) + "\n")
    rep = build_report(str(tmp_path))
    chk = next(
        c for c in rep["checks"] if c["name"] == "budget_alloc_consistent"
    )
    assert chk["ok"] and not chk["skipped"], chk
    # a record claiming a never-recorded epoch fails the check
    with open(os.path.join(str(tmp_path), "metrics.jsonl"), "a") as f:
        f.write(json.dumps(
            {"kind": "step", "step": 4, "loss": 1.0, "budget_epoch": 7}
        ) + "\n")
    rep = build_report(str(tmp_path))
    chk = next(
        c for c in rep["checks"] if c["name"] == "budget_alloc_consistent"
    )
    assert not chk["ok"]


def test_report_check_skipped_without_budget(tmp_path):
    from atomo_tpu.obs.report import build_report

    rep = build_report(str(tmp_path))
    chk = next(
        c for c in rep["checks"] if c["name"] == "budget_alloc_consistent"
    )
    assert chk["ok"] and chk["skipped"]


# ------------------------------------------------------- error feedback


MESH4 = None


def _mesh4():
    global MESH4
    if MESH4 is None:
        MESH4 = make_mesh(4)
    return MESH4


def _setup_step(codec, **kw):
    mesh = _mesh4()
    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.05, momentum=0.9)
    images = jax.random.uniform(jax.random.PRNGKey(1), (16, 28, 28, 1))
    labels = jax.random.randint(jax.random.PRNGKey(2), (16,), 0, 10)
    host0 = jax.device_get(
        create_state(model, opt, jax.random.PRNGKey(0), images)
    )
    step = make_distributed_train_step(model, opt, mesh, codec, **kw)
    si, sl = shard_batch(mesh, images, labels)

    def fresh():
        return replicate_state(
            mesh, jax.tree_util.tree_map(jnp.asarray, host0)
        )

    return step, fresh, si, sl


TOPK = SvdCodec(rank=2, sample="topk")


@pytest.mark.slow
def test_ef_step1_equals_plain_bitwise():
    """Zero residual: the first EF step IS the plain step, bit for bit —
    the honest-start contract on _zero_ef_residual_host."""
    key = jax.random.PRNGKey(0)
    step_p, fresh, si, sl = _setup_step(TOPK, aggregate="gather")
    step_e, _, _, _ = _setup_step(
        TOPK, aggregate="gather", error_feedback=True
    )
    sp, _ = step_p(fresh(), key, si, sl)
    se, me = step_e(init_ef_state(_mesh4(), fresh()), key, si, sl)
    assert isinstance(se, EfState)
    assert _eq(jax.device_get(sp.params), jax.device_get(se.params))
    assert float(me["ef_res_norm"]) > 0  # topk is lossy: residual exists


@pytest.mark.slow
def test_ef_superstep_partition_invariance():
    """The residual rides the scan carry: two K=2 blocks equal one K=4
    block bit-for-bit — the PR-2 partition invariance WITHIN the scan
    family, EF carry included (scan-vs-standalone keeps its documented
    last-mantissa fusion-drift class, so K=1 is not the oracle here)."""
    from atomo_tpu.parallel import shard_superbatch

    key = jax.random.PRNGKey(0)
    mesh = _mesh4()
    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.05, momentum=0.9)
    images = jax.random.uniform(jax.random.PRNGKey(1), (16, 28, 28, 1))
    labels = jax.random.randint(jax.random.PRNGKey(2), (16,), 0, 10)
    host0 = jax.device_get(
        create_state(model, opt, jax.random.PRNGKey(0), images)
    )

    def run_blocks(block_k, n_blocks):
        step = make_distributed_train_step(
            model, opt, mesh, TOPK, aggregate="gather",
            error_feedback=True, superstep=block_k,
        )
        st = init_ef_state(mesh, replicate_state(
            mesh, jax.tree_util.tree_map(jnp.asarray, host0)
        ))
        imk = jnp.broadcast_to(images, (block_k,) + images.shape)
        lbk = jnp.broadcast_to(labels, (block_k,) + labels.shape)
        sik, slk = shard_superbatch(mesh, imk, lbk)
        for _ in range(n_blocks):
            st, _ = step(st, key, sik, slk)
        return st

    a = run_blocks(2, 2)
    b = run_blocks(4, 1)
    assert _eq(jax.device_get(a.params), jax.device_get(b.params))
    assert _eq(jax.device_get(a.residual), jax.device_get(b.residual))


def test_ef_bias_contract_and_telescoping():
    """The stated EF math at codec level: decode(encode(.)) is BIASED
    for the topk contraction (E != g — here deterministic, so one draw
    shows it), while the telescoping identity holds exactly: the sum of
    applied estimates plus the in-flight residual equals the sum of the
    true gradients fed in."""
    g = jax.random.normal(jax.random.PRNGKey(0), (64, 32))
    codec = SvdCodec(rank=2, sample="topk")
    one = codec.decode(
        codec.encode(jax.random.PRNGKey(1), g), tuple(g.shape)
    )
    assert float(jnp.max(jnp.abs(one - g))) > 1e-3  # biased: not g
    e = jnp.zeros_like(g)
    applied = jnp.zeros_like(g)
    fed_total = jnp.zeros_like(g)
    for t in range(6):
        gt = jax.random.normal(jax.random.fold_in(
            jax.random.PRNGKey(2), t
        ), g.shape) * 0.1
        fed = gt + e
        d = codec.decode(
            codec.encode(jax.random.PRNGKey(3), fed), tuple(g.shape)
        )
        e = fed - d
        applied = applied + d
        fed_total = fed_total + gt
    np.testing.assert_allclose(
        np.asarray(applied + e), np.asarray(fed_total), rtol=1e-4,
        atol=1e-5,
    )
    # bounded, not compounding: the residual stays the size of one
    # step's compression error, far below the accumulated gradient mass
    assert float(jnp.linalg.norm(e)) < float(jnp.linalg.norm(fed_total))


@pytest.mark.slow
def test_ef_kill_restart_resume_bit_exact(tmp_path):
    """The EF residual rides checkpoints: run to 4 with saves, resume to
    6 — final params bit-identical to the uninterrupted run (the
    ISSUE-15 EF carry drill)."""
    from atomo_tpu.parallel import distributed_train_loop

    mesh = _mesh4()
    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.05, momentum=0.9)

    def make_iter():
        return BatchIterator(
            synthetic_dataset(SPECS["mnist"], True, size=64), 16, seed=0
        )

    oracle = distributed_train_loop(
        model, opt, mesh, make_iter(), codec=TOPK, aggregate="gather",
        error_feedback=True, max_steps=6, log_every=0, eval_freq=0,
        seed=0,
    )
    assert isinstance(oracle, EfState)
    distributed_train_loop(
        model, opt, mesh, make_iter(), codec=TOPK, aggregate="gather",
        error_feedback=True, max_steps=4, log_every=0, eval_freq=0,
        seed=0, train_dir=str(tmp_path), save_freq=2,
    )
    logs = []
    resumed = distributed_train_loop(
        model, opt, mesh, make_iter(), codec=TOPK, aggregate="gather",
        error_feedback=True, max_steps=6, log_every=0, eval_freq=0,
        seed=0, train_dir=str(tmp_path), resume=True, log_fn=logs.append,
    )
    assert any("Resumed" in l and "step 4" in l for l in logs), logs
    assert _eq(
        jax.device_get(resumed.params), jax.device_get(oracle.params)
    )
    assert _eq(
        jax.device_get(resumed.residual), jax.device_get(oracle.residual)
    )


@pytest.mark.slow
def test_ef_resume_of_plain_checkpoint_rezeros_residual(tmp_path, recwarn):
    from atomo_tpu.parallel import distributed_train_loop

    mesh = _mesh4()
    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.05, momentum=0.9)

    def make_iter():
        return BatchIterator(
            synthetic_dataset(SPECS["mnist"], True, size=64), 16, seed=0
        )

    distributed_train_loop(
        model, opt, mesh, make_iter(), codec=TOPK, aggregate="gather",
        max_steps=2, log_every=0, eval_freq=0, seed=0,
        train_dir=str(tmp_path), save_freq=2,
    )
    resumed = distributed_train_loop(
        model, opt, mesh, make_iter(), codec=TOPK, aggregate="gather",
        error_feedback=True, max_steps=4, log_every=0, eval_freq=0,
        seed=0, train_dir=str(tmp_path), resume=True,
    )
    assert isinstance(resumed, EfState)
    assert any(
        "no residual carry" in str(w.message) for w in recwarn.list
    )


def test_ef_builder_conflict_matrix():
    mesh = _mesh4()
    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.05)
    from atomo_tpu.training import GuardConfig

    with pytest.raises(ValueError, match="dense training has no residual"):
        make_distributed_train_step(
            model, opt, mesh, None, error_feedback=True
        )
    with pytest.raises(ValueError, match="delayed"):
        make_distributed_train_step(
            model, opt, mesh, TOPK, aggregate="gather",
            overlap="delayed", error_feedback=True,
        )
    with pytest.raises(ValueError, match="guard"):
        make_distributed_train_step(
            model, opt, mesh, TOPK, aggregate="gather",
            guard=GuardConfig(), error_feedback=True,
        )
    with pytest.raises(ValueError, match="num_aggregate"):
        make_distributed_train_step(
            model, opt, mesh, TOPK, aggregate="gather",
            num_aggregate=2, error_feedback=True,
        )


def test_cli_preflight_rejects():
    from atomo_tpu.cli import _argv_preflight, build_parser

    parser = build_parser()

    def pf(argv):
        args = parser.parse_args(["train"] + argv)
        _argv_preflight(args)

    # budget conflicts
    with pytest.raises(SystemExit, match="budget-bytes"):
        pf(["--budget-bytes", "1000"])
    # qsgd bit allocation is a STATED law (B/(2^b-1)^2) — accepted now;
    # terngrad's max-norm scale + sigma clip is not, and stays rejected
    pf(["--budget-alloc", "variance", "--code", "qsgd"])
    with pytest.raises(SystemExit, match="terngrad"):
        pf(["--budget-alloc", "variance", "--code", "terngrad"])
    with pytest.raises(SystemExit, match="fixed_k"):
        pf(["--budget-alloc", "variance", "--code", "svd",
            "--sample", "topk"])
    with pytest.raises(SystemExit, match="no budget to allocate"):
        pf(["--budget-alloc", "variance", "--code", "sgd"])
    with pytest.raises(SystemExit, match="on-diverge"):
        pf(["--budget-alloc", "variance", "--code", "svd",
            "--obs-quality", "--obs-record", "--train-dir", "/tmp/x",
            "--on-diverge", "skip", "--save-freq", "2"])
    # error-feedback conflicts
    with pytest.raises(SystemExit, match="has none"):
        pf(["--error-feedback", "--code", "sgd"])
    with pytest.raises(SystemExit, match="multi-device"):
        pf(["--error-feedback", "--code", "svd", "--n-devices", "1"])
    with pytest.raises(SystemExit, match="delayed"):
        pf(["--error-feedback", "--code", "svd", "--n-devices", "4",
            "--overlap", "delayed", "--aggregate", "gather"])
    with pytest.raises(SystemExit, match="guard"):
        pf(["--error-feedback", "--code", "svd", "--n-devices", "4",
            "--grad-guard"])
    # EF x autopilot is now a probed composition (the tuner narrows its
    # space to the EF-compatible candidates) — accepted, not rejected
    pf(["--error-feedback", "--code", "svd", "--sample", "topk",
        "--n-devices", "4", "--auto", "tune", "--train-dir", "/tmp/x"])
    # the contraction-pairing warning, not a reject
    with pytest.warns(UserWarning, match="CONTRACTION"):
        pf(["--error-feedback", "--code", "svd", "--n-devices", "4"])


def test_pack_kernel_default_consults_decision_record(monkeypatch):
    """The use_pallas precedent as a mechanism (ISSUE-15 satellite):
    pack_kernel=None is the jnp oracle everywhere today (no measured win
    on record), flips default-ON exactly when a TPU device kind gains a
    recorded win, and never flips off-TPU."""
    from atomo_tpu.codecs import QsgdCodec
    from atomo_tpu.ops import qsgd_kernels as qk

    assert qk.pack_kernel_default() is False  # CPU suite: always jnp
    assert QsgdCodec(bits=2)._pack_kernel() is False
    assert QsgdCodec(bits=2, pack_kernel=True)._pack_kernel() is True
    # a recorded win flips the default on matching TPU hardware...
    monkeypatch.setitem(
        qk.PACK_KERNEL_MEASURED_WINS, "v5e",
        {"win": True, "evidence": "synthetic-test-entry"},
    )
    monkeypatch.setattr(qk, "is_tpu", lambda: True)

    class FakeDev:
        device_kind = "TPU v5e"

    monkeypatch.setattr(
        qk.jax, "devices", lambda *a, **k: [FakeDev()]
    )
    assert qk.pack_kernel_default() is True
    # ...but never on a kind without a recorded win
    FakeDev.device_kind = "TPU v4"
    assert qk.pack_kernel_default() is False
    # and never off-TPU, win or no win (the automatic jnp fallback)
    monkeypatch.setattr(qk, "is_tpu", lambda: False)
    FakeDev.device_kind = "TPU v5e"
    assert qk.pack_kernel_default() is False


# ------------------------------------------------- qsgd bit allocation
# The second water-filling target (same solver, different law): the
# knob is the leaf's bit width b, the stated law is E q_err2 =
# B_l / (2^b - 1)^2 with B_l = (1/6) sum_buckets n_b s_b^2, and the
# pricing is the codec's own analytic leaf_payload_bytes.


def test_qsgd_analytic_payload_matches_executed_across_knobs():
    from atomo_tpu.codecs import QsgdCodec

    grads = _grad_tree()
    leaves = jax.tree_util.tree_leaves(grads)
    for bits in (1, 2, 4, 8, 16):
        for bucket in (64, 512):
            qc = QsgdCodec(bits=bits, bucket_size=bucket)
            _, stats = encode_tree(qc, jax.random.PRNGKey(0), grads)
            assert stats.payload_bytes == sum(
                qc.leaf_payload_bytes(tuple(l.shape)) for l in leaves
            ), (bits, bucket)


def test_qsgd_bit_allocation_wire_match_predicted_equals_executed():
    from atomo_tpu.budget.allocator import MAX_BITS
    from atomo_tpu.codecs import QsgdCodec

    qc = QsgdCodec(bits=4, bucket_size=256)
    grads = _grad_tree()
    spectra = measure_spectra(qc, grads)
    alloc = solve_allocation(qc, spectra, mode="variance")
    assert all(1 <= b <= MAX_BITS for b in alloc.ks)
    wrapped = budgeted_codec(qc, alloc.ks)
    _, stats = encode_tree(wrapped, jax.random.PRNGKey(0), grads)
    assert stats.payload_bytes == alloc.payload_bytes
    # the per-leaf pairs the +ab candidates price with sum to the same
    assert sum(p for _, p in allocation_leaf_budgets(
        qc, spectra, alloc.ks
    )) == alloc.payload_bytes
    # and the recorded prediction is the stated bit law at those knobs
    from atomo_tpu.budget import predicted_variance

    assert alloc.predicted_variance == pytest.approx(
        predicted_variance(spectra, alloc.ks, codec=qc)
    )


def test_qsgd_uniform_point_is_configured_bits_byte_for_byte():
    from atomo_tpu.codecs import QsgdCodec

    qc = QsgdCodec(bits=2, bucket_size=512)
    grads = _grad_tree()
    spectra = measure_spectra(qc, grads)
    assert uniform_ks(spectra) == (2, 2, 2, 2)
    wrapped = budgeted_codec(qc, uniform_ks(spectra))
    key = jax.random.PRNGKey(11)
    p0, s0 = encode_tree(qc, key, grads)
    p1, s1 = encode_tree(wrapped, key, grads)
    assert s0.payload_bytes == s1.payload_bytes
    assert _eq(p0, p1)
    assert _eq(decode_tree(qc, p0, grads), decode_tree(wrapped, p1, grads))


def test_qsgd_bit_solver_pure_and_monotone():
    from atomo_tpu.codecs import QsgdCodec

    qc = QsgdCodec(bits=4, bucket_size=256)
    spectra = measure_spectra(qc, _grad_tree())
    a1 = solve_allocation(qc, spectra, mode="variance")
    a2 = solve_allocation(qc, spectra, mode="variance")
    assert a1 == a2
    uni = solve_allocation(qc, spectra, mode="uniform")
    rich = solve_allocation(
        qc, spectra, budget_bytes=uni.payload_bytes * 2, mode="variance"
    )
    assert rich.predicted_variance <= uni.predicted_variance + 1e-9
    tight = solve_allocation(
        qc, spectra, budget_bytes=uni.payload_bytes * 3 // 4,
        mode="variance",
    )
    assert tight.payload_bytes <= uni.payload_bytes * 3 // 4


def test_qsgd_terngrad_scheme_refused():
    from atomo_tpu.codecs import QsgdCodec

    tern = QsgdCodec(bits=1, scheme="terngrad")
    with pytest.raises(ValueError, match="terngrad"):
        measure_spectra(tern, _grad_tree())
