"""Pallas QSGD kernel tests (interpret mode on CPU; same kernels compile to
Mosaic on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from atomo_tpu.ops import pallas_quantize_pack, pallas_unpack_dequantize

INTERP = dict(interpret=True)


@pytest.mark.parametrize("bits", [1, 2, 4])
@pytest.mark.parametrize("n", [512, 1000, 4096 + 17])
def test_roundtrip_error_bounded(bits, n):
    """decode(encode(x)) stays within one quantization level per bucket."""
    x = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.float32)
    words, scales = pallas_quantize_pack(x, 7, bits=bits, bucket_size=512, **INTERP)
    out = pallas_unpack_dequantize(
        words, scales, bits=bits, bucket_size=512, n=n, **INTERP
    )
    levels = (1 << bits) - 1
    n_buckets = -(-n // 512)
    xb = np.zeros(n_buckets * 512, np.float32)
    xb[:n] = np.asarray(x)
    per_bucket_tol = np.repeat(np.asarray(scales) / levels, 512)[:n]
    err = np.abs(np.asarray(out) - np.asarray(x))
    assert np.all(err <= per_bucket_tol + 1e-6)


def test_codes_are_legal_and_deterministic():
    x = jax.random.normal(jax.random.PRNGKey(1), (2048,), jnp.float32)
    w1, s1 = pallas_quantize_pack(
        x, 42, bits=2, bucket_size=512, internal_rng=False, **INTERP
    )
    w2, s2 = pallas_quantize_pack(
        x, 42, bits=2, bucket_size=512, internal_rng=False, **INTERP
    )
    np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    assert w1.dtype == jnp.uint32 and s1.dtype == jnp.float32


def test_unbiasedness_over_seeds():
    """E_seed[decode(encode(x))] ≈ x — the QSGD contract, kernel edition."""
    n = 512
    x = jax.random.normal(jax.random.PRNGKey(2), (n,), jnp.float32)
    acc = np.zeros(n, np.float64)
    trials = 200
    for seed in range(trials):
        # external uniforms: the interpreter's on-core PRNG is a zero stub
        w, s = pallas_quantize_pack(
            x, seed, bits=2, bucket_size=512, internal_rng=False, **INTERP
        )
        acc += np.asarray(
            pallas_unpack_dequantize(w, s, bits=2, bucket_size=512, n=n, **INTERP)
        )
    mean = acc / trials
    scale = float(jnp.linalg.norm(x))
    # std of the estimator is O(scale/levels/sqrt(trials))
    np.testing.assert_allclose(mean, np.asarray(x), atol=4 * scale / 3 / np.sqrt(trials))


def test_scales_are_bucket_l2_norms():
    x = jax.random.normal(jax.random.PRNGKey(3), (1024,), jnp.float32)
    _, scales = pallas_quantize_pack(x, 0, bits=2, bucket_size=512, **INTERP)
    expect = np.linalg.norm(np.asarray(x).reshape(2, 512), axis=1)
    np.testing.assert_allclose(np.asarray(scales), expect, rtol=1e-5)


def test_zero_input_gives_zero_output():
    x = jnp.zeros((600,), jnp.float32)
    w, s = pallas_quantize_pack(x, 5, bits=2, bucket_size=512, **INTERP)
    out = pallas_unpack_dequantize(w, s, bits=2, bucket_size=512, n=600, **INTERP)
    np.testing.assert_array_equal(np.asarray(out), np.zeros(600, np.float32))
