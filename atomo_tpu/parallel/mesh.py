"""Device mesh construction — the TPU-native replacement for the reference's
MPI world (mpirun -n <P+1> --hostfile, src/run_pytorch.sh:1).

The reference topology is 1 master + N workers over TCP
(src/distributed_nn.py:243-259). SPMD has no master: every chip runs the
same compiled program; the 'parameter server' is the replicated update.
Axis taxonomy (forward-looking — the reference is DP-only, SURVEY.md §2.1):

  dp  data parallelism (the reference's workers)           — first-class
  sp  sequence/context parallelism (ring/Ulysses)          — atomo_tpu.parallel.ring
  tp  tensor parallelism (Megatron-style sharded blocks)   — atomo_tpu.parallel.tp
  ep  expert parallelism (switch-MoE, a2a dispatch)        — atomo_tpu.parallel.moe
  pp  pipeline parallelism (GPipe microbatch schedule)     — atomo_tpu.parallel.pp
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    n_devices: Optional[int] = None,
    axes: Sequence[tuple[str, int]] = (),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh.

    Default: 1-D ('dp', n) over all visible devices. Pass ``axes`` as
    [('dp', 4), ('sp', 2)] for multi-axis layouts; sizes must multiply to
    the device count.
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    if not axes:
        axes = (("dp", len(devs)),)
    names = tuple(a for a, _ in axes)
    sizes = tuple(s for _, s in axes)
    if int(np.prod(sizes)) != len(devs):
        raise ValueError(f"mesh axes {axes} need {np.prod(sizes)} devices, have {len(devs)}")
    arr = np.asarray(devs).reshape(sizes)
    return Mesh(arr, names)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    return NamedSharding(mesh, P(axis))
