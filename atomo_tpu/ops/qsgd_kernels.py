"""Pallas TPU kernels for the QSGD quantize→bit-pack hot path.

Reference equivalent: the per-value uint64 shifting loops of
src/codings/qsgd.py:52-79 (pack) and :126-139 (unpack), run in numpy on the
host CPU. Here the whole encode — per-bucket scale (L2 for qsgd, max-norm
for terngrad), stochastic rounding (on-core PRNG, no key streams from HBM),
sign/magnitude coding, and uint32 word packing — is one fused VMEM-resident
kernel: the gradient is read from HBM exactly once and only the ~(1+b)/32-
sized words go back out, so encode bandwidth ≈ the payload size rather than
2x the dense gradient.

Wire format (shared with codecs.qsgd since round 2): words are laid out
per-bucket, shape (n_buckets, words_per_bucket) uint32, each bucket padded
to a whole number of words — floor(32/(1+b)) values per word, lane j at bit
j*(1+b). ``QsgdCodec`` emits and accepts this exact layout from both its
jnp path and these kernels, so the fused kernels ARE the production encode
on TPU (VERDICT r1 next-round #2); the jnp path is the test oracle.

RNG: passing ``u`` (external jax.random uniforms) makes the kernel
bit-identical to the jnp oracle; ``u=None`` draws from the on-core PRNG —
the zero-extra-bandwidth TPU hot path (per-block seeds: the block index is
folded into the seed so stochastic-rounding noise is independent across
blocks — round-1 ADVICE finding). Kernels run under the TPU-semantics
interpreter on CPU for tests (whose prng_random_bits is a zero stub, so
interpreter tests must pass explicit ``u``).

The grid tiles buckets; bucket_size is padded to the word boundary, so any
bucket_size works (the default 512 = reference --bucket-size).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def is_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _interpret_mode(interpret: bool):
    """True → the TPU-semantics interpreter (generic interpret mode has no
    CPU lowering for pltpu.prng_* primitives)."""
    return pltpu.InterpretParams() if interpret else False


def _bucket_scale(x, *, scheme: str):
    if scheme == "terngrad":
        return jnp.max(jnp.abs(x), axis=1, keepdims=True)
    return jnp.sqrt(jnp.sum(x * x, axis=1, keepdims=True))  # L2 per bucket


def _finish_quantize(x, u, words_ref, scales_ref, *, bits, levels, vpw, scheme):
    scale = _bucket_scale(x, scheme=scheme)
    safe = jnp.maximum(scale, jnp.finfo(jnp.float32).tiny)
    y = jnp.abs(x) / safe * levels
    lo = jnp.floor(y)
    frac = y - lo
    level = jnp.clip(lo + (u < frac), 0, levels).astype(jnp.uint32)
    sign = (x < 0).astype(jnp.uint32)
    codes = (sign << bits) | level  # (B_blk, bucket)

    bpv = bits + 1
    b_blk, bucket = codes.shape
    n_words = bucket // vpw  # bucket pre-padded to a vpw multiple by caller
    lanes = codes.reshape(b_blk, n_words, vpw)
    shifts = (jnp.arange(vpw, dtype=jnp.uint32) * bpv)[None, None, :]
    words_ref[:] = jnp.sum(lanes << shifts, axis=2, dtype=jnp.uint32)
    scales_ref[:] = scale


def _quantize_pack_kernel(
    x_ref, seed_ref, words_ref, scales_ref, *, bits, levels, vpw, scheme
):
    """One grid step: a block of buckets (B_blk, bucket) → packed words.
    Stochastic-rounding uniforms come from the on-core PRNG (no HBM key
    stream). The block index is folded into the seed so each block draws an
    independent stream (ADVICE r1: a shared scalar seed correlated the
    rounding noise across blocks)."""
    pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
    x = x_ref[:]  # (B_blk, bucket)
    rbits = pltpu.bitcast(pltpu.prng_random_bits(x.shape), jnp.uint32)
    # uniform in [0,1) from the top 24 bits (exact float32 representability)
    u = (rbits >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
    _finish_quantize(
        x, u, words_ref, scales_ref, bits=bits, levels=levels, vpw=vpw, scheme=scheme
    )


def _quantize_pack_kernel_ext(
    x_ref, u_ref, words_ref, scales_ref, *, bits, levels, vpw, scheme
):
    """External-uniform variant: u in [0,1) supplied as a second input —
    bit-identical to the jnp oracle when fed the same uniforms."""
    _finish_quantize(
        x_ref[:], u_ref[:], words_ref, scales_ref,
        bits=bits, levels=levels, vpw=vpw, scheme=scheme,
    )


def _unpack_dequantize_kernel(
    words_ref, scales_ref, out_ref, *, bits: int, levels: int, vpw: int
):
    bpv = bits + 1
    words = words_ref[:]  # (B_blk, n_words)
    b_blk, n_words = words.shape
    shifts = (jnp.arange(vpw, dtype=jnp.uint32) * bpv)[None, None, :]
    mask = jnp.uint32((1 << bpv) - 1)
    codes = ((words[:, :, None] >> shifts) & mask).reshape(b_blk, n_words * vpw)
    level = (codes & jnp.uint32(levels)).astype(jnp.float32)
    sign = 1.0 - 2.0 * ((codes >> bits) & 1).astype(jnp.float32)
    out_ref[:] = sign * level / levels * scales_ref[:]


def padded_bucket(bucket_size: int, bits: int) -> int:
    """Bucket size rounded up to a whole number of uint32 words."""
    vpw = 32 // (bits + 1)
    return -(-bucket_size // vpw) * vpw


def words_per_bucket(bucket_size: int, bits: int) -> int:
    vpw = 32 // (bits + 1)
    return padded_bucket(bucket_size, bits) // vpw


@partial(
    jax.jit,
    static_argnames=("bits", "bucket_size", "scheme", "interpret", "block"),
)
def pallas_quantize_pack(
    x: jax.Array,
    seed: jax.Array,
    u: Optional[jax.Array] = None,
    *,
    bits: int,
    bucket_size: int = 512,
    scheme: str = "qsgd",
    interpret: bool = False,
    block: int = 8,
):
    """Fused QSGD encode. x: flat float32; returns (words, scales) with
    words (n_buckets, words_per_bucket) uint32, scales (n_buckets,) f32 —
    the codec wire format.

    ``u=None`` draws stochastic-rounding uniforms from the on-core PRNG
    seeded per-block from ``seed`` (TPU hot path, zero extra bandwidth);
    passing ``u`` of shape (n_buckets, bucket_size) uses those uniforms
    (oracle-checkable; required under the interpreter, whose
    prng_random_bits is a zero stub)."""
    vpw = 32 // (bits + 1)
    n = x.shape[0]
    n_buckets = -(-n // bucket_size)
    blocks = -(-n_buckets // block)
    pad_buckets = blocks * block
    bucket_p = padded_bucket(bucket_size, bits)
    n_words = bucket_p // vpw

    grid_x = jnp.zeros((pad_buckets, bucket_p), jnp.float32)
    grid_x = grid_x.at[:n_buckets, :bucket_size].set(
        jnp.zeros((n_buckets * bucket_size,), jnp.float32).at[:n].set(x).reshape(
            n_buckets, bucket_size
        )
    )

    out_shape = (
        jax.ShapeDtypeStruct((pad_buckets, n_words), jnp.uint32),
        jax.ShapeDtypeStruct((pad_buckets, 1), jnp.float32),
    )
    out_specs = (
        pl.BlockSpec((block, n_words), lambda i: (i, 0)),
        pl.BlockSpec((block, 1), lambda i: (i, 0)),
    )
    levels = (1 << bits) - 1
    if u is None:
        seeds = jnp.asarray(seed, jnp.int32).reshape(1)
        words, scales = pl.pallas_call(
            partial(
                _quantize_pack_kernel,
                bits=bits, levels=levels, vpw=vpw, scheme=scheme,
            ),
            out_shape=out_shape,
            grid=(blocks,),
            in_specs=[
                pl.BlockSpec((block, bucket_p), lambda i: (i, 0)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=out_specs,
            interpret=_interpret_mode(interpret),
        )(grid_x, seeds)
    else:
        grid_u = jnp.zeros((pad_buckets, bucket_p), jnp.float32)
        grid_u = grid_u.at[:n_buckets, :bucket_size].set(u)
        words, scales = pl.pallas_call(
            partial(
                _quantize_pack_kernel_ext,
                bits=bits, levels=levels, vpw=vpw, scheme=scheme,
            ),
            out_shape=out_shape,
            grid=(blocks,),
            in_specs=[
                pl.BlockSpec((block, bucket_p), lambda i: (i, 0)),
                pl.BlockSpec((block, bucket_p), lambda i: (i, 0)),
            ],
            out_specs=out_specs,
            interpret=_interpret_mode(interpret),
        )(grid_x, grid_u)
    return words[:n_buckets], scales[:n_buckets, 0]


@partial(jax.jit, static_argnames=("bits", "bucket_size", "n", "interpret", "block"))
def pallas_unpack_dequantize(
    words: jax.Array,
    scales: jax.Array,
    *,
    bits: int,
    bucket_size: int = 512,
    n: int,
    interpret: bool = False,
    block: int = 8,
):
    """Fused QSGD decode: (words, scales) → flat float32 of length n."""
    vpw = 32 // (bits + 1)
    n_buckets = scales.shape[0]
    blocks = -(-n_buckets // block)
    pad_buckets = blocks * block
    bucket_p = padded_bucket(bucket_size, bits)
    n_words = bucket_p // vpw

    w = jnp.zeros((pad_buckets, n_words), jnp.uint32).at[:n_buckets].set(words)
    s = jnp.zeros((pad_buckets, 1), jnp.float32).at[:n_buckets, 0].set(scales)

    vals = pl.pallas_call(
        partial(
            _unpack_dequantize_kernel, bits=bits, levels=(1 << bits) - 1, vpw=vpw
        ),
        out_shape=jax.ShapeDtypeStruct((pad_buckets, bucket_p), jnp.float32),
        grid=(blocks,),
        in_specs=[
            pl.BlockSpec((block, n_words), lambda i: (i, 0)),
            pl.BlockSpec((block, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block, bucket_p), lambda i: (i, 0)),
        interpret=_interpret_mode(interpret),
    )(w, s)
    return vals[:n_buckets, :bucket_size].reshape(-1)[:n]
