"""Per-layer spectra + the ATOMO water-filling byte allocator.

THE VARIANCE MODEL (stated, tested): the repo's default sampler is
``fixed_k`` importance sampling with replacement — k atoms drawn with
q_i = s_i / sum(s), coefficients s_i / (k q_i). Its estimator error has

    E ||ghat - g||_F^2  =  ( (sum_i s_i)^2 - sum_i s_i^2 ) / k  =  A / k

(the cross terms vanish by unbiasedness; A is a property of the layer's
singular-value spectrum alone). So the total variance of a per-layer
allocation {k_l} is sum_l A_l / k_l, and minimizing it under a wire-byte
budget sum_l bytes_l(k_l) <= B is the paper's water-filling problem with
diminishing returns per atom — solved here by an exact greedy: give the
next atom slot to the layer with the best marginal variance reduction
per byte, tie-broken by leaf index so the allocation is a PURE
deterministic function of (spectra, budget).

Degenerate points of the same dial (tested as identities):

  * ``uniform``: every adaptive layer at the base rank — byte-for-byte
    today's fixed-budget behavior (the wrapper with uniform ranks
    produces bit-identical payloads to the plain codec).
  * spend-everything: an unbounded budget drives every layer to full
    rank, where the codec's dense-fallback rule (payload >= dense)
    ships the exact DensePayload — i.e. ``--on-diverge densify``'s
    remedy, reached as the limit of the budget dial.

Byte pricing is the codec's OWN static accounting
(``SvdCodec.leaf_payload_bytes`` — the clamped actual, pinned equal to
``jax.eval_shape`` over the real encode in tests/test_comm_model.py),
so a predicted allocation total and the executed program's
``msg_bytes`` agree to the byte: the bench config 16 wire-match gate.

THE QSGD BIT LAW (the second water-filling target, same machinery,
different pricing/variance pair): stochastic rounding of |x|/s onto
L(b) = 2^b - 1 levels has per-value error (s/L)^2 f(1-f) with f the
fractional level position. Under the uniform-residual model
(E f(1-f) = 1/6 — exact in the fine-grid limit L >> |x| sqrt(n)/s,
the regime where QSGD's own variance bound is tight), a bucketed leaf
obeys

    E ||ghat - g||_F^2  =  B_l / (2^b - 1)^2,
    B_l = (1/6) sum_buckets n_b * s_b^2

(n_b = real values in the bucket, s_b = its L2 scale; B_l is a
property of the gradient's bucket norms alone, and the 1/6 constant
cancels in every allocation ratio, so the greedy ordering does not
depend on the residual model). The knob is the leaf's bit width b,
priced by the codec's own packed-word accounting
(``QsgdCodec.leaf_payload_bytes``); unlike SVD there is NO dense
fallback in the wire format, so the solver never claims an exact-wire
zero-variance point — it simply refuses to buy bits whose payload
would meet or exceed the dense bytes. The uniform degenerate point is
every leaf at the codec's configured ``bits`` — byte-for-byte the
plain codec. TernGrad's max-norm scale + sigma clip has a DIFFERENT
error law (not stated here) and stays rejected.

Scope (honest): the solver allocates SVD ranks for the ``fixed_k``
sampler and QSGD bit widths for the L2-scale ``qsgd`` scheme — the
two families whose variance laws are stated above. Every other
codec/sampler pair is rejected at the CLI until its law is stated too.
"""

from __future__ import annotations

import dataclasses
import heapq
import math
from typing import Optional, Sequence


@dataclasses.dataclass(frozen=True)
class LayerSpectrum:
    """One leaf's allocation inputs, canonical flatten order.

    ``a`` is the variance numerator — A = (sum s)^2 - sum s^2 of the
    leaf's matricized spectrum for SVD ranks, or B = (1/6) sum n_b s_b^2
    of its bucket norms for QSGD bits; ``r_full`` caps the useful knob
    (full rank, or the last bit width whose payload still beats dense);
    ``adaptive`` is False for leaves with no knob — SVD leaves shipped
    dense at ANY rank (zero variance, fixed payload) and QSGD leaves
    whose 1-bit payload already meets dense (they still ship quantized
    at the base bits and contribute variance there, but the solver
    never moves them)."""

    index: int
    name: str
    shape: tuple
    dense_bytes: int
    r_full: int
    a: float
    base_k: int
    adaptive: bool


@dataclasses.dataclass(frozen=True)
class Allocation:
    """A solved per-layer budget split (the artifact's epoch body)."""

    mode: str  # "uniform" | "variance"
    ks: tuple  # per-leaf knob (SVD rank or QSGD bits), flatten order
    payload_bytes: int  # predicted total wire bytes (clamped actual)
    budget_bytes: int  # the budget the solver was given
    predicted_variance: float  # sum of the stated per-leaf law
    epoch: int = 0

    def describe(self) -> str:
        return (
            f"budget allocation ({self.mode}, epoch {self.epoch}): "
            f"{self.payload_bytes / 1e6:.4f} MB/replica predicted wire "
            f"of a {self.budget_bytes / 1e6:.4f} MB budget, predicted "
            f"variance {self.predicted_variance:.6g}"
        )


def knob_name(codec) -> str:
    """Which field the allocator waters: ``rank`` (SVD fixed_k) or
    ``bits`` (QSGD). The dispatch key for pricing AND variance law."""
    return "rank" if hasattr(codec, "rank") else "bits"


def _with_knob(codec, k: int):
    import dataclasses as _dc

    return _dc.replace(codec, **{knob_name(codec): int(k)})


def variance_at(codec, a: float, k: int) -> float:
    """The stated per-leaf law at knob value ``k``: A/k for SVD ranks,
    B/(2^b - 1)^2 for QSGD bits (module docstring)."""
    if knob_name(codec) == "bits":
        lv = float((1 << int(k)) - 1)
        return a / (lv * lv)
    return a / k


def _leaf_bytes(codec, spectrum: LayerSpectrum, k: int) -> int:
    """Wire bytes of this leaf at knob ``k`` — the codec's own clamped
    static pricing (dense fallback included, where the format has one)."""
    return int(_with_knob(codec, k).leaf_payload_bytes(spectrum.shape))


def measure_spectra(codec, grads) -> list:
    """Per-leaf :class:`LayerSpectrum` from a PROBE gradient tree.

    ``grads`` is a host (or device) gradient pytree — one backward pass
    over a fixed batch (``sparse.hybrid.probe_gradient``; callers must
    feed a batch that does not advance the training stream's shuffle
    RNG, the --aggregate auto precedent). Each leaf is matricized with
    the CODEC's own resize policy and its full singular-value spectrum
    taken host-side (numpy — probe-time only, never traced; the
    matrices are capped at ``max_min_dim`` on the small side, so this
    is cheap). Pure given the gradient: same probe, same spectra.

    A ``bits`` codec (QSGD) dispatches to the bucket-norm measurement —
    same LayerSpectrum container, the B_l numerator of the module
    docstring's bit law instead of the SVD A_l."""
    if knob_name(codec) == "bits":
        return _measure_bit_spectra(codec, grads)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from atomo_tpu.codecs.svd import resize_to_2d

    flat, _ = jax.tree_util.tree_flatten_with_path(grads)
    out = []
    for i, (path, leaf) in enumerate(flat):
        name = jax.tree_util.keystr(path)
        shape = tuple(int(d) for d in leaf.shape)
        arr = np.asarray(jax.device_get(leaf), dtype=np.float32)
        dense_b = int(arr.size) * 4
        mat, _, _pad = resize_to_2d(
            jnp.asarray(arr),
            policy=codec.reshape,
            max_min_dim=codec.max_min_dim,
        )
        mat = np.asarray(jax.device_get(mat))
        r_full = int(min(mat.shape))
        s = np.linalg.svd(mat, compute_uv=False)
        a = float(np.sum(s)) ** 2 - float(np.sum(s * s))
        base_k = max(min(int(codec.rank), r_full), 1)
        # adaptive iff rank 1 already beats dense — otherwise the codec
        # ships this leaf dense at EVERY rank and there is no knob
        adaptive = not _always_dense(codec, shape)
        out.append(
            LayerSpectrum(
                index=i, name=name, shape=shape, dense_bytes=dense_b,
                r_full=r_full, a=max(a, 0.0), base_k=base_k,
                adaptive=adaptive,
            )
        )
    return out


#: Bit widths past this point buy nothing: float32 inputs carry 24
#: significand bits, and the packed (1+b)-bit layout needs b+1 <= 32.
MAX_BITS = 16


def _measure_bit_spectra(codec, grads) -> list:
    """Per-leaf :class:`LayerSpectrum` for QSGD bit allocation.

    The numerator is the bit law's B_l = (1/6) sum_b n_b s_b^2 over the
    leaf's REAL (unpadded) bucket contents — n_b values and L2 scale
    s_b per bucket, exactly the bucketing :meth:`QsgdCodec.encode`
    performs, measured host-side from the probe gradient (no extra
    device work). ``r_full`` is the last bit width (<= MAX_BITS) whose
    payload still beats the leaf's dense bytes; ``base_k`` is the
    codec's configured ``bits`` UNCLAMPED — the uniform point must be
    byte-for-byte the plain codec, which never falls back to dense.
    TernGrad is refused: its max-norm scale + sigma clip follows a
    different error law that the module docstring does not state."""
    import jax
    import numpy as np

    if getattr(codec, "scheme", "qsgd") != "qsgd":
        raise ValueError(
            f"bit allocation needs the L2-scale qsgd scheme, got "
            f"{codec.scheme!r}: the terngrad max-norm law is not stated"
        )
    flat, _ = jax.tree_util.tree_flatten_with_path(grads)
    out = []
    for i, (path, leaf) in enumerate(flat):
        name = jax.tree_util.keystr(path)
        shape = tuple(int(d) for d in leaf.shape)
        arr = np.asarray(jax.device_get(leaf), dtype=np.float32).reshape(-1)
        dense_b = int(arr.size) * 4
        bs = int(codec.bucket_size)
        b_num = 0.0
        for start in range(0, arr.size, bs):
            chunk = arr[start:start + bs]
            s_b = float(np.linalg.norm(chunk))
            b_num += chunk.size * s_b * s_b
        b_num /= 6.0
        adaptive = not _always_dense(codec, shape)
        r_full = 1
        for b in range(1, MAX_BITS + 1):
            if _with_knob(codec, b).leaf_payload_bytes(shape) < dense_b:
                r_full = b
        base_k = int(codec.bits)
        if not adaptive:
            r_full = base_k
        out.append(
            LayerSpectrum(
                index=i, name=name, shape=shape, dense_bytes=dense_b,
                r_full=r_full, a=max(b_num, 0.0), base_k=base_k,
                adaptive=adaptive,
            )
        )
    return out


def _always_dense(codec, shape) -> bool:
    """Is this leaf knob-less? SVD: dense-fallback already at rank 1
    (i.e. at every rank). QSGD: the 1-bit payload already meets the
    dense bytes, so no bit width can beat dense wire."""
    shape = tuple(shape)
    if knob_name(codec) == "bits":
        dense = 4
        for d in shape:
            dense *= int(d)
        return _with_knob(codec, 1).leaf_payload_bytes(shape) >= dense
    return bool(_with_knob(codec, 1)._dense_fallback(shape))


def spectra_from_qerr2(
    spectra: Sequence[LayerSpectrum],
    qerr2_mean: Sequence[float],
    current_ks: Sequence[int],
    codec=None,
) -> list:
    """Fold an observed per-layer q_err2 series into fresh spectra.

    Under the stated law E q_err2_l = A_l / k_l (SVD ranks; for QSGD
    bits the same inversion reads B_l ~= mean(q_err2_l) * (2^b - 1)^2
    when ``codec`` is a bits codec), the mean of the recorded
    ``--obs-quality`` series at the CURRENT allocation is an unbiased
    online estimate of the numerator — no extra
    SVDs, the streamed-encode leaf visits already paid for the signal.
    Non-adaptive leaves keep their measured A (they have no knob and a
    lossless/dense leaf reads q_err2 = 0 anyway); an unusable sample
    (non-finite, negative) keeps the prior A — a gap is not a sample,
    the drift-detector convention.

    A leaf whose CURRENT payload sits at the exact dense fallback also
    keeps its prior A (pass ``codec`` to enable the check — the
    retuner does): its observed q_err2 is exactly 0 because the wire
    is exact, NOT because its spectrum mass vanished, and folding that
    0 into A = 0 would let the re-solve strip the leaf back to rank 1
    "for free" while the hysteresis sees no predicted regression —
    the demote/re-promote oscillation the boundary re-solve must not
    exhibit (mirrors predicted_variance's zero-variance special
    case)."""
    out = []
    for l in spectra:
        a = l.a
        if l.adaptive and l.index < len(qerr2_mean):
            q = qerr2_mean[l.index]
            k = max(int(current_ks[l.index]), 1)
            at_dense = (
                codec is not None
                and _leaf_bytes(codec, l, k) >= l.dense_bytes
            )
            if (
                not at_dense
                and q is not None
                and math.isfinite(float(q))
                and float(q) >= 0
            ):
                if codec is not None and knob_name(codec) == "bits":
                    # invert the bit law: B = q_err2 * (2^b - 1)^2
                    a = float(q) / variance_at(codec, 1.0, k)
                else:
                    a = float(q) * k
        out.append(dataclasses.replace(l, a=a))
    return out


def uniform_ks(spectra: Sequence[LayerSpectrum]) -> tuple:
    """The degenerate uniform point: every leaf at its (clamped) base
    rank — today's fixed-budget behavior, byte for byte."""
    return tuple(l.base_k for l in spectra)


def predicted_variance(
    spectra: Sequence[LayerSpectrum], ks: Sequence[int], codec=None
) -> float:
    """Total predicted estimator variance under the stated per-leaf
    law. SVD ranks: sum_l A_l / k_l over adaptive leaves (a leaf whose
    payload at k_l reaches the dense fallback is exact — variance 0 —
    when ``codec`` is given to price it; non-adaptive leaves ship dense,
    zero variance). QSGD bits: sum_l B_l / (2^b - 1)^2 over EVERY leaf —
    the wire format has no exact point, and a knob-less leaf still
    quantizes at its base bits."""
    bits = codec is not None and knob_name(codec) == "bits"
    total = 0.0
    for l in spectra:
        k = max(int(ks[l.index]), 1)
        if bits:
            total += variance_at(codec, l.a, k)
            continue
        if not l.adaptive:
            continue
        if codec is not None and _leaf_bytes(codec, l, k) >= l.dense_bytes:
            continue  # dense fallback ships exact: zero variance
        total += l.a / k
    return total


def allocation_payload_bytes(
    codec, spectra: Sequence[LayerSpectrum], ks: Sequence[int]
) -> int:
    """Predicted total wire bytes of an allocation — the clamped-actual
    per-leaf pricing summed (what bench config 16's wire-match gate
    compares against the executed program's msg_bytes)."""
    return int(
        sum(_leaf_bytes(codec, l, ks[l.index]) for l in spectra)
    )


def allocation_leaf_budgets(
    codec, spectra: Sequence[LayerSpectrum], ks: Sequence[int]
) -> list:
    """Per-leaf ``(dense_bytes, payload_bytes)`` pairs in canonical
    order — ``comm_model.leaf_budget_totals`` input, so the ``+ab``
    autopilot candidates are priced from the SAME per-leaf sums the
    executed program reports (the PR-12 honest-accounting invariant)."""
    return [
        (int(l.dense_bytes), _leaf_bytes(codec, l, ks[l.index]))
        for l in spectra
    ]


def solve_allocation(
    codec,
    spectra: Sequence[LayerSpectrum],
    budget_bytes: Optional[int] = None,
    mode: str = "variance",
    epoch: int = 0,
) -> Allocation:
    """Distribute ``budget_bytes`` of wire across layers to minimize
    total estimator variance (module docstring). PURE and deterministic:
    the greedy's priority queue breaks ties by leaf index, so the same
    spectra and budget always yield the same allocation (tested).

    ``budget_bytes=None`` (or <= 0) spends exactly the uniform
    allocation's total — the equal-total-wire-bytes comparison bench
    config 16 publishes. ``mode="uniform"`` skips the solve and returns
    the degenerate point. A budget at or past every layer's dense cost
    returns the spend-everything point (all-dense fallback — the
    densify remedy as the dial's limit)."""
    n = len(spectra)
    base = uniform_ks(spectra)
    uniform_total = allocation_payload_bytes(codec, spectra, base)
    if budget_bytes is None or int(budget_bytes) <= 0:
        budget_bytes = uniform_total
    budget_bytes = int(budget_bytes)
    if mode == "uniform":
        return Allocation(
            mode="uniform", ks=base, payload_bytes=uniform_total,
            budget_bytes=budget_bytes,
            predicted_variance=predicted_variance(spectra, base, codec),
            epoch=epoch,
        )
    if mode != "variance":
        raise ValueError(
            f"unknown allocation mode {mode!r}: expected uniform | variance"
        )
    ks = [1] * n
    spent = 0
    for l in spectra:
        if not l.adaptive:
            ks[l.index] = l.base_k  # fixed leaves: priced, never re-ranked
        spent += _leaf_bytes(codec, l, ks[l.index])
    # The greedy: each move raises one adaptive leaf's knob by one; its
    # gain is the stated law's marginal drop — SVD ranks:
    # A (1/k - 1/(k+1)), or the FULL remaining A/k when the next rank
    # crosses into the dense fallback (exact: variance drops to zero);
    # QSGD bits: B (1/L(b)^2 - 1/L(b+1)^2) with NO dense-crossing move
    # (the format has no exact point — a bit width whose payload meets
    # dense is simply never bought) — per delta-byte. heapq is a
    # min-heap: push -gain/byte.
    bits_knob = knob_name(codec) == "bits"
    heap: list = []

    def push_move(l: LayerSpectrum, k: int):
        if k >= l.r_full:
            return
        here = _leaf_bytes(codec, l, k)
        if here >= l.dense_bytes:
            return  # already at the exact dense fallback: nothing to buy
        nxt = _leaf_bytes(codec, l, k + 1)
        d_bytes = nxt - here
        if bits_knob:
            if nxt >= l.dense_bytes:
                return  # never pay dense wire for a lossy payload
            gain = variance_at(codec, l.a, k) - variance_at(
                codec, l.a, k + 1
            )
        elif nxt >= l.dense_bytes:
            gain = l.a / k  # crossing into the exact dense fallback
        else:
            gain = l.a * (1.0 / k - 1.0 / (k + 1))
        if d_bytes <= 0:
            # a free (or byte-saving) rank raise — take it greedily with
            # an infinite ratio; ties still break by index
            ratio = math.inf
        else:
            ratio = gain / d_bytes
        heapq.heappush(heap, (-ratio, l.index, k, d_bytes))

    by_index = {l.index: l for l in spectra}
    for l in spectra:
        if l.adaptive:
            push_move(l, ks[l.index])
    while heap:
        neg_ratio, idx, k, d_bytes = heapq.heappop(heap)
        if ks[idx] != k:
            continue  # stale move (the leaf advanced past it)
        if spent + d_bytes > budget_bytes:
            continue  # unaffordable; cheaper moves may still fit
        ks[idx] = k + 1
        spent += d_bytes
        push_move(by_index[idx], k + 1)
    ks_t = tuple(ks)
    return Allocation(
        mode="variance", ks=ks_t,
        payload_bytes=allocation_payload_bytes(codec, spectra, ks_t),
        budget_bytes=budget_bytes,
        predicted_variance=predicted_variance(spectra, ks_t, codec),
        epoch=epoch,
    )
