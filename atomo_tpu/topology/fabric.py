"""Two-tier fabric description — the topology the comm model can price.

``utils/comm_model.resolve_fabric`` returns ONE scalar bandwidth (the
slowest link on the gradient path). On a two-tier mesh that prices ICI
hops at DCN bandwidth: a flat advisory quoting one blended number cannot
say "the inner dense psum costs 1.7 ms over ICI while the outer factor
gather costs 9 ms over DCN", which is exactly the arithmetic that decides
whether re-compressing at the boundary wins. :class:`TwoTierFabric` keeps
the two tiers separate and the prediction honest per tier.

Parsing (``resolve_two_tier``) extends the ONE-parser rule: each tier
token goes through ``comm_model.resolve_fabric``'s grammar (named preset
or positive finite GB/s), so the CLI advisory, the planner, and the
autopilot cannot disagree about what a fabric string means. Accepted
forms for ``--fabric`` on a two-tier mesh:

  ``auto``            inner = ici preset, outer = dcn preset
  ``<outer>``         one token names the OUTER (slow) tier; inner stays
                      the ici preset (the historical single-scalar
                      meaning: the slowest link on the gradient path)
  ``<inner>:<outer>`` both tiers explicit, e.g. ``ici:eth10g`` or
                      ``45:1.25`` (per-chip GB/s numbers)
  ``measured``        both tiers from the startup fabric probe's
                      ``fabric_probe.json`` (measured bandwidths AND
                      per-hop latencies — obs.fabric.measured_two_tier)

Latency anchors are stated estimates (per-hop ICI ~1 us, DCN ~25 us —
the order-of-magnitude split between on-chip links and a routed
datacenter network), included so many-hop collectives on the slow tier
are not priced as free below the bandwidth floor; the probe ladder
corrects them like every other anchor.
"""

from __future__ import annotations

import dataclasses

from atomo_tpu.utils.comm_model import FABRICS, resolve_fabric

# stated per-hop latency estimates (seconds); see module docstring
ICI_HOP_LATENCY_S = 1e-6
DCN_HOP_LATENCY_S = 25e-6


@dataclasses.dataclass(frozen=True)
class TwoTierFabric:
    """Per-tier bandwidth/latency + the (outer, inner) group shape.

    ``inner_*`` is the fast tier (ICI within a slice/host): groups of
    ``inner_ways`` chips with an all-to-all-capable fast interconnect.
    ``outer_*`` is the slow tier (DCN/Ethernet across slices):
    ``outer_ways`` groups whose representatives exchange over the scarce
    fabric. ``outer_ways * inner_ways`` == the mesh's data-parallel chip
    count. Bandwidths are per-chip effective ring bandwidths (bytes/s),
    the same convention as ``comm_model.FABRICS``.
    """

    inner_bw: float
    outer_bw: float
    inner_ways: int
    outer_ways: int
    inner_latency_s: float = ICI_HOP_LATENCY_S
    outer_latency_s: float = DCN_HOP_LATENCY_S
    inner_label: str = "ici"
    outer_label: str = "dcn"

    def tier_ways(self, tier: str) -> int:
        return self.inner_ways if tier == "inner" else self.outer_ways

    def tier_bw(self, tier: str) -> float:
        return self.inner_bw if tier == "inner" else self.outer_bw

    def tier_time_s(self, nbytes: float, tier: str, hops: int = 0) -> float:
        """Seconds to move ``nbytes`` per chip over one tier, plus the
        per-hop latency floor for ``hops`` serialized collective hops
        (0 = bandwidth term only)."""
        lat = (
            self.inner_latency_s if tier == "inner" else self.outer_latency_s
        )
        return float(nbytes) / self.tier_bw(tier) + lat * max(int(hops), 0)

    def describe(self) -> str:
        """One advisory-ready line: both tiers with their group shape and
        bandwidth — the per-tier numbers a blended scalar cannot carry."""
        return (
            f"inner {self.inner_ways}x {self.inner_label} @ "
            f"{self.inner_bw / 1e9:.2f} GB/s/chip, outer {self.outer_ways}x "
            f"{self.outer_label} @ {self.outer_bw / 1e9:.2f} GB/s/chip"
        )


def _tier_label(token: str) -> str:
    return token if token in FABRICS else f"{token}GBps"


def resolve_two_tier(
    fabric: str,
    *,
    dcn_ways: int,
    n_dev: int,
    n_proc: int = 1,
    measured=None,
) -> TwoTierFabric:
    """Parse a ``--fabric`` value into a :class:`TwoTierFabric` for a mesh
    of ``n_dev`` data-parallel chips split into ``dcn_ways`` slow-fabric
    groups. Grammar in the module docstring; every token reuses
    :func:`comm_model.resolve_fabric` so the two parsers cannot drift.
    Raises ValueError (same contract as resolve_fabric) on a bad token or
    a group shape that does not divide the mesh.

    ``measured`` (the ``fabric_probe.json`` document) serves two forms:
    the full ``measured`` token builds BOTH tiers from the probe —
    measured bandwidths and measured per-hop latencies, labels
    ``measured_ici``/``measured_dcn`` (obs.fabric.measured_two_tier) —
    and a ``measured`` TOKEN inside ``<inner>:<outer>`` resolves through
    ``resolve_fabric``'s slowest-tier convention like any other token."""
    k = int(dcn_ways)
    n = int(n_dev)
    if not (1 < k <= n) or n % k:
        raise ValueError(
            f"two-tier fabric needs 1 < dcn_ways <= n_dev with "
            f"dcn_ways | n_dev; got dcn_ways={k}, n_dev={n}"
        )
    if fabric == "measured":
        from atomo_tpu.obs.fabric import measured_two_tier

        if measured is None:
            # the same instruction resolve_fabric's scalar path gives
            raise ValueError(
                "--fabric measured resolves from a fabric_probe.json "
                "artifact and this surface has none — run `train "
                "--fabric measured` with a --train-dir so the startup "
                "probe measures both tiers (--dcn-ways set)"
            )
        return measured_two_tier(measured, dcn_ways=k, n_dev=n)
    if fabric == "auto":
        inner_tok, outer_tok = "ici", "dcn"
    elif ":" in fabric:
        inner_tok, _, outer_tok = fabric.partition(":")
        if not inner_tok or not outer_tok:
            raise ValueError(
                f"--fabric {fabric!r}: two-tier form is <inner>:<outer> "
                "with each side a named preset or a positive GB/s number"
            )
    else:
        # historical single-scalar meaning: the slowest link on the
        # gradient path = the OUTER tier; inner keeps the ici preset
        inner_tok, outer_tok = "ici", fabric
    return TwoTierFabric(
        inner_bw=resolve_fabric(inner_tok, n_proc=1, measured=measured),
        outer_bw=resolve_fabric(outer_tok, n_proc=n_proc, measured=measured),
        inner_ways=n // k,
        outer_ways=k,
        inner_label=_tier_label(inner_tok),
        outer_label=_tier_label(outer_tok),
    )
