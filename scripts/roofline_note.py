"""Roofline analysis for the bench ladder (VERDICT r3 weak #6: "MFU is low
everywhere and unexamined — no roofline note, nothing saying what the
ceiling is").

For each BASELINE.md config this compiles the EXACT step program bench.py
times and asks XLA's cost analysis for FLOPs and bytes accessed, then
applies the v5e roofline:

    t_lb  = max(flops / peak_flops, bytes / hbm_bw)
    MFU ceiling = (flops / peak_flops) / t_lb

A program whose arithmetic intensity (flops/byte) sits below the ridge
point (peak_flops / hbm_bw ≈ 240 flops/byte on v5e: 197e12 / 819e9) is
HBM-bound and CANNOT reach high MFU no matter the schedule — that is a
property of CIFAR-sized convs at batch 128, not a scheduling failure.
The note prints per config: flops, bytes, intensity, bound type, t_lb,
the implied MFU ceiling, and (where round-3 hardware rows exist) the
measured time as a fraction of t_lb ("roofline efficiency" — how close
the program runs to its own physics, which is the number a schedule can
actually influence).

Caveats (stated in the artifact): cost_analysis is XLA's HLO-level
estimate on the compiling backend (CPU here when no TPU is attached),
and its bytes-accessed counts PRE-FUSION traffic — every HLO's operands
and outputs as if materialized — so it OVERSTATES real HBM bytes and the
bytes-side "bound" is a naive-traffic estimate, not a true floor
(observed: config 2 runs 1.5x FASTER than it, i.e. fusion removed ≥40%
of the counted traffic). The flops side and the intensity ORDERING
across configs remain honest; treat mfu_ceiling as indicative, and
roofline_efficiency > 1 as a direct measurement of fusion savings.

Usage: python scripts/roofline_note.py [--configs 1,2,3,4,5]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PEAK_TFLOPS = 197.0  # v5e bf16 MXU
HBM_GBPS = 819.0  # v5e HBM bandwidth
# round-3 measured scan-fenced ms/step (artifacts/BENCH_ONCHIP_r3.md) for
# the efficiency column; configs 4/5 have only superseded-protocol numbers
MEASURED_R3_MS = {1: 1.058, 2: 8.86, 3: 6.155}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", type=str, default="1,2,3,4,5")
    ap.add_argument("--out", type=str, default="artifacts")
    args = ap.parse_args()

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax
    import jax.numpy as jnp

    from bench import CONFIGS
    from atomo_tpu.codecs import get_codec
    from atomo_tpu.models import get_model
    from atomo_tpu.training import create_state, make_optimizer, make_train_step

    ridge = PEAK_TFLOPS * 1e12 / (HBM_GBPS * 1e9)
    rows = []
    for c in [int(x) for x in args.configs.split(",")]:
        cfg = CONFIGS[c]
        model = get_model(cfg["network"], 10)
        opt = make_optimizer("sgd", lr=0.01, momentum=0.9)
        rng = jax.random.PRNGKey(0)
        h, w, ch = cfg["input"]
        images = jax.random.uniform(rng, (cfg["batch"], h, w, ch), jnp.float32)
        labels = jax.random.randint(rng, (cfg["batch"],), 0, 10)
        state = create_state(model, opt, rng, images)
        codec = get_codec(cfg["code"], svd_rank=cfg.get("rank", 3),
                          quantization_level=4)
        step = make_train_step(model, opt, codec=codec)
        compiled = step.lower(state, jax.random.PRNGKey(1), images, labels).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0))
        bytes_acc = float(ca.get("bytes accessed", 0.0))
        ai = flops / max(bytes_acc, 1.0)
        t_flops = flops / (PEAK_TFLOPS * 1e12)
        t_bytes = bytes_acc / (HBM_GBPS * 1e9)
        t_lb = max(t_flops, t_bytes)
        row = {
            "config": c,
            "metric": cfg["metric"],
            "flops": flops,
            "bytes_accessed": bytes_acc,
            "arith_intensity": round(ai, 1),
            "bound": "hbm" if t_bytes > t_flops else "mxu",
            "t_lb_ms": round(t_lb * 1e3, 3),
            "mfu_ceiling": round(t_flops / t_lb, 3),
        }
        if c in MEASURED_R3_MS:
            row["measured_r3_ms"] = MEASURED_R3_MS[c]
            row["roofline_efficiency"] = round(t_lb * 1e3 / MEASURED_R3_MS[c], 3)
        rows.append(row)
        print(json.dumps(row), flush=True)

    os.makedirs(args.out, exist_ok=True)
    with open(os.path.join(args.out, "ROOFLINE.json"), "w") as f:
        json.dump({"ridge_flops_per_byte": round(ridge, 1), "rows": rows}, f, indent=1)
    lines = [
        "# Roofline: what MFU can these configs even reach? (VERDICT r3 weak #6)",
        "",
        f"v5e: peak {PEAK_TFLOPS} TFLOP/s (bf16 MXU), HBM {HBM_GBPS} GB/s →",
        f"ridge point ≈ {ridge:.0f} flops/byte. A program below the ridge is",
        "HBM-bound: its MFU ceiling is intensity/ridge regardless of schedule.",
        "FLOPs/bytes are XLA cost-analysis estimates of the exact compiled",
        "step (codec included); see scripts/roofline_note.py caveats.",
        "",
        "| cfg | metric | GFLOPs | MB accessed | flops/byte | bound | t_lb ms | MFU ceiling | measured r3 ms | roofline eff |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            "| {config} | {metric} | {gf:.1f} | {mb:.0f} | {ai} | {bound} | "
            "{tlb} | {ceil} | {meas} | {eff} |".format(
                gf=r["flops"] / 1e9, mb=r["bytes_accessed"] / 1e6,
                ai=r["arith_intensity"], tlb=r["t_lb_ms"],
                ceil=r["mfu_ceiling"],
                meas=r.get("measured_r3_ms", "—"),
                eff=r.get("roofline_efficiency", "—"),
                **r,
            )
        )
    lines += [
        "",
        "Reading: bytes are XLA's PRE-FUSION count, so `t_lb` from the",
        "bytes side is a naive-traffic estimate, not a hard floor —",
        "`roofline eff` > 1 (config 2) directly measures how much traffic",
        "fusion eliminated. The durable conclusions: every ladder config",
        "sits far BELOW the ~240 flops/byte ridge, so all are HBM-bound at",
        "batch-128 CIFAR shapes and their MFU ceilings are single-digit to",
        "low-double-digit percent BY PHYSICS (small spatial dims, BN and",
        "elementwise traffic), not by scheduling; the measured 'low MFU'",
        "VERDICT r3 flagged is the expected operating point. Raising MFU",
        "requires bigger batches/models, not a different schedule.",
    ]
    with open(os.path.join(args.out, "ROOFLINE.md"), "w") as f:
        f.write("\n".join(lines) + "\n")
    print(json.dumps({"wrote": "artifacts/ROOFLINE.md", "rows": len(rows)}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
