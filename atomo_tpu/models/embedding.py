"""Embedding-tower model family: the row-sparse workload.

The "millions of users" workloads the ROADMAP targets are
recommendation/retrieval-shaped: a lookup table whose per-step gradient
touches only the rows the batch accessed, feeding a small dense tower.
No reference analogue (the reference zoo is CV-only); the family exists
to exercise the sparse exchange subsystem (sparse/) on gradients whose
row sparsity is structural, not incidental.

Input convention: the data pipeline feeds ``(batch, slots)`` float32 row
ids (the Zipf sampler, data/zipf.py — float32 so the existing
BatchIterator/shard_batch/checkpoint machinery carries them unchanged;
ids are exact in f32 up to 2^24, enforced at construction). The model
casts to int32 and looks rows up with ``jnp.take``, whose backward is a
scatter-add — each sample contributes gradient to at most ``slots`` rows,
the bound ``sparse.hybrid.infer_row_bounds`` turns into the lossless row
budget. The table param is named ``table`` on purpose: the hybrid
planner's stated name-matching (TABLE_NAME_HINTS) keys off it.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

# float32 holds integers exactly only up to 2^24: a bigger table would
# silently alias row ids in the data pipeline's float batches
MAX_F32_EXACT_ROWS = 1 << 24


class EmbeddingTower(nn.Module):
    """Table lookup -> concat -> 2-layer dense tower -> classes."""

    num_classes: int = 10
    rows: int = 4096
    dim: int = 16
    hidden: int = 64

    @nn.compact
    def __call__(self, x, train: bool = False):
        del train
        if self.rows > MAX_F32_EXACT_ROWS:
            raise ValueError(
                f"EmbeddingTower rows={self.rows} exceeds 2^24: the "
                "float32 data pipeline cannot carry row ids exactly"
            )
        idx = jnp.asarray(x, jnp.int32)  # (batch, slots) row ids
        table = self.param(
            "table", nn.initializers.normal(0.02), (self.rows, self.dim)
        )
        emb = jnp.take(table, idx, axis=0)  # backward = row scatter-add
        h = emb.reshape((emb.shape[0], -1))
        h = nn.relu(nn.Dense(self.hidden)(h))
        return nn.Dense(self.num_classes)(h)
