"""Tuning — the knob-selection subsystem.

Grew out of the single-file LR grid search (src/tune.sh parity) into a
package when PR 7 added the performance autopilot:

  * :mod:`gridsearch` — the reference's LR grid search (regex log contract
    kept), now recording its results as a JSON artifact through the shared
    probe ladder.
  * :mod:`probe` — the measured-probe runner the autopilot and the grid
    search share: fenced short-run timing of a candidate step program,
    with every completed row written atomically (the bench ladder's
    partial-artifact discipline).
  * :mod:`autopilot` — ``--auto tune``: predict a ranked candidate list
    from the comm model, probe the top of it, pick the knob vector, write
    the ``tune_decision.json`` decision artifact, and re-tune online when
    the step-time drift detector fires.

The historical ``atomo_tpu.tuning`` import surface is preserved here.
"""

from atomo_tpu.tuning.gridsearch import (  # noqa: F401
    DEFAULT_GRID,
    WORKER_LINE_RE,
    TuneResult,
    grid_search,
    parse_worker_lines,
)
