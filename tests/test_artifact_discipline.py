"""The artifact-writer lint as a tier-1 gate: any ``json.dump`` that
bypasses write_json_atomic/IncidentLog for a train_dir artifact fails the
suite, not a code review. scripts/tier1.sh also runs the script directly,
so both verification surfaces enforce the same rule."""

import importlib.util
import os

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_artifact_discipline",
        os.path.join(_REPO, "scripts", "check_artifact_discipline.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_no_artifact_discipline_bypasses():
    mod = _load_checker()
    violations = mod.collect_violations(_REPO)
    assert not violations, "\n".join(violations)


def test_lint_catches_a_package_bypass(tmp_path):
    """The lint is only worth wiring in if it actually fires: a synthetic
    package file with a bare json.dump must be flagged."""
    mod = _load_checker()
    pkg = tmp_path / "atomo_tpu" / "utils"
    pkg.mkdir(parents=True)
    bad = pkg / "rogue.py"
    bad.write_text(
        "import json\n"
        "def w(train_dir, obj):\n"
        "    with open(train_dir + '/x.json', 'w') as f:\n"
        "        json.dump(obj, f)\n"
    )
    out = mod.scan_file(
        str(bad), os.path.join("atomo_tpu", "utils", "rogue.py")
    )
    assert len(out) == 1 and "write_json_atomic" in out[0]
    # the tracing implementation itself stays allowed
    tracing = pkg / "tracing.py"
    tracing.write_text("import json\njson.dump({}, open('/dev/null','w'))\n")
    assert mod.scan_file(
        str(tracing), os.path.join("atomo_tpu", "utils", "tracing.py")
    ) == []


def test_lint_covers_mesh_subsystem_by_construction(tmp_path):
    """The walk covers every atomo_tpu/ subpackage with no allowlist to
    forget — a json.dump smuggled into the NEW mesh/ subsystem must be
    flagged exactly like the utils/ case (PR-14 satellite: new
    subsystems inherit the artifact discipline for free)."""
    mod = _load_checker()
    pkg = tmp_path / "atomo_tpu" / "mesh"
    pkg.mkdir(parents=True)
    bad = pkg / "rogue.py"
    bad.write_text(
        "import json\n"
        "def w(train_dir, obj):\n"
        "    with open(train_dir + '/mesh.json', 'w') as f:\n"
        "        json.dump(obj, f)\n"
    )
    out = mod.scan_file(
        str(bad), os.path.join("atomo_tpu", "mesh", "rogue.py")
    )
    assert len(out) == 1 and "write_json_atomic" in out[0]
    # and the REAL mesh package is clean (collect_violations walks it)
    real = os.path.join(_REPO, "atomo_tpu", "mesh")
    assert os.path.isdir(real)
    assert not [
        v for v in mod.collect_violations(_REPO) if "atomo_tpu/mesh" in v
    ]


def test_lint_covers_budget_subsystem_by_construction(tmp_path):
    """The mesh/obs precedent applied to the NEW budget/ subsystem: the
    walk covers it with no allowlist to forget — a json.dump smuggled
    into atomo_tpu/budget/ is flagged, and the real package (which
    writes budget_alloc.json through write_json_atomic) is clean."""
    mod = _load_checker()
    pkg = tmp_path / "atomo_tpu" / "budget"
    pkg.mkdir(parents=True)
    bad = pkg / "rogue.py"
    bad.write_text(
        "import json\n"
        "def w(train_dir, obj):\n"
        "    with open(train_dir + '/budget_alloc.json', 'w') as f:\n"
        "        json.dump(obj, f)\n"
    )
    out = mod.scan_file(
        str(bad), os.path.join("atomo_tpu", "budget", "rogue.py")
    )
    assert len(out) == 1 and "write_json_atomic" in out[0]
    real = os.path.join(_REPO, "atomo_tpu", "budget")
    assert os.path.isdir(real)
    assert not [
        v for v in mod.collect_violations(_REPO)
        if "atomo_tpu/budget" in v
    ]


def test_lint_covers_controller_subsystem_by_construction(tmp_path):
    """The budget precedent applied to the NEW controller/ subsystem:
    the AST walk covers atomo_tpu/controller/ with no allowlist to
    forget — a json.dump smuggled next to controller_decision.json's
    writer is flagged, and the real package (which writes through the
    tune ladder's write_json_atomic) is clean."""
    mod = _load_checker()
    pkg = tmp_path / "atomo_tpu" / "controller"
    pkg.mkdir(parents=True)
    bad = pkg / "rogue.py"
    bad.write_text(
        "import json\n"
        "def w(train_dir, obj):\n"
        "    with open(train_dir + '/controller_decision.json', 'w') as f:\n"
        "        json.dump(obj, f)\n"
    )
    out = mod.scan_file(
        str(bad), os.path.join("atomo_tpu", "controller", "rogue.py")
    )
    assert len(out) == 1 and "write_json_atomic" in out[0]
    real = os.path.join(_REPO, "atomo_tpu", "controller")
    assert os.path.isdir(real)
    assert not [
        v for v in mod.collect_violations(_REPO)
        if "atomo_tpu/controller" in v
    ]


def test_lint_covers_parallel_subsystem_by_construction(tmp_path):
    """The controller precedent applied to atomo_tpu/parallel/ — the
    package the delayed-overlap carry grew in (PR-19): the AST walk
    covers it with no allowlist to forget — a json.dump smuggled next
    to the carry checkpointing helpers is flagged, and the real package
    (whose state moves through flax serialization + save_checkpoint,
    never ad-hoc json) is clean."""
    mod = _load_checker()
    pkg = tmp_path / "atomo_tpu" / "parallel"
    pkg.mkdir(parents=True)
    bad = pkg / "rogue.py"
    bad.write_text(
        "import json\n"
        "def w(train_dir, obj):\n"
        "    with open(train_dir + '/carry_meta.json', 'w') as f:\n"
        "        json.dump(obj, f)\n"
    )
    out = mod.scan_file(
        str(bad), os.path.join("atomo_tpu", "parallel", "rogue.py")
    )
    assert len(out) == 1 and "write_json_atomic" in out[0]
    real = os.path.join(_REPO, "atomo_tpu", "parallel")
    assert os.path.isdir(real)
    assert not [
        v for v in mod.collect_violations(_REPO)
        if "atomo_tpu/parallel" in v
    ]


def test_lint_catches_a_script_train_dir_dump(tmp_path):
    mod = _load_checker()
    bad = tmp_path / "scripts" / "rogue.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import json, os\n"
        "def w(train_dir, obj):\n"
        "    json.dump(obj, open(os.path.join(train_dir, 'a.json'), 'w'))\n"
    )
    out = mod.scan_file(str(bad), os.path.join("scripts", "rogue.py"))
    assert len(out) == 1 and "train_dir" in out[0]
    # artifacts/-level writes in scripts stay out of scope
    ok = tmp_path / "scripts" / "fine.py"
    ok.write_text(
        "import json\n"
        "json.dump({}, open('artifacts/out.json', 'w'))\n"
    )
    assert mod.scan_file(str(ok), os.path.join("scripts", "fine.py")) == []
