"""Single-host trainer: the reference `single_machine.py` / `NN_Trainer`
equivalent, with optional in-loop gradient compression.

Reference behavior (src/nn_ops.py:101-189): per batch zero_grad -> forward ->
cross-entropy -> backward -> optimizer.step -> prec@1/5 log; per epoch
validate. This trainer adds the 'compression on, comm off' mode (SURVEY.md §7
build-order step 4): each step's gradient is encoded and decoded in-graph
before the optimizer update, so codec effects on convergence are measurable
without a mesh — the oracle against which distributed runs are compared
(§4 'single_machine as correctness baseline').

Everything (forward, backward, augment, encode, decode, update) is one
compiled XLA program per step; the host loop only feeds batches and reads
metrics.
"""

from __future__ import annotations

import dataclasses
import os
import time
from functools import partial
from typing import Any, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax
from flax.core import FrozenDict

from atomo_tpu.codecs import decode_tree, encode_tree
from atomo_tpu.data.pipeline import augment_batch
from atomo_tpu.obs.recorder import emit_worker_line
from atomo_tpu.utils.metrics import StepMetrics, Timer, accuracy


@dataclasses.dataclass
class TrainConfig:
    augment: bool = False
    compress_in_loop: bool = False
    label_smoothing: float = 0.0


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def cast_params(params, compute_dtype):
    """Mixed-precision entry cast of the parameter tree: floating leaves to
    ``compute_dtype`` (bf16 fwd/bwd on the MXU); the f32 master params stay
    outside. The single contract shared by every loss function — CV paths
    also cast their input images (cast_compute_inputs), token-id paths use
    this alone (integer inputs have nothing to cast)."""
    return jax.tree_util.tree_map(
        lambda a: a.astype(compute_dtype)
        if jnp.issubdtype(a.dtype, jnp.floating) else a,
        params,
    )


def cast_compute_inputs(params, images, compute_dtype):
    """cast_params plus the image batch (see cast_params)."""
    return cast_params(params, compute_dtype), images.astype(compute_dtype)


def cast_compute_outputs(logits, new_stats):
    """Mixed-precision exit cast: loss/softmax and BN running stats in f32."""
    return logits.astype(jnp.float32), jax.tree_util.tree_map(
        lambda a: a.astype(jnp.float32), new_stats
    )


def create_state(model, optimizer, rng, sample_input) -> TrainState:
    variables = model.init(
        {"params": rng, "dropout": jax.random.PRNGKey(0)}, sample_input, train=False
    )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", FrozenDict())
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=optimizer.init(params),
    )


def snapshot_state(state) -> "TrainState":
    """Host-side deep copy of a TrainState — the donation-aliasing guard.

    ``make_train_step(..., superstep=K)`` and
    ``make_distributed_train_step`` DONATE their state argument: after the
    call, the caller's reference points at deleted (or reused) device
    buffers. Worse, on jax 0.4.37 ``replicate_state``/``jax.device_put``
    can ALIAS the source buffers instead of copying, so even a
    "different" pre-step reference may share memory with the donated one.
    Tests (and any debug code) that need pre-step values must snapshot
    through ``jax.device_get`` BEFORE stepping — this helper additionally
    forces a real copy of every leaf, because on the CPU backend
    device_get itself can return views of the live buffers."""
    import numpy as np

    return jax.tree_util.tree_map(
        lambda a: np.array(a, copy=True), jax.device_get(state)
    )


def make_train_step(model, optimizer, codec=None, augment: bool = False,
                    compute_dtype=None, guard=None, chaos=None,
                    superstep: int = 1, remedy=None,
                    track_grad_norm: bool = False,
                    track_quality: bool = False):
    """Build the jitted single-host train step.

    codec != None applies encode->decode to the gradient pytree in-graph
    (per-leaf folded PRNG keys) before the optimizer — the compression
    study path.

    compute_dtype (e.g. jnp.bfloat16) selects mixed-precision compute:
    master params, optimizer state, gradients, loss, and BN running stats
    stay float32; the forward/backward matmuls and convs run in the given
    dtype — the MXU's native bf16 path, a TPU capability the all-f32
    CPU-torch reference has no analogue for. None = full f32.

    guard (resilience.GuardConfig) arms in-graph anomaly screening: a step
    whose raw gradient is non-finite (or beyond guard.max_grad_norm) is
    skipped — params, optimizer state and BN stats hold their pre-step
    values, the step counter still advances (the batch was consumed), and
    metrics["skipped"] is 1. Single host has no surviving contributions to
    rescale; skipping outright is the n=kept=0 case of the distributed
    skip-and-rescale policy (resilience.py rationale).

    chaos (utils.chaos.ChaosInjector) bakes the configured gradient faults
    into the compiled step — test/validation hook, zero-cost when None.

    remedy (resilience.RemedyConfig) applies the divergence doctor's
    ``rewarm`` ramp: the post-codec gradient is pre-scaled by
    ``remedy_scale(remedy, state.step)`` (an in-graph function of the
    carried step counter, so superstep partitions agree bitwise). None
    (default) adds no ops — the program is unchanged.

    track_grad_norm adds ``metrics["grad_norm"]`` (global L2 of the raw
    post-chaos gradient) for the divergence detector's trend counter; off
    (default) leaves the metrics pytree — and therefore the compiled
    program — exactly as before.

    track_quality (``--obs-quality``; needs a codec) adds the in-graph
    per-layer estimator-quality probes (obs.quality.quality_probe):
    ``metrics["q_err2"]``/``metrics["q_rel"]`` are (L,) per-leaf series
    of this step's encode error. Off (default) the program is
    byte-identical (lowered-HLO tested) and on only ADDS metric outputs,
    so trajectories are bit-identical armed vs off.

    superstep > 1 returns the FUSED variant: one jitted program that runs
    ``superstep`` full optimizer steps under a single ``lax.scan``
    (amortizing host dispatch, the dominant per-step cost on tunneled
    backends — see README "Performance"). Call it with ``images``/
    ``labels`` carrying a leading (K,) in-block step axis; it returns
    ``(state, metrics)`` where every metrics leaf is the per-step series
    stacked to shape (K,). Per-step RNG folding is unchanged (keys fold
    from the in-carry ``state.step``), so K fused steps are bit-identical
    to K sequential K=1 steps on the same data; the guard's skip logic
    lives in the scan carry, so an anomalous step inside the block holds
    state exactly as the sequential path would. DONATION: the fused
    variant donates the state argument — the caller's reference is
    invalidated by the call; snapshot via :func:`snapshot_state` first if
    pre-step values are needed (jax 0.4.37 device_put aliasing makes any
    shallower copy unsafe). Compile cost: the scan length is baked into
    the compiled program, so a run sees at most TWO compiles of this
    variant — the K-block shape plus one shorter tail block when
    (max_steps - start) % K != 0; padding the tail to K was rejected as
    it would complicate the resume-replay data contract for a one-off
    cost.
    """
    from atomo_tpu.training.resilience import grad_ok, select_state, zero_if

    if superstep < 1:
        raise ValueError(f"superstep must be >= 1, got {superstep}")
    if track_quality and codec is None:
        raise ValueError(
            "track_quality probes the codec's estimator error; dense "
            "training has no estimator to probe — drop one"
        )

    def loss_fn(params, batch_stats, images, labels, dropout_key):
        if compute_dtype is not None:
            params, images = cast_compute_inputs(params, images, compute_dtype)
        variables = {"params": params}
        has_bn = bool(jax.tree_util.tree_leaves(batch_stats))
        if has_bn:
            variables["batch_stats"] = batch_stats
        out = model.apply(
            variables,
            images,
            train=True,
            rngs={"dropout": dropout_key},
            mutable=["batch_stats"] if has_bn else [],
        )
        logits, mutated = out
        new_stats = mutated.get("batch_stats", batch_stats)
        if compute_dtype is not None:
            logits, new_stats = cast_compute_outputs(logits, new_stats)
        loss = cross_entropy_loss(logits, labels)
        return loss, (logits, new_stats)

    def step_core(state: TrainState, key: jax.Array, images, labels):
        k_aug, k_drop, k_codec = jax.random.split(jax.random.fold_in(key, state.step), 3)
        if augment:
            images = augment_batch(k_aug, images)
        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params, state.batch_stats, images, labels, k_drop)

        if chaos is not None:
            grads = chaos.inject_grads(grads, state.step + 1)
        gnorm = None
        if track_grad_norm:
            from atomo_tpu.training.resilience import global_sq_norm

            # raw (pre-screen, pre-codec) global L2: the detector's trend
            # signal must see what the screen saw, not what survived it
            gnorm = jnp.sqrt(global_sq_norm(grads))
        ok = None
        if guard is not None:
            ok = grad_ok(grads, guard.max_grad_norm)
            # keep non-finite values out of the codec/optimizer arithmetic;
            # the skipped step's outputs are discarded below regardless
            grads = zero_if(~ok, grads)

        msg_bytes = 0
        qm = None
        if codec is not None:
            payloads, stats = encode_tree(codec, k_codec, grads)
            if track_quality:
                from atomo_tpu.obs.quality import quality_probe

                # per-layer ||decode(encode(g)) - g||^2 of THIS encode —
                # the estimator-variance feed; off adds zero ops
                qm = quality_probe(codec, payloads, grads)
            grads = decode_tree(codec, payloads, grads)
            msg_bytes = stats.payload_bytes

        if remedy is not None:
            from atomo_tpu.training.resilience import apply_remedy

            grads = apply_remedy(remedy, state.step, grads)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        skipped = jnp.float32(0.0)
        if ok is not None:
            new_params = select_state(ok, new_params, state.params)
            new_opt = select_state(ok, new_opt, state.opt_state)
            new_stats = select_state(ok, new_stats, state.batch_stats)
            skipped = 1.0 - ok.astype(jnp.float32)
        prec1, prec5 = accuracy(logits, labels)
        metrics = {
            "loss": loss,
            "prec1": prec1,
            "prec5": prec5,
            "msg_bytes": jnp.asarray(msg_bytes, jnp.int32),
            "skipped": skipped,
        }
        if gnorm is not None:
            metrics["grad_norm"] = gnorm
        if qm is not None:
            metrics.update(qm)
        return (
            TrainState(
                step=state.step + 1,
                params=new_params,
                batch_stats=new_stats,
                opt_state=new_opt,
            ),
            metrics,
        )

    if superstep == 1:
        return jax.jit(step_core)

    @partial(jax.jit, donate_argnums=(0,))
    def train_superstep(state: TrainState, key: jax.Array, images, labels):
        # per-step keys fold from the in-carry state.step, so the scan body
        # IS the sequential step — the fusion only removes dispatches
        def body(st, xs):
            return step_core(st, key, xs[0], xs[1])

        return jax.lax.scan(body, state, (images, labels))

    return train_superstep


def make_eval_step(model):
    @jax.jit
    def eval_step(state: TrainState, images, labels):
        variables = {"params": state.params}
        if jax.tree_util.tree_leaves(state.batch_stats):
            variables["batch_stats"] = state.batch_stats
        logits = model.apply(variables, images, train=False)
        loss = cross_entropy_loss(logits, labels)
        prec1, prec5 = accuracy(logits, labels)
        return {"loss": loss, "prec1": prec1, "prec5": prec5}

    return eval_step


def evaluate(model, state: TrainState, test_iter) -> dict[str, float]:
    """Full-test-set metrics (the reference validate, nn_ops.py:171-189)."""
    eval_step = make_eval_step(model)
    totals: dict[str, float] = {"loss": 0.0, "prec1": 0.0, "prec5": 0.0}
    n = 0
    for images, labels in test_iter.epoch():
        m = eval_step(state, jnp.asarray(images), jnp.asarray(labels))
        bs = images.shape[0]
        for k_ in totals:
            totals[k_] += float(m[k_]) * bs
        n += bs
    return {k_: v / max(n, 1) for k_, v in totals.items()}


def train_loop(
    model,
    optimizer,
    train_iter,
    test_iter=None,
    *,
    codec=None,
    augment: bool = False,
    max_steps: int = 100,
    eval_freq: int = 0,
    seed: int = 0,
    train_dir: Optional[str] = None,
    save_freq: int = 0,
    resume: bool = False,
    compress_ckpt: bool = True,
    log_fn=print,
    log_every: int = 1,
    compute_dtype=None,
    guard=None,
    chaos=None,
    health_timeout: float = 0.0,
    on_health_failure=None,
    keep_ckpts: int = 0,
    superstep: int = 1,
    diverge=None,
    tuner=None,
    track_quality: bool = False,
    recorder=None,
) -> TrainState:
    """The reference train_and_validate loop (nn_ops.py:123-169), jitted,
    plus working checkpoint/resume (gap §5.4) and the fault-tolerance
    stack: anomaly-guarded stepping (``guard``), deterministic fault
    injection (``chaos``), a heartbeat watchdog (``health_timeout`` > 0,
    ``on_health_failure`` pluggable), retry-wrapped checkpoint IO, and
    keep-last-K retention (``keep_ckpts``).

    Resume determinism: on resume the data stream is fast-forwarded past
    the ``start_step`` batches the interrupted run consumed, so a
    kill→restart→resume run replays the exact batch sequence of an
    uninterrupted one (host-side numpy indexing — cheap relative to a
    step). ``chaos`` defaults to the ATOMO_CHAOS env config so subprocess
    harnesses inject faults without plumbing.

    ``superstep`` > 1 switches to fused block execution: K optimizer steps
    per dispatch under one ``lax.scan`` (make_train_step's fused variant),
    data fed as device-resident (K, batch, ...) blocks with the next
    block's transfer double-buffered behind the current block's compute,
    and metrics fetched ONCE per block. Host-side cadence — log lines,
    eval, checkpoints, watchdog beats, chaos kill/sleep — is evaluated at
    superstep boundaries: a cadence point crossed inside a block fires at
    the block's final step (checkpoint steps snap to boundaries).
    Trajectories are bit-identical to K=1 (per-step RNG folds from the
    carried step counter; the data stream is index-determined), including
    across kill→restart→resume at a step that is not a multiple of K —
    the resumed run simply starts a fresh block at checkpoint_step+1.
    K=1 preserves the original per-step loop exactly.

    ``diverge`` (resilience.DivergeConfig) arms the divergence doctor:
    the per-step loss/skip/grad-norm series feeds a windowed detector
    (one scalar fetch per step in the per-step loop — the price of
    surveillance; the superstep loop's existing one-fetch-per-block
    amortizes it away), checkpoints earn a ``healthy`` tag only after the
    detector window clears past them, and an alarm rolls the run back to
    the newest healthy checkpoint, replays the data stream, and applies
    the configured remedy — with the chaos generation bumped so
    step-targeted faults do not re-fire on the replay. Budget exhaustion
    raises resilience.DivergenceError (the CLI maps it to
    ROLLBACK_EXIT_CODE for the run-level supervisor).

    ``tuner`` (tuning.autopilot.OnlineRetuner) feeds the per-step
    wall-time series to the step-time drift detector (resilience
    rung 0.5). A single device has no exchange to re-pick, so the
    single-host loop runs the tuner observe-only: sustained drift is
    recorded to ``incidents.jsonl`` at the next checkpoint boundary, the
    config is kept. Costs one scalar fetch per step in the per-step loop
    (the doctor's surveillance price); the superstep loop amortizes it
    into the block's one fetch.

    ``recorder`` (obs.recorder.FlightRecorder) arms the flight recorder:
    one ``metrics.jsonl`` record per step (per-step shares per superstep
    block), pruned in lockstep with the checkpoint timeline on rollback.
    None (default) adds zero device ops — the programs and the stdout
    log are byte-identical. ``track_quality`` arms the in-graph
    per-layer estimator-quality probes (see make_train_step)."""
    from atomo_tpu.training.checkpoint import latest_step, load_checkpoint
    from atomo_tpu.training.resilience import (
        SUPERVISED_ENV,
        DivergenceDoctor,
        RecoveryRig,
        diverge_conflict,
        heartbeat_watchdog,
        resolve_chaos,
        retrying_saver,
    )
    from atomo_tpu.utils.tracing import IncidentLog

    chaos = resolve_chaos(chaos)
    if chaos is not None:
        chaos.maybe_die_crashloop()  # crashloop@M: attempt-keyed death
    sample_images, _ = next(iter(train_iter.epoch()))
    state = create_state(
        model, optimizer, jax.random.PRNGKey(seed), jnp.asarray(sample_images)
    )
    start_step = 0
    if resume and train_dir and latest_step(train_dir) is not None:
        try:
            state = load_checkpoint(train_dir, state)
            start_step = int(state.step)
            log_fn(f"Resumed from {train_dir} at step {start_step}")
        except FileNotFoundError as exc:
            # files exist but none passed integrity checks — a fresh start
            # beats dying when the operator asked for elastic restarts
            log_fn(f"Resume requested but {exc}; starting fresh")

    rig = None
    incidents = None
    if train_dir and (
        diverge is not None or tuner is not None
        or os.environ.get(SUPERVISED_ENV) == "1"
    ):
        incidents = IncidentLog.for_train_dir(train_dir)
    if tuner is not None:
        tuner.bind(incidents=incidents, log_fn=log_fn)
    if diverge is not None:
        reason = diverge_conflict(
            diverge.remedy,
            train_dir=train_dir,
            codec=codec,
            keep_ckpts=keep_ckpts,
            save_freq=save_freq,
            window=diverge.detector.window,
        )
        if reason:
            raise ValueError(reason)

    def build_step(generation=0, remedy_cfg=None, densify=False):
        chaos_now = (
            chaos.with_generation(generation)
            if chaos is not None and generation
            else chaos
        )
        return make_train_step(
            model, optimizer,
            codec=None if densify else codec,
            augment=augment, compute_dtype=compute_dtype, guard=guard,
            chaos=chaos_now, superstep=superstep, remedy=remedy_cfg,
            track_grad_norm=diverge is not None,
            # the densify window swaps to dense aggregation — no
            # estimator left to probe for its duration
            track_quality=False if densify else track_quality,
        )

    if track_quality and codec is None:
        raise ValueError(
            "track_quality (--obs-quality) probes the codec's estimator "
            "error; dense training has no estimator — drop one"
        )
    if recorder is not None:
        recorder.context.setdefault("aggregate", "local")
        # a resumed run replays from the checkpoint: cut the stale metric
        # tail the killed attempt wrote past its last save, or the replay
        # would duplicate those steps in the timeline
        recorder.prune_past(start_step)
        if track_quality:
            from atomo_tpu.obs.quality import quality_meta

            # the static per-layer kept-byte split, once (trace-time
            # shapes only — nothing materializes)
            recorder.write_meta(
                quality_meta(codec, jax.device_get(state.params))
            )
    step_fn = build_step()
    save_fn = retrying_saver(log_fn, incidents)
    key = jax.random.PRNGKey(seed + 1)
    timer = Timer()
    # replay: skip the batches the interrupted run consumed so the resumed
    # data order matches the uninterrupted run's (docstring); index-only.
    # The RNG snapshot is the rollback engine's replay anchor
    # (pipeline.BatchIterator.restream) and MUST be taken before forever()
    # advances the shuffle RNG; it is a doctor-only iterator requirement,
    # so a disarmed loop keeps the old iterator contract.
    rng_snapshot = train_iter.snapshot_rng() if diverge is not None else None
    stream = train_iter.forever(skip=start_step)
    if diverge is not None:

        def _reload(target):
            tpl = create_state(
                model, optimizer, jax.random.PRNGKey(seed),
                jnp.asarray(sample_images),
            )
            if target <= 0:
                return tpl  # no healthy checkpoint survived: from scratch
            return load_checkpoint(train_dir, tpl, step=target)

        rig = RecoveryRig(
            DivergenceDoctor(diverge, train_dir, incidents, log_fn),
            diverge,
            _reload,
            lambda target: train_iter.restream(rng_snapshot, skip=target),
            build_step,
        )
    n_train = len(train_iter.dataset)
    last_saved = start_step
    if superstep > 1:
        # the watchdog beats once per BLOCK: scale its budget by K so a
        # --health-timeout tuned for per-step beats does not falsely fire
        # on a healthy fused run (K steps + one metric fetch per beat)
        with heartbeat_watchdog(
            health_timeout * superstep, on_health_failure
        ) as monitor:
            return _superstep_steps(
                state, step_fn, model, stream, train_iter, test_iter, key,
                timer, n_train, start_step, max_steps, superstep, log_every,
                log_fn, eval_freq, save_freq, train_dir, compress_ckpt,
                save_fn, monitor, guard=guard, chaos=chaos,
                keep_ckpts=keep_ckpts, rig=rig, tuner=tuner,
                recorder=recorder,
            )
    with heartbeat_watchdog(health_timeout, on_health_failure) as monitor:
        step = start_step
        t_obs = time.perf_counter()  # the tuner's step-time series anchor
        t_rec = time.perf_counter()  # the flight recorder's wall anchor
        while step < max_steps:
            step += 1
            if chaos is not None:
                chaos.maybe_die(step)
                chaos.maybe_sleep(step)
            images, labels = next(stream)
            state, metrics = step_fn(state, key, jnp.asarray(images), jnp.asarray(labels))
            if monitor is not None:
                jax.block_until_ready(metrics["loss"])
                monitor.beat(step)
            if recorder is not None:
                # one fetch per step — the doctor's surveillance-price
                # precedent; record BEFORE the doctor observes, so a
                # diverged step lands in the timeline and the rollback's
                # prune (checkpoint.prune_after -> prune_metrics_after)
                # cuts it in lockstep with the checkpoint files
                m_host = jax.device_get(metrics)
                now_r = time.perf_counter()
                recorder.record_block(
                    step, m_host, wall_s=now_r - t_rec,
                    drift=tuner.state if tuner is not None else None,
                    generation=(
                        rig.doctor.generation if rig is not None else None
                    ),
                )
                t_rec = now_r
            if rig is not None:
                # one scalar fetch per step: per-step surveillance is the
                # price of per-step rollback granularity (the superstep
                # loop amortizes it into the block's single fetch)
                alarm_step, reason = rig.observe(step, metrics)
                if reason is not None:
                    # raises DivergenceError when the budget is spent
                    state, stream, step_fn, chaos, step = rig.recover(
                        alarm_step, reason, chaos
                    )
                    last_saved = min(last_saved, step)
                    # recovery wall is not step time: restamp the tuner's
                    # anchor or it pollutes the next drift observation
                    t_obs = time.perf_counter()
                    t_rec = time.perf_counter()
                    continue
                new_fn = rig.maybe_end_densify(step)
                if new_fn is not None:
                    step_fn = new_fn
            if tuner is not None:
                # fence before stamping (async dispatch would time the
                # enqueue); one fetch per step, only while armed
                float(metrics["loss"])
                now = time.perf_counter()
                tuner.observe(now - t_obs)
                t_obs = now
            # guard diagnostics share the log cadence: fetching the skip
            # flag every step would block host dispatch on every step's
            # result even when nothing is ever dropped
            if (
                guard is not None
                and log_every and step % log_every == 0
                and float(metrics["skipped"]) > 0
            ):
                log_fn(
                    f"Guard: Step: {step}, Dropped: 1/1, Action: skip "
                    "(anomalous gradient; params/opt state held)"
                )
            if log_every and step % log_every == 0:
                rec = StepMetrics(
                    rank=0,
                    step=step,
                    epoch=step * train_iter.batch_size // max(n_train, 1),
                    samples_seen=(step * train_iter.batch_size) % max(n_train, 1),
                    dataset_size=n_train,
                    loss=float(metrics["loss"]),
                    time_cost=timer.lap(),
                    msg_bytes=int(metrics["msg_bytes"]),
                    prec1=float(metrics["prec1"]),
                    prec5=float(metrics["prec5"]),
                )
                emit_worker_line(recorder, rec, log_fn)
            if eval_freq and test_iter is not None and step % eval_freq == 0:
                ev = evaluate(model, state, test_iter)
                log_fn(
                    "Validation: Step: {}, Loss: {:.4f}, Prec@1: {:.4f}, Prec@5: {:.4f}".format(
                        step, ev["loss"], ev["prec1"], ev["prec5"]
                    )
                )
            if save_freq and train_dir and step % save_freq == 0:
                path = save_fn(
                    train_dir, state, step, compress=compress_ckpt,
                    keep=keep_ckpts,
                )
                last_saved = step
                if rig is not None:
                    rig.note_save(step)
                if chaos is not None:
                    chaos.maybe_corrupt_checkpoint(path, step)
                if tuner is not None:
                    # observe-only on one device: records the drift
                    # incident at the boundary, keeps the config
                    tuner.maybe_retune(step, "local")
            if tuner is not None:
                # restamp after boundary work (eval/save): cadence costs
                # must not enter the drift baseline
                t_obs = time.perf_counter()
            if recorder is not None:
                t_rec = time.perf_counter()  # same boundary-work rule
        # autosave the final state so a restart never replays the tail
        # (strictly `<`: a resume past max_steps runs no steps and must not
        # write a file whose name disagrees with the state's step field)
        if save_freq and train_dir and last_saved < max_steps:
            path = save_fn(
                train_dir, state, max_steps, compress=compress_ckpt,
                keep=keep_ckpts,
            )
            if rig is not None:
                rig.note_save(max_steps)
            if chaos is not None:  # ckpt faults target autosaves too
                chaos.maybe_corrupt_checkpoint(path, max_steps)
    return state


def _crossed(cadence: int, lo: int, hi: int) -> bool:
    """True iff a multiple of ``cadence`` lies in (lo, hi] — the boundary
    test that snaps every per-step cadence (log/eval/save) to superstep
    boundaries: the event fires at ``hi``, the block's final step."""
    return bool(cadence) and hi // cadence > lo // cadence


def _chaos_corrupt_range(chaos, path, lo: int, hi: int) -> None:
    """Apply chaos checkpoint faults aimed at ANY step in (lo, hi] to the
    boundary checkpoint written at ``hi`` — the same block-boundary snap
    kill/sleep get (a ``truncate@3`` drill must still corrupt the file the
    save cadence snapped to step 4)."""
    if chaos is None:
        return
    for t in range(lo + 1, hi + 1):
        chaos.maybe_corrupt_checkpoint(path, t)


def _block_log_record(s, m, train_iter, n_train, lap, last_logged):
    """Worker-line record for a superstep block boundary: loss/precision
    are PER-STEP AVERAGES over the block (msg_bytes is a per-step
    constant), time_cost the per-step average of the span since the last
    log. Shared by the single-host and distributed block loops so the log
    format cannot drift between them."""
    import numpy as np

    return StepMetrics(
        rank=0,
        step=s,
        epoch=s * train_iter.batch_size // max(n_train, 1),
        samples_seen=(s * train_iter.batch_size) % max(n_train, 1),
        dataset_size=n_train,
        loss=float(np.mean(m["loss"])),
        time_cost=lap / max(s - last_logged, 1),
        msg_bytes=int(np.asarray(m["msg_bytes"]).reshape(-1)[-1]),
        prec1=float(np.mean(m["prec1"])),
        prec5=float(np.mean(m["prec5"])),
    )


def _superstep_steps(
    state, step_fn, model, stream, train_iter, test_iter, key, timer,
    n_train, start_step, max_steps, superstep, log_every, log_fn,
    eval_freq, save_freq, train_dir, compress_ckpt, save_fn, monitor,
    guard=None, chaos=None, keep_ckpts=0, rig=None, tuner=None,
    recorder=None,
):
    """train_loop's fused block path: one dispatch per K steps, one metric
    fetch per block (the fetch is also the fence the watchdog beats on),
    next block double-buffered onto the device behind the current one.
    ``rig`` (resilience.RecoveryRig) adds divergence rollback: the block's
    per-step (K,) metric series feeds the detector at the block's one
    fetch, and a rollback rebuilds the feed from the replayed stream —
    the resumed run starts a fresh block at target+1, which the scan
    family's partition invariance makes bit-identical to never having
    diverged."""
    import numpy as np

    from atomo_tpu.data.pipeline import BlockStream, SuperstepFeed

    put_fn = lambda im, lb: (jax.device_put(jnp.asarray(im)),  # noqa: E731
                             jax.device_put(jnp.asarray(lb)))
    feed = SuperstepFeed(BlockStream(stream), put_fn)
    s = start_step
    last_saved = start_step
    last_logged = start_step
    t_obs = time.perf_counter()  # the tuner's step-time series anchor
    t_rec = time.perf_counter()  # the flight recorder's wall anchor
    feed.start(min(superstep, max_steps - s))
    while s < max_steps:
        kb, dev_im, dev_lb = feed.take()
        b0, s = s, s + kb
        if chaos is not None:
            # host faults resolve at the block boundary: the block is ONE
            # dispatch, so a kill/sleep aimed at any step it covers fires
            # before the block runs (none of its steps have executed yet
            # — the checkpoint/resume contract is preserved)
            for t in range(b0 + 1, s + 1):
                chaos.maybe_die(t)
                chaos.maybe_sleep(t)
        state, mblk = step_fn(state, key, dev_im, dev_lb)
        # enqueue the NEXT block's host->device transfer while the current
        # superstep executes (async dispatch above returns immediately)
        feed.start(min(superstep, max_steps - s))
        m = jax.device_get(mblk)  # the block's ONE host sync
        if monitor is not None:
            monitor.beat(s)
        if recorder is not None:
            # rides the block's one fetch (zero extra device ops); the
            # block wall becomes kb equal per-step shares — the drift
            # detector's partition-consistency convention. Recorded
            # BEFORE the doctor observes: a diverged block lands in the
            # timeline and the rollback prune cuts it in lockstep.
            now_r = time.perf_counter()
            recorder.record_block(
                b0 + 1, m, wall_s=now_r - t_rec,
                drift=tuner.state if tuner is not None else None,
                generation=(
                    rig.doctor.generation if rig is not None else None
                ),
            )
            t_rec = now_r
        if rig is not None:
            alarm_step, reason = rig.observe(b0 + 1, m)
            if reason is not None:
                state, stream, step_fn, chaos, s = rig.recover(
                    alarm_step, reason, chaos
                )
                last_saved = min(last_saved, s)
                last_logged = min(last_logged, s)
                # drop the feed's staged lookahead block: it belongs to
                # the discarded timeline
                feed = SuperstepFeed(BlockStream(stream), put_fn)
                feed.start(min(superstep, max_steps - s))
                # recovery wall is not step time: restamp the tuner anchor
                t_obs = time.perf_counter()
                t_rec = time.perf_counter()
                continue
            new_fn = rig.maybe_end_densify(s)
            if new_fn is not None:
                step_fn = new_fn
        if tuner is not None:
            # the block's wall as kb equal per-step shares (the
            # device_get above already fenced the dispatch): one mean
            # per block would make the detector K-times less sensitive
            # than the per-step loop — partition consistency
            kb_n = max(kb, 1)
            tuner.observe([(time.perf_counter() - t_obs) / kb_n] * kb_n)
        n_skipped = float(np.sum(m["skipped"])) if guard is not None else 0.0
        if guard is not None and _crossed(log_every, b0, s) and n_skipped > 0:
            log_fn(
                f"Guard: Step: {s}, Dropped: {int(n_skipped)}/{kb}, "
                "Action: skip (anomalous gradient inside the superstep; "
                "params/opt state held for those steps)"
            )
        if _crossed(log_every, b0, s):
            rec = _block_log_record(
                s, m, train_iter, n_train, timer.lap(), last_logged
            )
            last_logged = s
            emit_worker_line(recorder, rec, log_fn)
        if eval_freq and test_iter is not None and _crossed(eval_freq, b0, s):
            ev = evaluate(model, state, test_iter)
            log_fn(
                "Validation: Step: {}, Loss: {:.4f}, Prec@1: {:.4f}, Prec@5: {:.4f}".format(
                    s, ev["loss"], ev["prec1"], ev["prec5"]
                )
            )
        if save_freq and train_dir and _crossed(save_freq, b0, s):
            path = save_fn(
                train_dir, state, s, compress=compress_ckpt, keep=keep_ckpts
            )
            last_saved = s
            if rig is not None:
                rig.note_save(s)
            # ckpt faults snap like kill/sleep: a fault aimed anywhere in
            # this block corrupts the boundary file
            _chaos_corrupt_range(chaos, path, b0, s)
            if tuner is not None:
                tuner.maybe_retune(s, "local")  # observe-only on 1 device
        if tuner is not None:
            t_obs = time.perf_counter()  # boundary work is not step time
        if recorder is not None:
            t_rec = time.perf_counter()  # same boundary-work rule
    # autosave the final state so a restart never replays the tail (same
    # strictly-< contract as the per-step loop)
    if save_freq and train_dir and last_saved < max_steps:
        path = save_fn(
            train_dir, state, max_steps, compress=compress_ckpt,
            keep=keep_ckpts,
        )
        if rig is not None:
            rig.note_save(max_steps)
        _chaos_corrupt_range(chaos, path, last_saved, max_steps)
    return state
