"""Produce the LM convergence-parity artifact: compressed vs dense training
of the transformer LM on a dp mesh.

The CV artifact (scripts/convergence_artifact.py) proves the codec on
ResNet gradient spectra; this one proves it on TRANSFORMER gradients — the
matrices the tp/sp/pp/ep superset axes actually train. Two runs of the
dp-parallel LM step (parallel/lm.py with sp=1), identical data/seeds:
dense pmean vs SVD rank-3 gather. Writes artifacts/LM_CONVERGENCE.json +
.md with both loss curves, the final-window loss ratio, and the measured
byte reduction.

Data: deterministic synthetic streams in the lm CLI's style (arithmetic
progressions with random starts/strides — learnable structure, reproducible
from this script's fixed seed; stride range differs from the CLI's).

Usage: python scripts/lm_convergence_artifact.py [--steps 300] [--out artifacts]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--out", type=str, default="artifacts")
    ap.add_argument("--ratio-bound", type=float, default=1.35)
    args = ap.parse_args()

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax
    import jax.numpy as jnp
    import numpy as np

    from atomo_tpu.codecs import SvdCodec
    from atomo_tpu.models.transformer import TransformerLM
    from atomo_tpu.parallel.lm import make_lm_train_step, shard_tokens
    from atomo_tpu.parallel.mesh import make_mesh
    from atomo_tpu.parallel.replicated import replicate_state
    from atomo_tpu.training import create_state, make_optimizer

    n_dev = min(4, len(jax.devices()))
    cfg = dict(vocab_size=64, max_len=64, width=64, depth=2, num_heads=4)
    batch, seq = 8 * n_dev, 64
    mesh = make_mesh(n_dev, axes=(("dp", n_dev), ("sp", 1)))
    opt = make_optimizer("sgd", lr=0.1, momentum=0.9)

    rng = np.random.default_rng(0)

    def batch_tokens():
        starts = rng.integers(0, cfg["vocab_size"], size=(batch, 1))
        strides = rng.integers(1, 5, size=(batch, 1))
        return ((starts + strides * np.arange(seq)) % cfg["vocab_size"]).astype(
            np.int32
        )

    batches = [batch_tokens() for _ in range(args.steps)]

    curves, bytes_info = {}, {}
    for tag, codec in (("dense", None), ("svd3", SvdCodec(rank=3))):
        lm = TransformerLM(**cfg)
        state = create_state(
            lm, opt, jax.random.PRNGKey(0), jnp.zeros((1, seq), jnp.int32)
        )
        state = replicate_state(mesh, state)
        step = make_lm_train_step(cfg, opt, mesh, codec)
        losses = []
        t0 = time.time()
        for i, toks in enumerate(batches):
            state, m = step(
                state, jax.random.PRNGKey(1000 + i), shard_tokens(mesh, toks)
            )
            losses.append(float(m["loss"]))
        curves[tag] = losses
        bytes_info[tag] = dict(
            msg_bytes=float(m["msg_bytes"]), dense_bytes=float(m["dense_bytes"])
        )
        print(
            f"{tag}: final {losses[-1]:.4f} "
            f"({time.time() - t0:.1f}s, {len(losses)} steps)",
            flush=True,
        )

    w = max(args.steps // 10, 1)
    final_dense = float(np.mean(curves["dense"][-w:]))
    final_svd = float(np.mean(curves["svd3"][-w:]))
    ratio = final_svd / max(final_dense, 1e-9)
    reduction = bytes_info["svd3"]["dense_bytes"] / max(
        bytes_info["svd3"]["msg_bytes"], 1.0
    )
    # parity alone is not enough: both runs must have actually converged
    # (sibling artifact's guard — a broken step would give ratio ~1.0)
    converged = (
        final_dense < curves["dense"][0] * 0.5
        and final_svd < curves["svd3"][0] * 0.5
    )
    ok = ratio < args.ratio_bound and converged

    os.makedirs(args.out, exist_ok=True)
    payload = dict(
        model="TransformerLM", config=cfg, batch=batch, seq_len=seq,
        n_devices=n_dev, steps=args.steps, optimizer="sgd lr=0.1 m=0.9",
        platform=jax.devices()[0].platform,
        device=jax.devices()[0].device_kind,
        final_window=w, final_loss_dense=final_dense,
        final_loss_svd3=final_svd, ratio=ratio,
        ratio_bound=args.ratio_bound, byte_reduction=reduction,
        bytes=bytes_info, converged=converged, passes=ok, curves=curves,
    )
    with open(os.path.join(args.out, "LM_CONVERGENCE.json"), "w") as f:
        json.dump(payload, f)
    with open(os.path.join(args.out, "LM_CONVERGENCE.md"), "w") as f:
        f.write(
            "# LM convergence parity: SVD rank-3 vs dense\n\n"
            f"TransformerLM ({cfg['depth']}x{cfg['width']}, vocab "
            f"{cfg['vocab_size']}), batch {batch}, seq {seq}, {n_dev}-way dp "
            f"mesh on {payload['device']}; {args.steps} steps, synthetic "
            "arithmetic-progression streams (deterministic).\n\n"
            f"| run | final loss (last {w} mean) |\n|---|---|\n"
            f"| dense pmean | {final_dense:.4f} |\n"
            f"| svd rank-3 gather | {final_svd:.4f} |\n\n"
            f"ratio {ratio:.3f} (bound {args.ratio_bound}), both runs "
            f"converged: {converged} — {'PASS' if ok else 'FAIL'}; byte "
            f"reduction {reduction:.1f}x per step per chip "
            f"(svd {bytes_info['svd3']['msg_bytes']:.0f} B vs dense "
            f"{bytes_info['svd3']['dense_bytes']:.0f} B).\n"
        )
    print(
        f"ratio={ratio:.3f} bound={args.ratio_bound} "
        f"byte_reduction={reduction:.1f}x -> {'PASS' if ok else 'FAIL'}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
