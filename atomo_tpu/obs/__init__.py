"""Observability subsystem — the flight recorder (PR 11).

Three layers over the evidence artifacts PRs 5-10 established:

  * :mod:`~atomo_tpu.obs.recorder` — ``FlightRecorder``: one JSON line
    per training step into ``train_dir/metrics.jsonl`` (the IncidentLog
    append/torn-line discipline), carrying the per-step signal that used
    to exist only as ephemeral stdout text — loss, step wall, guard
    verdicts, wire bytes, the aggregate mode actually in effect — plus a
    rolling predicted-vs-measured calibration column.
  * :mod:`~atomo_tpu.obs.quality` — opt-in in-graph estimator-quality
    probes (``--obs-quality``): per-layer compression error of the
    codec's unbiased estimator inside the fused step, the data feed the
    adaptive variance-budget work (ROADMAP open item 5) consumes.
  * :mod:`~atomo_tpu.obs.report` — join metrics.jsonl + incidents.jsonl
    + membership.json + tune_decision.json into one time-ordered
    ``run_report.json`` with cross-artifact consistency checks (the
    ``report`` CLI verb).
"""

from atomo_tpu.obs.recorder import (  # noqa: F401
    METRICS_FILE_NAME,
    FlightRecorder,
    emit_worker_line,
    metrics_path,
    prune_metrics_after,
)
