"""Auxiliary subsystem tests: tracing spans, health monitor, launch helpers,
optimizer schedule parity."""

import time

import jax
import numpy as np
import pytest

from atomo_tpu.parallel.launch import HealthMonitor, global_mesh, initialize
from atomo_tpu.training import make_optimizer, stepwise_shrink
from atomo_tpu.utils.tracing import StepTimer, annotate, span


def test_span_records_into_sink():
    sink = {}
    with span("io", sink):
        time.sleep(0.01)
    assert sink["io"] >= 0.01


def test_annotate_is_safe_anywhere():
    with annotate("region"):
        pass


def test_step_timer_stats():
    t = StepTimer(window=4)
    for _ in range(6):
        time.sleep(0.002)
        t.lap()
    assert t.mean > 0 and t.steps_per_sec > 0


def test_health_monitor_raises_after_silence():
    hm = HealthMonitor(timeout=0.01)
    hm.beat(3)
    time.sleep(0.05)
    with pytest.raises(RuntimeError, match="step 3"):
        hm.check()
    hm.beat(4)
    hm.check()  # fresh beat passes


def test_initialize_single_host_is_noop():
    initialize()  # no coordinator configured -> no-op


def test_initialize_env_var_path(monkeypatch):
    """The pod bootstrap: launch_pod.sh exports JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID; initialize() must forward them to
    jax.distributed.initialize (VERDICT r1 next-round #5)."""
    calls = {}

    def fake_init(coordinator_address=None, num_processes=None, process_id=None):
        calls.update(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "2")
    initialize()
    assert calls == dict(
        coordinator_address="10.0.0.1:1234", num_processes=4, process_id=2
    )


def test_watchdog_fires_on_stalled_step():
    """A stalled training step (no beat within timeout) must raise the
    alarm via the watchdog thread — the monitored-loop contract."""
    from atomo_tpu.parallel.launch import HealthWatchdog

    failures = []
    hm = HealthMonitor(timeout=0.05)
    wd = HealthWatchdog(hm, interval=0.01, on_failure=failures.append).start()
    try:
        hm.beat(1)
        time.sleep(0.2)  # the "stall"
    finally:
        wd.stop()
    assert failures and "step 1" in str(failures[0])


def test_watchdog_quiet_while_beating():
    from atomo_tpu.parallel.launch import HealthWatchdog

    failures = []
    hm = HealthMonitor(timeout=0.2)
    wd = HealthWatchdog(hm, interval=0.01, on_failure=failures.append).start()
    try:
        for s in range(10):
            hm.beat(s)
            time.sleep(0.01)
    finally:
        wd.stop()
    assert not failures


@pytest.mark.slow
def test_distributed_loop_beats_monitor():
    """distributed_train_loop with health_timeout armed completes a short
    run and tears the watchdog down cleanly (production wiring check)."""
    from atomo_tpu.codecs import SvdCodec
    from atomo_tpu.data import BatchIterator, SPECS, synthetic_dataset
    from atomo_tpu.models import get_model
    from atomo_tpu.parallel import distributed_train_loop, make_mesh
    from atomo_tpu.training import make_optimizer

    ds = synthetic_dataset(SPECS["mnist"], True)
    it = BatchIterator(ds, 16, seed=0)
    lines = []
    distributed_train_loop(
        get_model("lenet", 10),
        make_optimizer("sgd", lr=0.01),
        make_mesh(4),
        it,
        codec=SvdCodec(rank=2),
        max_steps=3,
        log_fn=lines.append,
        health_timeout=60.0,
    )
    assert any("Step: 3" in l for l in lines)


def test_global_mesh_spans_devices():
    mesh = global_mesh()
    assert mesh.devices.size == len(jax.devices())


@pytest.mark.slow
def test_profile_dir_captures_trace(tmp_path):
    """--profile-dir must produce a jax.profiler trace of steady-state steps
    (the fused-program observability story, utils/tracing docstring)."""
    from atomo_tpu.codecs import SvdCodec
    from atomo_tpu.data import BatchIterator, SPECS, synthetic_dataset
    from atomo_tpu.models import get_model
    from atomo_tpu.parallel import distributed_train_loop, make_mesh
    from atomo_tpu.training import make_optimizer

    ds = synthetic_dataset(SPECS["mnist"], True, size=64)
    lines = []
    distributed_train_loop(
        get_model("lenet", 10),
        make_optimizer("sgd", lr=0.01),
        make_mesh(2),
        BatchIterator(ds, 8, seed=0),
        codec=SvdCodec(rank=2),
        max_steps=4,
        log_fn=lines.append,
        profile_dir=str(tmp_path),
        profile_steps=2,
    )
    assert any("Profiling steps 2..3" in l for l in lines)
    trace_files = [
        f for _, _, fs in __import__("os").walk(tmp_path) for f in fs
    ]
    assert trace_files, "no profiler trace written"


def test_lr_schedule_parity():
    """lr = base * 0.95^(step//50) — sync_replicas_master_nn.py:106-107,232-234."""
    sched = stepwise_shrink(0.01, 0.95, 50)
    assert float(sched(0)) == pytest.approx(0.01)
    assert float(sched(49)) == pytest.approx(0.01)
    assert float(sched(50)) == pytest.approx(0.01 * 0.95)
    assert float(sched(250)) == pytest.approx(0.01 * 0.95**5)


def test_adam_amsgrad_variants_build():
    import optax

    for kwargs in (
        dict(name="adam"),
        dict(name="adam", amsgrad=True),
        dict(name="adam", weight_decay=1e-4),
        dict(name="sgd", momentum=0.9, nesterov=True, weight_decay=5e-4),
    ):
        opt = make_optimizer(**kwargs)
        assert isinstance(opt, optax.GradientTransformation)


def test_initialize_retries_transient_failure(monkeypatch):
    """The restart race: the coordinator is not listening yet on the first
    attempt; initialize() must back off and retry instead of dying (and
    must reset jax's half-initialized distributed state between tries)."""
    calls = []

    def flaky(**kw):
        calls.append(kw)
        if len(calls) == 1:
            raise RuntimeError("connect timed out")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "2")
    initialize(backoff=0.01)
    assert len(calls) == 2
    assert calls[1]["coordinator_address"] == "10.0.0.1:1234"


def test_fence_tree_returns_finite_scalar_and_fences():
    """PR-4: the shared device->host fence used by every phase timer —
    returns the fetched float (finiteness is the caller's validity
    check) and works on pytrees and bare arrays alike."""
    from atomo_tpu.utils.tracing import fence_tree

    v = fence_tree({"a": jax.numpy.arange(4.0), "b": jax.numpy.ones((2, 2))})
    assert v == 6.0
    assert fence_tree(jax.numpy.full((3,), float("nan"))) != fence_tree(
        jax.numpy.zeros((3,))
    )  # NaN propagates out where validity checks can see it
