"""Cross-replica sharded weight update (Xu et al., 2004.13336).

The replicated program keeps N copies of everything: params, momentum/Adam
buffers, and the weight-update computation all exist once per chip. ZeRO-1
(:func:`atomo_tpu.parallel.replicated.zero1_state`) sharded the optimizer
STATE and the update computation over the dp axis but kept the master
params replicated — each chip still persists the full dense model between
steps. This module finishes the move, per the paper's recipe:

  * **sharded-persistent master weights** — the flat padded parameter
    vector lives sharded over the data axes; each chip persistently holds
    its 1/N slice and nothing else. The dense model never persists
    per-chip: it is materialized TRANSIENTLY inside the step (one tiled
    all_gather) for forward/backward and discarded.
  * **sharded update computation** — the optimizer update runs on the
    (grad-slice, master-slice, opt-slice) triple, exactly the ZeRO-1
    sliced update; ZeRO-1 is now the degenerate "shard state only" point
    of this family.
  * **bit-identity** — the all_gather of exact slices reassembles the
    replicated params byte-for-byte, the PRNG folds from the same step
    counter, and the update is slice-invariant (probed at setup, same as
    ZeRO-1), so sharded-update trajectories are bit-identical to
    replicated ones per codec (tested per codec in tests/test_mesh.py).

Per-chip persistent state, P params / N chips (f32, momentum-SGD):
replicated 8P bytes; zero1 4P + 4P/N; sharded-update 8P/N — the memory
row bench config 15 (``sharded_update_memory``) measures from the actual
device buffers rather than asserts.

The carry is ordinary: a :class:`ShardedUpdateState` is a pytree of plain
arrays, so it rides ``lax.scan`` (superstep), checkpoints (``device_get``
gathers slices to full host arrays — restore re-shards), and the
``--overlap delayed`` :class:`~atomo_tpu.parallel.replicated
.OverlapCarry` unchanged — which is what dissolves the historical
``zero1 x delayed x supervision`` dead end: the in-flight payload is just
another sharded carry leaf next to the master slices.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


@flax.struct.dataclass
class ShardedUpdateState:
    """The sharded-persistent train state: ``master`` is the flat padded
    parameter vector sharded over the data axes ((n_shards * chunk,)
    global, one chunk per chip); ``opt_state`` holds the optimizer
    buffers on the same flat layout (the ZeRO-1 layout); ``batch_stats``
    and ``step`` stay replicated.

    ``params`` is a PLACEMENT VIEW ONLY (the master vector, for fencing /
    block_until_ready in loop plumbing that touches ``.params`` of any
    state family) — it is NOT the parameter pytree; materialize that with
    :meth:`ShardedUpdateSpecs.materialize_host` or in-graph via the tiled
    all_gather the train step performs."""

    step: Any
    master: Any
    batch_stats: Any
    opt_state: Any

    @property
    def params(self):
        return self.master


class ShardedUpdateSpecs:
    """Static build artifact of :func:`sharded_update_state`: the flat
    layout (chunk length, true size, unravel closure), the data axes the
    master shards over, and the PartitionSpec trees the one compile path
    (:func:`atomo_tpu.parallel.compile.compile_step`) annotates the pjit
    boundary with. One instance per run — the step builder closes over
    it, so there is exactly one layout definition the dynamic slices and
    the state allocations can agree on (the ZeRO-1 ONE-definition rule,
    inherited)."""

    def __init__(self, *, axes, n_shards, chunk, d_flat, unravel,
                 opt_specs):
        self.axes: tuple[str, ...] = tuple(axes)
        self.n_shards: int = n_shards
        self.chunk: int = chunk
        self.d_flat: int = d_flat
        self.unravel: Callable = unravel
        self.opt_specs = opt_specs

    @property
    def gather_axes(self):
        """The axis argument collectives take: the bare name on a flat
        mesh, the (outer, inner) tuple on a two-tier one."""
        return self.axes[0] if len(self.axes) == 1 else self.axes

    @property
    def master_spec(self):
        return P(self.axes)

    def state_spec(self) -> ShardedUpdateState:
        """The TrainState-of-PartitionSpecs the compile path consumes."""
        return ShardedUpdateState(
            step=P(), master=P(self.axes), batch_stats=P(),
            opt_state=self.opt_specs,
        )

    def materialize_host(self, master) -> Any:
        """Gather the master vector to host and unravel the parameter
        pytree — the eval/checkpoint-template view. ``master`` may be the
        global sharded array or an already-host array."""
        flat = jnp.asarray(jax.device_get(master))
        return self.unravel(flat[: self.d_flat])


def chunk_len(flat_size: int, n_shards: int) -> int:
    """Per-chip slice length of the flat sharded buffers. ONE definition
    shared by the allocations here and the train step's dynamic slices
    (:mod:`atomo_tpu.parallel.replicated` delegates its ZeRO-1 chunk to
    this), or every momentum slice silently misaligns with its parameter
    slice."""
    return -(-flat_size // n_shards)


def check_slice_invariant(optimizer, n_shards: int, dtype) -> None:
    """Validity probe for every sharded-update family (ZeRO-1 and full
    sharded-update alike): updating a SLICE of the flat param vector must
    equal the slice of the full-vector update — true for elementwise
    transforms (sgd momentum, adam, weight decay, per-element clipping)
    but silently FALSE for globally-mixing ones (e.g.
    optax.clip_by_global_norm, whose norm would be taken per-slice).
    Run the optimizer on a tiny vector, sliced and unsliced, at setup
    time; raise on divergence rather than train subtly wrong. The probe
    sweeps gradient SCALES (1, 1e4, 1e-4) because threshold-gated mixing
    only activates at some magnitudes."""
    probe_n = 8 * n_shards
    pk, gk = jax.random.split(jax.random.PRNGKey(17))
    p_full = jax.random.normal(pk, (probe_n,), dtype)
    g_base = jax.random.normal(gk, (probe_n,), dtype)
    chunk = probe_n // n_shards
    for scale in (1.0, 1e4, 1e-4):
        g_full = g_base * scale
        u_full, _ = optimizer.update(g_full, optimizer.init(p_full), p_full)
        parts = []
        for i in range(n_shards):
            p_i = p_full[i * chunk:(i + 1) * chunk]
            g_i = g_full[i * chunk:(i + 1) * chunk]
            u_i, _ = optimizer.update(g_i, optimizer.init(p_i), p_i)
            parts.append(u_i)
        ref = jnp.concatenate(parts)
        tol = 1e-5 * float(jnp.max(jnp.abs(u_full))) + 1e-12
        if not jnp.allclose(u_full, ref, rtol=1e-5, atol=tol):
            raise ValueError(
                "sharded update: this optimizer's update is not "
                f"slice-invariant (at gradient scale {scale:g}, a sliced "
                "update differs from the slice of the full update — e.g. "
                "a global-norm clip in the chain). Sharding the update "
                "would train silently wrong; use the replicated optimizer "
                "path or an elementwise chain (sgd/momentum/adam/wd)."
            )


def _flat_axes(mesh, axis) -> tuple[tuple[str, ...], int]:
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return axes, n


def flat_opt_state(mesh, optimizer, *, chunk, n_shards, axes, dtype):
    """ONE construction of the flat sharded optimizer state (the ZeRO-1
    layout, shared by ``zero1_state`` and :func:`sharded_update_state`):
    init on a per-chip zero chunk, tile vector buffers to one
    ``(n_shards * chunk,)`` global sharded over ``axes``, keep scalar
    leaves (counts) replicated. Returns ``(opt_global, opt_specs)`` —
    the placed state and its PartitionSpec tree."""
    local = optimizer.init(jnp.zeros((chunk,), dtype))

    def glob(leaf):
        leaf = jnp.asarray(leaf)
        if leaf.ndim == 0:  # counts etc.: replicated scalars
            return jax.device_put(leaf, NamedSharding(mesh, P()))
        # identical zero-init per shard; stored as one (n*chunk,) global
        return jax.device_put(
            jnp.tile(leaf, n_shards), NamedSharding(mesh, P(axes))
        )

    opt_global = jax.tree_util.tree_map(glob, local)
    opt_specs = jax.tree_util.tree_map(
        lambda l: P(axes) if jnp.asarray(l).ndim else P(), local
    )
    return opt_global, opt_specs


def sharded_update_state(
    mesh, state, optimizer, axis="dp"
) -> tuple[ShardedUpdateState, ShardedUpdateSpecs]:
    """Build the sharded-persistent state from a host/replicated
    ``TrainState``: ravel the params flat, pad to a multiple of the shard
    count, place the padded vector sharded over ``axis`` (a name, or the
    ("dp", "ici") tuple on a two-tier mesh), and init the optimizer on
    the flat layout exactly as ZeRO-1 does. Returns ``(state, specs)``;
    pass ``sharded_update=specs`` to ``make_distributed_train_step``.

    Degenerate meshes are first-class: on 1 device the chunk is the whole
    (padded) vector and the program is the replicated one with an
    identity all_gather."""
    from jax.flatten_util import ravel_pytree

    axes, n = _flat_axes(mesh, axis)
    flat, unravel = ravel_pytree(jax.device_get(state.params))
    check_slice_invariant(optimizer, n, flat.dtype)
    chunk = chunk_len(flat.size, n)
    pad = chunk * n - flat.size
    master = jnp.pad(flat, (0, pad))
    opt_global, opt_specs = flat_opt_state(
        mesh, optimizer, chunk=chunk, n_shards=n, axes=axes,
        dtype=flat.dtype,
    )
    specs = ShardedUpdateSpecs(
        axes=axes, n_shards=n, chunk=chunk, d_flat=flat.size,
        unravel=unravel, opt_specs=opt_specs,
    )
    new_state = ShardedUpdateState(
        step=jax.device_put(
            jnp.asarray(state.step), NamedSharding(mesh, P())
        ),
        master=jax.device_put(master, NamedSharding(mesh, P(axes))),
        batch_stats=jax.device_put(
            jax.device_get(state.batch_stats), NamedSharding(mesh, P())
        ),
        opt_state=opt_global,
    )
    return new_state, specs


def place_sharded_update(
    mesh, host_state: ShardedUpdateState, specs: ShardedUpdateSpecs
) -> ShardedUpdateState:
    """Place a host-side :class:`ShardedUpdateState` (a checkpoint
    restore, a reshard source) onto ``mesh`` with the layout ``specs``
    describe — resume and fresh init MUST place identically or a
    restored trajectory drifts from an uninterrupted one."""
    def put(tree, spec):
        sh = NamedSharding(mesh, spec)
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(jnp.asarray(a), sh), tree
        )

    return ShardedUpdateState(
        step=put(host_state.step, P()),
        master=put(host_state.master, specs.master_spec),
        batch_stats=put(host_state.batch_stats, P()),
        opt_state=jax.tree_util.tree_map(
            lambda a, sp: jax.device_put(
                jnp.asarray(a), NamedSharding(mesh, sp)
            ),
            host_state.opt_state,
            specs.opt_specs,
        ),
    )


def sharded_state_from_params(
    mesh, params, batch_stats, step, optimizer, axis="dp"
) -> tuple[ShardedUpdateState, ShardedUpdateSpecs]:
    """Rebuild a fresh-momentum sharded state from bare (params,
    batch_stats, step) — the layout-mismatch resume fallback (a
    replicated checkpoint restored into a sharded-update run, or a
    reshaped mesh): params carry over, the optimizer state re-initializes
    sharded, and the caller warns out loud exactly like the ZeRO-1
    fallback."""
    from atomo_tpu.training.trainer import TrainState

    state = TrainState(
        step=jnp.asarray(step, jnp.int32), params=params,
        batch_stats=batch_stats, opt_state=None,
    )
    return sharded_update_state(mesh, state, optimizer, axis=axis)
