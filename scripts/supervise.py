#!/usr/bin/env python
"""Run-level supervisor: wrap ANY train command in a crash-loop budget.

The `--max-restarts` CLI flag covers the common case (the trainer
re-execs itself); this script is the generic form for commands the CLI
does not own — launcher wrappers, multi-flag shell pipelines, other
entrypoints:

    python scripts/supervise.py --max-restarts 3 --restart-backoff 0.5 \
        --train-dir out/models -- \
        python -m atomo_tpu.cli train --synthetic --max-steps 200 ...

Semantics (training.resilience.run_supervised):
  * child exit 0                 -> clean exit, done (rc 0)
  * child exit 23 (ROLLBACK_EXIT_CODE: the in-process rollback budget is
    spent)                       -> prune the checkpoint timeline back to
    the newest HEALTHY step so --resume cannot land on diverged weights,
    then restart against the budget
  * child exit 29 (MEMBERSHIP_EXIT_CODE: an elastic membership epoch
    boundary)                    -> re-exec with --n-devices rewritten to
    the world size recorded in train-dir/membership.json and the epoch id
    in ATOMO_MEMBERSHIP_EPOCH — a planned reshape, never charged against
    the restart budget (requires --train-dir; a 29 without a newer
    recorded epoch is triaged as a crash)
  * any other nonzero exit       -> crash; restart against the budget
Restarts wait a decorrelated-jittered backoff and burn one unit of the
budget; exhaustion exits with the child's last code. When --train-dir is
given, restarts also append `--resume` (once) — resume is only meaningful
against a checkpoint dir, and an arbitrary wrapped command may not accept
the flag (--no-resume-flag suppresses it explicitly). Every decision is
one JSON line in train_dir/incidents.jsonl (utils.tracing.IncidentLog) —
the machine-readable post-mortem.

The child sees ATOMO_SUPERVISED=1 (so a supervised CLI run never
re-supervises itself) and ATOMO_RUN_ATTEMPT=<n> (the 0-based run index,
which attempt-keyed chaos like `crashloop@M` reads).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="supervise",
        description="crash-loop-budgeted supervisor for train commands",
    )
    parser.add_argument("--max-restarts", type=int, default=2, metavar="N")
    parser.add_argument("--restart-backoff", type=float, default=1.0,
                        metavar="SEC", help="backoff base seconds "
                        "(decorrelated jitter, capped at 30x)")
    parser.add_argument("--train-dir", type=str, default="",
                        help="checkpoint dir: enables healthy-checkpoint "
                        "pruning on rollback-requested exits and the "
                        "incidents.jsonl record")
    parser.add_argument("--no-resume-flag", action="store_true",
                        default=False,
                        help="do not append --resume to restarted commands "
                        "(--resume is only appended when --train-dir is "
                        "given; commands without the flag would otherwise "
                        "die parsing it on every restart)")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="the command to supervise (prefix with --)")
    args = parser.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no command given (append it after --)")

    from atomo_tpu.training.resilience import run_supervised

    resume = None
    if args.train_dir and not args.no_resume_flag:
        resume = "--resume"
    return run_supervised(
        cmd,
        max_restarts=args.max_restarts,
        backoff_base=args.restart_backoff,
        backoff_max=args.restart_backoff * 30,
        train_dir=args.train_dir or None,
        resume_flag=resume,
    )


if __name__ == "__main__":
    raise SystemExit(main())
