"""Flash-attention Pallas kernel vs the jnp oracles (TPU interpreter on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from atomo_tpu.ops.attention_kernels import flash_attention
from atomo_tpu.parallel.ring import blockwise_attention, full_attention


def _qkv(key, b=2, h=3, s=64, d=16):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(key), 3)
    return (
        jax.random.normal(kq, (b, h, s, d), jnp.float32),
        jax.random.normal(kk, (b, h, s, d), jnp.float32),
        jax.random.normal(kv, (b, h, s, d), jnp.float32),
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("blocks", [(16, 16), (32, 16), (64, 64)])
def test_flash_matches_full_attention(causal, blocks):
    q, k, v = _qkv(0)
    bq, bk = blocks
    got = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk)
    want = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_flash_non_tiling_falls_back():
    q, k, v = _qkv(1, s=50)  # 50 % 16 != 0 -> blockwise fallback
    got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    want = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_flash_gradients_match_full_attention():
    q, k, v = _qkv(2, b=1, h=2, s=32, d=8)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=16, block_k=16) ** 2
        )

    def loss_full(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_flash_bf16_inputs():
    q, k, v = _qkv(3, s=32, d=8)
    q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))
    got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    want = blockwise_attention(q, k, v, causal=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=3e-2
    )


def test_ulysses_with_flash_local_attention_matches_full():
    """sp=4 Ulysses with the Pallas flash kernel as its local attention ==
    unsharded full attention (collective swap + fused kernel compose)."""
    from jax.sharding import PartitionSpec as P

    from atomo_tpu.parallel.mesh import make_mesh
    from atomo_tpu.parallel.ring import ulysses_attention

    mesh = make_mesh(4, axes=(("sp", 4),))
    q, k, v = _qkv(4, b=2, h=4, s=64, d=16)
    want = full_attention(q, k, v, causal=True)
    fn = jax.jit(
        jax.shard_map(
            lambda q, k, v: ulysses_attention(
                q, k, v, axis_name="sp", axis_size=4, causal=True,
                block_size=16, local_impl="flash",
            ),
            mesh=mesh,
            in_specs=(P(None, None, "sp", None),) * 3,
            out_specs=P(None, None, "sp", None),
            check_vma=False,
        )
    )
    got = fn(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ulysses_rejects_unknown_local_impl():
    from atomo_tpu.parallel.ring import ulysses_attention

    q, k, v = _qkv(5, h=4, s=16, d=8)
    with pytest.raises(ValueError, match="local_impl"):
        # axis-free path never reached: validation precedes collectives
        ulysses_attention(
            q, k, v, axis_name="sp", axis_size=1, causal=True,
            local_impl="nope",
        )
