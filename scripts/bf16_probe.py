"""bf16-vs-f32 localization probe (VERDICT r3 weak #2: the --bf16 step
measured SLOWER than f32 on the v5e — 7.78-7.91 vs 6.50 ms — which inverts
the MXU's native-bf16 advantage; this script finds where the time goes).

Five scan-fenced timings on whatever backend jax resolves (meant for the
real chip; CPU numbers are not probative for the MXU question):

  matmul_f32 / matmul_bf16   pure (4096x4096)@(4096x4096) — the MXU sanity
                             anchor: bf16 MUST win here or the chip/axon
                             path itself is miscounting
  resnet_f32 / resnet_bf16   the full train-step pair bench.py compares
  convnet_f32 / convnet_bf16 the same ResNet-18 trunk with BatchNorm
                             REMOVED (GroupNorm-free plain conv stack):
                             if the bf16 regression disappears here, the
                             cost is BN's bf16 statistics path, not convs

Prints one JSON line with all numbers + the implied suspect.

Usage: python scripts/bf16_probe.py [--steps 20]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from atomo_tpu.models import get_model
    from atomo_tpu.training import create_state, make_optimizer, make_train_step

    dev = jax.devices()[0]
    steps = args.steps
    out = {"platform": dev.platform, "device": dev.device_kind, "steps": steps}

    def timed_scan(fn, *xs):
        """best-of-3 ms per iteration of `steps` scanned calls, scalar-fenced."""

        @jax.jit
        def many(*ys):
            def body(acc, _):
                r = fn(*[y + acc * 1e-30 for y in ys])
                return jnp.float32(jnp.sum(r) * 1e-20), None

            acc, _ = jax.lax.scan(body, jnp.float32(0), None, length=steps)
            return acc

        s = float(many(*xs))  # compile + warm
        if not math.isfinite(s):
            raise RuntimeError("sync scalar not finite")
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            float(many(*xs))
            best = min(best, (time.perf_counter() - t0) / steps)
        return round(best * 1e3, 3)

    # 1) MXU anchor
    for dt, tag in ((jnp.float32, "matmul_f32_ms"), (jnp.bfloat16, "matmul_bf16_ms")):
        a = jax.random.normal(jax.random.PRNGKey(0), (4096, 4096), dt)
        b = jax.random.normal(jax.random.PRNGKey(1), (4096, 4096), dt)
        out[tag] = timed_scan(
            lambda x, y: jnp.matmul(x, y).astype(jnp.float32), a, b
        )
        print(json.dumps({**out, "partial": True}), flush=True)

    # 2) the bench pair: full ResNet-18 train step
    model = get_model("resnet18", 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.9)
    rng = jax.random.PRNGKey(0)
    images = jax.random.uniform(rng, (128, 32, 32, 3), jnp.float32)
    labels = jax.random.randint(rng, (128,), 0, 10)

    def step_ms(compute_dtype):
        state = create_state(model, opt, rng, images)
        step = make_train_step(model, opt, compute_dtype=compute_dtype)
        key = jax.random.PRNGKey(1)

        @jax.jit
        def many(s0):
            def body(s, _):
                s, m = step(s, key, images, labels)
                return s, m["loss"]

            s_out, losses = jax.lax.scan(body, s0, None, length=steps)
            return losses[-1]

        float(many(state))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            float(many(state))
            best = min(best, (time.perf_counter() - t0) / steps)
        return round(best * 1e3, 3)

    out["resnet_f32_ms"] = step_ms(None)
    print(json.dumps({**out, "partial": True}), flush=True)
    out["resnet_bf16_ms"] = step_ms(jnp.bfloat16)
    print(json.dumps({**out, "partial": True}), flush=True)

    # 3) BN isolation: the same trunk shape with no BatchNorm at all
    class PlainConvNet(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            widths = (64, 64, 64, 128, 128, 256, 256, 512, 512)
            strides = (1, 1, 1, 2, 1, 2, 1, 2, 1)
            for w, s in zip(widths, strides):
                x = nn.Conv(w, (3, 3), strides=(s, s), use_bias=False)(x)
                x = nn.relu(x)
            x = x.mean(axis=(1, 2))
            return nn.Dense(10)(x)

    def conv_ms(dtype):
        net = PlainConvNet()
        params = net.init(rng, images)["params"]
        if dtype is not None:
            params_c = jax.tree_util.tree_map(
                lambda a: a.astype(dtype), params
            )
            im = images.astype(dtype)
        else:
            params_c, im = params, images

        def fwd_bwd(p, x):
            def loss(pp):
                lg = net.apply({"params": pp}, x)
                return jnp.mean(lg.astype(jnp.float32) ** 2)

            l, g = jax.value_and_grad(loss)(p)
            return l + sum(
                jnp.sum(a.astype(jnp.float32) ** 2) * 1e-20
                for a in jax.tree_util.tree_leaves(g)
            )

        return timed_scan(lambda x: fwd_bwd(params_c, x), im)

    out["convnet_f32_ms"] = conv_ms(None)
    print(json.dumps({**out, "partial": True}), flush=True)
    out["convnet_bf16_ms"] = conv_ms(jnp.bfloat16)

    mm_ok = out["matmul_bf16_ms"] < out["matmul_f32_ms"]
    conv_gain = out["convnet_f32_ms"] / max(out["convnet_bf16_ms"], 1e-9)
    resnet_gain = out["resnet_f32_ms"] / max(out["resnet_bf16_ms"], 1e-9)
    if not mm_ok:
        suspect = "backend: even the pure MXU matmul shows no bf16 win"
    elif conv_gain > 1.05 and resnet_gain < 1.0:
        suspect = (
            "BatchNorm: plain convs gain from bf16 but the BN'd train step "
            "loses — bf16 statistics/cast chain in BN is the regression"
        )
    elif conv_gain < 1.05:
        suspect = (
            "convolutions at CIFAR shapes: XLA already runs the f32 convs "
            "on bf16 MXU passes, so --bf16 only adds cast overhead"
        )
    else:
        suspect = "none: bf16 wins end-to-end on this session"
    out["suspect"] = suspect
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
