"""The run-side elastic controller — ties layers 1+2 together and owns
layer 3 (re-grow).

One :class:`ElasticCoordinator` per train loop. Its life cycle:

  adopt      at loop start: load (or begin) the membership history, check
             the epoch on disk matches the world this run was launched
             with, record the epoch-0 ``membership`` incident on a fresh
             run. A crash restart WITHIN an epoch adopts silently — the
             epoch is a property of the roster, not the process.
  observe    per step (or per superstep block): fold the guarded step's
             ``ok_bits`` series through the :class:`AbsenceTracker`.
             Between a member's death and the next checkpoint boundary
             the run just keeps training — the in-graph guard is already
             masking the dead member and computing the surviving-roster
             mean (``survivor_decode_mean``), so absence costs nothing
             but gradient variance (the unbiased-subset argument).
  maybe_transition
             at every periodic checkpoint boundary: if members are
             persistently absent and the shrink is viable (the global
             batch must divide the smaller world — an unviable shrink is
             recorded and the member stays carried), commit the next
             epoch; else if the run is below full strength and
             ``readmit_at`` has passed, commit a grow epoch back to the
             FULL roster. HOW a committed epoch reshapes the run is the
             ``reshard`` mode: under ``reshard="live"`` the loop's
             ``live`` callback re-slices the in-process state onto the
             new mesh (params + momentum carried exactly — a data
             movement, not a process death) and training continues in
             the same process; when the live path is not viable (the
             callback refuses, or no callback is wired) a
             ``reshard_fallback`` incident records WHY and the epoch
             record + incident land exactly as under
             ``reshard="reexec"``: raise
             :class:`~atomo_tpu.elastic.membership.MembershipChange`,
             which the CLI turns into MEMBERSHIP_EXIT_CODE; the
             supervisor re-execs at the new world size without charging
             the crash budget.

Re-grow (layer 3) is deliberately boundary-triggered, not mid-step: the
re-admitted member starts from the newest checkpoint with the shard map
re-derived (same stream, re-split over the larger roster), which is
exactly the documented re-shard every epoch transition performs — there
is no special-case "catch-up" path to get wrong.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from atomo_tpu.elastic.membership import (
    MembershipChange,
    MembershipEpoch,
    MembershipLog,
)
from atomo_tpu.elastic.shrink import AbsenceTracker


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """``--elastic`` knobs.

    patience:   consecutive guard-masked steps before a replica is
                declared ABSENT (one masked step is rung-1 noise).
    readmit_at: step at/after which a below-strength world re-grows to
                the full roster at the next checkpoint boundary (0 = no
                automatic re-admission; re-grow by relaunching with the
                full ``--n-devices`` by hand).
    reshard:    "live" | "reexec" — how a membership transition reshapes
                the run. "live" re-slices the in-process state onto the
                new mesh at the boundary (no exit, no re-exec, no
                checkpoint round-trip) and falls back to the rc=29
                re-exec protocol with a recorded ``reshard_fallback``
                incident whenever the in-process path is not viable
                (layout-owned state, mesh shape not buildable, fused
                superstep block). "reexec" is the PR-9 protocol
                unchanged. The dataclass default stays "reexec" so
                direct constructions keep their historical behavior;
                the CLI's ``--elastic-reshard`` flag defaults to live —
                the primary path.
    max_regrows: lifetime cap on AUTOMATIC re-admissions (counted as
                ``grow`` epochs in membership.json, so it survives
                restarts). A genuinely still-dead host would otherwise
                cycle shrink -> grow -> re-mask -> shrink forever —
                every cycle a full re-exec + recompile that no restart
                budget bounds (membership re-execs are deliberately
                budget-free, and each one records a strictly newer
                epoch, so the supervisor's runaway guard never fires).
                Past the cap the world stays shrunken; re-grow by hand.
    """

    patience: int = 6
    readmit_at: int = 0
    max_regrows: int = 1
    reshard: str = "reexec"

    def __post_init__(self):
        if self.reshard not in ("live", "reexec"):
            raise ValueError(
                f"elastic reshard mode must be 'live' or 'reexec', "
                f"got {self.reshard!r}"
            )
        if self.patience < 1:
            raise ValueError(
                f"elastic patience must be >= 1, got {self.patience}"
            )
        if self.readmit_at < 0:
            raise ValueError(
                f"--readmit-at must be >= 0, got {self.readmit_at}"
            )
        if self.max_regrows < 0:
            raise ValueError(
                f"max_regrows must be >= 0, got {self.max_regrows}"
            )


class ElasticCoordinator:
    """Host-side membership controller for one train loop (see module
    docstring). ``batch_size`` is the GLOBAL batch the loop feeds —
    shrink viability is batch divisibility over the smaller world.
    ``max_steps`` suppresses transitions at or past the end of the run
    (a reshape that would immediately exit cleanly is a wasted re-exec).
    """

    def __init__(
        self,
        cfg: ElasticConfig,
        train_dir: Optional[str],
        *,
        n_dev: int,
        batch_size: int,
        max_steps: int = 0,
        incidents=None,
        log_fn=print,
    ):
        self.cfg = cfg
        self.train_dir = train_dir
        self.n_dev = int(n_dev)
        self.batch_size = int(batch_size)
        self.max_steps = int(max_steps)
        self.incidents = incidents
        self.log_fn = log_fn
        self.log = MembershipLog.load(train_dir)
        self.tracker = AbsenceTracker(self.n_dev, cfg.patience)
        self.pending_dead: set[int] = set()
        self._carry_logged = False
        self.epoch: Optional[MembershipEpoch] = None
        self._rng_crc = None  # run-start stream fingerprint (see adopt)

    # -- lifecycle ------------------------------------------------------

    def _shard_map(self, start_step: int, world: int, rng_crc=None) -> dict:
        """The deterministic data-shard derivation this epoch trains
        under (membership.py module docstring): contiguous split of the
        seed-deterministic batch stream, replayed past ``start_step``
        consumed batches."""
        sm = {
            "kind": "contiguous",
            "batch_size": self.batch_size,
            "per_replica": self.batch_size // max(world, 1),
            "skip": int(start_step),
        }
        if rng_crc is not None:
            sm["rng_crc"] = int(rng_crc)
        return sm

    def _device_detail(self) -> dict:
        try:
            from atomo_tpu.parallel.launch import device_roster

            return {"devices": device_roster(self.n_dev)}
        except Exception:  # noqa: BLE001 — detail is best-effort context
            return {}

    def _incident(self, action: str, rec: MembershipEpoch, **extra) -> None:
        if self.incidents is not None:
            self.incidents.append(
                "membership",
                action=action,
                step=rec.start_step,
                epoch=rec.epoch,
                world=rec.world_size,
                roster=list(rec.roster),
                **extra,
            )

    def adopt(self, start_step: int, rng_crc=None) -> MembershipEpoch:
        """Bind this run to the membership history: begin epoch 0 on a
        fresh run, adopt the recorded epoch when the world matches, or
        record an ``operator_resize`` epoch when the operator relaunched
        at a world size no transition planned (say it out loud — a
        silent mismatch would make the per-epoch records lie).

        ``rng_crc`` is the run-start shuffle-RNG fingerprint
        (``BatchIterator.rng_signature`` taken BEFORE ``forever()``). It
        is a pure function of the data seed, so every restart of the
        same run reproduces it — it is kept and stamped into EVERY epoch
        record this coordinator appends (including the shrink/grow
        transitions planned later in the run), so each epoch's shard_map
        pins the stream state its derivation replays from."""
        self._rng_crc = rng_crc
        cur = self.log.latest()
        if cur is None:
            rec = MembershipEpoch(
                epoch=0,
                world_size=self.n_dev,
                roster=tuple(range(self.n_dev)),
                start_step=start_step,
                reason="init",
                shard_map=self._shard_map(start_step, self.n_dev, rng_crc),
                detail=self._device_detail(),
            )
            self.log.append(rec)
            self._incident("begin", rec)
            self.log_fn(
                f"Elastic: membership epoch 0 begins (world {self.n_dev}, "
                f"roster {list(rec.roster)})"
            )
        elif cur.world_size != self.n_dev:
            full = self.log.full_world
            if self.n_dev == full:
                roster = tuple(range(full))
            else:
                roster = tuple(cur.roster[: self.n_dev]) if (
                    self.n_dev < cur.world_size
                ) else tuple(range(self.n_dev))
            rec = MembershipEpoch(
                epoch=cur.epoch + 1,
                world_size=self.n_dev,
                roster=roster,
                start_step=start_step,
                reason="operator_resize",
                shard_map=self._shard_map(start_step, self.n_dev, rng_crc),
                detail=self._device_detail(),
            )
            self.log.append(rec)
            self._incident("resize", rec, from_world=cur.world_size)
            self.log_fn(
                f"Elastic: operator resize {cur.world_size} -> "
                f"{self.n_dev}; membership epoch {rec.epoch} recorded"
            )
        else:
            self.log_fn(
                f"Elastic: membership epoch {cur.epoch} adopted "
                f"(world {cur.world_size}) at step {start_step}"
            )
        self.epoch = self.log.latest()
        # cross-check the supervisor's epoch env against the adopted
        # record: the env is what epoch-keyed chaos (die@) reads, so a
        # stale value means the drill faults key on the wrong epoch —
        # say so in the log AND the incident stream instead of silently
        # adopting (world size alone cannot distinguish epochs)
        import os

        from atomo_tpu.utils.tracing import MEMBERSHIP_EPOCH_ENV

        env_epoch = int(os.environ.get(MEMBERSHIP_EPOCH_ENV, "0") or 0)
        if env_epoch and env_epoch != self.epoch.epoch:
            self.log_fn(
                f"Elastic: WARNING {MEMBERSHIP_EPOCH_ENV}={env_epoch} "
                f"disagrees with the adopted membership epoch "
                f"{self.epoch.epoch} — epoch-keyed chaos (die@) will key "
                "on the env value; fix the launcher or unset the var"
            )
            if self.incidents is not None:
                self.incidents.append(
                    "membership",
                    action="epoch_env_mismatch",
                    step=start_step,
                    epoch=self.epoch.epoch,
                    world=self.n_dev,
                    env_epoch=env_epoch,
                )
        return self.epoch

    # -- observation ----------------------------------------------------

    def observe(self, first_step: int, metrics) -> None:
        """Fold a fetched metrics dict's ``ok_bits`` (per-step scalar or a
        superstep block's ``(K,)`` series) through the absence tracker."""
        bits = metrics.get("ok_bits")
        if bits is None:
            return
        for i, slot in self.tracker.observe_series(bits):
            member = self.epoch.roster[slot] if self.epoch else slot
            self.pending_dead.add(slot)
            self.log_fn(
                f"Elastic: replica {slot} (member {member}) absent "
                f"for {self.cfg.patience} consecutive steps at step "
                f"{first_step + i}; shrink planned for the next "
                "checkpoint boundary (carried masked — the exact "
                "surviving-roster mean — until then)"
            )

    # -- transitions ----------------------------------------------------

    def reshard_spec(self, new_world: int):
        """The target mesh shape of a reshape, in the one mesh grammar
        (:class:`~atomo_tpu.mesh.spec.MeshSpec`) — recorded with every
        shrink/grow incident so the reshape is a mesh-shape transition in
        the artifact record, not a bare device count. Elastic meshes are
        flat by construction (the coordinator rejects hierarchical
        runs)."""
        from atomo_tpu.mesh import MeshSpec

        return MeshSpec.from_world(new_world)

    def reshard_live(self, state, specs, optimizer, *, new_world: int):
        """Reshape as DATA MOVEMENT: re-shard a live sharded-update state
        onto the shrunken/grown flat mesh without exiting the process
        (:func:`atomo_tpu.mesh.reshard.reshard_sharded_update` — gathers
        once, re-slices, continues the same optimizer trajectory).
        Returns ``(new_mesh, new_state, new_specs)``.

        This is the sharded-update flavor of the in-process reshape;
        the elastic train loop's replicated flavor is
        :func:`atomo_tpu.mesh.reshard.reshard_replicated`, driven at
        membership boundaries by :meth:`maybe_transition` under
        ``reshard="live"``. The exit-and-re-exec protocol
        (:class:`MembershipChange` -> rc=29 -> supervisor relaunch) is
        the recorded FALLBACK — the only correct move when the dead
        replica took its host process down. Drilled directly in
        tests/test_mesh.py."""
        from atomo_tpu.mesh.reshard import reshard_sharded_update

        new_mesh = self.reshard_spec(new_world).build()
        new_state, new_specs = reshard_sharded_update(
            state, specs, new_mesh, optimizer
        )
        return new_mesh, new_state, new_specs

    def _commit_live(self, rec: MembershipEpoch) -> None:
        """Internal reset after a successful IN-PROCESS reshape: this
        coordinator now governs the new world — same fields a re-exec'd
        child would construct fresh, minus the process death. The
        absence tracker restarts empty (mesh slots renumbered) and the
        one-shot carry guard re-arms (a later unviable shrink in the
        new epoch deserves its own incident)."""
        self.n_dev = rec.world_size
        self.epoch = rec
        self.tracker = AbsenceTracker(self.n_dev, self.cfg.patience)
        self.pending_dead.clear()
        self._carry_logged = False

    def _commit(self, kind: str, rec: MembershipEpoch, live, **incident_kw):
        """Make a due transition durable and reshape the run.

        Under ``reshard="live"`` with a wired ``live`` callback, try the
        in-process path first: the callback attempts the reshape and
        returns ``(ok, why)``. On ok the epoch record + incident land
        (tagged ``reshard="live"``) and the loop continues in-process —
        no exception, no exit. On refusal a ``reshard_fallback``
        incident records exactly why the live path was not taken, and
        the re-exec protocol proceeds unchanged. Re-exec mode (or no
        callback under live mode — e.g. a loop that never wired one)
        goes straight to the protocol: append, incident, raise."""
        live_mode = self.cfg.reshard == "live"
        if live_mode and live is not None:
            ok, why = live(kind, rec)
            if ok:
                self.log.append(rec)
                self._incident(kind, rec, reshard="live", **incident_kw)
                self.log_fn(
                    f"Elastic: LIVE {kind} {self.n_dev} -> "
                    f"{rec.world_size} at checkpoint step "
                    f"{rec.start_step} (membership epoch {rec.epoch}) — "
                    "state re-sliced in-process, no re-exec"
                )
                self._commit_live(rec)
                return
        else:
            why = (
                "re-exec mode configured (--elastic-reshard reexec)"
                if not live_mode
                else "no live reshard path wired into this loop"
            )
        if live_mode and self.incidents is not None:
            # the acceptance bar: re-exec only ever happens WITH a
            # recorded reason under live mode
            self.incidents.append(
                "membership",
                action="reshard_fallback",
                step=rec.start_step,
                epoch=rec.epoch,
                world=rec.world_size,
                reason=why,
            )
        if live_mode:
            self.log_fn(
                f"Elastic: live reshard not taken ({why}); falling back "
                "to the re-exec protocol"
            )
        self.log.append(rec)
        self._incident(kind, rec, **incident_kw)
        raise MembershipChange(kind, rec)

    def maybe_transition(self, step: int, live=None) -> None:
        """Call at every periodic checkpoint boundary (AFTER the save
        landed — the next epoch resumes from it). ``live`` is the
        loop's in-process reshape callback ``(kind, rec) -> (ok, why)``
        (used only under ``reshard="live"``): on ok the loop has
        already re-sliced its state/mesh/program for ``rec`` and this
        method returns normally; otherwise raises
        :class:`MembershipChange` when a transition is due; plain
        return when none is."""
        if self.epoch is None or (self.max_steps and step >= self.max_steps):
            return
        if self.pending_dead:
            new_world = self.n_dev - len(self.pending_dead)
            # viability must match what the RE-EXEC'D child will accept:
            # elastic itself needs a multi-device mesh, so a shrink to 1
            # survivor would hand the supervisor a child that dies on its
            # own preflight (rc=2, give-up) — carry instead
            if new_world < 2 or self.batch_size % new_world:
                if not self._carry_logged:
                    self._carry_logged = True
                    why = (
                        f"global batch {self.batch_size} does not divide "
                        f"over {new_world} survivors"
                        if new_world >= 2
                        else f"{new_world} survivor(s) cannot form a "
                        "multi-device elastic mesh"
                    )
                    self.log_fn(
                        f"Elastic: cannot shrink to world {new_world} "
                        f"({why}); carrying the absent member(s) masked "
                        "for the rest of the run"
                    )
                    if self.incidents is not None:
                        self.incidents.append(
                            "membership",
                            action="carry",
                            step=step,
                            epoch=self.epoch.epoch,
                            world=self.n_dev,
                            reason=why,
                            dead=sorted(
                                self.epoch.roster[s]
                                for s in self.pending_dead
                            ),
                        )
                return
            dead_members = sorted(
                self.epoch.roster[s] for s in self.pending_dead
            )
            roster = tuple(
                m for m in self.epoch.roster if m not in dead_members
            )
            rec = MembershipEpoch(
                epoch=self.epoch.epoch + 1,
                world_size=new_world,
                roster=roster,
                start_step=step,
                reason="shrink",
                dead=tuple(dead_members),
                shard_map=self._shard_map(step, new_world, self._rng_crc),
            )
            self.log_fn(
                f"Elastic: shrinking {self.n_dev} -> {new_world} at "
                f"checkpoint step {step} (member(s) {dead_members} left; "
                f"membership epoch {rec.epoch}); data stream re-shards "
                "deterministically over the surviving roster"
            )
            self._commit(
                "shrink", rec, live,
                dead=dead_members, from_world=self.n_dev,
                mesh_axes=self.reshard_spec(new_world).shape_dict(),
            )
            return
        if (
            self.cfg.readmit_at
            and step >= self.cfg.readmit_at
            and self.n_dev < self.log.full_world
        ):
            grows = sum(e.reason == "grow" for e in self.log.epochs)
            if grows >= self.cfg.max_regrows:
                # the flap guard (see ElasticConfig.max_regrows): a
                # member that died AGAIN after re-admission stays out
                if not self._carry_logged:
                    self._carry_logged = True
                    self.log_fn(
                        f"Elastic: re-admission budget spent ({grows} "
                        f"grow epoch(s) recorded, max_regrows="
                        f"{self.cfg.max_regrows}); staying at world "
                        f"{self.n_dev} — re-grow by relaunching with "
                        "the full --n-devices by hand"
                    )
                    if self.incidents is not None:
                        self.incidents.append(
                            "membership",
                            action="regrow_budget_spent",
                            step=step,
                            epoch=self.epoch.epoch,
                            world=self.n_dev,
                            regrows=grows,
                        )
                return
            full = self.log.full_world
            rec = MembershipEpoch(
                epoch=self.epoch.epoch + 1,
                world_size=full,
                roster=tuple(range(full)),
                start_step=step,
                reason="grow",
                shard_map=self._shard_map(step, full, self._rng_crc),
            )
            self.log_fn(
                f"Elastic: re-admitting to the full roster "
                f"({self.n_dev} -> {full}) at checkpoint step {step} "
                f"(membership epoch {rec.epoch}); the shard map is "
                "re-derived over the full roster"
            )
            self._commit(
                "grow", rec, live,
                from_world=self.n_dev,
                mesh_axes=self.reshard_spec(full).shape_dict(),
            )
