"""CLI surface tests: flag parity with src/distributed_nn.py:31-82, subcommand
dispatch, end-to-end smoke train, tuning parser contract."""

import time
import warnings

import pytest

from atomo_tpu.cli import build_parser, main
from atomo_tpu.tuning import DEFAULT_GRID, parse_worker_lines


REFERENCE_FLAGS = [
    # every flag the reference CLI accepts (distributed_nn.py:31-82)
    "--batch-size", "--test-batch-size", "--max-steps", "--epochs", "--lr",
    "--momentum", "--lr-shrinkage", "--no-cuda", "--seed", "--log-interval",
    "--network", "--code", "--bucket-size", "--dataset", "--comm-type",
    "--num-aggregate", "--eval-freq", "--train-dir", "--compress",
    "--enable-gpu", "--svd-rank", "--quantization-level",
]


def test_reference_flag_parity():
    parser = build_parser()
    sub = next(
        a for a in parser._actions if hasattr(a, "choices") and a.choices
    )
    train = sub.choices["train"]
    known = {s for a in train._actions for s in a.option_strings}
    missing = [f for f in REFERENCE_FLAGS if f not in known]
    assert not missing, f"reference flags missing from CLI: {missing}"


def test_bare_flags_behave_like_train(tmp_path):
    """`python -m atomo_tpu --network LeNet ...` == reference invocation."""
    rc = main([
        "--network", "LeNet", "--dataset", "MNIST", "--synthetic",
        "--batch-size", "8", "--max-steps", "2", "--eval-freq", "0",
        "--log-interval", "0", "--train-dir", str(tmp_path), "--n-devices", "1",
        "--momentum", "0.0",
    ])
    assert rc == 0


@pytest.mark.slow
def test_train_svd_smoke_with_checkpoint(tmp_path):
    rc = main([
        "train", "--network", "LeNet", "--dataset", "MNIST", "--synthetic",
        "--batch-size", "8", "--max-steps", "2", "--eval-freq", "2",
        "--save-freq", "2", "--log-interval", "0",
        "--train-dir", str(tmp_path), "--n-devices", "1",
        "--code", "svd", "--svd-rank", "2", "--momentum", "0.0",
    ])
    assert rc == 0
    assert (tmp_path / "model_step_2").exists()  # reference naming


def test_evaluate_subcommand(tmp_path):
    main([
        "train", "--network", "LeNet", "--dataset", "MNIST", "--synthetic",
        "--batch-size", "8", "--max-steps", "2", "--save-freq", "2",
        "--eval-freq", "0", "--log-interval", "0",
        "--train-dir", str(tmp_path), "--n-devices", "1", "--momentum", "0.0",
    ])
    rc = main([
        "evaluate", "--network", "LeNet", "--dataset", "MNIST", "--synthetic",
        "--test-batch-size", "32", "--model-dir", str(tmp_path),
        "--max-polls", "1", "--stop-when-idle", "--momentum", "0.0",
    ])
    assert rc == 0


def test_dead_flags_warn_not_crash(tmp_path):
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        rc = main([
            "train", "--network", "LeNet", "--dataset", "MNIST", "--synthetic",
            "--batch-size", "8", "--max-steps", "1", "--eval-freq", "0",
            "--log-interval", "0", "--train-dir", str(tmp_path),
            "--n-devices", "1", "--momentum", "0.0",
            "--comm-type", "Isend", "--num-aggregate", "3", "--enable-gpu",
        ])
    assert rc == 0
    text = " ".join(str(x.message) for x in w)
    assert "comm-type" in text and "num-aggregate" in text


def test_unknown_network_errors():
    with pytest.raises(ValueError):
        main([
            "train", "--network", "NopeNet", "--dataset", "MNIST",
            "--synthetic", "--max-steps", "1", "--n-devices", "1",
        ])


def test_tuning_parser_contract():
    """The regex must parse StepMetrics.worker_line output — the contract the
    reference's tiny_tuning_parser.py:17-19 relies on."""
    from atomo_tpu.utils.metrics import StepMetrics

    line = StepMetrics(
        rank=1, step=42, epoch=3, samples_seen=128, dataset_size=1000,
        loss=1.2345, time_cost=0.5, msg_bytes=1 << 20, prec1=55.0, prec5=90.0,
    ).worker_line()
    losses = parse_worker_lines(line, step=42)
    assert losses == [1.2345]
    assert parse_worker_lines(line, step=41) == []


def test_default_grid_matches_reference():
    # tune.sh:7 sweeps 2^-7 .. 2^-1
    assert DEFAULT_GRID == [2.0**-k for k in range(7, 0, -1)]


def test_tune_subcommand_smoke(capsys):
    rc = main([
        "tune", "--network", "LeNet", "--dataset", "MNIST", "--synthetic",
        "--batch-size", "8", "--grid", "0.1,0.01", "--tuning-steps", "3",
        "--window", "2", "--n-devices", "1", "--momentum", "0.0",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "best lr:" in out


@pytest.mark.parametrize(
    "layout,extra",
    [
        ("dp", []),
        ("dp-sp", ["--ways", "2", "--attn-impl", "ring"]),
        ("dp-sp", ["--ways", "2", "--attn-impl", "ulysses"]),
        ("dp-sp", ["--ways", "2", "--attn-impl", "ulysses-flash"]),
        ("dp-tp", ["--ways", "2"]),
        ("dp-tp", ["--ways", "2", "--bf16"]),
        ("dp-ep", ["--ways", "2", "--num-experts", "4"]),
        ("dp-pp", ["--ways", "2", "--microbatches", "2"]),
    ],
)
@pytest.mark.slow
def test_lm_subcommand_all_layouts(layout, extra, capsys):
    """Every parallelism layout is drivable end-to-end from the CLI on the
    8-device CPU mesh and prints the LM log line with a finite loss."""
    rc = main([
        "lm", "--layout", layout, "--vocab-size", "16", "--seq-len", "8",
        "--width", "16", "--depth", "2", "--num-heads", "2",
        "--batch-size", "8", "--max-steps", "2", "--log-interval", "1",
        "--n-devices", "4", "--code", "svd", "--svd-rank", "2",
        "--aggregate", "gather",  # pin the compressed wire the Msg assert checks
        *extra,
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert f"Layout: {layout}" in out
    import re

    losses = [float(m) for m in re.findall(r"Loss: ([0-9.]+)", out)]
    assert losses and all(l == l for l in losses)
    msgs = [float(m) for m in re.findall(r"Msg\(MB\): ([0-9.]+)", out)]
    dense = [float(m) for m in re.findall(r"Dense\(MB\): ([0-9.]+)", out)]
    assert msgs[-1] < dense[-1]  # svd codec actually compresses


def test_lm_subcommand_rejects_bad_ways():
    with pytest.raises(SystemExit):
        main(["lm", "--layout", "dp-tp", "--ways", "3", "--n-devices", "4"])


@pytest.mark.slow
def test_lm_data_file_byte_corpus(tmp_path, capsys):
    """--data-file trains on raw bytes of a real file (vocab 256)."""
    corpus = tmp_path / "corpus.txt"
    corpus.write_bytes((b"the quick brown fox jumps over the lazy dog. " * 40))
    rc = main([
        "lm", "--layout", "dp", "--data-file", str(corpus),
        "--vocab-size", "256", "--seq-len", "8", "--width", "16",
        "--depth", "1", "--num-heads", "2", "--batch-size", "8",
        "--max-steps", "2", "--log-interval", "1", "--n-devices", "2",
        "--code", "svd", "--svd-rank", "2", "--eval-freq", "2",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "PPL:" in out
    # --eval-freq with --data-file: held-out chunks (last 10%) validate
    assert "LM Validation: Step: 2" in out


def test_lm_data_file_rejects_small_vocab(tmp_path):
    corpus = tmp_path / "c.bin"
    corpus.write_bytes(b"x" * 1000)
    with pytest.raises(SystemExit, match="vocab-size"):
        main([
            "lm", "--data-file", str(corpus), "--vocab-size", "16",
            "--seq-len", "8", "--n-devices", "2",
        ])


@pytest.mark.slow
def test_train_zero1_multidevice(tmp_path, capsys):
    rc = main([
        "train", "--network", "LeNet", "--dataset", "MNIST", "--synthetic",
        "--batch-size", "8", "--max-steps", "2", "--eval-freq", "0",
        "--log-interval", "1", "--train-dir", str(tmp_path),
        "--n-devices", "4", "--code", "svd", "--svd-rank", "2",
        "--momentum", "0.9", "--zero1",
    ])
    assert rc == 0
    assert "Step: 2" in capsys.readouterr().out


@pytest.mark.slow
def test_lm_checkpoint_resume_sharded_layout(tmp_path, capsys):
    """lm --train-dir/--resume round-trips a MODEL-SHARDED (dp-tp) state:
    the checkpoint gathers from sharded buffers and restores onto the mesh
    shardings via load_sharded_checkpoint's shard_state path."""
    common = [
        "lm", "--layout", "dp-tp", "--ways", "2", "--vocab-size", "16",
        "--seq-len", "8", "--width", "16", "--depth", "1", "--num-heads", "2",
        "--batch-size", "8", "--log-interval", "1", "--n-devices", "4",
        "--code", "svd", "--svd-rank", "2", "--train-dir", str(tmp_path),
    ]
    assert main([*common, "--max-steps", "2"]) == 0
    assert (tmp_path / "model_step_2").exists()
    assert main([*common, "--max-steps", "4", "--resume"]) == 0
    out = capsys.readouterr().out
    assert "Resumed from" in out and "Step: 4" in out
    assert (tmp_path / "model_step_4").exists()


@pytest.mark.parametrize(
    "layout,extra",
    [
        ("dp", []),
        ("dp-tp", ["--ways", "2"]),
        ("dp-ep", ["--ways", "2", "--num-experts", "4"]),
        ("dp-pp", ["--ways", "2", "--microbatches", "2"]),
    ],
)
@pytest.mark.slow
def test_lm_eval_freq_prints_validation(layout, extra, capsys):
    """--eval-freq prints a held-out validation line for every layout via
    its single-device oracle forward on the gathered params."""
    rc = main([
        "lm", "--layout", layout, "--vocab-size", "16", "--seq-len", "8",
        "--width", "16", "--depth", "2", "--num-heads", "2",
        "--batch-size", "8", "--max-steps", "2", "--log-interval", "2",
        "--n-devices", "4", "--code", "svd", "--svd-rank", "2",
        "--eval-freq", "2", *extra,
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "LM Validation: Step: 2" in out
    import re

    vls = [float(m) for m in re.findall(r"Validation: Step: 2, Loss: ([0-9.]+)", out)]
    assert vls and all(v == v for v in vls)
    if layout == "dp-ep":
        # ADVICE r3 #5: dp-ep also reports CE under the TRAINING per-chip
        # drop regime (chunked forward at the training capacity)
        m = re.search(r"Loss@TrainCap: ([0-9.]+) \(C=(\d+)\)", out)
        assert m, "dp-ep validation must include the train-capacity CE"
        assert float(m.group(1)) == float(m.group(1))  # finite
        # C must be the per-chip budget: ceil(1.25 * (8/4)*8 / 4) = 5
        assert int(m.group(2)) == 5


def test_overlap_flag_surface():
    """PR-4: the --overlap flag parses with its two modes and defaults to
    off (the byte-for-byte blocking program)."""
    parser = build_parser()
    sub = next(
        a for a in parser._actions if hasattr(a, "choices") and a.choices
    )
    train = sub.choices["train"]
    act = next(a for a in train._actions if "--overlap" in a.option_strings)
    assert act.default == "off"
    assert sorted(act.choices) == ["delayed", "off"]
    args = train.parse_args(["--overlap", "delayed"])
    assert args.overlap == "delayed"
    with pytest.raises(SystemExit):
        train.parse_args(["--overlap", "eager"])


# ---------------- PR 5: divergence-doctor / supervisor flags ----------------


def test_on_diverge_flag_validation():
    # densify needs a compressing codec
    with pytest.raises(SystemExit):
        main([
            "train", "--synthetic", "--n-devices", "1", "--max-steps", "1",
            "--code", "sgd", "--on-diverge", "densify",
            "--train-dir", "/tmp/nonexistent-unused",
        ])
    # --phase-metrics has no doctor wiring
    with pytest.raises(SystemExit):
        main([
            "train", "--synthetic", "--n-devices", "2", "--max-steps", "1",
            "--code", "svd", "--on-diverge", "skip", "--phase-metrics",
            "--train-dir", "/tmp/nonexistent-unused",
        ])
    # densify cannot compose with the delayed overlap
    with pytest.raises(SystemExit):
        main([
            "train", "--synthetic", "--n-devices", "2", "--max-steps", "1",
            "--code", "qsgd", "--aggregate", "gather",
            "--overlap", "delayed", "--on-diverge", "densify",
            "--train-dir", "/tmp/nonexistent-unused",
        ])
    # densify cannot compose with hierarchical aggregation (hierarchical
    # needs a codec, so without this guard the conflict surfaced as an
    # uncaught ValueError at ROLLBACK time, after the timeline was pruned)
    with pytest.raises(SystemExit):
        main([
            "train", "--synthetic", "--n-devices", "2", "--max-steps", "1",
            "--code", "qsgd", "--aggregate", "hierarchical",
            "--on-diverge", "densify",
            "--train-dir", "/tmp/nonexistent-unused",
        ])
    # a config conflict must fail fast in the supervisor PARENT (argv-level
    # pre-flight), not re-exec children through the whole restart budget;
    # under supervision the old path took >= 2 backoffs before giving up
    for typo in (
        ["--code", "sgd", "--on-diverge", "densify"],
        ["--superstep", "-1"],
        ["--code", "qsgd", "--overlap", "delayed", "--aggregate", "psum"],
        ["--chaos", "frob@3"],
    ):
        t0 = time.monotonic()
        with pytest.raises(SystemExit):
            main([
                "train", "--synthetic", "--n-devices", "1", "--max-steps",
                "1", "--max-restarts", "5", "--restart-backoff", "30",
                "--train-dir", "/tmp/nonexistent-unused", *typo,
            ])
        assert time.monotonic() - t0 < 10  # no re-exec, no backoff sleeps


def test_on_diverge_preflight_symmetry():
    """_argv_preflight mirrors the in-run conflict gate: multi-device-only
    features are claimed only when the mesh can be multi-device, and every
    argv-knowable conflict (num-aggregate, retention-vs-window) fails fast
    in the supervisor parent instead of burning the restart budget."""
    from atomo_tpu.cli import _argv_preflight, build_parser

    parser = build_parser()
    sub = next(
        a for a in parser._actions if hasattr(a, "choices") and a.choices
    )
    train = sub.choices["train"]

    def preflight(*argv):
        _argv_preflight(train.parse_args(
            ["--synthetic", "--train-dir", "/tmp/unused", *argv]
        ))

    # argv-knowable densify x num-aggregate conflict: caught pre-exec
    with pytest.raises(SystemExit) as ei:
        preflight("--code", "qsgd", "--on-diverge", "densify",
                  "--num-aggregate", "2", "--n-devices", "2")
    assert "num-aggregate" in str(ei.value)
    # zero1 is multi-device-only: claimed on a mesh, ignored at n-devices 1
    with pytest.raises(SystemExit) as ei:
        preflight("--code", "qsgd", "--on-diverge", "skip",
                  "--zero1", "--n-devices", "4")
    assert "zero1" in str(ei.value)
    # --n-devices 1 disables the multi-device features: the in-run check
    # passes None for them, and preflight must not reject what it accepts
    preflight("--code", "qsgd", "--on-diverge", "densify",
              "--num-aggregate", "2", "--n-devices", "1")
    preflight("--code", "qsgd", "--on-diverge", "densify",
              "--aggregate", "hierarchical", "--n-devices", "1")
    preflight("--code", "qsgd", "--on-diverge", "skip",
              "--zero1", "--n-devices", "1")
    # keep-last-K retention shorter than the healthy-tag window
    with pytest.raises(SystemExit) as ei:
        preflight("--code", "sgd", "--on-diverge", "skip", "--n-devices",
                  "1", "--keep-ckpts", "1", "--save-freq", "2",
                  "--diverge-window", "16")
    assert "keep-ckpts" in str(ei.value)
    # supervised restarts append --resume, and a --zero1 run cannot resume
    # the delayed in-flight payload: every restart would fail instantly
    with pytest.raises(SystemExit) as ei:
        preflight("--code", "qsgd", "--overlap", "delayed", "--zero1",
                  "--n-devices", "4", "--max-restarts", "2")
    assert "zero1" in str(ei.value)
    # with checkpointing disabled (--train-dir "") resume is a no-op, so
    # supervised fresh restarts of a zero1+delayed run are fine
    _argv_preflight(train.parse_args(
        ["--synthetic", "--train-dir", "", "--code", "qsgd", "--overlap",
         "delayed", "--zero1", "--n-devices", "4", "--max-restarts", "2"]
    ))
    # a typo'd chaos spec is argv-knowable: caught before any re-exec
    with pytest.raises(SystemExit) as ei:
        preflight("--chaos", "frob@3")
    assert "frob" in str(ei.value)
    # checkpointing disabled: the doctor could never roll back to anything
    with pytest.raises(SystemExit) as ei:
        preflight("--on-diverge", "skip", "--save-freq", "0",
                  "--eval-freq", "0")
    assert "cadence" in str(ei.value)
    # --n-devices 0 (= all visible) is ambiguous from argv: preflight must
    # NOT claim multi-device features for it (a 1-device host accepts
    # these configs) — the in-run check rejects cheaply via rc=2 on a mesh
    preflight("--code", "qsgd", "--on-diverge", "skip", "--zero1",
              "--n-devices", "0")
    preflight("--code", "qsgd", "--on-diverge", "densify",
              "--num-aggregate", "2", "--n-devices", "0")
    # degenerate detector knobs are argv-knowable too: they must fail in
    # the supervisor parent, not as a ValueError in every jax-booted child
    with pytest.raises(SystemExit) as ei:
        preflight("--on-diverge", "skip", "--diverge-window", "1")
    assert "window" in str(ei.value)
    with pytest.raises(SystemExit) as ei:
        preflight("--on-diverge", "skip", "--diverge-patience", "0")
    assert "patience" in str(ei.value)


def test_preflight_validates_env_chaos_spec(monkeypatch):
    """Supervised children inherit ATOMO_CHAOS, so a typo'd env spec would
    burn the restart budget exactly like a typo'd --chaos flag; preflight
    must validate it when no flag overrides it."""
    from atomo_tpu.cli import _argv_preflight, build_parser

    parser = build_parser()
    sub = next(
        a for a in parser._actions if hasattr(a, "choices") and a.choices
    )
    train = sub.choices["train"]
    args = train.parse_args(["--synthetic", "--train-dir", "/tmp/unused"])

    monkeypatch.setenv("ATOMO_CHAOS", "frob@3")
    with pytest.raises(SystemExit) as ei:
        _argv_preflight(args)
    assert "frob" in str(ei.value)
    # a valid env spec passes, and an explicit --chaos flag wins (the env
    # is ignored in-run when the flag is set, so only the flag is checked)
    monkeypatch.setenv("ATOMO_CHAOS", "nan@2")
    _argv_preflight(args)
    monkeypatch.setenv("ATOMO_CHAOS", "frob@3")
    args2 = train.parse_args(
        ["--synthetic", "--train-dir", "/tmp/unused", "--chaos", "nan@2"]
    )
    _argv_preflight(args2)


def test_on_diverge_smoke_train(tmp_path):
    """A sane short run with the doctor armed: trains to completion with
    no rollback, writes healthy tags once the window clears."""
    rc = main([
        "train", "--synthetic", "--dataset", "MNIST", "--network", "LeNet",
        "--batch-size", "8", "--max-steps", "6", "--eval-freq", "0",
        "--save-freq", "2", "--log-interval", "0", "--n-devices", "1",
        "--train-dir", str(tmp_path), "--on-diverge", "skip",
        "--diverge-window", "2",
    ])
    assert rc == 0
    from atomo_tpu.training import latest_healthy_step

    # saves at 2/4/6; window 2 cleared past step 2 and 4 by step 6
    assert latest_healthy_step(str(tmp_path)) >= 2
