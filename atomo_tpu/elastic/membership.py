"""Membership epochs — layer 1 of the elastic-world subsystem.

A *membership epoch* is a span of steps trained by one fixed roster of
replicas. The whole elastic design rests on making epochs explicit and
durable: the determinism contract is stated PER EPOCH (bit-exact
trajectories within an epoch, a documented deterministic re-shard at every
transition), and the post-mortem question "which replicas contributed to
step S" must be answerable from disk — so every epoch is one record in
``train_dir/membership.json`` (written with the same tmp+rename atomicity
as every other evidence file) and one ``membership`` line in
``incidents.jsonl``.

Why the estimator math licenses this at all (PAPER.md): every codec is an
unbiased estimator of the mean gradient, and the mean over ANY subset of
replicas is still an unbiased estimate of the true gradient — just with
more variance. The guard's skip-and-rescale already exploits that for a
*transient* anomaly; a *persistently* absent replica is the same argument
applied for longer, which is why the run can keep training on N-1 at all
(Parallax, PAPERS.md 1808.02621, grounds rebalancing the data-parallel
work across the changed world).

The data-shard map is DERIVED, not stored: the batch stream is a pure
function of (data seed, batches consumed) — ``BatchIterator.forever(skip)``
replays it from any step — and the global batch splits contiguously over
the roster order, so an epoch record only needs ``(batch_size, skip,
rng_crc)`` to pin the exact per-replica sample assignment for every step
it covers. ``rng_crc`` (``BatchIterator.rng_signature``) fingerprints the
shuffle-RNG state the derivation starts from, so a post-mortem can verify
the claim instead of trusting it.

Epoch transitions happen only at checkpoint boundaries: the exiting run
appends the NEXT epoch's record, logs the ``membership`` incident, and
exits with :data:`~atomo_tpu.training.resilience.MEMBERSHIP_EXIT_CODE` so
the supervisor re-execs at the new world size (``apply_world_to_argv``)
WITHOUT charging the crash-restart budget — a planned reshape is not a
crash.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from atomo_tpu.utils.tracing import write_json_atomic

MEMBERSHIP_FILE_NAME = "membership.json"


def membership_path(train_dir: str) -> str:
    return os.path.join(train_dir, MEMBERSHIP_FILE_NAME)


@dataclasses.dataclass(frozen=True)
class MembershipEpoch:
    """One epoch of the membership history.

    epoch:      0-based transition counter (strictly increasing).
    world_size: replicas training during this epoch.
    roster:     the ORIGINAL member ids still present, in mesh order —
                mesh replica ``i`` of this epoch is member ``roster[i]``,
                so a shrunken world's replica numbering is always
                translatable back to the full roster.
    start_step: the checkpoint step the epoch begins at (0 = run start).
    reason:     init | shrink | grow | operator_resize.
    dead:       members that left at this transition (shrink only).
    shard_map:  the deterministic data-shard derivation — see module
                docstring; enough to reconstruct which samples replica i
                consumed at any step of the epoch.
    detail:     free-form context (device roster etc.), JSON-able.
    """

    epoch: int
    world_size: int
    roster: tuple[int, ...]
    start_step: int = 0
    reason: str = "init"
    dead: tuple[int, ...] = ()
    shard_map: dict = dataclasses.field(default_factory=dict)
    detail: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.world_size != len(self.roster):
            raise ValueError(
                f"membership epoch {self.epoch}: world_size "
                f"{self.world_size} != roster length {len(self.roster)}"
            )
        if self.world_size < 1:
            raise ValueError(
                f"membership epoch {self.epoch}: world_size must be >= 1"
            )

    def to_dict(self) -> dict:
        return {
            "epoch": int(self.epoch),
            "world_size": int(self.world_size),
            "roster": [int(m) for m in self.roster],
            "start_step": int(self.start_step),
            "reason": self.reason,
            "dead": [int(m) for m in self.dead],
            "shard_map": dict(self.shard_map),
            "detail": dict(self.detail),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "MembershipEpoch":
        return cls(
            epoch=int(d["epoch"]),
            world_size=int(d["world_size"]),
            roster=tuple(int(m) for m in d["roster"]),
            start_step=int(d.get("start_step", 0)),
            reason=str(d.get("reason", "init")),
            dead=tuple(int(m) for m in d.get("dead", ())),
            shard_map=dict(d.get("shard_map", {})),
            detail=dict(d.get("detail", {})),
        )


class MembershipLog:
    """The ``membership.json`` file: the full epoch history, appended
    atomically (tmp+rename — the write_json_atomic discipline every
    evidence artifact in this repo shares), loadable after exactly the
    failures the elastic subsystem drills."""

    def __init__(self, path: Optional[str], epochs=None):
        self.path = path
        self.epochs: list[MembershipEpoch] = list(epochs or [])

    @classmethod
    def load(cls, train_dir: Optional[str]) -> "MembershipLog":
        """Read train_dir/membership.json; missing/unreadable file = empty
        history (a torn file must not crash the run that documents it —
        the IncidentLog.append precedent)."""
        path = membership_path(train_dir) if train_dir else None
        epochs = []
        if path and os.path.exists(path):
            import json

            try:
                with open(path) as f:
                    doc = json.load(f)
                epochs = [
                    MembershipEpoch.from_dict(e)
                    for e in doc.get("epochs", [])
                ]
            except (OSError, ValueError, KeyError) as exc:
                import warnings

                warnings.warn(
                    f"membership log {path!r} unreadable ({exc}); "
                    "treating as empty history"
                )
                epochs = []
        return cls(path, epochs)

    @property
    def full_world(self) -> int:
        """The ORIGINAL world size — epoch 0's. Re-admission grows back
        toward this roster, never past it."""
        return self.epochs[0].world_size if self.epochs else 0

    def latest(self) -> Optional[MembershipEpoch]:
        return self.epochs[-1] if self.epochs else None

    def append(self, rec: MembershipEpoch) -> MembershipEpoch:
        last = self.latest()
        if last is not None and rec.epoch != last.epoch + 1:
            raise ValueError(
                f"membership epochs must be contiguous: appending epoch "
                f"{rec.epoch} after {last.epoch}"
            )
        if last is None and rec.epoch != 0:
            raise ValueError(
                f"the first membership epoch must be 0, got {rec.epoch}"
            )
        self.epochs.append(rec)
        self._write()
        return rec

    def _write(self) -> None:
        if not self.path:
            return
        try:
            write_json_atomic(
                self.path,
                {
                    "kind": "membership",
                    "full_world": self.full_world,
                    "epochs": [e.to_dict() for e in self.epochs],
                },
            )
        except OSError as exc:
            import warnings

            warnings.warn(f"membership log write failed: {exc}")


class MembershipChange(RuntimeError):
    """A membership epoch boundary was reached: the run must re-exec at a
    different world size. The CLI translates this into
    :data:`~atomo_tpu.training.resilience.MEMBERSHIP_EXIT_CODE` (the
    supervisor's planned-reshape triage — restarts on it do NOT burn the
    crash budget); the new epoch's record is already durable in
    membership.json when this is raised."""

    def __init__(self, kind: str, record: MembershipEpoch):
        self.kind = kind  # "shrink" | "grow"
        self.record = record
        self.epoch = record.epoch
        self.world_size = record.world_size
        super().__init__(
            f"{kind} to world size {record.world_size} at step "
            f"{record.start_step} (membership epoch {record.epoch})"
        )


def apply_world_to_argv(argv, world_size: int) -> list[str]:
    """Rewrite a train command's ``--n-devices`` to ``world_size`` (both
    the ``--n-devices N`` and ``--n-devices=N`` spellings; appended when
    absent — an ``--n-devices 0``/flagless command means "all visible",
    which an elastic reshape must pin down explicitly). The supervisor's
    half of a membership transition."""
    out = list(argv)
    handled = False
    i = 0
    while i < len(out):
        tok = out[i]
        if tok == "--n-devices" and i + 1 < len(out):
            out[i + 1] = str(world_size)
            handled = True
            i += 2
            continue
        if tok.startswith("--n-devices="):
            out[i] = f"--n-devices={world_size}"
            handled = True
        i += 1
    if not handled:
        out += ["--n-devices", str(world_size)]
    return out
