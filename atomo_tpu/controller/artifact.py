"""``controller_decision.json`` — the ONE decision artifact.

The controller supersedes the two resume sources of truth the repo grew
separately (``tune_decision.json`` for the autopilot's knob vector,
``budget_alloc.json`` for the per-leaf allocation) with a single
document: the full probe ladder (the ``tune_decision`` row shape,
``kind: "controller_decision"``), one winner knob vector spanning every
decider's axes, and — in ``meta`` so they land atomically with the
FIRST row, not in a post-finish rewrite a kill could lose —
``meta.controller`` (deciders searched, pack-kernel resolution),
``meta.allocation`` (the solved per-leaf knob epoch the ``+ab`` knob
resolves against on resume) and ``meta.hybrid`` (the per-leaf
assignment the ``+sp`` knob resolves against).

Resume discipline is the ``decision_reusable`` family, composed:
:func:`controller_reusable` refuses on everything the tune check
refuses on (no winner, world/mesh/quorum mismatch) PLUS a knob vector
whose ``budget_alloc``/``sparse_rows`` entries reference meta sections
the artifact does not carry. LEGACY FALLBACK (stated, never silent):
:func:`load_resume_decision` prefers ``controller_decision.json``; when
a train_dir predates the controller it falls back to reading
``tune_decision.json`` (+ ``budget_alloc.json`` for the allocation)
and says so — old runs keep resuming, new runs write one artifact.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from atomo_tpu.tuning.autopilot import (
    TUNE_DECISION_NAME,
    decision_reusable,
)

CONTROLLER_DECISION_NAME = "controller_decision.json"


def controller_path(train_dir: str) -> str:
    return os.path.join(train_dir, CONTROLLER_DECISION_NAME)


def read_controller(train_dir: Optional[str]) -> Optional[dict]:
    """Parse controller_decision.json; missing/unparseable -> None (the
    caller re-solves from scratch and says so)."""
    if not train_dir:
        return None
    try:
        with open(controller_path(train_dir)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def controller_reusable(
    doc,
    *,
    n_dev: int,
    mesh_axes: Optional[dict] = None,
    quorum: Optional[int] = None,
    staleness: Optional[int] = None,
    fleet_roster: Optional[str] = None,
) -> tuple:
    """Can a ``--resume`` reuse this recorded controller decision?

    Composes :func:`~atomo_tpu.tuning.autopilot.decision_reusable`
    (world size, mesh shape, quorum pinning — one validity law, not a
    fork of it) with the controller's own closure condition: a winner
    whose knob vector turns on ``budget_alloc`` or ``sparse_rows`` is
    only executable if the artifact carries the meta section that knob
    resolves against. Returns ``(reusable, reason)``; pure function of
    the document (tested), like its parents."""
    if doc and doc.get("kind") != "controller_decision":
        return False, (
            f"artifact kind is {doc.get('kind')!r}, not a controller "
            "decision — re-solving"
        )
    ok, reason = decision_reusable(
        doc, n_dev=n_dev, mesh_axes=mesh_axes,
        quorum=quorum, staleness=staleness,
        fleet_roster=fleet_roster,
    )
    if not ok:
        return ok, reason
    knobs = ((doc.get("winner") or {}).get("knobs")) or {}
    meta = doc.get("meta") or {}
    if knobs.get("budget_alloc") == "variance" and not (
        (meta.get("allocation") or {}).get("ks")
    ):
        return False, (
            "winner pins budget_alloc=variance but the artifact carries "
            "no meta.allocation.ks to rebuild the per-leaf codec from — "
            "re-solving"
        )
    if knobs.get("sparse_rows") == "on" and not (
        (meta.get("hybrid") or {}).get("assignments")
    ):
        return False, (
            "winner pins sparse_rows=on but the artifact carries no "
            "meta.hybrid assignment to rebuild the exchange plan from — "
            "re-solving"
        )
    return True, reason


def load_resume_decision(
    train_dir: Optional[str], log_fn=print
) -> tuple:
    """The resume read path with the STATED legacy fallback: returns
    ``(doc, source)`` where source is ``"controller"`` for
    controller_decision.json, ``"legacy"`` for a tune_decision.json
    (with any budget_alloc.json allocation grafted into
    ``meta.allocation`` so the one resume code path downstream reads
    one shape), or ``(None, "none")``. The fallback is logged — a run
    resuming from pre-controller artifacts should say so, not pass as a
    controller run."""
    doc = read_controller(train_dir)
    if doc is not None:
        return doc, "controller"
    if not train_dir:
        return None, "none"
    try:
        with open(os.path.join(train_dir, TUNE_DECISION_NAME)) as f:
            legacy = json.load(f)
    except (OSError, ValueError):
        return None, "none"
    log_fn(
        "Controller: no controller_decision.json in this train_dir; "
        "falling back to the legacy tune_decision.json"
        " (pre-controller run — its knob vector is honored as-is)"
    )
    from atomo_tpu.budget.artifact import latest_epoch, read_alloc

    ep = latest_epoch(read_alloc(train_dir))
    if ep and ep.get("ks"):
        meta = legacy.setdefault("meta", {})
        meta.setdefault(
            "allocation",
            {"epoch": ep.get("epoch"), "ks": ep.get("ks"),
             "source": "budget_alloc.json (legacy fallback)"},
        )
        log_fn(
            "Controller: grafted the legacy budget_alloc.json epoch "
            f"{ep.get('epoch')} into the decision's allocation view"
        )
    return legacy, "legacy"
