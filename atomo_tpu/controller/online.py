"""One re-solve loop — the controller's online half.

The repo had grown three independent online reactors: the drift
retuner (``tuning.autopilot.OnlineRetuner`` — step-time drift →
gather/ring re-probe), the budget retuner (``budget.retune`` —
q_err2 drift → re-allocation) and the hybrid re-plan (deferred to
restart by design: the assignment changes payload shapes AND the
trajectory class). Each logged its own incident family and re-decided
on its own trigger; nobody owned the joint knob vector.

:class:`ControllerRetuner` subsumes them by COMPOSITION, not
replacement: the inner reactors keep their signals, their hysteresis
gates and their incident records (``perf_drift``, ``budget_realloc`` —
the report's existing checks stay meaningful), and the controller
wraps each APPLIED change in one ``controller_redecide`` incident
quoting the old/new knob vector and the step-time/variance evidence
both ways — the single audit stream the ISSUE-17 artifact story needs.
Flight-recorder feeding is unchanged: the loop's existing retune hooks
(``tuner.observe`` / ``tuner.maybe_retune`` /
``budget_tuner.maybe_realloc``) all land on this one object, which
satisfies BOTH protocols, so the loop wiring (replicated.py) did not
fork.

Re-decisions stay checkpoint-boundary-gated and hysteresis-gated
because the inner reactors already are (drift patience, budget
``min_gain``); the controller adds no second trigger — one change, one
boundary, one incident. A hybrid re-plan remains restart-territory and
the redecide record for any other change says so (``hybrid_note``)
instead of pretending the axis is online-movable.
"""

from __future__ import annotations

from typing import Optional

HYBRID_NOTE = (
    "hybrid assignment is not online-movable (payload shapes and "
    "trajectory class change); re-plan happens at restart from the "
    "controller artifact"
)


class ControllerRetuner:
    """Compose the drift retuner + budget retuner behind one object
    satisfying both loop protocols (module docstring). Either inner
    reactor may be None — the corresponding axis is then simply not
    re-decided online, exactly as before the controller existed."""

    def __init__(
        self,
        *,
        tuner=None,
        budget_tuner=None,
        knobs: Optional[dict] = None,
        incidents=None,
        log_fn=print,
    ):
        self.tuner = tuner
        self.budget_tuner = budget_tuner
        # the decision's winner knob vector, kept current as re-decisions
        # apply — the redecide incidents quote it whole, old and new
        self.knobs = dict(knobs or {})
        self.incidents = incidents
        self.log_fn = log_fn
        self.redecisions = 0
        self._last_probe_ms: dict = {}
        if tuner is not None and tuner.probe_fn is not None:
            orig = tuner.probe_fn

            def _recording_probe(mode):
                v = float(orig(mode))
                self._last_probe_ms[mode] = round(v, 4)
                return v

            tuner.probe_fn = _recording_probe

    # -- shared protocol plumbing -------------------------------------
    def bind(self, incidents=None, recorder=None, log_fn=None):
        """Late-bind loop-owned sinks; forwards to both inner reactors
        (the loop calls this once as ``tuner`` and once as
        ``budget_tuner`` — idempotent by construction)."""
        if incidents is not None:
            self.incidents = incidents
        if log_fn is not None:
            self.log_fn = log_fn
        if self.tuner is not None:
            self.tuner.bind(incidents=incidents, log_fn=log_fn)
        if self.budget_tuner is not None:
            self.budget_tuner.bind(
                incidents=incidents, recorder=recorder, log_fn=log_fn
            )
        return self

    def _redecide(self, step, axis, old_knobs, new_knobs, evidence):
        self.redecisions += 1
        if self.incidents is not None:
            self.incidents.append(
                "controller_redecide",
                step=int(step),
                axis=axis,
                knobs_old=old_knobs,
                knobs_new=new_knobs,
                evidence=evidence,
                hybrid_note=HYBRID_NOTE,
            )
        self.log_fn(
            f"Controller: re-decision at step {step} on the {axis} axis: "
            f"{old_knobs} -> {new_knobs}"
        )

    # -- OnlineRetuner protocol (the loop's ``tuner=``) ---------------
    @property
    def pending(self):
        return self.tuner.pending if self.tuner is not None else None

    @property
    def state(self):
        return self.tuner.state if self.tuner is not None else None

    def observe(self, dts):
        if self.tuner is None:
            return None
        return self.tuner.observe(dts)

    def maybe_retune(self, step: int, current_mode: str):
        if self.tuner is None:
            return None
        self._last_probe_ms = {}
        new_mode = self.tuner.maybe_retune(step, current_mode)
        if new_mode is not None:
            old = dict(self.knobs)
            self.knobs = {**self.knobs, "aggregate": new_mode}
            self._redecide(
                step, "aggregate", old, dict(self.knobs),
                evidence={
                    "probed_ms_per_step": dict(self._last_probe_ms),
                    "old_mode_ms": self._last_probe_ms.get(current_mode),
                    "new_mode_ms": self._last_probe_ms.get(new_mode),
                },
            )
        return new_mode

    # -- BudgetRetuner protocol (the loop's ``budget_tuner=``) --------
    def maybe_realloc(self, step: int):
        if self.budget_tuner is None:
            return None
        old_ks = list(self.budget_tuner.alloc.ks)
        old_var = float(self.budget_tuner.alloc.predicted_variance)
        new_codec = self.budget_tuner.maybe_realloc(step)
        if new_codec is not None:
            new = self.budget_tuner.alloc
            old = dict(self.knobs)
            self.knobs = {
                **self.knobs,
                "budget_alloc": "variance",
                "budget_epoch": int(new.epoch),
            }
            self._redecide(
                step, "allocation", old, dict(self.knobs),
                evidence={
                    "ks_old": [int(k) for k in old_ks],
                    "ks_new": [int(k) for k in new.ks],
                    "predicted_variance_old": round(old_var, 8),
                    "predicted_variance_new": round(
                        float(new.predicted_variance), 8
                    ),
                    "basis": (
                        "each variance under its own solve's spectra; "
                        "the paired budget_realloc incident quotes the "
                        "apples-to-apples pair under fresh spectra"
                    ),
                },
            )
        return new_codec
