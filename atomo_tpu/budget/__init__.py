"""Adaptive variance-budget codecs — ATOMO's allocation, finally closed.

The source paper's core contribution (Wang et al., 1806.04090) is
variance-minimizing atom allocation under a communication budget — yet
until this package the repo spent a FIXED per-layer budget: one global
``--svd-rank`` knob, every layer padded to the same atom count. This
package closes the loop:

  * :mod:`~atomo_tpu.budget.allocator` — per-layer gradient spectra
    (measured from a probe gradient, or folded online from the
    ``--obs-quality`` q_err2 series) and the water-filling solver that
    distributes a GLOBAL wire-byte budget across layers to minimize
    total estimator variance. The existing fixed budget is the
    degenerate "uniform" point of the dial; ``--on-diverge densify``'s
    spend-everything remedy is its other end (an unbounded budget drives
    every layer into the codec's exact dense fallback).
  * :mod:`~atomo_tpu.budget.codec` — :class:`PerLeafCodec`, the wrapper
    that threads the allocation's per-layer ranks through
    ``encode_tree``/``encode_leaf_subset``/``decode_tree`` as STATIC
    per-leaf values (trace-time constant shapes under jit/scan/
    stream-encode; the codecs.base group keys carry the resolved
    per-leaf codec so vmap buckets stay shape-sound).
  * :mod:`~atomo_tpu.budget.artifact` — ``budget_alloc.json``: the
    allocation as a first-class run artifact (written atomically,
    reused on ``--resume`` like ``tune_decision.json``) so
    kill->restart->resume replays bit-exact from the recorded epochs.
  * :mod:`~atomo_tpu.budget.retune` — the checkpoint-boundary
    re-allocator: folds the recorded per-layer q_err2 series into fresh
    spectra estimates and re-solves; a changed allocation lands as a
    ``budget_realloc`` incident quoting old/new per-layer splits and
    predicted variance both ways.
  * :mod:`~atomo_tpu.budget.feedback` — error-feedback residual
    accumulation (``--error-feedback``) documentation lives with the
    carry implementation in ``parallel.replicated`` (EfState); this
    package states the bias contract the tests assert.

Grounding: SparCML (1802.08021) treats representation choice as a
per-layer priced decision rather than a global constant; the q_err2
probe (PR 11) makes the per-layer variance signal observable in-graph;
streamed encode (PR 10) and ``--svd-mode randomized`` make repeated
per-layer small SVDs affordable.
"""

from atomo_tpu.budget.allocator import (  # noqa: F401
    Allocation,
    LayerSpectrum,
    allocation_leaf_budgets,
    measure_spectra,
    predicted_variance,
    solve_allocation,
    spectra_from_qerr2,
    uniform_ks,
)
from atomo_tpu.budget.artifact import (  # noqa: F401
    BUDGET_ALLOC_NAME,
    alloc_path,
    alloc_reusable,
    allocation_meta,
    append_epoch,
    latest_epoch,
    new_alloc_doc,
    read_alloc,
    write_alloc,
)
from atomo_tpu.budget.codec import PerLeafCodec, budgeted_codec  # noqa: F401
from atomo_tpu.budget.retune import BudgetRetuner  # noqa: F401
