"""Sparse gradient exchange: lossless row codec + per-layer hybrid plans.

Three layers (the PR-12 subsystem): the embedding-tower WORKLOAD lives in
``models/embedding.py`` + ``data/zipf.py``; the CODEC here
(:mod:`~atomo_tpu.sparse.rowcodec`) moves (row-index, row-value) pairs
with a static worst-case budget, losslessly; the HYBRID PLAN
(:mod:`~atomo_tpu.sparse.hybrid`) assigns each leaf sparse-row vs the
existing dense/compressed exchange from measured density and comm-model
pricing, and ``make_distributed_train_step(hybrid=...)`` executes it.
"""

from atomo_tpu.sparse.hybrid import (  # noqa: F401
    HybridPlan,
    LeafAssignment,
    infer_row_bounds,
    measured_densities,
    plan_for_model,
    plan_hybrid,
    probe_gradient,
)
from atomo_tpu.sparse.rowcodec import (  # noqa: F401
    RowCodec,
    RowPayload,
    row_payload_bytes,
)
