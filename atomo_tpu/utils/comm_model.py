"""Analytic comm-cost model: when does gradient compression win wall-clock?

ATOMO's raison d'être is "fewer bytes -> faster synchronous steps"
(reference README.md:5-7; the paper's speedup claims are all measured on
10 Gbps-class EC2 fabrics). On a single chip there is no inter-chip link to
save, so compression only ever ADDS its encode/decode tax — every honest
single-chip measurement has svd slower than dense (BENCH_ONCHIP_r3.md).
This module turns the measured byte win + measured codec tax into the
quantity that actually decides deployment: the implied synchronous-step
time at N ways over a fabric of bandwidth B, and the crossover bandwidth
below which compression wins.

Model (stated assumptions — VERDICT r3 next-round #1a):
  * Synchronous data parallelism, ring collectives, no compute/comm
    overlap — the reference's own execution model (the PS blocks on all
    workers: src/sync_replicas_master_nn.py:113-124).
  * Dense baseline exchanges the full gradient with a ring all-reduce:
    per-chip wire traffic 2*D*(N-1)/N bytes through one link direction.
  * Compressed exchange all_gathers the fixed-size payload P (factors,
    not dense gradients, move — atomo_tpu.parallel.replicated): per-chip
    traffic P*(N-1) bytes. Payloads are decoded redundantly on every chip
    (replicated-PS equivalence), costing zero extra comm.
  * The codec tax (encode + fused decode-mean at the measured mesh width)
    rides the measured single-chip step times: tax = t_svd_1chip -
    t_dense_1chip. Decode-mean cost grows mildly with N (the fused matmul
    is (m, N*k)@(N*k, n)); the model charges the measured-at-N value to
    every N — stated, not hidden.
  * Bandwidth B is per-chip effective ring bandwidth of the slowest fabric
    link on the gradient path. Reference points: TPU v5e ICI ~45 GB/s per
    link direction (2-D torus); 400 Gbps pod DCN NIC shared by 8 chips
    ~6.25 GB/s/chip; the reference's EC2 regime 10 GbE ~1.25 GB/s.

Two structural facts the tables below make visible:
  * Compression stops paying at very large N regardless of bandwidth:
    all_gather traffic P*(N-1) crosses all-reduce traffic 2*D*(N-1)/N at
    N = 2*D/P = 2x the byte reduction (144 ways at config 2's 72x).
  * On fast ICI the tax dominates: at 45 GB/s the dense ResNet-18
    exchange costs ~1.7 ms while the codec tax is ~2.4 ms — compression
    is a DCN/Ethernet-regime tool (exactly the regime the reference paper
    targets), not an intra-pod one at these model sizes.
"""

from __future__ import annotations

import math

DEFAULT_WAYS = (8, 16, 32, 64)
# (label, bytes/s): per-chip effective ring bandwidths to tabulate
DEFAULT_BANDWIDTHS = (
    ("ici_45GBps", 45e9),
    ("dcn_6.25GBps", 6.25e9),
    ("eth10G_1.25GBps", 1.25e9),
)

# named fabric presets for --fabric (per-chip effective ring bandwidth of
# the slowest link on the gradient path; see module docstring sources)
FABRICS = {"ici": 45e9, "dcn": 6.25e9, "eth10g": 1.25e9}

# Measured single-chip codec tax anchor: ResNet-18/CIFAR-10 on TPU v5e,
# artifacts/BENCH_ONCHIP_r3.md — svd3 9.01 ms vs dense 6.50 ms (tax 2.5 ms
# on a 44.7 MB dense gradient); the qsgd encode measured ~2.5 ms on the
# same tree. `estimate_codec_tax_s` scales that anchor linearly with the
# dense gradient size: the encode work (matmuls/eighs per layer for svd,
# elementwise quantize for qsgd) is ~linear in elements at fixed shapes.
# An estimate, not a measurement — overridable via --codec-tax-ms.
_TAX_ANCHOR_S = 2.5e-3
_TAX_ANCHOR_BYTES = 44.7e6


def estimate_codec_tax_s(dense_bytes: float) -> float:
    return _TAX_ANCHOR_S * float(dense_bytes) / _TAX_ANCHOR_BYTES


def choose_aggregate(
    *,
    has_codec: bool,
    dense_bytes: float,
    payload_bytes: float,
    ways: int,
    fabric_bw: float,
    tax_s: float | None = None,
    cross_host: bool = False,
    allow_ring: bool = True,
) -> tuple[str, str]:
    """``--aggregate auto``: pick gather / psum / hierarchical / ring + why.

    The reference never had this choice — its one PS pushed every message
    over one 10 GbE fabric (src/distributed_worker.py:330-335). Here the
    framework has three exchange modes and a measured cost model
    (artifacts/COMM_CROSSOVER.md), so the default can pick per deployment:

      * no compressing codec         -> psum (dense all-reduce; nothing else
                                       makes sense)
      * mesh crosses hosts (DCN/
        Ethernet on the outer axis)  -> hierarchical (dense psum rides ICI,
                                       factors cross the slow fabric)
      * single fabric: with a codec BOTH modes pay the encode->decode
        round trip (psum with a codec is the same estimator over a dense
        wire — the quantization noise is the user's algorithm choice, not
        ours to silently drop), so the tax cancels and the choice reduces
        to wire bytes: gather iff P*(N-1) < 2*D*(N-1)/N, i.e.
        N < 2*(byte reduction). Within the gather-wins region, the
        gathered buffer N*P is checked against the dense gradient D:
        once it would be the larger transient (N >= byte reduction) the
        pick upgrades to ``ring`` — the streamed schedule that rotates
        the same payloads with ppermute, overlaps decode with transfer,
        and never materializes the buffer (``allow_ring=False`` for
        callers without the ring step, e.g. the lm layouts). The fabric
        and tax still decide the
        ADVISORY: when the wire saving at this fabric is smaller than the
        tax, compression itself is costing wall-clock vs dense training
        (--code sgd) and the printed line says so with numbers — the
        measured single-chip truth (artifacts/BENCH_ONCHIP_r3.md: svd3
        9.01 ms vs dense 6.50 ms with no wire to save).

    Returns (mode, one-line justification) — the caller prints the line so
    the selection is never silent.
    """
    if not has_codec:
        return "psum", "no compressing codec: dense all-reduce (psum)"
    if ways <= 1:
        return (
            "psum",
            "single device: no exchange; psum keeps codec semantics "
            "without a gather",
        )
    if cross_host:
        return (
            "hierarchical",
            "mesh crosses hosts: dense psum over ICI, factors over the "
            "slow inter-host fabric (artifacts/COMM_CROSSOVER.md concl. 2)",
        )
    ar = ring_allreduce_wire_bytes(dense_bytes, ways)
    ag = ring_allgather_wire_bytes(payload_bytes, ways)
    n_star = max_beneficial_ways(dense_bytes, payload_bytes)
    if ag >= ar:
        return (
            "psum",
            f"dense all-reduce wins at {ways} ways: the factor all_gather "
            f"would move {ag / 1e6:.2f} MB/chip >= {ar / 1e6:.2f} MB/chip "
            f"dense (compression stops paying past N = 2x reduction = "
            f"{n_star:.0f}); the codec round trip runs either way",
        )
    if tax_s is None:
        tax_s = estimate_codec_tax_s(dense_bytes)

    def tax_advisory(saved_s: float) -> str:
        """The gather pick's honesty NOTE when the wire saving at this
        fabric is smaller than the codec tax. The ring pick carries a
        strictly STRONGER always-on note instead (its total wire is >=
        the dense all-reduce in the whole regime auto selects it, so
        "saving vs tax" arithmetic is moot there — wire alone already
        costs more than dense)."""
        if saved_s >= tax_s:
            return ""
        return (
            f"; NOTE on {fabric_bw / 1e9:.2f} GB/s/chip the wire saving "
            f"{saved_s * 1e3:.2f} ms < codec tax ~{tax_s * 1e3:.2f} ms — "
            "compression is costing wall-clock here; dense training "
            "(--code sgd) would be faster end-to-end"
        )

    buf = gather_buffer_bytes(payload_bytes, ways)
    if allow_ring and buf >= dense_bytes:
        # the gathered buffer has outgrown a dense gradient (N >= byte
        # reduction): stream it instead — same payloads, ppermute
        # rotation with decode overlapped, O(1) live payload memory. The
        # wire pays the dense/N-sized segment all_gather on top of the
        # N-1 payload hops (ring_stream_wire_bytes) — cheap next to the
        # buffer it deletes in exactly this regime.
        rs = ring_stream_wire_bytes(payload_bytes, dense_bytes, ways)
        # honesty note, ALWAYS true in this regime: N >= byte reduction
        # implies P >= D/N, so ring's rotation + segment all_gather moves
        # at least the dense all-reduce's bytes (rs - ar = (N-1)(P - D/N)
        # >= 0). The pick trades wire for memory/overlap and the line
        # says so outright — stronger than the gather path's conditional
        # saving-vs-tax advisory, which compares a different pair (gather
        # wire vs dense) and would understate ring's wire cost
        return (
            "ring",
            f"ring-streamed gather at {ways} ways: the gathered buffer "
            f"would hold {buf / 1e6:.2f} MB/chip >= the {dense_bytes / 1e6:.2f} "
            f"MB dense gradient; streaming rotates payloads over {ways - 1} "
            f"ppermute hops with decode overlapped ({rs / 1e6:.2f} MB/chip "
            f"on the wire incl. the segment all_gather) and never "
            "materializes the buffer; NOTE total wire >= the "
            f"{ar / 1e6:.2f} MB/chip dense all-reduce at this N — the pick "
            "buys O(1) payload memory and decode/transfer overlap, not "
            "bytes (use --aggregate gather to minimize wire)",
        )
    saved_s = (ar - ag) / fabric_bw
    reason = (
        f"factor all_gather wins at {ways} ways: {ag / 1e6:.2f} MB/chip "
        f"vs {ar / 1e6:.2f} MB/chip dense (both modes pay the codec "
        "round trip, so wire bytes decide)"
    ) + tax_advisory(saved_s)
    return "gather", reason


def quorum_exposed_wait_s(delays, quorum: int) -> float:
    """The quorum step's exposed straggler wait: the Q-th order statistic
    of the per-replica delay vector (seconds). A blocking step pays
    ``max(delays)`` — the slowest replica gates every step; a quorum-Q
    step only waits until Q payloads are present, so its exposure is the
    Q-th smallest delay (quorum.schedule's quorum floor promotes the
    nearest stragglers first, making this exact, not a bound). This is
    the quantity the autopilot's ``+qK`` candidates are priced by and
    bench config 17 measures."""
    d = sorted(float(x) for x in delays)
    if not d:
        return 0.0
    q = min(max(int(quorum), 1), len(d))
    return d[q - 1]


def leaf_budget_totals(leaf_budgets) -> tuple[float, float]:
    """Sum per-leaf ``(dense_bytes, payload_bytes)`` pairs into the
    ``(dense, payload)`` totals every wire formula consumes — THE one
    honest accounting function (PR-12 refactor): the single-codec paths
    route their whole-tree scalars through it as a one-leaf list, and
    the hybrid candidates sum the same per-leaf pairs the executed
    program reports (``sparse.hybrid.HybridPlan.leaf_budgets``), so
    prediction and execution can never disagree about what a byte is."""
    d = 0.0
    p = 0.0
    for pair in leaf_budgets:
        d += float(pair[0])
        p += float(pair[1])
    return d, p


def codec_leaf_payload_bytes(codec, shape, dtype="float32") -> int:
    """One leaf's wire bytes under ``codec`` — the CLAMPED actual.

    The fixed-budget honesty rule: a layer whose full rank is below the
    configured atom budget (``rank``, or ``rank + budget_slack`` for the
    Bernoulli-budget sampler) pays only its clamped slot count, and a
    layer the codec ships dense pays exactly its DensePayload — never
    the nominal ``rank + slack`` slots. Codecs that publish their static
    accounting (``SvdCodec.leaf_payload_bytes``) are priced analytically;
    anything else falls back to ``jax.eval_shape`` over the real encode
    (zero cost, nothing materializes). The two paths are pinned equal in
    tests/test_comm_model.py, so every comm-model consumer — the byte
    budgets, ``predict_step_s``, the adaptive budget allocator's
    candidate pricing — and the executed program agree to the byte."""
    fn = getattr(codec, "leaf_payload_bytes", None)
    if fn is not None:
        return int(fn(tuple(int(d) for d in shape)))
    import jax
    import jax.numpy as jnp

    from atomo_tpu.codecs.base import payload_nbytes

    shapes = jax.eval_shape(
        lambda: codec.encode(
            jax.random.PRNGKey(0),
            jnp.zeros(tuple(int(d) for d in shape), dtype),
        )
    )
    return int(payload_nbytes(shapes))


def ring_allreduce_wire_bytes(dense_bytes: float, ways: int) -> float:
    """Per-chip one-direction wire traffic of a ring all-reduce."""
    return 2.0 * dense_bytes * (ways - 1) / ways


def ring_allgather_wire_bytes(payload_bytes: float, ways: int) -> float:
    """Per-chip wire traffic of a ring all-gather of per-chip payloads."""
    return float(payload_bytes) * (ways - 1)


def ring_stream_wire_bytes(
    payload_bytes: float, dense_bytes: float, ways: int
) -> float:
    """Per-chip wire traffic of ``aggregate='ring'`` — honest accounting.

    Two components, both counted (the Msg(MB) honesty rule): the ppermute
    rotation sends each chip's payload N-1 times (identical to the ring
    all_gather's hop count, but the O(N·payload) destination buffer never
    materializes), PLUS the tiled all_gather of the decoded mean's
    per-chip segments — dense/N bytes received from each of the other N-1
    chips. The segment exchange is the price of exact cross-chip
    determinism (each flat-gradient element is summed by exactly one
    owner chip and republished); it is what makes ring's replicas
    bit-identical by construction. Consequence: ring always moves MORE
    wire bytes than gather (by ~dense_bytes at large N) — its wins are
    the O(1) live payload memory and the decode/transfer overlap, which
    is why ``choose_aggregate`` only picks it when the gathered buffer
    would outgrow a dense gradient (ways >= byte reduction)."""
    return float(payload_bytes) * (ways - 1) + float(dense_bytes) * (
        ways - 1
    ) / ways


def gather_buffer_bytes(payload_bytes: float, ways: int) -> float:
    """Live memory of gather mode's replicated all_gather destination —
    the O(N·payload) transient ``aggregate='ring'`` eliminates (ring's
    live payload memory is one rotating payload; its staging transient is
    one dense-gradient-sized buffer, independent of N)."""
    return float(payload_bytes) * ways


def stream_bucket_count(dense_bytes: float, bucket_bytes: float) -> int:
    """Layer-bucket count of a ``--stream-encode`` plan, ESTIMATED from
    byte totals under uniform packing. An estimate, not the real plan:
    the planner never splits a leaf, so a single leaf above the bound
    (an LM embedding) makes the real count — and the real exposed tail —
    much smaller than this ratio suggests. Callers that can see the
    gradient tree should pass the REAL ``plan_layer_buckets(...).n_buckets``
    through the candidate's ``stream_buckets`` knob instead (the CLI
    autopilot does); this fallback only orders probe ladders, and the
    calibration warning catches it when it misleads.
    ``bucket_bytes <= 0`` is the single-bucket plan."""
    if bucket_bytes <= 0:
        return 1
    return max(1, int(math.ceil(float(dense_bytes) / float(bucket_bytes))))


def stream_exposed_encode_s(encode_s: float, n_buckets: int) -> float:
    """Encode seconds still ON the critical path under ``--stream-encode``:
    the pipeline TAIL. With the gradient tree in n reverse-topological
    buckets, bucket b's encode runs under backprop of the layers feeding
    bucket b+1 — only the LAST bucket's encode (~1/n of the total,
    uniform-bucket model) has no backprop left to hide under. n = 1 (or
    stream off) keeps the whole encode exposed — exactly the pre-stream
    accounting ``overlap_report`` used to state."""
    return max(float(encode_s), 0.0) / max(int(n_buckets), 1)


def pipeline_bubble_fraction(n_stages: int, microbatches: int) -> float:
    """Idle fraction of the GPipe schedule: ``(n-1) / (m + n-1)``.

    The pipeline runs ``m + n-1`` ticks to push ``m`` microbatches through
    ``n`` stages (parallel.pp's ``lax.scan`` length, exactly); each stage
    computes on ``m`` of them and idles (or computes pipeline garbage —
    same wall-clock) on the other ``n-1``. The classic GPipe bubble;
    driving it down is why ``--microbatches`` exists."""
    n = max(int(n_stages), 1)
    m = max(int(microbatches), 1)
    return (n - 1) / (m + n - 1)


def pipeline_bubble_s(compute_s: float, n_stages: int, microbatches: int) -> float:
    """Wall-clock the bubble ADDS to a replica step: ``compute * (n-1)/m``.

    With bubble-free replica compute ``compute_s`` split over ``m``
    microbatch ticks, the schedule's ``m + n-1`` ticks cost
    ``compute_s * (m + n-1)/m`` — i.e. the bubble's surcharge is
    ``compute_s * (n-1)/m``. This is the number ``overlap_report`` prices
    NEXT TO encode exposure: both are critical-path time no dp-wire
    compression can touch."""
    n = max(int(n_stages), 1)
    m = max(int(microbatches), 1)
    return max(float(compute_s), 0.0) * (n - 1) / m


def tp_psum_wire_bytes(
    activation_bytes: float, ways: int, n_blocks: int
) -> float:
    """Per-chip wire bytes of the Megatron tp collectives for ONE step:
    every block exits its two parallel regions with a psum of the
    (B_local, S, W) residual activation — 2 per block forward, and the
    shard_map transpose runs the SAME 2 again in backward (the transpose
    of psum is psum) — each a ring all-reduce of ``activation_bytes``
    over the ``ways`` tp peers:
    ``4 * n_blocks * ring_allreduce_wire_bytes(act, ways)``. Priced from
    the measured fabric like every other wire term (ISSUE: the comm
    model must price the model-axis collectives, not just the dp wire)."""
    return (
        4.0
        * max(int(n_blocks), 0)
        * ring_allreduce_wire_bytes(float(activation_bytes), ways)
    )


def moe_all_to_all_wire_bytes(
    dispatch_bytes: float, ways: int, n_layers: int
) -> float:
    """Per-chip wire bytes of the MoE expert shuffle for ONE step: each
    layer runs two tiled ``all_to_all`` collectives (dispatch + return)
    over the (E, C, W) slot buffer of ``dispatch_bytes``, and AD's
    transpose runs both again in backward. A tiled all_to_all keeps 1/n
    of the buffer local and wires the other ``(n-1)/n``:
    ``4 * n_layers * dispatch_bytes * (ways-1)/ways``."""
    w = max(int(ways), 1)
    return (
        4.0
        * max(int(n_layers), 0)
        * max(float(dispatch_bytes), 0.0)
        * (w - 1)
        / w
    )


def overlap_hidden_comm_s(comm_s: float, compute_s: float) -> float:
    """Seconds of the exchange+decode chain that ``--overlap delayed``
    hides underneath fwd/bwd+update: overlap hides min(comm, compute) —
    the chain runs concurrently with compute and only its excess over the
    compute it hides under remains exposed."""
    return min(max(float(comm_s), 0.0), max(float(compute_s), 0.0))


def overlap_exposed_comm_s(comm_s: float, compute_s: float) -> float:
    """Seconds of the exchange+decode chain still ON the critical path
    under ``--overlap delayed``: max(0, comm - compute). Zero whenever the
    comm chain fits under the compute it overlaps — the regime where the
    delayed step time equals the compute-only step time for any N."""
    return max(0.0, float(comm_s) - float(compute_s))


def overlap_report(
    *,
    dense_bytes: float,
    payload_bytes: float,
    ways: int,
    fabric_bw: float,
    compute_s: float,
    decode_s: float = 0.0,
    aggregate: str = "gather",
    encode_s: float = 0.0,
    stream_encode: bool = False,
    stream_buckets: int = 1,
    pipeline_stages: int = 1,
    pipeline_microbatches: int = 1,
) -> dict:
    """Model what ``--overlap delayed`` buys at N ``ways`` over a fabric.

    The comm chain the mode takes off the critical path is the payload
    exchange (gather's all_gather wire, or ring's rotation + segment
    all_gather) plus the decode-mean (``decode_s``, a measured per-step
    number — pass 0 to model wire only). Blocking step = compute + chain;
    delayed step = compute + exposed(chain), where overlap hides
    min(chain, compute) — BOTH numbers are reported, per the honesty rule
    that a hidden cost is still a cost (it returns the moment compute
    shrinks below it).

    Encode (``encode_s``, measured — pass 0 to omit it as before) is NOT
    in the delayed chain: it consumes THIS step's gradient. Without
    ``--stream-encode`` it is therefore fully exposed in either mode.
    With ``stream_encode`` the layer-bucket pipeline hides all but the
    TAIL under backprop — exposed encode becomes
    :func:`stream_exposed_encode_s` (``encode_s / stream_buckets``) and
    the report states the pipeline accounting explicitly: the hidden
    share is a cost backprop absorbs, not a cost that vanished.

    ``pipeline_stages > 1`` adds the GPipe bubble
    (:func:`pipeline_bubble_s` on ``compute_s``) to BOTH step numbers —
    like exposed encode it is critical-path time the dp-wire saving
    cannot touch, so the ``dp x pp`` layouts report it side by side with
    encode exposure instead of hiding it inside "compute". Under delayed
    the bubble is ALSO overlap headroom: the consume chain reads only
    step-start values, so the scheduler runs it underneath the drain
    ticks as well as the compute — whatever part of the chain spills
    past the compute can still hide under the bubble
    (``bubble_hidden_ms``), and only the remainder stays exposed in
    ``delayed_step_ms``.
    """
    if aggregate == "ring":
        wire = ring_stream_wire_bytes(payload_bytes, dense_bytes, ways)
    else:
        wire = ring_allgather_wire_bytes(payload_bytes, ways)
    comm_s = wire / float(fabric_bw) + max(float(decode_s), 0.0)
    hidden = overlap_hidden_comm_s(comm_s, compute_s)
    exposed = overlap_exposed_comm_s(comm_s, compute_s)
    enc = max(float(encode_s), 0.0)
    enc_exposed = (
        stream_exposed_encode_s(enc, stream_buckets) if stream_encode
        else enc
    )
    bubble = pipeline_bubble_s(
        compute_s, pipeline_stages, pipeline_microbatches
    )
    # bubble credit: the chain hides under compute first (hidden), then
    # whatever spills past compute hides under the drain-tick bubble —
    # exposed-under-delayed is only the excess over BOTH
    bubble_hidden = min(exposed, bubble)
    delayed_exposed = max(0.0, comm_s - float(compute_s) - bubble)
    return {
        "aggregate": aggregate,
        "ways": ways,
        "wire_mb_per_chip": round(wire / 1e6, 3),
        "comm_chain_ms": round(comm_s * 1e3, 3),
        "compute_ms": round(float(compute_s) * 1e3, 3),
        "hidden_ms": round(hidden * 1e3, 3),
        "exposed_ms": round(exposed * 1e3, 3),
        "encode_ms": round(enc * 1e3, 3),
        "encode_exposed_ms": round(enc_exposed * 1e3, 3),
        "encode_hidden_ms": round((enc - enc_exposed) * 1e3, 3),
        "stream_encode": bool(stream_encode),
        "stream_buckets": int(stream_buckets) if stream_encode else 1,
        "pipeline_bubble_ms": round(bubble * 1e3, 3),
        "pipeline_bubble_fraction": round(
            pipeline_bubble_fraction(pipeline_stages, pipeline_microbatches),
            4,
        ),
        "bubble_hidden_ms": round(bubble_hidden * 1e3, 3),
        "blocking_step_ms": round(
            (compute_s + comm_s + enc_exposed + bubble) * 1e3, 3
        ),
        "delayed_step_ms": round(
            (compute_s + delayed_exposed + enc_exposed + bubble) * 1e3, 3
        ),
        "assumptions": (
            "delayed overlaps exchange+decode with fwd/bwd+update; hides "
            "min(comm, compute), exposes the excess; encode consumes this "
            "step's gradient — fully exposed without --stream-encode, and "
            "with it the layer-bucket pipeline hides all but the tail "
            "(exposed encode = max(0, encode_tail) = encode/n_buckets, "
            "uniform-bucket model); pipeline_stages>1 adds the GPipe "
            "bubble compute*(n_stages-1)/microbatches to both step "
            "numbers, and under delayed the bubble is ALSO hiding budget "
            "(bubble_hidden_ms): exposed = max(0, comm - compute - "
            "bubble) — see atomo_tpu/utils/comm_model.py"
        ),
    }


def resolve_fabric(fabric: str, *, n_proc: int = 1, measured=None) -> float:
    """Per-chip bandwidth (bytes/s) for a ``--fabric`` value: ``auto``
    (ici single-host, dcn multi-host), a named preset, ``measured`` (the
    ``fabric_probe.json`` artifact — see below), or a positive finite
    per-chip GB/s number. ONE parser for the CLI's ``--aggregate auto``
    advisory and the autopilot's predictor, so the two surfaces cannot
    disagree about what a fabric string means. Raises ValueError with
    the usage line on anything else.

    A single scalar prices every hop at one bandwidth — on a two-tier
    mesh that is the OUTER (slowest) tier by convention, and per-tier
    arithmetic lives in ``topology.fabric.resolve_two_tier``, which
    reuses this grammar per tier token AND additionally accepts the
    two-tier ``<inner>:<outer>`` form (each side any token this parser
    takes) — a ``:``-carrying string reaching THIS scalar parser is
    rejected with the pointer below, not silently mis-read.

    ``measured`` resolves to the SLOWEST probed tier's bandwidth from a
    startup fabric probe (obs.fabric.probe_fabric); the caller threads
    the probe document via ``measured=`` — the CLI runs the probe when
    ``--fabric measured`` is passed with a ``--train-dir``. Without a
    document the token is a config error with the instruction attached
    (a preset must never silently stand in for a measurement)."""
    if fabric == "measured":
        if measured is None:
            raise ValueError(
                "--fabric measured resolves from a fabric_probe.json "
                "artifact (obs.fabric.probe_fabric) and this surface has "
                "none — run `train --fabric measured` with a --train-dir "
                "so the startup probe measures the mesh and records it"
            )
        from atomo_tpu.obs.fabric import measured_outer_bw

        return measured_outer_bw(measured)
    if fabric == "auto":
        return FABRICS["dcn" if n_proc > 1 else "ici"]
    if fabric in FABRICS:
        return FABRICS[fabric]
    try:
        bw = float(fabric) * 1e9
    except (TypeError, ValueError):
        bw = -1.0
    if not (0 < bw < float("inf")):  # also rejects nan/inf strings
        raise ValueError(
            f"--fabric {fabric!r}: expected auto | measured | "
            f"{' | '.join(sorted(FABRICS))} | <positive finite GB/s>"
            + (
                " (two-tier <inner>:<outer> strings are accepted by the "
                "two-tier surfaces — topology.fabric.resolve_two_tier — "
                "with each side any of the forms above)"
                if ":" in str(fabric)
                else " | <inner>:<outer> on two-tier surfaces"
            )
        )
    return bw


# ---------------------------------------------------------------------------
# Autopilot predictor: candidate knob vectors + analytic step-time model
# ---------------------------------------------------------------------------
#
# The ~6 orthogonal performance knobs (codec+rank, --aggregate, --superstep,
# --overlap, --zero1, ring bucket size) define a config space no static
# default covers (the PR-4 measured result: the delayed-overlap win is
# load-dependent skew absorption, not a constant). These helpers turn the
# byte accounting above into a RANKED candidate list the autopilot probes:
# the prediction orders the ladder (so the few measured probes go to the
# plausible winners), the measurement decides, and a >2x disagreement is
# logged as a calibration warning instead of silently trusted either way.
#
# Anchors (estimates, stated): compute scales the measured single-chip
# ResNet-18 dense step (6.50 ms on a 44.7 MB gradient, v5e —
# artifacts/BENCH_ONCHIP_r3.md) linearly with gradient bytes, like the
# codec-tax anchor; per-dispatch host cost is ~3 ms on tunneled TPU
# backends (measured, bench.py timing notes) and noise locally.

_COMPUTE_ANCHOR_S = 6.5e-3
_COMPUTE_ANCHOR_BYTES = 44.7e6
DISPATCH_ANCHOR_S = {"tpu": 3e-3, "cpu": 2e-4, "gpu": 5e-4}
# measured-vs-predicted ratio past which the model is called out as stale
CALIBRATION_MAX_RATIO = 2.0


def estimate_compute_s(dense_bytes: float) -> float:
    """Crude fwd+bwd+update wall estimate from gradient size (the measured
    ResNet-18 anchor scaled linearly — same estimator class as
    :func:`estimate_codec_tax_s`). Only used to ORDER the probe ladder and
    to model how much comm ``--overlap delayed`` can hide; the measured
    probes decide, and :func:`calibration_warning` reports when this
    anchor has drifted from reality."""
    return _COMPUTE_ANCHOR_S * float(dense_bytes) / _COMPUTE_ANCHOR_BYTES


def candidate_name(cand: dict) -> str:
    """Stable display/sort key for a knob vector (also the tie-break of
    last resort in the autopilot's winner selection — deterministic).
    Hierarchical candidates carry their topology.schedule plan inline:
    ``hier[psum+ring]+off+k1``; model-axis LM candidates lead with their
    layout (and codec, when the vector pins one):
    ``lm[tp2]+qsgd8+gather+off+se+k1``."""
    bits = []
    ma = cand.get("model_axes")
    if ma:
        shape = "".join(
            f"{a}{int(s)}"
            for a, s in dict(ma).items()
            if a not in ("dp", "ici") and int(s) > 1
        )
        bits.append(f"lm[{shape}]")
        if cand.get("codec"):
            bits.append(str(cand["codec"]))
    if cand.get("aggregate") == "hierarchical":
        bits.append(f"hier[{cand.get('plan', 'legacy')}]")
        bits.append(cand.get("overlap", "off"))
    elif cand.get("aggregate"):
        bits.append(cand["aggregate"])
        bits.append(cand.get("overlap", "off"))
    if cand.get("stream_encode") == "on":
        bits.append("se")  # backward-interleaved layer-streamed encode
    if cand.get("sparse_rows") == "on":
        bits.append("sp")  # per-layer sparse-row hybrid exchange
    if cand.get("budget_alloc") == "variance":
        bits.append("ab")  # adaptive variance-budget per-layer ranks
    if cand.get("quorum"):
        # bounded-staleness quorum aggregation: K is the staleness bound
        bits.append(f"q{cand.get('staleness', 1)}")
    bits.append(f"k{cand.get('superstep', 1)}")
    if cand.get("aggregate") == "ring":
        bits.append(f"b{cand.get('ring_bucket_size', 65536)}")
    return "+".join(bits)


def enumerate_candidates(
    *,
    has_codec: bool,
    ways: int,
    allow_ring: bool = True,
    allow_psum: bool = True,
    allow_overlap: bool = True,
    allow_stream: bool = False,
    stream_bucket_bytes: int = 4 << 20,
    stream_buckets: int = 0,
    allow_sparse: bool = False,
    sparse_leaf_budgets=None,
    allow_budget: bool = False,
    budget_leaf_budgets=None,
    allow_quorum: bool = False,
    quorum_q: int = 0,
    quorum_staleness_options=(1, 2),
    superstep_options=(1, 8),
    bucket_options=(65536,),
    dcn_ways: int = 0,
    plan_names=None,
) -> list[dict]:
    """The autopilot's candidate knob vectors, conflict-free by
    construction (the same compatibility matrix ``_argv_preflight`` and
    the loops enforce): a single device has no exchange to tune, a dense
    code has only psum, ``delayed`` exists only for the compressed
    gather/ring exchanges. The caller narrows further via the allow_*
    flags (e.g. ``--num-aggregate`` excludes psum, ``--on-diverge
    densify`` and ``--zero1`` exclude delayed).

    ``dcn_ways`` > 1 (a multi-tier mesh: ``--dcn-ways`` groups over the
    slow fabric) additionally emits one hierarchical candidate per
    topology.schedule plan (``plan_names`` narrows the plan space) —
    the PR-8 lift of the autopilot's hierarchical exclusion. They carry
    no delayed form (the two-level schedules are blocking) and require a
    codec (the plans compress at least one tier).

    ``allow_stream`` emits a ``--stream-encode on`` variant of every
    compressed gather/ring candidate (suffix ``+se``; the hierarchical
    plans are excluded — their boundary re-encode is not bucket-aware).
    The knob is trajectory-neutral (bit-identical payloads for any
    bucket plan), so stream candidates are pure schedule points;
    ``stream_bucket_bytes`` rides along so prediction and probe price
    the plan the run would execute.

    ``allow_sparse`` emits a ``--sparse-rows on`` variant (suffix
    ``+sp``) of every plain blocking gather/ring candidate, carrying the
    hybrid plan's per-leaf ``leaf_budgets`` so :func:`predict_step_s`
    prices the candidate's wire from the SAME per-leaf sums the executed
    program reports (honest pricing, not a separate estimate). Unlike
    the +se variants, sparse candidates change the trajectory only on
    lossy-codec tables (the row path is lossless), and compose with
    neither delayed overlap nor stream-encode (the in-run conflict
    matrix), so only the plain blocking points gain variants.

    ``allow_budget`` emits a ``--budget-alloc variance`` variant (suffix
    ``+ab``) of every plain blocking gather/ring candidate, priced from
    the adaptive allocation's per-leaf pairs
    (``budget.allocation_leaf_budgets`` — the clamped-actual sums the
    wrapped codec's executed program reports, the bench config 16
    wire-match gate); the sparse-candidate restrictions apply for the
    same reason until the delayed/streamed compositions are probed.
    ``+sp`` and ``+ab`` do not cross (the hybrid planner prices the
    dense sub-list at the base codec's budget).

    ``allow_quorum`` emits a bounded-staleness quorum variant (suffix
    ``+qK``, one per staleness bound K in ``quorum_staleness_options``,
    each carrying ``quorum=quorum_q`` — the caller's Q floor, typically
    N-1) of every plain blocking gather/ring candidate: the same
    restriction set as ``+sp``/``+ab`` because quorum composes with
    neither delayed overlap, stream-encode, hierarchical nor supersteps
    (the in-run conflict matrix —
    parallel.replicated.make_distributed_train_step). The variants are
    only worth probing under straggler load, so callers pass
    ``allow_quorum`` exactly when a ``slow@`` chaos table (or a measured
    skew) gives :func:`predict_step_s` a delay vector to price them
    by."""
    ks = sorted({max(int(k), 1) for k in superstep_options})
    out: list[dict] = []
    if ways <= 1:
        for k in ks:
            out.append({"superstep": k})
    elif not has_codec:
        for k in ks:
            out.append({"aggregate": "psum", "overlap": "off", "superstep": k})
    else:
        aggs = ["gather"]
        if allow_ring:
            aggs.append("ring")
        if allow_psum:
            aggs.append("psum")
        for agg in aggs:
            overlaps = ["off"]
            if allow_overlap and agg in ("gather", "ring"):
                overlaps.append("delayed")
            buckets = (
                sorted({int(b) for b in bucket_options})
                if agg == "ring"
                else [None]
            )
            streams = [None]
            if allow_stream and agg in ("gather", "ring"):
                streams.append(int(stream_bucket_bytes))
            for ov in overlaps:
                for k in ks:
                    for b in buckets:
                        for sb in streams:
                            c = {
                                "aggregate": agg,
                                "overlap": ov,
                                "superstep": k,
                            }
                            if b is not None:
                                c["ring_bucket_size"] = b
                            if sb is not None:
                                c["stream_encode"] = "on"
                                c["stream_bucket_bytes"] = sb
                                if stream_buckets > 0:
                                    # the REAL plan's bucket count when
                                    # the caller could see the gradient
                                    # tree — predict_step_s prefers it
                                    # over the byte-ratio estimate
                                    c["stream_buckets"] = int(
                                        stream_buckets
                                    )
                            out.append(c)
                            if (
                                allow_sparse
                                and sparse_leaf_budgets
                                and agg in ("gather", "ring")
                                and ov == "off"
                                and sb is None
                            ):
                                # the flag alone — the per-leaf budgets
                                # live ONCE at the ranking call
                                # (rank_candidates' sparse_leaf_budgets),
                                # not duplicated into every candidate
                                # row of the decision artifact
                                out.append({**c, "sparse_rows": "on"})
                            if (
                                allow_budget
                                and budget_leaf_budgets
                                and agg in ("gather", "ring")
                                and ov == "off"
                                and sb is None
                            ):
                                # same discipline as +sp: the flag
                                # alone; the allocation's per-leaf pairs
                                # live once at the ranking call
                                out.append(
                                    {**c, "budget_alloc": "variance"}
                                )
                            if (
                                allow_quorum
                                and int(quorum_q) >= 1
                                and agg in ("gather", "ring")
                                and ov == "off"
                                and sb is None
                                and k == 1
                            ):
                                # superstep > 1 is in quorum's conflict
                                # matrix: the host feeds a fresh arrival
                                # vector every step
                                for st in sorted(
                                    {max(int(s), 1)
                                     for s in quorum_staleness_options}
                                ):
                                    out.append(
                                        {
                                            **c,
                                            "quorum": int(quorum_q),
                                            "staleness": st,
                                        }
                                    )
    if (
        has_codec
        and ways > 1
        and int(dcn_ways) > 1
        and ways % int(dcn_ways) == 0
    ):
        from atomo_tpu.topology.schedule import PLAN_NAMES

        names = PLAN_NAMES if plan_names is None else tuple(plan_names)
        for pname in names:
            for k in ks:
                out.append(
                    {
                        "aggregate": "hierarchical",
                        "plan": pname,
                        "overlap": "off",
                        "superstep": k,
                    }
                )
    for c in out:
        c["name"] = candidate_name(c)
    return out


def predict_step_s(
    cand: dict,
    *,
    dense_bytes: float,
    payload_bytes: float,
    ways: int,
    fabric_bw: float,
    compute_s: float | None = None,
    tax_s: float | None = None,
    dispatch_s: float = 0.0,
    fabric2=None,
    leaf_budgets=None,
    sparse_leaf_budgets=None,
    budget_leaf_budgets=None,
    quorum_delays=None,
) -> float:
    """Model one candidate's synchronous step time (seconds).

    BYTE ACCOUNTING IS PER LEAF (PR-12 refactor): the whole-tree
    ``dense_bytes``/``payload_bytes`` scalars, an explicit
    ``leaf_budgets`` list of per-leaf pairs, a candidate's own
    ``cand["leaf_budgets"]`` override, and — for ``+sp`` hybrid
    candidates (``sparse_rows == "on"``) — the hybrid plan's
    ``sparse_leaf_budgets`` all flow through ONE summing function,
    :func:`leaf_budget_totals`, before any wire formula runs, so the
    single-codec paths and the hybrid candidates share one honest
    accounting and the report shapes stay exactly as before. A sparse
    candidate still pays the full codec tax (the dense-assigned share
    dominates it; stated conservative, the probe ladder corrects).

    step = compute + encode + comm_chain + dispatch/K, where the comm
    chain is the candidate's wire bytes over ``fabric_bw`` plus the
    decode-mean, ``--overlap delayed`` replaces the chain with its
    exposed excess over compute (overlap_exposed_comm_s — encode stays on
    the critical path, it consumes this step's gradient), and
    ``--superstep K`` divides the per-dispatch host cost by K. The codec
    tax (encode + decode round trip) is split evenly across the two ends
    — the anchor measures only their sum. A ``--stream-encode on``
    candidate replaces the encode term with its pipeline TAIL
    (:func:`stream_exposed_encode_s` over the bucket count implied by the
    candidate's ``stream_bucket_bytes``): the rest of the encode runs
    under backprop. All the byte formulas are the
    honest-accounting ones above; the anchors are stated estimates the
    probe ladder corrects.

    Hierarchical candidates (a ``plan`` knob) are priced PER TIER by
    ``topology.schedule.predict_plan_step_s`` and require ``fabric2`` (a
    :class:`~atomo_tpu.topology.fabric.TwoTierFabric`); on a two-tier
    mesh the flat candidates' ``fabric_bw`` should be the OUTER tier's
    bandwidth — the slowest link on their gradient path.

    ``quorum_delays`` (per-replica straggler delay vector, seconds —
    from the chaos ``slow@`` table or a measured skew) adds the straggler
    exposure every synchronous step pays: a blocking candidate waits for
    the SLOWEST replica (``max(delays)``); a ``+qK`` quorum candidate
    waits only the Q-th order statistic
    (:func:`quorum_exposed_wait_s`) — the entire wall-clock case for
    quorum aggregation, visible in the ranking exactly when a delay
    vector exists.

    Model-axis LM candidates (``model_axes`` set) carry their axis
    collectives PRE-PRICED as two floats the emitter computed from the
    measured fabric — ``model_comm_s`` (tp psum / MoE all-to-all wire
    over the INNER tier, :func:`tp_psum_wire_bytes` /
    :func:`moe_all_to_all_wire_bytes`) and ``pipeline_bubble_s``
    (:func:`pipeline_bubble_s`) — added to every non-hierarchical step
    prediction: the dp-wire knobs compete on top of a floor the model
    axes set, not instead of it."""
    model_extra_s = float(cand.get("model_comm_s") or 0.0) + float(
        cand.get("pipeline_bubble_s") or 0.0
    )
    lb = cand.get("leaf_budgets")
    if lb is None and cand.get("sparse_rows") == "on":
        lb = sparse_leaf_budgets
    if lb is None and cand.get("budget_alloc") == "variance":
        # the +ab candidates' wire: the adaptive allocation's clamped
        # per-leaf pairs (budget.allocation_leaf_budgets) — the same
        # sums the wrapped codec's executed program reports
        lb = budget_leaf_budgets
    if lb is None:
        lb = leaf_budgets
    if lb is None:
        lb = [(dense_bytes, payload_bytes)]
    dense_bytes, payload_bytes = leaf_budget_totals(lb)
    if compute_s is None:
        compute_s = estimate_compute_s(dense_bytes)
    ways = int(ways)
    k = max(int(cand.get("superstep", 1)), 1)
    if cand.get("aggregate") == "hierarchical":
        from atomo_tpu.topology.schedule import (
            plan_from_name,
            predict_plan_step_s,
        )

        if fabric2 is None:
            raise ValueError(
                "hierarchical candidates need fabric2 (a TwoTierFabric); "
                "build one with topology.fabric.resolve_two_tier"
            )
        return predict_plan_step_s(
            plan_from_name(cand.get("plan", "legacy")),
            dense_bytes=dense_bytes,
            payload_bytes=float(payload_bytes),
            fabric=fabric2,
            compute_s=compute_s,
            tax_s=tax_s,
            dispatch_s=dispatch_s,
            superstep=k,
        )
    if ways <= 1:
        # no exchange; the codec round trip still runs when armed (the
        # caller models the single-device compression-study step)
        rt = tax_s if tax_s is not None else (
            estimate_codec_tax_s(dense_bytes) if payload_bytes else 0.0
        )
        return compute_s + rt + model_extra_s + dispatch_s / k
    agg = cand.get("aggregate", "psum")
    has_codec = bool(payload_bytes) and payload_bytes > 0
    if not has_codec:
        wire = ring_allreduce_wire_bytes(dense_bytes, ways)
        return compute_s + wire / fabric_bw + model_extra_s + dispatch_s / k
    if tax_s is None:
        tax_s = estimate_codec_tax_s(dense_bytes)
    encode_s = decode_s = tax_s / 2.0
    if cand.get("stream_encode") == "on" and agg in ("gather", "ring"):
        # layer-streamed encode: only the last bucket's tail stays
        # exposed. Prefer the candidate's REAL plan bucket count
        # (stream_buckets, attached by callers that can see the gradient
        # tree) over the uniform-packing byte estimate, which overstates
        # granularity when a single leaf exceeds the bound
        n_b = int(cand.get("stream_buckets", 0)) or stream_bucket_count(
            dense_bytes, cand.get("stream_bucket_bytes", 4 << 20)
        )
        encode_s = stream_exposed_encode_s(encode_s, n_b)
    if agg == "psum":
        # codec semantics over a dense wire: the round trip runs per-chip,
        # the exchange is the dense all-reduce
        wire = ring_allreduce_wire_bytes(dense_bytes, ways)
    elif agg == "ring":
        wire = ring_stream_wire_bytes(payload_bytes, dense_bytes, ways)
    else:
        wire = ring_allgather_wire_bytes(payload_bytes, ways)
    chain = wire / fabric_bw + decode_s
    if cand.get("overlap") == "delayed" and agg in ("gather", "ring"):
        # the consume chain reads only step-start values, so it hides
        # under compute AND (for dp x pp candidates) the drain-tick
        # bubble — the bubble the candidate is already charged for is
        # simultaneously overlap headroom (overlap_report's
        # bubble_hidden_ms term)
        chain = overlap_exposed_comm_s(
            chain, compute_s + float(cand.get("pipeline_bubble_s") or 0.0)
        )
    straggler_s = 0.0
    if quorum_delays:
        # every synchronous step is gated by its stragglers: blocking
        # waits for the slowest replica, quorum only for the Q-th arrival
        if cand.get("quorum"):
            straggler_s = quorum_exposed_wait_s(
                quorum_delays, int(cand["quorum"])
            )
        else:
            straggler_s = max(float(x) for x in quorum_delays)
    return (
        compute_s + encode_s + chain + straggler_s + model_extra_s
        + dispatch_s / k
    )


def rank_candidates(
    cands: list[dict],
    *,
    dense_bytes: float,
    payload_bytes: float,
    ways: int,
    fabric_bw: float,
    compute_s: float | None = None,
    tax_s: float | None = None,
    dispatch_s: float = 0.0,
    fabric2=None,
    sparse_leaf_budgets=None,
    budget_leaf_budgets=None,
    quorum_delays=None,
) -> list[dict]:
    """Candidates + their predicted ms/step, best first (ties broken by
    name so the order — and therefore which candidates get probed — is
    deterministic for a given context). ``fabric2`` prices any
    hierarchical candidates per tier; ``sparse_leaf_budgets`` prices any
    ``+sp`` candidates from the hybrid plan's per-leaf pairs,
    ``budget_leaf_budgets`` any ``+ab`` candidates from the adaptive
    allocation's, and ``quorum_delays`` adds the per-candidate straggler
    exposure (blocking max vs quorum Q-th order statistic — see
    :func:`predict_step_s`)."""
    rows = []
    for c in cands:
        s = predict_step_s(
            c,
            dense_bytes=dense_bytes,
            payload_bytes=payload_bytes,
            ways=ways,
            fabric_bw=fabric_bw,
            compute_s=compute_s,
            tax_s=tax_s,
            dispatch_s=dispatch_s,
            fabric2=fabric2,
            sparse_leaf_budgets=sparse_leaf_budgets,
            budget_leaf_budgets=budget_leaf_budgets,
            quorum_delays=quorum_delays,
        )
        rows.append({**c, "predicted_ms_per_step": round(s * 1e3, 4)})
    rows.sort(key=lambda r: (r["predicted_ms_per_step"], r["name"]))
    return rows


def recommend_for_scenario(
    *,
    codec_budgets: dict,
    measured_ms: dict,
    ways: int,
    fabric_bw: float,
    dense_key: str = "dense",
    dispatch_s: float = 0.0,
    allow_overlap: bool = True,
    allow_stream: bool = False,
) -> dict:
    """Per-scenario recommended config: measured single-chip anchors +
    the analytic fabric term (exactly crossover_report's construction,
    generalized over the whole candidate space INCLUDING the codec axis
    — the SparCML-style pick the scenario-matrix bench row and the
    README tables publish).

    ``codec_budgets``: codec name -> (dense_bytes, payload_bytes);
    ``measured_ms``: codec name -> measured single-chip ms/step (the
    dense entry is the compute anchor; a codec's measured excess over it
    is its measured tax — no estimate anchors involved). Returns
    ``{"winner": {...}, "ranked": [...]}``, one entry per codec carrying
    its best candidate's name and predicted ms/step at ``ways`` over
    ``fabric_bw``. Pure and deterministic (same inputs, same table)."""
    if dense_key not in measured_ms:
        raise ValueError(f"measured_ms needs the {dense_key!r} anchor")
    compute_s = float(measured_ms[dense_key]) / 1e3
    rows = []
    for name, (db, pb) in sorted(codec_budgets.items()):
        has_codec = name != dense_key and pb
        tax_s = (
            max(float(measured_ms[name]) / 1e3 - compute_s, 0.0)
            if has_codec and name in measured_ms
            else 0.0
        )
        cands = enumerate_candidates(
            has_codec=bool(has_codec), ways=ways,
            allow_overlap=allow_overlap,
            # stream-encode candidates (+se) are opt-in here so the
            # published tables' candidate space only widens when the
            # caller asks (scenario_table.py --stream; bench config 10
            # keeps its historical space)
            allow_stream=allow_stream,
        )
        top = rank_candidates(
            cands,
            dense_bytes=db,
            payload_bytes=pb if has_codec else 0,
            ways=ways,
            fabric_bw=fabric_bw,
            compute_s=compute_s,
            tax_s=tax_s if has_codec else None,
            dispatch_s=dispatch_s,
        )[0]
        rows.append(
            {
                "code": name,
                "candidate": top["name"],
                "predicted_ms_per_step": top["predicted_ms_per_step"],
                "measured_1chip_ms": measured_ms.get(name),
                "codec_tax_ms": round(tax_s * 1e3, 3),
            }
        )
    rows.sort(key=lambda r: (r["predicted_ms_per_step"], r["code"]))
    return {"winner": rows[0], "ranked": rows}


def calibration_warning(
    predicted_s: float, measured_s: float, label: str = ""
) -> str | None:
    """The model-honesty check: when a probe's measured step time and the
    prediction disagree by more than :data:`CALIBRATION_MAX_RATIO` in
    EITHER direction, return a one-line warning carrying both numbers
    (the caller logs it) — the model is stale for this deployment and
    must not be silently trusted for the next ranking. None = within
    tolerance (or nothing to compare)."""
    p, m = float(predicted_s), float(measured_s)
    if not (p > 0 and m > 0) or not (math.isfinite(p) and math.isfinite(m)):
        return None
    ratio = max(p / m, m / p)
    if ratio <= CALIBRATION_MAX_RATIO:
        return None
    return (
        f"comm_model calibration: {label or 'candidate'} measured "
        f"{m * 1e3:.2f} ms/step vs predicted {p * 1e3:.2f} ms/step "
        f"({ratio:.1f}x apart, tolerance {CALIBRATION_MAX_RATIO:.0f}x) — "
        "the analytic anchors are stale for this deployment; trust the "
        "measured ladder (predictions only order the probes)"
    )


def rolling_calibration(
    prev: float | None,
    measured_s: float,
    predicted_s: float,
    window: int = 32,
) -> float | None:
    """One fold of the TRACKED calibration series: an EMA (span
    ``window``) of the measured/predicted step-time ratio. This is
    :func:`calibration_warning`'s one-shot >2x honesty check generalized
    into the per-step column the flight recorder emits
    (obs/recorder.py): the autopilot warns once at probe time, the
    recorder keeps score for the whole run, so a prediction that goes
    stale MID-run (a contended host, a changed load profile) is visible
    in the timeline, not just at startup. ``prev`` is the previous EMA
    value (None on the first sample); returns the new EMA, or ``prev``
    unchanged when either input is unusable (a gap is not a sample —
    the drift-detector convention)."""
    m, p = float(measured_s), float(predicted_s)
    if not (m > 0 and p > 0) or not (math.isfinite(m) and math.isfinite(p)):
        return prev
    ratio = m / p
    if prev is None:
        return ratio
    alpha = 2.0 / (max(window, 2) + 1.0)
    return prev + alpha * (ratio - prev)


def max_beneficial_ways(dense_bytes: float, payload_bytes: float) -> float:
    """N above which the all_gather moves MORE bytes than dense all-reduce
    (gather traffic grows ~linearly in N; all-reduce saturates at 2D)."""
    return 2.0 * dense_bytes / max(float(payload_bytes), 1.0)


def crossover_bandwidth(
    dense_bytes: float, payload_bytes: float, ways: int, codec_tax_s: float
) -> float | None:
    """Bandwidth below which compression wins the synchronous step.

    Solves t_dense_comm(B) = t_svd_comm(B) + tax for B. Returns None when
    the byte saving is negative at this N (compression can never win).
    """
    saved = ring_allreduce_wire_bytes(dense_bytes, ways) - ring_allgather_wire_bytes(
        payload_bytes, ways
    )
    if saved <= 0:
        return None
    if codec_tax_s <= 0:
        return float("inf")  # compression is free -> wins at any bandwidth
    return saved / codec_tax_s


def crossover_report(
    dense_bytes: float,
    payload_bytes: float,
    dense_step_s: float,
    svd_step_s: float,
    ways_list=DEFAULT_WAYS,
    bandwidths=DEFAULT_BANDWIDTHS,
) -> dict:
    """The per-config comm model attached to bench rows (JSON-ready).

    ``dense_step_s``/``svd_step_s`` are measured single-chip step times
    (compute + codec, no inter-chip comm); the model adds the fabric term.
    """
    tax_s = max(svd_step_s - dense_step_s, 0.0)
    rows = []
    for ways in ways_list:
        ar = ring_allreduce_wire_bytes(dense_bytes, ways)
        ag = ring_allgather_wire_bytes(payload_bytes, ways)
        bw_star = crossover_bandwidth(dense_bytes, payload_bytes, ways, tax_s)
        per_bw = {}
        for label, bw in bandwidths:
            t_dense = dense_step_s + ar / bw
            t_svd = svd_step_s + ag / bw
            per_bw[label] = {
                "dense_ms": round(t_dense * 1e3, 3),
                "compressed_ms": round(t_svd * 1e3, 3),
                "speedup": round(t_dense / t_svd, 3),
            }
        # JSON-safe crossover: inf (tax <= 0 — compression is free or
        # better even with no wire) must NOT serialize as the non-standard
        # `Infinity` token; carry it as null + an explicit flag instead
        is_inf = bw_star is not None and bw_star == float("inf")
        rows.append(
            {
                "ways": ways,
                "allreduce_wire_mb": round(ar / 1e6, 3),
                "allgather_wire_mb": round(ag / 1e6, 3),
                "crossover_bw_gbps_per_chip": (
                    None if (bw_star is None or is_inf)
                    else round(bw_star / 1e9, 2)
                ),
                "crossover": (
                    "never" if bw_star is None
                    else ("any_bandwidth" if is_inf else "below_listed_bw")
                ),
                "implied": per_bw,
            }
        )
    return {
        "assumptions": (
            "sync ring collectives, no comm/compute overlap; dense=allreduce "
            "2D(N-1)/N, compressed=allgather P(N-1) bytes/chip; codec tax = "
            "measured single-chip svd-dense step delta; see "
            "atomo_tpu/utils/comm_model.py"
        ),
        "dense_bytes": int(dense_bytes),
        "payload_bytes": int(payload_bytes),
        "codec_tax_ms": round(tax_s * 1e3, 3),
        "max_beneficial_ways": round(
            max_beneficial_ways(dense_bytes, payload_bytes), 1
        ),
        "ways": rows,
    }
