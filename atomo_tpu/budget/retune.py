"""Checkpoint-boundary budget re-allocation (the OnlineRetuner path).

A gradient spectrum DRIFTS over training (early spectra are spiky, late
ones noise-flat), so the startup allocation goes stale. The retuner
closes the loop the way the autopilot's OnlineRetuner closes step-time
drift: observe online, act only at checkpoint boundaries, record every
decision as an incident.

The online signal is the ``--obs-quality`` q_err2 series the flight
recorder already lands in metrics.jsonl — under the stated fixed_k law
``E q_err2_l = A_l / k_l``, the window mean times the current rank is a
fresh per-layer A_l estimate with ZERO extra device work
(``allocator.spectra_from_qerr2``). At each checkpoint boundary the
loop's retune hook calls :meth:`maybe_realloc`; the solver re-runs at
the SAME byte budget, and an allocation that changed — past a stated
hysteresis (any rank moved AND predicted variance improves by
``min_gain``) — lands as:

  * a new epoch appended to ``budget_alloc.json`` (atomic rewrite, the
    resume source of truth),
  * a ``budget_realloc`` incident quoting old/new per-layer splits and
    the predicted variance BOTH WAYS (both allocations priced under the
    fresh spectra — the apples-to-apples pair),
  * a new ``budget_alloc_epochN`` meta line + the ``budget_epoch``
    context column in metrics.jsonl (the recorder),
  * a rebuilt step program from the loop (payload shapes changed — a
    new program family boundary, snapped to the checkpoint exactly so
    kill->restart->resume replays bit-exact from the recorded epoch).

Armed only when the q series actually lands on disk (``--obs-quality``
+ ``--obs-record``): a retuner without its signal would be guessing,
and refusing to guess is the house style.
"""

from __future__ import annotations

import math
from typing import Optional

from atomo_tpu.budget.allocator import (
    predicted_variance,
    solve_allocation,
    spectra_from_qerr2,
)
from atomo_tpu.budget.artifact import (
    allocation_meta,
    append_epoch,
    write_alloc,
)
from atomo_tpu.budget.codec import budgeted_codec


class BudgetRetuner:
    """Fold the recorded q_err2 stream; re-solve at checkpoint
    boundaries; re-allocate out loud (module docstring)."""

    def __init__(
        self,
        *,
        train_dir: str,
        base_codec,
        spectra,
        alloc,
        doc: dict,
        min_samples: int = 8,
        min_gain: float = 0.02,
        incidents=None,
        recorder=None,
        log_fn=print,
    ):
        self.train_dir = train_dir
        self.base_codec = base_codec
        self.spectra = list(spectra)
        self.alloc = alloc
        self.doc = doc
        self.min_samples = int(min_samples)
        self.min_gain = float(min_gain)
        self.incidents = incidents
        self.recorder = recorder
        self.log_fn = log_fn
        self.last_boundary = int(
            (doc.get("epochs") or [{}])[-1].get("start_step", 0)
        )
        self.reallocs = 0

    @property
    def epoch(self) -> int:
        return int(self.alloc.epoch)

    def bind(self, incidents=None, recorder=None, log_fn=None):
        """Late-bind the loop-owned incident log / recorder / logger
        (the OnlineRetuner.bind precedent)."""
        if incidents is not None:
            self.incidents = incidents
        if recorder is not None:
            self.recorder = recorder
        if log_fn is not None:
            self.log_fn = log_fn
        return self

    def _window_qerr2(self, step: int) -> Optional[list]:
        """Per-layer mean of the recorded q_err2 series over steps in
        (last_boundary, step]; None when fewer than ``min_samples``
        usable records landed (a gap is not a sample)."""
        from atomo_tpu.obs.recorder import FlightRecorder, metrics_path

        recs = [
            r
            for r in FlightRecorder.read_steps(
                metrics_path(self.train_dir)
            )
            if self.last_boundary < int(r.get("step", -1)) <= step
            and isinstance(r.get("q_err2"), list)
        ]
        if len(recs) < self.min_samples:
            return None
        n = len(self.spectra)
        sums = [0.0] * n
        counts = [0] * n
        for r in recs:
            q = r["q_err2"]
            for i in range(min(n, len(q))):
                v = q[i]
                if isinstance(v, (int, float)) and math.isfinite(float(v)):
                    sums[i] += float(v)
                    counts[i] += 1
        return [
            (sums[i] / counts[i]) if counts[i] else None for i in range(n)
        ]

    def maybe_realloc(self, step: int):
        """Execute the boundary re-solve. Returns the new wrapped codec
        when the allocation changed (the loop rebuilds the step from
        it), else None. Every outcome past the sample gate is one
        incident record — switch or keep."""
        qmeans = self._window_qerr2(step)
        if qmeans is None:
            return None  # not enough recorded signal yet: not a decision
        fresh = spectra_from_qerr2(
            self.spectra, qmeans, self.alloc.ks, codec=self.base_codec
        )
        new = solve_allocation(
            self.base_codec, fresh,
            budget_bytes=self.alloc.budget_bytes,
            mode="variance", epoch=self.alloc.epoch + 1,
        )
        # predicted variance BOTH WAYS under the SAME fresh spectra: the
        # old split re-priced vs the new split
        var_old = predicted_variance(fresh, self.alloc.ks, self.base_codec)
        var_new = float(new.predicted_variance)
        changed = tuple(new.ks) != tuple(self.alloc.ks)
        improved = (
            var_old > 0
            and (var_old - var_new) / var_old >= self.min_gain
        )
        self.last_boundary = int(step)
        if not (changed and improved):
            if self.incidents is not None:
                self.incidents.append(
                    "budget_realloc",
                    action="keep",
                    step=step,
                    epoch=self.epoch,
                    predicted_variance_old=round(var_old, 8),
                    predicted_variance_new=round(var_new, 8),
                    reason=(
                        "allocation unchanged" if not changed else
                        f"gain {(var_old - var_new) / max(var_old, 1e-30):.3%}"
                        f" below the {self.min_gain:.0%} hysteresis"
                    ),
                )
            self.log_fn(
                f"Budget: boundary re-solve at step {step} keeps "
                f"allocation epoch {self.epoch} (predicted variance "
                f"{var_old:.4g} -> {var_new:.4g} under fresh spectra)"
            )
            return None
        old_ks = list(self.alloc.ks)
        self.spectra = fresh
        self.alloc = new
        self.doc = append_epoch(
            self.doc, self.base_codec, fresh, new, start_step=step
        )
        write_alloc(self.train_dir, self.doc)
        self.reallocs += 1
        moved = [
            {
                "name": self.spectra[i].name,
                "k_old": int(old_ks[i]),
                "k_new": int(new.ks[i]),
            }
            for i in range(len(old_ks))
            if old_ks[i] != new.ks[i]
        ]
        if self.incidents is not None:
            self.incidents.append(
                "budget_realloc",
                action=f"realloc->epoch{new.epoch}",
                step=step,
                epoch=new.epoch,
                budget_bytes=int(new.budget_bytes),
                payload_bytes=int(new.payload_bytes),
                predicted_variance_old=round(var_old, 8),
                predicted_variance_new=round(var_new, 8),
                ks_old=[int(k) for k in old_ks],
                ks_new=[int(k) for k in new.ks],
                moved=moved,
            )
        if self.recorder is not None:
            ep_rec = (self.doc.get("epochs") or [])[-1]
            self.recorder.write_meta(allocation_meta(ep_rec))
            self.recorder.set_context(budget_epoch=new.epoch)
        self.log_fn(
            f"Budget: spectrum drift re-allocation at step {step}: "
            f"epoch {new.epoch - 1} -> {new.epoch}, "
            f"{len(moved)} layer(s) moved, predicted variance "
            f"{var_old:.4g} -> {var_new:.4g} at "
            f"{new.payload_bytes / 1e6:.4f} MB wire (budget "
            f"{new.budget_bytes / 1e6:.4f} MB); program rebuilt at this "
            "checkpoint boundary"
        )
        return budgeted_codec(self.base_codec, new.ks)
