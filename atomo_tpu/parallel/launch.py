"""Multi-host launch — the TPU-native replacement for mpirun + hostfiles.

Reference behavior: L0 cluster tools provision EC2 nodes and write a hostfile
(tools/pytorch_ec2.py:656), then `mpirun -n <P+1> --hostfile hosts_address`
forks one Python process per rank (src/run_pytorch.sh:1). On TPU pods the
runtime already starts one process per host; what remains is distributed
initialization and building a global mesh whose ICI-adjacent axes stay inside
a slice while DCN connects slices.

``initialize()`` wraps jax.distributed.initialize (no-op on a single host),
``global_mesh()`` builds a mesh over *all* processes' devices, and
``HealthMonitor`` is the failure-detection hook the reference lacks entirely
(a dead MPI worker hangs its master's waitany forever — SURVEY.md §5.3;
here a missed heartbeat raises on the host so the job scheduler can restart
from the last checkpoint).
"""

from __future__ import annotations

import os
import time
from typing import Optional, Sequence

import jax

from atomo_tpu.parallel.mesh import make_mesh


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize the multi-host runtime.

    Single-process (one host, any number of local devices): no-op.
    Multi-process: wires jax.distributed so jax.devices() spans all hosts.
    Arguments default from the standard env (JAX_COORDINATOR_ADDRESS etc.)
    or the TPU metadata the runtime provides.
    """
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        env = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("JAX_PROCESS_ID")
        process_id = int(env) if env else None
    if coordinator_address is None and num_processes in (None, 1):
        return  # single host
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(axes: Sequence[tuple[str, int]] = ()) -> "jax.sharding.Mesh":
    """Mesh over every device across all processes. With multi-slice
    topologies put the fastest-varying (ICI) axis last so collectives ride
    ICI within a slice and only the outer axis crosses DCN."""
    return make_mesh(axes=tuple(axes), devices=jax.devices())


class HealthMonitor:
    """Step-heartbeat failure detector (capability the reference lacks).

    Call ``beat(step)`` after every completed step; ``check()`` raises
    ``RuntimeError`` if no beat arrived within ``timeout`` seconds — e.g.
    from a watchdog thread or the eval loop. Pair with checkpoint/resume for
    restart-based elasticity: SPMD jobs fail as a unit (an XLA collective
    with a dead participant times out), so recovery = restart from the last
    ``model_step_N``.
    """

    def __init__(self, timeout: float = 300.0):
        self.timeout = timeout
        self._last = time.monotonic()
        self._last_step = -1

    def beat(self, step: int) -> None:
        self._last = time.monotonic()
        self._last_step = step

    def check(self) -> None:
        silent = time.monotonic() - self._last
        if silent > self.timeout:
            raise RuntimeError(
                f"no training heartbeat for {silent:.0f}s "
                f"(last completed step {self._last_step}); "
                "restart from the latest checkpoint"
            )
