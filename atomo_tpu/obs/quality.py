"""In-graph estimator-quality probes (``--obs-quality``).

ATOMO's defining quantity is the sparsified estimator's VARIANCE (Wang et
al., 1806.04090: the atom allocation minimizes estimator variance under a
byte budget) — and until this module it was not observable at all. The
probe adds, inside the fused step, the per-layer compression error of the
codec's unbiased estimator:

  * ``q_err2[l]`` — ``||decode(encode(g_l)) - g_l||^2`` in f32, the
    squared estimator error of layer ``l``'s OWN encode this step. Its
    expectation over codec keys IS the estimator variance (the encode is
    unbiased, so E||ĝ-g||^2 = tr Var[ĝ]), which makes the recorded
    series a per-layer variance estimate averaged over steps.
  * ``q_rel[l]`` — ``q_err2[l] / ||g_l||^2``, the scale-free relative
    variance proxy that makes layers comparable (the quantity the
    adaptive variance-budget reallocation of ROADMAP open item 5 will
    minimize across layers).

The per-layer BYTE split (what the budget buys per layer) is static at
trace time — :func:`quality_meta` records it once as a ``meta`` line in
metrics.jsonl rather than per step.

Cost contract: the probe reuses the existing shape-group vmapping of
codecs/base.py (``decode_tree(bucketed=True)`` — one vmapped decode per
same-shape leaf group; the decode it adds is the SAME arithmetic the
step's own decode path runs, so XLA dedups what it can) plus one f32
reduction per leaf. Off (the default) adds zero ops: the step programs
are byte-identical to before (lowered-HLO text tested, the stream-encode
precedent), and armed-vs-off trajectories are bit-identical (the probe
only ADDS metric outputs — tested).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from atomo_tpu.codecs import decode_tree


def quality_probe(codec, payloads, grads) -> dict:
    """Traced per-layer estimator-error telemetry for one encode.

    ``payloads`` is the encode of ``grads`` (this replica's own, BEFORE
    any exchange); returns ``{"q_err2": (L,), "q_rel": (L,)}`` f32
    arrays over the gradient tree's L leaves in canonical flatten order
    (the same order quality_meta names them in). ``q_rel`` floors the
    denominator at f32-tiny so a zero-gradient layer reads 0/tiny = 0
    error, not NaN."""
    decoded = decode_tree(codec, payloads, grads)
    return quality_from_decoded(
        jax.tree_util.tree_leaves(decoded),
        jax.tree_util.tree_leaves(grads),
    )


def quality_from_decoded(d_leaves, g_leaves) -> dict:
    """The error math of :func:`quality_probe` over an already-decoded
    leaf list — shared with the hybrid exchange's probe, whose per-leaf
    decode dispatches on the plan's assignment (sparse-assigned leaves
    decode losslessly and read exactly 0 here: the lossless contract,
    observed live in the telemetry stream)."""
    err2 = []
    g2 = []
    for g, d in zip(g_leaves, d_leaves):
        gf = g.astype(jnp.float32)
        df = d.astype(jnp.float32)
        diff = df - gf
        err2.append(jnp.sum(diff * diff))
        g2.append(jnp.sum(gf * gf))
    q_err2 = jnp.stack(err2)
    q_g2 = jnp.stack(g2)
    return {
        "q_err2": q_err2,
        "q_rel": q_err2 / jnp.maximum(q_g2, jnp.float32(1e-30)),
    }


def quality_meta(
    codec,
    tree: Any,
    stream_bucket_bytes: Optional[int] = None,
    hybrid=None,
) -> dict:
    """The static half of the quality telemetry: the per-layer kept-byte
    split — layer name, shape, dense bytes, payload bytes — computed at
    zero cost with ``jax.eval_shape`` (nothing materializes; the
    _zero_carry_host precedent). Recorded once as a ``meta`` line so the
    per-step records stay small; keyed by the same canonical leaf order
    ``q_err2``/``q_rel`` index.

    ``hybrid`` (sparse.hybrid.HybridPlan) adds the per-layer MEASURED
    density, the assignment (sparse vs dense) and — for sparse-assigned
    layers — the static row budget, and overrides those layers' payload
    bytes with the row-payload wire size, so the byte split describes
    the exchange that actually runs. The ``report`` verb's consistency
    checks audit these columns (density in [0, 1]; a sparse-assigned
    layer's payload strictly below its dense bytes)."""
    import numpy as np

    from atomo_tpu.codecs import encode_tree

    shapes = jax.eval_shape(
        lambda p: encode_tree(codec, jax.random.PRNGKey(0), p)[0], tree
    )
    flat_paths, treedef = jax.tree_util.tree_flatten_with_path(tree)
    p_leaves = treedef.flatten_up_to(shapes)
    if hybrid is not None and hybrid.n_leaves != len(flat_paths):
        raise ValueError(
            f"hybrid plan covers {hybrid.n_leaves} leaves but the tree "
            f"has {len(flat_paths)} — plan and tree must match"
        )
    layers = []
    for i, ((path, leaf), p) in enumerate(zip(flat_paths, p_leaves)):
        dense = int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        pay = int(
            sum(
                int(np.prod(s.shape)) * np.dtype(s.dtype).itemsize
                for s in jax.tree_util.tree_leaves(p)
            )
        )
        row = {
            "name": jax.tree_util.keystr(path),
            "shape": [int(d) for d in leaf.shape],
            "dense_bytes": dense,
            "payload_bytes": pay,
        }
        if hybrid is not None:
            a = hybrid.assignments[i]
            row["assignment"] = a.kind
            row["density"] = round(float(a.density), 6)
            row["payload_bytes"] = int(a.payload_bytes)
            if a.kind == "sparse":
                row["row_budget"] = int(a.row_budget)
        layers.append(row)
    out = {
        "what": "obs_quality",
        "codec": getattr(codec, "name", str(codec)),
        "n_layers": len(layers),
        "dense_bytes": int(sum(l["dense_bytes"] for l in layers)),
        "payload_bytes": int(sum(l["payload_bytes"] for l in layers)),
        "layers": layers,
    }
    if stream_bucket_bytes is not None:
        out["stream_bucket_bytes"] = int(stream_bucket_bytes)
    return out
