"""Native C++ lossless codec tests (the blosc-capability replacement,
reference src/utils.py:3-16)."""

import os

import numpy as np
import pytest

from atomo_tpu.native import lossless

pytestmark = pytest.mark.skipif(
    not lossless.available(), reason="g++ toolchain unavailable"
)


@pytest.mark.parametrize("typesize", [1, 2, 4, 8])
@pytest.mark.parametrize(
    "data",
    [
        b"",
        b"x",
        b"abc" * 1000,
        np.arange(10000, dtype=np.float32).tobytes(),
        np.random.RandomState(0).randn(5000).astype(np.float64).tobytes(),
        os.urandom(4096),
    ],
    ids=["empty", "one", "repeat", "arange", "randn", "urandom"],
)
def test_roundtrip(data, typesize):
    blob = lossless.compress(data, typesize=typesize)
    assert lossless.decompress(blob) == data


def test_structured_floats_compress_well():
    data = np.arange(100000, dtype=np.float64).tobytes()
    blob = lossless.compress(data, typesize=8)
    assert len(blob) < len(data) / 10  # shuffle makes this highly regular


def test_incompressible_stored_near_raw():
    data = os.urandom(100000)
    blob = lossless.compress(data, typesize=1)
    assert len(blob) <= len(data) + 64  # stored fallback, tiny header only


def test_bad_magic_rejected():
    with pytest.raises(ValueError):
        lossless.decompress(b"NOPE" + b"\x00" * 32)


def test_truncated_rejected():
    data = np.arange(1000, dtype=np.float32).tobytes()
    blob = lossless.compress(data, typesize=4)
    with pytest.raises(ValueError):
        lossless.decompress(blob[: len(blob) // 2])


import struct


def _alz_header(rawlen: int, flags: int = 0, typesize: int = 1) -> bytes:
    return struct.pack("<4sBBQ", b"ALZ1", flags, typesize, rawlen)


@pytest.mark.parametrize(
    "length_varint",
    [
        # huge literal len: ip + len overflows a pointer; len fits uint64
        b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\x01",
        # len >= 2^63: static_cast<int64_t>(len) goes negative
        b"\x80\x80\x80\x80\x80\x80\x80\x80\x80\x01",
    ],
    ids=["ptr-overflow", "int64-negative"],
)
def test_overflowing_varint_len_rejected(length_varint):
    """Corruption-controlled varint lengths near 2^64 must fail closed
    (ValueError), never read/write out of bounds (the ADVICE r1 finding)."""
    for opcode in (b"\x00", b"\x01"):
        stream = opcode + length_varint + b"\x01\x00" + b"A" * 16
        blob = _alz_header(rawlen=64) + stream
        with pytest.raises(ValueError):
            lossless.decompress(blob)


def test_match_beyond_cap_rejected():
    # valid-looking match op whose len exceeds the declared raw size
    stream = b"\x00\x04AAAA" + b"\x01\xff\x7f" + b"\x01\x00"
    blob = _alz_header(rawlen=8) + stream
    with pytest.raises(ValueError):
        lossless.decompress(blob)


def test_forged_huge_rawlen_rejected_without_allocating():
    """A hostile header claiming a near-2^62 raw size over a tiny payload
    must raise BEFORE the rawlen-sized allocation (VERDICT r2 weak #5):
    the C++ stream pre-scan proves the payload decodes to 5 bytes, so the
    forged 4 EiB claim is rejected with no buffer ever allocated — this
    test would OOM/MemoryError the host if the allocation happened."""
    stream = b"\x00\x05HELLO"  # decodes to exactly 5 bytes
    blob = _alz_header(rawlen=1 << 61) + stream
    with pytest.raises(ValueError, match="corrupt header"):
        lossless.decompress(blob)


def test_rawlen_mismatch_smaller_also_rejected():
    """Understating rawlen is also a corrupt header, not a silent truncate."""
    stream = b"\x00\x05HELLO"
    blob = _alz_header(rawlen=2) + stream
    with pytest.raises(ValueError):
        lossless.decompress(blob)


def test_legitimate_high_ratio_blob_still_decompresses():
    """The DoS guard must NOT cap legitimate expansion: a zero run
    compresses ~4000:1 here and must still round-trip (a fixed
    rawlen/payload ratio bound would reject it)."""
    data = b"\x00" * (1 << 22)  # 4 MiB of zeros
    blob = lossless.compress(data, typesize=1)
    assert len(blob) < len(data) // 1000
    assert lossless.decompress(blob) == data
