"""Worker for the real 2-process jax.distributed smoke test.

Launched (never imported) by tests/test_multiprocess.py: two copies of this
script form a 2-process jax.distributed job on localhost, each contributing
2 virtual CPU devices to a global 4-device 'dp' mesh, and run ONE compressed
SPMD training step end-to-end. This executes the code CI could previously
only monkeypatch (VERDICT r2 next-round #5):

  * launch.initialize()'s env path actually calling
    jax.distributed.initialize (replaces the reference's mpirun rank
    dispatch, src/distributed_nn.py:86-88,243-259);
  * shard_batch's jax.make_array_from_process_local_data branch
    (parallel/replicated.py) — each process feeds only its local shard;
  * the gather-aggregate step with cross-process collectives.

Prints one `RESULT {json}` line; the parent asserts both processes agree
bit-for-bit on the post-step state (replicated-PS equivalence, SURVEY.md §7
hard-part 4).
"""

import json
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from atomo_tpu.parallel import launch  # noqa: E402
from atomo_tpu.utils.chaos import ChaosInjector  # noqa: E402

# simulated process death (kill@1) BEFORE the distributed handshake, so the
# fault-tolerance drill can kill real workers without deadlocking the peer
# in a collective (tests/test_fault_tolerance.py)
_chaos = ChaosInjector.from_env()
if _chaos is not None:
    _chaos.maybe_die(1)

launch.initialize()  # env path: JAX_COORDINATOR_ADDRESS / _NUM_PROCESSES / _ID

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from atomo_tpu.codecs import SvdCodec  # noqa: E402
from atomo_tpu.models import get_model  # noqa: E402
from atomo_tpu.parallel.launch import global_mesh  # noqa: E402
from atomo_tpu.parallel.replicated import (  # noqa: E402
    make_distributed_train_step,
    replicate_state,
    shard_batch,
)
from atomo_tpu.training import create_state, make_optimizer  # noqa: E402


def _params_sha256(params) -> str:
    import hashlib

    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(params):
        h.update(np.asarray(jax.device_get(leaf)).tobytes())
    return h.hexdigest()


def main_lm() -> None:
    """dp x sp LM mode (ATOMO_MP_MODE=lm): the SEQUENCE axis spans the two
    processes (mesh rows = sp = process index), so ring attention's K/V
    ppermutes and the boundary-target fetch cross a REAL process boundary
    every step — the multi-host long-context claim, actually executed. The
    dp pair (and its compressed gather) lives inside each process."""
    from atomo_tpu.models.transformer import TransformerLM
    from atomo_tpu.parallel.lm import make_lm_train_step

    pid = jax.process_index()
    mesh = global_mesh((("sp", 2), ("dp", 2)))  # sp major: rows = processes
    cfg = dict(vocab_size=16, max_len=16, width=16, depth=1, num_heads=2)
    opt = make_optimizer("sgd", lr=0.05, momentum=0.9)
    sample = jnp.zeros((2, 16), jnp.int32)
    state = replicate_state(
        mesh, create_state(TransformerLM(**cfg), opt, jax.random.PRNGKey(0), sample)
    )
    step = make_lm_train_step(cfg, opt, mesh, SvdCodec(rank=2))

    # both processes generate the SAME global batch (seed is shared); each
    # contributes its own half of every sequence (its sp shard)
    full = np.random.RandomState(42).randint(0, 16, (4, 16)).astype(np.int32)
    local_toks = full[:, pid * 8 : (pid + 1) * 8]
    from jax.sharding import NamedSharding, PartitionSpec as P

    toks = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp", "sp")), local_toks
    )
    assert toks.shape == (4, 16), toks.shape
    state, metrics = step(state, jax.random.PRNGKey(1), toks)
    print(
        "RESULT "
        + json.dumps(
            {
                "pid": int(pid),
                "loss": float(metrics["loss"]),
                "msg_bytes": int(metrics["msg_bytes"]),
                "params_sha256": _params_sha256(state.params),
            }
        ),
        flush=True,
    )


def main() -> None:
    assert jax.process_count() == 2, f"process_count={jax.process_count()}"
    assert len(jax.devices()) == 4, f"global devices={len(jax.devices())}"
    if os.environ.get("ATOMO_MP_MODE") == "lm":
        main_lm()
        return
    pid = jax.process_index()

    mesh = global_mesh((("dp", 4),))
    model = get_model("lenet", 10)
    opt = make_optimizer("sgd", lr=0.01, momentum=0.0)
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((4, 28, 28, 1), jnp.float32)
    state = replicate_state(mesh, create_state(model, opt, rng, sample))
    step = make_distributed_train_step(
        model, opt, mesh, codec=SvdCodec(rank=2), aggregate="gather"
    )

    # each process feeds its OWN local shard (2 local devices x 2 samples),
    # independently generated — the reference's workers also shuffle
    # independently (src/distributed_nn.py:93-207)
    local_im = np.random.RandomState(pid).rand(4, 28, 28, 1).astype(np.float32)
    local_lb = np.random.RandomState(100 + pid).randint(0, 10, (4,)).astype(np.int32)
    gi, gl = shard_batch(mesh, local_im, local_lb)
    assert gi.shape[0] == 8, gi.shape  # global batch = both processes' shards

    state, metrics = step(state, jax.random.PRNGKey(1), gi, gl)
    # ATOMO_MP_DUMP: process 0 saves the post-step param leaves so the
    # parent test can compare them leaf-wise against its single-process
    # oracle (a summary scalar would absorb compensating divergences)
    dump_path = os.environ.get("ATOMO_MP_DUMP", "")
    if dump_path and pid == 0:
        np.savez(
            dump_path,
            *[np.asarray(jax.device_get(l))
              for l in jax.tree_util.tree_leaves(state.params)],
        )
    # fingerprint the post-step replicated params: a cryptographic hash of
    # the raw bytes — an L1-sum scalar would absorb sub-rounding or
    # compensating divergences and defeat the bit-for-bit claim
    print(
        "RESULT "
        + json.dumps(
            {
                "pid": int(pid),
                "loss": float(metrics["loss"]),
                "msg_bytes": int(metrics["msg_bytes"]),
                "params_sha256": _params_sha256(state.params),
                "dump_path": dump_path or None,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
