#!/usr/bin/env bash
# LR grid search — the reference's src/tune.sh:7-33 (ResNet-18/CIFAR-10,
# lr in {2^-7 .. 2^-1}, 100 steps per candidate). Runs in-process instead of
# spawning 17 MPI ranks per candidate; same scoring contract (mean loss over
# the final logged steps, parsed from the worker log-line format).
set -euo pipefail

python -m atomo_tpu tune \
  --network ResNet18 \
  --dataset Cifar10 \
  --batch-size 128 \
  --code svd \
  --svd-rank 3 \
  --tuning-steps 100 \
  "$@"
