"""Flax model zoo, name-dispatched like the reference build_model
(src/distributed_worker.py:139-164 / src/sync_replicas_master_nn.py:146-171).

Reference CLI names: LeNet, ResNet18, ResNet34, FC, DenseNet, VGG11, AlexNet.
Extended (capability superset): ResNet50/101/152/110, VGG13/16/19 (+ _bn),
DenseNet100.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn

from atomo_tpu.models.alexnet import AlexNet, alexnet  # noqa: F401
from atomo_tpu.models.embedding import EmbeddingTower  # noqa: F401
from atomo_tpu.models.densenet import (  # noqa: F401
    DenseNet,
    densenet_bc_100,
    densenet_reference,
)
from atomo_tpu.models.lenet import FCNN, LeNet  # noqa: F401
from atomo_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet110,
    ResNet152,
)
from atomo_tpu.models.vgg import (  # noqa: F401
    VGG,
    vgg11,
    vgg11_bn,
    vgg13,
    vgg13_bn,
    vgg16,
    vgg16_bn,
    vgg19,
    vgg19_bn,
)

_REGISTRY: dict[str, Callable[[int], nn.Module]] = {
    # reference CLI surface
    "lenet": lambda n: LeNet(num_classes=n),
    "fc": lambda n: FCNN(num_classes=n),
    "resnet18": ResNet18,
    "resnet34": ResNet34,
    "densenet": densenet_reference,
    "vgg11": vgg11_bn,  # the reference's VGG11 is vgg11_bn (worker :153-154)
    "alexnet": alexnet,
    # capability superset
    "resnet50": ResNet50,
    "resnet101": ResNet101,
    "resnet152": ResNet152,
    "resnet110": ResNet110,
    "densenet100": densenet_bc_100,
    "vgg11_plain": vgg11,
    "vgg13": vgg13_bn,
    "vgg16": vgg16_bn,
    "vgg19": vgg19_bn,
    "vgg13_plain": vgg13,
    "vgg16_plain": vgg16,
    "vgg19_plain": vgg19,
    # sparse/embedding workload family (PR-12): row-sparse table + dense
    # tower; sizes beyond the CLI's --emb-rows/--emb-dim knobs register
    # here
    "embedding": lambda n: EmbeddingTower(num_classes=n),
    "embedding_wide": lambda n: EmbeddingTower(
        num_classes=n, rows=65536, dim=32
    ),
}


def get_model(name: str, num_classes: int = 10) -> nn.Module:
    """Build a model by CLI name (case-insensitive)."""
    key = name.lower()
    if key not in _REGISTRY:
        raise ValueError(
            f"unknown network {name!r}; known: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[key](num_classes)


def model_names() -> list[str]:
    return sorted(_REGISTRY)
